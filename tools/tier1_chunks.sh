#!/usr/bin/env bash
# Run the tier-1 suite in N sequential pytest chunks.
#
# The single-invocation tier-1 command (ROADMAP.md) cannot finish inside
# its 870 s cap on the 1-core box — XLA:CPU compiles dominate and the
# seed already timed out (CHANGES.md PR 1 note). Splitting the test
# FILES round-robin into N chunks keeps every invocation under the cap
# while preserving the exact same selection (-m 'not slow'); the
# persistent .jax_cache is shared across chunks, so compile work is
# paid once. Round-robin (not contiguous) so the alphabetical cluster
# of compile-heavy device suites (test_bl_*, test_pallas_*, ...)
# spreads across chunks.
#
# Usage:
#   tools/tier1_chunks.sh [N] [--list] [extra pytest args...]
# Env:
#   CHUNK_TIMEOUT  seconds per chunk (default 870, the tier-1 cap)
#
# --list prints the chunk -> file assignment (one line per chunk) and
# exits 0 without running anything, so a CI log's chunked verdicts are
# auditable against exactly which files each chunk covered.
#
# Registration is by glob: every tests/test_*.py is picked up
# automatically. New suites MUST keep the conventions the chunking
# relies on: compile-heavy device suites and new subsystem suites go
# late-alphabet (test_zz_*) so the capped single tier-1 invocation
# keeps its early-dot throughput. Currently registered late-alphabet:
#   test_zz_analyze.py     static-analysis suite (host-only, <60 s,
#                          no backend init — pure AST + one aiohttp
#                          harness)
#   test_zz_flight.py      threshold flight recorder suite (host-only)
#   test_zz_obs_health.py  chain-health SLO / OTLP export suite
#
# Exit status: 0 iff every chunk passed.

set -u
cd "$(dirname "$0")/.."

# first arg is N only when it is a positive integer — otherwise it is a
# pytest arg and the default chunk count applies (a bad N must never
# yield a zero-iteration loop that exits 0 without running anything);
# --list is accepted before or after N
N=4
LIST=0
if [ "${1:-}" = "--list" ]; then
    LIST=1
    shift
fi
if [[ "${1:-}" =~ ^[0-9]+$ ]] && [ "$1" -ge 1 ]; then
    N=$1
    shift
fi
if [ "${1:-}" = "--list" ]; then
    LIST=1
    shift
fi

FILES=()
while IFS= read -r f; do FILES+=("$f"); done < <(ls tests/test_*.py | sort)

if [ "$LIST" -eq 1 ]; then
    for ((i = 0; i < N; i++)); do
        chunk=()
        for ((j = i; j < ${#FILES[@]}; j += N)); do
            chunk+=("${FILES[j]}")
        done
        echo "chunk $((i + 1))/$N: ${chunk[*]:-}"
    done
    exit 0
fi

fail=0
for ((i = 0; i < N; i++)); do
    chunk=()
    for ((j = i; j < ${#FILES[@]}; j += N)); do
        chunk+=("${FILES[j]}")
    done
    [ ${#chunk[@]} -eq 0 ] && continue
    echo "=== chunk $((i + 1))/$N: ${chunk[*]}" >&2
    timeout -k 10 "${CHUNK_TIMEOUT:-870}" \
        env JAX_PLATFORMS=cpu python -m pytest "${chunk[@]}" -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "=== chunk $((i + 1))/$N FAILED (rc=$rc)" >&2
        fail=1
    fi
done
exit $fail
