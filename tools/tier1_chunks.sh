#!/usr/bin/env bash
# Run the tier-1 suite in N sequential pytest chunks.
#
# The single-invocation tier-1 command (ROADMAP.md) cannot finish inside
# its 870 s cap on the 1-core box — XLA:CPU compiles dominate and the
# seed already timed out (CHANGES.md PR 1 note). Splitting the test
# FILES round-robin into N chunks keeps every invocation under the cap
# while preserving the exact same selection (-m 'not slow'); the
# persistent .jax_cache is shared across chunks, so compile work is
# paid once. Round-robin (not contiguous) so the alphabetical cluster
# of compile-heavy device suites (test_bl_*, test_pallas_*, ...)
# spreads across chunks.
#
# Usage:
#   tools/tier1_chunks.sh [N] [--list] [extra pytest args...]
# Env:
#   CHUNK_TIMEOUT  seconds per chunk (default 870, the tier-1 cap)
#
# --list prints the chunk -> file assignment (one line per chunk) and
# exits 0 without running anything, so a CI log's chunked verdicts are
# auditable against exactly which files each chunk covered. It also
# flags any KNOWN-CONFLICTING pair that still shares a chunk (only
# possible at N=1).
#
# Known-conflicting pairs (CONFLICTS below) are separated STRUCTURALLY:
# after the round-robin assignment, the later member of a pair that
# landed in the same chunk is moved to the next chunk — the PR-10 note
# (test_daemon + test_mock_and_scale contention-flake the reshare
# timeout when run back to back on the 1-core box) no longer depends
# on round-robin luck as the file list grows.
#
# Registration is by glob: every tests/test_*.py is picked up
# automatically. New suites MUST keep the conventions the chunking
# relies on: compile-heavy device suites and new subsystem suites go
# late-alphabet (test_zz_*) so the capped single tier-1 invocation
# keeps its early-dot throughput. Currently registered late-alphabet:
#   test_zz_analyze.py     static-analysis suite (host-only, <60 s,
#                          no backend init — pure AST + one aiohttp
#                          harness)
#   test_zz_chaos.py       chaos network simulator (host-only,
#                          structural crypto — no pairings, no
#                          compiles; ~10 s)
#   test_zz_concurrency.py concurrency-analysis tier: lockheld/
#                          threadshare/awaitatomic fixtures, thread
#                          hammers, cache-race regressions (host-only,
#                          pure AST + threads, no compiles; ~7 s).
#                          CONFLICTS check vs test_zz_analyze: both
#                          parse the full tree (~2 s each, CPU-bound,
#                          no shared mutable state, no clocks) — they
#                          coexist in one chunk fine; no pair entry
#                          needed.
#   test_zz_dkg_scale.py   large-group ceremony tier: batched-phase
#                          verdict bit-identity vs per-item oracles
#                          (lockstep G1 membership, parse_commits,
#                          comb share checks, RLC reshare bindings),
#                          structural n=48/64 ceremony + reshare,
#                          FakeClock chunked-admission regression,
#                          attributable-reject counters (host-pinned
#                          by an autouse fixture; real crypto only at
#                          small n; ~60 s). CONFLICTS evaluation vs
#                          test_daemon/test_mock_and_scale: runs DKG
#                          phasers but only on its OWN LocalBoards
#                          with a private FakeClock (fast-sync
#                          elsewhere), resets the FLIGHT dkg ring
#                          around each use and asserts counter
#                          DELTAS — no shared timers or state; no
#                          pair entry needed.
#   test_zz_fanout.py      edge fan-out push tier: SSE/NDJSON hub,
#                          shedding, segment store, SO_REUSEPORT
#                          worker smoke (host-only, no pairings except
#                          the worker smoke's ~15 real signatures;
#                          ~15 s wall). CONFLICTS check vs
#                          test_daemon/test_mock_and_scale: the worker
#                          smoke spawns 3 short-lived relay processes
#                          on the wall clock but runs no DKG and no
#                          reshare timers — no contention pair needed.
#   test_zz_flight.py      threshold flight recorder suite (host-only)
#   test_zz_incident.py    incident engine: chaos-driven detector
#                          matrix, ts-ring/bundle rotation, restart
#                          persistence, bundle hygiene, ?n= matrix
#                          (host-only; structural crypto + one real
#                          share synthesis, no pairings, no compiles;
#                          ~5 s). CONFLICTS evaluation vs
#                          test_zz_chaos/test_zz_analyze: same
#                          structural-crypto FakeClock harness (~7 s
#                          CPU, no wall-clock timers, no DKG/reshare
#                          phasers) and its own recorder instances —
#                          coexists in one chunk fine; no pair entry
#                          needed.
#   test_zz_obs_health.py  chain-health SLO / OTLP export suite
#   test_zz_remediate.py   auto-remediation plane: playbook-engine
#                          guardrails, bounded supervisor, ledger-sink
#                          analyzer fixtures, chaos-oracle e2e matrix,
#                          /debug/remediation ?n= (host-only,
#                          structural crypto + FakeClock; ~6 s).
#                          CONFLICTS evaluation vs test_zz_chaos/
#                          test_zz_incident: same structural-crypto
#                          harness, per-test IncidentManager/
#                          PlaybookEngine instances, the one singleton
#                          test detaches in its finally — coexists in
#                          one chunk fine; no pair entry needed.
#   test_zz_client_catchup.py  million-client catch-up tier: adaptive
#                          RLC span walk, pipelined fetch/verify
#                          cancel-resume, trust ring, checkpoint
#                          bootstrap/forgery matrix, /checkpoints/
#                          latest route (host-only; structural crypto
#                          plus ~45 real signatures on 40-round
#                          chains, batch dispatch pinned to host by
#                          an autouse fixture; ~6 s). CONFLICTS
#                          evaluation vs test_zz_chaos/
#                          test_zz_incident: same structural-crypto
#                          patch pattern with per-test client/network
#                          instances, no wall-clock timers, no DKG/
#                          reshare phasers — coexists in one chunk
#                          fine; no pair entry needed.
#   test_zz_selfheal.py    self-healing plane: retry policy, breakers,
#                          quorum repair, stale serving (host-only,
#                          structural crypto; ~5 s)
#   test_zz_timelock_serve.py  timelock serving tier
#   test_zz_vault_scale.py segment timelock vault (ISSUE 20): shard
#                          math coverage, SQLite<->segment CLI
#                          migration equivalence both directions,
#                          O(1)-at-depth status/pending_count,
#                          chunked-open crash resume, two-worker
#                          partitioned sweep, SSE open-notify +
#                          shedding, restart persistence (host-pinned
#                          by an autouse fixture; real crypto only on
#                          handfuls of ciphertexts; ~30 s). CONFLICTS
#                          evaluation vs test_daemon/
#                          test_mock_and_scale: pure tmp_path vaults
#                          and in-process aiohttp TestClient, no DKG/
#                          reshare phasers, no wall-clock timers
#                          beyond short sweep polls; vs
#                          test_zz_timelock_serve: same host-pinned
#                          batch fixture pattern, per-test vault
#                          dirs — coexists in one chunk fine; no pair
#                          entry needed.
#
# Exit status: 0 iff every chunk passed.

set -u
cd "$(dirname "$0")/.."

# pairs that must never share a chunk (space-separated file names);
# keep each pair alphabetically ordered — the SECOND member moves
CONFLICTS=(
    "tests/test_daemon.py tests/test_mock_and_scale.py"
)

# first arg is N only when it is a positive integer — otherwise it is a
# pytest arg and the default chunk count applies (a bad N must never
# yield a zero-iteration loop that exits 0 without running anything);
# --list is accepted before or after N
N=4
LIST=0
if [ "${1:-}" = "--list" ]; then
    LIST=1
    shift
fi
if [[ "${1:-}" =~ ^[0-9]+$ ]] && [ "$1" -ge 1 ]; then
    N=$1
    shift
fi
if [ "${1:-}" = "--list" ]; then
    LIST=1
    shift
fi

FILES=()
while IFS= read -r f; do FILES+=("$f"); done < <(ls tests/test_*.py | sort)

# round-robin assignment: chunk_of[i] = i % N
chunk_of=()
for ((i = 0; i < ${#FILES[@]}; i++)); do
    chunk_of[i]=$((i % N))
done

# find_pair_indices <a> <b>: sets PAIR_IA/PAIR_IB to the FILES indices
# (-1 when absent) — the one pair-matching rule, shared by the resolver
# and the flagger so they can never diverge
find_pair_indices() {
    PAIR_IA=-1 PAIR_IB=-1
    local i
    for ((i = 0; i < ${#FILES[@]}; i++)); do
        [ "${FILES[i]}" = "$1" ] && PAIR_IA=$i
        [ "${FILES[i]}" = "$2" ] && PAIR_IB=$i
    done
}

# structural conflict separation: move the later member of a
# same-chunk conflicting pair to the next chunk (deterministic; a
# no-op when round-robin already separated them or N=1)
if [ "$N" -gt 1 ]; then
    for pair in "${CONFLICTS[@]}"; do
        read -r a b <<<"$pair"
        find_pair_indices "$a" "$b"
        if [ "$PAIR_IA" -ge 0 ] && [ "$PAIR_IB" -ge 0 ] &&
            [ "${chunk_of[PAIR_IA]}" -eq "${chunk_of[PAIR_IB]}" ]; then
            chunk_of[PAIR_IB]=$(((chunk_of[PAIR_IB] + 1) % N))
        fi
    done
fi

# flag any conflicting pair still sharing a chunk (N=1, or a future
# three-way conflict the one-step move cannot untangle)
flag_conflicts() {
    local rc=0
    for pair in "${CONFLICTS[@]}"; do
        read -r a b <<<"$pair"
        find_pair_indices "$a" "$b"
        if [ "$PAIR_IA" -ge 0 ] && [ "$PAIR_IB" -ge 0 ] &&
            [ "${chunk_of[PAIR_IA]}" -eq "${chunk_of[PAIR_IB]}" ]; then
            echo "WARNING: known-conflicting pair in one chunk" \
                "($((chunk_of[PAIR_IA] + 1))/$N): $a + $b" >&2
            rc=1
        fi
    done
    return $rc
}

if [ "$LIST" -eq 1 ]; then
    for ((c = 0; c < N; c++)); do
        chunk=()
        for ((i = 0; i < ${#FILES[@]}; i++)); do
            [ "${chunk_of[i]}" -eq "$c" ] && chunk+=("${FILES[i]}")
        done
        echo "chunk $((c + 1))/$N: ${chunk[*]:-}"
    done
    flag_conflicts
    exit 0
fi

flag_conflicts || true

fail=0
for ((c = 0; c < N; c++)); do
    chunk=()
    for ((i = 0; i < ${#FILES[@]}; i++)); do
        [ "${chunk_of[i]}" -eq "$c" ] && chunk+=("${FILES[i]}")
    done
    [ ${#chunk[@]} -eq 0 ] && continue
    echo "=== chunk $((c + 1))/$N: ${chunk[*]}" >&2
    timeout -k 10 "${CHUNK_TIMEOUT:-870}" \
        env JAX_PLATFORMS=cpu python -m pytest "${chunk[@]}" -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
    rc=$?
    if [ $rc -ne 0 ]; then
        echo "=== chunk $((c + 1))/$N FAILED (rc=$rc)" >&2
        fail=1
    fi
done
exit $fail
