#!/usr/bin/env python
"""Static metrics lint: every metric declared in drand_tpu/metrics must be
referenced at least once outside its declaration module (no dead
catalogue entries — the `engine_device_batches` regression, ISSUE 1),
and metric names must be unique across the four registries (a duplicate
name silently splits one logical series across registries).

Run standalone (exit 1 on problems) or from the tier-1 suite
(tests/test_metrics.py::test_metrics_lint) so regressions fail fast.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
METRICS_FILE = REPO / "drand_tpu" / "metrics" / "__init__.py"
_METRIC_TYPES = {"Counter", "Gauge", "Histogram", "Summary", "Info"}


def declared_metrics() -> dict[str, str]:
    """python identifier -> prometheus metric name, parsed from the
    module-level assignments in drand_tpu/metrics/__init__.py."""
    tree = ast.parse(METRICS_FILE.read_text())
    out: dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        call = node.value
        if not (isinstance(target, ast.Name) and isinstance(call, ast.Call)):
            continue
        fn = call.func
        fn_name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        if fn_name not in _METRIC_TYPES or not call.args:
            continue
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out[target.id] = first.value
    return out


def _corpus() -> str:
    """Every python source that may legitimately reference a metric,
    minus the declaration module itself."""
    parts = []
    for base in ("drand_tpu", "tests", "tools", "scripts"):
        root = REPO / base
        if not root.is_dir():
            continue
        for path in sorted(root.rglob("*.py")):
            if path == METRICS_FILE:
                continue
            parts.append(path.read_text())
    bench = REPO / "bench.py"
    if bench.is_file():
        parts.append(bench.read_text())
    return "\n".join(parts)


def run_lint() -> list[str]:
    """-> list of problems (empty when clean)."""
    problems: list[str] = []
    decls = declared_metrics()
    if not decls:
        return ["no metric declarations found (parser broken?)"]
    seen: dict[str, str] = {}
    for py_name, metric_name in decls.items():
        if metric_name in seen:
            problems.append(
                f"duplicate metric name {metric_name!r}: declared as both "
                f"{seen[metric_name]} and {py_name}")
        seen[metric_name] = py_name
    corpus = _corpus()
    for py_name, metric_name in sorted(decls.items()):
        if not re.search(rf"\b{re.escape(py_name)}\b", corpus):
            problems.append(
                f"dead metric: {py_name} ({metric_name!r}) is declared but "
                f"never referenced outside drand_tpu/metrics")
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"check_metrics: {p}", file=sys.stderr)
    if not problems:
        print(f"check_metrics: OK ({len(declared_metrics())} metrics, "
              f"all referenced, names unique)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
