#!/usr/bin/env python
"""Static metrics lint: every metric declared in drand_tpu/metrics must be
referenced at least once outside its declaration module (no dead
catalogue entries — the `engine_device_batches` regression, ISSUE 1),
metric names must be unique across the four registries (a duplicate
name silently splits one logical series across registries), and the
engine_op_seconds ``path`` label values used at the dispatch sites must
come from the documented set (a typo'd path label would silently fork
the series operators alert on).

Run standalone (exit 1 on problems) or from the tier-1 suite
(tests/test_metrics.py::test_metrics_lint) so regressions fail fast.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
METRICS_FILE = REPO / "drand_tpu" / "metrics" / "__init__.py"
_METRIC_TYPES = {"Counter", "Gauge", "Histogram", "Summary", "Info"}

# engine_op_seconds base path labels (crypto/batch.py _timed); the
# _error/_invalid suffixes are appended dynamically on failure paths.
# "wire_rlc" is the device wire-pipeline RLC tier (ops/engine.py
# verify_wire_rlc: device hash-to-curve + in-graph lane-MSM, 2 Miller
# pairs per catch-up span).
KNOWN_ENGINE_PATHS = {"host", "device", "host_rlc", "wire_rlc"}
# known label VALUES per labelled counter whose cardinality is a fixed
# enum (new values need a deliberate catalogue update here + README)
KNOWN_LABEL_VALUES = {"hash_to_g2_cache_requests": {"result": {"hit",
                                                               "miss"}}}


def declared_metrics() -> dict[str, str]:
    """python identifier -> prometheus metric name, parsed from the
    module-level assignments in drand_tpu/metrics/__init__.py."""
    tree = ast.parse(METRICS_FILE.read_text())
    out: dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        call = node.value
        if not (isinstance(target, ast.Name) and isinstance(call, ast.Call)):
            continue
        fn = call.func
        fn_name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        if fn_name not in _METRIC_TYPES or not call.args:
            continue
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out[target.id] = first.value
    return out


def _corpus() -> str:
    """Every python source that may legitimately reference a metric,
    minus the declaration module itself."""
    parts = []
    for base in ("drand_tpu", "tests", "tools", "scripts"):
        root = REPO / base
        if not root.is_dir():
            continue
        for path in sorted(root.rglob("*.py")):
            if path == METRICS_FILE:
                continue
            parts.append(path.read_text())
    bench = REPO / "bench.py"
    if bench.is_file():
        parts.append(bench.read_text())
    return "\n".join(parts)


def engine_path_labels() -> set[str]:
    """Every literal ``path`` argument handed to crypto/batch.py's
    ``_timed`` dispatch timer (second positional arg)."""
    src = (REPO / "drand_tpu" / "crypto" / "batch.py").read_text()
    out: set[str] = set()
    for node in ast.walk(ast.parse(src)):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_timed"
                and len(node.args) >= 2):
            continue
        arg = node.args[1]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.add(arg.value)
        else:
            out.add("<dynamic>")
    return out


def labels_used(corpus: str, identifier: str) -> dict[str, set[str]]:
    """Literal ``IDENT.labels(key="value")`` kwargs across the corpus."""
    out: dict[str, set[str]] = {}
    pat = rf"\b{re.escape(identifier)}\.labels\(([^)]*)\)"
    for m in re.finditer(pat, corpus):
        for k, v in re.findall(r"(\w+)\s*=\s*[\"']([^\"']+)[\"']",
                               m.group(1)):
            out.setdefault(k, set()).add(v)
    return out


def run_lint() -> list[str]:
    """-> list of problems (empty when clean)."""
    problems: list[str] = []
    decls = declared_metrics()
    if not decls:
        return ["no metric declarations found (parser broken?)"]
    seen: dict[str, str] = {}
    for py_name, metric_name in decls.items():
        if metric_name in seen:
            problems.append(
                f"duplicate metric name {metric_name!r}: declared as both "
                f"{seen[metric_name]} and {py_name}")
        seen[metric_name] = py_name
    corpus = _corpus()
    for py_name, metric_name in sorted(decls.items()):
        if not re.search(rf"\b{re.escape(py_name)}\b", corpus):
            problems.append(
                f"dead metric: {py_name} ({metric_name!r}) is declared but "
                f"never referenced outside drand_tpu/metrics")
    # engine_op_seconds path labels at the dispatch sites must be from
    # the documented set (suffixes are appended dynamically)
    for path in sorted(engine_path_labels()):
        if path not in KNOWN_ENGINE_PATHS:
            problems.append(
                f"unknown engine_op_seconds path label {path!r} in "
                f"crypto/batch.py (known: {sorted(KNOWN_ENGINE_PATHS)})")
    # fixed-enum label values: literal uses must be in the catalogue
    name_to_py = {v: k for k, v in decls.items()}
    for metric_name, expected in KNOWN_LABEL_VALUES.items():
        py_name = name_to_py.get(metric_name)
        if py_name is None:
            problems.append(
                f"KNOWN_LABEL_VALUES names undeclared metric "
                f"{metric_name!r}")
            continue
        used = labels_used(corpus, py_name)
        if not used:
            # a configured metric with zero literal label uses means the
            # check validates nothing — e.g. values routed through a
            # wrapper variable; keep call-site values literal instead
            problems.append(
                f"{metric_name}: no literal .labels(...) uses found — "
                f"the KNOWN_LABEL_VALUES lint cannot validate it")
        for key, values in used.items():
            bad = values - expected.get(key, set())
            if bad:
                problems.append(
                    f"{metric_name}: unexpected {key} label value(s) "
                    f"{sorted(bad)} (known: {sorted(expected.get(key, set()))})")
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"check_metrics: {p}", file=sys.stderr)
    if not problems:
        print(f"check_metrics: OK ({len(declared_metrics())} metrics, "
              f"all referenced, names unique)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
