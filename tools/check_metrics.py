#!/usr/bin/env python
"""Static metrics lint: every metric declared in drand_tpu/metrics must be
referenced at least once outside its declaration module (no dead
catalogue entries — the `engine_device_batches` regression, ISSUE 1),
metric names must be unique across the four registries (a duplicate
name silently splits one logical series across registries), every
declaration must carry real help text (ISSUE 6: operators read the
catalogue off /metrics), and the engine_op_seconds ``path`` label
values used at the dispatch sites must come from the documented set (a
typo'd path label would silently fork the series operators alert on).

The Grafana dashboard (tools/grafana/drand_tpu.json) is cross-checked
too: every metric its PromQL expressions reference must exist in the
catalogue (counters may appear with the exposition-format ``_total``
suffix, histograms with ``_bucket``/``_sum``/``_count``) — a dashboard
panel silently flat at zero because of a renamed metric is exactly the
failure mode this lint exists to catch.

Run standalone (exit 1 on problems) or from the tier-1 suite
(tests/test_metrics.py::test_metrics_lint) so regressions fail fast.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
METRICS_FILE = REPO / "drand_tpu" / "metrics" / "__init__.py"
DASHBOARD_FILE = REPO / "tools" / "grafana" / "drand_tpu.json"
_METRIC_TYPES = {"Counter", "Gauge", "Histogram", "Summary", "Info"}

# PromQL functions/keywords/aggregators that appear as bare identifiers
# in dashboard expressions and are NOT metric names
_PROMQL_WORDS = {
    "rate", "irate", "increase", "delta", "deriv", "sum", "avg", "min",
    "max", "count", "by", "without", "on", "ignoring", "group_left",
    "group_right", "histogram_quantile", "quantile", "topk", "bottomk",
    "abs", "clamp_min", "clamp_max", "label_replace", "label_join",
    "time", "vector", "scalar", "offset", "and", "or", "unless", "le",
    "bool", "avg_over_time", "max_over_time", "min_over_time",
    "sum_over_time", "count_over_time", "increase_over_time",
}
# exposition-format suffixes prometheus_client appends to the declared
# name (counters -> _total; histograms -> _bucket/_sum/_count)
_SAMPLE_SUFFIXES = ("_total", "_bucket", "_sum", "_count")

# engine_op_seconds base path labels (crypto/batch.py _timed); the
# _error/_invalid suffixes are appended dynamically on failure paths.
# "wire_rlc" is the device wire-pipeline RLC tier (ops/engine.py
# verify_wire_rlc: device hash-to-curve + in-graph lane-MSM, 2 Miller
# pairs per catch-up span); "wire_rlc_sharded" is the same tier with
# the combine sharded over the batch axis of the engine mesh (one
# cross-shard reduction, still one pairing row per span).
# "host_shared" is the timelock round-open host tier
# (crypto/timelock.decrypt_batch: one shared-signature Miller-line
# precomputation for the whole round, per-item evaluation only).
KNOWN_ENGINE_PATHS = {"host", "device", "host_rlc", "wire_rlc",
                      "wire_rlc_sharded", "host_shared"}
# known label VALUES per labelled counter whose cardinality is a fixed
# enum (new values need a deliberate catalogue update here + README)
KNOWN_LABEL_VALUES = {
    "hash_to_g2_cache_requests": {"result": {"hit", "miss"}},
    "timelock_gt_cache_requests": {"result": {"hit", "miss"}},
    "timelock_ciphertexts_total": {"result": {"submitted", "opened",
                                              "rejected"}},
    # threshold flight recorder (obs/flight.py, ISSUE 10). The `index`
    # label of beacon_partial_events_total is the share index — dynamic
    # but bounded by the group size, so only the `event` enum is pinned
    # here (non-literal label kwargs are invisible to labels_used by
    # design).
    "beacon_partial_arrival_seconds": {"source": {"grpc", "gossip",
                                                  "self"}},
    "beacon_partial_events_total": {"event": {"contributed", "late",
                                              "invalid"}},
    "dkg_phase_seconds": {"phase": {"deal", "response", "justification",
                                    "finish"}},
    # fault-detection set (obs/flight.py reachability, ISSUE 11). The
    # `index` label of beacon_peer_sends_total / beacon_peer_reachable
    # is the share index — dynamic but bounded by the group size (the
    # beacon_partial_events_total rule), so only the `outcome` enum is
    # pinned here.
    "beacon_peer_sends_total": {"outcome": {"ok", "failed"}},
    # the `verdict` label is the handler/gossip rejection string —
    # minted only by code paths (invalid/stale/future/duplicate),
    # passed through a variable so only `source` is literal-checkable
    # here
    "beacon_ingress_rejects_total": {"source": {"grpc", "gossip",
                                                "self"}},
    # self-healing set (ISSUE 12). net_retry_attempts_total's `op` is
    # the call-site tag (partial|sync|repair|control|gossip|timelock) —
    # bounded by the code paths that mint it, passed through the retry
    # helper as a variable, so only `outcome` is literal-checkable.
    "net_retry_attempts_total": {"outcome": {"ok", "retry", "exhausted",
                                             "rejected"}},
    "beacon_partial_repairs_total": {"outcome": {"recovered", "synced",
                                                 "failed"}},
    # edge fan-out set (ISSUE 14): the hub's proto labels are
    # branch-literal (http_server/fanout.py _wakeup_counter), the shed
    # reasons literal at both shed sites, the store backend literal in
    # each backend's read path
    "relay_wakeups_total": {"proto": {"sse", "ndjson"}},
    "relay_shed_total": {"reason": {"watcher_cap", "slow_consumer",
                                    "timelock_slow"}},
    "chain_store_reads_total": {"backend": {"sqlite", "segment"}},
    # timelock at scale (ISSUE 20): vault reads literal in each
    # backend's get() path, notify events branch-literal in
    # TimelockNotifyHub.publish_open
    "vault_reads_total": {"backend": {"sqlite", "segment"}},
    "timelock_notify_total": {"event": {"opened", "rejected"}},
    # incident engine (ISSUE 15): every rule carries its canonical
    # severity at a branch-literal call site (obs/incident.py
    # _incident_counter — the flight.py label-helper pattern); unknown
    # operator rules collapse to rule="custom"
    "incidents_total": {
        "rule": {"missed_round", "readiness_flip", "breaker_open",
                 "reachability_drop", "sync_stall", "margin_degraded",
                 "ingress_flood", "shed_surge", "worker_down", "custom"},
        "severity": {"critical", "major", "warning"},
    },
    # auto-remediation (ISSUE 16): outcomes are branch-literal in
    # obs/remediate.py _action_counter (the `playbook` label there
    # rides a variable — bounded by the playbook registry, the
    # net_retry `op` rule); the active gauge's playbooks ARE
    # branch-literal (_active_gauge), unknown ones collapse to
    # playbook="custom"
    "remediation_actions_total": {
        "outcome": {"ok", "failed", "dry_run", "budget_exhausted",
                    "reverted"},
    },
    "remediation_active": {
        "playbook": {"sync_resume", "quorum_pull", "partition_posture",
                     "respawn_worker", "reshare_recommend", "custom"},
    },
    # million-client catch-up (ISSUE 17): checkpoint bootstrap results
    # are branch-literal in client/verify.py _maybe_bootstrap (ok after
    # the spot-check passes, rejected when the signed checkpoint fails
    # verification and the client falls back to the full walk)
    "checkpoint_bootstraps_total": {"result": {"ok", "rejected"}},
    # large-group ceremonies (ISSUE 19): every phase/verdict pair is
    # branch-literal at its mint site (dkg/protocol.py verification
    # paths + dkg/board.py _accept signature check) — a misbehaving
    # dealer in an n=1024 ceremony is attributable, not silently
    # dropped
    "dkg_bundle_rejects_total": {
        "phase": {"deal", "response", "justification"},
        "verdict": {"bad_signature", "wrong_threshold", "bad_point",
                    "binding_mismatch", "bad_share", "unknown_dealer"},
    },
}


def _declarations() -> list[tuple[str, str, str]]:
    """(python identifier, prometheus name, help text) triples parsed
    from the module-level assignments in drand_tpu/metrics/__init__.py.
    Help is the second positional arg ('' when absent/non-literal)."""
    tree = ast.parse(METRICS_FILE.read_text())
    out: list[tuple[str, str, str]] = []
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        call = node.value
        if not (isinstance(target, ast.Name) and isinstance(call, ast.Call)):
            continue
        fn = call.func
        fn_name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        if fn_name not in _METRIC_TYPES or not call.args:
            continue
        first = call.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        help_text = ""
        if len(call.args) > 1:
            second = call.args[1]
            if isinstance(second, ast.Constant) \
                    and isinstance(second.value, str):
                help_text = second.value
            else:
                # implicit adjacent-literal concatenation parses as a
                # Constant already; anything else (f-string, name) is a
                # lint problem surfaced by the empty help below
                try:
                    help_text = ast.literal_eval(second)
                except (ValueError, SyntaxError):
                    help_text = ""
        out.append((target.id, first.value, help_text))
    return out


def declared_metrics() -> dict[str, str]:
    """python identifier -> prometheus metric name."""
    return {py: name for py, name, _ in _declarations()}


def _corpus() -> str:
    """Every python source that may legitimately reference a metric,
    minus the declaration module itself."""
    parts = []
    for base in ("drand_tpu", "tests", "tools", "scripts"):
        root = REPO / base
        if not root.is_dir():
            continue
        for path in sorted(root.rglob("*.py")):
            if path == METRICS_FILE:
                continue
            parts.append(path.read_text())
    bench = REPO / "bench.py"
    if bench.is_file():
        parts.append(bench.read_text())
    return "\n".join(parts)


def engine_path_labels() -> set[str]:
    """Every literal ``path`` argument handed to crypto/batch.py's
    ``_timed`` dispatch timer (second positional arg)."""
    src = (REPO / "drand_tpu" / "crypto" / "batch.py").read_text()
    out: set[str] = set()
    for node in ast.walk(ast.parse(src)):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_timed"
                and len(node.args) >= 2):
            continue
        arg = node.args[1]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.add(arg.value)
        else:
            out.add("<dynamic>")
    return out


def labels_used(corpus: str, identifier: str) -> dict[str, set[str]]:
    """Literal ``IDENT.labels(key="value")`` kwargs across the corpus."""
    out: dict[str, set[str]] = {}
    pat = rf"\b{re.escape(identifier)}\.labels\(([^)]*)\)"
    for m in re.finditer(pat, corpus):
        for k, v in re.findall(r"(\w+)\s*=\s*[\"']([^\"']+)[\"']",
                               m.group(1)):
            out.setdefault(k, set()).add(v)
    return out


def dashboard_metric_refs(path: pathlib.Path = DASHBOARD_FILE) -> set[str]:
    """Every metric-shaped identifier referenced by the dashboard's
    PromQL expressions. Label selectors ``{...}`` and range selectors
    ``[...]`` are stripped first (their contents are label names/values
    and durations, not metrics); remaining identifiers that are not
    PromQL functions/keywords are metric references — our catalogue
    names all contain '_', which also filters stray words."""
    import json

    doc = json.loads(path.read_text())
    exprs: list[str] = []

    def walk(node):
        if isinstance(node, dict):
            if isinstance(node.get("expr"), str):
                exprs.append(node["expr"])
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc.get("panels", []))
    refs: set[str] = set()
    for expr in exprs:
        cleaned = re.sub(r"\{[^}]*\}", "", expr)
        cleaned = re.sub(r"\[[^\]]*\]", "", cleaned)
        for tok in re.findall(r"[a-zA-Z_][a-zA-Z0-9_]*", cleaned):
            if tok in _PROMQL_WORDS or "_" not in tok:
                continue
            refs.add(tok)
    return refs


def check_dashboard(decls: dict[str, str]) -> list[str]:
    """Cross-check the Grafana dashboard against the catalogue."""
    if not DASHBOARD_FILE.is_file():
        return [f"dashboard missing: {DASHBOARD_FILE}"]
    try:
        refs = dashboard_metric_refs()
    except ValueError as e:
        return [f"dashboard is not valid JSON: {e}"]
    if not refs:
        return ["dashboard references no metrics (extractor broken?)"]
    known = set(decls.values())
    problems = []
    for ref in sorted(refs):
        candidates = {ref}
        for suf in _SAMPLE_SUFFIXES:
            if ref.endswith(suf):
                candidates.add(ref[: -len(suf)])
        if not candidates & known:
            problems.append(
                f"dashboard references unknown metric {ref!r} "
                f"(tools/grafana/drand_tpu.json vs the catalogue)")
    return problems


def run_lint() -> list[str]:
    """-> list of problems (empty when clean)."""
    problems: list[str] = []
    triples = _declarations()
    decls = {py: name for py, name, _ in triples}
    if not decls:
        return ["no metric declarations found (parser broken?)"]
    seen: dict[str, str] = {}
    for py_name, metric_name, help_text in triples:
        if metric_name in seen:
            problems.append(
                f"duplicate metric name {metric_name!r}: declared as both "
                f"{seen[metric_name]} and {py_name}")
        seen[metric_name] = py_name
        if len(help_text.strip()) < 10:
            problems.append(
                f"{py_name} ({metric_name!r}): missing/too-short help "
                f"text — the catalogue is operator documentation")
    corpus = _corpus()
    for py_name, metric_name in sorted(decls.items()):
        if not re.search(rf"\b{re.escape(py_name)}\b", corpus):
            problems.append(
                f"dead metric: {py_name} ({metric_name!r}) is declared but "
                f"never referenced outside drand_tpu/metrics")
    # engine_op_seconds path labels at the dispatch sites must be from
    # the documented set (suffixes are appended dynamically)
    for path in sorted(engine_path_labels()):
        if path not in KNOWN_ENGINE_PATHS:
            problems.append(
                f"unknown engine_op_seconds path label {path!r} in "
                f"crypto/batch.py (known: {sorted(KNOWN_ENGINE_PATHS)})")
    # fixed-enum label values: literal uses must be in the catalogue
    name_to_py = {v: k for k, v in decls.items()}
    for metric_name, expected in KNOWN_LABEL_VALUES.items():
        py_name = name_to_py.get(metric_name)
        if py_name is None:
            problems.append(
                f"KNOWN_LABEL_VALUES names undeclared metric "
                f"{metric_name!r}")
            continue
        used = labels_used(corpus, py_name)
        if not used:
            # a configured metric with zero literal label uses means the
            # check validates nothing — e.g. values routed through a
            # wrapper variable; keep call-site values literal instead
            problems.append(
                f"{metric_name}: no literal .labels(...) uses found — "
                f"the KNOWN_LABEL_VALUES lint cannot validate it")
        for key, values in used.items():
            bad = values - expected.get(key, set())
            if bad:
                problems.append(
                    f"{metric_name}: unexpected {key} label value(s) "
                    f"{sorted(bad)} (known: {sorted(expected.get(key, set()))})")
    problems.extend(check_dashboard(decls))
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"check_metrics: {p}", file=sys.stderr)
    if not problems:
        print(f"check_metrics: OK ({len(declared_metrics())} metrics, "
              f"all referenced, names unique)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
