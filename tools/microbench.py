"""Microbenchmarks for the TPU compute primitives the field arithmetic
could be built from. Informs the roofline note (ROOFLINE.md): measures
sustained throughput of

  - int32 elementwise multiply-add on the VPU (current ops/bl.py core)
  - f32 elementwise multiply-add on the VPU (candidate: float limbs)
  - bf16 MXU matmul with f32 accumulation (candidate: constant-Toeplitz
    REDC, exact for 8-bit limb operands)
  - int8 MXU matmul with int32 accumulation (candidate alternative)

Each case runs inside ONE Pallas kernel (the axon stack's XLA glue
miscompile makes plain-XLA loops untrustworthy; Mosaic is the production
path anyway) as a dependent fori_loop chain over live VMEM tiles.

Usage: python tools/microbench.py [reps]
"""

from __future__ import annotations

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from drand_tpu.utils.jit_cache import enable_persistent_cache

enable_persistent_cache()

N_ITERS = 512


def _time(fn, *args, reps=5):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else \
        fn(*args).block_until_ready()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best


def _pallas1(kernel, out_sd):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        kernel, out_shape=out_sd,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM))


def vpu_kernel(x_ref, y_ref, o_ref):
    y = y_ref[:]

    def body(i, x):
        return x * y + y

    o_ref[:] = jax.lax.fori_loop(0, N_ITERS, body, x_ref[:])


def mxu_kernel(a_ref, b_ref, o_ref):
    a = a_ref[:]

    def body(i, acc):
        return jnp.dot(acc.astype(a_ref.dtype), a,
                       preferred_element_type=o_ref.dtype)

    o_ref[:] = jax.lax.fori_loop(
        0, N_ITERS, body, b_ref[:].astype(o_ref.dtype))


def run():
    results = {}
    # --- VPU elementwise: (256, 128) tile, 512 dependent mul+add ---
    shape = (256, 128)
    n_ops = N_ITERS * shape[0] * shape[1] * 2  # mul + add
    for dtype, name in ((jnp.int32, "vpu_int32"), (jnp.float32, "vpu_f32"),
                        (jnp.bfloat16, "vpu_bf16")):
        x = jnp.ones(shape, dtype)
        y = jnp.ones(shape, dtype)
        fn = jax.jit(_pallas1(vpu_kernel,
                              jax.ShapeDtypeStruct(shape, dtype)))
        dt = _time(fn, x, y)
        results[name] = n_ops / dt / 1e9
        print(f"{name:12s} {n_ops / dt / 1e9:10.1f} Gop/s  ({dt*1e3:.2f} ms)")

    # --- MXU matmul: (128,128)@(128,128) chains ---
    for in_dt, acc_dt, name in (
            (jnp.bfloat16, jnp.float32, "mxu_bf16_f32"),
            (jnp.int8, jnp.int32, "mxu_int8_i32"),
            (jnp.float32, jnp.float32, "mxu_f32_f32")):
        m = 128
        a = jnp.ones((m, m), in_dt)
        b = jnp.ones((m, m), in_dt)
        n_ops = N_ITERS * m * m * m * 2
        try:
            fn = jax.jit(_pallas1(mxu_kernel,
                                  jax.ShapeDtypeStruct((m, m), acc_dt)))
            dt = _time(fn, a, b)
            results[name] = n_ops / dt / 1e12
            print(f"{name:12s} {n_ops / dt / 1e12:10.2f} Top/s  "
                  f"({dt*1e3:.2f} ms)")
        except Exception as e:  # noqa: BLE001 - probing lowering support
            print(f"{name:12s} UNSUPPORTED: {type(e).__name__}: "
                  f"{str(e)[:200]}")
    return results


if __name__ == "__main__":
    print("devices:", jax.devices())
    run()
