"""Prototype: Miller loop as a Pallas grid over iterations (small body per
step, scratch-carried state) vs the current single-fori_loop kernel.

Hypothesis: the 63-iteration fori_loop body is too large for good Mosaic
register allocation (measured 15M fp-mul/s vs 157M for a lean chain
kernel); a grid step per iteration should compile to far better code.

Usage: python tools/proto_miller_grid.py [B]
"""

from __future__ import annotations

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from drand_tpu.utils.jit_cache import enable_persistent_cache

enable_persistent_cache()

from drand_tpu.ops import bl
from drand_tpu.ops import pallas_pairing as pp
from drand_tpu.ops.bl import NLIMBS, DTYPE, f12_conj


def _miller_grid_kernel(flags_ref, c_ref, xp_ref, yp_ref, q_ref, o_ref,
                        f_ref, tx_ref, ty_ref, tz_ref):
    """One Miller iteration per grid step. flags_ref is scalar-prefetched
    SMEM; state persists in scratch across steps."""
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    with bl.const_context(c_ref[:]):
        xp, yp, q = xp_ref[:], yp_ref[:], q_ref[:]
        npairs = q.shape[0]
        b = q.shape[-1]
        xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]

        @pl.when(i == 0)
        def _init():
            one_fp = jnp.broadcast_to(
                bl._crow("ONE"), xq.shape[:-3] + (NLIMBS, b)).astype(DTYPE)
            f_ref[:] = bl.f12_one((), b)
            tx_ref[:] = xq
            ty_ref[:] = yq
            tz_ref[:] = jnp.stack([one_fp, jnp.zeros_like(one_fp)], axis=-3)

        f = bl.f12_sqr(f_ref[:])
        T, lines = pp._dbl_step((tx_ref[:], ty_ref[:], tz_ref[:]), xp, yp)
        f_ref[:] = pp._sparse_mul_035(f, lines, npairs, split=True)
        tx_ref[:], ty_ref[:], tz_ref[:] = T

        @pl.when(flags_ref[i] != 0)
        def _add():
            Ta, lines_a = pp._add_step(
                (tx_ref[:], ty_ref[:], tz_ref[:]), q, xp, yp)
            f_ref[:] = pp._sparse_mul_035(f_ref[:], lines_a, npairs,
                                          split=True)
            tx_ref[:], ty_ref[:], tz_ref[:] = Ta

        @pl.when(i == pl.num_programs(0) - 1)
        def _fin():
            o_ref[:] = f12_conj(f_ref[:])


def miller_grid(xp, yp, q):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    npairs, b = q.shape[0], q.shape[-1]
    f12_dims = (2, 3, 2, NLIMBS, b)
    t_dims = (npairs, 2, NLIMBS, b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pp.N_MILLER,),
        in_specs=[
            pl.BlockSpec(bl.CONST_BUFFER.shape, lambda i, *_: (0, 0)),
            pl.BlockSpec(xp.shape, lambda i, *_: (0,) * xp.ndim),
            pl.BlockSpec(yp.shape, lambda i, *_: (0,) * yp.ndim),
            pl.BlockSpec(q.shape, lambda i, *_: (0,) * q.ndim),
        ],
        out_specs=pl.BlockSpec(f12_dims, lambda i, *_: (0,) * 5),
        scratch_shapes=[pltpu.VMEM(f12_dims, DTYPE),
                        pltpu.VMEM(t_dims, DTYPE),
                        pltpu.VMEM(t_dims, DTYPE),
                        pltpu.VMEM(t_dims, DTYPE)],
    )
    fn = pl.pallas_call(
        _miller_grid_kernel,
        out_shape=jax.ShapeDtypeStruct(f12_dims, DTYPE),
        grid_spec=grid_spec)
    flags = jnp.asarray(pp.MILLER_FLAGS[0], dtype=jnp.int32)
    return fn(flags, jnp.asarray(bl.CONST_BUFFER), xp, yp, q)


def run(B=128):
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2
    from drand_tpu.ops import limb
    from drand_tpu.ops.engine import _g1_aff, _g2_aff

    sk = 0x1F3A
    pub_aff = _g1_aff(PointG1.generator().mul(sk))
    sigs, msgs = [], []
    for i in range(8):
        m = b"bench-%d" % i
        msgs.append(_g2_aff(hash_to_g2(m)))
        sigs.append(_g2_aff(PointG2.from_bytes(bls.sign(sk, m),
                                               subgroup_check=False)))
    pubs = np.broadcast_to(pub_aff, (B, 2, limb.NLIMBS))
    sigs = np.stack([sigs[i % 8] for i in range(B)])
    msgs = np.stack([msgs[i % 8] for i in range(B)])
    xp, yp, q = pp.pack_verify_inputs(pubs, sigs, msgs)

    grid_fn = jax.jit(miller_grid)
    t0 = time.perf_counter()
    out_g = np.asarray(grid_fn(xp, yp, q))
    print(f"grid miller: compile+run {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    # reference: existing fused kernel
    consts = jnp.asarray(bl.CONST_BUFFER)
    f12_shape = jax.ShapeDtypeStruct((2, 3, 2, NLIMBS, B), DTYPE)
    f12_dims = (2, 3, 2, NLIMBS, B)
    t_dims = (2, 2, NLIMBS, B)
    old_fn = jax.jit(lambda c, fl, x, y, qq: pp._pallas(
        pp._miller_kernel, f12_shape, "vsvvv",
        scratch_shapes=(f12_dims, t_dims, t_dims, t_dims))(c, fl, x, y, qq))
    flags = jnp.asarray(pp.MILLER_FLAGS)
    out_o = np.asarray(old_fn(consts, flags, xp, yp, q))
    same = (out_g == out_o).all()
    print(f"outputs identical: {same}")
    if not same:
        print("MISMATCH", np.argwhere(out_g != out_o)[:5])

    K = 48
    for name, fn, args in (("old", old_fn, (consts, flags, xp, yp, q)),
                           ("grid", grid_fn, (xp, yp, q))):
        o = None
        t0 = time.perf_counter()
        for _ in range(K):
            if o is not None:  # chain a dependency to force ordering
                dep = (o[0, 0, 0, :1, :1] * 0)
                a0 = args[-3] + dep[None] if name == "grid" else args[0]
                o = fn(*((a0,) + args[1:])) if name == "grid" else \
                    fn(args[0], args[1], args[2] + dep[None], args[3],
                       args[4])
            else:
                o = fn(*args)
        np.asarray(o)
        dt = (time.perf_counter() - t0) / K
        print(f"{name}: {dt*1e3:.2f} ms/call @ B={B}")


if __name__ == "__main__":
    run(int(sys.argv[1]) if len(sys.argv) > 1 else 128)
