"""Prototype: Montgomery REDC as int8 MXU matmuls inside a Pallas kernel.

Validates exactness vs the host oracle and times a chain of stacked
f2_mul-style multiplies (the pairing's inner op) with the current
all-VPU mont_mul vs the MXU-REDC variant. Decides the bl.py redesign.

Usage: python tools/proto_mxu.py [B] [iters]
"""

from __future__ import annotations

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from drand_tpu.utils.jit_cache import enable_persistent_cache

enable_persistent_cache()

from drand_tpu.crypto.fields import P, Fp
from drand_tpu.ops import bl
from drand_tpu.ops import limb as _x

NLIMBS = bl.NLIMBS
MASK = bl.MASK
BITS = bl.BITS

# --- constant Toeplitz matrices, int8 6/6-bit split -----------------------

def _toeplitz(limbs, out_len):
    t = np.zeros((out_len, NLIMBS), np.int64)
    for k in range(out_len):
        for i in range(NLIMBS):
            j = k - i
            if 0 <= j < NLIMBS:
                t[k, i] = limbs[j]
    return t


def _split_matrix(limbs, out_len):
    """(4*out_len, 2*NLIMBS) int8 block matrix for inputs [x_lo; x_hi]
    (7-bit split) against entries e = e_lo + 64*e_hi (6-bit split).
    Output row blocks: S_ll, S_hl, S_lh, S_hh with weights 1,64,128,8192."""
    t = _toeplitz(limbs, out_len)
    e_lo, e_hi = t & 63, t >> 6
    z = np.zeros_like(t)
    g = np.block([[e_lo, z], [e_hi, z], [z, e_lo], [z, e_hi]])
    assert g.max() <= 127
    return g.astype(np.int8)


G_NPRIME = _split_matrix(np.asarray(_x._NPRIME_LIMBS, np.int64), NLIMBS)
G_P = _split_matrix(np.asarray(_x.P_LIMBS, np.int64), 2 * NLIMBS)


def _redc_matmul(g, x):
    """x: (..., 32, B) limbs <= 2^13 -> combined conv with the constant
    matrix, exact. Leading dims handled by a static unrolled loop."""
    r4 = g.shape[0]
    r = r4 // 4
    x_lo = (x & 127).astype(jnp.int8)
    x_hi = (x >> 7).astype(jnp.int8)
    xs = jnp.concatenate([x_lo, x_hi], axis=-2)  # (..., 64, B)
    lead = x.shape[:-2]
    if lead:
        flat = xs.reshape((-1,) + xs.shape[-2:])
        outs = [jax.lax.dot_general(
            g, flat[i], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
            for i in range(flat.shape[0])]
        s = jnp.stack(outs, axis=0).reshape(lead + (r4, x.shape[-1]))
    else:
        s = jax.lax.dot_general(g, xs, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.int32)
    ll = s[..., 0 * r:1 * r, :]
    hl = s[..., 1 * r:2 * r, :]
    lh = s[..., 2 * r:3 * r, :]
    hh = s[..., 3 * r:4 * r, :]
    return ll + (hl << 6) + (lh << 7) + (hh << 13)


_G2 = None
_G3 = None


def mont_mul_mxu(a, b):
    """bl.mont_mul with the two constant-operand convolutions on the MXU."""
    t = bl._conv(a, b, 2 * NLIMBS)
    t = bl._fold(t, rounds=3, grow=True)
    m = _redc_matmul(_G2, t[..., :NLIMBS, :])
    m = bl._fold_drop(m, rounds=3)
    u = _redc_matmul(_G3, m)
    z = jnp.zeros_like(u[..., :1, :])
    u = jnp.concatenate([u, z], axis=-2) + t
    u = bl._fold(u, rounds=3, grow=True)
    k = jnp.any(u[..., :NLIMBS, :] != 0, axis=-2).astype(bl.DTYPE)
    hi = u[..., NLIMBS:, :]
    r_ = jnp.concatenate([hi[..., :1, :] + k[..., None, :], hi[..., 1:, :]],
                         axis=-2)
    return bl._wrap(bl._fold(r_, rounds=1, grow=False), passes=2)


# --- kernels ---------------------------------------------------------------

def _chain_kernel(n_iters, use_mxu, c_ref, g2_ref, g3_ref, a_ref, b_ref,
                  o_ref):
    global _G2, _G3
    with bl.const_context(c_ref[:]):
        _G2, _G3 = g2_ref[:], g3_ref[:]
        mm = mont_mul_mxu if use_mxu else bl.mont_mul
        a, b = a_ref[:], b_ref[:]

        def body(i, a):
            return mm(a, b)

        o_ref[:] = jax.lax.fori_loop(0, n_iters, body, a)


def run(batch=128, iters=64):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import random
    rnd = random.Random(7)
    vals_a = [rnd.randrange(P) for _ in range(batch)]
    vals_b = [rnd.randrange(P) for _ in range(batch)]
    vals_a[0], vals_b[0] = P - 1, P - 1
    # stacked leading dim 3, mirroring f2_mul's Karatsuba stack
    a = jnp.broadcast_to(bl.pack_fp(vals_a), (3, NLIMBS, batch))
    b = jnp.broadcast_to(bl.pack_fp(vals_b), (3, NLIMBS, batch))

    lanebuf = bl.lane_buffer(batch)
    out_sd = jax.ShapeDtypeStruct((3, NLIMBS, batch), bl.DTYPE)

    results = {}
    for use_mxu in (False, True):
        kern = functools.partial(_chain_kernel, iters, use_mxu)
        fn = jax.jit(pl.pallas_call(
            kern, out_shape=out_sd,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM)))
        args = (jnp.asarray(lanebuf), jnp.asarray(G_NPRIME),
                jnp.asarray(G_P), a, b)
        t0 = time.perf_counter()
        out = np.asarray(fn(*args))
        print(f"mxu={use_mxu}: compile+run {time.perf_counter()-t0:.1f}s",
              file=sys.stderr)
        # correctness vs host: a * b^iters * R^-iters... just iterate host
        R_INV = pow(1 << 384, -1, P)
        host = list(vals_a)
        for _ in range(iters):
            host = [(x * y * R_INV) % P for x, y in zip(host, vals_b)]
        got = bl.unpack_fp(out[0])
        exp = [(h * 1) % P for h in host]
        ok = got == exp
        print(f"mxu={use_mxu}: correct={ok}")
        if not ok:
            badidx = [i for i, (g, e) in enumerate(zip(got, exp)) if g != e]
            print(f"  bad lanes: {badidx[:8]} ...", file=sys.stderr)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            best = min(best, time.perf_counter() - t0)
        n_mm = iters * 3
        print(f"mxu={use_mxu}: {best*1e3:.2f} ms for {n_mm} stacked "
              f"mont_muls @ B={batch} -> "
              f"{n_mm * batch / best / 1e6:.2f} M fp-mul/s")
        results[use_mxu] = best
    if False in results and True in results:
        print(f"speedup: {results[False] / results[True]:.2f}x")


if __name__ == "__main__":
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    it = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    run(b, it)
