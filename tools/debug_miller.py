"""Bisect the device Miller loop against a host big-int simulation of the
SAME formulas (value level, mod p) to locate a wrong operation.

Usage: PYTHONPATH= JAX_PLATFORMS=cpu python tools/debug_miller.py [msg-id]
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from drand_tpu.utils.jit_cache import enable_persistent_cache

enable_persistent_cache()

import numpy as np
import jax
import jax.numpy as jnp

from drand_tpu.crypto import bls
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.hash_to_curve import hash_to_g2
from drand_tpu.crypto.fields import Fp2, P
from drand_tpu.ops import pallas_pairing as pp, limb
from drand_tpu.ops.engine import _g1_aff, _g2_aff

XI = None  # placeholder


def xi_mul(a: Fp2) -> Fp2:
    return Fp2((a.c0 - a.c1) % P, (a.c0 + a.c1) % P)


def s_mul(a: Fp2, k: int) -> Fp2:
    return Fp2(a.c0 * k % P, a.c1 * k % P)


def fp_mul(a: Fp2, s: int) -> Fp2:
    return Fp2(a.c0 * s % P, a.c1 * s % P)


def dbl_step(T, xp, yp):
    X, Y, Z = T
    X2 = X.square(); Y2 = Y.square(); Z2 = Z.square()
    Z3 = Z2 * Z; YZ3 = Y * Z3
    lam_s = s_mul(X2 * Z2, 3)
    c0 = xi_mul(fp_mul(s_mul(YZ3, 2), yp))
    c5 = -fp_mul(lam_s, xp)
    X3cu = X2 * X
    c3 = s_mul(X3cu, 3) - s_mul(Y2, 2)
    C = Y2.square()
    D = s_mul((X + Y2).square() - (X2 + C), 2)
    E = s_mul(X2, 3)
    F = E.square()
    Xn = F - s_mul(D, 2)
    Yn = E * (D - Xn) - s_mul(C, 8)
    Zn = s_mul(Y * Z, 2)
    return (Xn, Yn, Zn), (c0, c3, c5)


def add_step(T, q, xp, yp):
    X, Y, Z = T
    xq, yq = q
    Z2 = Z.square(); Z3 = Z2 * Z
    U2 = xq * Z2; S2 = yq * Z3
    H = U2 - X; M = S2 - Y
    HZ = H * Z
    c0 = xi_mul(fp_mul(HZ, yp))
    c5 = -fp_mul(M, xp)
    c3 = M * xq - HZ * yq
    HH = H.square(); HHH = HH * H
    V = X * HH
    M2 = M.square()
    Xn = M2 - (HHH + s_mul(V, 2))
    Yn = M * (V - Xn) - Y * HHH
    Zn = Z * H
    return (Xn, Yn, Zn), (c0, c3, c5)


def f12_to_w(c):  # c: dict (c1,c6,c2)->int; here keep as list of 6 Fp2
    return c


def sparse_mul(fw, lines):
    """fw: list of 6 Fp2 (w-basis); lines: (c0, c3, c5) per pair folded
    sequentially."""
    for (c0, c3, c5) in lines:
        p0 = [w * c0 for w in fw]
        p3 = [w * c3 for w in fw]
        p5 = [w * c5 for w in fw]
        out = []
        for k in range(6):
            t = p0[k]
            t3 = p3[(k - 3) % 6]
            if k - 3 < 0:
                t3 = xi_mul(t3)
            t5 = p5[(k - 5) % 6]
            if k - 5 < 0:
                t5 = xi_mul(t5)
            out.append(t + t3 + t5)
        fw = out
    return fw


def w_sqr(fw):
    """f^2 in the w-basis via schoolbook with w^6 = xi (M-twist tower:
    w^2 = v, v^3 = xi)."""
    out = [Fp2.zero() for _ in range(6)]
    for i in range(6):
        for j in range(6):
            t = fw[i] * fw[j]
            k = i + j
            if k >= 6:
                t = xi_mul(t)
                k -= 6
            out[k] = out[k] + t
    return out


def run_host(xps, yps, qs, n_iter):
    """Simulate the bl miller loop for npairs pairs at VALUE level."""
    npairs = len(qs)
    fw = [Fp2.one()] + [Fp2.zero()] * 5
    Ts = [(qs[i][0], qs[i][1], Fp2.one()) for i in range(npairs)]
    flags = pp.MILLER_FLAGS[0]
    for it in range(n_iter):
        fw = w_sqr(fw)
        lines = []
        for i in range(npairs):
            Ts[i], ln = dbl_step(Ts[i], xps[i], yps[i])
            lines.append(ln)
        fw = sparse_mul(fw, lines)
        if flags[it]:
            lines = []
            for i in range(npairs):
                Ts[i], ln = add_step(Ts[i], qs[i], xps[i], yps[i])
                lines.append(ln)
            fw = sparse_mul(fw, lines)
    return fw, Ts


def device_partial(xp, yp, q, n_iter):
    def run(xp, yp, q):
        npairs = q.shape[0]
        b = q.shape[-1]
        xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]
        from drand_tpu.ops import bl
        from drand_tpu.ops.bl import NLIMBS, DTYPE
        one_fp = jnp.broadcast_to(bl._crow("ONE"),
                                  xq.shape[:-3] + (NLIMBS, b)).astype(DTYPE)
        one2 = jnp.stack([one_fp, jnp.zeros_like(one_fp)], axis=-3)
        f0 = bl.f12_one((), b)
        getter = pp.value_bit_getter(jnp.asarray(pp.MILLER_FLAGS))

        def body(i, state):
            f, X, Y, Z = state
            f = bl.f12_sqr(f)
            (X, Y, Z), lines = pp._dbl_step((X, Y, Z), xp, yp)
            f = pp._sparse_mul_035(f, lines, npairs)
            (Xa, Ya, Za), lines_a = pp._add_step((X, Y, Z), q, xp, yp)
            fa = pp._sparse_mul_035(f, lines_a, npairs)
            cond = getter(i) != 0
            f = jnp.where(cond, fa, f)
            X = jnp.where(cond, Xa, X)
            Y = jnp.where(cond, Ya, Y)
            Z = jnp.where(cond, Za, Z)
            return f, X, Y, Z

        return jax.lax.fori_loop(0, n_iter, body, (f0, xq, yq, one2))

    return jax.jit(run)(xp, yp, q)


def unpack_f2(arr):  # (2, 32, 1)
    return Fp2(limb.fp_from_device(arr[0, :, 0]) % P,
               limb.fp_from_device(arr[1, :, 0]) % P)


def main(mi=126):
    sk = 0x77
    pub = PointG1.generator().mul(sk)
    m = b"pack-%d" % mi
    sig = PointG2.from_bytes(bls.sign(sk, m), subgroup_check=False)
    h = hash_to_g2(m)
    pubs = _g1_aff(pub)[None]
    sigs = _g2_aff(sig)[None]
    msgs = _g2_aff(h)[None]
    xp, yp, q = pp.pack_verify_inputs(pubs, sigs, msgs)

    # host-side inputs (value level)
    g1neg = -PointG1.generator()
    nx, ny = g1neg.to_affine()
    px, py = pub.to_affine()
    sx, sy = sig.to_affine()
    hx, hy = h.to_affine()
    xps = [nx.v, px.v]
    yps = [ny.v, py.v]
    qs = [(sx, sy), (hx, hy)]

    lo, hi = 0, pp.N_MILLER
    # first verify divergence at full length
    for n_iter in (pp.N_MILLER,):
        fw_h, Ts_h = run_host(xps, yps, qs, n_iter)
        f_d, X_d, Y_d, Z_d = device_partial(xp, yp, q, n_iter)
        fw_d = np.asarray(jax.jit(lambda f: jnp.stack(
            [pp.f12_to_w(f)[k] for k in range(6)]))(f_d))
        div = [k for k in range(6) if unpack_f2(fw_d[k]) != fw_h[k]]
        print(f"n_iter={n_iter}: diverging w-coeffs {div}")
        if not div:
            print("no divergence at full length?!")
            return
    while hi - lo > 1:
        mid = (lo + hi) // 2
        fw_h, Ts_h = run_host(xps, yps, qs, mid)
        f_d, X_d, Y_d, Z_d = device_partial(xp, yp, q, mid)
        fw_d = np.asarray(jax.jit(lambda f: jnp.stack(
            [pp.f12_to_w(f)[k] for k in range(6)]))(f_d))
        div = [k for k in range(6) if unpack_f2(fw_d[k]) != fw_h[k]]
        # also compare T states
    # T device: X_d (np, 2, 32, 1)
        tdiv = []
        for i in range(2):
            for nm, comp_d, comp_h in (("X", X_d, Ts_h[i][0]),
                                       ("Y", Y_d, Ts_h[i][1]),
                                       ("Z", Z_d, Ts_h[i][2])):
                if unpack_f2(np.asarray(comp_d)[i]) != comp_h:
                    tdiv.append((i, nm))
        print(f"iter {mid}: f-div {div} T-div {tdiv}")
        if div or tdiv:
            hi = mid
        else:
            lo = mid
    print(f"first divergence at iteration {hi}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 126)
