"""secretflow: secret material flowing into observable surfaces.

Sources are identifiers that name key material (private shares, DKG
secrets, ECIES/HKDF-derived keys, setup secrets) plus anything assigned
from such an identifier within the same function. Sinks are the places
an operator — or anyone scraping /metrics, /debug/trace or the logs —
can read: logger calls, ``print``, metric ``.labels(...)`` values,
exception constructor arguments, trace-span attributes, and the
incident/forensic **bundle writers** (obs/incident.py, ISSUE 15) —
bundles are written to disk and shipped to whoever handles the
post-mortem, so a ``pri_share`` flowing into one is exfiltration
exactly like logging it.

A name bound to an imported MODULE never taints (the ``secrets`` stdlib
module is the obvious trap), and string constants never taint — only
references to secret-named values do.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, FuncInfo, Project

SECRET_NAME_RE = re.compile(
    r"(?i)(^|_)(sk|secret|secrets|pri_share|private_key|privkey|"
    r"enc_key|mac_key|ikm|okm|prk|keystream|share_secret|dist_key|"
    r"longterm_key)(_|$)")

_LOG_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
                "critical"}

# calls that PRESERVE their argument's content (a secret stays a secret
# through these); any other call's return value is treated as laundered
# — `out = rpc_call(secret)` yields a status object, not the secret,
# and flagging it would bury the real leaks in noise
_CONVERTERS = {"str", "bytes", "hex", "repr", "format", "int", "dumps",
               "hexlify", "b64encode", "b16encode", "to_bytes", "to_json",
               "join", "encode", "decode"}

# the forensic-bundle writer sink class (obs/incident.py): any call to
# one of these — bare or as a method, leading underscores stripped —
# with a secret-named argument is a HIGH finding. Bundles land on disk
# and travel to operators/support, the same trust boundary as a log
# line (the known-bad fixture lives in tests/test_zz_analyze.py).
_BUNDLE_SINKS = {"freeze_bundle", "write_bundle", "capture_bundle",
                 "persist_bundle", "support_bundle", "freeze_locked",
                 "persist_locked"}

# the remediation-ledger writer sink class (obs/remediate.py, ISSUE
# 16): ledger entries ride the incident bundle (annotate_remediation
# merges them into the summary the persist path serializes) AND the
# /debug/remediation payload — the same disk/operator trust boundary
# as the bundles, so a playbook logging a share fails the gate the
# same way.
_LEDGER_SINKS = {"record_action", "annotate_remediation",
                 "append_ledger", "ledger_entry"}


def _is_bundle_sink(name: str | None) -> bool:
    return name is not None and name.lstrip("_") in _BUNDLE_SINKS


def _is_ledger_sink(name: str | None) -> bool:
    return name is not None and name.lstrip("_") in _LEDGER_SINKS


def _is_module_alias(name: str, fn: FuncInfo) -> bool:
    target = fn.module.imports.get(name)
    # an import bound to a dotted module path (or bare module) is a
    # module alias; "from x import y" also lands here but a secret
    # VALUE imported across modules keeps its secret name and still
    # matches at its definition site's sinks
    return target is not None


def _tainted_names(expr: ast.AST, local_taint: set[str],
                   fn: FuncInfo) -> list[str]:
    """Secret-named references inside ``expr``, with call-result
    laundering: names feeding a non-converter call's arguments do not
    taint the surrounding expression (constants never taint)."""
    out: list[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            visit(node.func)  # a method ON a secret still taints
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            if fname in _CONVERTERS:
                for a in node.args:
                    visit(a)
                for kw in node.keywords:
                    if kw.value is not None:
                        visit(kw.value)
            return
        if isinstance(node, ast.Name):
            if node.id in local_taint or (
                    SECRET_NAME_RE.search(node.id)
                    and not _is_module_alias(node.id, fn)):
                out.append(node.id)
        elif isinstance(node, ast.Attribute):
            if SECRET_NAME_RE.search(node.attr):
                out.append(node.attr)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return out


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for fn in project.iter_functions():
        findings.extend(_scan_function(fn))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def _scan_function(fn: FuncInfo) -> list[Finding]:
    # one-hop local propagation: x = <expr referencing a secret name>
    local_taint: set[str] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if _tainted_names(node.value, set(), fn):
                local_taint.add(node.targets[0].id)

    out: list[Finding] = []

    def emit(rule: str, line: int, names: list[str], sink: str) -> None:
        uniq = sorted(set(names))
        out.append(Finding(
            pass_name="secretflow", rule=rule, severity="high",
            path=fn.module.relpath, line=line, symbol=fn.qualname,
            message=(f"secret-named value(s) {', '.join(uniq)} flow into "
                     f"{sink} in `{fn.qualname}` — key material must "
                     f"never reach logs/metrics/traces/exceptions"),
        ))

    def check_call_args(call: ast.Call) -> list[str]:
        names: list[str] = []
        for a in call.args:
            names.extend(_tainted_names(a, local_taint, fn))
        for kw in call.keywords:
            if kw.value is not None:
                names.extend(_tainted_names(kw.value, local_taint, fn))
        return names

    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, skip):
                continue
            if isinstance(child, ast.Raise) and isinstance(child.exc,
                                                           ast.Call):
                names = check_call_args(child.exc)
                if names:
                    emit("secret-in-exception", child.lineno, names,
                         "an exception message")
            elif isinstance(child, ast.Call):
                func = child.func
                if isinstance(func, ast.Attribute):
                    if func.attr in _LOG_METHODS:
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-log", child.lineno, names,
                                 "a log line")
                    elif func.attr == "labels":
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-metric-label", child.lineno,
                                 names, "a metric label")
                    elif func.attr == "span":
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-trace-attr", child.lineno,
                                 names, "a trace-span attribute")
                    elif func.attr == "update" and isinstance(
                            func.value, ast.Attribute) \
                            and func.value.attr == "attrs":
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-trace-attr", child.lineno,
                                 names, "a trace-span attribute")
                    elif _is_bundle_sink(func.attr):
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-bundle", child.lineno,
                                 names, "a forensic bundle")
                    elif _is_ledger_sink(func.attr):
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-ledger", child.lineno,
                                 names, "a remediation ledger")
                elif isinstance(func, ast.Name):
                    if func.id == "print":
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-print", child.lineno, names,
                                 "stdout")
                    elif _is_bundle_sink(func.id):
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-bundle", child.lineno,
                                 names, "a forensic bundle")
                    elif _is_ledger_sink(func.id):
                        names = check_call_args(child)
                        if names:
                            emit("secret-in-ledger", child.lineno,
                                 names, "a remediation ledger")
            walk(child)

    for stmt in fn.node.body:
        if isinstance(stmt, skip):
            continue
        walk(stmt)
        if isinstance(stmt, ast.Raise) and isinstance(stmt.exc, ast.Call):
            names = check_call_args(stmt.exc)
            if names:
                emit("secret-in-exception", stmt.lineno, names,
                     "an exception message")
    return out
