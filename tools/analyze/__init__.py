"""drand-tpu static analysis suite (`drand analyze` / tools/analyze/run.py).

Pure-AST — never imports the analyzed code, never initializes a jax
backend — so the whole suite is host-only and fast enough to gate every
PR from tier-1. Eight passes:

- ``loopblock``   blocking work (pairings, engine dispatch, sqlite,
                  ``time.sleep``, sync sockets) reachable from an
                  ``async def`` without an executor hand-off
- ``lockheld``    a ``threading.Lock`` held across an ``await``, an
                  executor hand-off, or pairing-class work
- ``threadshare`` unlocked mutation of state shared between the event
                  loop and ``to_thread`` workers (thread-context map
                  over the call graph)
- ``awaitatomic`` check-then-act on shared state split across an
                  ``await`` (stale-cache TOCTOU); high when the state
                  is also thread-shared
- ``secretflow``  secret material flowing into logs, metric labels,
                  exception strings or trace-span attributes
- ``jaxhazard``   Python control flow on tracers, float dtypes in limb
                  math, host transfers and re-jitting inside hot paths
- ``asyncsanity`` un-awaited coroutines and fire-and-forget tasks
                  without a strong reference
- ``metrics``     the tools/check_metrics.py catalogue lint, folded in
                  so tier-1 has one analysis entry point

See README "Static analysis" for usage and the baseline workflow.
"""

from .core import Finding, Project, SEV_RANK  # noqa: F401
from .run import run_analysis  # noqa: F401
