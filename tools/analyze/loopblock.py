"""loopblock: blocking work reachable from ``async def`` bodies.

The Go reference runs its pairing work in goroutines; asyncio gives no
such free pass — one ``batch.verify_beacons`` on a 1024-round catch-up
span parks the event loop for seconds, freezing /healthz, gossip and
DKG. This pass propagates "blocking" taint from known-heavy leaves up
the intra-project call graph and flags every ``async def`` that can
reach one without an executor hand-off (``asyncio.to_thread`` /
``run_in_executor`` — functions passed as *arguments* to those never
create call edges, so a hand-off neutralizes the path by construction).

Severity is the strongest leaf on the path: pairing-class work
(pairings, Miller loops, MSM, engine dispatch) is high; bounded
point-multiplication and sync-I/O-class work (``time.sleep``, sqlite,
sockets, single scalar muls) is medium.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, SEV_RANK

# (regex over the RESOLVED dotted target, severity, label)
DEFAULT_LEAVES: tuple[tuple[str, str, str], ...] = (
    # pairing-class: multi-ms to seconds per call — never on the loop
    (r"^drand_tpu\.crypto\.pairing\.", "high", "pairing"),
    (r"^drand_tpu\.crypto\.batch\.(verify_beacons|verify_partials|"
     r"verify_recovered_many|recover|aggregate_round|eval_commits|"
     r"decrypt_round_batch)$",
     "high", "engine dispatch"),
    (r"^drand_tpu\.crypto\.batch_verify\.", "high", "RLC batch verify"),
    # timelock IBE: encrypt/decrypt are one pairing each, the batch
    # entrypoints a whole round's worth — never inline on the loop
    (r"^drand_tpu\.crypto\.timelock\.(encrypt|decrypt|decrypt_batch)$",
     "high", "timelock IBE"),
    (r"^drand_tpu\.crypto\.tbls\.(verify_partial|verify_recovered|"
     r"recover|aggregate)", "high", "threshold BLS"),
    (r"^drand_tpu\.chain\.beacon\.verify_beacon", "high", "beacon verify"),
    (r"^drand_tpu\.ops\.engine\.", "high", "device engine"),
    # bounded-but-real blocking: scalar muls, disk commits, sync waits
    (r"^time\.sleep$", "medium", "time.sleep"),
    (r"^sqlite3\.", "medium", "sqlite"),
    (r"^socket\.", "medium", "sync socket"),
    (r"^urllib\.request\.", "medium", "sync urllib"),
    (r"^requests\.", "medium", "sync requests"),
    (r"^subprocess\.(run|check_output|check_call|call)$", "medium",
     "subprocess wait"),
    (r"^drand_tpu\.crypto\.bls\.(sign|verify|keygen)$", "medium",
     "BLS point op"),
    (r"^drand_tpu\.crypto\.ecies\.(encrypt|decrypt)$", "medium",
     "ECIES point op"),
)

# unresolved ``obj.method(...)`` fallback: bare attribute names that are
# unambiguous in this codebase (curated — generic names like "recover"
# or "put" would drown the pass in dynamic-dispatch guesses)
DEFAULT_ATTR_LEAVES: dict[str, tuple[str, str]] = {
    "verify_beacons": ("high", "engine dispatch"),
    "aggregate_round": ("high", "engine dispatch"),
    "verify_partials": ("high", "engine dispatch"),
    "verify_recovered_many": ("high", "engine dispatch"),
    "eval_commits": ("high", "engine dispatch"),
    "miller_loop": ("high", "pairing"),
    "pairing_check": ("high", "pairing"),
    "pairing_check_groups": ("high", "pairing"),
    # timelock batch entrypoints (ISSUE 9): a future `async def` that
    # decrypts a round inline on the event loop is a HIGH finding
    "decrypt_round_batch": ("high", "timelock batch decrypt"),
    "decrypt_batch": ("high", "timelock batch decrypt"),
    "decrypt_many": ("high", "timelock batch decrypt"),
    "timelock_open": ("high", "timelock batch decrypt"),
}

# functions whose bodies are exempt (test scaffolding has no production
# event loop; the analyzer package itself would self-flag its fixtures)
DEFAULT_EXCLUDE_PREFIXES = ("drand_tpu.testing",)

# retry-sleep rule (ISSUE 12, scope widened by ISSUE 14): module path
# prefixes where a raw ``asyncio.sleep`` inside a retry/backoff loop is
# a medium finding — retries there must go through
# drand_tpu/utils/retry.py, whose sleeps ride the INJECTABLE clock, or
# FakeClock chaos runs lose determinism (a wall-clock sleep is
# invisible to the fault scheduler's wake-target stepping). http_server/
# and relay/ joined the scope when the relay watch loop moved onto the
# policy — their restart loops are retrying network edges like any
# other. A loop counts as retry/backoff when its body both handles an
# exception (``try/except``) and awaits ``asyncio.sleep`` — the
# signature of a hand-rolled retry; ``asyncio.sleep(0)`` is a
# cooperative yield, not a backoff, and stays exempt.
RETRY_SLEEP_PREFIXES = ("drand_tpu/net/", "drand_tpu/chain/",
                        "drand_tpu/timelock/", "drand_tpu/http_server/",
                        "drand_tpu/relay/")

_MAX_PATH = 7


def _retry_sleep_findings(project: Project,
                          prefixes: tuple[str, ...] = RETRY_SLEEP_PREFIXES,
                          ) -> list[Finding]:
    """Medium findings for raw asyncio.sleep in retry loops (see
    RETRY_SLEEP_PREFIXES). AST-local: nested defs are skipped (they are
    indexed as their own functions), so a callback defined inside a
    loop never charges the enclosing function."""

    def _iter_no_nested(node: ast.AST):
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, skip):
                continue
            yield child
            yield from _iter_no_nested(child)

    def _is_asyncio_sleep(call: ast.Call, imports: dict) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)):
            return False
        if imports.get(f.value.id, f.value.id) != "asyncio":
            return False
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value == 0:
            return False  # a cooperative yield, not a backoff
        return True

    findings: list[Finding] = []
    for fn in project.iter_functions():
        rel = fn.module.relpath
        if not rel.startswith(prefixes):
            continue
        hit: ast.Call | None = None
        for loop in _iter_no_nested(fn.node):
            if not isinstance(loop, (ast.While, ast.For, ast.AsyncFor)):
                continue
            body = list(_iter_no_nested(loop))
            if not any(isinstance(n, ast.Try) for n in body):
                continue  # no exception handling: not a retry loop
            for n in body:
                if isinstance(n, ast.Call) \
                        and _is_asyncio_sleep(n, fn.module.imports):
                    hit = n
                    break
            if hit is not None:
                break
        if hit is None:
            continue
        findings.append(Finding(
            pass_name="loopblock",
            rule="retry-sleep",
            severity="medium",
            path=rel,
            line=hit.lineno,
            symbol=fn.qualname,
            message=(f"`{fn.qualname}` awaits a raw asyncio.sleep inside "
                     f"a retry/backoff loop — use the injectable-clock "
                     f"policy (drand_tpu.utils.retry) so FakeClock chaos "
                     f"runs stay deterministic"),
            detail="retry-sleep",
        ))
    return findings


def classify_leaf(call, leaf_res, attr_leaves) -> tuple[str, str] | None:
    """``(severity, leaf description)`` when one call site hits a known
    blocking leaf (resolved regex or curated bare-attribute list), else
    None. Shared with the lockheld pass so "pairing-class" can never
    mean two different things."""
    if call.target is not None:
        for rx, sev, label in leaf_res:
            if rx.search(call.target):
                return sev, f"{call.target} ({label})"
        # a project-internal call is not a leaf hit unless the
        # regex matched; external targets only match via regex
    if call.target is None and call.attr in attr_leaves:
        sev, label = attr_leaves[call.attr]
        return sev, f".{call.attr}(...) ({label})"
    return None


def blocking_taint(project: Project,
                   leaves: tuple[tuple[str, str, str], ...] = DEFAULT_LEAVES,
                   attr_leaves: dict[str, tuple[str, str]] | None = None,
                   exclude_prefixes: tuple[str, ...] =
                   DEFAULT_EXCLUDE_PREFIXES,
                   ) -> dict[str, tuple[str, str, tuple[str, ...]]]:
    """The blocking-taint fixpoint over the call graph:
    ``qualname -> (severity, leaf description, call path)`` for every
    function that can reach a known-heavy leaf. This is loopblock's
    core; the lockheld pass reuses it to decide whether a call made
    UNDER a lock reaches pairing-class work."""
    if attr_leaves is None:
        attr_leaves = DEFAULT_ATTR_LEAVES
    leaf_res = [(re.compile(pat), sev, label) for pat, sev, label in leaves]

    def excluded(qn: str) -> bool:
        return any(qn.startswith(p) for p in exclude_prefixes)

    # taint[qualname] = (severity, leaf description, path tuple)
    taint: dict[str, tuple[str, str, tuple[str, ...]]] = {}

    def offer(qn: str, sev: str, leaf: str, path: tuple[str, ...]) -> bool:
        cur = taint.get(qn)
        if cur is not None and (SEV_RANK[cur[0]], -len(cur[2])) >= \
                (SEV_RANK[sev], -len(path)):
            return False
        taint[qn] = (sev, leaf, path)
        return True

    # seed: direct leaf calls
    for fn in project.iter_functions():
        if excluded(fn.qualname):
            continue
        for call in fn.calls:
            sev_label = classify_leaf(call, leaf_res, attr_leaves)
            if sev_label is not None:
                offer(fn.qualname, sev_label[0], sev_label[1],
                      (fn.qualname, sev_label[1]))

    # reverse edges: caller -> set of project callees
    callers: dict[str, set[str]] = {}
    for fn in project.iter_functions():
        if excluded(fn.qualname):
            continue
        for call in fn.calls:
            if call.target in project.functions \
                    and not excluded(call.target):
                callers.setdefault(call.target, set()).add(fn.qualname)

    # propagate up to a fixpoint
    work = list(taint.keys())
    while work:
        callee = work.pop()
        sev, leaf, path = taint[callee]
        if len(path) >= _MAX_PATH:
            continue
        for caller in callers.get(callee, ()):
            if offer(caller, sev, leaf, (caller,) + path):
                work.append(caller)
    return taint


def run(project: Project,
        leaves: tuple[tuple[str, str, str], ...] = DEFAULT_LEAVES,
        attr_leaves: dict[str, tuple[str, str]] | None = None,
        exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES,
        ) -> list[Finding]:
    taint = blocking_taint(project, leaves, attr_leaves, exclude_prefixes)
    findings: list[Finding] = []
    for fn in project.iter_functions():
        if not fn.is_async or fn.qualname not in taint:
            continue
        sev, leaf, path = taint[fn.qualname]
        chain = " -> ".join(p.split(".")[-1] if i else p
                            for i, p in enumerate(path))
        kind = "pairing-class" if sev == "high" else "blocking"
        findings.append(Finding(
            pass_name="loopblock",
            rule=f"async-blocking-{sev}",
            severity=sev,
            path=fn.module.relpath,
            line=fn.line,
            symbol=fn.qualname,
            message=(f"async `{fn.qualname}` reaches {kind} call "
                     f"{leaf} with no executor hand-off: {chain} — wrap "
                     f"the blocking step in asyncio.to_thread(...)"),
            # the leaf scopes baseline entries: suppressing the reviewed
            # eval_commits path must not also suppress a verify_beacons
            # call someone adds to the same function later
            detail=leaf,
        ))
    findings.extend(_retry_sleep_findings(project))
    findings.sort(key=lambda f: (-SEV_RANK[f.severity], f.path, f.line))
    return findings
