"""awaitatomic: check-then-act on shared state split across an
``await`` (ISSUE 13).

Single-threaded asyncio code still interleaves — at every ``await``.
The classic TOCTOU: test an attribute (a cache slot, a "seen" set, a
lazily-fetched handle), ``await`` something, then act on the result of
the stale test::

    async def info(self):
        if self._info is None:            # check
            self._info = await fetch()    # act — but N tasks raced the
        return self._info                 # check and ALL fetch

Between the check and the act every other task on the loop runs: two
concurrent callers both see ``None`` and both fetch (duplicate work,
double-submit, lost writes when the second overwrite clobbers state the
first caller already published). The gossip relay's in-flight guard
(relay/gossip.py ``_inflight``) exists precisely because this bug
shipped once.

Rule (deliberately narrow — tuned against false positives like every
pass here): inside one ``async def``, an ``if``/``while`` whose test
READS ``self.X`` (or a module global), where the guarded branch reaches
an ``await`` BEFORE it WRITES the same ``self.X``/global (assignment,
subscript store, or container-mutator call). Reads or writes outside
the guarded branch don't pair — a ``finally: self._busy = False`` after
a top-of-function check is a deliberate clear, not a TOCTOU.

Severity: medium — the damage is usually duplicated work or a
re-inserted cache entry. Escalated to HIGH when the attribute is also
*thread-shared* (the threadshare pass's dual-context map): then the
stale check races real OS threads, not just cooperative tasks, and the
act can corrupt state a worker is mid-way through.

Suppression by construction: a check-then-act wholly inside an ``async
with <…lock>`` block (an asyncio lock serializing the tasks) is not
flagged.
"""

from __future__ import annotations

import ast

from .core import Finding, Project
from . import threadshare

DEFAULT_EXCLUDE_PREFIXES = ("drand_tpu.testing",)


def _iter_no_nested(node: ast.AST):
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, skip):
            continue
        yield child
        yield from _iter_no_nested(child)


def _self_attr_reads(expr: ast.AST) -> set[str]:
    """Attribute names read off ``self`` anywhere inside ``expr``."""
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            out.add(node.attr)
    return out


def _global_reads(expr: ast.AST, candidates: set[str]) -> set[str]:
    return {node.id for node in ast.walk(expr)
            if isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in candidates}


def _writes_in(node: ast.AST) -> tuple[set[str], set[str]]:
    """(self-attr names, bare names) written/mutated by this single
    statement-level node (no recursion into nested statements)."""
    attrs: set[str] = set()
    names: set[str] = set()

    def target(expr: ast.AST) -> None:
        if isinstance(expr, ast.Name):
            names.add(expr.id)
        elif isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name):
                if expr.value.id == "self":
                    attrs.add(expr.attr)
                else:
                    names.add(expr.value.id)
        elif isinstance(expr, ast.Subscript):
            target(expr.value)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                target(el)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            target(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        target(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            target(t)
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in threadshare.MUTATOR_METHODS:
        target(node.func.value)
    return attrs, names


class _BranchScan:
    """Linear scan of a guarded branch: does an await happen between
    the last CHECK of a watched name and a WRITE to it?

    ``await_count`` advances at every suspension point;
    ``last_check[name]`` records the count at the most recent
    ``if``/``while`` test that re-read the name. A write is a finding
    only when awaits happened since that check — so the documented fix
    idiom (re-check the attribute after the await, then write with no
    further suspension) analyzes clean, as does any write the branch
    makes before its first await."""

    def __init__(self, attrs: set[str], names: set[str]):
        self.attrs = attrs
        self.names = names
        self.await_count = 0
        # the guarding test itself happened at count 0
        self.last_check: dict[tuple[str, str], int] = {}
        self.hits: list[tuple[str, str, int]] = []  # (kind, name, line)

    def scan(self, stmts) -> None:
        for stmt in stmts:
            self._scan_node(stmt)

    def _scan_node(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            self.await_count += 1  # (__aenter__ suspends too)
        if isinstance(node, (ast.If, ast.While)):
            self._scan_node(node.test)
            for a in _self_attr_reads(node.test) & self.attrs:
                self.last_check[("attr", a)] = self.await_count
            for n in _global_reads(node.test, self.names):
                self.last_check[("global", n)] = self.await_count
            for stmt in (*node.body, *node.orelse):
                self._scan_node(stmt)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # `self._x = await f()`: the value's await resolves BEFORE
            # the store lands, so scan it first — the single-statement
            # form is the most common shape of this bug
            if node.value is not None:
                self._scan_node(node.value)
            self._check_writes(node)
            return
        self._check_writes(node)
        for child in ast.iter_child_nodes(node):
            self._scan_node(child)

    def _check_writes(self, node: ast.AST) -> None:
        w_attrs, w_names = _writes_in(node)
        for a in w_attrs & self.attrs:
            if self.await_count > self.last_check.get(("attr", a), 0):
                self.hits.append(("attr", a, node.lineno))
        for n in w_names & self.names:
            if self.await_count > self.last_check.get(("global", n), 0):
                self.hits.append(("global", n, node.lineno))


def run(project: Project,
        exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES,
        dual_attrs: set | None = None,
        dual_globals: set | None = None,
        ) -> list[Finding]:
    """``dual_attrs``/``dual_globals`` come from
    ``threadshare.analyze`` (computed here when not supplied) and
    escalate findings on thread-shared state to high."""
    if dual_attrs is None or dual_globals is None:
        _, _, dual_attrs, dual_globals, _ = threadshare.analyze(
            project, exclude_prefixes)

    mod_globals = threadshare._module_globals(project)
    findings: list[Finding] = []

    for fn in project.iter_functions():
        if not fn.is_async:
            continue
        if any(fn.qualname.startswith(p) for p in exclude_prefixes):
            continue
        candidates = mod_globals.get(fn.module.name, set())
        seen: set[tuple[str, str]] = set()
        # async-with-lock regions are serialized: collect their spans
        locked_lines: set[int] = set()
        for node in _iter_no_nested(fn.node):
            if isinstance(node, ast.AsyncWith) and any(
                    threadshare.lock_name(i.context_expr) is not None
                    for i in node.items):
                end = getattr(node, "end_lineno", node.lineno)
                locked_lines.update(range(node.lineno, end + 1))
        for node in _iter_no_nested(fn.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if node.lineno in locked_lines:
                continue
            attrs = _self_attr_reads(node.test) if fn.cls else set()
            names = _global_reads(node.test, candidates)
            if not attrs and not names:
                continue
            scan = _BranchScan(attrs, names)
            scan.scan(node.body)
            scan_else = _BranchScan(attrs, names)
            scan_else.scan(node.orelse)
            for kind, name, line in scan.hits + scan_else.hits:
                if line in locked_lines or (kind, name) in seen:
                    continue
                seen.add((kind, name))
                shared = ((fn.cls, name) in dual_attrs if kind == "attr"
                          else (fn.module.name, name) in dual_globals)
                what = (f"self.{name}" if kind == "attr" else name)
                findings.append(Finding(
                    pass_name="awaitatomic",
                    rule=("check-then-act-threaded" if shared
                          else "check-then-act"),
                    severity="high" if shared else "medium",
                    path=fn.module.relpath, line=line,
                    symbol=fn.qualname,
                    message=(f"`{fn.qualname}` checks `{what}` at line "
                             f"{node.lineno}, awaits, then writes it at "
                             f"line {line} — every task on the loop "
                             f"interleaves at the await, so the check "
                             f"is stale by the time the write lands"
                             + (" (and the attribute is ALSO touched "
                                "from worker threads — see "
                                "threadshare)" if shared else "")
                             + "; serialize with an asyncio.Lock, an "
                             "in-flight guard (the gossip _inflight "
                             "pattern), or re-check after the await"),
                    detail=name))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
