"""lockheld: a ``threading.Lock``/``RLock`` held across an ``await``,
an executor hand-off, or pairing-class work (ISSUE 13).

The failure mode is process-global, not local: this codebase mixes real
OS threads (``asyncio.to_thread`` crypto workers, SQLite handles opened
``check_same_thread=False``) with one event loop, and every shared
structure is guarded by a *threading* lock. A thread that suspends or
computes for seconds while holding one of those locks starves every
other acquirer — and when the next acquirer is LOOP-side code (a
``/healthz`` probe reading a guarded snapshot, the handler appending a
flight event), the blocking ``acquire()`` parks the entire event loop
until the holder finishes. That converts one slow worker into a
whole-process outage, which is why every rule here is high severity.

Rules (all scoped to the lexical body of a sync ``with <lock>`` block;
``async with`` is an *asyncio* lock — a different discipline with its
own pass, awaitatomic):

- ``lock-across-await``: any ``await`` inside the block. The lock stays
  held across the suspension, for as many loop iterations as the
  awaited thing takes.
- ``lock-across-handoff``: an ``asyncio.to_thread`` /
  ``run_in_executor`` call inside the block — the hand-off *queues*
  work on another thread; holding a lock the worker (or anyone else)
  may want is a deadlock-shaped bug even before the await lands.
- ``lock-over-pairing``: a call inside the block whose blocking taint
  (loopblock's fixpoint — same leaves, same propagation) is
  pairing-class high. Tens of milliseconds to seconds of crypto under
  a lock that loop-side readers contend on.

Lock identification is by name: a ``with`` context expression whose
final dotted segment ends in ``lock`` (case-insensitive) — ``_lock``,
``_ENGINE_LOCK``, ``self._ledger_lock`` — matching the repo-wide
convention the threadshare pass also enforces (new-code rule in
ROADMAP: thread-shared mutable state must name its lock). Medium-class
leaves (sqlite, ``time.sleep``) are deliberately NOT flagged under
locks: single-writer stores hold their one lock across exactly one
sqlite statement by design (chain/store.py, timelock/vault.py), and
flagging that idiom would drown the pass.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, _dotted
from .loopblock import (DEFAULT_ATTR_LEAVES, DEFAULT_EXCLUDE_PREFIXES,
                        DEFAULT_LEAVES, blocking_taint, classify_leaf)

LOCK_NAME_RE = re.compile(r"lock$", re.IGNORECASE)

_HANDOFF_ATTRS = ("to_thread", "run_in_executor")


def lock_name(expr: ast.AST) -> str | None:
    """The dotted rendering of a with-item context expression when it
    names a lock (final segment ends in "lock"), else None. Shared with
    threadshare, whose guarded-mutation rule must agree on what counts
    as holding a lock."""
    # `with self._lock:` / `with _ENGINE_LOCK:` — a bare name/attribute
    parts = _dotted(expr)
    if parts is not None and LOCK_NAME_RE.search(parts[-1]):
        return ".".join(parts)
    return None


def _iter_no_nested(node: ast.AST):
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, skip):
            continue
        yield child
        yield from _iter_no_nested(child)


def run(project: Project,
        leaves: tuple[tuple[str, str, str], ...] = DEFAULT_LEAVES,
        attr_leaves: dict[str, tuple[str, str]] | None = None,
        exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES,
        ) -> list[Finding]:
    if attr_leaves is None:
        attr_leaves = DEFAULT_ATTR_LEAVES
    leaf_res = [(re.compile(pat), sev, label) for pat, sev, label in leaves]
    taint = blocking_taint(project, leaves, attr_leaves, exclude_prefixes)

    findings: list[Finding] = []

    def emit(fn, rule: str, line: int, lock: str, what: str,
             detail: str) -> None:
        findings.append(Finding(
            pass_name="lockheld", rule=rule, severity="high",
            path=fn.module.relpath, line=line, symbol=fn.qualname,
            message=(f"`{fn.qualname}` holds `{lock}` across {what} — a "
                     f"loop-side acquirer then blocks the whole event "
                     f"loop until the holder finishes; narrow the "
                     f"critical section to the shared-state access"),
            detail=detail))

    for fn in project.iter_functions():
        if any(fn.qualname.startswith(p) for p in exclude_prefixes):
            continue
        # call-site lookup for taint/leaf classification: the extracted
        # CallSites carry resolution; match them back to AST calls by
        # (line, bare name) like asyncsanity does
        sites: dict[tuple[int, str], list] = {}
        for cs in fn.calls:
            sites.setdefault((cs.line, cs.attr), []).append(cs)

        for w in _iter_no_nested(fn.node):
            if not isinstance(w, ast.With):
                continue
            lock = None
            for item in w.items:
                lock = lock_name(item.context_expr)
                if lock is not None:
                    break
            if lock is None:
                continue
            seen_rules: set[str] = set()
            for node in (n for stmt in w.body
                         for n in (stmt, *_iter_no_nested(stmt))):
                if isinstance(node, ast.Await) \
                        and "await" not in seen_rules:
                    seen_rules.add("await")
                    emit(fn, "lock-across-await", node.lineno, lock,
                         "an await", f"{lock}:await")
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = (func.id if isinstance(func, ast.Name)
                        else func.attr if isinstance(func, ast.Attribute)
                        else None)
                if name is None:
                    continue
                if name in _HANDOFF_ATTRS and "handoff" not in seen_rules:
                    seen_rules.add("handoff")
                    emit(fn, "lock-across-handoff", node.lineno, lock,
                         f"an executor hand-off ({name})",
                         f"{lock}:handoff")
                    continue
                for cs in sites.get((node.lineno, name), ()):
                    hit = classify_leaf(cs, leaf_res, attr_leaves)
                    if hit is None and cs.target in taint:
                        sev, leaf, _path = taint[cs.target]
                        hit = (sev, leaf)
                    if hit is not None and hit[0] == "high" \
                            and f"pair:{hit[1]}" not in seen_rules:
                        seen_rules.add(f"pair:{hit[1]}")
                        emit(fn, "lock-over-pairing", node.lineno, lock,
                             f"pairing-class work ({hit[1]})",
                             f"{lock}:{hit[1]}")
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
