"""Shared AST infrastructure: findings, module/import resolution, the
intra-project call graph every pass walks.

Resolution is deliberately conservative — a call target that cannot be
traced to a project function or an imported module is recorded with its
bare attribute name only, and passes match those against small curated
lists. False negatives are possible (dynamic dispatch, attributes of
attributes); false positives are what the passes are tuned against,
because a lint nobody trusts is a lint nobody runs.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field

SEVERITIES = ("high", "medium", "low", "info")
SEV_RANK = {s: i for i, s in enumerate(reversed(SEVERITIES))}


@dataclass
class Finding:
    """One analyzer result. ``key`` is line-number-free so baseline
    entries survive unrelated edits to the same file. Passes that can
    report *different* hazards under one (rule, symbol) — loopblock
    emits one finding per async def, naming the strongest leaf — set
    ``detail`` so a baseline entry suppresses only the reviewed hazard:
    a new leaf reached by the same function produces a new key."""

    pass_name: str
    rule: str
    severity: str
    path: str        # repo-relative, forward slashes
    line: int
    symbol: str      # qualified function/module symbol the finding anchors to
    message: str
    detail: str = ""  # extra key component scoping baseline suppression

    @property
    def key(self) -> str:
        base = f"{self.pass_name}:{self.rule}:{self.path}:{self.symbol}"
        return f"{base}:{self.detail}" if self.detail else base

    def to_dict(self) -> dict:
        return {
            "pass": self.pass_name, "rule": self.rule,
            "severity": self.severity, "path": self.path,
            "line": self.line, "symbol": self.symbol,
            "message": self.message, "key": self.key,
        }

    def render(self) -> str:
        return (f"[{self.severity:<6}] {self.path}:{self.line} "
                f"{self.symbol}\n    {self.message}\n    key: {self.key}")


@dataclass
class CallSite:
    """One call expression inside a function body."""

    target: str | None   # resolved dotted target ("time.sleep", project qualname) or None
    attr: str            # bare callee name (attribute or identifier)
    line: int
    text: str            # dotted rendering for messages ("self._store.put")


@dataclass
class FuncInfo:
    qualname: str
    module: "Module"
    node: ast.AST
    is_async: bool
    line: int
    cls: str | None = None           # enclosing class qualname
    calls: list[CallSite] = field(default_factory=list)


@dataclass
class Module:
    name: str
    path: pathlib.Path
    relpath: str
    tree: ast.Module
    imports: dict[str, str] = field(default_factory=dict)


def _dotted(expr: ast.AST) -> list[str] | None:
    """["a", "b", "c"] for a plain a.b.c chain, else None."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return list(reversed(parts))
    return None


def _resolve_relative(module_name: str, is_package: bool,
                      target: str | None, level: int) -> str:
    if level == 0:
        return target or ""
    parts = module_name.split(".")
    if not is_package:
        parts = parts[:-1]
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
    return ".".join(parts + ([target] if target else []))


class Project:
    """Parsed view of a Python tree rooted at ``root``.

    ``packages`` restricts the walk (e.g. ``("drand_tpu",)`` for the
    repo); None walks every ``*.py`` under root — what the fixture
    tests use.
    """

    def __init__(self, root: str | pathlib.Path,
                 packages: tuple[str, ...] | None = None):
        self.root = pathlib.Path(root).resolve()
        self.modules: dict[str, Module] = {}
        self.functions: dict[str, FuncInfo] = {}
        # class qualname -> (method name -> qualname, base exprs, module)
        self._classes: dict[str, tuple[dict[str, str], list[str],
                                       "Module"]] = {}
        roots = ([self.root / p for p in packages] if packages
                 else [self.root])
        files: list[pathlib.Path] = []
        for r in roots:
            if r.is_file():
                files.append(r)
            else:
                files.extend(p for p in sorted(r.rglob("*.py"))
                             if "__pycache__" not in p.parts)
        for path in files:
            self._load(path)
        for mod in self.modules.values():
            self._index_module(mod)
        for fn in self.functions.values():
            self._extract_calls(fn)
        self._link_decorators()

    # ------------------------------------------------------------ loading
    def _module_name(self, path: pathlib.Path) -> str:
        rel = path.relative_to(self.root)
        parts = list(rel.parts)
        parts[-1] = parts[-1][:-3]  # strip .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts) if parts else rel.stem

    def _load(self, path: pathlib.Path) -> None:
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            return  # not this tool's job; the test suite will scream
        name = self._module_name(path)
        rel = str(path.relative_to(self.root)).replace("\\", "/")
        mod = Module(name=name, path=path, relpath=rel, tree=tree)
        is_pkg = path.name == "__init__.py"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        top = alias.name.split(".")[0]
                        mod.imports[top] = top
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_relative(name, is_pkg, node.module,
                                         node.level)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    mod.imports[local] = (f"{base}.{alias.name}" if base
                                          else alias.name)
        self.modules[name] = mod

    # ----------------------------------------------------------- indexing
    def _index_module(self, mod: Module) -> None:
        def index(body, scope: str, cls: str | None) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{scope}.{node.name}"
                    self.functions[qn] = FuncInfo(
                        qualname=qn, module=mod, node=node,
                        is_async=isinstance(node, ast.AsyncFunctionDef),
                        line=node.lineno, cls=cls)
                    index(node.body, qn, None)
                elif isinstance(node, ast.ClassDef):
                    cqn = f"{scope}.{node.name}"
                    bases = []
                    for b in node.bases:
                        d = _dotted(b)
                        if d:
                            bases.append(".".join(d))
                    self._classes[cqn] = ({}, bases, mod)
                    index(node.body, cqn, cqn)
                    methods = {
                        n.name: f"{cqn}.{n.name}" for n in node.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
                    self._classes[cqn] = (methods, bases, mod)

        index(mod.tree.body, mod.name, None)

    def _resolve_class(self, mod: Module, name: str) -> str | None:
        """A base-class expression to a project class qualname."""
        for cand in (f"{mod.name}.{name}", mod.imports.get(name, ""),
                     mod.imports.get(name.split(".")[0], "")):
            if cand and cand in self._classes:
                return cand
        # dotted base via imported module: store.CallbackStore
        parts = name.split(".")
        if len(parts) > 1 and parts[0] in mod.imports:
            cand = ".".join([mod.imports[parts[0]]] + parts[1:])
            if cand in self._classes:
                return cand
        return None

    def _method_lookup(self, mod: Module, cls: str, name: str,
                       depth: int = 0) -> str | None:
        if cls not in self._classes or depth > 4:
            return None
        methods, bases, defining_mod = self._classes[cls]
        if name in methods:
            return methods[name]
        for b in bases:
            bq = self._resolve_class(defining_mod, b)
            if bq:
                hit = self._method_lookup(mod, bq, name, depth + 1)
                if hit:
                    return hit
        return None

    # -------------------------------------------------- symbol resolution
    def resolve_expr(self, fn: FuncInfo,
                     expr: ast.AST) -> tuple[str | None, str, str]:
        """Resolve a Name/Attribute expression in ``fn``'s scope to
        ``(dotted target or None, bare name, display text)``. Shared by
        call extraction and the passes that resolve bare function
        REFERENCES (``asyncio.to_thread(f, ...)`` arguments, thread
        targets, decorator expressions)."""
        if isinstance(expr, ast.Name):
            n = expr.id
            mod = fn.module
            for cand in (f"{fn.qualname}.{n}", f"{mod.name}.{n}"):
                if cand in self.functions:
                    return cand, n, n
            if n in mod.imports:
                return mod.imports[n], n, n
            return None, n, n
        if isinstance(expr, ast.Attribute):
            parts = _dotted(expr)
            if parts is None:
                return None, expr.attr, f"?.{expr.attr}"
            mod = fn.module
            text = ".".join(parts)
            if parts[0] == "self" and fn.cls and len(parts) == 2:
                hit = self._method_lookup(mod, fn.cls, parts[1])
                return hit, parts[1], text
            if parts[0] in mod.imports:
                base = mod.imports[parts[0]]
                # imported module member (time.sleep, jnp.where, ...) or
                # an imported project function — the dotted form either way
                return ".".join([base] + parts[1:]), parts[-1], text
            if f"{mod.name}.{parts[0]}" in self._classes:
                # ClassName.method(...) on a module-local class
                hit = self._method_lookup(
                    mod, f"{mod.name}.{parts[0]}", parts[-1])
                return hit, parts[-1], text
            return None, parts[-1], text
        return None, "<dynamic>", "<dynamic>"

    def resolve_class(self, fn: FuncInfo, expr: ast.AST) -> str | None:
        """Resolve an expression naming a project class (a constructor
        call's ``func``) to its class qualname, else None. Module-local
        class names resolve here even though ``resolve_expr`` (which
        answers for *functions*) leaves them None."""
        if isinstance(expr, ast.Name):
            mod = fn.module
            for cand in (f"{mod.name}.{expr.id}",
                         mod.imports.get(expr.id, "")):
                if cand and cand in self._classes:
                    return cand
            return None
        target, _, _ = self.resolve_expr(fn, expr)
        if target is not None and target in self._classes:
            return target
        return None

    def class_method(self, cls_qualname: str, name: str) -> str | None:
        """Method qualname of ``name`` on a project class (base classes
        included), else None."""
        if cls_qualname not in self._classes:
            return None
        _, _, mod = self._classes[cls_qualname]
        return self._method_lookup(mod, cls_qualname, name)

    def class_methods(self, cls_qualname: str) -> dict[str, str]:
        """Own (non-inherited) methods of a project class: name ->
        qualname; empty for unknown classes."""
        if cls_qualname not in self._classes:
            return {}
        return dict(self._classes[cls_qualname][0])

    def iter_classes(self):
        """Project class qualnames (the per-class state passes walk)."""
        return self._classes.keys()

    # ------------------------------------------------------ call extraction
    def _extract_calls(self, fn: FuncInfo) -> None:
        def resolve(call: ast.Call) -> CallSite:
            target, attr, text = self.resolve_expr(fn, call.func)
            return CallSite(target, attr, call.lineno, text)

        # Lambda is skipped too: a lambda body runs when the lambda is
        # CALLED, not where it is written — attributing its calls to the
        # enclosing function would break loopblock's guarantee that
        # executor hand-offs neutralize by construction (e.g.
        # ``await asyncio.to_thread(lambda: batch.verify(...))`` must
        # not create a call edge from the enclosing async def)
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, skip):
                    continue
                if isinstance(child, ast.Call):
                    fn.calls.append(resolve(child))
                walk(child)

        # walk the body only: decorators run at def time, not call time,
        # and nested defs/classes are indexed as their own functions
        for stmt in fn.node.body:
            if isinstance(stmt, skip):
                continue
            if isinstance(stmt, ast.Call):  # unreachable, Calls are exprs
                fn.calls.append(resolve(stmt))
            walk(stmt)

    # ------------------------------------------------- decorator wrappers
    def _passthrough_wrapper(self,
                             factory: FuncInfo) -> tuple[str, ast.Call] | None:
        """``(wrapper qualname, the wrapper's param-call node)`` when
        ``factory`` is a functools.wraps-style pass-through decorator: a
        sync function taking the wrapped function as a parameter,
        defining ONE nested def that calls that parameter, and returning
        the nested def. Anything fancier (argument-taking decorator
        factories, class decorators) stays unresolved — conservative,
        like the rest of the graph. The call node anchors the synthetic
        wrapper->wrapped edge at the real ``f(...)`` site, so passes
        that match CallSites back to the AST (lockheld) see it."""
        node = factory.node
        if factory.is_async or not isinstance(node, ast.FunctionDef):
            return None
        params = {a.arg for a in (node.args.posonlyargs + node.args.args)}
        if not params:
            return None
        nested = [n for n in node.body if isinstance(n, ast.FunctionDef)]
        if len(nested) != 1:
            return None
        wrapper = nested[0]
        returned = any(isinstance(n, ast.Return)
                       and isinstance(n.value, ast.Name)
                       and n.value.id == wrapper.name
                       for n in node.body)
        if not returned:
            return None
        param_call = next(
            (n for n in ast.walk(wrapper)
             if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
             and n.func.id in params), None)
        if param_call is None:
            return None
        wrapper_qn = f"{factory.qualname}.{wrapper.name}"
        if wrapper_qn not in self.functions:
            return None
        return wrapper_qn, param_call

    def _link_decorators(self) -> None:
        """Resolve calls THROUGH single-decorator pass-through wrappers
        (ISSUE 13 satellite). ``@deco`` rebinds ``g`` to ``deco``'s
        returned wrapper, so calling ``g()`` executes BOTH bodies: the
        wrapper's (which may sleep, lock, or dispatch) and the wrapped
        function's. The graph previously had only the edge to the
        wrapped def — a decorator that blocks (or holds a lock) around
        every call it wraps was a loopblock/lockheld blind spot. Here:
        a function decorated with exactly ONE bare project decorator
        whose shape is a pass-through wrapper gains a synthetic edge to
        the wrapper, and the wrapper gains an edge to each function it
        wraps — taint then flows through the decoration in both
        directions, to a fixpoint like every other edge."""
        # factory qualname -> (wrapper qualname, param-call node) | None
        wrappers: dict[str, tuple[str, ast.Call] | None] = {}

        def factory_wrapper(qn: str):
            if qn not in wrappers:
                info = self.functions.get(qn)
                wrappers[qn] = (self._passthrough_wrapper(info)
                                if info is not None else None)
            return wrappers[qn]

        for fn in list(self.functions.values()):
            decs = getattr(fn.node, "decorator_list", [])
            if len(decs) != 1 or not isinstance(decs[0],
                                                (ast.Name, ast.Attribute)):
                continue
            target, _, text = self.resolve_expr(fn, decs[0])
            if target is None or target not in self.functions:
                continue
            hit = factory_wrapper(target)
            if hit is None:
                continue
            wrapper_qn, param_call = hit
            wrapper = self.functions[wrapper_qn]
            fn.calls.append(CallSite(
                wrapper_qn, wrapper_qn.rsplit(".", 1)[-1], fn.line,
                f"@{text}"))
            # anchored at the wrapper's real `f(...)` call, under the
            # param's name, so AST-matching passes see the edge where
            # the wrapped body actually executes (e.g. inside a
            # with-lock block)
            wrapper.calls.append(CallSite(
                fn.qualname, param_call.func.id, param_call.lineno,
                f"wraps:{fn.qualname}"))

    # ------------------------------------------------------------ helpers
    def iter_functions(self):
        return self.functions.values()
