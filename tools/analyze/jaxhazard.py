"""jaxhazard: JAX-specific correctness and recompile hazards.

Rules:

- ``tracer-branch`` (high): Python ``if``/``while`` on a traced value
  inside a jitted function — either the condition computes through
  ``jnp.``/``lax.`` directly, or it references a name assigned from a
  ``jnp.``/``lax.`` call, or it references a non-static parameter.
  Tracing either raises ``TracerBoolConversionError`` or silently bakes
  one branch into the executable.
- ``float-dtype`` (high): float dtypes inside the limb-arithmetic
  modules (``ops/``) — 255-bit limb math must stay exact-integer; a
  float sneaking in is silent precision loss, not an error.
- ``host-transfer`` (medium): ``np.array``/``np.asarray``/
  ``jax.device_get``/``device_put``/``.block_until_ready()``/
  ``int()``/``float()`` over traced values inside a jitted function —
  a device round-trip per call, invisible in the profile.
- ``dynamic-shape`` (high): a non-static parameter of a jitted function
  used in ``range()`` or a shape position — concretization fails at
  trace time or forces a recompile per distinct value, which is exactly
  what the ``engine_compile_seconds`` split exists to catch.
- ``jit-per-call`` (medium): ``jax.jit(f)(...)`` immediately invoked
  inside a function body — a fresh compile cache (and likely a fresh
  compile) on every call.

Jit detection covers decorators (``@jit``, ``@jax.jit``,
``@partial(jax.jit, ...)``) and module-level ``g = jax.jit(f, ...)``
rebinding of a local function.
"""

from __future__ import annotations

import ast

from .core import Finding, FuncInfo, Project, _dotted

# attribute-position tokens stay narrow: ``limb.double`` is a limb
# DOUBLING helper, not numpy.double — generic aliases only match as
# dtype string literals
_FLOAT_ATTRS = {"float16", "float32", "float64", "bfloat16", "float_"}
_FLOAT_STRINGS = _FLOAT_ATTRS | {"half", "single", "double", "float"}
_TRANSFER_CALLS = {"numpy.array", "numpy.asarray", "numpy.frombuffer",
                   "jax.device_get", "jax.device_put"}
_JAXY_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.")


def _jaxy_name(dotted: str | None) -> bool:
    return dotted is not None and (
        dotted.startswith(_JAXY_PREFIXES) or dotted.startswith("jnp.")
        or dotted.startswith("lax."))


def _resolve_dotted(fn: FuncInfo, expr: ast.AST) -> str | None:
    parts = _dotted(expr)
    if not parts:
        return None
    head = fn.module.imports.get(parts[0], parts[0])
    return ".".join([head] + parts[1:])


def _jit_static_params(fn: FuncInfo) -> tuple[bool, set[str]] | None:
    """(is_jitted, static param names), or None when not jitted."""
    node = fn.node
    decs = getattr(node, "decorator_list", [])
    for dec in decs:
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call else dec
        dotted = _resolve_dotted(fn, target)
        if dotted is None:
            continue
        if dotted.endswith(".jit") or dotted == "jit" \
                or dotted == "jax.jit":
            return True, _statics_from_call(fn, call)
        if dotted.endswith("partial") and call and call.args:
            inner = _resolve_dotted(fn, call.args[0])
            if inner and (inner.endswith(".jit") or inner == "jit"):
                return True, _statics_from_call(fn, call)
    return None


def _statics_from_call(fn: FuncInfo, call: ast.Call | None) -> set[str]:
    if call is None:
        return set()
    # static_argnums indexes the POSITIONAL parameter list, which starts
    # with positional-only params — args.args alone misaligns them
    params = [a.arg for a in (fn.node.args.posonlyargs
                              + fn.node.args.args)]
    statics: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    statics.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(params):
                        statics.add(params[n.value])
    return statics


def _module_level_jitted(project: Project) -> dict[str, set[str]]:
    """qualname -> static names, for ``g = jax.jit(f, ...)`` bindings."""
    out: dict[str, set[str]] = {}
    for mod in project.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            parts = _dotted(call.func)
            if not parts:
                continue
            head = mod.imports.get(parts[0], parts[0])
            dotted = ".".join([head] + parts[1:])
            if not (dotted == "jax.jit" or dotted.endswith(".jit")
                    or dotted == "jit"):
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            target = f"{mod.name}.{call.args[0].id}"
            if target in project.functions:
                fn = project.functions[target]
                out[target] = _statics_from_call(fn, call)
    return out


def run(project: Project,
        float_dtype_dirs: tuple[str, ...] = ("ops/",)) -> list[Finding]:
    findings: list[Finding] = []
    jitted_extra = _module_level_jitted(project)

    for fn in project.iter_functions():
        uses_jax = any(v.startswith(("jax", "jnp", "lax"))
                       for v in fn.module.imports.values())
        jit = _jit_static_params(fn)
        statics: set[str] = set()
        is_jitted = False
        if jit is not None:
            is_jitted, statics = jit
        elif fn.qualname in jitted_extra:
            is_jitted, statics = True, jitted_extra[fn.qualname]
        if is_jitted:
            findings.extend(_scan_jitted(fn, statics))
        if uses_jax:
            findings.extend(_scan_jit_per_call(fn))
    findings.extend(_scan_float_dtypes(project, float_dtype_dirs))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def _scan_jitted(fn: FuncInfo, statics: set[str]) -> list[Finding]:
    out: list[Finding] = []
    # positional-only and keyword-only params trace like any other
    # argument (jax.jit traces kwargs too) — only the statics are exempt
    params = {a.arg for a in (fn.node.args.posonlyargs + fn.node.args.args
                              + fn.node.args.kwonlyargs)} \
        - statics - {"self"}

    # names assigned from jnp./lax. calls are tracer-ish
    tracerish: set[str] = set(params)
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign):
            src_jaxy = any(
                isinstance(c, ast.Call) and _jaxy_name(
                    _resolve_dotted(fn, c.func))
                for c in ast.walk(node.value))
            if src_jaxy:
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tracerish.add(n.id)

    def refs_tracer(expr: ast.AST) -> str | None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in tracerish:
                return n.id
            if isinstance(n, ast.Call) and _jaxy_name(
                    _resolve_dotted(fn, n.func)):
                return ast.unparse(n.func) if hasattr(ast, "unparse") \
                    else "jnp call"
        return None

    for node in ast.walk(fn.node):
        if isinstance(node, (ast.If, ast.While)):
            hit = refs_tracer(node.test)
            if hit:
                kind = "while" if isinstance(node, ast.While) else "if"
                out.append(Finding(
                    pass_name="jaxhazard", rule="tracer-branch",
                    severity="high", path=fn.module.relpath,
                    line=node.lineno, symbol=fn.qualname,
                    message=(f"Python `{kind}` on traced value `{hit}` "
                             f"inside jitted `{fn.qualname}` — use "
                             f"lax.cond/select, or mark the value "
                             f"static")))
        elif isinstance(node, ast.Call):
            dotted = _resolve_dotted(fn, node.func)
            # np.array/asarray on CONSTANTS at trace time is fine (and
            # idiomatic); only a traced operand means a device sync
            np_pull = (dotted in _TRANSFER_CALLS
                       and dotted.startswith("numpy.")
                       and any(refs_tracer(a) for a in node.args))
            always = (dotted in _TRANSFER_CALLS
                      and not dotted.startswith("numpy."))
            if np_pull or always or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"):
                out.append(Finding(
                    pass_name="jaxhazard", rule="host-transfer",
                    severity="medium", path=fn.module.relpath,
                    line=node.lineno, symbol=fn.qualname,
                    message=(f"host<->device transfer `{dotted or 'block_until_ready'}` "
                             f"inside jitted `{fn.qualname}` — hoist out "
                             f"of the traced path")))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("int", "float", "bool") \
                    and node.args and refs_tracer(node.args[0]):
                out.append(Finding(
                    pass_name="jaxhazard", rule="host-transfer",
                    severity="medium", path=fn.module.relpath,
                    line=node.lineno, symbol=fn.qualname,
                    message=(f"`{node.func.id}()` concretizes a traced "
                             f"value inside jitted `{fn.qualname}` — a "
                             f"device sync per call")))
            elif isinstance(node.func, ast.Name) and node.func.id == "range":
                for arg in node.args:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name) and n.id in params:
                            out.append(Finding(
                                pass_name="jaxhazard", rule="dynamic-shape",
                                severity="high", path=fn.module.relpath,
                                line=node.lineno, symbol=fn.qualname,
                                message=(f"non-static parameter `{n.id}` "
                                         f"drives `range()` inside jitted "
                                         f"`{fn.qualname}` — trace-time "
                                         f"error or recompile per value; "
                                         f"mark it static or use "
                                         f"lax.fori_loop")))
                            break
            elif dotted and dotted.rsplit(".", 1)[-1] in (
                    "zeros", "ones", "empty", "full", "arange") \
                    and _jaxy_name(dotted) and node.args:
                for n in ast.walk(node.args[0]):
                    if isinstance(n, ast.Name) and n.id in params:
                        out.append(Finding(
                            pass_name="jaxhazard", rule="dynamic-shape",
                            severity="high", path=fn.module.relpath,
                            line=node.lineno, symbol=fn.qualname,
                            message=(f"non-static parameter `{n.id}` used "
                                     f"as a shape in jitted "
                                     f"`{fn.qualname}` — shapes must be "
                                     f"concrete at trace time; mark it "
                                     f"static (and watch the recompile "
                                     f"cache key)")))
                        break
    return out


def _scan_jit_per_call(fn: FuncInfo) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        # jax.jit(f)(args): the OUTER call's func is itself a jit call
        inner = node.func
        if isinstance(inner, ast.Call):
            dotted = _resolve_dotted(fn, inner.func)
            if dotted and (dotted == "jax.jit" or dotted.endswith(".jit")
                           or dotted == "jit"):
                out.append(Finding(
                    pass_name="jaxhazard", rule="jit-per-call",
                    severity="medium", path=fn.module.relpath,
                    line=node.lineno, symbol=fn.qualname,
                    message=(f"`jit(...)(...)` immediately invoked inside "
                             f"`{fn.qualname}` — a fresh compile cache "
                             f"every call; hoist the jitted callable")))
    return out


def _scan_float_dtypes(project: Project,
                       dirs: tuple[str, ...]) -> list[Finding]:
    out: list[Finding] = []
    for mod in project.modules.values():
        # match whole path components, not substrings: "ops/" must hit
        # drand_tpu/ops/bl.py but not a future loops/ or drops/ package
        parents = mod.relpath.split("/")[:-1]
        if not any(d.strip("/") in parents for d in dirs):
            continue
        for node in ast.walk(mod.tree):
            name = None
            if isinstance(node, ast.Attribute) \
                    and node.attr in _FLOAT_ATTRS:
                name = node.attr
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in _FLOAT_STRINGS:
                name = node.value
            if name is None:
                continue
            out.append(Finding(
                pass_name="jaxhazard", rule="float-dtype",
                severity="high", path=mod.relpath,
                line=getattr(node, "lineno", 1), symbol=mod.name,
                message=(f"float dtype `{name}` in limb-math module "
                         f"`{mod.name}` — 255-bit limb arithmetic must "
                         f"stay exact-integer (i32 lanes); floats are "
                         f"silent precision loss")))
    return out
