"""asyncsanity: coroutine and task lifecycle discipline.

- ``unawaited-coroutine`` (high): a bare expression statement calling a
  project ``async def`` — the coroutine object is created, never
  scheduled, and the work silently never happens ("coroutine was never
  awaited" only shows up as a GC-time warning, if ever).
- ``task-without-ref`` (medium): ``asyncio.create_task`` /
  ``ensure_future`` / ``loop.create_task`` whose result is discarded.
  The event loop holds tasks WEAKLY — a GC pass can cancel the work
  mid-flight (the PR-6 exporter bug, now caught mechanically). The fix
  is ``drand_tpu.utils.aio.spawn``, which parks a strong reference
  until the task completes; calls resolving to it are exempt.
"""

from __future__ import annotations

import ast

from .core import Finding, Project

DEFAULT_SAFE_SPAWNERS = ("drand_tpu.utils.aio.spawn",)
_TASK_MAKERS = {"create_task", "ensure_future"}


def run(project: Project,
        safe_spawners: tuple[str, ...] = DEFAULT_SAFE_SPAWNERS,
        ) -> list[Finding]:
    findings: list[Finding] = []
    safe_basenames = {s.rsplit(".", 1)[-1] for s in safe_spawners}

    for fn in project.iter_functions():
        body_stmts = []
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

        def collect(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, skip):
                    continue
                if isinstance(child, ast.Expr):
                    body_stmts.append(child)
                collect(child)

        for stmt in fn.node.body:
            if isinstance(stmt, skip):
                continue
            if isinstance(stmt, ast.Expr):
                body_stmts.append(stmt)
            collect(stmt)

        for stmt in body_stmts:
            call = stmt.value
            if not isinstance(call, ast.Call):
                continue
            target, attr = _resolve(fn, call)
            if target in safe_spawners or (
                    target is None and attr in safe_basenames):
                continue
            if target in project.functions \
                    and project.functions[target].is_async:
                findings.append(Finding(
                    pass_name="asyncsanity", rule="unawaited-coroutine",
                    severity="high", path=fn.module.relpath,
                    line=call.lineno, symbol=fn.qualname,
                    message=(f"`{attr}(...)` is an async def but the "
                             f"coroutine is neither awaited nor "
                             f"scheduled in `{fn.qualname}` — the call "
                             f"silently does nothing")))
            elif attr in _TASK_MAKERS:
                findings.append(Finding(
                    pass_name="asyncsanity", rule="task-without-ref",
                    severity="medium", path=fn.module.relpath,
                    line=call.lineno, symbol=fn.qualname,
                    message=(f"fire-and-forget `{attr}(...)` discards the "
                             f"task reference in `{fn.qualname}` — the "
                             f"loop holds tasks weakly and GC can cancel "
                             f"it mid-flight; use "
                             f"drand_tpu.utils.aio.spawn")))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def _resolve(fn, call: ast.Call):
    """(resolved dotted target or None, bare callee name)."""
    for cs in fn.calls:
        if cs.line == call.lineno and isinstance(call.func, (ast.Name,
                                                             ast.Attribute)):
            name = (call.func.id if isinstance(call.func, ast.Name)
                    else call.func.attr)
            if cs.attr == name:
                return cs.target, cs.attr
    # fallback: resolve in place
    if isinstance(call.func, ast.Name):
        return fn.module.imports.get(call.func.id), call.func.id
    if isinstance(call.func, ast.Attribute):
        return None, call.func.attr
    return None, "<dynamic>"
