"""threadshare: unlocked mutation of state shared between the event
loop and worker threads (ISSUE 13).

Since PR 7 every pairing-class call runs in ``asyncio.to_thread``
workers while the daemon's protocol surfaces stay on the loop — two
genuinely concurrent worlds sharing one address space. ``go test
-race`` would catch a write racing a read across that boundary at
runtime; this pass approximates it statically:

1. **Thread-context map.** Roots are function references handed to a
   thread: ``asyncio.to_thread(f, ...)``, ``loop.run_in_executor(_, f,
   ...)``, ``threading.Thread(target=f)``, ``<executor/pool>.submit(f,
   ...)`` (plus ``functools.partial`` unwrapping and calls inside
   ``lambda`` hand-offs). The thread context is their forward closure
   over the call graph — including constructor and context-manager
   edges (``with _timed(...):`` runs ``__enter__``/``__exit__`` on the
   dispatching thread).
2. **Loop-context map.** Roots are every ``async def`` plus callbacks
   handed to ``call_soon``/``call_soon_threadsafe``/``call_later``;
   same closure.
3. A class attribute or module global is **dual-context** when code in
   BOTH closures touches it (reads count: a loop-side read racing a
   thread-side write is the bug). Mutating it without holding a lock is
   a HIGH finding.

"Holding a lock" means the mutation is lexically inside a sync ``with
<…lock>`` block (the lockheld pass's naming rule — ``async with`` is an
asyncio lock, which does NOT exclude OS threads), or the mutating
method is *lock-covered*: every resolved call site of the method sits
inside such a block of the same project (the ``FlightRecorder._get``
idiom — private helpers that the public ``note_*`` methods only ever
invoke under ``self._lock``). That is how ``_lock``-guarded-by-
construction types — the obs singletons, the stores, the vault — vouch
themselves without a suppression list.

Known false-negative directions (conservative by design, like the rest
of the suite): receivers that cannot be resolved (``self._vault.get``
as a ``to_thread`` argument — an attribute of an attribute), aliasing
through locals, and dynamic dispatch. ``__init__`` is exempt
(construction happens-before publication), as are ``__enter__`` /
``__exit__`` self-attribute writes (context-manager instances are
per-use by idiom; their *module-global* mutations still count — that
is exactly how the ``_timed`` warm-shapes race was caught).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Finding, FuncInfo, Project, _dotted
from .lockheld import lock_name

DEFAULT_EXCLUDE_PREFIXES = ("drand_tpu.testing",)

# container-mutating method names: obj.X.<these>(...) mutates obj.X
MUTATOR_METHODS = frozenset((
    "append", "appendleft", "add", "discard", "remove", "clear",
    "update", "pop", "popleft", "popitem", "setdefault", "extend",
    "insert", "move_to_end",
))

_LOOP_CB_ATTRS = {"call_soon": 0, "call_soon_threadsafe": 0,
                  "call_later": 1, "call_at": 1}

THREAD = "thread"
LOOP = "loop"


def _iter_no_nested(node: ast.AST):
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, skip):
            continue
        yield child
        yield from _iter_no_nested(child)


@dataclass
class _Touch:
    """One self-attribute or module-global access site."""

    fn: FuncInfo
    name: str           # attribute name / global name
    line: int
    mutates: bool
    locked: bool        # lexically inside a sync `with <lock>` block


@dataclass
class _FnFacts:
    """Everything this pass needs from one function's AST, collected in
    a single locked-region-aware walk."""

    attr_touches: list[_Touch] = field(default_factory=list)
    global_touches: list[_Touch] = field(default_factory=list)
    extra_callees: list[str] = field(default_factory=list)
    thread_refs: list[str] = field(default_factory=list)
    loop_refs: list[str] = field(default_factory=list)
    locked_callees: list[str] = field(default_factory=list)
    unlocked_callees: list[str] = field(default_factory=list)


def _module_globals(project: Project) -> dict[str, set[str]]:
    """module name -> names bound at module top level (assignment
    targets only — the mutable-state candidates; imports resolve via
    the imports map instead)."""
    out: dict[str, set[str]] = {}
    for mod in project.modules.values():
        names: set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(node.target, ast.Name):
                    names.add(node.target.id)
        out[mod.name] = names
    return out


def _resolve_ref(project: Project, fn: FuncInfo,
                 expr: ast.AST) -> list[str]:
    """Project-function qualnames a bare callable reference can reach:
    a Name/Attribute, a ``functools.partial(f, ...)`` call, or the
    calls inside a ``lambda`` body."""
    if isinstance(expr, ast.Lambda):
        out = []
        for node in ast.walk(expr.body):
            if isinstance(node, ast.Call):
                target, _, _ = project.resolve_expr(fn, node.func)
                if target in project.functions:
                    out.append(target)
        return out
    if isinstance(expr, ast.Call):
        # functools.partial(f, ...) hands off f
        _, attr, _ = project.resolve_expr(fn, expr.func)
        if attr == "partial" and expr.args:
            return _resolve_ref(project, fn, expr.args[0])
        return []
    target, _, _ = project.resolve_expr(fn, expr)
    return [target] if target in project.functions else []


def _collect(project: Project, fn: FuncInfo,
             mod_globals: dict[str, set[str]]) -> _FnFacts:
    facts = _FnFacts()
    globals_here = mod_globals.get(fn.module.name, set())
    # names that are local to this function shadow module globals —
    # unless declared `global`
    declared_global: set[str] = set()
    local_names: set[str] = set()
    args = fn.node.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        local_names.add(a.arg)
    for node in _iter_no_nested(fn.node):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            local_names.add(node.id)

    def is_global(name: str) -> bool:
        return (name in globals_here
                and (name in declared_global or name not in local_names))

    def touch_attr(name: str, line: int, mutates: bool,
                   locked: bool) -> None:
        facts.attr_touches.append(_Touch(fn, name, line, mutates, locked))

    def touch_global(name: str, line: int, mutates: bool,
                     locked: bool) -> None:
        if is_global(name):
            facts.global_touches.append(
                _Touch(fn, name, line, mutates, locked))

    def self_attr(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None

    def mutation_target(expr: ast.AST, line: int, locked: bool) -> None:
        """Record an assignment/deletion target as a mutation."""
        if isinstance(expr, ast.Name):
            touch_global(expr.id, line, True, locked)
            return
        a = self_attr(expr)
        if a is not None:
            touch_attr(a, line, True, locked)
            return
        if isinstance(expr, ast.Subscript):
            # self.X[k] = v / G[k] = v mutate the container X / G
            mutation_target(expr.value, line, locked)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                mutation_target(el, line, locked)

    def walk(node: ast.AST, locked: bool) -> None:
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, skip):
                continue
            if isinstance(child, ast.With):
                inner = locked or any(
                    lock_name(item.context_expr) is not None
                    for item in child.items)
                # CM classes: `with C(...):` runs __enter__/__exit__
                for item in child.items:
                    if isinstance(item.context_expr, ast.Call):
                        cls = project.resolve_class(
                            fn, item.context_expr.func)
                        if cls is not None:
                            for m in ("__init__", "__enter__", "__exit__"):
                                qn = project.class_method(cls, m)
                                if qn is not None:
                                    facts.extra_callees.append(qn)
                for sub in child.items:
                    walk(sub.context_expr, locked)
                for stmt in child.body:
                    walk(stmt, inner)
                    _visit(stmt, inner)
                continue
            _visit(child, locked)
            walk(child, locked)

    def _visit(child: ast.AST, locked: bool) -> None:
        if isinstance(child, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (child.targets if isinstance(child, ast.Assign)
                       else [child.target])
            for t in targets:
                mutation_target(t, child.lineno, locked)
            if isinstance(child, ast.AugAssign):
                pass  # target covered above; value side visited below
        elif isinstance(child, ast.Delete):
            for t in child.targets:
                mutation_target(t, child.lineno, locked)
        elif isinstance(child, ast.Call):
            func = child.func
            # obj.X.append(...) — a mutator call on the container
            if isinstance(func, ast.Attribute) \
                    and func.attr in MUTATOR_METHODS:
                a = self_attr(func.value)
                if a is not None:
                    touch_attr(a, child.lineno, True, locked)
                elif isinstance(func.value, ast.Name):
                    touch_global(func.value.id, child.lineno, True,
                                 locked)
            # thread hand-offs / loop callbacks / callee bookkeeping
            _classify_call(child, locked)
        elif isinstance(child, ast.Attribute) \
                and isinstance(child.ctx, ast.Load):
            a = self_attr(child)
            if a is not None:
                touch_attr(a, child.lineno, False, locked)
        elif isinstance(child, ast.Name) and isinstance(child.ctx,
                                                        ast.Load):
            touch_global(child.id, child.lineno, False, locked)

    def _classify_call(call: ast.Call, locked: bool) -> None:
        func = call.func
        target, attr, _ = project.resolve_expr(fn, func)
        if attr == "to_thread" and call.args:
            facts.thread_refs.extend(_resolve_ref(project, fn,
                                                  call.args[0]))
        elif attr == "run_in_executor" and len(call.args) >= 2:
            facts.thread_refs.extend(_resolve_ref(project, fn,
                                                  call.args[1]))
        elif attr == "Thread" or target == "threading.Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    facts.thread_refs.extend(
                        _resolve_ref(project, fn, kw.value))
        elif attr == "submit" and call.args \
                and isinstance(func, ast.Attribute):
            recv = _dotted(func.value)
            if recv and any(s in recv[-1].lower()
                            for s in ("executor", "pool")):
                facts.thread_refs.extend(_resolve_ref(project, fn,
                                                      call.args[0]))
        elif attr in _LOOP_CB_ATTRS:
            idx = _LOOP_CB_ATTRS[attr]
            if len(call.args) > idx:
                facts.loop_refs.extend(_resolve_ref(project, fn,
                                                    call.args[idx]))
        if target in project.functions:
            (facts.locked_callees if locked
             else facts.unlocked_callees).append(target)
        else:
            cls = project.resolve_class(fn, func)
            if cls is not None:
                qn = project.class_method(cls, "__init__")
                if qn is not None:
                    facts.extra_callees.append(qn)

    walk(fn.node, False)
    return facts


def analyze(project: Project,
            exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES):
    """The shared context analysis: returns ``(contexts, facts_by_fn,
    dual_attrs, dual_globals, lock_covered)`` where ``contexts`` maps
    function qualnames to subsets of {"thread", "loop"}, ``dual_attrs``
    is ``{(class_qualname, attr)}`` and ``dual_globals`` is
    ``{(module, name)}`` touched from both worlds, and ``lock_covered``
    is the set of methods whose every resolved call site sits inside a
    with-lock block. awaitatomic reuses this to escalate TOCTOU
    findings on thread-shared attributes."""

    def excluded(qn: str) -> bool:
        return any(qn.startswith(p) for p in exclude_prefixes)

    mod_globals = _module_globals(project)
    facts: dict[str, _FnFacts] = {}
    for fn in project.iter_functions():
        if excluded(fn.qualname):
            continue
        facts[fn.qualname] = _collect(project, fn, mod_globals)

    # forward edges: resolved calls + constructor/CM edges
    edges: dict[str, set[str]] = {}
    for qn, f in facts.items():
        outs: set[str] = set()
        for cs in project.functions[qn].calls:
            if cs.target in project.functions \
                    and not excluded(cs.target):
                outs.add(cs.target)
        outs.update(t for t in f.extra_callees if not excluded(t))
        edges[qn] = outs

    contexts: dict[str, set[str]] = {qn: set() for qn in facts}

    def flood(roots: set[str], tag: str) -> None:
        work = [r for r in roots if r in contexts]
        for r in work:
            contexts[r].add(tag)
        while work:
            qn = work.pop()
            for callee in edges.get(qn, ()):
                if tag not in contexts[callee]:
                    contexts[callee].add(tag)
                    work.append(callee)

    thread_roots: set[str] = set()
    loop_roots: set[str] = set()
    for qn, f in facts.items():
        thread_roots.update(f.thread_refs)
        loop_roots.update(f.loop_refs)
        if project.functions[qn].is_async:
            loop_roots.add(qn)
    flood(thread_roots, THREAD)
    flood(loop_roots, LOOP)

    # lock-covered methods: every resolved call site sits inside a
    # with-lock block (the FlightRecorder._get idiom)
    called_locked: set[str] = set()
    called_unlocked: set[str] = set()
    for f in facts.values():
        called_locked.update(f.locked_callees)
        called_unlocked.update(f.unlocked_callees)
    lock_covered = called_locked - called_unlocked

    # context per (class, attr) / (module, global): reads AND writes
    # outside __init__ count — a loop-side read racing a thread-side
    # write is the bug this pass exists for
    attr_ctx: dict[tuple[str, str], set[str]] = {}
    global_ctx: dict[tuple[str, str], set[str]] = {}
    global_mutated: set[tuple[str, str]] = set()
    for qn, f in facts.items():
        fn = project.functions[qn]
        ctx = contexts[qn]
        if fn.cls is not None and fn.node.name != "__init__":
            for t in f.attr_touches:
                attr_ctx.setdefault((fn.cls, t.name), set()).update(ctx)
        for t in f.global_touches:
            key = (fn.module.name, t.name)
            global_ctx.setdefault(key, set()).update(ctx)
            if t.mutates:
                global_mutated.add(key)

    dual_attrs = {k for k, c in attr_ctx.items() if THREAD in c
                  and LOOP in c}
    dual_globals = {k for k, c in global_ctx.items()
                    if THREAD in c and LOOP in c and k in global_mutated}
    return contexts, facts, dual_attrs, dual_globals, lock_covered


def run(project: Project,
        exclude_prefixes: tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES,
        analysis=None) -> list[Finding]:
    """``analysis`` is an optional precomputed :func:`analyze` result —
    the runner shares one with awaitatomic instead of walking twice."""
    contexts, facts, dual_attrs, dual_globals, lock_covered = \
        analysis if analysis is not None \
        else analyze(project, exclude_prefixes)

    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()  # (fn qualname, state name)
    for qn, f in facts.items():
        fn = project.functions[qn]
        vouched = qn in lock_covered
        if fn.node.name in ("__init__",):
            continue
        ctx = contexts[qn]
        if not ctx:
            continue  # unreachable from either world: no race partner
        attr_exempt = fn.node.name in ("__enter__", "__exit__")
        for t in f.attr_touches:
            if not t.mutates or t.locked or vouched or attr_exempt:
                continue
            if fn.cls is None or (fn.cls, t.name) not in dual_attrs:
                continue
            if (qn, t.name) in seen:
                continue
            seen.add((qn, t.name))
            findings.append(Finding(
                pass_name="threadshare", rule="unlocked-shared-mutation",
                severity="high", path=fn.module.relpath, line=t.line,
                symbol=qn,
                message=(f"`{qn}` mutates `self.{t.name}` without the "
                         f"owning lock, but `{fn.cls.rsplit('.', 1)[-1]}"
                         f".{t.name}` is reachable from BOTH the event "
                         f"loop and to_thread workers "
                         f"({'+'.join(sorted(ctx))} context here) — "
                         f"guard the mutation with the class lock or "
                         f"confine the state to one context"),
                detail=t.name))
        for t in f.global_touches:
            if not t.mutates or t.locked or vouched:
                continue
            key = (fn.module.name, t.name)
            if key not in dual_globals:
                continue
            if (qn, t.name) in seen:
                continue
            seen.add((qn, t.name))
            findings.append(Finding(
                pass_name="threadshare", rule="unlocked-global-mutation",
                severity="high", path=fn.module.relpath, line=t.line,
                symbol=qn,
                message=(f"`{qn}` mutates module global `{t.name}` "
                         f"without a lock, but `{fn.module.name}."
                         f"{t.name}` is touched from BOTH the event "
                         f"loop and to_thread workers "
                         f"({'+'.join(sorted(ctx))} context here) — "
                         f"guard it with a module lock (the _H2C_LOCK "
                         f"pattern) or confine it to one context"),
                detail=t.name))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings
