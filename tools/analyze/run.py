#!/usr/bin/env python
"""Aggregating runner for the drand-tpu static-analysis suite.

    python tools/analyze/run.py [--json] [--sarif PATH]
                                [--fail-on high|medium|low]
                                [--passes loopblock,lockheld,...]
                                [--baseline PATH] [--root DIR]
                                [--prune-baseline]

    drand-tpu analyze [--json] [--fail-on ...]     (same thing via CLI)

Host-only and import-free with respect to the analyzed code: everything
is AST, so no jax backend ever initializes and a full-tree run takes a
couple of seconds. Exit status 1 iff any finding at/above ``--fail-on``
(default: high) is not suppressed by the baseline.

Baseline (tools/analyze/baseline.json): reviewed suppressions.

    {"entries": [{"key": "<finding key>", "reason": "<why it is ok>"}]}

Every entry MUST carry a non-empty reason — an unexplained suppression
is itself a high finding. Entries matching nothing (the code got fixed)
are flagged medium so the file never accretes dead weight. Finding keys
are printed with each finding and are line-number-free, so baselines
survive unrelated edits — but loopblock keys DO include the leaf the
path reaches (and lockheld the lock+hazard, threadshare/awaitatomic
the state name), so suppressing one reviewed hazard does not also
suppress a different one added to the same function later.
``--prune-baseline`` rewrites the baseline file in place, dropping
entries the current run flags as stale (pass actually ran, key matched
nothing) while preserving the written reasons of every kept entry.

``--json`` schema (stable; CI parses it)::

    {
      "findings":   [Finding...],   # unsuppressed, strongest first
      "suppressed": [Finding...],   # matched a baseline entry
      "counts":     {"high": N, "medium": N, ...},
      "fail_on":    "high",
      "failing":    N               # findings at/above fail_on
    }
    Finding = {
      "pass": str, "rule": str, "severity": "high|medium|low|info",
      "path": str,                  # repo-relative, forward slashes
      "line": int,                  # 1-based; advisory (keys are
      "symbol": str,                #  line-free)
      "message": str,
      "key": str                    # the baseline-suppression key
    }

``--sarif PATH`` additionally writes the unsuppressed findings as SARIF
2.1.0 (one run, ruleId = "<pass>/<rule>", level error/warning/note for
high/medium/low, the baseline key under partialFingerprints) so CI can
annotate diffs; ``tests/test_zz_analyze.py`` emits it on gate failure
for auditable logs.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

if __package__ in (None, ""):  # executed as a script
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
    from tools.analyze import (asyncsanity, awaitatomic, jaxhazard,
                               lockheld, loopblock, secretflow,
                               threadshare)
    from tools.analyze.core import Finding, Project, SEV_RANK
else:
    from . import (asyncsanity, awaitatomic, jaxhazard, lockheld,
                   loopblock, secretflow, threadshare)
    from .core import Finding, Project, SEV_RANK

REPO = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "baseline.json"
PASSES = ("loopblock", "lockheld", "threadshare", "awaitatomic",
          "secretflow", "jaxhazard", "asyncsanity", "metrics")


def _metrics_pass(root: pathlib.Path) -> list[Finding]:
    """tools/check_metrics.py folded in as the fifth pass, so tier-1 and
    operators drive ONE entry point. Still runnable standalone."""
    if root.resolve() != REPO:
        return []  # catalogue lint is repo-specific, skip on fixtures
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_metrics
        problems = check_metrics.run_lint()
    finally:
        sys.path.remove(str(REPO / "tools"))
    out = []
    for p in problems:
        import hashlib
        tag = hashlib.blake2b(p.encode(), digest_size=4).hexdigest()
        out.append(Finding(
            pass_name="metrics", rule="catalogue", severity="high",
            path="drand_tpu/metrics/__init__.py", line=1,
            symbol=f"problem-{tag}", message=p))
    return out


def load_baseline(path: pathlib.Path) -> tuple[dict[str, str], list[Finding]]:
    """key -> reason, plus findings for malformed entries."""
    problems: list[Finding] = []
    if not path.is_file():
        return {}, problems
    rel = str(path)
    try:
        doc = json.loads(path.read_text())
    except ValueError as e:
        problems.append(Finding(
            pass_name="baseline", rule="malformed", severity="high",
            path=rel, line=1, symbol="<baseline>",
            message=f"baseline is not valid JSON: {e}"))
        return {}, problems
    out: dict[str, str] = {}
    for i, entry in enumerate(doc.get("entries", [])):
        key = entry.get("key", "")
        reason = (entry.get("reason") or "").strip()
        if not key:
            problems.append(Finding(
                pass_name="baseline", rule="malformed", severity="high",
                path=rel, line=1, symbol=f"entry-{i}",
                message="baseline entry missing 'key'"))
            continue
        if len(reason) < 10:
            problems.append(Finding(
                pass_name="baseline", rule="missing-reason",
                severity="high", path=rel, line=1, symbol=key,
                message=(f"baseline entry {key!r} has no written reason "
                         f"— every suppression must explain why the "
                         f"finding is acceptable")))
            continue
        out[key] = reason
    return out, problems


def run_analysis(root: str | pathlib.Path = REPO,
                 passes: tuple[str, ...] = PASSES,
                 baseline_path: str | pathlib.Path | None = None,
                 packages: tuple[str, ...] | None = None) -> dict:
    """-> {"findings": [...], "suppressed": [...], "counts": {...}}.

    ``findings`` are unsuppressed, strongest first. ``root`` defaults to
    the repo; fixture tests point it at temp trees (which skips the
    repo-specific metrics pass automatically).
    """
    root = pathlib.Path(root)
    if packages is None and root.resolve() == REPO:
        packages = ("drand_tpu",)
    project = Project(root, packages=packages)
    all_findings: list[Finding] = []
    if "loopblock" in passes:
        all_findings.extend(loopblock.run(project))
    if "lockheld" in passes:
        all_findings.extend(lockheld.run(project))
    if "threadshare" in passes or "awaitatomic" in passes:
        # one shared context analysis: the thread/loop closure feeds
        # both passes (awaitatomic escalates on thread-shared attrs)
        shared = threadshare.analyze(project)
        if "threadshare" in passes:
            all_findings.extend(threadshare.run(project, analysis=shared))
        if "awaitatomic" in passes:
            _, _, dual_attrs, dual_globals, _ = shared
            all_findings.extend(awaitatomic.run(
                project, dual_attrs=dual_attrs,
                dual_globals=dual_globals))
    if "secretflow" in passes:
        all_findings.extend(secretflow.run(project))
    if "jaxhazard" in passes:
        all_findings.extend(jaxhazard.run(project))
    if "asyncsanity" in passes:
        all_findings.extend(asyncsanity.run(project))
    if "metrics" in passes:
        all_findings.extend(_metrics_pass(root))

    bl_path = pathlib.Path(baseline_path) if baseline_path \
        else DEFAULT_BASELINE
    baseline, bl_problems = load_baseline(bl_path)
    all_findings.extend(bl_problems)

    suppressed, open_findings = [], []
    used_keys: set[str] = set()
    for f in all_findings:
        if f.key in baseline:
            used_keys.add(f.key)
            suppressed.append(f)
        else:
            open_findings.append(f)
    for key in sorted(set(baseline) - used_keys):
        # staleness is only decidable for entries whose pass actually
        # ran this invocation — a --passes subset must not misreport
        # the other passes' suppressions as dead
        if key.split(":", 1)[0] not in passes:
            continue
        open_findings.append(Finding(
            pass_name="baseline", rule="stale-entry", severity="medium",
            path=str(bl_path), line=1, symbol=key,
            message=(f"baseline entry {key!r} matches no current finding "
                     f"— the code was fixed; delete the entry")))

    open_findings.sort(
        key=lambda f: (-SEV_RANK[f.severity], f.pass_name, f.path, f.line))
    counts: dict[str, int] = {}
    for f in open_findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return {
        "findings": open_findings,
        "suppressed": suppressed,
        "counts": counts,
    }


def to_sarif(report: dict, fail_on: str = "high") -> dict:
    """The report's unsuppressed findings as a SARIF 2.1.0 log (one
    run; ruleId = "<pass>/<rule>"; the baseline key rides in
    partialFingerprints so diff-annotation tooling can track a finding
    across line moves, exactly like the baseline file does)."""
    level = {"high": "error", "medium": "warning", "low": "note",
             "info": "note"}
    rules: dict[str, dict] = {}
    results = []
    for f in report["findings"]:
        rule_id = f"{f.pass_name}/{f.rule}"
        rules.setdefault(rule_id, {
            "id": rule_id,
            "shortDescription": {"text": f"{f.pass_name}: {f.rule}"},
        })
        results.append({
            "ruleId": rule_id,
            "level": level.get(f.severity, "note"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
                "logicalLocations": [{"fullyQualifiedName": f.symbol}],
            }],
            "partialFingerprints": {"drandAnalyzeKey/v1": f.key},
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "drand-tpu-analyze",
                "rules": list(rules.values()),
            }},
            "results": results,
            "properties": {"failOn": fail_on,
                           "counts": report["counts"],
                           "suppressed": len(report["suppressed"])},
        }],
    }


def write_sarif(report: dict, path: str | pathlib.Path,
                fail_on: str = "high") -> None:
    """Serialize :func:`to_sarif` to ``path`` (the --sarif flag and the
    tier-1 test's on-failure audit log share this)."""
    pathlib.Path(path).write_text(
        json.dumps(to_sarif(report, fail_on), indent=2) + "\n")


def prune_baseline(report: dict, passes: tuple[str, ...],
                   path: pathlib.Path) -> tuple[list[str], int]:
    """Rewrite the baseline at ``path`` dropping entries the current
    run proves stale: VALID entries (key + written reason) whose pass
    actually ran and whose key matched no finding. Malformed entries
    (missing key/reason) are kept — they are live high findings a human
    must resolve, not dead weight — and reasons of kept entries are
    preserved byte-for-byte. Returns (dropped keys, kept count)."""
    doc = json.loads(path.read_text()) if path.is_file() else {}
    valid, _problems = load_baseline(path)
    matched = {f.key for f in report["suppressed"]}
    kept, dropped = [], []
    for entry in doc.get("entries", []):
        key = entry.get("key", "")
        stale = (key in valid and key not in matched
                 and key.split(":", 1)[0] in passes)
        if stale:
            dropped.append(key)
        else:
            kept.append(entry)
    if dropped:
        # replace only the entries list: any other top-level keys the
        # document carries survive the rewrite
        doc["entries"] = kept
        path.write_text(json.dumps(doc, indent=2) + "\n")
    return dropped, len(kept)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="drand analyze",
        description="drand-tpu AST static-analysis suite")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (schema in the module "
                         "docstring)")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write unsuppressed findings as SARIF "
                         "2.1.0 to PATH (CI diff annotation)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline file dropping entries "
                         "this run flags as stale (reasons of kept "
                         "entries preserved)")
    ap.add_argument("--fail-on", choices=("high", "medium", "low"),
                    default="high",
                    help="exit 1 when an unsuppressed finding at/above "
                         "this severity exists (default: high)")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of {','.join(PASSES)}")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--root", default=None,
                    help="tree to analyze (default: this repo)")
    args = ap.parse_args(argv)

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = set(passes) - set(PASSES)
    if unknown:
        ap.error(f"unknown pass(es): {sorted(unknown)}")
    report = run_analysis(root=args.root or REPO, passes=passes,
                          baseline_path=args.baseline)

    if args.prune_baseline:
        bl = (pathlib.Path(args.baseline) if args.baseline
              else DEFAULT_BASELINE)
        dropped, kept = prune_baseline(report, passes, bl)
        # stderr: --json's stdout is a documented machine contract and
        # must stay a single parseable JSON document
        for key in dropped:
            print(f"prune-baseline: dropped stale entry {key}",
                  file=sys.stderr)
        print(f"prune-baseline: {len(dropped)} dropped, {kept} kept "
              f"({bl})", file=sys.stderr)
        # the dropped entries' stale-entry findings are resolved by the
        # rewrite — do not double-report them below
        report["findings"] = [
            f for f in report["findings"]
            if not (f.rule == "stale-entry" and f.symbol in dropped)]
        report["counts"] = {}
        for f in report["findings"]:
            report["counts"][f.severity] = \
                report["counts"].get(f.severity, 0) + 1

    findings = report["findings"]
    threshold = SEV_RANK[args.fail_on]
    failing = [f for f in findings if SEV_RANK[f.severity] >= threshold]
    if args.sarif:
        write_sarif(report, args.sarif, args.fail_on)

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": [f.to_dict() for f in report["suppressed"]],
            "counts": report["counts"],
            "fail_on": args.fail_on,
            "failing": len(failing),
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        n_sup = len(report["suppressed"])
        counts = " ".join(f"{k}={v}"
                          for k, v in sorted(report["counts"].items()))
        print(f"\nanalyze: {len(findings)} finding(s) "
              f"({counts or 'none'}), {n_sup} suppressed by baseline, "
              f"{len(failing)} at/above --fail-on={args.fail_on}")
        if failing:
            print("analyze: FAIL — fix the finding or add a baseline "
                  "entry with a written reason (key printed above)")
        else:
            print("analyze: OK")
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
