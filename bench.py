#!/usr/bin/env python
"""Benchmarks on one chip: BASELINE.md's five configs plus the headline
pairing throughput.

Prints ONE JSON line per config to stdout with the HEADLINE LAST (the
driver parses the final line); diagnostics go to stderr.

Measurement methodology — matters on the tunneled axon TPU:
- Dispatch is async, but a blocking sync (np.asarray / block_until_ready
  on an in-flight result) costs ~100 ms of transport polling regardless
  of the actual wait, and the shared tunnel shows minute-scale load
  variance (the same kernel measures 14 ms or 250 ms depending on the
  window). Every timed section therefore pipelines many calls with a
  single tail drain, runs several trials, and reports the best sustained
  window. Device-profiler cross-check (jax.profiler device timeline,
  2026-07-30): the B=128 verify chain is 11.6 ms/call on-device — the
  round-2 figure of 2,015 pairings/s was per-call sync overhead, not
  compute.
- Every batch size is self-checked (positive AND negative rows) against
  host truth before it is timed; a failing size is skipped (the known
  axon libtpu skew produces silently-wrong executables at some shapes —
  ops/engine.py bucket validation).

Environment knobs:
    BENCH_BATCH        batch sizes to try, largest first (default
                       "512,128,16,8,4"); multiples of 128 run the
                       batch-blocked grid-kernel chain
    BENCH_MIN_SECONDS  minimum timed window per trial (default 5.0)
    BENCH_TRIALS       trials per config (default 2; best wins)
    BENCH_CONFIGS      comma list to run: any of
                       client_catchup,msm,glv4,rlc,obs,flight,incident,
                       remediate,chaos,timelock,fanout,segstore,
                       vault_scale,shard,e2e,catchup,recover,deal,replay,
                       headline
                       (default: all; client_catchup, msm, glv4, rlc, obs,
                       flight, incident, remediate, chaos, timelock, fanout,
                       segstore and vault_scale are host-only and run
                       FIRST, before backend init, so they report even with
                       the TPU tunnel down — shard re-execs onto the
                       virtual CPU mesh and is bounded by the remaining
                       budget)
    BENCH_CATCHUP_ROUNDS    client_catchup structural chain depth (1000000)
    BENCH_CATCHUP_BASELINE  chunk-64 baseline walk subset (131072)
    BENCH_CATCHUP_REAL_SPAN real-crypto corruption/checkpoint span (160)
    BENCH_CHAOS_N      chaos_soak network size (default 32)
    BENCH_FANOUT_WATCHERS  relay_fanout concurrent watchers (10000)
    BENCH_FANOUT_SOCKETS   how many of them are real TCP SSE streams
                           (1024; 2 fds per socket watcher under the
                           box's 20k rlimit caps this)
    BENCH_FANOUT_ROUNDS    rounds to hold the watchers through (10)
    BENCH_SEGSTORE_DEPTH   segment-vs-sqlite chain depth (1000000)
    BENCH_SEGSTORE_READ    rounds per cursor_from walk (200000)
    BENCH_VAULT_ROWS       vault_scale timelock depth, both backends
                           (10000000; ~5 GiB transient disk)
    BENCH_VAULT_OPEN_K     vault_scale boundary-open ciphertext count
                           (10000; the sweep decrypts all of them —
                           ~40 ms each on the 1-core box, so raise
                           BENCH_BUDGET_SECONDS for a full-scale run)
    DRAND_TPU_CONV     tree|kara|unroll — limb conv strategy (A/B)
    DRAND_TPU_LAZY     1|0 — lazy Fp2/6/12 reduction (A/B)
    DRAND_TPU_PAIRFOLD 1|0 — paired-line Miller fold (A/B)
                       (knobs are recorded in the headline JSON;
                       scripts/ab_bench.sh runs the matrix)

Reference hot paths measured: chain/beacon/chain.go:136-141 (aggregator
recover+verify), client/verify.go:146-163 (catchup), kyber vss deal
verification (DKG), demo/ (e2e network).
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj):
    print(json.dumps(obj), flush=True)


def trials_of(trials, fn):
    """(best, sorted trial list) of ``trials`` runs — the tunnel's ~20x
    load variance makes best-window a device-time estimate and the
    median the steady-state estimate; headline reports both (VERDICT r4
    weak #5)."""
    vals = []
    for i in range(trials):
        v = fn()
        log(f"  trial {i}: {v:.2f}")
        vals.append(v)
    return min(vals), sorted(vals)


def best_of(trials, fn):
    return trials_of(trials, fn)[0]


def _mk_pool(sk, pool=8):
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2
    from drand_tpu.ops.engine import _g1_aff, _g2_aff

    pub_aff = _g1_aff(PointG1.generator().mul(sk))
    sigs, msgs, raw = [], [], []
    for i in range(pool):
        m = b"drand-tpu-bench-round-%d" % i
        s = bls.sign(sk, m)
        raw.append((m, s))
        msgs.append(_g2_aff(hash_to_g2(m)))
        sigs.append(_g2_aff(PointG2.from_bytes(s, subgroup_check=False)))
    return pub_aff, sigs, msgs, raw


def bench_headline(trials, min_seconds):
    """Pairing throughput: pipelined batched verify calls, tail drain."""
    import numpy as np
    from drand_tpu.ops import limb, pallas_pairing as pp

    batches = [int(b) for b in
               os.environ.get("BENCH_BATCH", "512,128,8").split(",")]
    measured = 0
    sk = 0x1F3A
    pub_aff, pool_sigs, pool_msgs, _ = _mk_pool(sk)
    best_rate = None
    for batch in batches:
        pubs = np.broadcast_to(pub_aff, (batch, 2, limb.NLIMBS))
        sigs = np.stack([pool_sigs[i % 8] for i in range(batch)])
        msgs = np.stack([pool_msgs[i % 8] for i in range(batch)])
        # pack to the device layout ONCE: the timed loop measures the
        # jitted kernel chain, not per-call host packing
        use_grid = batch % pp.GRID_BLOCK == 0
        args_ok = pp.pack_verify_inputs(pubs, sigs, msgs)
        bad = sigs.copy()
        bad[0] = pool_sigs[1]
        args_bad = pp.pack_verify_inputs(pubs, bad, msgs)

        def verify(args):
            if use_grid:
                return pp._verify_pl_grid(*args, npairs=2, b=batch)
            return pp._verify_pl(*args, npairs=2, b=batch)

        t0 = time.perf_counter()
        try:
            out = np.asarray(verify(args_ok))
        except Exception as e:  # noqa: BLE001 — probe the next size
            log(f"batch {batch}: failed to compile/run: {e!r} — skipping")
            continue
        log(f"batch {batch}: first call (compile+run) "
            f"{time.perf_counter() - t0:.1f}s")
        if not out.all():
            log(f"batch {batch}: False on valid inputs (backend "
                f"miscompile) — skipping")
            continue
        bad_out = np.asarray(verify(args_bad))
        if bad_out[0] or not bad_out[1:].all():
            log(f"batch {batch}: negative self-check failed — skipping")
            continue

        # estimate per-call time with a short pipelined burst. Drain
        # discipline: sync ONCE on the last output (one ~100 ms transport
        # polling penalty), then pull the completed results — draining
        # in-flight outputs one by one pays the polling floor per call.
        t0 = time.perf_counter()
        outs = [verify(args_ok) for _ in range(4)]
        outs[-1].block_until_ready()
        est = (time.perf_counter() - t0) / 4
        k = max(4, int(min_seconds / max(est, 1e-4)))

        def timed():
            import jax.numpy as jnp

            t0 = time.perf_counter()
            outs = [verify(args_ok) for _ in range(k)]
            outs[-1].block_until_ready()
            dt = time.perf_counter() - t0
            # one stacked transfer: per-array d2h pays a ~100 ms polling
            # floor through the tunnel even for completed results
            res = np.asarray(jnp.stack(outs))
            if not res.all():
                raise RuntimeError("self-check failed inside timed loop")
            return dt / k

        per_call, tvals = trials_of(trials, timed)
        mid = len(tvals) // 2
        per_call_med = (tvals[mid] if len(tvals) % 2
                        else (tvals[mid - 1] + tvals[mid]) / 2)
        rate = 2 * batch / per_call
        log(f"batch {batch}: {per_call * 1e3:.1f} ms/call best "
            f"-> {rate:.0f} pairings/s")
        if best_rate is None or rate > best_rate[0]:
            best_rate = (rate, batch, per_call, per_call_med)
        measured += 1
        if measured >= 2:
            break  # two good sizes suffice; smaller ones are fallbacks
    if best_rate is None:
        log("FATAL: no batch size produced correct results")
        raise SystemExit(1)
    rate, batch, per_call, per_call_med = best_rate
    from drand_tpu.ops import bl as _bl

    return {"metric": "pairings_per_sec", "value": round(rate, 1),
            "unit": "pairings/s", "vs_baseline": round(rate / 200000.0, 4),
            "batch": batch, "ms_per_call": round(per_call * 1e3, 2),
            "median_rate": round(2 * batch / per_call_med, 1),
            "median_ms_per_call": round(per_call_med * 1e3, 2),
            # A/B knobs active for this record (all trace-time consts)
            "conv": _bl.CONV_MODE, "lazy": _bl.LAZY, "pairfold": pp.PAIRFOLD}


def bench_catchup(trials, n_rounds=10_000):
    """10k-round catchup: wire-format dual-ish verification throughput via
    engine.verify_wire (device hashing + decompression + pairing), checks
    tiled from a pool of real signatures (verification cost is
    content-independent straight-line code)."""
    import numpy as np
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.crypto import batch as cbatch

    sk = 0x1F3A
    _, _, _, raw = _mk_pool(sk, pool=64)
    pub = PointG1.generator().mul(sk)
    eng = cbatch.engine()
    checks = [raw[i % 64] for i in range(n_rounds)]
    path = "wire"
    try:
        head = np.asarray(eng.verify_wire(pub, checks[:128]))
        if not head.all():
            raise RuntimeError("wire self-check returned False")
    except Exception as e:  # noqa: BLE001 — wire KAT can fail on a bad
        # tunnel window; the triples path (pre-decoded points) still
        # measures the pairing side of catchup
        log(f"catchup: wire path unavailable ({e!r}) — timing the "
            f"triples path (signatures pre-decoded, hashing on host)")
        path = "triples"
        from drand_tpu.crypto.curves import PointG2
        from drand_tpu.crypto.hash_to_curve import hash_to_g2

        tri_pool = [(pub, PointG2.from_bytes(s, subgroup_check=False),
                     hash_to_g2(m)) for m, s in raw]
        triples = [tri_pool[i % 64] for i in range(n_rounds)]

    def timed():
        t0 = time.perf_counter()
        if path == "wire":
            ok = eng.verify_wire(pub, checks)
        else:
            ok = eng.verify_bls(triples)
        dt = time.perf_counter() - t0
        if not np.asarray(ok).all():
            raise RuntimeError("catchup verification failed")
        return dt

    dt = best_of(trials, timed)
    return {"metric": "catchup_10k_rounds_seconds", "value": round(dt, 2),
            "unit": "s", "rounds_per_sec": round(n_rounds / dt, 1),
            "path": path, "vs_baseline": None}


def bench_recover(trials, t=67, n=100, k_rounds=2):
    """67-of-100 round: verify all partials + Lagrange-recover + verify
    the recovered signature — the aggregator's per-round work
    (chain/beacon/chain.go:91-166) at League-of-Entropy-plus scale."""
    from drand_tpu.crypto import tbls
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.crypto.poly import PriPoly
    from drand_tpu.crypto import batch as cbatch

    poly = PriPoly.random(t, seed=b"bench-recover")
    pub_poly = poly.commit()
    pubkey = pub_poly.commit()
    msg = b"bench-recover-round"
    partials = [tbls.sign_partial(s, msg) for s in poly.shares(n)]
    eng = cbatch.engine()

    # warm + correctness: ONE fused dispatch does partial-verify +
    # Lagrange MSM + recovered-verify (engine.aggregate_round;
    # chain/beacon/chain.go:91-166) — the recovered signature is checked
    # CRYPTOGRAPHICALLY in-graph (pairing equality implies the recovery
    # matched the unique group signature; no host re-derivation needed).
    # The fused executable is KAT-gated; a disabled bucket falls back to
    # the classic 3-dispatch path, reported via "fused".
    oks, sig = eng.aggregate_round(pub_poly, msg, partials, t, n)
    assert all(oks), "partial verification failed"
    assert sig and eng.verify_sigs(pubkey, [(msg, sig)]) == [True]
    fused = eng.agg_fused_active(len(partials), t)
    # which aggregate path the round takes: the RLC combine (2 Miller
    # pairs for all partials + 2 for the recovered check) or the classic
    # fused per-item graph
    rlc = eng.agg_rlc_active(len(partials))

    def timed():
        t0 = time.perf_counter()
        for _ in range(k_rounds):
            oks, sig = eng.aggregate_round(pub_poly, msg, partials, t, n)
            if not all(oks) or not sig:
                raise RuntimeError("aggregate round failed")
        return (time.perf_counter() - t0) / k_rounds

    per_round = best_of(trials, timed)
    return {"metric": "recover_67_of_100_seconds_per_round",
            "value": round(per_round, 3), "unit": "s/round",
            "rounds_per_sec": round(1 / per_round, 2), "fused": fused,
            "rlc": rlc, "vs_baseline": None}


def bench_deal_verify(trials, n=128):
    """n=128 DKG deal verification per node: n host g·s checks against ONE
    batched commitment evaluation on device (crypto.batch.eval_commits)
    vs the reference-shaped host loop (per-dealer PubPoly.eval)."""
    import random

    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.crypto.poly import PriPoly
    from drand_tpu.crypto import batch as cbatch
    from drand_tpu.crypto.fields import R

    t = n // 2 + 1
    rnd = random.Random(1234)
    polys = [PriPoly([rnd.randrange(1, R) for _ in range(t)])
             for _ in range(n)]
    pubs = [p.commit() for p in polys]
    my_index = 3
    shares = [p.eval(my_index).value for p in polys]
    eng = cbatch.engine()
    g = PointG1.generator()

    # correctness: the deal check g·s == eval is itself the oracle (the
    # engine's eval KAT covers device-vs-host; a full 128×t host eval
    # here would cost minutes on this box)
    dev = eng.eval_commits(pubs, my_index)
    assert all(g.mul(s) == e for s, e in zip(shares, dev))

    def timed_dev():
        # fresh polys per trial would re-pay host packing; the DKG does
        # exactly one evaluation pass per node, so time pack+eval+check
        t0 = time.perf_counter()
        evals = eng.eval_commits(pubs, my_index)
        ok = all(g.mul(s) == e for s, e in zip(shares, evals))
        if not ok:
            raise RuntimeError("deal verify failed")
        return time.perf_counter() - t0

    def timed_host():
        t0 = time.perf_counter()
        for p, s in zip(pubs, shares):
            p._eval_cache.clear()
            if g.mul(s) != p.eval(my_index).value:
                raise RuntimeError("deal verify failed")
        return time.perf_counter() - t0

    dt_host = best_of(1, timed_host)
    dt_dev = best_of(trials, timed_dev)
    return {"metric": "dkg_deal_verify_n128_seconds",
            "value": round(dt_dev, 3), "unit": "s",
            "host_loop_seconds": round(dt_host, 3),
            "speedup_vs_host": round(dt_host / dt_dev, 2),
            "path": ("pallas-horner" if eng._eval_use_pallas(n)
                     else "xla-graph"),
            "vs_baseline": None}


def bench_dkg_ceremony(trials):
    """Large-group ceremonies (ISSUE 19), host-only, runs FIRST (before
    backend init — like client_catchup, the record must land even with
    the tunnel down, and a stray dispatch must not kick a cold backend
    probe).

    Two measurements:
    - REAL-crypto per-receiver deal verification at n dealers: the
      batched phase admission (batch.parse_commits lockstep membership
      + one eval_commits dispatch + one fixed-base-comb share_checks
      pass) vs the reference-shaped sequential loop
      (from_bytes(subgroup_check=True) per point, per-dealer Horner,
      generator ladder per share). The sequential side is sampled over
      BENCH_DKG_SEQ_SAMPLE dealers and extrapolated — at n=256 the full
      loop would be ~2 minutes of pure baseline.
    - STRUCTURAL n=256 ceremony + 256→256 reshare wall time with the
      flight recorder's per-phase seconds (testing/dkg_scale — the
      protocol machinery at scale; the crypto speedup is the first
      number's job)."""
    from drand_tpu.crypto import batch as _batch
    saved_mode = _batch._MODE
    _batch.configure("host")
    try:
        return _bench_dkg_ceremony(trials)
    finally:
        _batch.configure(saved_mode)


def _bench_dkg_ceremony(trials):
    import asyncio
    import random

    from drand_tpu.crypto import batch, ecies
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.crypto.fields import R
    from drand_tpu.crypto.poly import PriPoly, PubPoly
    from drand_tpu.key.keys import new_key_pair
    from drand_tpu.obs.flight import FLIGHT
    from drand_tpu.testing import dkg_scale

    n = int(os.environ.get("BENCH_DKG_N", "256"))
    t = int(os.environ.get("BENCH_DKG_T", "25"))
    seq_sample = min(n, int(os.environ.get("BENCH_DKG_SEQ_SAMPLE", "16")))

    # ---- A: real-crypto deal verification for ONE receiver, n dealers
    rnd = random.Random(20260807)
    me = new_key_pair("bench-recv.test:9000", seed=b"bench-dkg-recv")
    my_index = 3
    g = PointG1.generator()
    log(f"  building {n} dealer bundles (t={t}, real crypto)...")
    polys = [PriPoly([rnd.randrange(1, R) for _ in range(t)])
             for _ in range(n)]
    pubs = [p.commit() for p in polys]
    wires = [tuple(c.to_bytes() for c in pub.commits) for pub in pubs]
    cts = [ecies.encrypt(me.public.key,
                         p.eval(my_index).value.to_bytes(32, "big"))
           for p in polys]

    def verify_seq(idxs):
        out = []
        for i in idxs:
            pts = [PointG1.from_bytes(c, subgroup_check=True)
                   for c in wires[i]]
            ev = PubPoly(pts).eval(my_index).value
            s = int.from_bytes(ecies.decrypt(me.key, cts[i]), "big") % R
            out.append(g.mul(s) == ev)
        return out

    def verify_batched(idxs):
        parsed = batch.parse_commits([wires[i] for i in idxs])
        evs = batch.eval_commits([PubPoly(p) for p in parsed], my_index)
        vals = [int.from_bytes(ecies.decrypt(me.key, cts[i]), "big") % R
                for i in idxs]
        return batch.share_checks(list(zip(vals, evs)))

    # correctness gate before timing: verdicts bit-identical on a good
    # sample AND on a corrupted dealer (bad share → False on both sides)
    sample = list(range(seq_sample))
    good_ct = cts[1]
    cts[1] = ecies.encrypt(me.public.key, (99).to_bytes(32, "big"))
    seq_v, bat_v = verify_seq(sample), verify_batched(sample)
    if seq_v != bat_v or bat_v[1] or not all(
            v for k, v in enumerate(bat_v) if k != 1):
        raise RuntimeError(f"verdict mismatch: seq={seq_v} batched={bat_v}")
    cts[1] = good_ct

    log(f"  sequential baseline over {seq_sample} dealers...")
    t0 = time.perf_counter()
    if not all(verify_seq(sample)):
        raise RuntimeError("sequential verify failed")
    dt_seq = (time.perf_counter() - t0) * n / seq_sample

    def timed_batched():
        t0 = time.perf_counter()
        if not all(verify_batched(range(n))):
            raise RuntimeError("batched verify failed")
        return time.perf_counter() - t0

    dt_bat = best_of(trials, timed_batched)
    speedup = dt_seq / dt_bat

    # ---- B: structural n-node ceremony + n→n reshare, per-phase timing
    log(f"  structural n={n} ceremony + reshare...")

    async def run_scale():
        pairs, nodes = dkg_scale.make_group(n, prefix="bench-scale")
        with dkg_scale.structural_dkg_crypto():
            FLIGHT.dkg.reset()
            t0 = time.perf_counter()
            res = await dkg_scale.run_ceremony(n, t, pairs=pairs,
                                               nodes=nodes)
            dt_c = time.perf_counter() - t0
            dkg_scale.check_structural_consistency(res, t)
            key = res[0].commits[0]
            tl_c = dkg_scale.phase_timeline(mode="dkg")
            FLIGHT.dkg.reset()
            t0 = time.perf_counter()
            res2 = await dkg_scale.run_reshare(res, pairs, nodes,
                                              t_old=t, t_new=t)
            dt_r = time.perf_counter() - t0
            dkg_scale.check_structural_consistency(res2, t,
                                                   expected_key=key)
            tl_r = dkg_scale.phase_timeline(mode="reshare")
            FLIGHT.dkg.reset()
        return dt_c, tl_c, dt_r, tl_r

    dt_cer, tl_cer, dt_res, tl_res = asyncio.run(run_scale())

    return {"metric": "dkg_deal_verify_batched_speedup",
            "value": round(speedup, 2), "unit": "x", "n": n, "t": t,
            "sequential_seconds": round(dt_seq, 2),
            "sequential_sampled_dealers": seq_sample,
            "batched_seconds": round(dt_bat, 3),
            "ceremony_seconds": round(dt_cer, 1),
            "ceremony_phase_seconds":
                {k: round(v, 2) for k, v in tl_cer.items()},
            "reshare_seconds": round(dt_res, 1),
            "reshare_phase_seconds":
                {k: round(v, 2) for k, v in tl_res.items()},
            "vs_baseline": None}


def bench_e2e(trials=1, n=5, t=3, rounds=4):
    """3-of-5 network end-to-end on the in-process harness (fake clock,
    real crypto/aggregation; demo/main.go:41-45 analogue). This config is
    a protocol-liveness measurement: live rounds are latency-bound (a
    handful of partials per round — the reference's host path is the
    right tool; the drand round PERIOD, not crypto, paces a real
    network), so it runs the host crypto path and a small round count;
    device throughput is what the other configs measure. The per-round
    cost is constant — the emitted value extrapolates to 100 rounds."""
    import asyncio

    from drand_tpu.chain.beacon import verify_beacon
    from drand_tpu.testing.harness import BeaconTestNetwork

    async def run():
        period = 2
        net = BeaconTestNetwork(n=n, t=t, period=period)
        try:
            await net.start_all()
            await net.advance_to_genesis()
            t0 = time.perf_counter()
            for r in range(1, rounds + 1):
                for i in range(n):
                    await net.wait_round(i, r)
                await net.clock.advance(period)
            dt = time.perf_counter() - t0
            pub = net.group.public_key.key()
            chain = list(net.nodes[0].store.cursor())
            assert chain[-1].round >= rounds
            for b in chain[1:][:4]:
                assert verify_beacon(pub, b)
            return dt
        finally:
            net.stop_all()

    dt = asyncio.run(run())
    per100 = dt * 100 / rounds
    return {"metric": "e2e_3of5_100rounds_seconds", "value": round(per100, 2),
            "unit": "s", "rounds_measured": rounds,
            "rounds_per_sec": round(rounds / dt, 2), "vs_baseline": None}


def bench_verify_rlc(trials):
    """Host RLC batch verification vs the per-item loop over a 64-beacon
    span (crypto/batch_verify.py). Pure host crypto — runs and reports
    even when the TPU tunnel is down, so the BENCH_*.json trajectory
    captures the pairing-count win unconditionally. Hash-to-curve is
    prewarmed (the per-round memo makes it identical, amortized work on
    both paths; this metric isolates the verification strategy)."""
    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import batch_verify, bls
    from drand_tpu.crypto import pairing as hpairing

    span = 64
    sk, pub = bls.keygen(seed=b"bench-rlc")
    prev, beacons = b"\x42" * 32, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))  # warms the h2c memo too
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    def timed_item():
        t0 = time.perf_counter()
        for b in beacons:
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("per-item verification failed")
        return time.perf_counter() - t0

    def timed_rlc():
        t0 = time.perf_counter()
        if not batch_verify.verify_beacons_rlc(pub, beacons).all():
            raise RuntimeError("RLC verification failed")
        return time.perf_counter() - t0

    trials = min(trials, 2)
    c0 = hpairing.N_PRODUCT_CHECKS
    dt_rlc = best_of(trials, timed_rlc)
    checks_per_pass = (hpairing.N_PRODUCT_CHECKS - c0) // trials
    dt_item = best_of(trials, timed_item)
    return {"metric": "verify_rlc_speedup",
            "value": round(dt_item / dt_rlc, 2), "unit": "x",
            "span": span, "per_item_seconds": round(dt_item, 3),
            "rlc_seconds": round(dt_rlc, 3),
            "product_checks_per_span": checks_per_pass,
            "vs_baseline": None}


def bench_client_catchup(trials):
    """Million-client catch-up (ISSUE 17): the VerifyingClient's strict
    walk over a 1M-round chain — adaptive RLC chunks + pipelined
    fetch/verify vs the per-chunk-64 per-round-fetch baseline walk.

    Host-only, runs FIRST (before backend init). The 1M-round machinery
    measurement uses the chaos structural-crypto stand-ins (real
    pairings would take hours on the 1-core box — the RLC *crypto*
    speedup is bench_verify_rlc's metric; this one isolates the walk
    machinery: chunking, pipelining, product-check economics). The
    corruption matrix and the checkpoint product-check accounting run on
    a real-crypto chain with N_PRODUCT_CHECKS deltas.

    The whole config pins the dispatch to host crypto: it runs before
    init_backend, and letting a stray verify_beacons kick the jax
    backend probe would stall a later dispatch behind a minute-scale
    cold compile on the bench box."""
    from drand_tpu.crypto import batch as _batch
    saved_mode = _batch._MODE
    _batch.configure("host")
    try:
        return _bench_client_catchup(trials)
    finally:
        _batch.configure(saved_mode)


def _bench_client_catchup(trials):
    import asyncio

    import numpy as np

    from drand_tpu.chain.beacon import Beacon, message, verify_beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.client import checkpoint as ckpt_mod
    from drand_tpu.client import verify as verify_mod
    from drand_tpu.client.interface import ClientError, result_from_beacon
    from drand_tpu.client.verify import VerifyingClient
    from drand_tpu.crypto import batch, batch_verify, bls
    from drand_tpu.crypto import pairing as hpairing
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.testing.chaos import group_sig, structural_crypto

    n_rounds = int(os.environ.get("BENCH_CATCHUP_ROUNDS", "1000000"))
    base_rounds = min(n_rounds, int(os.environ.get(
        "BENCH_CATCHUP_BASELINE", "131072")))
    SIG = 96
    genesis = b"\x42" * 32

    class SpanSource:
        """In-memory chain source: sigs packed in one buffer, beacons
        materialized per request. ``span``/``checkpoint`` toggle the
        optional bulk-fetch / checkpoint surfaces the client probes."""

        def __init__(self, sigs, n, info, checkpoint=None, span=True):
            self._sigs = sigs
            self._n = n
            self._info = info
            self._ckpt = checkpoint
            if not span:
                self.get_span = None
            if checkpoint is None:
                self.get_checkpoint = None

        def _sig(self, rn):
            return (genesis if rn == 0
                    else bytes(self._sigs[rn * SIG:(rn + 1) * SIG]))

        def _beacon(self, rn):
            return Beacon(round=rn, previous_sig=self._sig(rn - 1),
                          signature=self._sig(rn))

        async def info(self):
            return self._info

        async def get(self, rn=0):
            rn = rn or self._n
            return result_from_beacon(self._beacon(rn))

        async def get_span(self, lo, hi):
            # one bulk copy then fixed-stride slices: the fast path
            # should measure the walk, not per-round bytearray slicing
            raw = bytes(self._sigs[(lo - 1) * SIG:hi * SIG])
            cut = [raw[i:i + SIG] for i in range(0, len(raw), SIG)]
            if lo == 1:
                cut[0] = genesis
            return [Beacon(rn, cut[i], cut[i + 1])
                    for i, rn in enumerate(range(lo, hi))]

        async def get_checkpoint(self):
            return self._ckpt

    def build_chain(n):
        buf = bytearray(SIG * (n + 1))
        prev = genesis
        for r in range(1, n + 1):
            sig = group_sig(message(r, prev))
            buf[r * SIG:(r + 1) * SIG] = sig
            prev = sig
        return buf

    log(f"  building structural {n_rounds}-round chain...")
    t0 = time.perf_counter()
    sigs = build_chain(n_rounds)
    log(f"  chain built in {time.perf_counter() - t0:.1f}s")
    info = Info(public_key=PointG1.generator(), period=3, genesis_time=0,
                genesis_seed=genesis)

    checks = {"n": 0}
    record = {}
    with structural_crypto():
        # count product-CHECK EQUIVALENTS: one RLC product check per
        # verify_beacons call in the real path (bisection aside)
        orig_vb = batch.verify_beacons

        def counting_vb(pub, beacons, dst=b""):
            checks["n"] += 1
            return orig_vb(pub, beacons)

        batch.verify_beacons = counting_vb
        try:
            # --- the new walk: adaptive chunks + pipeline + get_span.
            # A fresh client each trial — the trust ring would otherwise
            # swallow every walk after the first (best_of: the 1-core
            # box's scheduling noise swings single runs ~1.5x)
            src = SpanSource(sigs, n_rounds, info)

            def timed_fast():
                checks["n"] = 0
                vc = VerifyingClient(src, strict_rounds=True,
                                     use_checkpoints=False)
                t0 = time.perf_counter()
                r = asyncio.run(vc.get(n_rounds))
                dt = time.perf_counter() - t0
                assert r.round == n_rounds and vc._trust[0] == n_rounds
                return dt

            dt_fast = best_of(max(2, trials), timed_fast)
            walk_checks = checks["n"]
            log(f"  1M walk: {dt_fast:.1f}s "
                f"({n_rounds / dt_fast:,.0f} rounds/s, "
                f"{walk_checks} product checks)")

            # --- baseline: the pre-ISSUE-17 walk inlined from the
            # seed client — sequential chunk-64 spans, per-round fetch
            # under the same 16-way concurrency, verify only after each
            # fetch completes (no pipelining, no get_span bulk fetch,
            # no adaptive chunk growth), measured on a subset
            src_b = SpanSource(sigs, base_rounds, info, span=False)

            async def baseline_walk(n):
                sem = asyncio.Semaphore(verify_mod.FETCH_CONCURRENCY)

                async def one(rn):
                    async with sem:
                        r = await src_b.get(rn)
                    if r.round != rn:
                        raise ClientError(
                            f"source returned round {r.round} for {rn}")
                    return Beacon(round=r.round,
                                  previous_sig=r.previous_signature,
                                  signature=r.signature,
                                  signature_v2=r.signature_v2)

                prev = genesis
                for lo in range(1, n + 1, 64):
                    hi = min(lo + 64, n + 1)
                    beacons = await asyncio.gather(
                        *(one(rn) for rn in range(lo, hi)))
                    for b in beacons:
                        if b.previous_sig != prev:
                            raise ClientError(
                                f"round {b.round}: broken chain")
                        prev = b.signature
                    oks = await asyncio.to_thread(
                        batch.verify_beacons, info.public_key,
                        list(beacons))
                    if not oks.all():
                        raise ClientError("corrupt history")

            def timed_base():
                t0 = time.perf_counter()
                asyncio.run(baseline_walk(base_rounds))
                return time.perf_counter() - t0

            dt_base = best_of(max(2, trials), timed_base)
            base_rate = base_rounds / dt_base
            speedup = (n_rounds / dt_fast) / base_rate
            log(f"  baseline walk: {base_rounds} rounds in {dt_base:.1f}s "
                f"({base_rate:,.0f} rounds/s) -> speedup {speedup:.1f}x")

            # --- checkpoint bootstrap on the 1M chain: O(1) product
            # checks vs the walk's O(chain / max_chunk)
            ckpt_round = n_rounds - 64
            ckpt_sig_round = bytes(
                sigs[ckpt_round * SIG:(ckpt_round + 1) * SIG])
            ckpt = ckpt_mod.Checkpoint(
                round=ckpt_round, signature=ckpt_sig_round,
                chain_hash=info.hash(),
                ckpt_sig=group_sig(ckpt_mod.checkpoint_message(
                    info.hash(), ckpt_round, ckpt_sig_round)))
            src_c = SpanSource(sigs, n_rounds, info, checkpoint=ckpt)
            vc_c = VerifyingClient(src_c, strict_rounds=True)
            checks["n"] = 0
            t0 = time.perf_counter()
            rc = asyncio.run(vc_c.get(n_rounds))
            dt_boot = time.perf_counter() - t0
            assert rc.round == n_rounds
            # +1: the checkpoint signature verification is itself one
            # product check in the real path (here a digest compare)
            boot_checks = checks["n"] + 1
            log(f"  checkpoint bootstrap: {dt_boot:.2f}s, "
                f"{boot_checks} product checks vs {walk_checks} "
                f"(x{walk_checks / boot_checks:.1f} fewer)")
        finally:
            batch.verify_beacons = orig_vb

    record.update({
        "metric": "client_catchup_1m_seconds",
        "value": round(dt_fast, 2), "unit": "s",
        "rounds": n_rounds,
        "rounds_per_sec": round(n_rounds / dt_fast),
        "under_60s": dt_fast < 60.0,
        "product_checks": walk_checks,
        "baseline_chunk64_rounds": base_rounds,
        "baseline_chunk64_seconds": round(dt_base, 2),
        "baseline_rounds_per_sec": round(base_rate),
        "speedup_vs_chunk64": round(speedup, 2),
        "checkpoint_product_checks": boot_checks,
        "checkpoint_vs_walk_checks": round(walk_checks / boot_checks, 1),
        "checkpoint_seconds": round(dt_boot, 2),
        "vs_baseline": None,
    })

    # --- real-crypto tier: corruption matrix + N_PRODUCT_CHECKS ------
    del sigs
    span = int(os.environ.get("BENCH_CATCHUP_REAL_SPAN", "160"))
    sk, pub = bls.keygen(seed=b"bench-client-catchup")
    info_r = Info(public_key=pub, period=3, genesis_time=0,
                  genesis_seed=genesis)
    log(f"  signing {span}-round real chain...")
    prev, real = genesis, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))
        real.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    class ListSource(SpanSource):
        def __init__(self, beacons, info, checkpoint=None):
            self._b = beacons
            self._n = len(beacons)
            self._info = info
            self._ckpt = checkpoint
            if checkpoint is None:
                self.get_checkpoint = None

        def _beacon(self, rn):
            return self._b[rn - 1]

        async def get_span(self, lo, hi):
            return self._b[lo - 1:hi - 1]

    # one corrupt beacon at head/middle/tail of the walk span: each must
    # be caught by the RLC bisection NAMING the exact round, with
    # verdicts bit-identical to the per-item loop
    matrix = []
    for pos, bad_round in (("head", 1), ("middle", span // 2),
                           ("tail", span - 1)):
        tampered = list(real)
        bad_sig = bytes(96)
        tampered[bad_round - 1] = Beacon(
            round=bad_round,
            previous_sig=tampered[bad_round - 1].previous_sig,
            signature=bad_sig)
        if bad_round < span:
            # keep the onward linkage consistent (a corrupt SOURCE would
            # serve a self-consistent forged chain): the fault must be
            # caught by the signature check, not the cheap linkage scan
            tampered[bad_round] = Beacon(
                round=bad_round + 1, previous_sig=bad_sig,
                signature=tampered[bad_round].signature)
        vc_r = VerifyingClient(ListSource(tampered, info_r),
                               strict_rounds=True, use_checkpoints=False)
        named = None
        try:
            asyncio.run(vc_r.get(span))
        except ClientError as e:
            named = e
        oks_rlc = batch_verify.verify_beacons_rlc(pub, tampered)
        oks_item = np.asarray([verify_beacon(pub, b) for b in tampered])
        matrix.append({
            "position": pos, "round": bad_round,
            "caught": named is not None
            and f"round {bad_round}:" in str(named),
            "bisection_matches_per_item":
                bool(np.array_equal(oks_rlc, oks_item)),
        })
        log(f"  corruption@{pos} (round {bad_round}): {named}")
    record["corruption_matrix"] = matrix

    # checkpoint bootstrap vs full walk, in REAL product checks
    # (crypto/pairing N_PRODUCT_CHECKS — every multi-pairing check
    # counts: RLC spans, per-item verifies, the checkpoint signature)
    ckpt_round = span - 16
    ckpt = ckpt_mod.Checkpoint(
        round=ckpt_round, signature=real[ckpt_round - 1].signature,
        chain_hash=info_r.hash(),
        ckpt_sig=bls.sign(sk, ckpt_mod.checkpoint_message(
            info_r.hash(), ckpt_round, real[ckpt_round - 1].signature)))
    c0 = hpairing.N_PRODUCT_CHECKS
    vc_ck = VerifyingClient(ListSource(real, info_r, checkpoint=ckpt),
                            strict_rounds=True)
    assert asyncio.run(vc_ck.get(span)).round == span
    boot_real = hpairing.N_PRODUCT_CHECKS - c0
    c0 = hpairing.N_PRODUCT_CHECKS
    vc_full = VerifyingClient(ListSource(real, info_r),
                              strict_rounds=True, use_checkpoints=False)
    assert asyncio.run(vc_full.get(span)).round == span
    full_real = hpairing.N_PRODUCT_CHECKS - c0
    log(f"  real checkpoint bootstrap: {boot_real} product checks vs "
        f"{full_real} for the {span}-round walk")
    record["real_span"] = span
    record["real_checkpoint_product_checks"] = boot_real
    record["real_walk_product_checks"] = full_real
    return record


def bench_obs_overhead(trials):
    """Observability overhead A/B around a host verify span (ISSUE 6):
    the same 32-beacon per-item verification loop run bare vs fully
    instrumented the way the syncer's hot path is — a round-activated
    trace context + one span per beacon + an engine_op_seconds
    observation per beacon (a deliberately DENSER instrumentation than
    production, which spans per chunk, so this bounds the real cost
    from above). Pure host crypto, runs before backend init — the
    "observability is cheap" claim stays provable with the tunnel
    down."""
    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.batch import _timed
    from drand_tpu.obs.trace import TRACER

    span = 32
    sk, pub = bls.keygen(seed=b"bench-obs")
    prev, beacons = b"\x51" * 32, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))  # warms the h2c memo too
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    def verify_all():
        for b in beacons:
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")

    def timed_bare():
        t0 = time.perf_counter()
        verify_all()
        return time.perf_counter() - t0

    def timed_instrumented():
        t0 = time.perf_counter()
        for b in beacons:
            with TRACER.activate(round_no=b.round, chain=b"bench-obs",
                                 retain=False), \
                    TRACER.span("sync_verify", chunk=1, peer="bench"), \
                    _timed("verify_beacons", "host", 1):
                if not chain_beacon.verify_beacon(pub, b):
                    raise RuntimeError("verification failed")
        return time.perf_counter() - t0

    trials = min(trials, 3)
    dt_bare = best_of(trials, timed_bare)
    dt_obs = best_of(trials, timed_instrumented)
    overhead_pct = (dt_obs - dt_bare) / dt_bare * 100.0
    return {"metric": "obs_overhead", "value": round(overhead_pct, 2),
            "unit": "%", "span": span,
            "bare_seconds": round(dt_bare, 4),
            "instrumented_seconds": round(dt_obs, 4),
            "spans_per_pass": span, "vs_baseline": None}


def bench_flight_overhead(trials):
    """Flight-recorder overhead A/B on a 64-round follow (ISSUE 10):
    the same 64-beacon verify-and-advance loop run bare vs with the
    flight recorder fed the way the live ingest path feeds it — t
    partial events + the quorum note + recover/store milestones per
    round (DENSER than a real follow, which records nothing for
    historical rounds — this bounds the live path's cost from above).
    Pure host crypto, runs before backend init; acceptance is ≤2%."""
    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import bls
    from drand_tpu.obs.flight import FlightRecorder

    span, t_of_n = 64, 3
    period, genesis = 10, 1_000_000
    sk, pub = bls.keygen(seed=b"bench-flight")
    prev, beacons = b"\x52" * 32, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))  # warms the h2c memo too
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    def verify_all():
        for b in beacons:
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")

    def timed_bare():
        t0 = time.perf_counter()
        verify_all()
        return time.perf_counter() - t0

    flight = FlightRecorder()

    def timed_instrumented():
        flight.reset()
        t0 = time.perf_counter()
        for b in beacons:
            boundary = genesis + (b.round - 1) * period
            for idx in range(t_of_n):
                flight.note_partial(
                    b.round, index=idx, source="grpc", verdict="valid",
                    now=boundary + 0.1 * idx, period=period,
                    genesis=genesis, n=t_of_n + 1, threshold=t_of_n)
            flight.note_quorum(b.round, have=t_of_n, threshold=t_of_n,
                               now=boundary + 0.3, period=period,
                               genesis=genesis)
            flight.note_milestone(b.round, "recover", now=boundary + 0.4,
                                  period=period, genesis=genesis)
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")
            flight.note_milestone(b.round, "store", now=boundary + 0.5,
                                  period=period, genesis=genesis)
        return time.perf_counter() - t0

    trials = min(trials, 3)
    dt_bare = best_of(trials, timed_bare)
    dt_flight = best_of(trials, timed_instrumented)
    overhead_pct = (dt_flight - dt_bare) / dt_bare * 100.0
    return {"metric": "flight_overhead", "value": round(overhead_pct, 2),
            "unit": "%", "span": span,
            "events_per_round": t_of_n + 3,
            "bare_seconds": round(dt_bare, 4),
            "instrumented_seconds": round(dt_flight, 4),
            "vs_baseline": None}


def bench_incident_overhead(trials):
    """Incident-engine overhead A/B on a 64-round follow (ISSUE 15):
    the flight_overhead loop with the SLI sampler + the full default
    detector rule set armed on top — one time-series sample (health +
    flight + metric-registry reads), spool append and an 8-rule
    evaluation per round, exactly what the store hook costs a live
    node. Pure host crypto, runs before backend init; acceptance is
    ≤2%."""
    import tempfile

    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import bls
    from drand_tpu.obs.flight import FlightRecorder
    from drand_tpu.obs.health import HealthState
    from drand_tpu.obs.incident import IncidentManager
    from drand_tpu.obs.timeseries import TimeSeriesRing

    span, t_of_n = 64, 3
    period, genesis = 10, 1_000_000
    sk, pub = bls.keygen(seed=b"bench-incident")
    prev, beacons = b"\x53" * 32, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))  # warms the h2c memo too
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    def timed_bare():
        t0 = time.perf_counter()
        for b in beacons:
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")
        return time.perf_counter() - t0

    flight = FlightRecorder()
    health = HealthState()
    health.note_dkg_complete()
    spool = os.path.join(tempfile.mkdtemp(prefix="drand-incident-bench-"),
                         "ts.ndjson")
    mgr = IncidentManager(flight=flight, health=health,
                          ring=TimeSeriesRing(spool_path=spool))

    def timed_armed():
        flight.reset()
        health.reset()
        health.note_dkg_complete()
        mgr.reset()  # clears the ring; the spool path stays armed
        t0 = time.perf_counter()
        for b in beacons:
            boundary = genesis + (b.round - 1) * period
            for idx in range(t_of_n):
                flight.note_partial(
                    b.round, index=idx, source="grpc", verdict="valid",
                    now=boundary + 0.1 * idx, period=period,
                    genesis=genesis, n=t_of_n + 1, threshold=t_of_n)
            flight.note_quorum(b.round, have=t_of_n, threshold=t_of_n,
                               now=boundary + 0.3, period=period,
                               genesis=genesis)
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")
            health.note_round_stored(b.round, 0.4, period)
            health.observe_chain(boundary + 0.4, period, genesis, b.round)
            mgr.on_round(b.round, now=boundary + 0.4, period=period)
        return time.perf_counter() - t0

    # trials INTERLEAVED bare/armed (not best_of per leg): the two legs
    # are ~3 s each on the 1-core box, where CPU contention drifts on
    # that scale — sequential legs read the drift as overhead. The
    # armed-leg overlay is ~40 ms; pairing keeps both legs under the
    # same drift regime.
    trials = max(2, min(trials, 3))
    dt_bare = dt_armed = float("inf")
    for _ in range(trials):
        dt_bare = min(dt_bare, timed_bare())
        dt_armed = min(dt_armed, timed_armed())
    minted = len(mgr.incidents())
    overhead_pct = (dt_armed - dt_bare) / dt_bare * 100.0
    return {"metric": "incident_overhead", "value": round(overhead_pct, 2),
            "unit": "%", "span": span, "rules_armed": len(mgr.rules),
            "samples_per_pass": span, "incidents_minted": minted,
            "bare_seconds": round(dt_bare, 4),
            "armed_seconds": round(dt_armed, 4),
            "vs_baseline": None}


def bench_remediation_overhead(trials):
    """Remediation-engine overhead A/B on a fault-free 64-round follow
    (ISSUE 16): the incident_overhead loop with the PlaybookEngine
    attached LIVE on top. On a healthy chain no rule fires, so the
    engine's cost is exactly the closed-loop hook — the manager's
    event hand-off check per sample — which is what a production node
    pays for having auto-remediation armed while nothing is wrong.
    Pure host crypto, runs before backend init; acceptance is ≤2%
    marginal over the incident-armed baseline."""
    import tempfile

    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import bls
    from drand_tpu.obs.flight import FlightRecorder
    from drand_tpu.obs.health import HealthState
    from drand_tpu.obs.incident import IncidentManager
    from drand_tpu.obs.remediate import PlaybookEngine
    from drand_tpu.obs.timeseries import TimeSeriesRing

    span, t_of_n = 64, 3
    period, genesis = 10, 1_000_000
    sk, pub = bls.keygen(seed=b"bench-remediate")
    prev, beacons = b"\x54" * 32, []
    for rnd in range(1, span + 1):
        sig = bls.sign(sk, message(rnd, prev))  # warms the h2c memo too
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig

    def make_manager():
        flight = FlightRecorder()
        health = HealthState()
        health.note_dkg_complete()
        spool = os.path.join(
            tempfile.mkdtemp(prefix="drand-remediate-bench-"),
            "ts.ndjson")
        mgr = IncidentManager(flight=flight, health=health,
                              ring=TimeSeriesRing(spool_path=spool))
        return flight, health, mgr

    flight_b, health_b, mgr_b = make_manager()          # incident-only
    flight_a, health_a, mgr_a = make_manager()          # + live engine
    engine = PlaybookEngine(dry_run=False)
    engine.attach(mgr_a)

    def timed(flight, health, mgr):
        flight.reset()
        health.reset()
        health.note_dkg_complete()
        mgr.reset()
        engine.reset()
        t0 = time.perf_counter()
        for b in beacons:
            boundary = genesis + (b.round - 1) * period
            for idx in range(t_of_n):
                flight.note_partial(
                    b.round, index=idx, source="grpc", verdict="valid",
                    now=boundary + 0.1 * idx, period=period,
                    genesis=genesis, n=t_of_n + 1, threshold=t_of_n)
            flight.note_quorum(b.round, have=t_of_n, threshold=t_of_n,
                               now=boundary + 0.3, period=period,
                               genesis=genesis)
            if not chain_beacon.verify_beacon(pub, b):
                raise RuntimeError("verification failed")
            health.note_round_stored(b.round, 0.4, period)
            health.observe_chain(boundary + 0.4, period, genesis, b.round)
            mgr.on_round(b.round, now=boundary + 0.4, period=period)
        return time.perf_counter() - t0

    # interleaved min-of pairs (the incident_overhead pattern): both
    # legs ride the same CPU-drift regime on the 1-core box
    trials = max(2, min(trials, 3))
    dt_bare = dt_armed = float("inf")
    for _ in range(trials):
        dt_bare = min(dt_bare, timed(flight_b, health_b, mgr_b))
        dt_armed = min(dt_armed, timed(flight_a, health_a, mgr_a))
    if len(mgr_a.incidents()) or len(engine.ledger(8)):
        raise RuntimeError("remediation overhead leg was not fault-free")
    overhead_pct = (dt_armed - dt_bare) / dt_bare * 100.0
    return {"metric": "remediation_overhead",
            "value": round(overhead_pct, 2), "unit": "%", "span": span,
            "playbooks_armed": len(engine.playbooks),
            "mode": "live",
            "bare_seconds": round(dt_bare, 4),
            "armed_seconds": round(dt_armed, 4),
            "vs_baseline": None}


def bench_chaos_soak(trials):
    """Chaos soak (ISSUE 11): a 32-node t=17 in-process beacon network
    on the FakeClock under a scripted fault schedule — healthy rounds,
    then a cross-link delay fault (the margin early-warning window),
    then a no-quorum partition (missed rounds), then heal. Reports the
    observability stack's DETECTION LEAD TIME (first quorum-margin
    warning -> first missed-round increment) and RECOVERY TIME (fault
    heal -> head lag back to 0), both read off the same SLI surfaces
    operators alert on. Structural crypto (testing/chaos.py): the
    verdict/timing plumbing is what is being measured, not pairings —
    pure host, runs FIRST before backend init, reports with the tunnel
    down."""
    import asyncio

    from drand_tpu.obs.state import isolated_observability
    from drand_tpu.testing.chaos import (ChaosBeaconNetwork, FaultEvent,
                                         LinkPolicy, detection_lead,
                                         recovery_seconds,
                                         structural_crypto)

    n = int(os.environ.get("BENCH_CHAOS_N", "32"))
    t = n // 2 + 1
    period = 4
    healthy, degraded, dead = 3, 3, 3
    fault_round = 2 + healthy          # first observed round is 2
    partition_round = fault_round + degraded
    heal_round = partition_round + dead
    rounds = heal_round + 6

    async def soak():
        net = ChaosBeaconNetwork(n=n, t=t, period=period)
        await net.start_all()
        await net.advance_to_genesis()
        half = list(range(n // 2))
        rest = list(range(n // 2, n))
        sched = [
            FaultEvent(fault_round, "link_all",
                       {"policy": LinkPolicy(delay_s=period * 0.6,
                                             jitter_s=period * 0.1)}),
            FaultEvent(partition_round, "partition",
                       {"groups": [half, rest]}),
            FaultEvent(heal_round, "heal"),
        ]
        try:
            return await net.run_schedule(sched, rounds=rounds)
        finally:
            net.stop_all()

    async def drop_soak(repair: bool):
        """The ISSUE-12 `repair` variant: the same 32-node schedule
        family, but the fault is a drop-the-push storm — EVERY partial
        push silently lost in flight for three rounds (receiver-side
        loss, exactly what the quorum-repair pull defeats: the pull
        path models a fresh connection and is not subject to the link
        policy). Run once with repair off (the pre-ISSUE-12 plane: the
        rounds miss) and once on (zero missed, recovery collapses)."""
        net = ChaosBeaconNetwork(n=n, t=t, period=period, repair=repair)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(fault_round, "link_all",
                       {"policy": LinkPolicy(drop=1.0)}),
            FaultEvent(heal_round, "heal"),
        ]
        try:
            return await net.run_schedule(sched, rounds=rounds)
        finally:
            net.stop_all()

    async def remediate_soak(live: bool):
        """The ISSUE-16 MTTR variant: a worker dies mid-soak and NO
        operator touches it. One leg with the remediation engine in
        dry-run (the pre-ISSUE-16 plane: the incident mints and
        annotates, the worker stays dead), one armed live (worker_down
        incident -> respawn_worker playbook -> supervised restart ->
        incident closes). MTTR = fault to the victim serving again,
        read off the same round observations; None = never recovered.
        Smaller net than the main soak — the comparison is the loop
        closure, not scale."""
        from drand_tpu.obs.incident import IncidentManager
        from drand_tpu.obs.remediate import (PlaybookEngine,
                                             attach_supervisor,
                                             worker_down_rule)
        from drand_tpu.utils.aio import spawn as aio_spawn
        from drand_tpu.utils.supervise import Supervisor

        rn = 8
        rt = rn // 2 + 1
        net = ChaosBeaconNetwork(n=rn, t=rt, period=period)
        await net.start_all()
        await net.advance_to_genesis()
        victim = rn - 1
        sup = Supervisor(clock=net.clocks[0], respawn_budget=3,
                         backoff_base_s=period / 4)
        sup.register(f"node-{victim}",
                     is_alive=lambda: victim not in net.crashed,
                     respawn=lambda: aio_spawn(net.restart(victim)))
        mgr = IncidentManager(flight=net.flights[0],
                              health=net.healths[0])
        mgr.rules.append(worker_down_rule(sup, cooldown_s=period))
        engine = PlaybookEngine(clock=net.clocks[0], dry_run=not live,
                                max_actions=8, window_s=16 * period)
        engine.attach(mgr)
        attach_supervisor(engine, sup)
        alive_round = [None]

        def on_round(r, now):
            mgr.on_round(r, now=now, period=period)
            if alive_round[0] is None and r > fault_round \
                    and victim not in net.crashed:
                alive_round[0] = r

        sched = [FaultEvent(fault_round, "crash", {"nodes": [victim]})]
        try:
            await net.run_schedule(sched, rounds=rounds,
                                   on_round=on_round)
        finally:
            net.stop_all()
        mttr = (None if alive_round[0] is None
                else (alive_round[0] - fault_round) * period)
        return mttr, mgr, engine, (victim in net.crashed)

    t0 = time.perf_counter()
    with structural_crypto(), isolated_observability():
        obs = asyncio.run(soak())
    lead = detection_lead(obs, period)
    rec = recovery_seconds(obs, heal_round, period)
    missed = max(ob.missed_total for ob in obs)
    if lead["lead_rounds"] is None or rec is None:
        raise RuntimeError(
            f"chaos soak inconclusive: lead={lead} recovery={rec}")
    log("chaos_soak: drop-the-push variant, repair off")
    with structural_crypto(), isolated_observability():
        obs_off = asyncio.run(drop_soak(repair=False))
    log("chaos_soak: drop-the-push variant, repair on")
    with structural_crypto(), isolated_observability():
        obs_on = asyncio.run(drop_soak(repair=True))
    log("chaos_soak: worker-death MTTR, remediation off (dry-run)")
    with structural_crypto(), isolated_observability():
        mttr_off, _mgr_off, eng_off, dead_off = asyncio.run(
            remediate_soak(live=False))
    log("chaos_soak: worker-death MTTR, remediation on (live)")
    with structural_crypto(), isolated_observability():
        mttr_on, mgr_on, eng_on, dead_on = asyncio.run(
            remediate_soak(live=True))
    wall = time.perf_counter() - t0
    missed_off = max(ob.missed_total for ob in obs_off)
    missed_on = max(ob.missed_total for ob in obs_on)
    rec_off = recovery_seconds(obs_off, heal_round, period)
    rec_on = recovery_seconds(obs_on, heal_round, period)
    if missed_off == 0:
        raise RuntimeError("repair variant inconclusive: the drop "
                           "schedule missed nothing even without repair")
    # the repair-on leg is the CLAIM, not a bystander: a quorum-repair
    # regression must fail the bench, not quietly skew a JSON field
    if missed_on:
        raise RuntimeError(
            f"repair variant regressed: {missed_on} rounds missed "
            f"WITH repair enabled (without: {missed_off})")
    if rec_on is None or (rec_off is not None and rec_on >= rec_off):
        raise RuntimeError(
            f"repair variant regressed: recovery {rec_on}s with repair "
            f"vs {rec_off}s without")
    # the remediation-off leg must leave the worker dead (dry-run only
    # ANNOTATES) or the A/B proves nothing about the closed loop
    if not dead_off or mttr_off is not None:
        raise RuntimeError(
            "remediation variant inconclusive: the worker came back "
            f"without the engine armed (mttr={mttr_off})")
    dry_entries = [e for e in eng_off.ledger(16)
                   if e["playbook"] == "respawn_worker"]
    if not dry_entries or any(e["outcome"] != "dry_run"
                              for e in dry_entries):
        raise RuntimeError(
            "remediation variant inconclusive: dry-run leg did not "
            f"annotate the respawn playbook (ledger={dry_entries})")
    # the live leg is the CLAIM: worker_down incident -> respawn_worker
    # -> supervised restart -> incident closes, strictly better MTTR
    live_ok = [e for e in eng_on.ledger(16)
               if e["playbook"] == "respawn_worker"
               and e["outcome"] == "ok"]
    closed = [inc for inc in mgr_on.incidents(16)
              if inc["rule"] == "worker_down"
              and inc["state"] == "closed"]
    if dead_on or mttr_on is None or not live_ok or not closed:
        raise RuntimeError(
            f"remediation variant regressed: mttr={mttr_on} "
            f"dead={dead_on} ledger_ok={len(live_ok)} "
            f"closed={len(closed)}")
    return {"metric": "chaos_soak_detection_lead",
            "value": float(lead["lead_seconds"]), "unit": "s",
            "nodes": n, "threshold": t, "period_s": period,
            "rounds": rounds,
            "lead_rounds": lead["lead_rounds"],
            "warn_round": lead["warn_round"],
            "missed_round": lead["missed_round"],
            "missed_rounds_total": missed,
            "recovery_seconds": rec,
            "repair": {
                "schedule": "drop_the_push",
                "missed_without_repair": missed_off,
                "missed_with_repair": missed_on,
                "recovery_seconds_without_repair": rec_off,
                "recovery_seconds_with_repair": rec_on,
            },
            "remediation": {
                "schedule": "worker_death",
                "mttr_seconds_without": mttr_off,
                "mttr_seconds_with": mttr_on,
                "incident_mttr_seconds": round(
                    closed[0]["closed_at"] - closed[0]["opened_at"], 3),
                "respawns_ok": len(live_ok),
            },
            "wall_seconds": round(wall, 1),
            "vs_baseline": None}


def bench_msm_pippenger(trials):
    """Host MSM strategy A/B on a 64-point G2 span with 128-bit RLC
    scalars: the ψ-endomorphism-split Pippenger (crypto/batch_verify.msm
    — what the RLC combine actually runs) vs the original interleaved
    4-bit-window ladder (msm_window, the reference). Pure host crypto,
    runs before backend init — the MSM win is reportable with the
    tunnel down, independent of any driver."""
    import secrets

    from drand_tpu.crypto import batch_verify
    from drand_tpu.crypto.curves import PointG2

    span = 64
    g2 = PointG2.generator()
    points = [g2.mul(3 + 2 * i) for i in range(span)]
    scalars = [secrets.randbits(batch_verify.RLC_SCALAR_BITS) | 1
               for _ in range(span)]
    expect = batch_verify.msm_window(points, scalars)
    if batch_verify.msm(points, scalars) != expect:
        raise RuntimeError("pippenger MSM disagrees with the window MSM")

    def timed(fn):
        def run():
            t0 = time.perf_counter()
            fn(points, scalars)
            return time.perf_counter() - t0
        return run

    trials = min(trials, 3)
    dt_pip = best_of(trials, timed(batch_verify.msm))
    dt_win = best_of(trials, timed(batch_verify.msm_window))
    return {"metric": "msm_pippenger_speedup",
            "value": round(dt_win / dt_pip, 2), "unit": "x",
            "span": span, "scalar_bits": batch_verify.RLC_SCALAR_BITS,
            "endo_split_bits": batch_verify._ENDO_Q_BITS,
            "window_seconds": round(dt_win, 3),
            "pippenger_seconds": round(dt_pip, 3),
            "vs_baseline": None}


def bench_msm_glv4(trials):
    """Host MSM full-width strategy A/B on a 64-point G2 span with
    255-bit scalars: the ψ² 4-D GLS Pippenger (crypto/batch_verify.msm
    — what recover's Lagrange combine and any wide-scalar RLC span now
    run) vs the interleaved 4-bit-window ladder at 255 bits
    (msm_window, the reference). Pure host crypto, runs before backend
    init — the GLV-4 win is reportable with the tunnel down, per the
    msm_pippenger_speedup pattern."""
    import secrets

    from drand_tpu.crypto import batch_verify, endo
    from drand_tpu.crypto.curves import PointG2

    span, nbits = 64, 255
    g2 = PointG2.generator()
    points = [g2.mul(3 + 2 * i) for i in range(span)]
    scalars = [secrets.randbits(nbits) | 1 for _ in range(span)]
    expect = batch_verify.msm_window(points, scalars, nbits=nbits)
    if batch_verify.msm(points, scalars) != expect:
        raise RuntimeError("GLV-4 MSM disagrees with the window MSM")

    def timed(fn):
        def run():
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0
        return run

    trials = min(trials, 3)
    dt_glv4 = best_of(trials, timed(
        lambda: batch_verify.msm(points, scalars)))
    dt_win = best_of(trials, timed(
        lambda: batch_verify.msm_window(points, scalars, nbits=nbits)))
    return {"metric": "msm_glv4_speedup",
            "value": round(dt_win / dt_glv4, 2), "unit": "x",
            "span": span, "scalar_bits": nbits,
            "digit_bits": endo.GLS4_DIGIT_BITS,
            "window_seconds": round(dt_win, 3),
            "glv4_seconds": round(dt_glv4, 3),
            "vs_baseline": None}


def bench_timelock_throughput(trials):
    """Timelock round-open A/B on a 64-ciphertext round: the
    shared-signature batch decryptor (crypto/timelock.decrypt_batch —
    what the vault's round-boundary open runs on host) vs a sequential
    ``timelock.decrypt`` loop (the per-item oracle a naive server would
    run). Pure host crypto, runs FIRST before backend init — the win is
    reportable with the tunnel down (the PR-5 msm_pippenger_speedup
    pattern). The batch tier decodes + canonical-folds the round
    signature once and precomputes the Miller line schedule; the
    sequential loop pays all of it per ciphertext."""
    from drand_tpu.chain.beacon import message_v2
    from drand_tpu.crypto import bls
    from drand_tpu.crypto import timelock as tl
    from drand_tpu.crypto.curves import PointG1

    span, round_no = 64, 1000
    sk, pub = bls.keygen(seed=b"bench-timelock")
    ident = message_v2(round_no)
    sig_bytes = bls.sign(sk, ident)
    cts = [tl.encrypt(pub, ident, b"sealed-bid-%03d" % i)
           for i in range(span)]
    # warm the comb table + caches outside the timed regions, and pin
    # correctness: every batch outcome must equal the oracle's
    ref = tl.decrypt(sig_bytes, cts[0])
    outs = tl.decrypt_batch(sig_bytes, cts)
    if not all(ok for ok, _, _ in outs) or outs[0][1] != ref:
        raise RuntimeError("batch decrypt disagrees with the oracle")

    def timed_seq():
        t0 = time.perf_counter()
        for ct in cts:
            tl.decrypt(sig_bytes, ct)
        return time.perf_counter() - t0

    def timed_batch():
        t0 = time.perf_counter()
        tl.decrypt_batch(sig_bytes, cts)
        return time.perf_counter() - t0

    trials = min(trials, 2)
    dt_seq = best_of(trials, timed_seq)
    dt_batch = best_of(trials, timed_batch)
    return {"metric": "timelock_throughput",
            "value": round(dt_seq / dt_batch, 2), "unit": "x",
            "span": span,
            "sequential_seconds": round(dt_seq, 3),
            "batch_seconds": round(dt_batch, 3),
            "batch_cts_per_sec": round(span / dt_batch, 1),
            "vs_baseline": None}


def bench_relay_fanout(trials):
    """Edge fan-out proof (ISSUE 14): a real PublicServer on the wall
    clock holds 10k+ concurrent /public/latest watchers through 10
    one-second rounds and reports (a) hub publishes per round — the
    per-worker wakeup count, which must be ~1 and NOT O(watchers) —
    (b) p99 boundary-to-delivery latency measured at the consumers,
    and (c) load-shed correctness on a capped sibling server (429 +
    Retry-After inside the round period, every shed counted). A slice
    of the watchers (BENCH_FANOUT_SOCKETS) are real TCP SSE streams;
    the rest subscribe at the hub layer (one process cannot hold 2 fds
    x 10k watchers under the 20k rlimit — the hub queue is the same
    code path either way, the sockets prove the framing/backpressure
    half at scale). Host-only, runs FIRST before backend init."""
    import asyncio

    import aiohttp

    from drand_tpu import metrics
    from drand_tpu.chain import time_math
    from drand_tpu.chain.info import Info
    from drand_tpu.client.interface import Client, ClientError, Result
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.http_server import fanout
    from drand_tpu.http_server.server import PublicServer

    watchers = int(os.environ.get("BENCH_FANOUT_WATCHERS", "10000"))
    sockets = min(int(os.environ.get("BENCH_FANOUT_SOCKETS", "1024")),
                  watchers)
    rounds = int(os.environ.get("BENCH_FANOUT_ROUNDS", "10"))
    period = 1
    genesis = int(time.time()) + 3
    boundary_perf: dict[int, float] = {}

    class Upstream(Client):
        def __init__(self):
            self.latest = None

        async def info(self):
            return Info(public_key=PointG1.generator(), period=period,
                        genesis_time=genesis, genesis_seed=b"f" * 32,
                        group_hash=b"f" * 32)

        async def get(self, round_no=0):
            if round_no == 0 and self.latest is not None:
                return self.latest
            raise ClientError("no beacon yet")

        async def watch(self):
            while True:
                now = time.time()
                next_r, next_t = time_math.next_round(int(now), period,
                                                      genesis)
                await asyncio.sleep(max(0.0, next_t - now))
                r = next_r - 1
                # anchor the round's SCHEDULED boundary on the perf
                # clock (subtract the sleep overshoot) so consumer-side
                # deltas measure boundary-to-delivery, not wake jitter
                boundary_perf[r] = (time.perf_counter()
                                    - (time.time() - next_t))
                self.latest = Result(round=r,
                                     signature=bytes([r % 251]) * 96)
                yield self.latest

    deliveries: list[float] = []  # boundary->consumer, all watchers

    async def run():
        upstream = Upstream()
        server = PublicServer(upstream, max_watchers=watchers + 64)
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/public/latest"
        stop = asyncio.Event()
        counts: list[int] = []

        async def hub_watcher():
            sub = server._hub.subscribe(fanout.PROTO_NDJSON)
            seen = 0
            try:
                while not stop.is_set():
                    item = await sub.next()
                    if item is None:
                        break
                    t = time.perf_counter()
                    r = item[0]
                    if r in boundary_perf:
                        deliveries.append(t - boundary_perf[r])
                    seen += 1
                    if seen >= rounds:
                        break
            finally:
                server._hub.unsubscribe(sub)
                counts.append(seen)

        async def sock_watcher(sess):
            seen = 0
            try:
                async with sess.get(
                        url, headers={"Accept": "text/event-stream"}
                ) as resp:
                    if resp.status != 200:
                        counts.append(-1)
                        return
                    rid = None
                    while seen < rounds and not stop.is_set():
                        line = await resp.content.readline()
                        if not line:
                            break
                        if line.startswith(b"id: "):
                            rid = int(line[4:])
                        elif line == b"\n" and rid is not None:
                            t = time.perf_counter()
                            if rid in boundary_perf:
                                deliveries.append(
                                    t - boundary_perf[rid])
                            seen += 1
                            rid = None
            except (aiohttp.ClientError, ConnectionError):
                pass
            finally:
                counts.append(seen)

        conn = aiohttp.TCPConnector(limit=0)
        sess = aiohttp.ClientSession(
            connector=conn, timeout=aiohttp.ClientTimeout(total=None))
        tasks = [asyncio.ensure_future(hub_watcher())
                 for _ in range(watchers - sockets)]
        # sockets come up in waves so the connect burst doesn't blow
        # the accept backlog
        for lo in range(0, sockets, 128):
            tasks += [asyncio.ensure_future(sock_watcher(sess))
                      for _ in range(lo, min(lo + 128, sockets))]
            await asyncio.sleep(0)
        pubs0 = server._hub.publishes
        wake0 = _counter_value(metrics.RELAY_WAKEUPS, proto="sse")
        deadline = genesis + (rounds + 3) * period
        held = server._hub.watcher_count()
        while time.time() < deadline and \
                sum(1 for t in tasks if t.done()) < len(tasks):
            held = max(held, server._hub.watcher_count())
            await asyncio.sleep(0.25)
        stop.set()
        server._hub.close_all()
        await asyncio.gather(*tasks, return_exceptions=True)
        pubs = server._hub.publishes - pubs0
        wakeups_sse = _counter_value(metrics.RELAY_WAKEUPS,
                                     proto="sse") - wake0

        # --- shed correctness on a capped sibling server
        shed_server = PublicServer(Upstream(), max_watchers=4)
        shed_site = await shed_server.start("127.0.0.1", 0)
        shed_port = shed_site._server.sockets[0].getsockname()[1]
        shed_url = f"http://127.0.0.1:{shed_port}/public/latest"
        shed0 = _counter_value(metrics.RELAY_SHED, reason="watcher_cap")
        headers = {"Accept": "text/event-stream"}
        heldresps = [await sess.get(shed_url, headers=headers)
                     for _ in range(4)]
        shed_ok = all(r.status == 200 for r in heldresps)
        for _ in range(5):
            r = await sess.get(shed_url, headers=headers)
            retry_after = int(r.headers.get("Retry-After", "0"))
            shed_ok = shed_ok and r.status == 429 \
                and 1 <= retry_after <= period
            r.close()
        sheds = _counter_value(metrics.RELAY_SHED,
                               reason="watcher_cap") - shed0
        shed_ok = shed_ok and sheds == 5
        for r in heldresps:
            r.close()
        await sess.close()
        await shed_server.stop()
        await server.stop()
        return held, counts, pubs, wakeups_sse, shed_ok, sheds

    held, counts, pubs, wakeups_sse, shed_ok, sheds = asyncio.run(run())
    complete = sum(1 for c in counts if c >= rounds - 1)
    if complete < (watchers * 95) // 100:
        raise RuntimeError(
            f"fanout inconclusive: only {complete}/{watchers} watchers "
            f"saw >= {rounds - 1} rounds")
    if not deliveries:
        raise RuntimeError("fanout measured no deliveries")
    deliveries.sort()
    p50 = deliveries[len(deliveries) // 2]
    p99 = deliveries[(len(deliveries) * 99) // 100]
    return {"metric": "relay_fanout",
            "value": round(pubs / max(1, rounds), 2),
            "unit": "wakeups_per_round",
            "watchers": watchers, "socket_watchers": sockets,
            "held_concurrently": held,
            "rounds": rounds, "period_s": period,
            "publishes": pubs,
            "sse_wakeups_per_round": round(
                wakeups_sse / max(1, pubs), 2),
            "deliveries": len(deliveries),
            "p50_boundary_to_delivery_s": round(p50, 4),
            "p99_boundary_to_delivery_s": round(p99, 4),
            "watchers_complete": complete,
            "shed_requests": sheds, "shed_ok": shed_ok,
            "vs_baseline": None}


def _counter_value(counter, **labels) -> float:
    return counter.labels(**labels)._value.get()


def bench_segment_store(trials):
    """Segment-vs-SQLite chain store read throughput at 1M-round depth
    (ISSUE 14): build the SAME synthetic chain in both backends, then
    measure `cursor_from` streaming from deep offsets (the catch-up /
    relay-archive serving pattern) and random `get` at depth. The
    segment store's fixed-width arithmetic addressing must be >= 2x the
    SQLite B-tree + hex-JSON path on the cursor walk. Host-only, runs
    FIRST before backend init; the chains live in a temp dir and are
    deleted afterwards (~1 GiB transient)."""
    import shutil
    import tempfile

    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.segments import SegmentStore
    from drand_tpu.chain.store import SQLiteStore

    depth = int(os.environ.get("BENCH_SEGSTORE_DEPTH", "1000000"))
    read_n = min(int(os.environ.get("BENCH_SEGSTORE_READ", "200000")),
                 depth)

    def synth(n):
        prev = b""
        for r in range(n):
            sig = bytes(((r + i) % 251 for i in range(4))) * 24
            yield Beacon(round=r, previous_sig=prev, signature=sig,
                         signature_v2=sig)
            prev = sig

    tmp = tempfile.mkdtemp(prefix="drand-segstore-bench-")
    try:
        seg = SegmentStore(os.path.join(tmp, "segments"))
        t0 = time.perf_counter()
        seg.put_many(synth(depth))
        build_seg = time.perf_counter() - t0
        sq = SQLiteStore(os.path.join(tmp, "chain.db"))
        t0 = time.perf_counter()
        sq.put_many(synth(depth))
        build_sq = time.perf_counter() - t0
        log(f"  built {depth} rounds: segment {build_seg:.1f}s, "
            f"sqlite {build_sq:.1f}s")

        def timed_cursor(store):
            def run():
                t0 = time.perf_counter()
                n = sum(1 for _ in store.cursor_from(depth - read_n))
                dt = time.perf_counter() - t0
                if n != read_n:
                    raise RuntimeError(f"cursor yielded {n} != {read_n}")
                return dt
            return run

        trials = max(1, min(trials, 2))
        dt_seg = best_of(trials, timed_cursor(seg))
        dt_sq = best_of(trials, timed_cursor(sq))

        import random as _random
        rng = _random.Random(7)
        sample = [rng.randrange(depth) for _ in range(2000)]

        def timed_gets(store):
            t0 = time.perf_counter()
            for r in sample:
                if store.get(r) is None:
                    raise RuntimeError(f"round {r} missing")
            return time.perf_counter() - t0

        get_seg = timed_gets(seg)
        get_sq = timed_gets(sq)
        seg.close()
        sq.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {"metric": "segment_store_speedup",
            "value": round(dt_sq / dt_seg, 2), "unit": "x",
            "depth_rounds": depth, "cursor_read_rounds": read_n,
            "segment_rounds_per_sec": round(read_n / dt_seg),
            "sqlite_rounds_per_sec": round(read_n / dt_sq),
            "segment_gets_per_sec": round(len(sample) / get_seg),
            "sqlite_gets_per_sec": round(len(sample) / get_sq),
            "build_seconds": {"segment": round(build_seg, 1),
                              "sqlite": round(build_sq, 1)},
            "vs_baseline": None}


def bench_vault_scale(trials, budget_left=None):
    """Host-pinned wrapper (the bench_client_catchup pattern): phases B
    and C dispatch real round opens through batch.decrypt_round_batch,
    and a stray device probe would stall the FIRST-group record behind
    a minute-scale cold compile — or hang with the tunnel down."""
    from drand_tpu.crypto import batch as _batch
    saved_mode = _batch._MODE
    _batch.configure("host")
    try:
        return _bench_vault_scale(trials, budget_left)
    finally:
        _batch.configure(saved_mode)


def _bench_vault_scale(trials, budget_left=None):
    """Planet-scale timelock serving (ISSUE 20), three host-only phases.

    A) BENCH_VAULT_ROWS (10M) pending ciphertexts built in BOTH vault
       backends, then submit/status/pending_count measured at depth:
       the segment backend's O(1) arithmetic seeks and counter-backed
       pending gauge against the SQLite B-tree probe + partial-index
       COUNT(*) scan. The >=3x gate on status/pending_count is the
       acceptance criterion; submit rides along.
    B) a K=BENCH_VAULT_OPEN_K (10k) boundary open on a fresh segment
       vault through the REAL TimelockService sweep. Correctness is
       meter-asserted: decrypt_many bumps pairing.N_PRODUCT_CHECKS
       exactly once per dispatch, so the sweep's delta must equal
       ceil(K/DRAND_TPU_TIMELOCK_OPEN_CHUNK) — one batched dispatch
       per chunk, no hidden re-splits. Submit p99 is measured DURING
       the sweep against idle p99 (the bounded-boundary-open claim),
       and sampled plaintexts must be bit-identical to the per-item
       tl.decrypt host oracle.
    C) crash-mid-sweep: the second dispatch raises, the round's first
       chunk stays committed, and a restarted service's catch-up sweep
       opens the remainder in ceil(remaining/chunk) dispatches without
       re-deciding committed rows (original decide timestamps survive
       — exactly-once).

    Encrypting 10k ciphertexts through the public path costs ~35 ms
    each on the 1-core box (a fresh 255-bit GT exponentiation per
    message), so fixture generation would dwarf the measured open. The
    bench precomputes a 4-bit fixed-base comb for the round's GT base
    and runs the SAME construction (sigma -> r -> U/V/W) ~6x faster;
    the comb is NOT trusted — sampled envelopes round-trip through the
    real tl.decrypt oracle, so a wrong table fails loudly instead of
    inflating the numbers.

    With neither BENCH_VAULT_* env set and under ~17 min of budget
    left, depth drops to 1M rows / K=600 / chunk=256 so the record
    still lands inside a default all-configs run; the official
    acceptance numbers come from a dedicated BENCH_CONFIGS=vault_scale
    run with the budget raised.
    """
    import asyncio
    import base64
    import hashlib
    import logging
    import math
    import random as _random
    import secrets
    import shutil
    import tempfile

    from drand_tpu.chain.beacon import message, message_v2
    from drand_tpu.chain.info import Info
    from drand_tpu.client import timelock as client_tl
    from drand_tpu.client.interface import Client, ClientError, Result
    from drand_tpu.crypto import batch as _batch
    from drand_tpu.crypto import bls
    from drand_tpu.crypto import pairing as _pairing
    from drand_tpu.crypto import timelock as tl
    from drand_tpu.timelock.segvault import SegmentVault
    from drand_tpu.timelock.service import TimelockService
    from drand_tpu.timelock.vault import TimelockVault
    from drand_tpu.utils.logging import KVLogger

    rows = int(os.environ.get("BENCH_VAULT_ROWS", "10000000"))
    open_k = int(os.environ.get("BENCH_VAULT_OPEN_K", "10000"))
    chunk = int(os.environ.get("DRAND_TPU_TIMELOCK_OPEN_CHUNK", "2048")
                or "2048")
    scaled = False
    if (budget_left is not None and budget_left < 1000.0
            and "BENCH_VAULT_ROWS" not in os.environ
            and "BENCH_VAULT_OPEN_K" not in os.environ):
        rows, open_k, chunk, scaled = 1_000_000, 600, 256, True
        log(f"  scaled by budget (left={budget_left:.0f}s): "
            f"rows=1M open_k=600 chunk=256")

    sk, pub = bls.keygen(seed=b"bench-vault-scale")
    info = Info(public_key=pub, period=3, genesis_time=1_700_000_000,
                genesis_seed=b"\x11" * 32)
    chain_hash = info.hash().hex()

    def _sig(rd):
        return bls.sign(sk, message_v2(rd))

    def _res(rd):
        return Result(round=rd,
                      signature=bls.sign(sk, message(rd, b"prev")),
                      signature_v2=_sig(rd))

    class _Chain(Client):
        def __init__(self, head):
            self.head = head

        async def get(self, round_no: int = 0) -> Result:
            rd = self.head if round_no == 0 else round_no
            if rd > self.head:
                raise ClientError(f"round {rd} not yet produced")
            return _res(rd)

        async def info(self) -> Info:
            return info

    def _comb(round_no):
        """4-bit fixed-base comb over the round's GT base: 64 windows
        x 15 precomputed multiples cover the 255-bit Fr exponent, so
        each message costs ~63 Fp12 multiplies instead of a fresh
        square-and-multiply pow."""
        base = tl._gt_base(pub, message_v2(round_no), tl.DEFAULT_DST_G2)
        table = []
        cur = base
        for _ in range(64):
            row = [None, cur]
            acc = cur
            for _ in range(14):
                acc = acc * cur
                row.append(acc)
            table.append(row)
            cur = acc * cur  # base^(16^(i+1))

        def enc(msg):
            sigma = secrets.token_bytes(tl.SIGMA_LEN)
            r = tl._h3(sigma, msg)
            u = tl._gen_mul(r)
            g = None
            e, i = r, 0
            while e:
                d = e & 15
                if d:
                    g = table[i][d] if g is None else g * table[i][d]
                e >>= 4
                i += 1
            v = tl._xor(sigma, tl._h_gt(g))
            w = tl._xor(msg, tl._h4(sigma, len(msg)))
            return {"v": client_tl.SCHEME_VERSION, "round": round_no,
                    "chain_hash": chain_hash, "U": u.to_bytes().hex(),
                    "V": base64.b64encode(v).decode(),
                    "W": base64.b64encode(w).decode()}
        return enc

    def _tok(i):
        return hashlib.blake2b(i.to_bytes(8, "big"),
                               digest_size=16).hexdigest()

    env_cache = {}

    def _synth(n):
        # one envelope blob per round is reused across its rows: the
        # stores key rows by token and treat the envelope as opaque,
        # so distinct blobs would only slow the build, not change the
        # read path being measured. The blob is CANONICAL-SHAPED
        # (96-hex U, b64 V/W of a 64-byte payload, chain_hash) — row
        # width is load-bearing for the status comparison: SQLite's
        # row read drags the envelope through the pager even with
        # with_envelope=False, the segment status path reads a fixed
        # 64-byte idx record and never touches envelope bytes
        for i in range(n):
            rd = 64 + (i & 63)
            s = env_cache.get(rd)
            if s is None:
                s = json.dumps(
                    {"v": 1, "round": rd, "chain_hash": "cd" * 32,
                     "U": "ab" * 48,
                     "V": base64.b64encode(b"s" * 32).decode(),
                     "W": base64.b64encode(b"w" * 64).decode()},
                    sort_keys=True)
                env_cache[rd] = s
            yield {"id": _tok(i), "round": rd, "envelope": s,
                   "status": "pending", "plaintext": None, "error": None,
                   "submitted": 1.7e9 + i * 1e-3, "opened": None}

    def _p99(lat):
        s = sorted(lat)
        return s[min(len(s) - 1, int(len(s) * 0.99))]

    # ------------------------------------------------- phase A: depth
    tmp_a = tempfile.mkdtemp(prefix="drand-vault-bench-a-")
    try:
        seg = SegmentVault(os.path.join(tmp_a, "segments"))
        t0 = time.perf_counter()
        seg.put_rows(_synth(rows), size_hint=rows)
        build_seg = time.perf_counter() - t0
        sq = TimelockVault(os.path.join(tmp_a, "timelock.db"))
        t0 = time.perf_counter()
        sq.put_rows(_synth(rows))
        build_sq = time.perf_counter() - t0
        log(f"  built {rows} pending rows: segment {build_seg:.1f}s, "
            f"sqlite {build_sq:.1f}s")

        rng = _random.Random(11)
        sample = [_tok(rng.randrange(rows)) for _ in range(2000)]

        def timed_status(v):
            def run():
                for t in sample[:50]:
                    v.get(t, False)  # warm
                t0 = time.perf_counter()
                for t in sample:
                    if v.get(t, False) is None:
                        raise RuntimeError(f"token {t} missing at depth")
                return (time.perf_counter() - t0) / len(sample)
            return run

        def timed_pending(v, expect):
            reps = 3 if isinstance(v, TimelockVault) else 500

            def run():
                if v.pending_count() != expect:
                    raise RuntimeError("pending_count drifted")
                t0 = time.perf_counter()
                for _ in range(reps):
                    v.pending_count()
                return (time.perf_counter() - t0) / reps
            return run

        submit_n = 256
        submit_env = {"v": 1, "round": 63, "U": "ab" * 48,
                      "V": "c2lnbWEtbWFzaw==", "W": "cGF5bG9hZA=="}

        def timed_submit(v, base):
            t0 = time.perf_counter()
            for i in range(base, base + submit_n):
                if not v.submit(_tok(i), 63, submit_env):
                    raise RuntimeError("duplicate token in submit timing")
            return (time.perf_counter() - t0) / submit_n

        passes = max(1, min(trials, 2))
        status_seg = best_of(passes, timed_status(seg))
        status_sq = best_of(passes, timed_status(sq))
        pend_seg = best_of(passes, timed_pending(seg, rows))
        pend_sq = best_of(passes, timed_pending(sq, rows))
        submit_seg = timed_submit(seg, rows)
        submit_sq = timed_submit(sq, rows)
        seg.close()
        sq.close()
    finally:
        shutil.rmtree(tmp_a, ignore_errors=True)

    status_x = status_sq / status_seg
    pend_x = pend_sq / pend_seg
    submit_x = submit_sq / submit_seg
    log(f"  status {status_x:.1f}x  pending_count {pend_x:.1f}x  "
        f"submit {submit_x:.1f}x (segment over sqlite)")

    # -------------------------------------- phase B: chunked K-open
    open_round = 10
    fut_round = 1_000_000
    quiet = KVLogger("bench-vault", logging.CRITICAL)
    sig_v2 = _sig(open_round)

    enc_rd = _comb(open_round)
    msgs = [b"vault-scale-%08d" % i for i in range(open_k)]
    t0 = time.perf_counter()
    envs = [enc_rd(m) for m in msgs]
    enc_wall = time.perf_counter() - t0
    # the comb is not trusted: sampled envelopes must round-trip
    # through the real per-item oracle before anything is timed
    for i in (0, open_k // 2, open_k - 1):
        if tl.decrypt(sig_v2, client_tl.parse_envelope(envs[i])) != msgs[i]:
            raise RuntimeError("comb encryption diverged from tl.decrypt")
    log(f"  encrypted {open_k} cts in {enc_wall:.1f}s "
        f"({enc_wall / open_k * 1e3:.1f} ms/ct, comb)")

    idle_n = 250
    est_sweep = open_k * 0.040
    pace = max(0.05, est_sweep / 1200.0)
    pool_n = min(1500, int(est_sweep / pace) + 300)
    enc_fut = _comb(fut_round)
    fut_envs = [enc_fut(b"future-%08d" % i) for i in range(idle_n + pool_n)]

    async def _phase_b(vault_dir):
        vault = SegmentVault(vault_dir)
        chain = _Chain(open_round - 1)
        svc = TimelockService(vault, chain, logger=quiet)
        await svc.start()
        deadline = time.perf_counter() + 60
        while svc._head != open_round - 1:
            if time.perf_counter() > deadline:
                raise RuntimeError("catch-up sweep never set the head")
            await asyncio.sleep(0.01)
        tokens = []
        t0 = time.perf_counter()
        for env in envs:
            tokens.append((await svc.submit(dict(env)))["id"])
        submit_wall = time.perf_counter() - t0
        # idle p99: future-round submits with no sweep running
        idle_lat = []
        for env in fut_envs[:idle_n]:
            t1 = time.perf_counter()
            await svc.submit(dict(env))
            idle_lat.append(time.perf_counter() - t1)
        fresh_futures = idle_n
        checks0 = _pairing.N_PRODUCT_CHECKS
        chain.head = open_round
        t_open = time.perf_counter()
        svc.on_result(_res(open_round))
        # paced submits WHILE the sweep drains the round: the p99 of
        # these against idle p99 is the bounded-boundary-open claim
        sweep_lat = []
        pool = fut_envs[idle_n:]
        pi = 0
        stop = time.perf_counter() + max(600.0, est_sweep * 4)
        while True:
            pending = await asyncio.to_thread(vault.pending_count)
            if pending <= fresh_futures and not svc._tasks:
                break
            if time.perf_counter() > stop:
                raise RuntimeError(
                    f"open sweep did not finish (pending={pending})")
            if pi < len(pool):
                env = pool[pi]
                pi += 1
                t1 = time.perf_counter()
                await svc.submit(dict(env))
                lat = time.perf_counter() - t1
                if pending > fresh_futures:  # sweep still live
                    sweep_lat.append(lat)
                fresh_futures += 1
            await asyncio.sleep(pace)
        open_wall = time.perf_counter() - t_open
        checks = _pairing.N_PRODUCT_CHECKS - checks0
        expected = math.ceil(open_k / chunk)
        if checks != expected:
            raise RuntimeError(
                f"dispatch meter: {checks} product checks != "
                f"ceil({open_k}/{chunk}) = {expected}")
        if await asyncio.to_thread(vault.pending_count) != fresh_futures:
            raise RuntimeError("round did not fully drain")
        for i in rng.sample(range(open_k), min(64, open_k)):
            rec = await asyncio.to_thread(vault.get, tokens[i], False)
            if (rec is None or rec["status"] != "opened"
                    or rec["plaintext"] != msgs[i]):
                raise RuntimeError(
                    f"ciphertext {i} not opened bit-identical")
        await svc.close()
        return submit_wall, idle_lat, sweep_lat, open_wall, expected

    # -------------------------------------- phase C: crash-resume
    crash_chunk = 8
    crash_n = 24  # 3 chunks; the injected crash kills dispatch 2
    crash_msgs = [b"crash-%04d" % i for i in range(crash_n)]
    crash_envs = [enc_rd(m) for m in crash_msgs]

    async def _phase_c(vault_dir):
        chain = _Chain(open_round - 1)
        vault = SegmentVault(vault_dir)
        svc = TimelockService(vault, chain, logger=quiet)
        await svc.start()
        deadline = time.perf_counter() + 60
        while svc._head != open_round - 1:
            if time.perf_counter() > deadline:
                raise RuntimeError("crash-phase head never set")
            await asyncio.sleep(0.01)
        toks = []
        for env in crash_envs:
            toks.append((await svc.submit(dict(env)))["id"])
        real = _batch.decrypt_round_batch
        calls = {"n": 0}

        def crashing(sig, cts, ch):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("bench-injected crash")
            return real(sig, cts, ch)

        checks0 = _pairing.N_PRODUCT_CHECKS
        _batch.decrypt_round_batch = crashing
        try:
            chain.head = open_round
            svc.on_result(_res(open_round))
            stop = time.perf_counter() + 120
            while svc._tasks:
                if time.perf_counter() > stop:
                    raise RuntimeError("crashed sweep never settled")
                await asyncio.sleep(0.02)
        finally:
            _batch.decrypt_round_batch = real
        first_checks = _pairing.N_PRODUCT_CHECKS - checks0
        first_opened = {}
        for t in toks:
            rec = await asyncio.to_thread(vault.get, t, False)
            if rec["status"] == "opened":
                first_opened[t] = rec["opened"]
        pending = await asyncio.to_thread(vault.pending_count)
        if (first_checks != 1 or len(first_opened) != crash_chunk
                or pending != crash_n - crash_chunk):
            raise RuntimeError(
                f"crash phase: checks={first_checks} "
                f"opened={len(first_opened)} pending={pending}")
        await svc.close()
        # restart over the same dir: the catch-up sweep resumes from
        # the last committed chunk
        vault2 = SegmentVault(vault_dir)
        svc2 = TimelockService(vault2, _Chain(open_round), logger=quiet)
        checks1 = _pairing.N_PRODUCT_CHECKS
        await svc2.start()
        stop = time.perf_counter() + 120
        while (await asyncio.to_thread(vault2.pending_count)
               or svc2._tasks):
            if time.perf_counter() > stop:
                raise RuntimeError("resume sweep never drained")
            await asyncio.sleep(0.02)
        resume_checks = _pairing.N_PRODUCT_CHECKS - checks1
        expected = math.ceil((crash_n - crash_chunk) / crash_chunk)
        if resume_checks != expected:
            raise RuntimeError(
                f"resume dispatches {resume_checks} != {expected}")
        for i, t in enumerate(toks):
            rec = await asyncio.to_thread(vault2.get, t, False)
            if rec["status"] != "opened" or rec["plaintext"] != crash_msgs[i]:
                raise RuntimeError("resume did not open bit-identical")
            if t in first_opened and rec["opened"] != first_opened[t]:
                raise RuntimeError(
                    "resume re-decided a committed row (not exactly-once)")
        await svc2.close()
        return resume_checks

    tmp_b = tempfile.mkdtemp(prefix="drand-vault-bench-b-")
    old_chunk_env = os.environ.get("DRAND_TPU_TIMELOCK_OPEN_CHUNK")
    old_si = sys.getswitchinterval()
    try:
        # a pure-Python decrypt thread only yields the GIL every
        # switchinterval; at the 5 ms default each of a submit's
        # ~10 GIL handoffs can stall that long, which would measure
        # the interpreter's scheduling quantum, not the chunked-open
        # design — tighten it for BOTH idle and sweep measurement
        sys.setswitchinterval(2e-5)
        os.environ["DRAND_TPU_TIMELOCK_OPEN_CHUNK"] = str(chunk)
        (submit_wall, idle_lat, sweep_lat, open_wall,
         dispatches) = asyncio.run(
            _phase_b(os.path.join(tmp_b, "segments")))
        os.environ["DRAND_TPU_TIMELOCK_OPEN_CHUNK"] = str(crash_chunk)
        resume_checks = asyncio.run(
            _phase_c(os.path.join(tmp_b, "crash-segments")))
    finally:
        sys.setswitchinterval(old_si)
        if old_chunk_env is None:
            os.environ.pop("DRAND_TPU_TIMELOCK_OPEN_CHUNK", None)
        else:
            os.environ["DRAND_TPU_TIMELOCK_OPEN_CHUNK"] = old_chunk_env
        shutil.rmtree(tmp_b, ignore_errors=True)

    p99_idle = _p99(idle_lat)
    p99_sweep = _p99(sweep_lat) if sweep_lat else float("nan")
    ratio = p99_sweep / p99_idle if p99_idle else float("nan")
    log(f"  open {open_k} in {open_wall:.1f}s over {dispatches} "
        f"dispatches; submit p99 idle {p99_idle * 1e3:.2f}ms / sweep "
        f"{p99_sweep * 1e3:.2f}ms ({len(sweep_lat)} samples)")
    return {"metric": "vault_scale_speedup",
            "value": round(min(status_x, pend_x), 2), "unit": "x",
            "rows": rows, "open_k": open_k, "open_chunk": chunk,
            "scaled_by_budget": scaled,
            "speedup": {"status": round(status_x, 2),
                        "pending_count": round(pend_x, 2),
                        "submit": round(submit_x, 2)},
            "segment_us": {"status": round(status_seg * 1e6, 2),
                           "pending_count": round(pend_seg * 1e6, 2),
                           "submit": round(submit_seg * 1e6, 2)},
            "sqlite_us": {"status": round(status_sq * 1e6, 2),
                          "pending_count": round(pend_sq * 1e6, 2),
                          "submit": round(submit_sq * 1e6, 2)},
            "build_seconds": {"segment": round(build_seg, 1),
                              "sqlite": round(build_sq, 1)},
            "open": {"dispatches": dispatches,
                     "wall_seconds": round(open_wall, 1),
                     "cts_per_sec": round(open_k / open_wall, 1),
                     "submit_seconds": round(submit_wall, 1),
                     "encrypt_seconds": round(enc_wall, 1)},
            "submit_p99_ms": {"idle": round(p99_idle * 1e3, 3),
                              "sweep": round(p99_sweep * 1e3, 3),
                              "ratio": round(ratio, 2),
                              "sweep_samples": len(sweep_lat)},
            "crash_resume": {"first_run_opened": crash_chunk,
                             "resume_dispatches": resume_checks,
                             "exactly_once": True},
            "vs_baseline": None}


def bench_sharded_catchup(budget_left):
    """Mesh-sharded wire-RLC catch-up on the virtual CPU mesh, driven
    through the driver's dryrun_multichip (per-shard device h2c +
    lane-MSM, ONE cross-shard reduction, 2 Miller pairs per span —
    meter-proven in the child). Runs in a JAX_PLATFORMS=cpu subprocess,
    so it reports without touching the (possibly down) TPU tunnel; the
    CPU-mesh rate proves the composition, not throughput."""
    import subprocess

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import __graft_entry__ as graft

    # child wall is compile-dominated (~5-10 min cold); cap it by the
    # remaining bench budget so this aux config can never starve the
    # headline, and skip the unrelated verify+recover dryrun leg
    timeout = max(120.0, min(float(os.environ.get(
        "DRAND_TPU_MULTICHIP_TIMEOUT", "1800")), budget_left))
    saved = {k: os.environ.get(k) for k in
             ("DRAND_TPU_MULTICHIP_TIMEOUT", "DRAND_TPU_DRYRUN_ONLY_CATCHUP")}
    os.environ["DRAND_TPU_MULTICHIP_TIMEOUT"] = str(timeout)
    os.environ["DRAND_TPU_DRYRUN_ONLY_CATCHUP"] = "1"
    try:
        out = graft._reexec_on_cpu_mesh(8, capture=True)
    except (RuntimeError, subprocess.SubprocessError) as e:
        raise RuntimeError(f"sharded catch-up dryrun failed: {e}") from e
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    for line in reversed(out.splitlines()):
        if line.startswith("SHARDED_CATCHUP "):
            record = json.loads(line[len("SHARDED_CATCHUP "):])
            return dict(record, vs_baseline=None)
    raise RuntimeError("dryrun produced no SHARDED_CATCHUP record")


def bench_replay_measured(budget_left, catchup_result=None):
    """1M-round replay, MEASURED (BASELINE config 5; the reference's
    de-facto capability of replaying a real chain —
    client/verify.go:146-163): stream rounds through the device
    wire-verification path (hash-to-curve + decompress + subgroup +
    pairing on device) and report the measured wall time.

    The stream cycles a content-varied pool of pre-packed wire buckets
    (engine.pack_wire_bucket), so the timed loop is the device path plus
    dispatch — host SHA message-expansion is paid once per pool and
    reported separately (it is per-message-parallel work a real deploy
    overlaps with device compute; on this 1-core host serializing it
    into the loop would measure the host, not the framework).

    BENCH_REPLAY_ROUNDS (default 1,000,000) requests the stream length;
    the actual length is clipped to the remaining bench budget using the
    measured catchup rate (never below 100k — the minimum for an honest
    at-scale claim). ``extrapolated`` is False only for a full 1M run."""
    import numpy as np
    import jax.numpy as jnp

    from drand_tpu.crypto import batch as cbatch
    from drand_tpu.ops.engine import WIRE_MAX_BUCKET

    eng = cbatch.engine()
    b = int(os.environ.get("BENCH_REPLAY_BUCKET", str(WIRE_MAX_BUCKET)))
    rounds_req = int(os.environ.get("BENCH_REPLAY_ROUNDS", "1000000"))
    pool = int(os.environ.get("BENCH_REPLAY_POOL", str(2 * b)))
    sk = 0x1F3A
    t0 = time.perf_counter()
    _, _, _, raw = _mk_pool(sk, pool=pool)
    from drand_tpu.crypto.curves import PointG1

    pub = PointG1.generator().mul(sk)
    buckets = [eng.pack_wire_bucket(pub, raw[s:s + b], b)
               for s in range(0, pool, b)]
    pack_s = time.perf_counter() - t0
    log(f"replay: packed {pool}-round pool into {len(buckets)} buckets "
        f"in {pack_s:.1f}s (host SHA expansion)")

    # self-check: every pool bucket verifies all-True; a corrupted copy
    # (sig of message 1 under message 0) flags exactly row 0
    for pk in buckets:
        ok, valid, n = eng.dispatch_wire_packed(pk)
        got = (np.asarray(ok) & valid)[:n]
        if not got.all():
            raise RuntimeError("replay pool failed self-check")
    m0, _ = raw[0]
    _, s1 = raw[1]
    bad = eng.pack_wire_bucket(pub, [(m0, s1)] + raw[1:b], b)
    ok, valid, n = eng.dispatch_wire_packed(bad)
    got = (np.asarray(ok) & valid)[:n]
    if got[0] or not got[1:].all():
        raise RuntimeError("replay negative self-check failed")

    # clip the stream to the remaining budget via the measured rate
    rate_est = (catchup_result or {}).get("rounds_per_sec") or 1000.0
    max_affordable = int(rate_est * max(0.0, budget_left) * 0.7)
    # floor: 100k is the minimum for an honest at-scale claim — unless
    # the caller explicitly asked for less (CPU smoke tests)
    rounds = max(min(100_000, rounds_req), min(rounds_req, max_affordable))
    n_chunks = (rounds + b - 1) // b
    rounds = n_chunks * b
    log(f"replay: streaming {rounds} rounds ({n_chunks} chunks of {b}; "
        f"budget_left={budget_left:.0f}s at ~{rate_est:.0f} r/s)")

    drain_every = 512
    bad_rounds = 0
    t0 = time.perf_counter()
    launches = []

    def drain():
        # a row passes iff (ok & valid) within [:n] — matching the
        # self-check above; a short final bucket's _PAD_SIG padding rows
        # beyond n are NOT failures (ADVICE r4)
        got = np.asarray(jnp.stack([d for d, _, _ in launches]))
        bad = 0
        for row, (_, valid, n) in zip(got, launches):
            bad += int((~(row & valid))[:n].sum())
        launches.clear()
        return bad

    for i in range(n_chunks):
        launches.append(eng.dispatch_wire_packed(buckets[i % len(buckets)]))
        if len(launches) >= drain_every:
            bad_rounds += drain()
    if launches:
        bad_rounds += drain()
    dt = time.perf_counter() - t0
    if bad_rounds:
        raise RuntimeError(f"replay: {bad_rounds} rounds failed "
                           f"verification mid-stream")
    rate = rounds / dt
    scaled = 1_000_000 / rate
    return {"metric": "replay_1m_rounds_seconds",
            "value": round(dt if rounds == 1_000_000 else scaled, 1),
            "unit": "s", "extrapolated": rounds < 1_000_000,
            "measured_rounds": rounds, "measured_seconds": round(dt, 1),
            "rounds_per_sec": round(rate, 1), "pool": pool,
            "pack_pool_seconds": round(pack_s, 1),
            "dual_sig_seconds": round(2 * scaled, 1),
            "vs_baseline": round(30.0 / scaled, 4)}


def bench_replay_1m(catchup_result, headline_result):
    """1M-round replay: extrapolated from the measured sustained rates —
    verification cost is content-independent, so the replay time is
    checks/rate. Dual-signature chains (V1+V2 per round) are 2e6 checks;
    both are reported, v1-only as the headline value."""
    if catchup_result:
        rate = catchup_result["rounds_per_sec"]  # single check per round
        basis = f"catchup_10k_rounds {catchup_result['path']} path"
    else:
        rate = headline_result["value"] / 2.0  # checks/s
        basis = "headline pairing rate"
    secs = 1_000_000 / rate
    return {"metric": "replay_1m_rounds_seconds", "value": round(secs, 1),
            "unit": "s", "extrapolated": True,
            "dual_sig_seconds": round(2 * secs, 1),
            "formula": f"1e6 checks / {rate:.1f} checks-per-sec "
                       f"(measured, {basis}); dual-signature chains are "
                       f"2e6 checks",
            "vs_baseline": round(30.0 / secs, 4)}


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from drand_tpu.utils.jit_cache import enable_persistent_cache

    enable_persistent_cache()

    trials = int(os.environ.get("BENCH_TRIALS", "2"))
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", "5.0"))
    # total wall budget: once exceeded, remaining aux configs are skipped
    # so the HEADLINE always runs and prints last (the driver parses the
    # final JSON line; an external kill mid-run must not leave an aux
    # config line as the "result")
    budget = float(os.environ.get("BENCH_BUDGET_SECONDS", "600"))
    t_start = time.perf_counter()
    which = os.environ.get(
        "BENCH_CONFIGS",
        "dkg_ceremony,client_catchup,msm,glv4,rlc,obs,flight,incident,"
        "remediate,chaos,timelock,fanout,segstore,vault_scale,shard,e2e,"
        "catchup,recover,deal,replay,headline").split(",")

    # --- outage-proofing (round-3 lesson: the official record must never
    # be an unparseable traceback). Two layers:
    # 1. backend init goes through the shared retry+watchdog helper — a
    #    down tunnel produces a structured final JSON line, not a hang or
    #    a raw RuntimeError (BENCH_r03 was rc=1 on exactly this).
    # 2. a global hard-deadline thread: if anything hangs mid-run (a sync
    #    on a dying tunnel blocks in C and is unkillable from Python's
    #    main thread), emit the best headline measured so far — or the
    #    structured error — and force-exit 0.
    # Every outage-path event is ALSO recorded as a structured
    # `diagnostics` entry in the final JSON record, so BENCH_*.json
    # distinguishes "tunnel down" from "regression" without stderr
    # archaeology.
    final_state = {"emitted": False, "headline": None}
    diagnostics = []

    def diag(event, **kw):
        diagnostics.append(dict({"event": event}, **kw))
        log(f"DIAG: {event} {kw}")

    def emit_final(reason=None):
        if final_state["emitted"]:
            return
        final_state["emitted"] = True
        if final_state["headline"] is not None:
            record = dict(final_state["headline"])
            if reason:
                record["note"] = reason
        else:
            record = {"metric": "pairings_per_sec", "value": None,
                      "unit": "pairings/s", "vs_baseline": None,
                      "error": reason or "unknown failure before headline"}
        if diagnostics:
            record["diagnostics"] = diagnostics
        emit(record)

    hard_deadline = float(os.environ.get("BENCH_HARD_DEADLINE_SECONDS",
                                         str(budget + 900)))
    import threading

    done_event = threading.Event()

    def _global_watchdog():
        if done_event.wait(hard_deadline):
            return
        log(f"WATCHDOG: bench exceeded hard deadline {hard_deadline:.0f}s "
            f"(tunnel hang mid-run?); emitting best-so-far and exiting")
        diag("watchdog_fired", deadline_s=hard_deadline,
             elapsed_s=round(time.perf_counter() - t_start, 1))
        emit_final(f"hard deadline {hard_deadline:.0f}s exceeded mid-run")
        os._exit(0)

    threading.Thread(target=_global_watchdog, daemon=True,
                     name="bench-watchdog").start()

    # the host-only configs run FIRST, before backend init: their
    # records must land even when the tunnel is down (that is the point
    # of having host-measured aux metrics in the trajectory)
    if "dkg_ceremony" in which:
        log("== large-group DKG: batched deal verify n=256 + structural "
            "ceremony/reshare per-phase timing (host-only) ==")
        try:
            emit(bench_dkg_ceremony(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="dkg_ceremony",
                 error=f"{type(e).__name__}: {e}")
    if "client_catchup" in which:
        log("== million-client catch-up: 1M-round strict walk, adaptive "
            "RLC chunks + pipeline + checkpoint bootstrap (host-only) ==")
        try:
            emit(bench_client_catchup(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="client_catchup",
                 error=f"{type(e).__name__}: {e}")
    if "msm" in which:
        log("== host MSM pippenger+endomorphism speedup (64-point G2) ==")
        try:
            emit(bench_msm_pippenger(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="msm",
                 error=f"{type(e).__name__}: {e}")
    if "glv4" in which:
        log("== host MSM GLS psi^2 4-D speedup (255-bit G2 scalars) ==")
        try:
            emit(bench_msm_glv4(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="glv4",
                 error=f"{type(e).__name__}: {e}")
    if "rlc" in which:
        log("== host RLC batch-verify speedup (64-beacon span) ==")
        try:
            emit(bench_verify_rlc(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="rlc",
                 error=f"{type(e).__name__}: {e}")
    if "obs" in which:
        log("== tracer+metrics overhead around a host verify span ==")
        try:
            emit(bench_obs_overhead(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="obs",
                 error=f"{type(e).__name__}: {e}")
    if "flight" in which:
        log("== flight-recorder overhead on a 64-round follow ==")
        try:
            emit(bench_flight_overhead(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="flight",
                 error=f"{type(e).__name__}: {e}")

    if "incident" in which:
        log("== incident-engine overhead on a 64-round follow ==")
        try:
            emit(bench_incident_overhead(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="incident",
                 error=f"{type(e).__name__}: {e}")

    if "remediate" in which:
        log("== remediation-engine overhead on a fault-free 64-round "
            "follow ==")
        try:
            emit(bench_remediation_overhead(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="remediate",
                 error=f"{type(e).__name__}: {e}")

    if "chaos" in which:
        log("== chaos soak: 32-node fault schedule, detection lead + "
            "recovery (host-only) ==")
        try:
            emit(bench_chaos_soak(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="chaos",
                 error=f"{type(e).__name__}: {e}")

    if "timelock" in which:
        log("== timelock shared-sig batch decrypt speedup (64-ct round) ==")
        try:
            emit(bench_timelock_throughput(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="timelock",
                 error=f"{type(e).__name__}: {e}")

    if "fanout" in which:
        log("== relay fan-out: 10k watchers x 10 rounds, wakeups + "
            "delivery p99 + shed correctness (host-only) ==")
        try:
            emit(bench_relay_fanout(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="fanout",
                 error=f"{type(e).__name__}: {e}")

    if "segstore" in which:
        log("== segment-vs-sqlite chain store reads at 1M-round depth "
            "(host-only) ==")
        try:
            emit(bench_segment_store(trials))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="segstore",
                 error=f"{type(e).__name__}: {e}")

    if "vault_scale" in which:
        left = budget - (time.perf_counter() - t_start)
        log(f"== planet-scale timelock vault: depth reads + chunked "
            f"K-open + crash resume (host-only, "
            f"budget_left={left:.0f}s) ==")
        try:
            emit(bench_vault_scale(trials, left))
        except Exception as e:  # noqa: BLE001 — best-effort aux config
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config="vault_scale",
                 error=f"{type(e).__name__}: {e}")

    if "shard" in which:
        # host-only like msm/rlc/obs (the work runs in a CPU-pinned
        # subprocess), but compile-heavy — bound by the remaining budget
        # so the cheap aux records and the headline are never starved
        left = budget - (time.perf_counter() - t_start)
        if left < 120.0:
            # bench_sharded_catchup floors its child watchdog at 120 s;
            # with less budget than that left, running it would overrun
            # the budget the floor exists to respect — skip instead
            log(f"== skipping shard: budget exhausted "
                f"(left={left:.0f}s < 120s) ==")
            diag("aux_config_skipped", config="shard",
                 error="budget exhausted")
        else:
            log(f"== sharded wire-RLC catch-up on the virtual CPU mesh "
                f"(budget_left={left:.0f}s) ==")
            try:
                emit(bench_sharded_catchup(left))
            except Exception as e:  # noqa: BLE001 — best-effort aux config
                import traceback

                log(traceback.format_exc())
                diag("aux_config_failed", config="shard",
                     error=f"{type(e).__name__}: {e}")

    from drand_tpu.utils.backend import BackendUnavailable, init_backend

    def _backend_failed(reason):
        diag("backend_unavailable", reason=reason)
        emit_final(reason)

    try:
        platform, devs = init_backend(
            deadline=float(os.environ.get("BENCH_BACKEND_DEADLINE", "180")),
            on_fail=_backend_failed, exit_code=0, log=log)
    except BackendUnavailable as e:
        # emit_final already ran via on_fail; exit 0 — an environmental
        # outage is a diagnosable record, not a bench bug
        log(f"FATAL(environment): {e}")
        return
    log(f"backend={platform} devices={devs} "
        f"configs={which} budget={budget}s")

    def have_time(section):
        left = budget - (time.perf_counter() - t_start)
        if left <= 0:
            log(f"== skipping {section}: budget exhausted ==")
            return False
        return True

    def section(name, fn):
        t0 = time.perf_counter()
        out = fn()
        log(f"== {name} done in {time.perf_counter() - t0:.0f}s "
            f"(elapsed {time.perf_counter() - t_start:.0f}s) ==")
        return out

    results = {}
    headline = None
    if "headline" in which:
        # headline runs FIRST: it warms the grid verify executables that
        # recover/deal reuse (the axon remote compiler re-processes each
        # kernel chain once per process, ~2 min per batch shape, and the
        # local persistent cache does not cover it) — but PRINTS last.
        log("== headline pairings/s ==")
        try:
            headline = section("headline", lambda: bench_headline(
                trials, min_seconds))
            final_state["headline"] = headline
        except BaseException as e:  # noqa: BLE001 — record, then best-effort aux
            import traceback

            log(traceback.format_exc())
            if isinstance(e, KeyboardInterrupt):
                emit_final("interrupted during headline")
                raise
            final_state["error"] = f"{type(e).__name__}: {e}"
            diag("headline_failed", error=final_state["error"])
            log(f"headline FAILED ({final_state['error']}); aux configs "
                f"will still run; final line will carry the error")


    def aux(name, fn):
        """Aux configs are best-effort: one failing must not kill the
        run or corrupt the final (headline) line."""
        try:
            results[name] = section(name, fn)
            if results[name]:
                emit(results[name])
        except Exception as e:  # noqa: BLE001
            import traceback

            log(traceback.format_exc())
            diag("aux_config_failed", config=name,
                 error=f"{type(e).__name__}: {e}")
            log(f"{name} FAILED ({type(e).__name__}: {e}) — continuing")

    # aux configs in decreasing information order; e2e (protocol
    # liveness, measured elsewhere by the test suite) goes last
    if "catchup" in which and have_time("catchup"):
        log("== catchup 10k rounds (wire path) ==")
        aux("catchup", lambda: bench_catchup(trials))
    if "replay" in which and have_time("replay"):
        log("== 1M-round replay (measured stream) ==")

        def run_replay():
            left = budget - (time.perf_counter() - t_start)
            try:
                return bench_replay_measured(left, results.get("catchup"))
            except Exception as e:  # noqa: BLE001 — formula fallback keeps
                # the config present in outage/degraded windows
                diag("replay_measured_fallback", error=repr(e))
                log(f"measured replay failed ({e!r}); formula fallback")
                if results.get("catchup") or headline:
                    return bench_replay_1m(results.get("catchup"), headline)
                raise
        aux("replay", run_replay)
    if "recover" in which and have_time("recover"):
        log("== 67-of-100 verify+recover ==")
        aux("recover", lambda: bench_recover(trials))
    if "deal" in which and have_time("deal"):
        log("== n=128 deal verify ==")
        aux("deal", lambda: bench_deal_verify(trials))
    if "e2e" in which and have_time("e2e"):
        log("== e2e 3-of-5 x 100 rounds ==")
        aux("e2e", bench_e2e)
    # The round-5 perf knobs (lazy reduction, pair fold) ship CPU-golden
    # when the tunnel is down at build time — the driver's bench may be
    # their FIRST real Mosaic compile. If the headline failed while a
    # knob is active (== "1", matching the consumers' gates), run ONE
    # headline-only child with the r4-proven conservative knobs, after
    # the parent's aux configs (so they are never lost), bounded by its
    # own subprocess timeout (so an external driver deadline cannot be
    # doubled). The child's record self-documents its knobs.
    if ("headline" in which and headline is None
            and not os.environ.get("BENCH_NO_FALLBACK")
            and (os.environ.get("DRAND_TPU_LAZY", "1") == "1"
                 or os.environ.get("DRAND_TPU_PAIRFOLD", "1") == "1")):
        log("headline failed with the r5 knobs active — one headline-only "
            "retry with DRAND_TPU_LAZY=0 DRAND_TPU_PAIRFOLD=0")
        diag("headline_knob_retry", lazy=0, pairfold=0)
        import subprocess

        env = dict(os.environ, BENCH_NO_FALLBACK="1",
                   BENCH_CONFIGS="headline",
                   DRAND_TPU_LAZY="0", DRAND_TPU_PAIRFOLD="0")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=budget + 300)
            sys.stderr.write(proc.stderr)
            child_out = proc.stdout.strip()
            if proc.returncode == 0 and child_out:
                # the child's final line becomes OUR final line — with
                # the parent's diagnostics merged in, so the record
                # still says WHY the retry happened (the r5-knob
                # headline failure must not read as a clean run)
                lines = child_out.splitlines()
                try:
                    record = json.loads(lines[-1])
                    record["diagnostics"] = (diagnostics
                                             + record.get("diagnostics", []))
                    lines[-1] = json.dumps(record)
                except ValueError:
                    pass  # unparseable child line: print verbatim
                print("\n".join(lines), flush=True)
                final_state["emitted"] = True
                done_event.set()
                return
            diag("knob_retry_failed", rc=proc.returncode)
            log(f"fallback bench rc={proc.returncode} — keeping the "
                f"parent's record")
        except subprocess.TimeoutExpired:
            diag("knob_retry_timeout", timeout_s=budget + 300)
            log("fallback bench timed out — keeping the parent's record")

    # LAST line is the headline (the driver parses the final JSON line),
    # or a structured error record if the headline was requested but
    # never materialized. When BENCH_CONFIGS excludes the headline, the
    # last aux result line stands — that run isn't an outage.
    if "headline" in which:
        emit_final(None if headline else final_state.get(
            "error", "headline config did not complete"))
    else:
        final_state["emitted"] = True  # disarm: aux-only run succeeded
    done_event.set()


if __name__ == "__main__":
    main()
