#!/usr/bin/env python
"""Headline benchmark: batched BLS12-381 pairing throughput on one chip.

Measures the device verification graph (ops/pairing.verify_prepared) that
backs the aggregator's recovered-signature checks and the chain-catchup
verifier — the reference's crypto hot path (chain/beacon/chain.go:136-141,
client/verify.go:146-163) executed as one multi-pairing batch.

Each verification is one BLS check e(-g1, sig) * e(pub, H(msg)) == 1,
i.e. TWO pairings (the reference computes two `Pairing` calls per verify).
Throughput counts pairings, matching BASELINE.md's north-star metric
(>= 200,000 pairings/sec on one TPU v5e chip).

Prints exactly ONE JSON line:
    {"metric": "pairings_per_sec", "value": N, "unit": "pairings/s",
     "vs_baseline": N / 200000}
Progress/diagnostics go to stderr. Environment knobs:
    BENCH_BATCH       comma-separated batch sizes to try, largest first
                      (default "128,16,8,4"). Sizes >= PALLAS_MIN_BUCKET
                      run the fused Mosaic kernel path
                      (ops/pallas_pairing.py); smaller ones run the XLA
                      graph (which the axon backend currently miscompiles
                      at batches >= ~16 — ops/engine.py DEFAULT_BUCKETS).
                      Every size is self-checked (positive AND negative)
                      against host truth; a failing size is skipped, the
                      largest CORRECT one wins.
    BENCH_MIN_SECONDS minimum timed window (default 5.0)
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from drand_tpu.utils.jit_cache import enable_persistent_cache

    enable_persistent_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2
    from drand_tpu.ops import limb, pairing

    batches = [int(b) for b in
               os.environ.get("BENCH_BATCH", "128,16,8,4").split(",")]
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", "5.0"))
    log(f"backend={jax.default_backend()} devices={jax.devices()} "
        f"batches={batches}")

    # Inputs: a small pool of real (pub, sig, H(msg)) triples tiled to the
    # batch — content doesn't affect timing (fixed-shape straight-line code),
    # but they must be valid curve points, and the check must return True.
    sk = 0x1F3A
    pub = PointG1.generator().mul(sk)
    pool = 8
    from drand_tpu.ops.engine import _g1_aff, _g2_aff

    pub_aff = _g1_aff(pub)
    t_prep = time.perf_counter()
    pool_sigs, pool_msgs = [], []
    for i in range(pool):
        msg = b"drand-tpu-bench-round-%d" % i
        pool_msgs.append(_g2_aff(hash_to_g2(msg)))
        pool_sigs.append(_g2_aff(
            PointG2.from_bytes(bls.sign(sk, msg), subgroup_check=False)))
    log(f"host prep: {time.perf_counter() - t_prep:.1f}s")
    verify_xla = jax.jit(pairing.verify_prepared)

    from drand_tpu.ops import pallas_pairing
    from drand_tpu.ops.engine import PALLAS_MIN_BUCKET

    rate = None
    for batch in batches:
        pubs = np.broadcast_to(pub_aff, (batch, 2, limb.NLIMBS))
        sigs = np.stack([pool_sigs[i % pool] for i in range(batch)])
        msgs = np.stack([pool_msgs[i % pool] for i in range(batch)])
        use_pallas = batch >= PALLAS_MIN_BUCKET
        if use_pallas:
            # engine-path: fused Mosaic kernels (ops/pallas_pairing.py).
            # Inputs are packed to the batch-last device layout ONCE —
            # the timed loop measures the jitted kernel chain, not
            # per-call host packing.
            def verify(x, y, qq):
                return pallas_pairing._verify_pl(x, y, qq, npairs=2,
                                                 b=batch)
            args = pallas_pairing.pack_verify_inputs(pubs, sigs, msgs)

            def repack(bad_s):
                return pallas_pairing.pack_verify_inputs(pubs, bad_s, msgs)
        else:
            verify = verify_xla
            args = (jnp.asarray(pubs), jnp.asarray(sigs), jnp.asarray(msgs))

            def repack(bad_s):
                return (args[0], jnp.asarray(bad_s), args[2])
        t0 = time.perf_counter()
        try:
            out = np.asarray(verify(*args))
        except Exception as e:  # noqa: BLE001 — probe the next size
            log(f"batch {batch} ({'pallas' if use_pallas else 'xla'}): "
                f"failed to compile/run: {e!r} — skipping")
            continue
        log(f"batch {batch} ({'pallas' if use_pallas else 'xla'}): "
            f"first call (compile+run) {time.perf_counter() - t0:.1f}s")
        if not out.all():
            log(f"batch {batch}: verification returned False on valid "
                f"inputs (known axon backend miscompile) — skipping")
            continue
        # negative self-check: a corrupted signature row must fail
        bad_sigs = sigs.copy()
        bad_sigs[0] = pool_sigs[(1) % pool]  # sig for a different message
        bad_out = np.asarray(verify(*repack(bad_sigs)))
        if bad_out[0] or not bad_out[1:].all():
            log(f"batch {batch}: negative self-check failed — skipping")
            continue
        calls = 0
        t0 = time.perf_counter()
        deadline = t0 + min_seconds
        while time.perf_counter() < deadline or calls < 3:
            np.asarray(verify(*args))
            calls += 1
        dt = time.perf_counter() - t0
        rate = 2 * batch * calls / dt
        log(f"{calls} calls x {batch} verifications in {dt:.2f}s "
            f"({dt / calls * 1e3:.0f} ms/call, {rate:.0f} pairings/s)")
        break
    if rate is None:
        log("FATAL: no batch size produced correct results")
        raise SystemExit(1)

    print(json.dumps({
        "metric": "pairings_per_sec",
        "value": round(rate, 1),
        "unit": "pairings/s",
        "vs_baseline": round(rate / 200000.0, 4),
    }))


if __name__ == "__main__":
    main()
