#!/usr/bin/env python
"""Headline benchmark: batched BLS12-381 pairing throughput on one chip.

Measures the device verification graph (ops/pairing.verify_prepared) that
backs the aggregator's recovered-signature checks and the chain-catchup
verifier — the reference's crypto hot path (chain/beacon/chain.go:136-141,
client/verify.go:146-163) executed as one multi-pairing batch.

Each verification is one BLS check e(-g1, sig) * e(pub, H(msg)) == 1,
i.e. TWO pairings (the reference computes two `Pairing` calls per verify).
Throughput counts pairings, matching BASELINE.md's north-star metric
(>= 200,000 pairings/sec on one TPU v5e chip).

Prints exactly ONE JSON line:
    {"metric": "pairings_per_sec", "value": N, "unit": "pairings/s",
     "vs_baseline": N / 200000}
Progress/diagnostics go to stderr. Environment knobs:
    BENCH_BATCH       comma-separated batch sizes to try, largest first
                      (default "64,16"); each batch's results are
                      self-checked against the host truth and a failing
                      batch size is skipped — the axon TPU backend
                      currently miscompiles the pairing graph at batches
                      >= ~64 (see ops/pairing.py docstring), so the
                      largest CORRECT batch wins
    BENCH_MIN_SECONDS minimum timed window (default 5.0)
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from drand_tpu.utils.jit_cache import enable_persistent_cache

    enable_persistent_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2
    from drand_tpu.ops import limb, pairing

    batches = [int(b) for b in
               os.environ.get("BENCH_BATCH", "64,16,8,4").split(",")]
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", "5.0"))
    log(f"backend={jax.default_backend()} devices={jax.devices()} "
        f"batches={batches}")

    # Inputs: a small pool of real (pub, sig, H(msg)) triples tiled to the
    # batch — content doesn't affect timing (fixed-shape straight-line code),
    # but they must be valid curve points, and the check must return True.
    sk = 0x1F3A
    pub = PointG1.generator().mul(sk)
    pool = 8
    from drand_tpu.ops.engine import _g1_aff, _g2_aff

    pub_aff = _g1_aff(pub)
    t_prep = time.perf_counter()
    pool_sigs, pool_msgs = [], []
    for i in range(pool):
        msg = b"drand-tpu-bench-round-%d" % i
        pool_msgs.append(_g2_aff(hash_to_g2(msg)))
        pool_sigs.append(_g2_aff(
            PointG2.from_bytes(bls.sign(sk, msg), subgroup_check=False)))
    log(f"host prep: {time.perf_counter() - t_prep:.1f}s")
    verify = jax.jit(pairing.verify_prepared)

    rate = None
    for batch in batches:
        pubs = np.broadcast_to(pub_aff, (batch, 2, limb.NLIMBS))
        sigs = np.stack([pool_sigs[i % pool] for i in range(batch)])
        msgs = np.stack([pool_msgs[i % pool] for i in range(batch)])
        pubs_d, sigs_d, msgs_d = (jnp.asarray(pubs), jnp.asarray(sigs),
                                  jnp.asarray(msgs))
        t0 = time.perf_counter()
        out = np.asarray(verify(pubs_d, sigs_d, msgs_d))
        log(f"batch {batch}: first call (compile+run) "
            f"{time.perf_counter() - t0:.1f}s")
        if not out.all():
            log(f"batch {batch}: verification returned False on valid "
                f"inputs (known axon large-batch miscompile) — skipping")
            continue
        calls = 0
        t0 = time.perf_counter()
        deadline = t0 + min_seconds
        while time.perf_counter() < deadline or calls < 3:
            verify(pubs_d, sigs_d, msgs_d).block_until_ready()
            calls += 1
        dt = time.perf_counter() - t0
        rate = 2 * batch * calls / dt
        log(f"{calls} calls x {batch} verifications in {dt:.2f}s "
            f"({dt / calls * 1e3:.0f} ms/call, {rate:.0f} pairings/s)")
        break
    if rate is None:
        log("FATAL: no batch size produced correct results")
        raise SystemExit(1)

    print(json.dumps({
        "metric": "pairings_per_sec",
        "value": round(rate, 1),
        "unit": "pairings/s",
        "vs_baseline": round(rate / 200000.0, 4),
    }))


if __name__ == "__main__":
    main()
