"""The suite facade — the capability boundary of the crypto engine.

Mirrors the reference's key/curve.go globals (the `Scheme` boundary that
BASELINE.json names as the swap point for the TPU engine):

    Pairing    -> drand_tpu.crypto.pairing
    KeyGroup   -> PointG1 (keys, 48B)
    SigGroup   -> PointG2 (signatures, 96B)
    Scheme     -> tbls module (threshold BLS on G2)
    AuthScheme -> bls module (plain BLS on G2)
    DKGAuthScheme -> schnorr module (Schnorr on G1)

Protocol code imports THIS module, never the primitives directly, so the
batched TPU engine (drand_tpu.ops) can be slotted behind the same calls.
"""

from __future__ import annotations

from . import bls as auth_scheme               # noqa: F401
from . import schnorr as dkg_auth_scheme       # noqa: F401
from . import tbls as scheme                   # noqa: F401
from . import ecies                            # noqa: F401
from . import timelock                         # noqa: F401
from .curves import PointG1 as KeyGroup        # noqa: F401
from .curves import PointG2 as SigGroup        # noqa: F401
from .hash_to_curve import DEFAULT_DST_G2      # noqa: F401
from .poly import (                            # noqa: F401
    PriPoly,
    PriShare,
    PubPoly,
    PubShare,
    lagrange_coefficients,
    minimum_threshold,
    recover_commit,
    recover_secret,
)
