"""Threshold BLS (t-of-n) on G2 — the core beacon scheme.

Replaces kyber tbls.NewThresholdSchemeOnG2 (reference key/curve.go:31) with
the exact API surface the reference consumes (SURVEY.md §2.2):
``sign_partial`` (chain/beacon/crypto.go:58), ``verify_partial``
(chain/beacon/node.go:112), ``index_of`` (chain/beacon/cache.go:42),
``recover`` (chain/beacon/chain.go:136), ``verify_recovered``
(chain/beacon/chain.go:141).

Wire format of a partial signature: 2-byte big-endian share index, then the
96-byte compressed G2 signature (kyber tbls.SigShare layout).

Batched verification/recovery across many partials/rounds is provided by the
TPU engine (drand_tpu.ops); this module is the exact-semantics host path.
"""

from __future__ import annotations

from .curves import PointG1, PointG2
from .hash_to_curve import DEFAULT_DST_G2, hash_to_g2
from .pairing import pairing_check
from .poly import PriShare, PubPoly, PubShare, recover_commit

INDEX_BYTES = 2
PARTIAL_SIG_SIZE = INDEX_BYTES + PointG2.COMPRESSED_SIZE  # 98
SIG_SIZE = PointG2.COMPRESSED_SIZE  # 96


class RecoveredSignatureInvalid(ValueError):
    """The Lagrange-recovered group signature failed its pairing check —
    security-significant (byzantine partials that individually verified,
    or state corruption), distinct from the routine not-enough-partials
    ValueError so callers can log it loudly."""


def sign_partial(share: PriShare, msg: bytes, dst: bytes = DEFAULT_DST_G2) -> bytes:
    """Partial signature: index-prefixed share-scalar * H(msg)."""
    sig = hash_to_g2(msg, dst).mul(share.value)
    return share.index.to_bytes(INDEX_BYTES, "big") + sig.to_bytes()


def index_of(partial: bytes) -> int:
    """Read the share index from a partial signature's prefix."""
    if len(partial) < INDEX_BYTES:
        raise ValueError("partial signature too short")
    return int.from_bytes(partial[:INDEX_BYTES], "big")


def verify_partial(
    pub_poly: PubPoly, msg: bytes, partial: bytes, dst: bytes = DEFAULT_DST_G2
) -> bool:
    """Check one partial against the signer's public key share
    pub_poly.eval(index). False on malformed input (ingress is untrusted)."""
    if len(partial) != PARTIAL_SIG_SIZE:
        return False
    idx = index_of(partial)
    try:
        sig = PointG2.from_bytes(partial[INDEX_BYTES:])
    except ValueError:
        return False
    if sig.is_infinity():
        return False
    pub_i = pub_poly.eval(idx).value
    return pairing_check([(-PointG1.generator(), sig), (pub_i, hash_to_g2(msg, dst))])


def recover(
    pub_poly: PubPoly,
    msg: bytes,
    partials: list[bytes],
    t: int,
    n: int,
    dst: bytes = DEFAULT_DST_G2,
) -> bytes:
    """Lagrange-recover the unique full BLS signature from >= t partials.

    Like kyber's tbls.Recover, partials are assumed pre-verified (the beacon
    aggregator verifies on ingress and re-verifies the recovered signature —
    chain/beacon/chain.go:136-141); invalid encodings are skipped.
    """
    shares: list[PubShare] = []
    seen: set[int] = set()
    for p in partials:
        if len(p) != PARTIAL_SIG_SIZE:
            continue
        idx = index_of(p)
        if idx in seen or idx >= n:
            continue
        try:
            pt = PointG2.from_bytes(p[INDEX_BYTES:])
        except ValueError:
            continue
        seen.add(idx)
        shares.append(PubShare(idx, pt))
        if len(shares) == t:
            break
    if len(shares) < t:
        raise ValueError(f"not enough valid partials: {len(shares)} < {t}")
    return recover_commit(shares, t).to_bytes()


def verify_recovered(
    pubkey: PointG1, msg: bytes, sig: bytes, dst: bytes = DEFAULT_DST_G2
) -> bool:
    """Verify a recovered (full) signature against the distributed public
    key — identical equation to plain BLS."""
    from . import bls

    return bls.verify(pubkey, msg, sig, dst)
