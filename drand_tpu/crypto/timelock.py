"""Timelock encryption over the beacon's unchained V2 signatures (IBE).

Reproduces the fork-specific capability demoed in
/root/reference/core/timelock_test.go:17-72 using kyber/encrypt/timelock:
encrypt a message to a FUTURE round; the round's V2 beacon signature (over
H(round) only — chain/beacon.go:110) is the IBE private key that decrypts it.

Boneh-Franklin style over the BLS12-381 pairing with drand's key layout
(master public key on G1, identity hashed to G2):

    encrypt(pub, round):  id = MessageV2(round); Q_id = H2(id) in G2
        sigma <- random 32B; r = H3(sigma || M) in Fr
        U = r * G1;  V = sigma XOR H_GT(e(pub, Q_id)^r);  W = M XOR H4(sigma)
    decrypt(sig_v2):      e(U, sig_v2) == e(pub, Q_id)^r  recovers sigma.

The Fujisaki-Okamoto re-encryption check (recompute r from sigma and test
U == r*G1) makes the scheme CCA-secure and rejects tampering.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

from .fields import R, Fp12, fr_from_bytes_wide
from .curves import PointG1, PointG2
from .hash_to_curve import hash_to_g2
from .pairing import pairing

SIGMA_LEN = 32


def _gt_to_bytes(e: Fp12) -> bytes:
    """Canonical GT serialization: the 12 Fp coefficients, c0-tower first,
    each 48-byte big-endian."""
    out = b""
    for six in (e.c0, e.c1):
        for two in (six.c0, six.c1, six.c2):
            out += two.c0.to_bytes(48, "big") + two.c1.to_bytes(48, "big")
    return out


def _h_gt(e: Fp12) -> bytes:
    return hashlib.sha256(b"IBE-H2" + _gt_to_bytes(e)).digest()


def _h3(sigma: bytes, msg: bytes) -> int:
    h = hashlib.sha256(b"IBE-H3" + sigma + msg).digest()
    h2 = hashlib.sha256(b"IBE-H3b" + sigma + msg).digest()
    v = fr_from_bytes_wide(h + h2)
    return v if v != 0 else 1

def _h4(sigma: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(b"IBE-H4" + ctr.to_bytes(2, "big") + sigma).digest()
        ctr += 1
    return out[:n]


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


@dataclass(frozen=True)
class Ciphertext:
    u: bytes  # 48B compressed G1 point
    v: bytes  # SIGMA_LEN bytes
    w: bytes  # len(message) bytes

    def to_bytes(self) -> bytes:
        return self.u + self.v + self.w

    @staticmethod
    def from_bytes(data: bytes) -> "Ciphertext":
        if len(data) < PointG1.COMPRESSED_SIZE + SIGMA_LEN:
            raise ValueError("ciphertext too short")
        off = PointG1.COMPRESSED_SIZE
        return Ciphertext(data[:off], data[off : off + SIGMA_LEN], data[off + SIGMA_LEN :])


def encrypt(pubkey: PointG1, identity: bytes, msg: bytes) -> Ciphertext:
    """Encrypt to the holder of the BLS signature over `identity` (for the
    beacon: identity = chain.MessageV2(round))."""
    q_id = hash_to_g2(identity)
    sigma = secrets.token_bytes(SIGMA_LEN)
    r = _h3(sigma, msg)
    u = PointG1.generator().mul(r)
    g_id_r = pairing(pubkey, q_id).pow(r)
    v = _xor(sigma, _h_gt(g_id_r))
    w = _xor(msg, _h4(sigma, len(msg)))
    return Ciphertext(u.to_bytes(), v, w)


def decrypt(signature: bytes | PointG2, ct: Ciphertext) -> bytes:
    """Decrypt with the round's full BLS signature (V2). Raises ValueError
    on tampering (FO re-encryption check)."""
    sig = signature if isinstance(signature, PointG2) else PointG2.from_bytes(signature)
    u = PointG1.from_bytes(ct.u)
    sigma = _xor(ct.v, _h_gt(pairing(u, sig)))
    msg = _xor(ct.w, _h4(sigma, len(ct.w)))
    r = _h3(sigma, msg)
    if PointG1.generator().mul(r) != u:
        raise ValueError("timelock decryption failed: invalid ciphertext or wrong round signature")
    return msg
