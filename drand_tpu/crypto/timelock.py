"""Timelock encryption over the beacon's unchained V2 signatures (IBE).

Reproduces the fork-specific capability demoed in
/root/reference/core/timelock_test.go:17-72 using kyber/encrypt/timelock:
encrypt a message to a FUTURE round; the round's V2 beacon signature (over
H(round) only — chain/beacon.go:110) is the IBE private key that decrypts it.

Boneh-Franklin style over the BLS12-381 pairing with drand's key layout
(master public key on G1, identity hashed to G2):

    encrypt(pub, round):  id = MessageV2(round); Q_id = H2(id) in G2
        sigma <- random 32B; r = H3(sigma || M) in Fr
        U = r * G1;  V = sigma XOR H_GT(e(pub, Q_id)^r);  W = M XOR H4(sigma)
    decrypt(sig_v2):      e(U, sig_v2) == e(pub, Q_id)^r  recovers sigma.

The Fujisaki-Okamoto re-encryption check (recompute r from sigma and test
U == r*G1) makes the scheme CCA-secure and rejects tampering.

Serving-tier batch decryption (the timelock vault's round-boundary open,
ISSUE 9): every ciphertext locked to one round shares the SAME G2 point —
the round's V2 signature — so the Miller loop's G2-side work (the line/T
trajectory, one Fp2 inversion per step) is identical across the whole
batch. :class:`RoundDecryptor` hoists it:

- decode + subgroup-check the signature ONCE per round, not per item;
- fold the canonical-GT cube correction into the shared point: the fast
  final exponentiation produces e(U, Q)^3 and the canonical value needs a
  255-bit GT exponentiation by 3^-1 mod r PER PAIRING — but by bilinearity
  e(U, Q) = e3(U, (3^-1 mod r) * Q), so ONE G2 scalar mul per round
  replaces the per-item GT pow (the dominant per-item cost);
- precompute the line (T, lambda) schedule from the folded point once; each
  item then pays only its own Fp12 accumulation + hard final exp.

The Fujisaki-Okamoto check stays exact per item (host ``r``-recompute,
the same ``r*G1 == U`` test :func:`decrypt` runs), so accept/reject is
bit-identical to the per-item oracle — the batch GT value EQUALS the
per-item ``pairing(U, sig)`` as a field element, hence byte-identical
hashes. ``decrypt_batch`` is the host tier of the
``crypto/batch.decrypt_round_batch`` dispatcher; the device tier
(ops/engine.py ``timelock_open``) rides the same shared-G2 structure with
the K varying U points on the batch axis.
"""

from __future__ import annotations

import hashlib
import secrets
import threading
from collections import OrderedDict
from dataclasses import dataclass

from .fields import R, Fp12, fr_from_bytes_wide
from .curves import PointG1, PointG2
from .hash_to_curve import DEFAULT_DST_G2, hash_to_g2
# _INV3_MOD_R: e(U, Q) == e3(U, _INV3_MOD_R * Q) where e3 is the fast
# final exponentiation's native (cubed) output — see the module docstring.
from .pairing import (_INV3_MOD_R, _MILLER_BITS, _line_value,
                      final_exponentiation, pairing)
from . import pairing as _pairing_mod

SIGMA_LEN = 32


def _gt_to_bytes(e: Fp12) -> bytes:
    """Canonical GT serialization: the 12 Fp coefficients, c0-tower first,
    each 48-byte big-endian."""
    out = b""
    for six in (e.c0, e.c1):
        for two in (six.c0, six.c1, six.c2):
            out += two.c0.to_bytes(48, "big") + two.c1.to_bytes(48, "big")
    return out


def _h_gt(e: Fp12) -> bytes:
    return hashlib.sha256(b"IBE-H2" + _gt_to_bytes(e)).digest()


def _h3(sigma: bytes, msg: bytes) -> int:
    h = hashlib.sha256(b"IBE-H3" + sigma + msg).digest()
    h2 = hashlib.sha256(b"IBE-H3b" + sigma + msg).digest()
    v = fr_from_bytes_wide(h + h2)
    return v if v != 0 else 1

def _h4(sigma: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(b"IBE-H4" + ctr.to_bytes(2, "big") + sigma).digest()
        ctr += 1
    return out[:n]


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# Fixed-base comb for generator scalar muls. Both hot sites multiply the
# G1 GENERATOR — encrypt's U = r*G1 and the FO re-encryption check. The
# comb itself now lives in crypto/curves (g1_comb_mul) so the DKG's
# batched g·s share checks share the same one-time table; these aliases
# keep the historical timelock-local names importable.
# ---------------------------------------------------------------------------

from .curves import _COMB_WINDOW  # noqa: E402,F401 — compat re-export
from .curves import _g1_comb_table as _comb_table  # noqa: E402,F401
from .curves import g1_comb_mul as _gen_mul  # noqa: E402


# ---------------------------------------------------------------------------
# Per-round GT base cache: every ciphertext locked to the same round
# recomputes pairing(pub, Q_round) — one full pairing per encrypt. The
# keyed counting LRU (the hash_to_g2 cache pattern) amortizes it to one
# pairing per (pubkey, round identity); hit/miss counts feed the
# timelock_gt_cache_requests{result} metric.
# ---------------------------------------------------------------------------

_GT_MAXSIZE = 256
_GT_CACHE: "OrderedDict[tuple[bytes, bytes, bytes], Fp12]" = OrderedDict()
_GT_LOCK = threading.Lock()
_gt_hits = 0
_gt_misses = 0


def gt_cache_info() -> dict:
    """Hit/miss/size counters of the GT base memo (process lifetime)."""
    return {"hits": _gt_hits, "misses": _gt_misses,
            "size": len(_GT_CACHE), "maxsize": _GT_MAXSIZE}


def gt_cache_clear() -> None:
    with _GT_LOCK:
        _GT_CACHE.clear()


def _gt_base(pubkey: PointG1, identity: bytes, dst: bytes) -> Fp12:
    """Memoized e(pub, H2(identity)) — the per-round encryption base."""
    global _gt_hits, _gt_misses
    from .. import metrics

    key = (pubkey.to_bytes(), identity, dst)
    with _GT_LOCK:
        got = _GT_CACHE.get(key)
        if got is not None:
            _GT_CACHE.move_to_end(key)
            _gt_hits += 1
    if got is not None:
        metrics.TIMELOCK_GT_CACHE_REQUESTS.labels(result="hit").inc()
        return got
    base = pairing(pubkey, hash_to_g2(identity, dst))
    with _GT_LOCK:
        _GT_CACHE[key] = base
        if len(_GT_CACHE) > _GT_MAXSIZE:
            _GT_CACHE.popitem(last=False)
        _gt_misses += 1
    metrics.TIMELOCK_GT_CACHE_REQUESTS.labels(result="miss").inc()
    return base


@dataclass(frozen=True)
class Ciphertext:
    u: bytes  # 48B compressed G1 point
    v: bytes  # SIGMA_LEN bytes
    w: bytes  # len(message) bytes

    def to_bytes(self) -> bytes:
        return self.u + self.v + self.w

    @staticmethod
    def from_bytes(data: bytes) -> "Ciphertext":
        if len(data) < PointG1.COMPRESSED_SIZE + SIGMA_LEN:
            raise ValueError("ciphertext too short")
        off = PointG1.COMPRESSED_SIZE
        return Ciphertext(data[:off], data[off : off + SIGMA_LEN], data[off + SIGMA_LEN :])


def encrypt(pubkey: PointG1, identity: bytes, msg: bytes,
            dst: bytes = DEFAULT_DST_G2) -> Ciphertext:
    """Encrypt to the holder of the BLS signature over `identity` (for the
    beacon: identity = chain.MessageV2(round))."""
    sigma = secrets.token_bytes(SIGMA_LEN)
    r = _h3(sigma, msg)
    u = _gen_mul(r)
    g_id_r = _gt_base(pubkey, identity, dst).pow(r)
    v = _xor(sigma, _h_gt(g_id_r))
    w = _xor(msg, _h4(sigma, len(msg)))
    return Ciphertext(u.to_bytes(), v, w)


def _finish(ct: Ciphertext, u: PointG1, gt: Fp12) -> bytes:
    """The per-item decryption tail from the pairing value: sigma/message
    unmasking + the exact Fujisaki-Okamoto re-encryption check. Shared by
    the per-item oracle, the host batch tier and the device tier, so
    accept/reject decisions come from ONE implementation."""
    sigma = _xor(ct.v, _h_gt(gt))
    msg = _xor(ct.w, _h4(sigma, len(ct.w)))
    r = _h3(sigma, msg)
    if _gen_mul(r) != u:
        raise ValueError("timelock decryption failed: invalid ciphertext or wrong round signature")
    return msg


def decrypt(signature: bytes | PointG2, ct: Ciphertext) -> bytes:
    """Decrypt with the round's full BLS signature (V2). Raises ValueError
    on tampering (FO re-encryption check)."""
    sig = signature if isinstance(signature, PointG2) else PointG2.from_bytes(signature)
    u = PointG1.from_bytes(ct.u)
    return _finish(ct, u, pairing(u, sig))


class RoundDecryptor:
    """Shared-signature IBE decryptor for one round (see module docstring).

    The G2-side Miller work — decode, subgroup check, the 3^-1 canonical
    fold, and the line (T, lambda) trajectory — is computed once in the
    constructor; :meth:`gt` then evaluates the precomputed lines at each
    item's U point. GT values are field-element-equal (hence
    byte-identical) to ``pairing(U, sig)``.
    """

    def __init__(self, signature: bytes | PointG2):
        sig = (signature if isinstance(signature, PointG2)
               else PointG2.from_bytes(signature))
        if sig.is_infinity():
            raise ValueError("signature is the point at infinity")
        self.sig = sig
        # canonical fold: e(U, sig) == e3(U, (3^-1 mod r) * sig)
        self.sig_folded = sig.mul(_INV3_MOD_R)
        # line schedule computed lazily: the device tier only evaluates
        # host lines when a lane false-rejects (ops/engine.timelock_open)
        self._lines = None

    @staticmethod
    def _precompute_lines(q: PointG2):
        """The affine Miller trajectory of crypto/pairing.miller_loop for
        a single fixed Q: per step the accumulator point T and the slope
        lambda, with the squaring schedule. Evaluating these at any G1
        point reproduces the reference Miller value bit-for-bit."""
        q_aff = q.to_affine()
        t = q_aff
        sched = []
        for bit in _MILLER_BITS:
            xt, yt = t
            lam2 = xt.square().mul_scalar(3) * (yt + yt).inverse()
            sched.append((True, t, lam2))  # squaring precedes this line
            x3 = lam2.square() - xt - xt
            y3 = lam2 * (xt - x3) - yt
            t = (x3, y3)
            if bit == "1":
                xt, yt = t
                xq, yq = q_aff
                lam2 = (yq - yt) * (xq - xt).inverse()
                sched.append((False, t, lam2))
                x3 = lam2.square() - xt - xq
                y3 = lam2 * (xt - x3) - yt
                t = (x3, y3)
        return sched

    def gt(self, u: PointG1) -> Fp12:
        """Canonical e(u, sig) via the precomputed lines (one Fp12
        accumulation + the hard final exponentiation; the cube correction
        is pre-folded into the shared point)."""
        if u.is_infinity():
            return Fp12.one()
        if self._lines is None:
            self._lines = self._precompute_lines(self.sig_folded)
        xa, ya = u.to_affine()
        p_aff = (xa.v, ya.v)
        f = Fp12.one()
        for squared, t, lam2 in self._lines:
            if squared:
                f = f.square()
            f = f * _line_value(t, lam2, p_aff)
        _pairing_mod.N_MILLER_PAIRS += 1
        return final_exponentiation(f.conjugate(), canonical=False)

    def decrypt(self, ct: Ciphertext) -> bytes:
        """Per-item decrypt with the shared precomputation — the same
        accept/reject behavior as :func:`decrypt` on this signature."""
        u = PointG1.from_bytes(ct.u)
        return _finish(ct, u, self.gt(u))

    def decrypt_many(self, cts, gts=None) -> list[tuple[bool, bytes, str]]:
        """Open a whole round: ``(ok, plaintext, error)`` per ciphertext,
        never raising — the vault stores per-item outcomes. ``gts``: an
        externally computed pairing value per ciphertext (the device
        tier), aligned with ``cts``; None entries (and items the device
        GT REJECTS) are decided by the host-exact path, so a wrong
        external value can only cost a recompute, never flip a verdict
        to accept."""
        out: list[tuple[bool, bytes, str]] = []
        for i, ct in enumerate(cts):
            try:
                # subgroup check elided: acceptance requires the FO test
                # r*G1 == U, and r*G1 is ALWAYS in the subgroup, so a U
                # outside it can never be accepted by either path — the
                # per-item oracle rejects it at decode, this path at the
                # FO check. Verdicts stay bit-identical; the ~9 ms/item
                # generic-mul check is the single largest per-item cost
                # after the pairing itself.
                u = PointG1.from_bytes(ct.u, subgroup_check=False)
            except ValueError as e:
                out.append((False, b"", str(e)))
                continue
            gt = gts[i] if gts is not None else None
            if gt is not None:
                try:
                    out.append((True, _finish(ct, u, gt), ""))
                    continue
                except ValueError:
                    pass  # false-reject-only: host path decides below
            try:
                out.append((True, _finish(ct, u, self.gt(u)), ""))
            except ValueError as e:
                out.append((False, b"", str(e)))
        _pairing_mod.N_PRODUCT_CHECKS += 1
        return out


def decrypt_batch(signature: bytes | PointG2,
                  cts) -> list[tuple[bool, bytes, str]]:
    """Host-tier batched round open: one shared-signature precomputation,
    then per-item evaluation — the ``crypto/batch.decrypt_round_batch``
    host path. Outcomes are bit-identical to a per-item
    :func:`decrypt` loop (same GT values, same FO check)."""
    return RoundDecryptor(signature).decrypt_many(cts)
