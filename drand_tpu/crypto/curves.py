"""BLS12-381 curve groups G1 (keys, 48B compressed) and G2 (signatures, 96B).

Matches the reference suite layout: keys on G1, signatures on G2
(/root/reference/key/curve.go:22-31) and the zcash/kyber compressed point
serialization (48-byte G1 pubkeys, 96-byte G2 sigs —
/root/reference/README.md:204, deploy/latest/group.toml).

Jacobian coordinates; a = 0 curves (y^2 = x^3 + 4 and y^2 = x^3 + 4(1+u)).
Cofactors are computed from the BLS parameter x at import (standard BLS12
polynomials), never hard-coded.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Iterable

from .fields import (
    P,
    R,
    X_BLS,
    FP_BYTES,
    Fp,
    Fp2,
)

# Cofactors from the BLS12 family polynomials (verified at import below).
H1 = (X_BLS - 1) ** 2 // 3
_h2_num = (
    X_BLS**8 - 4 * X_BLS**7 + 5 * X_BLS**6 - 4 * X_BLS**4
    + 6 * X_BLS**3 - 4 * X_BLS**2 - 4 * X_BLS + 13
)
assert _h2_num % 9 == 0
H2 = _h2_num // 9
assert (X_BLS - 1) ** 2 % 3 == 0


class _JacobianPoint:
    """Generic Jacobian point on y^2 = x^3 + B over FIELD (a = 0).

    Subclasses set FIELD, B, GENERATOR_AFFINE, COFACTOR, COMPRESSED_SIZE.
    Point at infinity is represented by Z = 0.
    """

    __slots__ = ("X", "Y", "Z")

    FIELD = None  # field class (Fp or Fp2)
    B = None  # curve coefficient
    COFACTOR = 1
    COMPRESSED_SIZE = 0

    def __init__(self, X, Y, Z):
        self.X = X
        self.Y = Y
        self.Z = Z

    # -- constructors -------------------------------------------------------
    @classmethod
    def infinity(cls):
        F = cls.FIELD
        return cls(F.one(), F.one(), F.zero())

    @classmethod
    def from_affine(cls, x, y):
        return cls(x, y, cls.FIELD.one())

    @classmethod
    def generator(cls):
        return cls.from_affine(*cls.GENERATOR_AFFINE)

    # -- predicates ---------------------------------------------------------
    def is_infinity(self) -> bool:
        return self.Z.is_zero()

    def __eq__(self, other) -> bool:
        if not isinstance(other, type(self)):
            return NotImplemented
        if self.is_infinity() or other.is_infinity():
            return self.is_infinity() and other.is_infinity()
        # X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3
        z1s = self.Z.square()
        z2s = other.Z.square()
        if self.X * z2s != other.X * z1s:
            return False
        return self.Y * (z2s * other.Z) == other.Y * (z1s * self.Z)

    def __hash__(self):
        if self.is_infinity():
            return hash((type(self).__name__, "inf"))
        x, y = self.to_affine()
        return hash((type(self).__name__, repr(x), repr(y)))

    def __repr__(self):
        if self.is_infinity():
            return f"{type(self).__name__}(infinity)"
        x, y = self.to_affine()
        return f"{type(self).__name__}({x!r}, {y!r})"

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        x, y = self.to_affine()
        return y.square() == x.square() * x + self.B

    def in_subgroup(self) -> bool:
        """Order-r check. O(log r) doublings; used on deserialization."""
        return self.mul(R).is_infinity()

    # -- group law ----------------------------------------------------------
    def to_affine(self):
        if self.is_infinity():
            raise ValueError("point at infinity has no affine coords")
        zinv = self.Z.inverse()
        zinv2 = zinv.square()
        return self.X * zinv2, self.Y * (zinv2 * zinv)

    @classmethod
    def batch_to_affine(cls, pts):
        """Affine coords for many points with ONE field inversion
        (Montgomery's simultaneous-inversion trick) — per-point
        ``to_affine`` pays a full exponentiation-based inverse each,
        which dominates host packing of large device batches.
        Raises on any point at infinity, like :meth:`to_affine`."""
        zs = []
        for p in pts:
            if p.is_infinity():
                raise ValueError("point at infinity has no affine coords")
            zs.append(p.Z)
        if not zs:
            return []
        prefix = [zs[0]]
        for z in zs[1:]:
            prefix.append(prefix[-1] * z)
        inv = prefix[-1].inverse()
        out = [None] * len(pts)
        for i in range(len(pts) - 1, -1, -1):
            zinv = inv * prefix[i - 1] if i else inv
            inv = inv * zs[i]
            zinv2 = zinv.square()
            out[i] = (pts[i].X * zinv2, pts[i].Y * (zinv2 * zinv))
        return out

    def double(self):
        if self.is_infinity():
            return self
        X1, Y1, Z1 = self.X, self.Y, self.Z
        A = X1.square()
        Bv = Y1.square()
        C = Bv.square()
        D = ((X1 + Bv).square() - A - C).mul_scalar(2)
        E = A.mul_scalar(3)
        F = E.square()
        X3 = F - D.mul_scalar(2)
        Y3 = E * (D - X3) - C.mul_scalar(8)
        Z3 = (Y1 * Z1).mul_scalar(2)
        return type(self)(X3, Y3, Z3)

    def __add__(self, other):
        if self.is_infinity():
            return other
        if other.is_infinity():
            return self
        X1, Y1, Z1 = self.X, self.Y, self.Z
        X2, Y2, Z2 = other.X, other.Y, other.Z
        Z1Z1 = Z1.square()
        Z2Z2 = Z2.square()
        U1 = X1 * Z2Z2
        U2 = X2 * Z1Z1
        S1 = Y1 * Z2 * Z2Z2
        S2 = Y2 * Z1 * Z1Z1
        H = U2 - U1
        if H.is_zero():
            if S1 == S2:
                return self.double()
            return self.infinity()
        I = H.square().mul_scalar(4)
        J = H * I
        r = (S2 - S1).mul_scalar(2)
        V = U1 * I
        X3 = r.square() - J - V.mul_scalar(2)
        Y3 = r * (V - X3) - (S1 * J).mul_scalar(2)
        Z3 = ((Z1 + Z2).square() - Z1Z1 - Z2Z2) * H
        return type(self)(X3, Y3, Z3)

    def __neg__(self):
        return type(self)(self.X, -self.Y, self.Z)

    def __sub__(self, other):
        return self + (-other)

    def mul(self, k: int):
        """Scalar multiplication (double-and-add, MSB first)."""
        k = int(k)
        if k < 0:
            return (-self).mul(-k)
        result = self.infinity()
        if k == 0 or self.is_infinity():
            return result
        for bit in bin(k)[2:]:
            result = result.double()
            if bit == "1":
                result = result + self
        return result

    def clear_cofactor(self):
        return self.mul(self.COFACTOR)

    @classmethod
    def msm(cls, scalars: Iterable[int], points: Iterable["_JacobianPoint"]):
        """Multi-scalar multiplication (naive host fallback; the TPU engine
        provides the batched Pippenger version)."""
        acc = cls.infinity()
        for s, pt in zip(scalars, points):
            acc = acc + pt.mul(s)
        return acc

    # -- serialization (zcash format) ---------------------------------------
    def _y_is_lexicographically_largest(self) -> bool:
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        """Compressed serialization: x with 3 flag bits in the top byte."""
        size = self.COMPRESSED_SIZE
        if self.is_infinity():
            out = bytearray(size)
            out[0] = 0xC0
            return bytes(out)
        x, _ = self.to_affine()
        out = bytearray(x.to_bytes())
        out[0] |= 0x80  # compression flag
        if self._y_is_lexicographically_largest():
            out[0] |= 0x20  # sort flag
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes, subgroup_check: bool = True):
        size = cls.COMPRESSED_SIZE
        if len(data) != size:
            raise ValueError(f"expected {size} bytes, got {len(data)}")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed encoding not supported")
        if flags & 0x40:  # infinity
            if any(data[1:]) or flags != 0xC0:
                raise ValueError("malformed infinity encoding")
            return cls.infinity()
        sort_flag = bool(flags & 0x20)
        xb = bytearray(data)
        xb[0] &= 0x1F
        x = cls.FIELD.from_bytes(bytes(xb))
        y2 = x.square() * x + cls.B
        y = y2.sqrt()
        if y is None:
            raise ValueError("x-coordinate not on curve")
        pt = cls.from_affine(x, y)
        if pt._y_is_lexicographically_largest() != sort_flag:
            pt = -pt
        if not pt.is_on_curve():
            raise ValueError("point not on curve")
        if subgroup_check and not pt.in_subgroup():
            raise ValueError("point not in the r-order subgroup")
        return pt

    def hash(self) -> bytes:
        """blake2b-256 of the compressed encoding (used in group hashing,
        mirroring /root/reference/key/group.go:24)."""
        return hashlib.blake2b(self.to_bytes(), digest_size=32).digest()


class PointG1(_JacobianPoint):
    """G1: y^2 = x^3 + 4 over Fp. Public keys live here (48-byte compressed)."""

    FIELD = Fp
    B = Fp(4)
    COFACTOR = H1
    COMPRESSED_SIZE = FP_BYTES
    GENERATOR_AFFINE = (
        Fp(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB),
        Fp(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1),
    )

    def _y_is_lexicographically_largest(self) -> bool:
        _, y = self.to_affine()
        return y.v > (P - 1) // 2


class PointG2(_JacobianPoint):
    """G2: y^2 = x^3 + 4(1+u) over Fp2. Signatures live here (96B compressed)."""

    FIELD = Fp2
    B = Fp2(4, 4)
    COFACTOR = H2
    COMPRESSED_SIZE = 2 * FP_BYTES
    GENERATOR_AFFINE = (
        Fp2(
            0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
            0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
        ),
        Fp2(
            0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
            0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
        ),
    )

    def _y_is_lexicographically_largest(self) -> bool:
        _, y = self.to_affine()
        neg = -y
        return (y.c1, y.c0) > (neg.c1, neg.c0)


# ---------------------------------------------------------------------------
# Fixed-base comb for G1 generator multiples (promoted from crypto/timelock,
# which keeps aliases): every g·s share-side check in the DKG and both
# timelock hot sites multiply the SAME base, so an 8-bit windowed table
# (32 windows × 255 multiples, built lazily once) turns a 255-bit
# double-and-add ladder into ≤ 32 additions per scalar.
# ---------------------------------------------------------------------------

_COMB_WINDOW = 8
_G1_COMB_TABLE: list[list["PointG1"]] | None = None
_G1_COMB_LOCK = threading.Lock()


def _g1_comb_table() -> list[list["PointG1"]]:
    global _G1_COMB_TABLE
    if _G1_COMB_TABLE is None:
        with _G1_COMB_LOCK:
            if _G1_COMB_TABLE is None:
                table = []
                base = PointG1.generator()
                for _ in range(-(-255 // _COMB_WINDOW)):
                    row = [PointG1.infinity(), base]
                    for _d in range(2, 1 << _COMB_WINDOW):
                        row.append(row[-1] + base)
                    table.append(row)
                    for _s in range(_COMB_WINDOW):
                        base = base.double()
                _G1_COMB_TABLE = table
    return _G1_COMB_TABLE


def g1_comb_mul(k: int) -> "PointG1":
    """k * G1 via the fixed-base comb (equal to generator().mul(k))."""
    k %= R
    if k == 0:
        return PointG1.infinity()
    table = _g1_comb_table()
    acc = PointG1.infinity()
    i = 0
    while k:
        d = k & ((1 << _COMB_WINDOW) - 1)
        if d:
            acc = acc + table[i][d]
        k >>= _COMB_WINDOW
        i += 1
    return acc


def _import_self_test() -> None:
    g1 = PointG1.generator()
    g2 = PointG2.generator()
    assert g1.is_on_curve(), "G1 generator off curve"
    assert g2.is_on_curve(), "G2 generator off curve"
    assert g1.mul(R).is_infinity(), "G1 generator order != r"
    assert g2.mul(R).is_infinity(), "G2 generator order != r"
    # serialization round-trips
    assert PointG1.from_bytes(g1.to_bytes()) == g1
    assert PointG2.from_bytes(g2.to_bytes()) == g2


_import_self_test()
