"""ECIES over G1 — private-randomness transport and DKG deal encryption.

Mirrors kyber/encrypt/ecies as used by the reference
(core/drand_public.go:130-148 PrivateRand; deal encryption inside the DKG):
ephemeral ECDH on G1, HKDF-SHA256 key derivation, AES-256-GCM AEAD.

Ciphertext layout: 48-byte compressed ephemeral G1 point || GCM sealed box.

When the ``cryptography`` package is missing (minimal images), a
self-contained AEAD stands in for AES-GCM: SHA256-CTR keystream +
HMAC-SHA256 tag over the same HKDF-derived key/nonce. The KDF is
bit-identical to the library HKDF (RFC 5869), but the sealed box is NOT
wire-compatible with AES-GCM peers — every node of a group must run the
same build, which the DKG deployment already requires.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import threading

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives import hashes
except ModuleNotFoundError:  # gated: fallback AEAD below
    AESGCM = None

from .fields import R
from .curves import PointG1

_KEY_LEN = 32
_NONCE_LEN = 12
_TAG_LEN = 16
EPH_SIZE = PointG1.COMPRESSED_SIZE


def _hkdf_sha256(ikm: bytes, length: int) -> bytes:
    """RFC 5869 HKDF-SHA256, salt=None, info=b"" — same output as the
    ``cryptography`` HKDF used on the main path."""
    prk = hmac.new(b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm, t, i = b"", b"", 1
    while len(okm) < length:
        t = hmac.new(prk, t + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


_FALLBACK_WARNED = False
# private_rand encrypt/decrypt run in to_thread workers while daemon
# startup paths touch this module on the loop — the warn-once flag is
# thread-shared (tools/analyze threadshare)
_WARN_LOCK = threading.Lock()


def _warn_fallback() -> None:
    """One-time notice that the non-wire-compatible AEAD is active, so a
    mixed-build group's decrypt failures are diagnosable from THIS node
    (the peer only ever sees 'invalid tag')."""
    global _FALLBACK_WARNED
    with _WARN_LOCK:
        if _FALLBACK_WARNED:
            return
        _FALLBACK_WARNED = True
    from ..utils.logging import default_logger

    default_logger("ecies").warn(
        "ecies", "aead_fallback_active",
        reason="'cryptography' package missing: using SHA256-CTR/HMAC "
               "AEAD, not wire-compatible with AES-GCM peers")


def _derive(dh: PointG1) -> tuple[bytes, bytes]:
    okm = HKDF(
        algorithm=hashes.SHA256(),
        length=_KEY_LEN + _NONCE_LEN,
        salt=None,
        info=b"",
    ).derive(dh.to_bytes())
    return okm[:_KEY_LEN], okm[_KEY_LEN:]


def _derive_fallback(dh: PointG1) -> tuple[bytes, bytes, bytes]:
    """(enc_key, mac_key, nonce) for the fallback AEAD — encryption and
    MAC keys are INDEPENDENT HKDF outputs (encrypt-then-MAC's security
    argument requires that; reusing one key for both would rest on an
    unanalyzed interaction between the CTR and HMAC constructions)."""
    _warn_fallback()
    okm = _hkdf_sha256(dh.to_bytes(), 2 * _KEY_LEN + _NONCE_LEN)
    return (okm[:_KEY_LEN], okm[_KEY_LEN:2 * _KEY_LEN],
            okm[2 * _KEY_LEN:])


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


def _fallback_seal(enc_key: bytes, mac_key: bytes, nonce: bytes,
                   msg: bytes) -> bytes:
    ct = bytes(a ^ b
               for a, b in zip(msg, _keystream(enc_key, nonce, len(msg))))
    tag = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()[:_TAG_LEN]
    return ct + tag


def _fallback_open(enc_key: bytes, mac_key: bytes, nonce: bytes,
                   sealed: bytes) -> bytes:
    ct, tag = sealed[:-_TAG_LEN], sealed[-_TAG_LEN:]
    want = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()[:_TAG_LEN]
    if not hmac.compare_digest(tag, want):
        # the sealed-box layout carries no algorithm tag (it must stay
        # byte-compatible with the reference when AES-GCM is present),
        # so a peer sealing with AES-GCM against our fallback AEAD is
        # indistinguishable from corruption — name the likely cause
        raise ValueError(
            "ECIES decryption failed: invalid tag (this build lacks the "
            "'cryptography' package and uses the fallback AEAD, which is "
            "not wire-compatible with AES-GCM peers)")
    return bytes(a ^ b
                 for a, b in zip(ct, _keystream(enc_key, nonce, len(ct))))


def encrypt(public: PointG1, msg: bytes) -> bytes:
    r = secrets.randbelow(R - 1) + 1
    eph = PointG1.generator().mul(r)
    dh = public.mul(r)
    if AESGCM is not None:
        key, nonce = _derive(dh)
        sealed = AESGCM(key).encrypt(nonce, msg, None)
    else:
        enc_key, mac_key, nonce = _derive_fallback(dh)
        sealed = _fallback_seal(enc_key, mac_key, nonce, msg)
    return eph.to_bytes() + sealed


def decrypt(sk: int, ciphertext: bytes) -> bytes:
    """Raises ValueError on any malformed or tampered ciphertext."""
    if len(ciphertext) < EPH_SIZE + 16:
        raise ValueError("ciphertext too short")
    eph = PointG1.from_bytes(ciphertext[:EPH_SIZE])
    dh = eph.mul(sk)
    if AESGCM is not None:
        key, nonce = _derive(dh)
        try:
            return AESGCM(key).decrypt(nonce, ciphertext[EPH_SIZE:], None)
        except Exception as e:  # InvalidTag
            raise ValueError(f"ECIES decryption failed: {e}") from e
    enc_key, mac_key, nonce = _derive_fallback(dh)
    return _fallback_open(enc_key, mac_key, nonce, ciphertext[EPH_SIZE:])
