"""ECIES over G1 — private-randomness transport and DKG deal encryption.

Mirrors kyber/encrypt/ecies as used by the reference
(core/drand_public.go:130-148 PrivateRand; deal encryption inside the DKG):
ephemeral ECDH on G1, HKDF-SHA256 key derivation, AES-256-GCM AEAD.

Ciphertext layout: 48-byte compressed ephemeral G1 point || GCM sealed box.
"""

from __future__ import annotations

import secrets

from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes

from .fields import R
from .curves import PointG1

_KEY_LEN = 32
_NONCE_LEN = 12
EPH_SIZE = PointG1.COMPRESSED_SIZE


def _derive(dh: PointG1) -> tuple[bytes, bytes]:
    okm = HKDF(
        algorithm=hashes.SHA256(),
        length=_KEY_LEN + _NONCE_LEN,
        salt=None,
        info=b"",
    ).derive(dh.to_bytes())
    return okm[:_KEY_LEN], okm[_KEY_LEN:]


def encrypt(public: PointG1, msg: bytes) -> bytes:
    r = secrets.randbelow(R - 1) + 1
    eph = PointG1.generator().mul(r)
    key, nonce = _derive(public.mul(r))
    sealed = AESGCM(key).encrypt(nonce, msg, None)
    return eph.to_bytes() + sealed


def decrypt(sk: int, ciphertext: bytes) -> bytes:
    """Raises ValueError on any malformed or tampered ciphertext."""
    if len(ciphertext) < EPH_SIZE + 16:
        raise ValueError("ciphertext too short")
    eph = PointG1.from_bytes(ciphertext[:EPH_SIZE])
    key, nonce = _derive(eph.mul(sk))
    try:
        return AESGCM(key).decrypt(nonce, ciphertext[EPH_SIZE:], None)
    except Exception as e:  # InvalidTag
        raise ValueError(f"ECIES decryption failed: {e}") from e
