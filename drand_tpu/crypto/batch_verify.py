"""Randomized batch verification (small-exponent RLC) — host path.

Collapses N independent BLS pairing checks into ONE 2-pairing product
check plus two size-N multi-scalar multiplications, the standard
random-linear-combination batch verifier from the BLS aggregate-verify
literature (kyber's ``bls.BatchVerify`` shape). For a span of checks
that all share the verification template ``e(-g1, sig_i) * e(pk_i,
H(m_i)) == 1``:

    sample independent random nonzero 128-bit scalars c_i, check
        e(-g1, sum c_i*sig_i) * e(sum-or-fixed side combined) == 1

Two span shapes appear in the beacon protocol and both are bilinear in
exactly one argument, so each collapses to 2 pairings + 2 MSMs:

- one public key, many messages (chain catch-up / sync / recovered-sig
  re-verification):  e(-g1, S) * e(pub, M) == 1 with
  S = sum c_i*sig_i (G2 MSM) and M = sum c_i*H(m_i) (G2 MSM);
- one message, many public keys (a round's partials): e(-g1, S) *
  e(K, H(msg)) == 1 with K = sum c_i*pk_i (G1 MSM).

Soundness (why this is safe):

- Every signature is individually decoded, subgroup-checked (via the
  psi-endomorphism fast check, same acceptance set as the generic
  order-r multiplication) and rejected if it is the point at infinity
  BEFORE entering the combination. Without per-item subgroup checks an
  adversary could plant cofactor-order components that cancel with
  probability 1/ord(component) — the classic small-subgroup attack on
  batch verification.
- With all points in the r-order subgroups, a batch containing at least
  one invalid signature passes only if the random vector (c_1..c_N)
  lands in a proper subspace of Fr^N fixed before the scalars are
  drawn: probability <= 2^-128 per verification (scalars are uniform
  nonzero 128-bit values, and r > 2^254).
- The scalars come from ``secrets`` (the OS CSPRNG) and MUST stay
  unpredictable: if an adversary knows c_i before choosing its inputs
  it can submit sig_1+D and sig_2-(c_1/c_2)*D, which cancel in the
  combination while both items are individually invalid. Never derive
  the scalars from the batch content.
- A zero scalar would delete its item from the check entirely, so
  scalars are drawn nonzero.

On batch failure the span bisects BATCHED: both halves are decided by
ONE grouped 4-pairing product check (fresh scalars per half, one shared
Miller pass — pairing.pairing_check_groups) instead of two sequential
2-pairing dispatches, recursing down to single items, which are decided
by the exact per-item oracle (tbls.verify_partial /
tbls.verify_recovered) — the returned bool array is therefore
bit-identical to the per-item path on every input, and an all-valid
span (the overwhelmingly common case) costs exactly one product check.

The combine MSMs run the ψ-endomorphism-split Pippenger (``msm``
below): G2 spans halve their 128-bit scalars through ψ and collapse
through the bucket method, with the original interleaved-window ladder
(``msm_window``) kept as the validation/bench reference.

Dispatch policy (which path runs when) lives in crypto/batch.py; the
device-graph version of the same combination lives in ops/engine.py.
"""

from __future__ import annotations

import secrets

import numpy as np

from . import endo, tbls
from .curves import PointG1, PointG2, _JacobianPoint
from .fields import R as FR_ORDER, X_BLS
from .hash_to_curve import DEFAULT_DST_G2, hash_to_g2
from .pairing import pairing_check, pairing_check_groups
from .poly import PubPoly

RLC_SCALAR_BITS = 128


def rlc_scalars(n: int) -> list[int]:
    """n independent uniform nonzero 128-bit scalars from the OS CSPRNG.

    Unpredictability is load-bearing (see module docstring): predictable
    scalars admit cancelling forgeries. Nonzero because a zero scalar
    removes its item from the combined check.
    """
    out = []
    for _ in range(n):
        c = 0
        while c == 0:
            c = secrets.randbits(RLC_SCALAR_BITS)
        out.append(c)
    return out


def decode_sig(sig_bytes: bytes) -> PointG2 | None:
    """Wire signature -> point, or None if it must be rejected per-item:
    malformed encoding, point at infinity, or outside the r-order
    subgroup (psi-endomorphism check — same acceptance set as
    ``PointG2.from_bytes(subgroup_check=True)``, ~3x cheaper, and the
    prefilter is the per-item cost of the RLC path)."""
    try:
        pt = PointG2.from_bytes(sig_bytes, subgroup_check=False)
    except ValueError:
        return None
    if pt.is_infinity():
        return None
    if not endo.subgroup_check_fast(pt):
        return None
    return pt


# ---------------------------------------------------------------------------
# Host MSM. Three layers:
#
# - ``msm_window``: the original interleaved 4-bit-window ladder (~46
#   point-adds per item + shared doublings) — kept as the small-span
#   fallback and as the bench/test reference the faster paths are
#   measured and validated against.
# - ``msm_pippenger``: the bucket method — per window, points land in
#   2^c - 1 digit buckets which collapse with one suffix-sum sweep, so
#   the add count is ~nwin*(n + 2^(c+1)) + nbits doublings, sublinear
#   per item once n outgrows the bucket overhead (window width ``c``
#   sized for n in [2, 1024]).
# - ``msm_endo_g2``: the ψ-endomorphism split for G2 spans. ψ acts as
#   multiplication by the BLS parameter x on the r-order subgroup
#   (crypto/endo.py), so with M = -x (63.7 bits) every 128-bit RLC
#   scalar c = q·M + rem becomes two <= _ENDO_Q_BITS-bit scalars on
#   (P, -ψ(P)) — HALF the doubling chain and half the window passes for
#   twice the (cheap, bucketed) points. The whole span is normalized
#   with ONE simultaneous inversion (batch_to_affine) so ψ costs two
#   Fp2 multiplications per point.
# - the ψ² 4-D GLS split (``_endo_split4_g2``) extends the same idea to
#   FULL-WIDTH scalars: any c (reduced mod r < M⁴) becomes four base-M
#   digits on (P, -ψP, ψ²P, -ψ³P) (endo.gls4_decompose/basis), so
#   255-bit Lagrange/verification scalars run a quarter-length chain —
#   the split ``recover``'s device ladders use too (ops/engine.py).
#
# ``msm`` dispatches: G2 spans split through ψ (two lanes for RLC-width
# scalars, four GLS lanes beyond), then bucket-vs-window by effective
# size. This is the term that must stay well under a Miller loop for
# the span speedup.
# ---------------------------------------------------------------------------

_MSM_WINDOW = 4
# ψ-split parameters: c = q·M + rem with M = -x > 0; q <= (2^128-1)//M
_ENDO_M = -X_BLS
assert _ENDO_M > 0
_ENDO_Q_BITS = (((1 << RLC_SCALAR_BITS) - 1) // _ENDO_M).bit_length()
# below this many (post-split) points the windowed ladder's lower fixed
# overhead beats the bucket sweep
_PIPPENGER_MIN = 16


def msm_window(points: list[_JacobianPoint], scalars: list[int],
               nbits: int = RLC_SCALAR_BITS):
    """sum_i scalars_i * points_i for nonnegative scalars < 2^nbits —
    interleaved windows, one shared doubling chain (the reference MSM)."""
    if not points:
        raise ValueError("empty MSM")
    cls = type(points[0])
    tables = []
    for p in points:
        tbl = [None] * (1 << _MSM_WINDOW)
        tbl[1] = p
        for k in range(2, 1 << _MSM_WINDOW):
            tbl[k] = tbl[k - 1] + p
        tables.append(tbl)
    acc = cls.infinity()
    nwin = (nbits + _MSM_WINDOW - 1) // _MSM_WINDOW
    for win in range(nwin - 1, -1, -1):
        if win != nwin - 1:
            for _ in range(_MSM_WINDOW):
                acc = acc.double()
        shift = win * _MSM_WINDOW
        for tbl, c in zip(tables, scalars):
            d = (c >> shift) & ((1 << _MSM_WINDOW) - 1)
            if d:
                acc = acc + tbl[d]
    return acc


def _pip_window(n: int) -> int:
    """Bucket width by span size (cost ~nwin*(n + 2^(c+1)): the optimum
    grows with log n; table tuned for the N in [2, 1024] dispatch range."""
    if n < 24:
        return 3
    if n < 80:
        return 4
    if n < 256:
        return 5
    if n < 900:
        return 6
    return 7


def msm_pippenger(points: list[_JacobianPoint], scalars: list[int],
                  nbits: int = RLC_SCALAR_BITS):
    """Bucket-method MSM: sum_i scalars_i * points_i, scalars < 2^nbits."""
    if not points:
        raise ValueError("empty MSM")
    cls = type(points[0])
    c = _pip_window(len(points))
    nwin = (nbits + c - 1) // c
    mask = (1 << c) - 1
    acc = None
    for win in range(nwin - 1, -1, -1):
        if acc is not None:
            for _ in range(c):
                acc = acc.double()
        shift = win * c
        buckets: list = [None] * (mask + 1)
        for p, s in zip(points, scalars):
            d = (s >> shift) & mask
            if d:
                buckets[d] = p if buckets[d] is None else buckets[d] + p
        # suffix sweep: total = sum_d d * bucket[d]
        running = total = None
        for d in range(mask, 0, -1):
            if buckets[d] is not None:
                running = (buckets[d] if running is None
                           else running + buckets[d])
            if running is not None:
                total = running if total is None else total + running
        if total is not None:
            acc = total if acc is None else acc + total
    return cls.infinity() if acc is None else acc


def _endo_split_g2(points: list[PointG2], scalars: list[int]):
    """(points, 128-bit scalars) -> (2x points, <= _ENDO_Q_BITS scalars)
    via c·P = rem·P + q·(-ψ(P)) where c = q·M + rem, M = -x (ψ(P) = [x]P
    on the r-order subgroup — every caller feeds subgroup-checked
    points: decode_sig's prefilter or hash_to_g2 outputs)."""
    xys = PointG2.batch_to_affine(points)
    pts2: list[PointG2] = []
    sc2: list[int] = []
    for (x, y), p, s in zip(xys, points, scalars):
        q, rem = divmod(s, _ENDO_M)
        if rem:
            pts2.append(p)
            sc2.append(rem)
        if q:
            pts2.append(-endo.psi_from_affine(x, y))
            sc2.append(q)
    return pts2, sc2


def _endo_split4_g2(points: list[PointG2], scalars: list[int]):
    """(points, any-width scalars) -> (<= 4x points, <= GLS4_DIGIT_BITS
    scalars) via the ψ² 4-D GLS decomposition: c mod r in base M = -x
    gives four <= 64-bit digits on (P, -ψP, ψ²P, -ψ³P)
    (endo.gls4_decompose / gls4_points_from_affine — every caller feeds
    subgroup-checked points, where ψ = [x] holds)."""
    xys = PointG2.batch_to_affine(points)
    pts4: list[PointG2] = []
    sc4: list[int] = []
    for (x, y), s in zip(xys, scalars):
        digits = endo.gls4_decompose(s)
        basis = None
        for k, d in enumerate(digits):
            if not d:
                continue
            if basis is None:
                basis = endo.gls4_points_from_affine(x, y)
            pts4.append(basis[k])
            sc4.append(d)
    return pts4, sc4


def msm(points: list[_JacobianPoint], scalars: list[int]):
    """sum_i scalars_i * points_i for nonnegative scalars — the RLC/
    Lagrange combine dispatcher: G2 spans ψ-split (two lanes for
    <= 128-bit scalars, four ψ² GLS lanes for full-width ones) to
    ~64-bit scalars, then bucket method above _PIPPENGER_MIN effective
    points, windowed ladder below. Value-identical to msm_window on
    every input (pure regrouping of the same group operation; wide
    scalars reduce mod the group order first)."""
    if not points:
        raise ValueError("empty MSM")
    cls = type(points[0])
    live = [(p, s) for p, s in zip(points, scalars)
            if s and not p.is_infinity()]
    if not live:
        return cls.infinity()
    pts = [p for p, _ in live]
    scs = [s for _, s in live]
    if isinstance(pts[0], PointG2):
        if any(s >> RLC_SCALAR_BITS for s in scs):
            pts, scs = _endo_split4_g2(pts, scs)
            nbits = endo.GLS4_DIGIT_BITS
        else:
            pts, scs = _endo_split_g2(pts, scs)
            nbits = _ENDO_Q_BITS
        if not pts:
            return cls.infinity()
    else:
        # G1 spans have no ψ: size the chain to the widest scalar
        nbits = max(RLC_SCALAR_BITS,
                    max(s.bit_length() for s in scs))
    if len(pts) >= _PIPPENGER_MIN:
        return msm_pippenger(pts, scs, nbits)
    return msm_window(pts, scs, nbits)


# ---------------------------------------------------------------------------
# The recursive span check
# ---------------------------------------------------------------------------

def _combine(items, fixed_g1: PointG1 | None, msg_pt: PointG2 | None):
    """The 2-pairing product check over ``items`` = [(pos, sig_pt,
    other)] as pairing pairs with FRESH scalars, where ``other`` is
    H(m_i) (fixed_g1 set: one-key-many-messages shape) or pk_i (msg_pt
    set: one-message-many-keys shape). None when a combination
    degenerates to infinity — a vacuously-degenerate combination must
    never decide a span, so callers treat None as a failed check and
    bisect down to the per-item oracle (for honest inputs this has
    ~2^-128 probability)."""
    cs = rlc_scalars(len(items))
    s_comb = msm([sig for _, sig, _ in items], cs)
    if fixed_g1 is not None:
        g1_side = fixed_g1
        g2_side = msm([other for _, _, other in items], cs)
    else:
        g1_side = msm([other for _, _, other in items], cs)
        g2_side = msg_pt
    if s_comb.is_infinity() or g1_side.is_infinity() or g2_side.is_infinity():
        return None
    return [(-PointG1.generator(), s_comb), (g1_side, g2_side)]


def _rlc_pass(items, fixed_g1: PointG1 | None, msg_pt: PointG2 | None) -> bool:
    pairs = _combine(items, fixed_g1, msg_pt)
    return pairs is not None and pairing_check(pairs)


def _resolve(items, out: list[bool], leaf, fixed_g1, msg_pt) -> None:
    """Mark out[pos] for every item: one RLC check per all-valid span,
    batched bisection otherwise, per-item oracle at the leaves."""
    if not items:
        return
    if len(items) == 1:
        pos = items[0][0]
        out[pos] = leaf(pos)
        return
    if _rlc_pass(items, fixed_g1, msg_pt):
        for pos, _, _ in items:
            out[pos] = True
        return
    _bisect(items, out, leaf, fixed_g1, msg_pt)


def _bisect(items, out: list[bool], leaf, fixed_g1, msg_pt) -> None:
    """``items``' combined check just failed: decide BOTH halves with
    one grouped 4-pairing product check (fresh scalars per half —
    pairing.pairing_check_groups shares the Miller pass) instead of two
    sequential 2-pairing dispatches, then recurse into failing halves
    without re-checking them. Singleton halves go straight to the exact
    per-item oracle, so the bool output stays bit-identical to the
    per-item loop on every input."""
    mid = len(items) // 2
    checks = []  # (half, pairs-or-None) awaiting the grouped verdict
    for half in (items[:mid], items[mid:]):
        if len(half) == 1:
            pos = half[0][0]
            out[pos] = leaf(pos)
            continue
        checks.append((half, _combine(half, fixed_g1, msg_pt)))
    live = [pairs for _, pairs in checks if pairs is not None]
    verdicts = iter(pairing_check_groups(live) if live else ())
    for half, pairs in checks:
        ok = next(verdicts) if pairs is not None else False
        if ok:
            for pos, _, _ in half:
                out[pos] = True
        else:
            _bisect(half, out, leaf, fixed_g1, msg_pt)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def verify_sigs_rlc(pubkey: PointG1, checks,
                    dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """Batch of (msg_bytes, sig_bytes) full-signature checks against ONE
    public key — RLC over distinct messages. Bool list aligned with
    ``checks``, bit-identical to per-item tbls.verify_recovered."""
    out = [False] * len(checks)
    if pubkey.is_infinity():
        return out
    items = []
    for i, (m, s) in enumerate(checks):
        pt = decode_sig(s)
        if pt is None:
            continue  # per-item reject; never enters the combination
        items.append((i, pt, hash_to_g2(m, dst)))

    def leaf(pos: int) -> bool:
        m, s = checks[pos]
        return tbls.verify_recovered(pubkey, m, s, dst)

    _resolve(items, out, leaf, pubkey, None)
    return out


def verify_beacons_rlc(pubkey: PointG1, beacons,
                       dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
    """Dual (V1 + V2-when-present) beacon verification over a span as one
    flattened RLC check — same bool-per-beacon contract as the per-item
    loop in crypto/batch.verify_beacons."""
    from ..chain import beacon as chain_beacon

    checks: list[tuple[bytes, bytes]] = []
    spans: list[tuple[int, int]] = []
    for b in beacons:
        start = len(checks)
        checks.append((chain_beacon.message(b.round, b.previous_sig),
                       b.signature))
        if b.is_v2():
            checks.append((chain_beacon.message_v2(b.round), b.signature_v2))
        spans.append((start, len(checks) - start))
    flat = verify_sigs_rlc(pubkey, checks, dst)
    return np.array([all(flat[s:s + c]) for s, c in spans], dtype=bool)


def verify_partials_rlc(pub_poly: PubPoly, msg: bytes, partials,
                        dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """A round's partial signatures — one message, per-index public key
    shares — as one RLC check. Bool list aligned with ``partials``,
    bit-identical to per-item tbls.verify_partial (duplicate share
    indices are independent items, exactly as the per-item loop treats
    them)."""
    out = [False] * len(partials)
    msg_pt = hash_to_g2(msg, dst)
    items = []
    for i, p in enumerate(partials):
        if len(p) != tbls.PARTIAL_SIG_SIZE:
            continue
        pt = decode_sig(p[tbls.INDEX_BYTES:])
        if pt is None:
            continue
        pk = pub_poly.eval(tbls.index_of(p)).value
        if pk.is_infinity():
            continue  # oracle: e(-g1, sig) alone is 1 only for sig == O
        items.append((i, pt, pk))

    def leaf(pos: int) -> bool:
        return tbls.verify_partial(pub_poly, msg, partials[pos], dst)

    _resolve(items, out, leaf, None, msg_pt)
    return out


def reshare_bindings_rlc(old_pub: PubPoly, items) -> list[bool]:
    """Reshare dual-group binding verdicts for a whole deal phase as ONE
    combined check: ``items`` = [(dealer_index, Q_d)] where Q_d is the
    dealer's constant-term commitment, which the protocol requires to
    equal ``old_pub.eval(dealer_index)``. With fresh 128-bit scalars c_d
    (rlc_scalars) and x_d = dealer_index + 1, all n Horner walks fold
    into two MSMs:

        Σ_d c_d·Q_d  ==  Σ_k (Σ_d c_d·x_d^k mod r)·C_k

    — one n-point 128-bit MSM over the constant terms plus one t-point
    full-width MSM over the OLD commits (the "one multi-point evaluation,
    not n Horner walks" shape). Soundness 2^-128 PER SPAN **provided
    every Q_d and old commit lies in G1** — that is the caller's
    contract (deal admission subgroup-checks all parsed commits first;
    old_pub comes from the trusted group file). On a failed span the
    resolver bisects with fresh scalars per half down to the exact
    per-dealer Horner oracle, so the bool list is bit-identical to
    ``[old_pub.eval(i).value == q for i, q in items]`` on every input.
    """
    out = [False] * len(items)

    def span_pass(span) -> bool:
        cs = rlc_scalars(len(span))
        lhs = msm([q for _, _, q in span], cs)
        t = len(old_pub.commits)
        ws = [0] * t
        for (_, idx, _), c in zip(span, cs):
            xp = 1
            x = idx + 1  # kyber abscissa convention (poly._x_of)
            for k in range(t):
                ws[k] = (ws[k] + c * xp) % FR_ORDER
                xp = xp * x % FR_ORDER
        return lhs == msm(old_pub.commits, ws)

    def resolve(span) -> None:
        if not span:
            return
        if len(span) == 1:
            pos, idx, q = span[0]
            out[pos] = old_pub.eval(idx).value == q
            return
        if span_pass(span):
            for pos, _, _ in span:
                out[pos] = True
            return
        mid = len(span) // 2
        resolve(span[:mid])
        resolve(span[mid:])

    resolve([(pos, idx, q) for pos, (idx, q) in enumerate(items)])
    return out
