"""Randomized batch verification (small-exponent RLC) — host path.

Collapses N independent BLS pairing checks into ONE 2-pairing product
check plus two size-N multi-scalar multiplications, the standard
random-linear-combination batch verifier from the BLS aggregate-verify
literature (kyber's ``bls.BatchVerify`` shape). For a span of checks
that all share the verification template ``e(-g1, sig_i) * e(pk_i,
H(m_i)) == 1``:

    sample independent random nonzero 128-bit scalars c_i, check
        e(-g1, sum c_i*sig_i) * e(sum-or-fixed side combined) == 1

Two span shapes appear in the beacon protocol and both are bilinear in
exactly one argument, so each collapses to 2 pairings + 2 MSMs:

- one public key, many messages (chain catch-up / sync / recovered-sig
  re-verification):  e(-g1, S) * e(pub, M) == 1 with
  S = sum c_i*sig_i (G2 MSM) and M = sum c_i*H(m_i) (G2 MSM);
- one message, many public keys (a round's partials): e(-g1, S) *
  e(K, H(msg)) == 1 with K = sum c_i*pk_i (G1 MSM).

Soundness (why this is safe):

- Every signature is individually decoded, subgroup-checked (via the
  psi-endomorphism fast check, same acceptance set as the generic
  order-r multiplication) and rejected if it is the point at infinity
  BEFORE entering the combination. Without per-item subgroup checks an
  adversary could plant cofactor-order components that cancel with
  probability 1/ord(component) — the classic small-subgroup attack on
  batch verification.
- With all points in the r-order subgroups, a batch containing at least
  one invalid signature passes only if the random vector (c_1..c_N)
  lands in a proper subspace of Fr^N fixed before the scalars are
  drawn: probability <= 2^-128 per verification (scalars are uniform
  nonzero 128-bit values, and r > 2^254).
- The scalars come from ``secrets`` (the OS CSPRNG) and MUST stay
  unpredictable: if an adversary knows c_i before choosing its inputs
  it can submit sig_1+D and sig_2-(c_1/c_2)*D, which cancel in the
  combination while both items are individually invalid. Never derive
  the scalars from the batch content.
- A zero scalar would delete its item from the check entirely, so
  scalars are drawn nonzero.

On batch failure the span bisects (each half re-checked with FRESH
scalars) down to single items, which are decided by the exact per-item
oracle (tbls.verify_partial / tbls.verify_recovered) — the returned
bool array is therefore bit-identical to the per-item path on every
input, and an all-valid span (the overwhelmingly common case) costs
exactly one product check.

Dispatch policy (which path runs when) lives in crypto/batch.py; the
device-graph version of the same combination lives in ops/engine.py.
"""

from __future__ import annotations

import secrets

import numpy as np

from . import endo, tbls
from .curves import PointG1, PointG2, _JacobianPoint
from .hash_to_curve import DEFAULT_DST_G2, hash_to_g2
from .pairing import pairing_check
from .poly import PubPoly

RLC_SCALAR_BITS = 128


def rlc_scalars(n: int) -> list[int]:
    """n independent uniform nonzero 128-bit scalars from the OS CSPRNG.

    Unpredictability is load-bearing (see module docstring): predictable
    scalars admit cancelling forgeries. Nonzero because a zero scalar
    removes its item from the combined check.
    """
    out = []
    for _ in range(n):
        c = 0
        while c == 0:
            c = secrets.randbits(RLC_SCALAR_BITS)
        out.append(c)
    return out


def decode_sig(sig_bytes: bytes) -> PointG2 | None:
    """Wire signature -> point, or None if it must be rejected per-item:
    malformed encoding, point at infinity, or outside the r-order
    subgroup (psi-endomorphism check — same acceptance set as
    ``PointG2.from_bytes(subgroup_check=True)``, ~3x cheaper, and the
    prefilter is the per-item cost of the RLC path)."""
    try:
        pt = PointG2.from_bytes(sig_bytes, subgroup_check=False)
    except ValueError:
        return None
    if pt.is_infinity():
        return None
    if not endo.subgroup_check_fast(pt):
        return None
    return pt


# ---------------------------------------------------------------------------
# Host MSM: interleaved 4-bit windows with one shared doubling chain —
# ~46 point-adds per item + 124 shared doublings for 128-bit scalars,
# vs ~192 ops per item for independent double-and-add. This is the term
# that must stay well under a Miller loop for the >=5x span speedup.
# ---------------------------------------------------------------------------

_MSM_WINDOW = 4


def msm(points: list[_JacobianPoint], scalars: list[int]):
    """sum_i scalars_i * points_i for nonnegative scalars < 2^128."""
    if not points:
        raise ValueError("empty MSM")
    cls = type(points[0])
    tables = []
    for p in points:
        tbl = [None] * (1 << _MSM_WINDOW)
        tbl[1] = p
        for k in range(2, 1 << _MSM_WINDOW):
            tbl[k] = tbl[k - 1] + p
        tables.append(tbl)
    acc = cls.infinity()
    nwin = (RLC_SCALAR_BITS + _MSM_WINDOW - 1) // _MSM_WINDOW
    for win in range(nwin - 1, -1, -1):
        if win != nwin - 1:
            for _ in range(_MSM_WINDOW):
                acc = acc.double()
        shift = win * _MSM_WINDOW
        for tbl, c in zip(tables, scalars):
            d = (c >> shift) & ((1 << _MSM_WINDOW) - 1)
            if d:
                acc = acc + tbl[d]
    return acc


# ---------------------------------------------------------------------------
# The recursive span check
# ---------------------------------------------------------------------------

def _rlc_pass(items, fixed_g1: PointG1 | None, msg_pt: PointG2 | None) -> bool:
    """One product check over ``items`` = [(pos, sig_pt, other)] where
    ``other`` is H(m_i) (fixed_g1 set: one-key-many-messages shape) or
    pk_i (msg_pt set: one-message-many-keys shape)."""
    cs = rlc_scalars(len(items))
    s_comb = msm([sig for _, sig, _ in items], cs)
    if fixed_g1 is not None:
        g1_side = fixed_g1
        g2_side = msm([other for _, _, other in items], cs)
    else:
        g1_side = msm([other for _, _, other in items], cs)
        g2_side = msg_pt
    if s_comb.is_infinity() or g1_side.is_infinity() or g2_side.is_infinity():
        # a vacuously-degenerate combination must never decide a span —
        # report failure so the caller bisects down to the per-item oracle
        # (for honest inputs this has ~2^-128 probability)
        return False
    return pairing_check([(-PointG1.generator(), s_comb),
                          (g1_side, g2_side)])


def _resolve(items, out: list[bool], leaf, fixed_g1, msg_pt) -> None:
    """Mark out[pos] for every item: one RLC check per all-valid span,
    bisection (fresh scalars per sub-span) otherwise, per-item oracle at
    the leaves."""
    if not items:
        return
    if len(items) == 1:
        pos = items[0][0]
        out[pos] = leaf(pos)
        return
    if _rlc_pass(items, fixed_g1, msg_pt):
        for pos, _, _ in items:
            out[pos] = True
        return
    mid = len(items) // 2
    _resolve(items[:mid], out, leaf, fixed_g1, msg_pt)
    _resolve(items[mid:], out, leaf, fixed_g1, msg_pt)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def verify_sigs_rlc(pubkey: PointG1, checks,
                    dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """Batch of (msg_bytes, sig_bytes) full-signature checks against ONE
    public key — RLC over distinct messages. Bool list aligned with
    ``checks``, bit-identical to per-item tbls.verify_recovered."""
    out = [False] * len(checks)
    if pubkey.is_infinity():
        return out
    items = []
    for i, (m, s) in enumerate(checks):
        pt = decode_sig(s)
        if pt is None:
            continue  # per-item reject; never enters the combination
        items.append((i, pt, hash_to_g2(m, dst)))

    def leaf(pos: int) -> bool:
        m, s = checks[pos]
        return tbls.verify_recovered(pubkey, m, s, dst)

    _resolve(items, out, leaf, pubkey, None)
    return out


def verify_beacons_rlc(pubkey: PointG1, beacons,
                       dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
    """Dual (V1 + V2-when-present) beacon verification over a span as one
    flattened RLC check — same bool-per-beacon contract as the per-item
    loop in crypto/batch.verify_beacons."""
    from ..chain import beacon as chain_beacon

    checks: list[tuple[bytes, bytes]] = []
    spans: list[tuple[int, int]] = []
    for b in beacons:
        start = len(checks)
        checks.append((chain_beacon.message(b.round, b.previous_sig),
                       b.signature))
        if b.is_v2():
            checks.append((chain_beacon.message_v2(b.round), b.signature_v2))
        spans.append((start, len(checks) - start))
    flat = verify_sigs_rlc(pubkey, checks, dst)
    return np.array([all(flat[s:s + c]) for s, c in spans], dtype=bool)


def verify_partials_rlc(pub_poly: PubPoly, msg: bytes, partials,
                        dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """A round's partial signatures — one message, per-index public key
    shares — as one RLC check. Bool list aligned with ``partials``,
    bit-identical to per-item tbls.verify_partial (duplicate share
    indices are independent items, exactly as the per-item loop treats
    them)."""
    out = [False] * len(partials)
    msg_pt = hash_to_g2(msg, dst)
    items = []
    for i, p in enumerate(partials):
        if len(p) != tbls.PARTIAL_SIG_SIZE:
            continue
        pt = decode_sig(p[tbls.INDEX_BYTES:])
        if pt is None:
            continue
        pk = pub_poly.eval(tbls.index_of(p)).value
        if pk.is_infinity():
            continue  # oracle: e(-g1, sig) alone is 1 only for sig == O
        items.append((i, pt, pk))

    def leaf(pos: int) -> bool:
        return tbls.verify_partial(pub_poly, msg, partials[pos], dst)

    _resolve(items, out, leaf, None, msg_pt)
    return out
