"""BLS12-381 field towers: Fp, Fp2, Fp6, Fp12 and the scalar field Fr.

Pure-Python reference engine (exact semantics; host signing path). The batched
TPU engine in ``drand_tpu.ops`` is golden-tested against this module.

Replaces the reference's external crypto stack (kyber-bls12381 wrapping
kilic/bls12-381 — see /root/reference/key/curve.go:19-38 for the suite
selection this module underpins).

Tower construction (standard for BLS12-381):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = 1 + u
    Fp12 = Fp6[w] / (w^2 - v)

All derived constants (Frobenius coefficients, sqrt helpers) are COMPUTED at
import time from p and the tower definition, never hard-coded, so they cannot
be silently wrong: import fails loudly if an invariant breaks.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Base constants (the only hard-coded numbers: curve parameters of BLS12-381)
# ---------------------------------------------------------------------------

# Field modulus p
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order r (the scalar field Fr)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative); p and r are polynomials in x:
#   r = x^4 - x^2 + 1,  p = (x-1)^2/3 * r + x
X_BLS = -0xD201000000010000

assert P % 6 == 1
assert R == X_BLS**4 - X_BLS**2 + 1
assert P == ((X_BLS - 1) ** 2 // 3) * R + X_BLS

FP_BYTES = 48  # big-endian serialized Fp element


# ---------------------------------------------------------------------------
# Fp — represented as plain python ints in [0, P)
# ---------------------------------------------------------------------------

def fp_add(a: int, b: int) -> int:
    return (a + b) % P


def fp_sub(a: int, b: int) -> int:
    return (a - b) % P


def fp_mul(a: int, b: int) -> int:
    return (a * b) % P


def fp_neg(a: int) -> int:
    return (-a) % P


def fp_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    return pow(a, -1, P)


def fp_is_square(a: int) -> bool:
    """Euler criterion; 0 counts as square."""
    a %= P
    return a == 0 or pow(a, (P - 1) // 2, P) == 1


_P_PLUS_1_OVER_4 = (P + 1) // 4  # valid since P % 4 == 3


def fp_sqrt(a: int) -> int | None:
    """Square root in Fp, or None if a is not a QR. p ≡ 3 (mod 4)."""
    a %= P
    r = pow(a, _P_PLUS_1_OVER_4, P)
    return r if r * r % P == a else None


def fp_to_bytes(a: int) -> bytes:
    return int(a % P).to_bytes(FP_BYTES, "big")


def fp_from_bytes(b: bytes) -> int:
    if len(b) != FP_BYTES:
        raise ValueError(f"Fp element must be {FP_BYTES} bytes, got {len(b)}")
    v = int.from_bytes(b, "big")
    if v >= P:
        raise ValueError("Fp element not canonical (>= p)")
    return v


class Fp:
    """Object wrapper over the int representation, giving Fp the same duck
    interface as Fp2 so curve/SSWU code can be written once for both."""

    __slots__ = ("v",)

    def __init__(self, v: int = 0):
        self.v = v % P

    @staticmethod
    def zero() -> "Fp":
        return Fp(0)

    @staticmethod
    def one() -> "Fp":
        return Fp(1)

    def is_zero(self) -> bool:
        return self.v == 0

    def __eq__(self, other) -> bool:
        return isinstance(other, Fp) and self.v == other.v

    def __hash__(self):
        return hash(("Fp", self.v))

    def __repr__(self):
        return f"Fp({hex(self.v)})"

    def __add__(self, o: "Fp") -> "Fp":
        return Fp(self.v + o.v)

    def __sub__(self, o: "Fp") -> "Fp":
        return Fp(self.v - o.v)

    def __neg__(self) -> "Fp":
        return Fp(-self.v)

    def __mul__(self, o: "Fp") -> "Fp":
        return Fp(self.v * o.v)

    def mul_scalar(self, k: int) -> "Fp":
        return Fp(self.v * k)

    def square(self) -> "Fp":
        return Fp(self.v * self.v)

    def inverse(self) -> "Fp":
        return Fp(fp_inv(self.v))

    def pow(self, e: int) -> "Fp":
        if e < 0:
            return Fp(pow(fp_inv(self.v), -e, P))
        return Fp(pow(self.v, e, P))

    def sqrt(self) -> "Fp | None":
        r = fp_sqrt(self.v)
        return None if r is None else Fp(r)

    def is_square(self) -> bool:
        return fp_is_square(self.v)

    def sgn0(self) -> int:
        return self.v & 1

    def to_bytes(self) -> bytes:
        return fp_to_bytes(self.v)

    @staticmethod
    def from_bytes(b: bytes) -> "Fp":
        return Fp(fp_from_bytes(b))


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1)
# ---------------------------------------------------------------------------

class Fp2:
    """Element c0 + c1*u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int = 0, c1: int = 0):
        self.c0 = c0 % P
        self.c1 = c1 % P

    # -- constructors -------------------------------------------------------
    @staticmethod
    def zero() -> "Fp2":
        return Fp2(0, 0)

    @staticmethod
    def one() -> "Fp2":
        return Fp2(1, 0)

    # -- predicates ---------------------------------------------------------
    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, other) -> bool:
        return isinstance(other, Fp2) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __repr__(self):
        return f"Fp2({hex(self.c0)}, {hex(self.c1)})"

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp2":
        return Fp2(-self.c0, -self.c1)

    def __mul__(self, o: "Fp2") -> "Fp2":
        # Karatsuba: (a0+a1 u)(b0+b1 u) = a0b0 - a1b1 + ((a0+a1)(b0+b1)-a0b0-a1b1) u
        v0 = self.c0 * o.c0
        v1 = self.c1 * o.c1
        return Fp2(v0 - v1, (self.c0 + self.c1) * (o.c0 + o.c1) - v0 - v1)

    def mul_scalar(self, k: int) -> "Fp2":
        return Fp2(self.c0 * k, self.c1 * k)

    def square(self) -> "Fp2":
        # (a+bu)^2 = (a+b)(a-b) + 2ab u
        a, b = self.c0, self.c1
        return Fp2((a + b) * (a - b), 2 * a * b)

    def conjugate(self) -> "Fp2":
        return Fp2(self.c0, -self.c1)

    def inverse(self) -> "Fp2":
        # 1/(a+bu) = (a-bu)/(a^2+b^2)
        norm = self.c0 * self.c0 + self.c1 * self.c1
        t = fp_inv(norm % P)
        return Fp2(self.c0 * t, -self.c1 * t)

    def pow(self, e: int) -> "Fp2":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fp2.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sqrt(self) -> "Fp2 | None":
        """Square root in Fp2 (p^2 ≡ 9 mod 16), via candidate method."""
        if self.is_zero():
            return Fp2.zero()
        cand = self.pow(_Q2_PLUS_7_OVER_16)
        for root4 in _FP2_ROOTS_OF_UNITY_4:
            r = cand * root4
            if r.square() == self:
                return r
        return None

    def is_square(self) -> bool:
        # norm is a QR in Fp iff element is a QR in Fp2
        norm = (self.c0 * self.c0 + self.c1 * self.c1) % P
        return fp_is_square(norm)

    def frobenius(self) -> "Fp2":
        """x -> x^p (= conjugation since p ≡ 3 mod 4)."""
        return self.conjugate()

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for Fp2 (m=2)."""
        sign_0 = self.c0 & 1
        zero_0 = self.c0 == 0
        sign_1 = self.c1 & 1
        return sign_0 | (zero_0 & sign_1)

    def to_bytes(self) -> bytes:
        """c1 || c0, matching the zcash/kyber G2 x-coordinate layout."""
        return fp_to_bytes(self.c1) + fp_to_bytes(self.c0)

    @staticmethod
    def from_bytes(b: bytes) -> "Fp2":
        if len(b) != 2 * FP_BYTES:
            raise ValueError("Fp2 element must be 96 bytes")
        return Fp2(fp_from_bytes(b[FP_BYTES:]), fp_from_bytes(b[:FP_BYTES]))


# Nonresidue xi = 1 + u used to build Fp6
XI = Fp2(1, 1)

# sqrt helper constants (computed, with self-checks)
_Q2_PLUS_7_OVER_16 = (P * P + 7) // 16
assert (P * P) % 16 == 9


def _compute_fp2_fourth_roots() -> list[Fp2]:
    """The four fourth-roots of unity in Fp2: 1, u, sqrt(u), sqrt(-u)."""
    # sqrt(u) has the form a*(1 ± u): need a^2 = 1/2 (for a+au) or
    # a^2 = -1/2 (for a-au); exactly one of ±1/2 is a QR mod p.
    half = fp_inv(2)
    a = fp_sqrt(half)
    if a is not None:
        c2 = Fp2(a, a)   # (a+au)^2 = 2a^2 u = u
        c3 = Fp2(a, -a)  # (a-au)^2 = -2a^2 u = -u
    else:
        a = fp_sqrt(fp_neg(half))
        assert a is not None, "neither 1/2 nor -1/2 is a QR: impossible"
        c2 = Fp2(a, -a)  # (a-au)^2 = -2a^2 u = u
        c3 = Fp2(a, a)   # (a+au)^2 = 2a^2 u = -u
    assert c2.square() == Fp2(0, 1)
    assert c3.square() == Fp2(0, -1)
    return [Fp2.one(), Fp2(0, 1), c2, c3]


_FP2_ROOTS_OF_UNITY_4 = _compute_fp2_fourth_roots()


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi)
# ---------------------------------------------------------------------------

class Fp6:
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fp2, c1: Fp2, c2: Fp2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    @staticmethod
    def zero() -> "Fp6":
        return Fp6(Fp2.zero(), Fp2.zero(), Fp2.zero())

    @staticmethod
    def one() -> "Fp6":
        return Fp6(Fp2.one(), Fp2.zero(), Fp2.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Fp6)
            and self.c0 == other.c0
            and self.c1 == other.c1
            and self.c2 == other.c2
        )

    def __hash__(self):
        return hash((self.c0, self.c1, self.c2))

    def __repr__(self):
        return f"Fp6({self.c0!r}, {self.c1!r}, {self.c2!r})"

    def __add__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fp6":
        return Fp6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fp6") -> "Fp6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        v0 = a0 * b0
        v1 = a1 * b1
        v2 = a2 * b2
        c0 = v0 + XI * ((a1 + a2) * (b1 + b2) - v1 - v2)
        c1 = (a0 + a1) * (b0 + b1) - v0 - v1 + XI * v2
        c2 = (a0 + a2) * (b0 + b2) - v0 + v1 - v2
        return Fp6(c0, c1, c2)

    def square(self) -> "Fp6":
        return self * self

    def mul_fp2(self, k: Fp2) -> "Fp6":
        return Fp6(self.c0 * k, self.c1 * k, self.c2 * k)

    def mul_by_v(self) -> "Fp6":
        """Multiply by v: (c0,c1,c2) -> (xi*c2, c0, c1)."""
        return Fp6(XI * self.c2, self.c0, self.c1)

    def inverse(self) -> "Fp6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - XI * (a1 * a2)
        t1 = XI * a2.square() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + XI * (a2 * t1) + XI * (a1 * t2)
        dinv = denom.inverse()
        return Fp6(t0 * dinv, t1 * dinv, t2 * dinv)


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

class Fp12:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fp6, c1: Fp6):
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def zero() -> "Fp12":
        return Fp12(Fp6.zero(), Fp6.zero())

    @staticmethod
    def one() -> "Fp12":
        return Fp12(Fp6.one(), Fp6.zero())

    @staticmethod
    def from_fp2(x: Fp2) -> "Fp12":
        return Fp12(Fp6(x, Fp2.zero(), Fp2.zero()), Fp6.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def is_one(self) -> bool:
        return self == Fp12.one()

    def __eq__(self, other) -> bool:
        return isinstance(other, Fp12) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __repr__(self):
        return f"Fp12({self.c0!r}, {self.c1!r})"

    def __add__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp12":
        return Fp12(-self.c0, -self.c1)

    def __mul__(self, o: "Fp12") -> "Fp12":
        a0, a1 = self.c0, self.c1
        b0, b1 = o.c0, o.c1
        v0 = a0 * b0
        v1 = a1 * b1
        c0 = v0 + v1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - v0 - v1
        return Fp12(c0, c1)

    def square(self) -> "Fp12":
        # complex squaring: (a0 + a1 w)^2 = (a0+a1)(a0 + v a1) - v0 - v*v0' ...
        a0, a1 = self.c0, self.c1
        v0 = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - v0 - v0.mul_by_v()
        c1 = v0 + v0
        return Fp12(c0, c1)

    def conjugate(self) -> "Fp12":
        """x -> x^(p^6): negate the w-odd half."""
        return Fp12(self.c0, -self.c1)

    def inverse(self) -> "Fp12":
        a0, a1 = self.c0, self.c1
        denom = a0.square() - a1.square().mul_by_v()
        dinv = denom.inverse()
        return Fp12(a0 * dinv, -(a1 * dinv))

    def pow(self, e: int) -> "Fp12":
        if e < 0:
            return self.inverse().pow(-e)
        result = Fp12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    # -- w-basis conversion for Frobenius ----------------------------------
    def _to_w_coeffs(self) -> list[Fp2]:
        """Coefficients of 1, w, w^2(=v), w^3, w^4, w^5 over Fp2."""
        return [
            self.c0.c0, self.c1.c0, self.c0.c1,
            self.c1.c1, self.c0.c2, self.c1.c2,
        ]

    @staticmethod
    def _from_w_coeffs(c: list[Fp2]) -> "Fp12":
        return Fp12(Fp6(c[0], c[2], c[4]), Fp6(c[1], c[3], c[5]))

    def frobenius(self, power: int = 1) -> "Fp12":
        """x -> x^(p^power) using precomputed gamma = xi^(i*(p^k-1)/6)."""
        power %= 12
        if power == 0:
            return self
        gammas = _FROBENIUS_GAMMA[power]
        coeffs = self._to_w_coeffs()
        out = []
        for i, c in enumerate(coeffs):
            ci = c
            # apply coefficient-wise p^power Frobenius of Fp2 (conj if odd)
            if power % 2 == 1:
                ci = ci.conjugate()
            out.append(ci * gammas[i])
        return Fp12._from_w_coeffs(out)

    def cyclotomic_square(self) -> "Fp12":
        """Granger-Scott squaring, valid in the cyclotomic subgroup.

        Golden-tested against ``square`` in tests.
        """
        # represent as (g0..g5) w-coeffs; use standard GS formulas over Fp2
        g0, g1, g2, g3, g4, g5 = self._to_w_coeffs()

        def _sq2(a: Fp2, b: Fp2) -> tuple[Fp2, Fp2]:
            # (a + b*y)^2 in Fp4 = Fp2[y]/(y^2 - xi)
            t0 = a.square()
            t1 = b.square()
            return t0 + XI * t1, (a + b).square() - t0 - t1

        a0, a1 = _sq2(g0, g3)  # Fp4 = Fp2[w^3], (w^3)^2 = xi
        b0, b1 = _sq2(g1, g4)
        c0, c1 = _sq2(g2, g5)

        def _f(goal: Fp2, t: Fp2) -> Fp2:
            return (t - goal).mul_scalar(2) + t  # 3t - 2*goal

        def _g(goal: Fp2, t: Fp2) -> Fp2:
            return (t + goal).mul_scalar(2) + t  # 3t + 2*goal

        h0 = _f(g0, a0)
        h1 = _g(g1, XI * c1)
        h2 = _f(g2, b0)
        h3 = _g(g3, a1)
        h4 = _f(g4, c0)
        h5 = _g(g5, b1)
        return Fp12._from_w_coeffs([h0, h1, h2, h3, h4, h5])

    def cyclotomic_pow(self, e: int) -> "Fp12":
        """Exponentiation using cyclotomic squarings (element must be in the
        cyclotomic subgroup). Negative exponents use conjugation (unitary)."""
        if e < 0:
            return self.conjugate().cyclotomic_pow(-e)
        result = Fp12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.cyclotomic_square()
            e >>= 1
        return result


def _compute_frobenius_gammas() -> dict[int, list[Fp2]]:
    """gamma[k][i] = xi^(i*(p^k-1)/6) for every power k in 1..11."""
    out: dict[int, list[Fp2]] = {}
    for k in range(1, 12):
        pk = P**k
        assert (pk - 1) % 6 == 0
        base = XI.pow((pk - 1) // 6)
        gam = [Fp2.one()]
        for _ in range(5):
            gam.append(gam[-1] * base)
        out[k] = gam
    return out


_FROBENIUS_GAMMA = _compute_frobenius_gammas()


# sanity: frobenius really is x -> x^p (checked on a fixed element at import)
def _frobenius_self_test() -> None:
    x = Fp12(
        Fp6(Fp2(3, 5), Fp2(7, 11), Fp2(13, 17)),
        Fp6(Fp2(19, 23), Fp2(29, 31), Fp2(37, 41)),
    )
    assert x.frobenius(1) == x.pow(P)
    assert x.frobenius(2) == x.frobenius(1).frobenius(1)
    assert x.frobenius(3) == x.frobenius(2).frobenius(1)
    assert x.conjugate() == x.frobenius(3).frobenius(3)


_frobenius_self_test()


# ---------------------------------------------------------------------------
# Fr — scalar field
# ---------------------------------------------------------------------------

FR_BYTES = 32


def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return (a * b) % R


def fr_neg(a: int) -> int:
    return (-a) % R


def fr_inv(a: int) -> int:
    if a % R == 0:
        raise ZeroDivisionError("inverse of 0 in Fr")
    return pow(a, -1, R)


def fr_from_bytes_wide(b: bytes) -> int:
    """Reduce arbitrary-length big-endian bytes mod r (for hashing to Fr)."""
    return int.from_bytes(b, "big") % R


def fr_from_seed(domain: bytes, seed: bytes) -> int:
    """Deterministic NONZERO scalar from a seed: 512-bit SHA-256 widening
    reduced into [1, r). The single derivation used by seeded keygen and
    polynomial sampling — keep it in one place."""
    import hashlib

    h = hashlib.sha256(domain + seed).digest()
    h2 = hashlib.sha256(h).digest()
    return (int.from_bytes(h + h2, "big") % (R - 1)) + 1


def fr_to_bytes(a: int) -> bytes:
    return int(a % R).to_bytes(FR_BYTES, "big")


def fr_from_bytes(b: bytes) -> int:
    if len(b) != FR_BYTES:
        raise ValueError(f"Fr element must be {FR_BYTES} bytes")
    v = int.from_bytes(b, "big")
    if v >= R:
        raise ValueError("Fr element not canonical (>= r)")
    return v
