"""Secret-sharing polynomials over Fr and their G1 commitments.

Replaces kyber's share.PriPoly / share.PubPoly / share.PriShare as used by
the reference (key/keys.go:235-244, chain/beacon/node.go:110,
chain/beacon/chain.go:136). Share indices follow kyber's convention:
share i evaluates the polynomial at x = i + 1 (x = 0 is the secret).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from .fields import R, fr_inv
from .curves import PointG1, PointG2, _JacobianPoint


@dataclass(frozen=True)
class PriShare:
    """Private share: (index, scalar). kyber share.PriShare analogue."""

    index: int
    value: int  # in Fr

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        h.update(self.index.to_bytes(2, "big"))
        h.update(self.value.to_bytes(32, "big"))
        return h.digest()


@dataclass(frozen=True)
class PubShare:
    """Public share: (index, group point)."""

    index: int
    value: _JacobianPoint


def _x_of(index: int) -> int:
    """Evaluation abscissa for a share index (kyber: x = index + 1)."""
    return index + 1


class PriPoly:
    """Secret polynomial f of degree t-1 over Fr; f(0) is the secret."""

    def __init__(self, coeffs: list[int]):
        if not coeffs:
            raise ValueError("polynomial needs at least one coefficient")
        self.coeffs = [c % R for c in coeffs]

    @staticmethod
    def random(t: int, seed: bytes | None = None) -> "PriPoly":
        """Degree t-1 polynomial. With seed, deterministic (tests/DKG
        derivation); without, from OS entropy."""
        import secrets

        from .fields import fr_from_seed

        coeffs = []
        for i in range(t):
            if seed is None:
                coeffs.append(secrets.randbelow(R - 1) + 1)
            else:
                coeffs.append(fr_from_seed(b"dkg-poly", seed + i.to_bytes(4, "big")))
        return PriPoly(coeffs)

    @property
    def threshold(self) -> int:
        return len(self.coeffs)

    def secret(self) -> int:
        return self.coeffs[0]

    def eval(self, index: int) -> PriShare:
        x = _x_of(index)
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % R
        return PriShare(index, acc)

    def eval_many(self, indices: list[int]) -> list[PriShare]:
        """All shares in ONE Horner sweep: per coefficient, one vectorized
        lane update instead of n independent walks — the dealing-side
        batch for large-group DKG (a n=1024 dealer evaluates its poly at
        every receiver index)."""
        xs = [_x_of(i) for i in indices]
        accs = [0] * len(xs)
        for c in reversed(self.coeffs):
            accs = [(a * x + c) % R for a, x in zip(accs, xs)]
        return [PriShare(i, a) for i, a in zip(indices, accs)]

    def shares(self, n: int) -> list[PriShare]:
        return [self.eval(i) for i in range(n)]

    def commit(self, base: _JacobianPoint | None = None) -> "PubPoly":
        if base is None:
            # fixed-base comb for the default G1 generator — same group
            # elements as generator().mul(c), ~8x cheaper per coefficient
            from .curves import g1_comb_mul

            return PubPoly([g1_comb_mul(c) for c in self.coeffs],
                           PointG1.generator())
        return PubPoly([base.mul(c) for c in self.coeffs], base)

    def add(self, other: "PriPoly") -> "PriPoly":
        if self.threshold != other.threshold:
            raise ValueError("threshold mismatch")
        return PriPoly([(a + b) % R for a, b in zip(self.coeffs, other.coeffs)])


class PubPoly:
    """Committed polynomial: commits[k] = [a_k] * base.

    eval(i) gives node i's public key share — the verification key for its
    partial signatures (reference: chain/beacon/node.go:110 PubPoly.Eval).
    """

    def __init__(self, commits: list[_JacobianPoint], base: _JacobianPoint | None = None):
        if not commits:
            raise ValueError("empty commitment list")
        self.commits = commits
        self.base = base if base is not None else PointG1.generator()
        self._eval_cache: dict[int, PubShare] = {}

    @property
    def threshold(self) -> int:
        return len(self.commits)

    def commit(self) -> _JacobianPoint:
        """The commitment to the secret: the distributed public key."""
        return self.commits[0]

    def eval(self, index: int) -> PubShare:
        """Node `index`'s public key share (memoized — the beacon verifies
        against the same handful of indices every round)."""
        cached = self._eval_cache.get(index)
        if cached is not None:
            return cached
        x = _x_of(index)
        acc = type(self.commits[0]).infinity()
        for c in reversed(self.commits):
            acc = acc.mul(x) + c
        share = PubShare(index, acc)
        self._eval_cache[index] = share
        return share

    def eval_many(self, indices: list[int]) -> list[PubShare]:
        """Host multi-point evaluation (memoized per index). This is the
        exact ORACLE for the batched forms of the same computation — the
        device `engine.eval_poly_indices` dispatch and the msm-backed RLC
        binding verdict both route through `crypto.batch.eval_poly_indices`
        / `batch_verify.reshare_bindings_rlc`, which fall back to and are
        bisection-checked against this loop."""
        return [self.eval(i) for i in indices]

    def add(self, other: "PubPoly") -> "PubPoly":
        if self.threshold != other.threshold:
            raise ValueError("threshold mismatch")
        return PubPoly([a + b for a, b in zip(self.commits, other.commits)], self.base)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PubPoly)
            and self.base == other.base
            and self.commits == other.commits
        )


def lagrange_coefficients(indices: list[int]) -> dict[int, int]:
    """lambda_i for interpolation at x=0 over the given share indices."""
    lambdas = {}
    for i in indices:
        xi = _x_of(i)
        num, den = 1, 1
        for j in indices:
            if j == i:
                continue
            xj = _x_of(j)
            num = (num * xj) % R
            den = (den * (xj - xi)) % R
        lambdas[i] = (num * fr_inv(den)) % R
    return lambdas


def recover_secret(shares: list[PriShare], t: int) -> int:
    """Lagrange-interpolate f(0) from >= t private shares."""
    if len(shares) < t:
        raise ValueError(f"need {t} shares, got {len(shares)}")
    use = shares[:t]
    lambdas = lagrange_coefficients([s.index for s in use])
    return sum(s.value * lambdas[s.index] for s in use) % R


def recover_commit(shares: list[PubShare], t: int) -> _JacobianPoint:
    """Lagrange-interpolate the group point at x=0 from >= t public shares.

    This is the signature-recovery hot path (reference:
    chain/beacon/chain.go:136 Scheme.Recover -> Lagrange on G2); the TPU
    engine provides the batched MSM version.
    """
    if len(shares) < t:
        raise ValueError(f"need {t} shares, got {len(shares)}")
    use = shares[:t]
    lambdas = lagrange_coefficients([s.index for s in use])
    cls = type(use[0].value)
    acc = cls.infinity()
    for s in use:
        acc = acc + s.value.mul(lambdas[s.index])
    return acc


def minimum_threshold(n: int) -> int:
    """vss.MinimumT analogue (reference: core/drand_control.go:641,
    key/keys.go:390): floor(n/2) + 1."""
    return n // 2 + 1
