"""Optimal ate pairing on BLS12-381.

e : G1 x G2 -> GT (order-r subgroup of Fp12*). Used for BLS signature
verification — the reference's hot call sites are VerifyPartial /
VerifyRecovered (/root/reference/chain/beacon/node.go:112,
/root/reference/chain/beacon/chain.go:141, /root/reference/chain/beacon.go:87).

Design notes:
- The twist untwisting constants are PROBED at import (try both M/D-twist
  embeddings, keep the one that lands on E(Fp12)), so no hard-coded
  twist-type assumption can be silently wrong.
- ``multi_pairing`` shares the Miller-loop squarings and the final
  exponentiation across all pairs — this is the product-of-pairings
  optimization the TPU batch verifier mirrors (SURVEY.md §5 long-context
  analogue: chain catch-up as one batched multi-pairing).
- The fast final exponentiation uses the standard Hayashida et al. chain
  (which natively produces the CUBE of the canonical pairing) followed by a
  3^-1 mod r correction, so ``pairing``/``multi_pairing`` return the
  canonical optimal-ate value. ``pairing_check`` skips the correction.
"""

from __future__ import annotations

from .fields import P, R, X_BLS, XI, Fp2, Fp6, Fp12
from .curves import PointG1, PointG2

# Lightweight op counters (plain ints — read/reset by tests and bench).
# The RLC batch verifier's acceptance criterion is "one 2-pairing product
# check for a whole all-valid span"; these make that claim checkable
# without monkeypatching the hot path.
N_PRODUCT_CHECKS = 0   # multi_pairing invocations that ran a Miller loop
N_MILLER_PAIRS = 0     # total (P, Q) pairs fed through Miller loops


# ---------------------------------------------------------------------------
# Monomials c * w^k  (c in Fp2, 0 <= k < 6) — sparse Fp12 elements used for
# the untwist map and line construction.
# ---------------------------------------------------------------------------

class _Mono:
    __slots__ = ("k", "c")

    def __init__(self, k: int, c: Fp2):
        # normalize: w^6 = xi
        q, k = divmod(k, 6)
        if q:
            c = c * XI.pow(q)
        self.k = k
        self.c = c

    def __mul__(self, o: "_Mono") -> "_Mono":
        return _Mono(self.k + o.k, self.c * o.c)

    def inverse(self) -> "_Mono":
        # (c w^k)^-1 = c^-1 w^-k = c^-1 xi^-1 w^(6-k)
        if self.k == 0:
            return _Mono(0, self.c.inverse())
        return _Mono(6 - self.k, (self.c * XI).inverse())

    def apply(self, x: Fp2) -> Fp12:
        """Return (x * c) placed in w-slot k as a full Fp12 element."""
        coeffs = [Fp2.zero()] * 6
        coeffs[self.k] = x * self.c
        return Fp12._from_w_coeffs(coeffs)


def _emb(x: Fp2) -> Fp12:
    return Fp12.from_fp2(x)


def _probe_untwist() -> tuple[_Mono, _Mono]:
    """Find the untwist map (x, y) -> (x*WX, y*WY) from the twist
    E'(Fp2): y^2 = x^3 + 4(1+u) onto E(Fp12): y^2 = x^3 + 4.

    Tries both twist orientations; asserts exactly one works.
    """
    gx, gy = PointG2.GENERATOR_AFFINE
    candidates = [
        (_Mono(2, Fp2.one()), _Mono(3, Fp2.one())),          # D-type: (x w^2, y w^3)
        (_Mono(2, Fp2.one()).inverse(), _Mono(3, Fp2.one()).inverse()),  # M-type
    ]
    four = _emb(Fp2(4, 0))
    found = []
    for wx, wy in candidates:
        X = wx.apply(gx)
        Y = wy.apply(gy)
        if Y * Y == X * X * X + four:
            found.append((wx, wy))
    assert len(found) == 1, f"untwist probe found {len(found)} candidates"
    return found[0]


_WX, _WY = _probe_untwist()
# Line-construction constants: lambda_12 = K_LAMBDA.apply(lambda_2), etc.
_K_LAMBDA = _WX * _WX * _WY.inverse()
_K_LX = _K_LAMBDA * _WX


def untwist(q: PointG2) -> tuple[Fp12, Fp12]:
    """Affine coordinates of q mapped onto E(Fp12)."""
    x, y = q.to_affine()
    return _WX.apply(x), _WY.apply(y)


# ---------------------------------------------------------------------------
# Miller loop
# ---------------------------------------------------------------------------

_MILLER_BITS = bin(abs(X_BLS))[3:]  # MSB is implicit starting value


def _line_value(t: tuple[Fp2, Fp2], lam2: Fp2, p_aff: tuple[int, int]) -> Fp12:
    """Value at the embedded G1 point of the line through untwist(t) with
    untwisted slope lambda = K_LAMBDA(lam2).

    l = y_P - y_T' - lambda * (x_P - x_T')
    """
    xt, yt = t
    xp, yp = p_aff
    out = _emb(Fp2(yp, 0)) - _WY.apply(yt) - _K_LAMBDA.apply(lam2.mul_scalar(xp)) \
        + _K_LX.apply(lam2 * xt)
    return out


def miller_loop(pairs: list[tuple[PointG1, PointG2]]) -> Fp12:
    """Shared-squaring Miller loop over |x| for a list of (P, Q) pairs.

    Points must not be at infinity (callers filter; pairing() handles it).
    One group of :func:`miller_loop_groups` — a single Miller-loop
    implementation serves both the plain and the grouped product checks.
    """
    return miller_loop_groups([pairs])[0]


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------

_HARD_EXP = (X_BLS - 1) ** 2 * (X_BLS + P) * (X_BLS**2 + P**2 - 1) + 3
assert _HARD_EXP == 3 * ((P**4 - P**2 + 1) // R)
# The Hayashida chain computes the CUBE of the canonical ate pairing.
# GT has order r and gcd(3, r) = 1, so cubing is invertible: raising the
# cubed value to 3^-1 mod r recovers the canonical pairing.
_INV3_MOD_R = pow(3, -1, R)


def final_exponentiation_slow(f: Fp12, canonical: bool = True) -> Fp12:
    """Obviously-correct path: easy part then one generic pow. Golden
    reference for the fast chain below."""
    f1 = f.conjugate() * f.inverse()        # f^(p^6 - 1)
    f2 = f1.frobenius(2) * f1               # ^(p^2 + 1) — now cyclotomic
    exp = (P**4 - P**2 + 1) // R if canonical else _HARD_EXP
    return f2.pow(exp)


def final_exponentiation(f: Fp12, canonical: bool = True) -> Fp12:
    """Fast path: easy part + Hayashida et al. chain
    m^((x-1)^2 (x+p) (x^2+p^2-1)) * m^3, all in the cyclotomic subgroup.

    With canonical=True (default) the cube is corrected so the result is the
    canonical optimal-ate pairing value, interoperable with other BLS12-381
    implementations (matters for GT consumers like timelock IBE). Equality
    checks (pairing_check) skip the correction — cubing preserves equality.
    """
    f1 = f.conjugate() * f.inverse()
    m = f1.frobenius(2) * f1
    a = m.cyclotomic_pow(X_BLS - 1)
    a = a.cyclotomic_pow(X_BLS - 1)
    a = a.cyclotomic_pow(X_BLS) * a.frobenius(1)            # ^(x+p)
    a = a.cyclotomic_pow(X_BLS).cyclotomic_pow(X_BLS) \
        * a.frobenius(2) * a.conjugate()                     # ^(x^2+p^2-1)
    cubed = a * m * m.cyclotomic_square()                    # * m^3
    return cubed.cyclotomic_pow(_INV3_MOD_R) if canonical else cubed


def multi_pairing(pairs: list[tuple[PointG1, PointG2]], canonical: bool = True) -> Fp12:
    """prod_i e(P_i, Q_i) with shared Miller squarings and one final exp."""
    live = [(p, q) for (p, q) in pairs if not p.is_infinity() and not q.is_infinity()]
    if not live:
        return Fp12.one()
    global N_PRODUCT_CHECKS, N_MILLER_PAIRS
    N_PRODUCT_CHECKS += 1
    N_MILLER_PAIRS += len(live)
    return final_exponentiation(miller_loop(live), canonical=canonical)


def pairing(p: PointG1, q: PointG2) -> Fp12:
    """The canonical optimal-ate pairing e(P, Q)."""
    return multi_pairing([(p, q)])


def miller_loop_groups(groups: list[list[tuple[PointG1, PointG2]]]) -> list[Fp12]:
    """Per-group Miller values in ONE pass over the |x| bits: line/T
    updates are per-pair exactly as in :func:`miller_loop`, but each
    group keeps its own accumulator (squared per bit), so one invocation
    yields independent products. Points must not be at infinity (callers
    filter). Empty groups yield Fp12.one()."""
    flat = [(g, p, q) for g, grp in enumerate(groups) for (p, q) in grp]
    p_affs, q_affs, gids = [], [], []
    for g, pt, q in flat:
        xa, ya = pt.to_affine()
        p_affs.append((xa.v, ya.v))
        q_affs.append(q.to_affine())
        gids.append(g)

    ts = list(q_affs)
    fs = [Fp12.one()] * len(groups)
    three = 3
    for bit in _MILLER_BITS:
        fs = [f.square() for f in fs]
        for i, g in enumerate(gids):
            xt, yt = ts[i]
            lam2 = xt.square().mul_scalar(three) * (yt + yt).inverse()
            fs[g] = fs[g] * _line_value(ts[i], lam2, p_affs[i])
            x3 = lam2.square() - xt - xt
            y3 = lam2 * (xt - x3) - yt
            ts[i] = (x3, y3)
        if bit == "1":
            for i, g in enumerate(gids):
                xt, yt = ts[i]
                xq, yq = q_affs[i]
                lam2 = (yq - yt) * (xq - xt).inverse()
                fs[g] = fs[g] * _line_value(ts[i], lam2, p_affs[i])
                x3 = lam2.square() - xt - xq
                y3 = lam2 * (xt - x3) - yt
                ts[i] = (x3, y3)
    return [f.conjugate() for f in fs]


def pairing_check_groups(groups: list[list[tuple[PointG1, PointG2]]]
                         ) -> list[bool]:
    """Independent product checks (prod e(P_i, Q_i) == 1 per group)
    decided in ONE grouped Miller pass — the batched-bisection primitive:
    a failed RLC span verifies BOTH halves as one 4-pairing dispatch
    instead of two sequential 2-pairing checks. Counts as one product
    check at the meter (one invocation; the per-pair Miller work is what
    N_MILLER_PAIRS tracks). A group whose pairs are all infinity-filtered
    is vacuously True, matching pairing_check on the same input."""
    live_groups = [[(p, q) for (p, q) in grp
                    if not p.is_infinity() and not q.is_infinity()]
                   for grp in groups]
    if not any(live_groups):
        return [True] * len(groups)
    global N_PRODUCT_CHECKS, N_MILLER_PAIRS
    N_PRODUCT_CHECKS += 1
    N_MILLER_PAIRS += sum(len(g) for g in live_groups)
    fs = miller_loop_groups(live_groups)
    return [final_exponentiation(f, canonical=False).is_one()
            if grp else True
            for f, grp in zip(fs, live_groups)]


def pairing_check(pairs: list[tuple[PointG1, PointG2]]) -> bool:
    """True iff prod e(P_i, Q_i) == 1 in GT (skips the cube correction —
    equality with 1 is invariant under cubing)."""
    return multi_pairing(pairs, canonical=False).is_one()
