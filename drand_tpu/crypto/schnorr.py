"""Schnorr signatures over G1.

The reference's DKGAuthScheme (key/curve.go:38): authenticates DKG broadcast
packets (core/broadcast.go via dkg.VerifyPacketSignature) and the leader's
signed group file (core/drand_control.go:714, core/group_setup.go:329).

sig = R_bytes || s_bytes with R = k*G1, s = k + H(R || pub || msg)*sk —
kyber sign/schnorr's layout: the challenge is SHA-512 over
(R.MarshalBinary() || pub.MarshalBinary() || msg) reduced big-endian
into Fr (kyber schnorr.go hash() with the bls12381 suite's mod-r
scalar), so DKG packet and group-push signatures verify across a
reference<->drand-tpu boundary. Kyber sources are absent from this
image; the layout is reproduced from the documented schnorr.go and
pinned by vectors in tests/test_schnorr.py.
"""

from __future__ import annotations

import hashlib
import hmac

from .fields import R, fr_from_bytes_wide
from .curves import PointG1

SIG_SIZE = PointG1.COMPRESSED_SIZE + 32  # 80 bytes


def _challenge(big_r: PointG1, pub: PointG1, msg: bytes) -> int:
    # kyber schnorr.go hash(): sha512(R || public || msg), scalar set
    # big-endian reduced mod r
    h = hashlib.sha512()
    h.update(big_r.to_bytes())
    h.update(pub.to_bytes())
    h.update(msg)
    return int.from_bytes(h.digest(), "big") % R


def _nonce(sk: int, msg: bytes) -> int:
    """Deterministic nonce (RFC 6979 flavour): HMAC(sk, msg) into Fr.
    Avoids catastrophic nonce reuse without an RNG dependency."""
    key = sk.to_bytes(32, "big")
    out = hmac.new(key, b"drand-tpu-schnorr-nonce" + msg, hashlib.sha256).digest()
    out2 = hmac.new(key, out + msg, hashlib.sha256).digest()
    k = fr_from_bytes_wide(out + out2)
    return k if k != 0 else 1


def sign(sk: int, msg: bytes) -> bytes:
    k = _nonce(sk, msg)
    big_r = PointG1.generator().mul(k)
    pub = PointG1.generator().mul(sk)
    c = _challenge(big_r, pub, msg)
    s = (k + c * sk) % R
    return big_r.to_bytes() + s.to_bytes(32, "big")


def verify(pub: PointG1, msg: bytes, sig: bytes) -> bool:
    if len(sig) != SIG_SIZE:
        return False
    try:
        big_r = PointG1.from_bytes(sig[: PointG1.COMPRESSED_SIZE])
    except ValueError:
        return False
    s = int.from_bytes(sig[PointG1.COMPRESSED_SIZE :], "big")
    if s >= R:
        return False
    c = _challenge(big_r, pub, msg)
    # s*G == R + c*pub
    return PointG1.generator().mul(s) == big_r + pub.mul(c)
