"""Plain BLS signatures on G2 with G1 public keys.

This is the reference's AuthScheme (key/curve.go:34, sign.NewSchemeOnG2):
identity self-signatures (key/keys.go:60-88) and group-transport auth.
Verification equation: e(-G1, sig) * e(pub, H(msg)) == 1.
"""

from __future__ import annotations

import secrets

from .fields import R, fr_from_seed
from .curves import PointG1, PointG2
from .hash_to_curve import DEFAULT_DST_G2, hash_to_g2
from .pairing import pairing_check


def keygen(seed: bytes | None = None) -> tuple[int, PointG1]:
    """(private scalar, public key = sk*G1)."""
    if seed is None:
        sk = secrets.randbelow(R - 1) + 1
    else:
        sk = fr_from_seed(b"drand-tpu-keygen", seed)
    return sk, PointG1.generator().mul(sk)


def sign(sk: int, msg: bytes, dst: bytes = DEFAULT_DST_G2) -> bytes:
    """sig = sk * H(msg) on G2, 96-byte compressed."""
    return hash_to_g2(msg, dst).mul(sk).to_bytes()


def verify(pub: PointG1, msg: bytes, sig: bytes, dst: bytes = DEFAULT_DST_G2) -> bool:
    """Pairing check; False on any malformed input (never raises on bad
    signatures — ingress data is untrusted)."""
    try:
        s = PointG2.from_bytes(sig)
    except ValueError:
        return False
    if s.is_infinity() or pub.is_infinity():
        return False
    return pairing_check([(-PointG1.generator(), s), (pub, hash_to_g2(msg, dst))])
