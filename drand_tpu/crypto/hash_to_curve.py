"""Hash-to-G2 for BLS signatures (message side of tbls.Sign/Verify).

Pipeline (RFC 9380 shape): expand_message_xmd(SHA-256) -> hash_to_field(Fp2)
-> simplified-SWU onto the 3-isogenous curve E' -> 3-isogeny -> clear cofactor.

The default domain separation tag matches the drand fork's G2 signature suite
(kyber-bls12381's BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_; see
/root/reference/key/curve.go:27-31 — signatures on G2).

Instead of hard-coding the 3-isogeny's 16 Fp2 rational-map coefficients, the
isogeny is DERIVED at import with Vélu's formulas: the kernel x-coordinate is
a root of the 3-division polynomial of E', found by polynomial-GCD root
extraction over Fp2, and the codomain is matched to E2 (y^2 = x^3 + 4(1+u))
exactly. The RFC-published map is then pinned out of the derived family by
matching the RFC 9380 J.10.1 test vector (see ``_select_isogeny`` /
``RFC_CONFORMANT``), making the output bit-for-bit interoperable with
blst/kyber/real drand chains. Import fails loudly if any step does not land
on E2, so the map cannot be silently wrong.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from .fields import P, Fp2, XI, fp_inv
from .curves import PointG2

# drand's G2 signature suite DST (kyber-bls12381)
DEFAULT_DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"

# SSWU target curve E': y^2 = x^3 + A'x + B' over Fp2, 3-isogenous to E2
_A_PRIME = Fp2(0, 240)
_B_PRIME = Fp2(1012, 1012)
_Z_SSWU = Fp2(-2, -1)  # Z = -(2 + u)


# ---------------------------------------------------------------------------
# expand_message_xmd + hash_to_field (RFC 9380 §5)
# ---------------------------------------------------------------------------

_H_BLOCK = 64   # SHA-256 block size
_H_OUT = 32     # SHA-256 output size
_L_FIELD = 64   # security-padded bytes per field element


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _H_OUT - 1) // _H_OUT
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _H_BLOCK
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = bi
    for i in range(2, ell + 1):
        xored = bytes(a ^ b for a, b in zip(b0, bi))
        bi = hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest()
        out += bi
    return out[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int) -> list[Fp2]:
    n = count * 2 * _L_FIELD
    uniform = expand_message_xmd(msg, dst, n)
    out = []
    for i in range(count):
        off = i * 2 * _L_FIELD
        c0 = int.from_bytes(uniform[off : off + _L_FIELD], "big") % P
        c1 = int.from_bytes(uniform[off + _L_FIELD : off + 2 * _L_FIELD], "big") % P
        out.append(Fp2(c0, c1))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU map onto E' (RFC 9380 §6.6.2)
# ---------------------------------------------------------------------------

def _g_prime(x: Fp2) -> Fp2:
    return x.square() * x + _A_PRIME * x + _B_PRIME


_MINUS_B_OVER_A = -(_B_PRIME * _A_PRIME.inverse())
_B_OVER_ZA = _B_PRIME * (_Z_SSWU * _A_PRIME).inverse()


def map_to_curve_sswu(u: Fp2) -> tuple[Fp2, Fp2]:
    """SSWU: field element -> affine point on E'."""
    zu2 = _Z_SSWU * u.square()
    tv = zu2.square() + zu2
    if tv.is_zero():
        x1 = _B_OVER_ZA
    else:
        x1 = _MINUS_B_OVER_A * (Fp2.one() + tv.inverse())
    gx1 = _g_prime(x1)
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = zu2 * x1
        gx2 = _g_prime(x2)
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither branch square — impossible"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


# ---------------------------------------------------------------------------
# 3-isogeny E' -> E2, derived with Vélu's formulas at import
# ---------------------------------------------------------------------------
# Polynomial helpers over Fp2 (dense coefficient lists, low-to-high degree).

def _poly_trim(a: list[Fp2]) -> list[Fp2]:
    while a and a[-1].is_zero():
        a.pop()
    return a


def _poly_mulmod(a: list[Fp2], b: list[Fp2], mod: list[Fp2]) -> list[Fp2]:
    out = [Fp2.zero()] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai.is_zero():
            continue
        for j, bj in enumerate(b):
            out[i + j] = out[i + j] + ai * bj
    return _poly_mod(out, mod)


def _poly_mod(a: list[Fp2], mod: list[Fp2]) -> list[Fp2]:
    a = _poly_trim(list(a))
    dm = len(mod) - 1
    inv_lead = mod[-1].inverse()
    while len(a) - 1 >= dm:
        coef = a[-1] * inv_lead
        shift = len(a) - 1 - dm
        for i, mi in enumerate(mod):
            a[shift + i] = a[shift + i] - coef * mi
        a = _poly_trim(a)
        if not a:
            break
    return a


def _poly_gcd(a: list[Fp2], b: list[Fp2]) -> list[Fp2]:
    a, b = _poly_trim(list(a)), _poly_trim(list(b))
    while b:
        a, b = b, _poly_mod(a, b)
    if a:
        inv_lead = a[-1].inverse()
        a = [c * inv_lead for c in a]
    return a


def _poly_powmod_x(e: int, mod: list[Fp2]) -> list[Fp2]:
    """x^e mod `mod`."""
    return _poly_powmod_poly([Fp2.zero(), Fp2.one()], e, mod)


def _all_roots_fp2(poly: list[Fp2]) -> list[Fp2]:
    """All distinct roots in Fp2 of `poly`, via x^(p^2)-x gcd and
    equal-degree splitting."""
    q = P * P
    xq = _poly_powmod_x(q, poly)
    diff = list(xq)
    while len(diff) < 2:
        diff.append(Fp2.zero())
    diff[1] = diff[1] - Fp2.one()
    lin = _poly_gcd(poly, diff)  # product of distinct linear factors
    if len(lin) < 2:
        return []

    roots: list[Fp2] = []

    def _split(f: list[Fp2], salt: int = 1) -> None:
        if len(f) == 2:
            roots.append(-(f[0] * f[1].inverse()))
            return
        while True:
            assert salt < 256, "root splitting failed to converge"
            shifted = _poly_mod([Fp2(salt, salt % 7), Fp2.one()], f)
            powed = list(_poly_powmod_poly(shifted, (q - 1) // 2, f))
            if not powed:
                powed = [Fp2.zero()]
            powed[0] = powed[0] - Fp2.one()
            g = _poly_gcd(f, _poly_trim(powed))
            if 2 <= len(g) < len(f):
                h = _poly_divide_exact(f, g)
                _split(g, salt + 1)
                if len(h) >= 2:
                    _split(h, salt + 1)
                return
            salt += 1

    _split(lin)
    return roots


def _poly_divide_exact(a: list[Fp2], b: list[Fp2]) -> list[Fp2]:
    """Exact polynomial division a / b (remainder must be zero)."""
    a = _poly_trim(list(a))
    out = [Fp2.zero()] * (len(a) - len(b) + 1)
    inv_lead = b[-1].inverse()
    while len(a) >= len(b):
        coef = a[-1] * inv_lead
        shift = len(a) - len(b)
        out[shift] = coef
        for i, bi in enumerate(b):
            a[shift + i] = a[shift + i] - coef * bi
        a = _poly_trim(a)
        if not a:
            break
    assert not a, "non-exact polynomial division"
    return out


def _poly_powmod_poly(base: list[Fp2], e: int, mod: list[Fp2]) -> list[Fp2]:
    result = [Fp2.one()]
    b = _poly_mod(list(base), mod)
    while e:
        if e & 1:
            result = _poly_mulmod(result, b, mod)
        b = _poly_mulmod(b, b, mod)
        e >>= 1
    return result


def _derive_isogeny_candidates():
    """Vélu 3-isogenies from E' with codomain matched onto E2.

    The RFC 9380 published isogeny is one member of this family (it can
    differ from an arbitrary Vélu derivation only by the choice of rational
    kernel and composition with an automorphism of E2, i.e. the choice of
    sixth root below). ``_select_isogeny`` picks the RFC member by matching
    the RFC J.10.1 test vector.

    Returns a list of (x0, v, u, c2, c3): kernel x-coord, Vélu sums, and the
    isomorphism scaling (x,y) -> (c2*x, c3*y) onto E2.
    """
    A, B = _A_PRIME, _B_PRIME
    # 3-division polynomial: psi3 = 3x^4 + 6A x^2 + 12B x - A^2
    psi3 = [
        -(A.square()),
        B.mul_scalar(12),
        A.mul_scalar(6),
        Fp2.zero(),
        Fp2(3, 0),
    ]
    candidates = []
    for x0 in _all_roots_fp2(psi3):
        # Vélu sums for the order-3 kernel {O, (x0, ±y0)} — only x0 and
        # y0^2 = g'(x0) appear, so the kernel need not be point-rational.
        gx = x0.square().mul_scalar(3) + A           # 3x0^2 + A
        v = gx.mul_scalar(2)                          # sum of v_Q
        uu = _g_prime(x0).mul_scalar(4)               # u_Q = 4 y0^2
        w = uu + x0 * v
        A2 = A - v.mul_scalar(5)
        B2 = B - w.mul_scalar(7)
        if not A2.is_zero():
            continue  # codomain not of j-invariant-0 shape: wrong kernel
        # isomorphism (x,y)->(c^2 x, c^3 y) needs B2 * c^6 = 4(1+u)
        ratio = Fp2(4, 4) * B2.inverse()
        for c2, c3 in _all_sixth_power_pairs(ratio):
            candidates.append((x0, v, uu, c2, c3))
    assert candidates, "no Vélu isogeny onto E2 found"
    return candidates


def _all_sixth_power_pairs(ratio: Fp2):
    """All distinct (c^2, c^3) with c^6 = ratio, c in Fp2."""
    s = ratio.sqrt()
    if s is None:
        return []
    base = None
    for sign in (s, -s):
        c = _cube_root_fp2(sign)
        if c is not None and c.pow(6) == ratio:
            base = c
            break
    if base is None:
        return []
    out = []
    seen = set()
    for zeta in _sixth_roots_of_unity():
        c = base * zeta
        key = (c.square(), c.square() * c)
        tag = (key[0].c0, key[0].c1, key[1].c0, key[1].c1)
        if tag not in seen:
            seen.add(tag)
            out.append(key)
    return out


def _sixth_roots_of_unity() -> list[Fp2]:
    one = Fp2.one()
    roots = [one, -one]
    w = _cube_root_of_unity()
    if w is not None:
        roots += [w, -w, w.square(), -(w.square())]
    return roots


def _cube_root_of_unity():
    s = Fp2(-3, 0).sqrt()
    if s is None:
        return None
    half = Fp2(fp_inv(2), 0)
    w = (Fp2(-1, 0) + s) * half
    assert w.pow(3) == Fp2.one() and w != Fp2.one()
    return w


def _cube_root_fp2(a: Fp2):
    """A cube root of a in Fp2*, or None if a is not a cube."""
    q = P * P
    m, k = q - 1, 0
    while m % 3 == 0:
        m //= 3
        k += 1
    if a.pow((q - 1) // 3) != Fp2.one():
        return None
    # base candidate: c = a^e with 3e ≡ 1 (mod m); off by 3^k-torsion only
    c = a.pow(pow(3, -1, m))
    # generator of the 3^k-torsion subgroup: z = g^m for a non-cube g
    g = Fp2(2, 1)
    while g.pow((q - 1) // 3) == Fp2.one():
        g = g + Fp2(1, 1)
    z = g.pow(m)
    zj = Fp2.one()
    for _ in range(3**k):
        cand = c * zj
        if cand.pow(3) == a:
            return cand
        zj = zj * z
    return None


# ---------------------------------------------------------------------------
# Isogeny selection: pin the RFC 9380 member of the derived family by
# matching the published BLS12381G2_XMD:SHA-256_SSWU_RO_ test vector
# (RFC 9380 J.10.1, empty message). If the vector matches, the map is
# bit-for-bit interoperable with blst/kyber/real drand chains; if no
# candidate matches (e.g. this build's recollection of the vector is wrong),
# fall back to the first valid candidate — still a deterministic, uniform
# hash, just not externally interoperable. RFC_CONFORMANT records which.
# ---------------------------------------------------------------------------

# RFC 9380 fast cofactor multiplier h_eff for G2 (validated at import below;
# discarded in favor of the plain curve cofactor H2 if invalid).
_H_EFF_RFC = int(
    "bc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff031508ffe1329c2f178731db956d82"
    "bf015d1212b02ec0ec69d7477c1ae954cbc06689f6a359894c0adebbf6b4e8020005aaa95551",
    16,
)

_RFC_J10_1_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
_RFC_J10_1_PX = Fp2(
    0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
    0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D,
)
_RFC_J10_1_PY = Fp2(
    0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
    0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6,
)


def _iso_apply(params, x: Fp2, y: Fp2) -> tuple[Fp2, Fp2]:
    x0, v, u, c2, c3 = params
    d = x - x0
    dinv = d.inverse()
    dinv2 = dinv.square()
    X = x + v * dinv + u * dinv2
    Y = y * (Fp2.one() - v * dinv2 - (u + u) * dinv2 * dinv)
    return c2 * X, c3 * Y


def _map_with(params, u: Fp2) -> PointG2:
    x, y = map_to_curve_sswu(u)
    X, Y = _iso_apply(params, x, y)
    return PointG2.from_affine(X, Y)


def _validate_h_eff() -> list[int]:
    """Cofactor multipliers to try, RFC h_eff first if it really clears."""
    from .fields import R as _R
    from .curves import H2

    probe = _map_with(_derive_isogeny_candidates()[0], Fp2(7, 13))
    out = []
    q = probe.mul(_H_EFF_RFC)
    if not q.is_infinity() and q.mul(_R).is_infinity():
        out.append(_H_EFF_RFC)
    out.append(H2)
    return out


def _select_isogeny():
    candidates = _derive_isogeny_candidates()
    h_options = _validate_h_eff()
    u0, u1 = hash_to_field_fp2(b"", _RFC_J10_1_DST, 2)
    for params in candidates:
        q = _map_with(params, u0) + _map_with(params, u1)
        for h in h_options:
            p = q.mul(h)
            if p.is_infinity():
                continue
            px, py = p.to_affine()
            if px == _RFC_J10_1_PX and py == _RFC_J10_1_PY:
                return params, h, True
    return candidates[0], h_options[0], False


_ISO_PARAMS, _H_CLEAR, RFC_CONFORMANT = _select_isogeny()


def _iso3(x: Fp2, y: Fp2) -> tuple[Fp2, Fp2]:
    """Apply the selected 3-isogeny + isomorphism: E' -> E2."""
    return _iso_apply(_ISO_PARAMS, x, y)


def _iso_self_test() -> None:
    """The composed map must land on E2 for arbitrary inputs."""
    b2 = Fp2(4, 4)
    for seed in (1, 2, 3):
        u = Fp2(seed * 1234567, seed * 7654321)
        x, y = map_to_curve_sswu(u)
        assert y.square() == _g_prime(x), "SSWU point off E'"
        X, Y = _iso3(x, y)
        assert Y.square() == X.square() * X + b2, "isogeny image off E2"


_iso_self_test()


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def map_to_curve_g2(u: Fp2) -> PointG2:
    x, y = map_to_curve_sswu(u)
    X, Y = _iso3(x, y)
    return PointG2.from_affine(X, Y)


# Keyed (msg, dst) memo for hash_to_g2. In one beacon round every node
# hashes the same two messages (V1 and V2) once per sign and once per
# incoming partial — sign_partial, t verify_partials, recover and
# verify_recovered of the same round all reuse one computed point. A
# hand-rolled LRU (not functools.lru_cache) so hit/miss counts are
# observable: they feed the hash_to_g2_cache_requests metric and tell
# an operator whether the per-round memo actually amortizes.
_H2C_MAXSIZE = 1024
_H2C_CACHE: "OrderedDict[tuple[bytes, bytes], PointG2]" = OrderedDict()
# functools.lru_cache is internally locked; this LRU must be too (a
# threaded embedder's concurrent hit + evicting miss would otherwise
# race move_to_end against popitem). The lock only covers dict ops —
# the ~30 ms hash-to-curve compute happens outside it.
_H2C_LOCK = threading.Lock()
_h2c_hits = 0
_h2c_misses = 0


def h2c_cache_info() -> dict:
    """Hit/miss/size counters of the hash_to_g2 memo (process lifetime)."""
    return {"hits": _h2c_hits, "misses": _h2c_misses,
            "size": len(_H2C_CACHE), "maxsize": _H2C_MAXSIZE}


def h2c_cache_clear() -> None:
    with _H2C_LOCK:
        _H2C_CACHE.clear()


def hash_to_g2(msg: bytes, dst: bytes = DEFAULT_DST_G2) -> PointG2:
    """Full hash_to_curve: uniform, deterministic map into the r-order
    subgroup of G2. This is H(m) in every signature equation.

    Memoized per (msg, dst) — see the LRU note above; hit/miss counters
    are exported as hash_to_g2_cache_requests{result}.
    """
    global _h2c_hits, _h2c_misses
    # metrics import is lazy (crypto/batch.py idiom) and the label
    # values are literal at the call sites so tools/check_metrics.py
    # can lint them against the catalogue
    from .. import metrics

    key = (msg, dst)
    with _H2C_LOCK:
        got = _H2C_CACHE.get(key)
        if got is not None:
            _H2C_CACHE.move_to_end(key)
            _h2c_hits += 1
    if got is not None:
        metrics.H2C_CACHE_REQUESTS.labels(result="hit").inc()
        return got
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    q = map_to_curve_g2(u0) + map_to_curve_g2(u1)
    pt = q.mul(_H_CLEAR)
    with _H2C_LOCK:
        _H2C_CACHE[key] = pt
        if len(_H2C_CACHE) > _H2C_MAXSIZE:
            _H2C_CACHE.popitem(last=False)
        _h2c_misses += 1
    metrics.H2C_CACHE_REQUESTS.labels(result="miss").inc()
    return pt
