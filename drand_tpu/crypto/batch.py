"""Batch crypto dispatch: device engine when configured, host otherwise.

Protocol code (the aggregator, the syncer, the verifying client) calls this
module instead of choosing an implementation — mirroring how the reference
gates all crypto behind the ``Scheme`` globals (key/curve.go:31), which is
exactly the boundary BASELINE.json names as the TPU swap point.

Modes (env ``DRAND_TPU_ENGINE`` or :func:`configure`):
- ``auto`` (default): use the device engine for batches of at least
  ``min_batch`` items; small/latency-sensitive calls stay on the host
  (per-round work is a handful of pairings — dispatch overhead would
  dominate; the device shines on catchup/recovery batches).
- ``device``: always use the device engine (tests force this).
- ``host``: never touch the device.

The device engine is created lazily (it imports jax and compiles on first
use) and any engine failure falls back to the host path — the host
implementation is the semantics oracle.

Host batches of >= max(DRAND_TPU_BATCH_VERIFY, 2) items (default on;
``DRAND_TPU_BATCH_VERIFY=0`` reverts to the exact per-item loops) run
the randomized-linear-combination batch verifier
(crypto/batch_verify.py): one 2-pairing product check per all-valid
span instead of one per item, recorded under ``path="host_rlc"`` in
engine_op_seconds so the speedup shows up next to ``host`` and
``device``.
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import deque

import numpy as np

from . import batch_verify, endo, tbls
from .curves import PointG1, g1_comb_mul
from .hash_to_curve import DEFAULT_DST_G2
from .poly import PubPoly

_MODE = os.environ.get("DRAND_TPU_ENGINE", "auto")
_MIN_BATCH = int(os.environ.get("DRAND_TPU_MIN_BATCH", "8"))
_ENGINE = None
# engine() is now reachable from several asyncio.to_thread workers at
# once (aggregator, sync verify, client catch-up) — the lazy singleton
# init must not construct two BatchedEngines (duplicate jit setup,
# discarded KAT verdicts)
_ENGINE_LOCK = threading.Lock()
# warn-once flags + the warm-shape set are written from every dispatch
# context at once (to_thread workers, the DKG's inline loop path) — one
# lock covers them all (tools/analyze threadshare: thread-shared mutable
# state must name its lock)
_STATE_LOCK = threading.Lock()
_FALLBACK_LOGGED = False

# Bounded fallback ledger (ISSUE 6 engine introspection): the last N
# times a dispatch left its preferred tier — device exceptions that fell
# back to host AND wire_rlc combines that returned None (false-reject
# fallback to the per-item graph). /debug/engine serves it so "why did
# this hour's traffic run on host?" is answerable from a running node.
FALLBACK_LEDGER_MAX = 32
_FALLBACK_LEDGER: deque = deque(maxlen=FALLBACK_LEDGER_MAX)
_LEDGER_LOCK = threading.Lock()


def _ledger_note(op: str, path: str, reason: str) -> None:
    from ..obs.trace import current_round

    with _LEDGER_LOCK:
        _FALLBACK_LEDGER.append({
            "op": op, "path": path, "reason": reason[:300],
            "round": current_round(), "time": _time.time()})


def fallback_ledger() -> list[dict]:
    """Newest-last copy of the bounded fallback ledger."""
    with _LEDGER_LOCK:
        return list(_FALLBACK_LEDGER)


def reset_fallback_ledger() -> None:
    with _LEDGER_LOCK:
        _FALLBACK_LEDGER.clear()


_RLC_KNOB_WARNED = False


def _rlc_threshold() -> int | None:
    """Host-path RLC batch-verification policy (DRAND_TPU_BATCH_VERIFY):
    ``0``/``off``/``false`` disables it — the host paths then run the
    exact per-item loops (the escape hatch); on (the default) routes
    host batches of at least max(k, 2) items through
    crypto/batch_verify's one-product-check path, where k is the knob's
    integer value. An UNRECOGNIZED value disables the fast path too
    (warn once): the knob exists to turn the new code OFF, so a
    misspelled disable attempt must never silently leave it on."""
    global _RLC_KNOB_WARNED
    raw = os.environ.get("DRAND_TPU_BATCH_VERIFY", "1").strip().lower()
    if raw in ("1", "on", "true", "yes", ""):
        return 2
    if raw in ("0", "off", "false", "no"):
        return None
    try:
        v = int(raw)
    except ValueError:
        with _STATE_LOCK:
            first = not _RLC_KNOB_WARNED
            _RLC_KNOB_WARNED = True
        if first:
            from ..utils.logging import default_logger

            default_logger("batch").warn(
                "rlc", "bad_knob_value", value=raw,
                effect="batch verification disabled (per-item path)")
        return None
    return None if v <= 0 else max(v, 2)


def _use_rlc(n_items: int) -> bool:
    thr = _rlc_threshold()
    return thr is not None and n_items >= thr


def _note_fallback(op: str, err: Exception) -> None:
    """Auto-mode device failures fall back to host silently except for a
    one-time warning — a persistently broken engine must be visible."""
    global _FALLBACK_LOGGED
    from .. import metrics

    metrics.ENGINE_FALLBACKS.inc()
    _ledger_note(op, "device", f"{type(err).__name__}: {err}")
    with _STATE_LOCK:
        first = not _FALLBACK_LOGGED
        _FALLBACK_LOGGED = True
    if first:
        from ..utils.logging import default_logger

        default_logger("batch").warn(
            "engine", "device_fallback", op=op, err=repr(err))


def _note_device_ok() -> None:
    """A device dispatch succeeded: re-arm the fallback warning so a
    backend that recovers and then breaks AGAIN warns again (the flag
    used to stay set for the life of the process)."""
    global _FALLBACK_LOGGED
    with _STATE_LOCK:
        _FALLBACK_LOGGED = False


def _note_dispatch(op: str) -> None:
    """Count every batched device-engine dispatch (engine_device_batches;
    failures additionally count in engine_device_fallbacks)."""
    from .. import metrics

    metrics.ENGINE_BATCHES.labels(op=op).inc()


# (op, path, batch-bucket) device shapes whose FIRST successful
# dispatch already happened — the first one carries the jit compile
# (seconds to minutes cold) and is split into engine_compile_seconds so
# steady-state engine_op_seconds percentiles stay alertable. Host paths
# never compile; only device-side paths divert.
_COMPILE_PATHS = ("device", "wire_rlc", "wire_rlc_sharded")
_WARM_SHAPES: set[tuple[str, str, str]] = set()


class _timed:
    """Observe engine_op_seconds{op,path,batch} around one dispatch —
    the per-op device-vs-host latency surface. Failed dispatches are
    recorded under ``path="<path>_error"`` so a wedged device's timeout
    samples don't masquerade as real device latency (the host-fallback
    call then contributes its own, separate, sample). Semantic
    rejections — ValueError, this module's documented "no fallback"
    convention (e.g. below-threshold recover) — land under
    ``<path>_invalid`` instead: an instant raise in the _error series
    would page operators alerting on wedged-device signals for a
    routine degraded round.

    The first SUCCESSFUL dispatch of each device (op, path, batch)
    shape observes ``engine_compile_seconds{op}`` instead — that sample
    is dominated by XLA/Mosaic compile + KAT probes, and folding it
    into engine_op_seconds would poison the steady-state p99 every
    process restart. Failed first dispatches stay in the <path>_error
    series (the shape is still cold for the retry)."""

    def __init__(self, op: str, path: str, n: int):
        self._labels = (op, path, n)

    def __enter__(self):
        self._t0 = _time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        op, path, n = self._labels
        dt = _time.perf_counter() - self._t0
        from .. import metrics

        bucket = metrics.batch_bucket(n)
        if exc_type is not None:
            path += ("_invalid" if issubclass(exc_type, ValueError)
                     else "_error")
        elif path in _COMPILE_PATHS:
            key = (op, path, bucket)
            # two workers can land the same cold shape's first dispatch
            # concurrently (sync catch-up + aggregator): exactly ONE
            # may claim the compile sample or both disappear from
            # engine_op_seconds while both feed compile_seconds
            with _STATE_LOCK:
                first = key not in _WARM_SHAPES
                _WARM_SHAPES.add(key)
            if first:
                metrics.ENGINE_COMPILE_SECONDS.labels(op=op).observe(dt)
                return False
        metrics.ENGINE_OP_SECONDS.labels(
            op=op, path=path, batch=bucket).observe(dt)
        return False


def configure(mode: str, min_batch: int | None = None, engine=None) -> None:
    """Override the dispatch policy (tests; daemon config)."""
    global _MODE, _MIN_BATCH, _ENGINE
    if mode not in ("auto", "device", "host"):
        raise ValueError(f"unknown engine mode {mode!r}")
    with _ENGINE_LOCK:
        _MODE = mode
        if min_batch is not None:
            _MIN_BATCH = min_batch
        if engine is not None:
            _ENGINE = engine
    if engine is not None:
        # a replacement engine owns no compiled executables: its first
        # dispatch per shape pays the jit compile again and must land in
        # engine_compile_seconds, not the steady-state series
        with _STATE_LOCK:
            _WARM_SHAPES.clear()


def engine():
    """The lazily-created device engine, or None in host mode.

    Guarded by a hang-safe subprocess probe: creating ``BatchedEngine``
    initializes the jax backend, and under axon that init HANGS (not
    raises) when the TPU tunnel is down — which would freeze the daemon's
    event loop forever. The probe (utils/backend.probe_backend) answers
    "would init hang?" from a killable child, and warms the in-process
    backend on success.

    Event-loop callers never block here: with no verdict yet the probe is
    kicked onto a background thread and this call raises
    ``BackendUnavailable`` — the dispatch wrappers fall back to host
    crypto until the probe lands (the daemon warms it at startup, so in
    practice only the first post-boot rounds are affected). The daemon's
    ``asyncio.to_thread`` workers (aggregator, sync verify, client
    catch-up) count as loop callers: they serve round-deadline work, so
    a tunnel-down probe must not park them for ~90 s — they are
    recognized by the default executor's ``asyncio_`` thread-name
    prefix (CPython names it in ``run_in_executor``). Only true
    synchronous callers (bench, CLI one-shots) block on the probe
    once."""
    global _ENGINE
    if _MODE == "host":
        return None
    if _ENGINE is None:
        import asyncio

        from ..utils.backend import (BackendUnavailable, probe_backend,
                                     probe_backend_bg, probe_state)

        st = probe_state()
        if st is None:
            try:
                asyncio.get_running_loop()
                nonblocking = True
            except RuntimeError:
                nonblocking = threading.current_thread().name.startswith(
                    "asyncio_")
            if nonblocking:
                probe_backend_bg()
                raise BackendUnavailable(
                    "jax backend probe in progress — host crypto fallback "
                    "for this call")
            st = probe_backend()
        if not st:
            raise BackendUnavailable(
                "jax backend probe failed (tunnel down?) — host crypto "
                "fallback in effect for this process")
        from ..ops.engine import BatchedEngine

        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = BatchedEngine()
    return _ENGINE


def engine_mesh_size() -> int:
    """Mesh size of the ALREADY-CREATED engine (1 otherwise) — a cheap
    attribute peek for callers sizing work mesh-divisibly (the syncer's
    verify chunks). Never constructs the engine: backend init can hang
    with the tunnel down, and chunk sizing must stay loop-safe."""
    eng = _ENGINE
    if eng is None or getattr(eng, "mesh", None) is None:
        return 1
    return int(eng.mesh.devices.size)


def _use_device(n_items: int) -> bool:
    if _MODE == "host":
        return False
    if _MODE == "device":
        return True
    return n_items >= _MIN_BATCH


# ---------------------------------------------------------------------------
# Batched operations (device with host fallback)
# ---------------------------------------------------------------------------

def verify_beacons(pubkey: PointG1, beacons,
                   dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
    """Per-beacon dual (V1 + V2-when-present) verification over a span —
    the catchup hot path. Returns a bool array aligned with ``beacons``."""
    from ..chain import beacon as chain_beacon

    if _use_device(len(beacons)):
        try:
            _note_dispatch("verify_beacons")
            eng = engine()
            out = None
            n_checks = sum(1 + (1 if b.is_v2() else 0) for b in beacons)
            if eng.wire_rlc_active(n_checks):
                # wire-RLC tier: device h2c + in-graph lane-MSM collapse
                # the span to ONE 2-pairing row (ops/engine.py); on a
                # mesh engine the combine shards over the batch axis and
                # reports under its own label. A None return is the
                # false-reject-only fallback — re-dispatch below through
                # the per-item wire graph for exact verdicts, under its
                # own path label.
                # literal path labels in each branch — check_metrics
                # lints _timed labels against the documented enum
                if eng.wire_rlc_sharded_active(n_checks):
                    with _timed("verify_beacons", "wire_rlc_sharded",
                                len(beacons)):
                        out = eng.verify_beacons_wire_rlc(pubkey, beacons,
                                                          dst)
                    tier = "wire_rlc_sharded"
                else:
                    with _timed("verify_beacons", "wire_rlc", len(beacons)):
                        out = eng.verify_beacons_wire_rlc(pubkey, beacons,
                                                          dst)
                    tier = "wire_rlc"
                if out is None:
                    _ledger_note(
                        "verify_beacons", tier,
                        "combine rejected (failed combined check / "
                        "untrusted shape) — per-item wire graph decides")
            if out is None:
                with _timed("verify_beacons", "device", len(beacons)):
                    out = eng.verify_beacons(pubkey, beacons, dst,
                                             try_wire_rlc=False)
            _note_device_ok()
            return out
        except Exception as e:  # noqa: BLE001 — host path is the oracle
            if _MODE == "device":
                raise
            _note_fallback("verify_beacons", e)
    if _use_rlc(len(beacons)):
        with _timed("verify_beacons", "host_rlc", len(beacons)):
            return batch_verify.verify_beacons_rlc(pubkey, beacons, dst)
    with _timed("verify_beacons", "host", len(beacons)):
        out = np.zeros(len(beacons), dtype=bool)
        for i, b in enumerate(beacons):
            ok = chain_beacon.verify_beacon(pubkey, b)
            if ok and b.is_v2():
                ok = chain_beacon.verify_beacon_v2(pubkey, b)
            out[i] = ok
        return out


def verify_partials(pub_poly: PubPoly, msg: bytes, partials,
                    dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """Verify many partials of one round at once (Scheme.VerifyPartial,
    chain/beacon/node.go:112, batched)."""
    if _use_device(len(partials)):
        try:
            _note_dispatch("verify_partials")
            with _timed("verify_partials", "device", len(partials)):
                out = engine().verify_partials(pub_poly, msg, partials, dst)
            _note_device_ok()
            return out
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("verify_partials", e)
    if _use_rlc(len(partials)):
        with _timed("verify_partials", "host_rlc", len(partials)):
            return batch_verify.verify_partials_rlc(pub_poly, msg, partials,
                                                    dst)
    with _timed("verify_partials", "host", len(partials)):
        return [tbls.verify_partial(pub_poly, msg, p, dst) for p in partials]


def verify_recovered_many(pubkey: PointG1, pairs,
                          dst: bytes = DEFAULT_DST_G2) -> list[bool]:
    """Batch of (msg, sig) full-signature checks — the aggregator's V1+V2
    re-verification becomes one call (chain/beacon/chain.go:141,159)."""
    if _use_device(len(pairs)):
        try:
            _note_dispatch("verify_recovered_many")
            with _timed("verify_recovered_many", "device", len(pairs)):
                out = engine().verify_sigs(pubkey, pairs, dst)
            _note_device_ok()
            return out
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("verify_recovered_many", e)
    if _use_rlc(len(pairs)):
        with _timed("verify_recovered_many", "host_rlc", len(pairs)):
            return batch_verify.verify_sigs_rlc(pubkey, pairs, dst)
    with _timed("verify_recovered_many", "host", len(pairs)):
        return [tbls.verify_recovered(pubkey, m, s, dst) for m, s in pairs]


def recover(pub_poly: PubPoly, msg: bytes, partials, t: int, n: int,
            dst: bytes = DEFAULT_DST_G2) -> bytes:
    """Lagrange recovery of the full signature (Scheme.Recover,
    chain/beacon/chain.go:136). Device MSM for large thresholds."""
    if _use_device(t):
        try:
            _note_dispatch("recover")
            with _timed("recover", "device", t):
                out = engine().recover(pub_poly, msg, partials, t, n, dst)
            _note_device_ok()
            return out
        except ValueError:
            raise  # semantic error (not enough partials): no fallback
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("recover", e)
    with _timed("recover", "host", t):
        return tbls.recover(pub_poly, msg, partials, t, n, dst)


def aggregate_round(pub_poly: PubPoly, msg: bytes, partials, t: int, n: int,
                    dst: bytes = DEFAULT_DST_G2, *,
                    prevalidated: bool = False):
    """The aggregator's whole per-round crypto — verify every partial,
    Lagrange-recover, verify the recovered signature — as ONE device
    dispatch when the engine is active (chain/beacon/chain.go:91-166).
    Returns ``(oks, sig_bytes)`` with ``oks`` aligned to ``partials``.
    Raises ``ValueError`` when recovery is impossible.

    ``prevalidated``: the caller already signature-checked every partial
    on ingress (the daemon's handler path) — the host fallback then skips
    the per-partial pairings (the fused device graph re-verifies anyway,
    at zero extra dispatches)."""
    from ..obs.trace import TRACER

    if _use_device(len(partials)):
        try:
            _note_dispatch("aggregate_round")
            # the fused dispatch recovers AND verifies in one executable:
            # the whole call is the round's "recover" stage
            with TRACER.span("recover", path="device", fused=True,
                             partials=len(partials)), \
                    _timed("aggregate_round", "device", len(partials)):
                out = engine().aggregate_round(pub_poly, msg, partials,
                                               t, n, dst)
            _note_device_ok()
            return out
        except ValueError:
            raise  # semantic error: no fallback
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("aggregate_round", e)
    with _timed("aggregate_round", "host", len(partials)):
        if prevalidated:
            oks = [len(p) == tbls.PARTIAL_SIG_SIZE for p in partials]
        else:
            with TRACER.span("verify", what="partials", n=len(partials)):
                if _use_rlc(len(partials)):
                    oks = batch_verify.verify_partials_rlc(
                        pub_poly, msg, partials, dst)
                else:
                    oks = [tbls.verify_partial(pub_poly, msg, p, dst)
                           for p in partials]
        good = [p for p, ok in zip(partials, oks) if ok]
        if len(good) < t:
            raise ValueError(f"not enough valid partials: {len(good)} < {t}")
        with TRACER.span("recover", path="host", partials=len(good)):
            sig = tbls.recover(pub_poly, msg, good, t, n, dst)
        with TRACER.span("verify", what="recovered"):
            if not tbls.verify_recovered(pub_poly.commit(), msg, sig, dst):
                raise tbls.RecoveredSignatureInvalid(
                    "recovered signature failed verification")
        return oks, sig


def decrypt_round_batch(signature, cts,
                        chunk: int | None = None
                        ) -> list[tuple[bool, bytes, str]]:
    """Open ALL of a round's timelock ciphertexts against its V2
    signature in one batched dispatch — the vault's round-boundary hot
    call (drand_tpu/timelock). Returns ``(ok, plaintext, error)`` per
    ciphertext, aligned with ``cts``, never raising per item.

    ``chunk`` is the open budget (ISSUE 20 bounded boundary opens): a
    positive value splits the K axis into ceil(K/chunk) independent
    dispatches — the shared-signature work re-amortizes inside each
    chunk, so the split is embarrassing. ``None`` reads the
    ``DRAND_TPU_TIMELOCK_OPEN_CHUNK`` default (unset/0 = one
    dispatch). The timelock service pre-chunks at this budget itself
    (it needs a vault commit between chunks) and hands each slice down
    with ``chunk=0``; direct callers get the same bound here.

    Device tier: ONE batched GT dispatch per chunk
    (ops/engine.timelock_open — the Miller line computation over the
    shared signature runs once, K varying U points on the batch axis)
    under ``engine_op_seconds{op="timelock", path="device"}``; a
    KAT-gate failure falls back to the host tier with a
    fallback-ledger entry. Host tier: the shared-signature batch
    decryptor (crypto/timelock.decrypt_batch) under
    ``path="host_shared"`` — the per-round line precomputation is
    hoisted, outcomes bit-identical to a per-item ``timelock.decrypt``
    loop. The Fujisaki-Okamoto check is host-exact on BOTH tiers."""
    from . import timelock

    if chunk is None:
        chunk = int(os.environ.get("DRAND_TPU_TIMELOCK_OPEN_CHUNK",
                                   "0") or 0)
    if chunk and chunk > 0 and len(cts) > chunk:
        out: list[tuple[bool, bytes, str]] = []
        for base in range(0, len(cts), chunk):
            out.extend(decrypt_round_batch(
                signature, cts[base:base + chunk], chunk=0))
        return out
    n = len(cts)
    if n and _use_device(n):
        try:
            _note_dispatch("timelock")
            with _timed("timelock", "device", n):
                out = engine().timelock_open(signature, cts)
            if out is not None:
                _note_device_ok()
                return out
            _ledger_note(
                "timelock", "device",
                "no timelock bucket passed known-answer validation — "
                "host shared-signature decrypt decides")
        except Exception as e:  # noqa: BLE001 — host path is the oracle
            if _MODE == "device":
                raise
            _note_fallback("timelock", e)
    with _timed("timelock", "host_shared", n):
        return timelock.decrypt_batch(signature, cts)


def eval_commits(polys: list[PubPoly], index: int) -> list[PointG1]:
    """Evaluate many commitment polynomials at one index — the DKG deal
    share-check `g·s_d == Σ_k C_{d,k}·index^k` done for every dealer at
    once (BASELINE config "n=128 deal verify"; kyber vss VerifyDeal)."""
    if _use_device(len(polys)):
        try:
            _note_dispatch("eval_commits")
            with _timed("eval_commits", "device", len(polys)):
                out = engine().eval_commits(polys, index)
            _note_device_ok()
            return out
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("eval_commits", e)
    with _timed("eval_commits", "host", len(polys)):
        return [p.eval(index).value for p in polys]


def parse_commits(bundles) -> list:
    """Decompress + subgroup-check EVERY pending deal bundle's commitment
    points in one host pass — ``bundles`` is a list of per-dealer byte
    tuples; the result aligns with it, ``None`` marking a rejected bundle
    (malformed encoding, or any point outside G1). Acceptance set is
    bit-identical to the sequential
    ``PointG1.from_bytes(c, subgroup_check=True)`` loop: decompression
    runs per point (the sqrt is unavoidable), while the dominant
    membership check runs as ONE lockstep chain over every pending point
    (crypto/endo.subgroup_check_fast_g1_many). Membership stays strictly
    per-point — an RLC aggregate has soundness 1/3 here (the order-3
    cofactor component cancels), so the batching lever is the shared
    fixed-[M] chain, not aggregation."""
    n = sum(len(b) for b in bundles)
    with _timed("parse_commits", "host", n):
        parsed = []
        for cs in bundles:
            try:
                parsed.append([PointG1.from_bytes(c, subgroup_check=False)
                               for c in cs])
            except ValueError:
                parsed.append(None)
        flat = [pt for pts in parsed if pts is not None for pt in pts]
        verdicts = iter(endo.subgroup_check_fast_g1_many(flat))
        out = []
        for pts in parsed:
            if pts is None:
                out.append(None)
                continue
            # consume ALL lane verdicts before deciding (a short-circuit
            # would desync the iterator from the flat lane order)
            oks = [next(verdicts) for _ in pts]
            out.append(pts if all(oks) else None)
        return out


def share_checks(pairs) -> list[bool]:
    """``g·s == expected`` for every pending share of a DKG phase in one
    call — ``pairs`` = [(scalar, expected_point)]. The fixed-base comb
    (crypto/curves.g1_comb_mul, the shared timelock 8-bit table) replaces
    a 255-bit generator ladder per share; verdicts are bit-identical to
    ``PointG1.generator().mul(s % R) == expected``."""
    with _timed("dkg_share_checks", "host", len(pairs)):
        return [g1_comb_mul(s) == exp for s, exp in pairs]


def eval_poly_indices(pub_poly: PubPoly, indices: list[int]) -> list[PointG1]:
    """ONE committed polynomial evaluated at MANY indices — the dual of
    :func:`eval_commits`, used by justification verification (one
    complained dealer, all its complained share indices per phase) and
    the reshare binding's device path. Device: the KAT-gated per-lane
    index graph (ops/engine.eval_poly_indices); host: the memoized
    Horner oracle (PubPoly.eval_many)."""
    if _use_device(len(indices)):
        try:
            _note_dispatch("eval_poly_indices")
            with _timed("eval_poly_indices", "device", len(indices)):
                out = engine().eval_poly_indices(pub_poly, indices)
            _note_device_ok()
            return out
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("eval_poly_indices", e)
    with _timed("eval_poly_indices", "host", len(indices)):
        return [s.value for s in pub_poly.eval_many(indices)]


def reshare_bindings(old_pub: PubPoly, items) -> list[bool]:
    """Dual-group binding verdicts for ALL dealers of a reshare deal
    phase in one dispatch — ``items`` = [(dealer_index, constant_commit)],
    each required to satisfy ``old_pub.eval(dealer_index) == commit``.
    Device: one eval_poly_indices dispatch plus exact compares; host
    above the RLC threshold: the 2-MSM combined verdict
    (batch_verify.reshare_bindings_rlc, bisecting to the exact Horner
    oracle); host otherwise: the memoized per-dealer loop. Caller
    contract for the RLC tier: every constant commit was already
    subgroup-checked (parse_commits) — the combination's 2^-128
    soundness argument requires all points in G1."""
    n = len(items)
    if _use_device(n):
        try:
            _note_dispatch("reshare_bindings")
            with _timed("reshare_bindings", "device", n):
                evs = engine().eval_poly_indices(
                    old_pub, [i for i, _ in items])
            _note_device_ok()
            return [ev == q for ev, (_, q) in zip(evs, items)]
        except Exception as e:  # noqa: BLE001
            if _MODE == "device":
                raise
            _note_fallback("reshare_bindings", e)
    if _use_rlc(n):
        with _timed("reshare_bindings", "host_rlc", n):
            return batch_verify.reshare_bindings_rlc(old_pub, items)
    with _timed("reshare_bindings", "host", n):
        return [old_pub.eval(i).value == q for i, q in items]
