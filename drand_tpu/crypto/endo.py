"""The ψ (untwist-Frobenius-twist) endomorphism on G2 and the fast paths
it enables: Scott subgroup membership and Budroni-Pintore cofactor
clearing.

ψ acts on affine twist coordinates as ψ(x, y) = (c_x·x̄, c_y·ȳ) (conjugate
then multiply by fixed Fp2 constants). Rather than hard-coding textbook
constants (whose exact values depend on the twist convention), the
constants are PROBED from this codebase's own curve arithmetic — solved
from ψ's defining property that it acts as multiplication by the BLS
parameter x on the r-order subgroup (eigenvalue p ≡ t−1 ≡ x mod r) —
and then self-validated at import on random points. This mirrors how
crypto/pairing.py probes its untwist embedding.

Speedups over the generic scalar versions (used by the device wire-prep
kernels; the host verify path keeps the generic code as the oracle):
- subgroup check: ψ(Q) == [x]Q          — one 64-bit chain vs a 255-bit one
- cofactor clear: [h_eff]P computed as
      ([x²−x−1]P) + ψ([x−1]P) + ψ²([2]P)
  via two nested [x]-multiplications   — vs one 636-bit chain.
  (Budroni-Pintore 2017; validated against q.mul(_H_CLEAR) below and in
  tests/test_endo.py.)

Reference parity: kyber-bls12381's G2 membership/cofactor internals
(kilc/bls12-381); drand consumes them via hash-to-G2 and point
deserialization (chain/beacon.go:87-115 verification paths).
"""

from __future__ import annotations

from .curves import H1, PointG1, PointG2
from .fields import Fp, Fp2, P, R, X_BLS
from .hash_to_curve import _H_CLEAR


def _solve_constants() -> tuple[Fp2, Fp2]:
    """Solve c_x, c_y from ψ(G) = [x mod r]G on the subgroup generator and
    an independent second point (the map must be pointwise-consistent)."""
    x_mod_r = X_BLS % R
    sols = []
    for seed in (1, 0xA5A5):
        g = PointG2.generator().mul(seed)
        gx, gy = g.to_affine()
        h = g.mul(x_mod_r)
        hx, hy = h.to_affine()
        cx = hx * gx.conjugate().inverse()
        cy = hy * gy.conjugate().inverse()
        sols.append((cx, cy))
    if sols[0] != sols[1]:
        raise AssertionError("psi constants are not pointwise-consistent")
    return sols[0]


PSI_CX, PSI_CY = _solve_constants()
# ψ² constants (applying ψ twice: conj∘conj = id, so these are plain
# per-coordinate Fp2 multipliers)
PSI2_CX = PSI_CX * PSI_CX.conjugate()
PSI2_CY = PSI_CY * PSI_CY.conjugate()
# ψ³ = ψ∘ψ²: ψ²(x,y) = (PSI2_CX·x, PSI2_CY·y), then one more conjugation
# pass pulls the ψ² multipliers through as their conjugates
PSI3_CX = PSI_CX * PSI2_CX.conjugate()
PSI3_CY = PSI_CY * PSI2_CY.conjugate()

# --- GLS 4-D scalar decomposition via ψ² ----------------------------------
# ψ acts as [x] on the r-order subgroup (x = X_BLS < 0), so with
# M = -x (> 0, 64 bits) the powers [M^k]P are ±ψ^k(P):
#     [M]P = -ψ(P),  [M²]P = ψ²(P),  [M³]P = -ψ³(P).
# r = x⁴ - x² + 1 = M⁴ - M² + 1 < M⁴, so every scalar c (reduced mod r)
# has exactly four base-M digits, each <= M-1 < 2^64 — a 255-bit ladder
# becomes four <= GLS4_DIGIT_BITS-bit ladders on (P, -ψP, ψ²P, -ψ³P).
GLS4_M = -X_BLS
GLS4_DIGIT_BITS = GLS4_M.bit_length()  # 64
if R >= GLS4_M ** 4:
    raise AssertionError("GLS4: r >= M^4 — four base-M digits insufficient")


def gls4_decompose(c: int) -> tuple[int, int, int, int]:
    """Base-M digits (d0, d1, d2, d3) of ``c mod r``, each < 2^64, with
    c·P = d0·P + d1·[M]P + d2·[M²]P + d3·[M³]P on the r-order subgroup."""
    c %= R
    d0 = c % GLS4_M
    c //= GLS4_M
    d1 = c % GLS4_M
    c //= GLS4_M
    d2 = c % GLS4_M
    return d0, d1, d2, c // GLS4_M


def gls4_points_from_affine(x: Fp2, y: Fp2) -> list[PointG2]:
    """The GLS basis [P, [M]P, [M²]P, [M³]P] = [P, -ψP, ψ²P, -ψ³P] from
    known-affine coordinates — six Fp2 multiplications, no inversions
    (callers normalize whole spans with one batch_to_affine). P must be
    in the r-order subgroup (ψ = [x] only holds there)."""
    xb, yb = x.conjugate(), y.conjugate()
    one = Fp2.one()
    return [PointG2(x, y, one),
            PointG2(PSI_CX * xb, -(PSI_CY * yb), one),
            PointG2(PSI2_CX * x, PSI2_CY * y, one),
            PointG2(PSI3_CX * xb, -(PSI3_CY * yb), one)]


def psi(q: PointG2) -> PointG2:
    """ψ(Q) for any Q on the twist (not only the r-order subgroup)."""
    if q.is_infinity():
        return q
    return psi_from_affine(*q.to_affine())


def psi_from_affine(x: Fp2, y: Fp2) -> PointG2:
    """ψ applied to known-affine coordinates — the batch entry for the
    host MSM's endomorphism split (crypto/batch_verify.msm_endo_g2):
    callers normalize a whole span with one simultaneous inversion
    (PointG2.batch_to_affine) and apply ψ per point without the per-point
    inverse that :func:`psi`'s to_affine would pay."""
    return PointG2(PSI_CX * x.conjugate(), PSI_CY * y.conjugate(), Fp2.one())


def psi2(q: PointG2) -> PointG2:
    if q.is_infinity():
        return q
    x, y = q.to_affine()
    return PointG2(PSI2_CX * x, PSI2_CY * y, Fp2.one())


def psi3(q: PointG2) -> PointG2:
    if q.is_infinity():
        return q
    x, y = q.to_affine()
    return PointG2(PSI3_CX * x.conjugate(), PSI3_CY * y.conjugate(),
                   Fp2.one())


def subgroup_check_fast(q: PointG2) -> bool:
    """Q ∈ G2 (r-order subgroup) ⟺ ψ(Q) == [x]Q (Scott's criterion for
    BLS12-381). Q must be on the twist curve."""
    if q.is_infinity():
        return True
    return psi(q) == _mul_int(q, X_BLS)


def _mul_int(q: PointG2, k: int) -> PointG2:
    """Signed scalar multiplication by a (possibly negative) int."""
    if k < 0:
        return -(q.mul(-k))
    return q.mul(k)


def clear_cofactor_fast(p: PointG2) -> PointG2:
    """[h_eff]P via Budroni-Pintore:
        [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P)
    with [x²−x]P computed as [x]([x]P)."""
    t1 = _mul_int(p, X_BLS)                   # [x]P
    t2 = _mul_int(t1, X_BLS)                  # [x²]P
    part1 = t2 + (-t1) + (-p)                 # [x²−x−1]P
    part2 = psi(t1 + (-p))                    # ψ([x−1]P)
    part3 = psi2(p.double())                  # ψ²([2]P)
    return part1 + part2 + part3


# --- G1 GLV endomorphism φ(x, y) = (β·x, y) and the fast subgroup check ---
# β is a primitive cube root of unity in Fp (solved from sqrt(-3), probed
# like the ψ constants above rather than hard-coded): φ is an order-3
# endomorphism of E(Fp) acting as multiplication by an eigenvalue λ on
# the r-order subgroup G1. For BLS12-381, λ is a root of z² + z + 1
# mod r; with M = -X_BLS the two roots are ±x² - {1,0}-flavored — which
# root the SOLVED β lands on depends on the sqrt branch, so _solve_beta
# probes the generator and keeps the β whose eigenvalue is -x² mod r,
# fixing the single check chain below.
#
# Soundness of `φ(P) == -[x²]P` as a G1 membership test for on-curve P
# (Scott 2021-style, adapted to this curve's cofactor): decompose P over
# E(Fp)'s abelian group. #E = h1·r with h1 = 3·Q² (Q prime,
# Q = 5044125407647214251) and gcd(r, h1) = 1. φ acts on every
# prime-order component as some cube root of unity; the test passes on a
# q-order component only if -x² is a root of z² + z + 1 mod q, i.e.
# q | (x²)² - x² + 1 = x⁴ - x² + 1 = r — impossible for q ∈ {Q, 3}
# (both < r, r prime). The order-3 component needs its own argument
# since z² + z + 1 ≡ (z - 1)² mod 3: there φ must act as [1], but
# -x² mod 3 ∈ {0, 2} (squares mod 3 are {0, 1}) ≠ 1, so order-3 torsion
# fails the chain too. Hence ONLY the r-order component survives —
# validated below on explicit order-3 torsion and non-subgroup points.
#
# Cost: two 64-bit ladders (M has Hamming weight 6) ≈ 3.3x faster than
# in_subgroup's 255-bit ladder; the batched lockstep variant amortizes
# one Montgomery inversion per chain step across all lanes and runs the
# whole chain in affine coordinates (~2 field muls per lane per step).


def _solve_beta() -> Fp:
    """β with φ = [-x² mod r] on G1, from sqrt(-3): the two primitive
    cube roots are (-1 ± sqrt(-3))/2; probe which one matches."""
    s = Fp(P - 3).sqrt()
    if s is None:
        raise AssertionError("GLV: -3 is not a square in Fp")
    half = Fp(2).inverse()
    b = (Fp(P - 1) + s) * half
    lam = (-X_BLS * X_BLS) % R
    g = PointG1.generator()
    target = g.mul(lam)
    for cand in (b, b.square()):
        if cand * cand * cand != Fp(1) or cand == Fp(1):
            raise AssertionError("GLV: candidate is not a primitive "
                                 "cube root of unity")
        if PointG1(g.X * cand, g.Y, g.Z) == target:
            return cand
    raise AssertionError("GLV: neither cube root acts as [-x²] on G1")


GLV_BETA = _solve_beta()


def phi_g1(p: PointG1) -> PointG1:
    """φ(P) for any P on E(Fp) (not only the r-order subgroup) — one
    field multiplication in Jacobian coordinates (x = X/Z² scales by β
    iff X does)."""
    if p.is_infinity():
        return p
    return PointG1(p.X * GLV_BETA, p.Y, p.Z)


def subgroup_check_fast_g1(p: PointG1) -> bool:
    """P ∈ G1 (r-order subgroup) ⟺ φ(P) == -[x²]P, for P on the curve
    (soundness argument in the section comment above). [x²]P is two
    64-bit [M]-ladders, M = -x."""
    if p.is_infinity():
        return True
    return phi_g1(p) == -(p.mul(GLS4_M).mul(GLS4_M))


_G1_M_BITS = tuple(int(b) for b in bin(GLS4_M)[2:])
# Lockstep pays one batched inversion (~a full modexp) per chain step;
# below this lane count the per-point Jacobian chain is cheaper.
_LOCKSTEP_MIN = 16


def _batch_inv_int(vals: list[int]) -> list[int]:
    """Montgomery simultaneous inversion on raw ints mod P; caller
    guarantees nonzero."""
    prefix = [vals[0]]
    acc = vals[0]
    for v in vals[1:]:
        acc = acc * v % P
        prefix.append(acc)
    inv = pow(acc, P - 2, P)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, 0, -1):
        out[i] = inv * prefix[i - 1] % P
        inv = inv * vals[i] % P
    out[0] = inv
    return out


def _lockstep_mul_m(xs: list[int], ys: list[int], dead: list[bool]) -> None:
    """[M]·(xᵢ, yᵢ) per lane IN PLACE, affine double-and-add run in
    lockstep across lanes with one batched inversion per chain step.
    Coordinates are RAW ints mod P — the chain is ~500 field ops per
    lane and the Fp wrapper's per-op object overhead would dominate it.
    Lanes hitting a degenerate case — a zero denominator, reachable only
    by small-order junk (genuine G1 points have acc = [c]P with
    0 < c±1 < r at every step, and no 2-torsion exists since h1·r is
    odd) — are flagged in `dead` and skipped; the caller resolves them
    with the exact per-point oracle."""
    n = len(xs)
    bx, by = list(xs), list(ys)  # chain base (added on set bits)
    live = [i for i in range(n) if not dead[i]]
    for bit in _G1_M_BITS[1:]:
        # double: λ = 3x² / 2y
        for i in live:
            if ys[i] == 0:
                dead[i] = True
        live = [i for i in live if not dead[i]]
        if not live:
            return
        invs = _batch_inv_int([(ys[i] + ys[i]) % P for i in live])
        for i, inv in zip(live, invs):
            x = xs[i]
            y = ys[i]
            lam = 3 * x * x * inv % P
            x2 = (lam * lam - x - x) % P
            ys[i] = (lam * (x - x2) - y) % P
            xs[i] = x2
        if bit:
            # add base: λ = (y_b - y) / (x_b - x)
            for i in live:
                if bx[i] == xs[i]:
                    dead[i] = True
            live = [i for i in live if not dead[i]]
            if not live:
                return
            invs = _batch_inv_int([(bx[i] - xs[i]) % P for i in live])
            for i, inv in zip(live, invs):
                x = xs[i]
                lam = (by[i] - ys[i]) * inv % P
                x3 = (lam * lam - x - bx[i]) % P
                ys[i] = (lam * (x - x3) - ys[i]) % P
                xs[i] = x3


def subgroup_check_fast_g1_many(points) -> list[bool]:
    """Per-point G1 membership verdicts, bit-identical to
    ``[p.in_subgroup() for p in points]`` for on-curve inputs.

    Membership is inherently per-point — a random-linear-combination
    aggregate has soundness only 1/3 here (the order-3 cofactor
    component can cancel), and a crafted dealer can make order-3 junk
    vanish at every share-check index — so the batching lever is
    LOCKSTEP, not aggregation: all lanes walk the same fixed [M] chain
    twice in affine coordinates, sharing one inversion per step."""
    n = len(points)
    if n < _LOCKSTEP_MIN:
        return [subgroup_check_fast_g1(p) for p in points]
    verdicts: list = [None] * n
    lanes = []
    for i, p in enumerate(points):
        if p.is_infinity():
            verdicts[i] = True
        else:
            lanes.append(i)
    if not lanes:
        return verdicts
    aff = PointG1.batch_to_affine([points[i] for i in lanes])
    xs = [a[0].v for a in aff]
    ys = [a[1].v for a in aff]
    px, py = list(xs), list(ys)
    dead = [False] * len(lanes)
    _lockstep_mul_m(xs, ys, dead)   # (xs, ys) = [M]P
    _lockstep_mul_m(xs, ys, dead)   # (xs, ys) = [M²]P
    beta = GLV_BETA.v
    for j, i in enumerate(lanes):
        if dead[j]:
            # degenerate chain lane — small-order junk; exact oracle
            verdicts[i] = points[i].in_subgroup()
        else:
            # φ(P) == -[M²]P in affine: (β·x_P, y_P) == (x, -y)
            verdicts[i] = (px[j] * beta % P == xs[j]
                           and py[j] == (P - ys[j]) % P)
    return verdicts


def _validate() -> None:
    # Explicit raises (not assert): these import-time checks are the
    # safety net for the probed ψ constants and must survive python -O.
    g = PointG2.generator().mul(0x77AB12)
    if psi(g) != _mul_int(g, X_BLS):
        raise ValueError("psi eigenvalue check failed")
    if psi2(g) != psi(psi(g)):
        raise ValueError("psi2 != psi∘psi")
    if not subgroup_check_fast(g):
        raise ValueError("fast subgroup check rejected a subgroup point")
    if psi3(g) != psi(psi2(g)):
        raise ValueError("psi3 != psi∘psi2")
    # the 4-D GLS identity on one wide scalar: Σ d_k·[M^k]P == c·P
    c = 0x6AF3_19C2_0000_0001_DEAD_BEEF_0000_7777_0123_4567_89AB_CDEF_FFFF_FFFF_0000_0003 % R
    d0, d1, d2, d3 = gls4_decompose(c)
    basis = gls4_points_from_affine(*g.to_affine())
    acc = basis[0].mul(d0) + basis[1].mul(d1) \
        + basis[2].mul(d2) + basis[3].mul(d3)
    if acc != _mul_int(g, c):
        raise ValueError("GLS4 decomposition check failed")
    # BP cofactor clearing must equal the generic [h_eff] multiplication
    # on a NON-subgroup curve point (a hash_to_curve pre-clearing output)
    from .hash_to_curve import hash_to_g2  # noqa: F401 (import check)
    from . import hash_to_curve as h2c

    u0, u1 = h2c.hash_to_field_fp2(b"endo-validate", h2c.DEFAULT_DST_G2, 2)
    q = h2c.map_to_curve_g2(u0) + h2c.map_to_curve_g2(u1)
    if clear_cofactor_fast(q) != q.mul(_H_CLEAR):
        raise ValueError("Budroni-Pintore clearing != [h_eff] mult")
    _validate_g1()


def _validate_g1() -> None:
    # the fast G1 check must accept subgroup points and reject the one
    # component the aggregate-soundness argument worries about: explicit
    # order-3 torsion (constructed by clearing everything BUT one
    # 3-factor from a random full-group point), plus a generic
    # non-subgroup point and a subgroup+torsion mix.
    g = PointG1.generator()
    good = g.mul(0x5EED_CAFE)
    if not (subgroup_check_fast_g1(g) and subgroup_check_fast_g1(good)):
        raise ValueError("G1 fast check rejected a subgroup point")
    torsion = None
    for xi in range(1, 64):
        x = Fp(xi)
        y = (x.square() * x + PointG1.B).sqrt()
        if y is None:
            continue
        cand = PointG1.from_affine(x, y)
        t = cand.mul(H1 * R // 3)
        if not t.is_infinity():
            torsion = t
            if cand.mul(H1 * R) != t.mul(3):
                raise ValueError("G1 torsion construction inconsistent")
            if not t.mul(3).is_infinity():
                raise ValueError("G1 torsion point is not order 3")
            break
    if torsion is None:
        raise ValueError("G1 validation found no order-3 torsion")
    mixed = good + torsion
    if subgroup_check_fast_g1(torsion) or subgroup_check_fast_g1(mixed):
        raise ValueError("G1 fast check accepted torsion")
    # lockstep variant: force the batched path (>= _LOCKSTEP_MIN lanes)
    # with torsion/mixed/infinity lanes interleaved among honest ones
    pts = [g.mul(3 + k) for k in range(_LOCKSTEP_MIN)]
    pts[2] = torsion
    pts[7] = mixed
    pts[11] = PointG1.infinity()
    want = [True] * _LOCKSTEP_MIN
    want[2] = want[7] = False
    if subgroup_check_fast_g1_many(pts) != want:
        raise ValueError("G1 lockstep check disagrees with per-point")


_validate()
