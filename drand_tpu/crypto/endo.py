"""The ψ (untwist-Frobenius-twist) endomorphism on G2 and the fast paths
it enables: Scott subgroup membership and Budroni-Pintore cofactor
clearing.

ψ acts on affine twist coordinates as ψ(x, y) = (c_x·x̄, c_y·ȳ) (conjugate
then multiply by fixed Fp2 constants). Rather than hard-coding textbook
constants (whose exact values depend on the twist convention), the
constants are PROBED from this codebase's own curve arithmetic — solved
from ψ's defining property that it acts as multiplication by the BLS
parameter x on the r-order subgroup (eigenvalue p ≡ t−1 ≡ x mod r) —
and then self-validated at import on random points. This mirrors how
crypto/pairing.py probes its untwist embedding.

Speedups over the generic scalar versions (used by the device wire-prep
kernels; the host verify path keeps the generic code as the oracle):
- subgroup check: ψ(Q) == [x]Q          — one 64-bit chain vs a 255-bit one
- cofactor clear: [h_eff]P computed as
      ([x²−x−1]P) + ψ([x−1]P) + ψ²([2]P)
  via two nested [x]-multiplications   — vs one 636-bit chain.
  (Budroni-Pintore 2017; validated against q.mul(_H_CLEAR) below and in
  tests/test_endo.py.)

Reference parity: kyber-bls12381's G2 membership/cofactor internals
(kilc/bls12-381); drand consumes them via hash-to-G2 and point
deserialization (chain/beacon.go:87-115 verification paths).
"""

from __future__ import annotations

from .curves import PointG2
from .fields import Fp2, P, R, X_BLS
from .hash_to_curve import _H_CLEAR


def _solve_constants() -> tuple[Fp2, Fp2]:
    """Solve c_x, c_y from ψ(G) = [x mod r]G on the subgroup generator and
    an independent second point (the map must be pointwise-consistent)."""
    x_mod_r = X_BLS % R
    sols = []
    for seed in (1, 0xA5A5):
        g = PointG2.generator().mul(seed)
        gx, gy = g.to_affine()
        h = g.mul(x_mod_r)
        hx, hy = h.to_affine()
        cx = hx * gx.conjugate().inverse()
        cy = hy * gy.conjugate().inverse()
        sols.append((cx, cy))
    if sols[0] != sols[1]:
        raise AssertionError("psi constants are not pointwise-consistent")
    return sols[0]


PSI_CX, PSI_CY = _solve_constants()
# ψ² constants (applying ψ twice: conj∘conj = id, so these are plain
# per-coordinate Fp2 multipliers)
PSI2_CX = PSI_CX * PSI_CX.conjugate()
PSI2_CY = PSI_CY * PSI_CY.conjugate()
# ψ³ = ψ∘ψ²: ψ²(x,y) = (PSI2_CX·x, PSI2_CY·y), then one more conjugation
# pass pulls the ψ² multipliers through as their conjugates
PSI3_CX = PSI_CX * PSI2_CX.conjugate()
PSI3_CY = PSI_CY * PSI2_CY.conjugate()

# --- GLS 4-D scalar decomposition via ψ² ----------------------------------
# ψ acts as [x] on the r-order subgroup (x = X_BLS < 0), so with
# M = -x (> 0, 64 bits) the powers [M^k]P are ±ψ^k(P):
#     [M]P = -ψ(P),  [M²]P = ψ²(P),  [M³]P = -ψ³(P).
# r = x⁴ - x² + 1 = M⁴ - M² + 1 < M⁴, so every scalar c (reduced mod r)
# has exactly four base-M digits, each <= M-1 < 2^64 — a 255-bit ladder
# becomes four <= GLS4_DIGIT_BITS-bit ladders on (P, -ψP, ψ²P, -ψ³P).
GLS4_M = -X_BLS
GLS4_DIGIT_BITS = GLS4_M.bit_length()  # 64
if R >= GLS4_M ** 4:
    raise AssertionError("GLS4: r >= M^4 — four base-M digits insufficient")


def gls4_decompose(c: int) -> tuple[int, int, int, int]:
    """Base-M digits (d0, d1, d2, d3) of ``c mod r``, each < 2^64, with
    c·P = d0·P + d1·[M]P + d2·[M²]P + d3·[M³]P on the r-order subgroup."""
    c %= R
    d0 = c % GLS4_M
    c //= GLS4_M
    d1 = c % GLS4_M
    c //= GLS4_M
    d2 = c % GLS4_M
    return d0, d1, d2, c // GLS4_M


def gls4_points_from_affine(x: Fp2, y: Fp2) -> list[PointG2]:
    """The GLS basis [P, [M]P, [M²]P, [M³]P] = [P, -ψP, ψ²P, -ψ³P] from
    known-affine coordinates — six Fp2 multiplications, no inversions
    (callers normalize whole spans with one batch_to_affine). P must be
    in the r-order subgroup (ψ = [x] only holds there)."""
    xb, yb = x.conjugate(), y.conjugate()
    one = Fp2.one()
    return [PointG2(x, y, one),
            PointG2(PSI_CX * xb, -(PSI_CY * yb), one),
            PointG2(PSI2_CX * x, PSI2_CY * y, one),
            PointG2(PSI3_CX * xb, -(PSI3_CY * yb), one)]


def psi(q: PointG2) -> PointG2:
    """ψ(Q) for any Q on the twist (not only the r-order subgroup)."""
    if q.is_infinity():
        return q
    return psi_from_affine(*q.to_affine())


def psi_from_affine(x: Fp2, y: Fp2) -> PointG2:
    """ψ applied to known-affine coordinates — the batch entry for the
    host MSM's endomorphism split (crypto/batch_verify.msm_endo_g2):
    callers normalize a whole span with one simultaneous inversion
    (PointG2.batch_to_affine) and apply ψ per point without the per-point
    inverse that :func:`psi`'s to_affine would pay."""
    return PointG2(PSI_CX * x.conjugate(), PSI_CY * y.conjugate(), Fp2.one())


def psi2(q: PointG2) -> PointG2:
    if q.is_infinity():
        return q
    x, y = q.to_affine()
    return PointG2(PSI2_CX * x, PSI2_CY * y, Fp2.one())


def psi3(q: PointG2) -> PointG2:
    if q.is_infinity():
        return q
    x, y = q.to_affine()
    return PointG2(PSI3_CX * x.conjugate(), PSI3_CY * y.conjugate(),
                   Fp2.one())


def subgroup_check_fast(q: PointG2) -> bool:
    """Q ∈ G2 (r-order subgroup) ⟺ ψ(Q) == [x]Q (Scott's criterion for
    BLS12-381). Q must be on the twist curve."""
    if q.is_infinity():
        return True
    return psi(q) == _mul_int(q, X_BLS)


def _mul_int(q: PointG2, k: int) -> PointG2:
    """Signed scalar multiplication by a (possibly negative) int."""
    if k < 0:
        return -(q.mul(-k))
    return q.mul(k)


def clear_cofactor_fast(p: PointG2) -> PointG2:
    """[h_eff]P via Budroni-Pintore:
        [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P)
    with [x²−x]P computed as [x]([x]P)."""
    t1 = _mul_int(p, X_BLS)                   # [x]P
    t2 = _mul_int(t1, X_BLS)                  # [x²]P
    part1 = t2 + (-t1) + (-p)                 # [x²−x−1]P
    part2 = psi(t1 + (-p))                    # ψ([x−1]P)
    part3 = psi2(p.double())                  # ψ²([2]P)
    return part1 + part2 + part3


def _validate() -> None:
    # Explicit raises (not assert): these import-time checks are the
    # safety net for the probed ψ constants and must survive python -O.
    g = PointG2.generator().mul(0x77AB12)
    if psi(g) != _mul_int(g, X_BLS):
        raise ValueError("psi eigenvalue check failed")
    if psi2(g) != psi(psi(g)):
        raise ValueError("psi2 != psi∘psi")
    if not subgroup_check_fast(g):
        raise ValueError("fast subgroup check rejected a subgroup point")
    if psi3(g) != psi(psi2(g)):
        raise ValueError("psi3 != psi∘psi2")
    # the 4-D GLS identity on one wide scalar: Σ d_k·[M^k]P == c·P
    c = 0x6AF3_19C2_0000_0001_DEAD_BEEF_0000_7777_0123_4567_89AB_CDEF_FFFF_FFFF_0000_0003 % R
    d0, d1, d2, d3 = gls4_decompose(c)
    basis = gls4_points_from_affine(*g.to_affine())
    acc = basis[0].mul(d0) + basis[1].mul(d1) \
        + basis[2].mul(d2) + basis[3].mul(d3)
    if acc != _mul_int(g, c):
        raise ValueError("GLS4 decomposition check failed")
    # BP cofactor clearing must equal the generic [h_eff] multiplication
    # on a NON-subgroup curve point (a hash_to_curve pre-clearing output)
    from .hash_to_curve import hash_to_g2  # noqa: F401 (import check)
    from . import hash_to_curve as h2c

    u0, u1 = h2c.hash_to_field_fp2(b"endo-validate", h2c.DEFAULT_DST_G2, 2)
    q = h2c.map_to_curve_g2(u0) + h2c.map_to_curve_g2(u1)
    if clear_cofactor_fast(q) != q.mul(_H_CLEAR):
        raise ValueError("Budroni-Pintore clearing != [h_eff] mult")


_validate()
