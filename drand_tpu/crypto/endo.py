"""The ψ (untwist-Frobenius-twist) endomorphism on G2 and the fast paths
it enables: Scott subgroup membership and Budroni-Pintore cofactor
clearing.

ψ acts on affine twist coordinates as ψ(x, y) = (c_x·x̄, c_y·ȳ) (conjugate
then multiply by fixed Fp2 constants). Rather than hard-coding textbook
constants (whose exact values depend on the twist convention), the
constants are PROBED from this codebase's own curve arithmetic — solved
from ψ's defining property that it acts as multiplication by the BLS
parameter x on the r-order subgroup (eigenvalue p ≡ t−1 ≡ x mod r) —
and then self-validated at import on random points. This mirrors how
crypto/pairing.py probes its untwist embedding.

Speedups over the generic scalar versions (used by the device wire-prep
kernels; the host verify path keeps the generic code as the oracle):
- subgroup check: ψ(Q) == [x]Q          — one 64-bit chain vs a 255-bit one
- cofactor clear: [h_eff]P computed as
      ([x²−x−1]P) + ψ([x−1]P) + ψ²([2]P)
  via two nested [x]-multiplications   — vs one 636-bit chain.
  (Budroni-Pintore 2017; validated against q.mul(_H_CLEAR) below and in
  tests/test_endo.py.)

Reference parity: kyber-bls12381's G2 membership/cofactor internals
(kilc/bls12-381); drand consumes them via hash-to-G2 and point
deserialization (chain/beacon.go:87-115 verification paths).
"""

from __future__ import annotations

from .curves import PointG2
from .fields import Fp2, P, R, X_BLS
from .hash_to_curve import _H_CLEAR


def _solve_constants() -> tuple[Fp2, Fp2]:
    """Solve c_x, c_y from ψ(G) = [x mod r]G on the subgroup generator and
    an independent second point (the map must be pointwise-consistent)."""
    x_mod_r = X_BLS % R
    sols = []
    for seed in (1, 0xA5A5):
        g = PointG2.generator().mul(seed)
        gx, gy = g.to_affine()
        h = g.mul(x_mod_r)
        hx, hy = h.to_affine()
        cx = hx * gx.conjugate().inverse()
        cy = hy * gy.conjugate().inverse()
        sols.append((cx, cy))
    if sols[0] != sols[1]:
        raise AssertionError("psi constants are not pointwise-consistent")
    return sols[0]


PSI_CX, PSI_CY = _solve_constants()
# ψ² constants (applying ψ twice: conj∘conj = id, so these are plain
# per-coordinate Fp2 multipliers)
PSI2_CX = PSI_CX * PSI_CX.conjugate()
PSI2_CY = PSI_CY * PSI_CY.conjugate()


def psi(q: PointG2) -> PointG2:
    """ψ(Q) for any Q on the twist (not only the r-order subgroup)."""
    if q.is_infinity():
        return q
    return psi_from_affine(*q.to_affine())


def psi_from_affine(x: Fp2, y: Fp2) -> PointG2:
    """ψ applied to known-affine coordinates — the batch entry for the
    host MSM's endomorphism split (crypto/batch_verify.msm_endo_g2):
    callers normalize a whole span with one simultaneous inversion
    (PointG2.batch_to_affine) and apply ψ per point without the per-point
    inverse that :func:`psi`'s to_affine would pay."""
    return PointG2(PSI_CX * x.conjugate(), PSI_CY * y.conjugate(), Fp2.one())


def psi2(q: PointG2) -> PointG2:
    if q.is_infinity():
        return q
    x, y = q.to_affine()
    return PointG2(PSI2_CX * x, PSI2_CY * y, Fp2.one())


def subgroup_check_fast(q: PointG2) -> bool:
    """Q ∈ G2 (r-order subgroup) ⟺ ψ(Q) == [x]Q (Scott's criterion for
    BLS12-381). Q must be on the twist curve."""
    if q.is_infinity():
        return True
    return psi(q) == _mul_int(q, X_BLS)


def _mul_int(q: PointG2, k: int) -> PointG2:
    """Signed scalar multiplication by a (possibly negative) int."""
    if k < 0:
        return -(q.mul(-k))
    return q.mul(k)


def clear_cofactor_fast(p: PointG2) -> PointG2:
    """[h_eff]P via Budroni-Pintore:
        [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P)
    with [x²−x]P computed as [x]([x]P)."""
    t1 = _mul_int(p, X_BLS)                   # [x]P
    t2 = _mul_int(t1, X_BLS)                  # [x²]P
    part1 = t2 + (-t1) + (-p)                 # [x²−x−1]P
    part2 = psi(t1 + (-p))                    # ψ([x−1]P)
    part3 = psi2(p.double())                  # ψ²([2]P)
    return part1 + part2 + part3


def _validate() -> None:
    # Explicit raises (not assert): these import-time checks are the
    # safety net for the probed ψ constants and must survive python -O.
    g = PointG2.generator().mul(0x77AB12)
    if psi(g) != _mul_int(g, X_BLS):
        raise ValueError("psi eigenvalue check failed")
    if psi2(g) != psi(psi(g)):
        raise ValueError("psi2 != psi∘psi")
    if not subgroup_check_fast(g):
        raise ValueError("fast subgroup check rejected a subgroup point")
    # BP cofactor clearing must equal the generic [h_eff] multiplication
    # on a NON-subgroup curve point (a hash_to_curve pre-clearing output)
    from .hash_to_curve import hash_to_g2  # noqa: F401 (import check)
    from . import hash_to_curve as h2c

    u0, u1 = h2c.hash_to_field_fp2(b"endo-validate", h2c.DEFAULT_DST_G2, 2)
    q = h2c.map_to_curve_g2(u0) + h2c.map_to_curve_g2(u1)
    if clear_cofactor_fast(q) != q.mul(_H_CLEAR):
        raise ValueError("Budroni-Pintore clearing != [h_eff] mult")


_validate()
