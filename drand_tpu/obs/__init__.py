"""Observability: the round-lifecycle tracing subsystem (obs/trace.py).

Import surface:
    from drand_tpu.obs import trace
    with trace.TRACER.activate(round_no=r, chain=seed):
        with trace.TRACER.span("collect", have=3):
            ...
"""

from . import trace  # noqa: F401
from .trace import (  # noqa: F401
    TRACEPARENT_HEADER,
    TRACER,
    Span,
    Tracer,
    current_round,
    current_trace_id,
    make_traceparent,
    outbound_metadata,
    parse_traceparent,
    round_trace_id,
    traceparent,
    traceparent_from,
    traceparent_from_context,
)
