"""Observability: round-lifecycle tracing (obs/trace.py), chain-health
state + SLOs (obs/health.py), and OTLP export of the span ring
(obs/export.py).

Import surface:
    from drand_tpu.obs import trace
    with trace.TRACER.activate(round_no=r, chain=seed):
        with trace.TRACER.span("collect", have=3):
            ...
    from drand_tpu.obs.health import HEALTH
    from drand_tpu.obs import export as obs_export

``health`` and ``export`` are imported lazily by their call sites (the
store decorator, the HTTP handlers) — importing ``drand_tpu.obs`` must
stay as cheap as it was in PR 1.
"""

from . import trace  # noqa: F401
from .trace import (  # noqa: F401
    merge_round_timelines,
    TRACEPARENT_HEADER,
    TRACER,
    Span,
    Tracer,
    current_round,
    current_trace_id,
    make_traceparent,
    outbound_metadata,
    parse_traceparent,
    round_trace_id,
    traceparent,
    traceparent_from,
    traceparent_from_context,
)
