"""Closed-loop auto-remediation: incidents drive audited playbooks
(ISSUE 16).

PR 15 made every chaos-proven fault mint an incident; PR 12 built the
recovery primitives (retry, breakers, quorum repair, multi-upstream
failover, worker respawn). This module closes the loop: a
:class:`PlaybookEngine` attached to an ``IncidentManager`` maps each
anomaly rule to a **remediation playbook**:

==================  ==================  =================================
rule                playbook            action
==================  ==================  =================================
sync_stall          sync_resume         rotate ``Syncer.follow`` to the
                                        next upstream, resume from the
                                        checkpoint (``store.last()+1``)
breaker_open        quorum_pull         targeted ``PartialRequest`` pull
(persistent)                            + half-open probe per OPEN peer
reachability_drop   partition_posture   serve stale from cache, lower
(majority)                              the watcher-shed threshold;
                                        reverted when the incident closes
worker_down         respawn_worker      respawn through the bounded
                                        ``utils.supervise.Supervisor``
margin_degraded     reshare_recommend   operator-visible reshare
(repeated, pinned)                      recommendation into the bundle
==================  ==================  =================================

**Guardrails are the feature**, and every one is observable:

- a global max-actions-per-window budget (live actions only),
- a per-playbook cooldown (one action per sustained fault, not one per
  sample),
- a DEFAULT-ON dry-run mode that only annotates the incident
  (``DRAND_TPU_REMEDIATE=live`` arms real actions),
- every attempted action + outcome appended to the incident's forensic
  bundle as a **remediation ledger** (the audit trail) and to the
  engine's own bounded ring, surfaced over ``GET /debug/remediation``,
  ``drand-tpu util remediate`` and the catalogued
  ``remediation_actions_total{playbook,outcome}`` /
  ``remediation_active{playbook}`` / ``remediation_mttr_seconds``
  metrics (MTTR as a first-class SLI).

Concurrency rules (ISSUE 13, enforced by tools/analyze): the manager
hands events to :meth:`PlaybookEngine.on_incidents` OUTSIDE its lock;
engine decisions are dict work under the engine's own lock with no
awaits inside it; actions are dispatched through
``drand_tpu.utils.aio.spawn`` (or ``run_coroutine_threadsafe`` from the
store thread) and any retries ride ``drand_tpu.utils.retry``'s
injectable clock, so the chaos e2e stays deterministic on the
FakeClock. The ledger writers (:meth:`PlaybookEngine.record_action`,
``IncidentManager.annotate_remediation``) are registered secretflow
sinks — key material flowing into a ledger entry fails the static gate
exactly like logging it would.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from ..utils.clock import Clock, SystemClock

# playbook names — the remediation_active{playbook} metric enum
# (tools/check_metrics.py KNOWN_LABEL_VALUES)
PLAYBOOK_SYNC = "sync_resume"
PLAYBOOK_PULL = "quorum_pull"
PLAYBOOK_POSTURE = "partition_posture"
PLAYBOOK_RESPAWN = "respawn_worker"
PLAYBOOK_RESHARE = "reshare_recommend"

# ledger outcomes — the remediation_actions_total{outcome} enum
OUTCOME_OK = "ok"
OUTCOME_FAILED = "failed"
OUTCOME_DRY_RUN = "dry_run"
OUTCOME_BUDGET = "budget_exhausted"
OUTCOME_REVERTED = "reverted"

# global action budget: at most MAX live actions per WINDOW seconds
DEFAULT_MAX_ACTIONS = int(os.environ.get("DRAND_TPU_REMEDIATE_MAX", "8"))
DEFAULT_WINDOW_S = float(
    os.environ.get("DRAND_TPU_REMEDIATE_WINDOW", "300"))
LEDGER_MAX = 256

_log = logging.getLogger("drand_tpu.obs.remediate")


def _env_dry_run() -> bool:
    """Dry-run is the DEFAULT: the engine annotates what it WOULD do
    until the operator explicitly arms it with
    ``DRAND_TPU_REMEDIATE=live``."""
    return os.environ.get("DRAND_TPU_REMEDIATE", "dry_run") != "live"


def _action_counter(playbook: str, outcome: str):
    """Branch-literal outcome labels for remediation_actions_total (the
    check_metrics KNOWN_LABEL_VALUES enum rule — the net_retry pattern:
    ``playbook`` rides a variable, bounded by the playbook registry).
    The engine mints only the five outcomes below; anything else is a
    bug and collapses to ``failed`` rather than forking the series."""
    from .. import metrics

    if outcome == "ok":
        return metrics.REMEDIATION_ACTIONS.labels(playbook=playbook,
                                                  outcome="ok")
    if outcome == "dry_run":
        return metrics.REMEDIATION_ACTIONS.labels(playbook=playbook,
                                                  outcome="dry_run")
    if outcome == "budget_exhausted":
        return metrics.REMEDIATION_ACTIONS.labels(
            playbook=playbook, outcome="budget_exhausted")
    if outcome == "reverted":
        return metrics.REMEDIATION_ACTIONS.labels(playbook=playbook,
                                                  outcome="reverted")
    return metrics.REMEDIATION_ACTIONS.labels(playbook=playbook,
                                              outcome="failed")


def _active_gauge(playbook: str):
    """Branch-literal playbook labels for remediation_active (the
    incidents_total ``_incident_counter`` pattern); operator-defined
    playbooks collapse to ``custom``."""
    from .. import metrics

    if playbook == "sync_resume":
        return metrics.REMEDIATION_ACTIVE.labels(playbook="sync_resume")
    if playbook == "quorum_pull":
        return metrics.REMEDIATION_ACTIVE.labels(playbook="quorum_pull")
    if playbook == "partition_posture":
        return metrics.REMEDIATION_ACTIVE.labels(
            playbook="partition_posture")
    if playbook == "respawn_worker":
        return metrics.REMEDIATION_ACTIVE.labels(
            playbook="respawn_worker")
    if playbook == "reshare_recommend":
        return metrics.REMEDIATION_ACTIVE.labels(
            playbook="reshare_recommend")
    return metrics.REMEDIATION_ACTIVE.labels(playbook="custom")


# ---------------------------------------------------------------------------
# playbooks
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Playbook:
    """One rule -> action mapping with its own guardrail knobs.

    ``min_fired`` gates on incident persistence (an incident's
    ``fired`` count — e.g. breaker_open must re-fire before the pull,
    a one-sample blip self-heals). ``when`` is an extra predicate over
    (incident summary, engine) — e.g. the MAJORITY check for partition
    posture. ``sticky`` playbooks stay active (gauge = 1) until the
    incident closes, at which point the registered revert runs.
    ``annotate_only`` playbooks never touch system state: their action
    is a synchronous recommendation builder whose output goes into the
    ledger even in dry-run mode (a recommendation IS an annotation)."""

    name: str
    rule: str
    describe: str
    cooldown_s: float = 60.0
    min_fired: int = 1
    annotate_only: bool = False
    sticky: bool = False
    when: Callable[[dict, "PlaybookEngine"], bool] | None = \
        field(default=None, repr=False)


def _majority_unreachable(summary: dict, engine: "PlaybookEngine") -> bool:
    """Partition posture fires only on a MAJORITY reachability drop:
    losing one peer is the breaker/pull playbooks' job; losing most of
    the mesh means this node is the partition minority and should serve
    degraded rather than hammer dead upstreams."""
    mgr = engine.manager
    sample = mgr.ring.last() if mgr is not None else None
    suspects = int((sample or {}).get("suspects") or 0)
    n = engine.n_peers
    if n:
        return 2 * suspects >= n
    return suspects >= 2


def default_playbooks() -> list[Playbook]:
    """The built-in rule -> playbook map (README "Auto-remediation"
    documents each with its guardrails)."""
    return [
        Playbook(PLAYBOOK_SYNC, rule="sync_stall",
                 describe="rotate the follow to the next upstream and "
                          "resume from the chain checkpoint",
                 cooldown_s=30.0),
        Playbook(PLAYBOOK_PULL, rule="breaker_open",
                 describe="targeted quorum-repair pull plus a half-open "
                          "probe of each OPEN peer breaker",
                 cooldown_s=30.0, min_fired=2),
        Playbook(PLAYBOOK_POSTURE, rule="reachability_drop",
                 describe="partition posture: serve stale from the "
                          "cache, lower the watcher-shed threshold",
                 cooldown_s=60.0, min_fired=2, sticky=True,
                 when=_majority_unreachable),
        Playbook(PLAYBOOK_RESPAWN, rule="worker_down",
                 describe="respawn dead supervised worker(s) through "
                          "the bounded supervisor",
                 cooldown_s=10.0),
        Playbook(PLAYBOOK_RESHARE, rule="margin_degraded",
                 describe="operator-visible reshare recommendation "
                          "written into the incident bundle",
                 cooldown_s=120.0, min_fired=3, annotate_only=True),
    ]


def worker_down_rule(supervisor, *, cooldown_s: float = 30.0):
    """An incident Rule minting ``worker_down`` while any worker
    registered with the Supervisor reads dead — the detection half of
    the respawn playbook (the rule ignores the SLI window; worker
    liveness is the supervisor's own probe)."""
    from .incident import Rule

    def _trigger(w: list[dict], ctx: dict) -> str | None:
        dead = supervisor.dead()
        if dead:
            return (f"{len(dead)} supervised worker(s) dead: "
                    f"{', '.join(dead)}")
        return None

    return Rule("worker_down", "major", "edge", _trigger,
                cooldown_s=cooldown_s)


def reshare_recommendation(flight, n_rounds: int = 8,
                           min_ratio: float = 0.5) -> str | None:
    """The reshare_recommend builder: a peer index whose shares were
    missing/late/invalid in at least ``min_ratio`` of the recent
    rounds, with at least twice the degradation of everyone else
    combined (= the fault is PINNED to one peer, not ambient), earns an
    operator-visible recommendation. Returns None when nothing is
    pinned — reshares are a ceremony, never auto-run."""
    from .flight import BITMAP_INVALID, BITMAP_LATE, BITMAP_MISSING

    counts: dict[int, int] = {}
    total = 0
    for rec in flight.rounds(n_rounds):
        total += 1
        for idx, ch in enumerate(rec.get("bitmap") or ""):
            if ch in (BITMAP_MISSING, BITMAP_INVALID, BITMAP_LATE):
                counts[idx] = counts.get(idx, 0) + 1
    if total < 3 or not counts:
        return None
    worst, bad = max(counts.items(), key=lambda kv: (kv[1], -kv[0]))
    others = sum(v for k, v in counts.items() if k != worst)
    if bad < min_ratio * total or bad < 2 * others:
        return None
    return (f"reshare recommended: peer index {worst} degraded in "
            f"{bad}/{total} recent rounds (missing/late/invalid "
            f"shares) while the rest of the group stayed healthy — "
            f"consider a reshare ceremony excluding it")


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class PlaybookEngine:
    """Guardrailed rule -> playbook dispatch with a full audit trail.

    Attach to an ``IncidentManager`` (:meth:`attach`); the manager
    hands minted/extended/closed incident events here outside its lock.
    Action callables are INJECTED per deployment (``attach_node``,
    ``attach_posture``, ``attach_supervisor`` below) so the daemon, a
    relay, and the chaos harness each wire exactly the handles they
    have. Thread-safe: decisions run under ``_lock`` (events arrive
    from the store thread AND the /healthz poll path), dispatch and
    ledger writes happen outside it."""

    def __init__(self, *, clock: Clock | None = None,
                 dry_run: bool | None = None,
                 max_actions: int | None = None,
                 window_s: float | None = None,
                 playbooks: list[Playbook] | None = None,
                 ledger_max: int = LEDGER_MAX):
        self._clock = clock or SystemClock()
        self.dry_run = _env_dry_run() if dry_run is None else dry_run
        self.max_actions = (DEFAULT_MAX_ACTIONS if max_actions is None
                            else max_actions)
        self.window_s = DEFAULT_WINDOW_S if window_s is None else window_s
        self.ledger_max = ledger_max
        self.playbooks = (list(playbooks) if playbooks is not None
                          else default_playbooks())
        self.n_peers: int | None = None
        self.supervisor = None
        self._by_rule: dict[str, list[Playbook]] = {}
        for pb in self.playbooks:
            self._by_rule.setdefault(pb.rule, []).append(pb)
        self._lock = threading.Lock()
        self._manager = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._actions: dict[str, Callable] = {}
        self._reverts: dict[str, Callable] = {}
        self._ledger: deque[dict] = deque(maxlen=ledger_max)
        self._recent: deque[float] = deque()   # live-dispatch timestamps
        self._cooldown_until: dict[str, float] = {}
        self._active: dict[str, str] = {}      # playbook -> incident id
        self._acted: set[str] = set()          # incident ids acted on
        self._dispatch_warned = False

    # ------------------------------------------------------------- wiring
    @property
    def manager(self):
        return self._manager

    def attach(self, manager) -> None:
        """Bind this engine to an IncidentManager (one engine per
        manager; re-attach replaces)."""
        with self._lock:
            self._manager = manager
        manager.engine = self

    def register_action(self, playbook: str, fn: Callable) -> None:
        """The playbook's action: ``async (incident_summary) -> detail``
        (annotate-only playbooks take a SYNC builder)."""
        with self._lock:
            self._actions[playbook] = fn

    def register_revert(self, playbook: str, fn: Callable) -> None:
        """Run when a sticky playbook's incident closes (posture
        restore). Async like actions."""
        with self._lock:
            self._reverts[playbook] = fn

    def arm(self) -> None:
        """Leave dry-run: actions really fire from here on."""
        with self._lock:
            self.dry_run = False

    def disarm(self) -> None:
        with self._lock:
            self.dry_run = True

    # ------------------------------------------------------------- intake
    def on_incidents(self, events: list[dict], now: float) -> None:
        """The manager's hand-off (called OUTSIDE its lock) — one entry
        per minted/extended/closed incident this sample."""
        self._capture_loop()
        for ev in events:
            kind = ev.get("event")
            summary = ev.get("summary") or {}
            if kind == "closed":
                self._on_closed(summary)
                continue
            if kind not in ("minted", "extended"):
                continue
            for pb in self._by_rule.get(summary.get("rule"), ()):
                self._consider(pb, summary, now)

    def _capture_loop(self) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        with self._lock:
            self._loop = loop

    def _consider(self, pb: Playbook, summary: dict, now: float) -> None:
        if summary.get("fired", 0) < pb.min_fired:
            return
        if pb.when is not None:
            try:
                if not pb.when(summary, self):
                    return
            except Exception:  # noqa: BLE001 — a broken predicate skips
                return
        inc_id = summary.get("id")
        dispatch = None
        with self._lock:
            if now < self._cooldown_until.get(pb.name, float("-inf")):
                return  # cooldown dedup: one action per sustained fault
            if pb.name in self._active:
                return  # an action is already in flight / posture held
            action = self._actions.get(pb.name)
            if pb.annotate_only:
                self._cooldown_until[pb.name] = now + pb.cooldown_s
                mode = "annotate"
            elif self.dry_run:
                self._cooldown_until[pb.name] = now + pb.cooldown_s
                mode = "dry_run"
            elif self._budget_left_locked(now) <= 0:
                self._cooldown_until[pb.name] = now + pb.cooldown_s
                mode = "budget"
            else:
                # live: reserve the budget slot + the active marker
                # inside the lock, then dispatch outside it
                self._recent.append(now)
                self._cooldown_until[pb.name] = now + pb.cooldown_s
                self._active[pb.name] = inc_id or ""
                if inc_id:
                    self._acted.add(inc_id)
                mode = "live"
        if mode == "annotate":
            self._run_annotate(pb, action, summary, now)
            return
        if mode == "dry_run":
            self.record_action(pb.name, OUTCOME_DRY_RUN, incident=inc_id,
                               mode="dry_run", detail=f"would: {pb.describe}",
                               t=now)
            return
        if mode == "budget":
            self.record_action(
                pb.name, OUTCOME_BUDGET, incident=inc_id, mode="live",
                detail=f"budget exhausted ({self.max_actions} actions/"
                       f"{self.window_s:g}s); not running: {pb.describe}",
                t=now)
            return
        _active_gauge(pb.name).set(1)
        if action is None:
            self._finish(pb, inc_id, OUTCOME_FAILED,
                         "no action registered for this playbook",
                         self._clock.now())
            return
        if not self._dispatch(self._run_action(pb, action, summary)):
            self._finish(pb, inc_id, OUTCOME_FAILED,
                         "no event loop to dispatch the action on",
                         self._clock.now())

    def _budget_left_locked(self, now: float) -> int:
        while self._recent and self._recent[0] <= now - self.window_s:
            self._recent.popleft()
        return self.max_actions - len(self._recent)

    def _dispatch(self, coro) -> bool:
        """Fire-and-forget on the event loop: ``aio.spawn`` when the
        caller is ON the loop; ``run_coroutine_threadsafe`` from the
        store thread. No loop at all (a pure-sync harness) = the action
        cannot run."""
        from ..utils.aio import spawn

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            with self._lock:
                loop = self._loop
            if loop is not None and not loop.is_closed():
                asyncio.run_coroutine_threadsafe(coro, loop)
                return True
            coro.close()
            with self._lock:
                warned = self._dispatch_warned
                self._dispatch_warned = True
            if not warned:
                _log.warning("remediation action dropped: no event loop")
            return False
        spawn(coro)
        return True

    # ------------------------------------------------------------ running
    async def _run_action(self, pb: Playbook, action: Callable,
                          summary: dict) -> None:
        inc_id = summary.get("id")
        try:
            detail = await action(dict(summary))
            outcome, text = OUTCOME_OK, str(detail)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — failures are ledgered
            outcome = OUTCOME_FAILED
            text = f"{type(e).__name__}: {e}"
        self._finish(pb, inc_id, outcome, text, self._clock.now())

    def _finish(self, pb: Playbook, inc_id: str | None, outcome: str,
                text: str, now: float) -> None:
        with self._lock:
            # sticky playbooks that SUCCEEDED hold their active marker
            # (and gauge) until the incident closes and the revert runs
            if not (pb.sticky and outcome == OUTCOME_OK):
                self._active.pop(pb.name, None)
        if not (pb.sticky and outcome == OUTCOME_OK):
            _active_gauge(pb.name).set(0)
        self.record_action(pb.name, outcome, incident=inc_id, mode="live",
                           detail=text, t=now)

    def _run_annotate(self, pb: Playbook, action: Callable | None,
                      summary: dict, now: float) -> None:
        if action is None:
            return
        try:
            text = action(dict(summary))
        except Exception as e:  # noqa: BLE001
            self.record_action(pb.name, OUTCOME_FAILED,
                               incident=summary.get("id"), mode="annotate",
                               detail=f"{type(e).__name__}: {e}", t=now)
            return
        if not text:
            # nothing pinned yet: don't burn the cooldown — the next
            # sample re-evaluates with more rounds of evidence
            with self._lock:
                self._cooldown_until.pop(pb.name, None)
            return
        self.record_action(pb.name, OUTCOME_OK, incident=summary.get("id"),
                           mode="annotate", detail=str(text), t=now)

    def _on_closed(self, summary: dict) -> None:
        from .. import metrics

        inc_id = summary.get("id") or ""
        opened, closed = summary.get("opened_at"), summary.get("closed_at")
        with self._lock:
            acted = inc_id in self._acted
            self._acted.discard(inc_id)
            reverts = [(pb, self._reverts.get(pb.name))
                       for pb in self._by_rule.get(summary.get("rule"), ())
                       if self._active.get(pb.name) == inc_id]
        if acted and opened is not None and closed is not None:
            # MTTR as an SLI: open-to-close of incidents we acted on
            metrics.REMEDIATION_MTTR.observe(max(0.0, closed - opened))
        for pb, revert in reverts:
            if revert is None:
                with self._lock:
                    self._active.pop(pb.name, None)
                _active_gauge(pb.name).set(0)
                continue
            self._dispatch(self._run_revert(pb, revert, summary))

    async def _run_revert(self, pb: Playbook, revert: Callable,
                          summary: dict) -> None:
        inc_id = summary.get("id")
        try:
            detail = await revert(dict(summary))
            outcome, text = OUTCOME_REVERTED, str(detail)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            outcome, text = OUTCOME_FAILED, f"{type(e).__name__}: {e}"
        with self._lock:
            self._active.pop(pb.name, None)
        _active_gauge(pb.name).set(0)
        self.record_action(pb.name, outcome, incident=inc_id, mode="live",
                           detail=text, t=self._clock.now())

    # ------------------------------------------------------------- ledger
    def record_action(self, playbook: str, outcome: str, *,
                      incident: str | None, mode: str, detail: str,
                      t: float) -> dict:
        """THE remediation-ledger writer (a registered secretflow sink,
        like the bundle writers): one entry per attempted action or
        refusal, appended to the engine ring AND the incident's bundle,
        counted on remediation_actions_total."""
        entry = {"t": round(t, 6), "playbook": playbook,
                 "incident": incident, "mode": mode, "outcome": outcome,
                 "detail": detail}
        with self._lock:
            self._ledger.append(entry)
            mgr = self._manager
        _action_counter(playbook, outcome).inc()
        if mgr is not None and incident:
            try:
                mgr.annotate_remediation(incident, entry)
            except Exception:  # noqa: BLE001 — the audit trail must not
                pass           # take the action path down
        return entry

    # ------------------------------------------------------------ outputs
    def ledger(self, n: int = 32) -> list[dict]:
        """The last ``n`` ledger entries, most recent first."""
        with self._lock:
            entries = list(self._ledger)[-n:] if n > 0 else []
        return [dict(e) for e in reversed(entries)]

    def status(self, n: int = 32) -> dict:
        """The /debug/remediation payload."""
        with self._lock:
            now = self._clock.now()
            used = self.max_actions - self._budget_left_locked(now)
            active = dict(self._active)
            cooldowns = {name: round(until - now, 3)
                         for name, until in self._cooldown_until.items()
                         if until > now}
            registered = set(self._actions)
            mode = "dry_run" if self.dry_run else "live"
            attached = self._manager is not None
        return {
            "mode": mode,
            "attached": attached,
            "budget": {"max": self.max_actions,
                       "window_s": self.window_s, "used": used},
            "active": active,
            "cooldowns_s": cooldowns,
            "playbooks": [{"playbook": pb.name, "rule": pb.rule,
                           "cooldown_s": pb.cooldown_s,
                           "min_fired": pb.min_fired,
                           "annotate_only": pb.annotate_only,
                           "registered": (pb.name in registered
                                          or pb.annotate_only),
                           "describe": pb.describe}
                          for pb in self.playbooks],
            "supervisor": (self.supervisor.status()
                           if self.supervisor is not None else None),
            "ledger": self.ledger(n),
        }

    def reset(self) -> None:
        """Back to boot state (tests/harness isolation) — guardrail
        counters, ledger and active markers only; attachments and
        registered actions survive (production wiring must not be
        unhooked by a scenario reset)."""
        with self._lock:
            active = list(self._active)
            self._ledger.clear()
            self._recent.clear()
            self._cooldown_until.clear()
            self._active.clear()
            self._acted.clear()
            self._dispatch_warned = False
        for name in active:
            _active_gauge(name).set(0)


# ---------------------------------------------------------------------------
# deployment wiring
# ---------------------------------------------------------------------------

def attach_node(engine: PlaybookEngine, handler) -> None:
    """Wire the beacon-node playbooks onto a Handler: sync_resume and
    quorum_pull act through the PR-12 recovery primitives; the reshare
    recommendation reads the handler's flight recorder."""
    engine.n_peers = len(handler.conf.group.nodes) - 1

    async def _sync_resume(summary: dict) -> str:
        return await handler.remediate_sync()

    async def _quorum_pull(summary: dict) -> str:
        return await handler.remediate_breakers()

    def _reshare(summary: dict) -> str | None:
        return reshare_recommendation(handler.flight)

    engine.register_action(PLAYBOOK_SYNC, _sync_resume)
    engine.register_action(PLAYBOOK_PULL, _quorum_pull)
    engine.register_action(PLAYBOOK_RESHARE, _reshare)


def attach_posture(engine: PlaybookEngine, server) -> None:
    """Wire partition posture onto a PublicServer: applied on a
    majority reachability drop, REVERTED when the incident closes."""

    async def _apply(summary: dict) -> str:
        return server.set_partition_posture(True)

    async def _revert(summary: dict) -> str:
        return server.set_partition_posture(False)

    engine.register_action(PLAYBOOK_POSTURE, _apply)
    engine.register_revert(PLAYBOOK_POSTURE, _revert)


def attach_supervisor(engine: PlaybookEngine, supervisor) -> None:
    """Wire the respawn playbook onto a utils.supervise.Supervisor;
    pair with ``worker_down_rule(supervisor)`` on the manager so death
    is detected as an incident and respawn rides the engine's budget,
    cooldown, dry-run and ledger."""
    engine.supervisor = supervisor

    async def _respawn(summary: dict) -> str:
        dead = supervisor.dead()
        if not dead:
            return "no dead workers"
        outcomes = [f"{name}={supervisor.maybe_respawn(name)}"
                    for name in dead]
        line = ", ".join(outcomes)
        if not any(o.endswith("=respawned") for o in outcomes):
            raise RuntimeError(f"respawn blocked: {line}")
        return line

    engine.register_action(PLAYBOOK_RESPAWN, _respawn)


# The per-process engine (the INCIDENTS/FLIGHT singleton pattern).
# NOT attached to INCIDENTS by default — the daemon/relay attach it via
# configure_from_env so harnesses with their own managers stay clean.
ENGINE = PlaybookEngine()


def configure_from_env(manager=None) -> PlaybookEngine:
    """Attach the singleton engine to ``manager`` (default: the
    INCIDENTS singleton) and (re)load the env knobs:
    ``DRAND_TPU_REMEDIATE`` (``live`` arms it; anything else = dry-run),
    ``DRAND_TPU_REMEDIATE_MAX`` / ``DRAND_TPU_REMEDIATE_WINDOW`` for
    the global action budget."""
    if manager is None:
        from .incident import INCIDENTS
        manager = INCIDENTS
    with ENGINE._lock:
        ENGINE.dry_run = _env_dry_run()
        ENGINE.max_actions = int(
            os.environ.get("DRAND_TPU_REMEDIATE_MAX",
                           str(DEFAULT_MAX_ACTIONS)))
        ENGINE.window_s = float(
            os.environ.get("DRAND_TPU_REMEDIATE_WINDOW",
                           str(DEFAULT_WINDOW_S)))
    ENGINE.attach(manager)
    return ENGINE
