"""Scoped reset/isolation of the per-process observability singletons.

``FLIGHT``, ``HEALTH`` and ``TRACER`` are deliberately per-process (the
instrumentation sites must not thread a recorder handle through every
layer), which means in-process multi-node harnesses — the e2e suites
and the chaos simulator (ISSUE 11) — all write into the SAME rings and
gauges. Before this module every such test hand-rolled its own subset
of ``.reset()`` calls, and a forgotten one leaked one scenario's rounds,
peer counters or missed-round marker into the next: exactly the kind of
cross-contamination that makes an SLI assertion pass for the wrong
reason.

:func:`reset_observability` is the one authoritative "back to boot
state" — every singleton, every time, so a new singleton added here is
picked up by every harness at once. :func:`isolated_observability`
scopes it: reset on enter AND on exit, so a scenario neither inherits
state nor bequeaths any (the exit half is what hand-rolled resets most
often forgot).

Prometheus counters/gauges are NOT rewound — prometheus state is
cumulative by design and every metric assertion in the tree reads
deltas (conftest.sample_count) — only the recorder/ring state that
snapshot-style assertions read directly.

Imports are lazy per the ``drand_tpu.obs`` cheapness rule: pulling this
module in costs nothing until a reset actually runs.
"""

from __future__ import annotations

from contextlib import contextmanager


def reset_observability() -> None:
    """Reset FLIGHT (rounds, peers, reachability, DKG timelines),
    HEALTH, TRACER, INCIDENTS (time-series ring + incident state) and
    the remediation ENGINE (ledger, budget, cooldowns, active markers)
    to boot state. Safe against concurrent note_* calls — each
    singleton's own reset carries its lock discipline."""
    from .flight import FLIGHT
    from .health import HEALTH
    from .incident import INCIDENTS
    from .remediate import ENGINE
    from .trace import TRACER

    FLIGHT.reset()
    HEALTH.reset()
    TRACER.reset()
    INCIDENTS.reset()
    ENGINE.reset()


@contextmanager
def isolated_observability():
    """Context manager for in-process multi-node harnesses: observability
    singletons are reset on entry (no inherited state) and again on exit
    (nothing leaks into the next scenario/test), even on failure."""
    reset_observability()
    try:
        yield
    finally:
        reset_observability()
