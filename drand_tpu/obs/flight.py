"""Threshold flight recorder: per-partial arrival telemetry, quorum
margins, and DKG phase timelines (ISSUE 10).

The paper's liveness property is t-of-n partial collection every
period, but the PR-1/PR-6 layers only see a round AFTER it aggregates
— span latency cannot answer "which node is chronically late?", "how
close did round R come to missing quorum?", or "where did the DKG
stall?". This module records the PROTOCOL-level events those questions
need, the way reference-network operators watch per-node partial
arrival to predict threshold loss before it becomes a missed round:

- every partial-signature event: sender share index, ingress source
  (``grpc`` handler vs ``gossip`` hop vs our own ``self`` broadcast),
  monotonic offset from the round's scheduled boundary, and the
  verify/dedup verdict;
- every aggregation milestone: the **quorum time** (arrival of the
  t-th valid partial — the moment the round became recoverable),
  recovery dispatch, store;
- the DKG/reshare path: phase transitions, deal/response/justification
  bundles seen per issuer, QUAL evolution — so a wedged DKG is
  diagnosable from ``/debug/flight/dkg`` instead of log archaeology.

Derived SLIs (metrics catalogue):

- ``beacon_quorum_margin_seconds`` = period − time-to-t-th-partial:
  the distance-to-missed-round early-warning signal. A healthy group
  holds margin ≈ period; a dying one watches it shrink toward 0 for
  rounds BEFORE ``beacon_rounds_missed_total`` ever fires.
- ``beacon_partial_arrival_seconds{source}``: valid-arrival offset
  from the boundary, split by ingress source.
- ``beacon_partial_events_total{index,event}``: per-peer contribution
  (``contributed``), lateness (``late`` = arrived more than period/2
  after the boundary), and ``invalid`` counters.
- ``beacon_contribution_gap``: group size minus distinct contributors
  of the last stored round (0 = full participation).
- ``dkg_phase_seconds{phase}``: DKG phase durations.

Recording is OFF the hot path by construction: every ``note_*`` is a
ring append under one lock — no pairing-class work, no I/O, no
awaits (analyzer-clean from the ingest path; ``bench.py
flight_overhead`` proves the cost on a 64-round follow). DoS posture:
only VALID events may create a ring entry — rejected future/stale/
invalid traffic appends to an existing round's record or is counted in
the per-peer counters only, so a flood of garbage rounds cannot evict
live flight records. Per-round event lists are bounded
(``max_events``, overflow counted in ``dropped``).

Secret hygiene: the recorder's API accepts indices, names, verdicts
and clock readings ONLY — shares (``pri_share``), partial-signature
bytes and keys never enter this module (asserted by
tests/test_zz_flight.py against a real DKG's secrets).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

# contribution-bitmap encoding (README Observability): one char per
# share index 0..n-1 of a round, rows = rounds in `drand util flight`
BITMAP_ONTIME = "#"    # valid partial within period/2 of the boundary
BITMAP_LATE = "~"      # valid but later than period/2
BITMAP_INVALID = "!"   # invalid partial(s) seen, no valid one
BITMAP_MISSING = "."   # nothing seen from this index

# verdicts recorded for partial events; "valid" is the only one that
# may CREATE a ring entry (see module docstring DoS posture)
VALID = "valid"
_PEER_EVENTS = ("contributed", "late", "invalid")


def _arrival_hist(source: str):
    """Branch-literal label values (check_metrics lints the enum from
    the literal call sites — same rule as crypto/batch._timed paths);
    unknown sources collapse to "grpc" rather than forking the series."""
    from .. import metrics

    if source == "gossip":
        return metrics.PARTIAL_ARRIVAL.labels(source="gossip")
    if source == "self":
        return metrics.PARTIAL_ARRIVAL.labels(source="self")
    return metrics.PARTIAL_ARRIVAL.labels(source="grpc")


def _phase_hist(phase: str):
    """Branch-literal DKG phase labels (see _arrival_hist)."""
    from .. import metrics

    if phase == "deal":
        return metrics.DKG_PHASE_SECONDS.labels(phase="deal")
    if phase == "response":
        return metrics.DKG_PHASE_SECONDS.labels(phase="response")
    if phase == "justification":
        return metrics.DKG_PHASE_SECONDS.labels(phase="justification")
    return metrics.DKG_PHASE_SECONDS.labels(phase="finish")


def _reject_counter(source: str, verdict: str):
    """Branch-literal source labels (the check_metrics enum rule);
    verdict values are the handler/gossip rejection strings — bounded
    by the code paths that mint them, passed through as-is."""
    from .. import metrics

    if source == "gossip":
        return metrics.INGRESS_REJECTS.labels(source="gossip",
                                              verdict=verdict)
    if source == "self":
        return metrics.INGRESS_REJECTS.labels(source="self",
                                              verdict=verdict)
    return metrics.INGRESS_REJECTS.labels(source="grpc", verdict=verdict)


def _send_counter(index: int, ok: bool):
    """Branch-literal outcome labels for beacon_peer_sends_total (the
    check_metrics KNOWN_LABEL_VALUES enum rule)."""
    from .. import metrics

    if ok:
        return metrics.PEER_SENDS.labels(outcome="ok", index=str(index))
    return metrics.PEER_SENDS.labels(outcome="failed", index=str(index))


def _repair_counter(outcome: str):
    """Branch-literal outcome labels for beacon_partial_repairs_total
    (the check_metrics KNOWN_LABEL_VALUES enum rule)."""
    from .. import metrics

    if outcome == "recovered":
        return metrics.PARTIAL_REPAIRS.labels(outcome="recovered")
    if outcome == "synced":
        return metrics.PARTIAL_REPAIRS.labels(outcome="synced")
    return metrics.PARTIAL_REPAIRS.labels(outcome="failed")


class FlightRecorder:
    """Bounded per-round ring of partial-arrival events + aggregation
    milestones, plus cumulative per-peer counters.

    ``max_rounds`` bounds retained rounds (FIFO eviction);
    ``max_events`` bounds each round's event list (a partial flood must
    not grow memory — overflow is counted in ``dropped``)."""

    def __init__(self, max_rounds: int = 128, max_events: int = 256):
        self.max_rounds = max_rounds
        self.max_events = max_events
        self._lock = threading.Lock()
        # round -> {"round","boundary","period","n","threshold",
        #           "quorum_offset_s","margin_s","events":[...],
        #           "milestones":[...],"dropped":int}
        self._rounds: OrderedDict[int, dict] = OrderedDict()
        # share index -> {"contributed","late","invalid"} totals
        self._peers: dict[int, dict] = {}
        # share index -> last outbound send succeeded (reachability;
        # fed by the handler's per-peer broadcast results)
        self._reach: dict[int, bool] = {}
        self.dkg = DKGFlight()

    # ------------------------------------------------------------ helpers
    @staticmethod
    def _offset(now: float, round_no: int, period: int, genesis: int) -> float:
        from ..chain import time_math

        return now - time_math.time_of_round(period, genesis, round_no)

    def _peer(self, index: int) -> dict:
        st = self._peers.get(index)
        if st is None:
            st = self._peers[index] = dict.fromkeys(_PEER_EVENTS, 0)
        return st

    def _get(self, round_no: int, create: bool, *, now: float, period: int,
             genesis: int, n: int | None = None,
             threshold: int | None = None) -> dict | None:
        rec = self._rounds.get(round_no)
        if rec is None:
            if not create:
                return None
            from ..chain import time_math

            rec = {"round": round_no,
                   "boundary": time_math.time_of_round(period, genesis,
                                                       round_no),
                   "period": period, "n": n, "threshold": threshold,
                   "quorum_offset_s": None, "margin_s": None,
                   # share index -> earliest valid arrival offset. The
                   # authority for dedup, bitmap and the contribution
                   # gap — NOT the capped event list, which an invalid
                   # flood can fill before an honest partial lands
                   "contrib": {}, "events": [], "milestones": [],
                   "dropped": 0}
            self._rounds[round_no] = rec
            while len(self._rounds) > self.max_rounds:
                self._rounds.popitem(last=False)
        else:
            if n is not None:
                rec["n"] = n
            if threshold is not None:
                rec["threshold"] = threshold
        return rec

    @staticmethod
    def _append(rec: dict, kind: str, item: dict, cap: int) -> None:
        if len(rec[kind]) >= cap:
            rec["dropped"] += 1
            return
        rec[kind].append(item)

    # ------------------------------------------------------------- inputs
    def note_partial(self, round_no: int, *, index: int | None, source: str,
                     verdict: str, now: float, period: int, genesis: int,
                     n: int | None = None, threshold: int | None = None,
                     sender: str | None = None) -> None:
        """One partial-signature ingress event. ``source`` is the enum
        {grpc, gossip, self}; ``verdict`` is ``valid`` or the rejection
        reason (invalid/stale/future/mismatch/duplicate). ``sender`` is
        a display tag only (hashed for gossip) — never a raw secret."""
        from .. import metrics

        offset = self._offset(now, round_no, period, genesis)
        valid = verdict == VALID
        late = valid and offset > period / 2
        # the index prefix is attacker-controlled bytes until the
        # signature verified, and even an "invalid" verdict's index is
        # only a claim — attribute to a peer (and mint a Prometheus
        # `index` label) ONLY for indices the group can actually hold,
        # so 2^16 garbage prefixes cannot bloat the peers table or the
        # beacon_partial_events_total cardinality
        attributable = (index is not None
                        and (n is None or 0 <= index < n))
        ev = {"t": now, "offset_s": round(offset, 6), "index": index,
              "source": source, "verdict": verdict}
        if sender is not None:
            ev["sender"] = sender
        with self._lock:
            rec = self._get(round_no, create=valid, now=now, period=period,
                            genesis=genesis, n=n, threshold=threshold)
            if valid and index is not None and rec is not None:
                if index in rec["contrib"]:
                    # a replayed/re-flooded copy of an already-recorded
                    # valid partial: visible in the event list, but it
                    # must not re-count the peer's contribution,
                    # re-feed the arrival histogram, or burn the
                    # counters a replay flood would otherwise inflate
                    valid = late = False
                    ev["verdict"] = verdict = "duplicate"
                else:
                    rec["contrib"][index] = ev["offset_s"]
            if rec is not None:
                self._append(rec, "events", ev, self.max_events)
            # per-peer attribution: contributions are signature-backed;
            # "invalid" counts only verification failures (window
            # rejects like stale/future stay visible in the round's
            # event list but never frame a peer's counters)
            if attributable:
                if valid:
                    st = self._peer(index)
                    st["contributed"] += 1
                    if late:
                        st["late"] += 1
                elif verdict == "invalid":
                    self._peer(index)["invalid"] += 1
        if valid:
            _arrival_hist(source).observe(max(0.0, offset))
        if ev["verdict"] != VALID:
            # every rejection — attributable or not — lands on the
            # flood/abuse counter (a garbage-prefix or window-reject
            # flood is otherwise invisible: it may not attribute to a
            # peer nor create a ring entry, by design)
            _reject_counter(source, ev["verdict"]).inc()
        if attributable:
            if valid:
                metrics.PARTIAL_EVENTS.labels(event="contributed",
                                              index=str(index)).inc()
                if late:
                    metrics.PARTIAL_EVENTS.labels(event="late",
                                                  index=str(index)).inc()
            elif verdict == "invalid":
                metrics.PARTIAL_EVENTS.labels(event="invalid",
                                              index=str(index)).inc()

    def note_send(self, index: int, ok: bool, *, n: int | None = None,
                  threshold: int | None = None) -> None:
        """One outbound partial-broadcast result to the group member at
        ``index`` (the handler's per-peer send fan-out). Maintains the
        per-peer reachability gauge and the partition-suspect count —
        the fault the quorum SLIs cannot see from the SENDING side: a
        partitioned node watches its peers go unreachable rounds before
        its own chain stalls. Out-of-group indices are ignored (same
        cardinality rule as note_partial attribution)."""
        from .. import metrics

        if n is not None and not 0 <= index < n:
            return
        with self._lock:
            changed = self._reach.get(index) is not ok
            self._reach[index] = ok
            suspects = sum(1 for up in self._reach.values() if not up)
        _send_counter(index, ok).inc()
        if changed:
            metrics.PEER_REACHABLE.labels(index=str(index)).set(
                1 if ok else 0)
        metrics.PARTITION_SUSPECTS.set(suspects)

    def reachability(self) -> dict[str, bool]:
        """Per-share-index reachability by last outbound send result
        (JSON-keyed; absent index = never sent to)."""
        with self._lock:
            return {str(i): up for i, up in sorted(self._reach.items())}

    def note_repair(self, round_no: int, *, outcome: str, pulled: int,
                    now: float, period: int, genesis: int) -> None:
        """One quorum-repair operation finished (ISSUE 12): the handler
        pulled missing partials because the round was still below
        threshold past the margin trigger. ``outcome`` is the enum
        recovered (pulls reached threshold) | synced (peers were
        already past the round; the beacon is being fetched instead) |
        failed. Lands as a ``repair`` milestone on the round's flight
        record (when one exists — repair never CREATES ring entries,
        same DoS rule as rejects) and on
        ``beacon_partial_repairs_total{outcome}``."""
        offset = self._offset(now, round_no, period, genesis)
        with self._lock:
            rec = self._get(round_no, create=False, now=now, period=period,
                            genesis=genesis)
            if rec is not None:
                self._append(rec, "milestones",
                             {"name": "repair", "t": now,
                              "offset_s": round(offset, 6),
                              "pulled": pulled,
                              "outcome": outcome}, self.max_events)
        _repair_counter(outcome).inc()

    def note_quorum(self, round_no: int, *, have: int, threshold: int,
                    now: float, period: int, genesis: int,
                    n: int | None = None) -> bool:
        """The t-th valid partial is in: the round became recoverable.
        Records the quorum time once per round and observes the
        quorum-margin SLI (period minus time-to-quorum — negative when
        quorum arrived after the round's whole period had passed).
        Returns True only on the FIRST quorum of the round, so callers
        can gate follow-up milestones on the same dedup."""
        from .. import metrics

        offset = self._offset(now, round_no, period, genesis)
        with self._lock:
            rec = self._get(round_no, create=True, now=now, period=period,
                            genesis=genesis, n=n, threshold=threshold)
            if rec["quorum_offset_s"] is not None:
                return False  # first quorum wins; never re-timed
            rec["quorum_offset_s"] = round(offset, 6)
            rec["margin_s"] = round(period - offset, 6)
            self._append(rec, "milestones",
                         {"name": "quorum", "t": now,
                          "offset_s": round(offset, 6), "have": have},
                         self.max_events)
        metrics.QUORUM_MARGIN.observe(period - offset)
        return True

    def note_milestone(self, round_no: int, name: str, *, now: float,
                       period: int, genesis: int) -> None:
        """An aggregation milestone (``recover`` dispatch, ``store``).
        On ``store`` the contribution-gap gauge is refreshed: group
        size minus distinct valid contributors of this round."""
        from .. import metrics

        offset = self._offset(now, round_no, period, genesis)
        gap = None
        with self._lock:
            rec = self._get(round_no, create=False, now=now, period=period,
                            genesis=genesis)
            if rec is None:
                return
            self._append(rec, "milestones",
                         {"name": name, "t": now,
                          "offset_s": round(offset, 6)}, self.max_events)
            if name == "store" and rec["n"]:
                gap = max(0, rec["n"] - len(rec["contrib"]))
        if gap is not None:
            metrics.CONTRIBUTION_GAP.set(gap)

    # ------------------------------------------------------------ outputs
    @staticmethod
    def _bitmap(rec: dict) -> str:
        """One char per share index (BITMAP_* encoding); '' when the
        group size was never learned for this round. Valid marks come
        from the contrib map (exact even when an event flood filled
        the capped list); invalid-only marks scan the event list —
        under a flood the invalid events ARE the flood."""
        n = rec.get("n")
        if not n:
            return ""
        half = rec["period"] / 2
        contrib = rec["contrib"]
        out = []
        for idx in range(n):
            if idx in contrib:
                out.append(BITMAP_LATE if contrib[idx] > half
                           else BITMAP_ONTIME)
            elif any(ev["index"] == idx and ev["verdict"] == "invalid"
                     for ev in rec["events"]):
                out.append(BITMAP_INVALID)
            else:
                out.append(BITMAP_MISSING)
        return "".join(out)

    def rounds(self, n: int = 16) -> list[dict]:
        """The last ``n`` round flight records, most recent first, each
        with its contribution bitmap rendered."""
        with self._lock:
            recs = list(self._rounds.values())[-n:] if n > 0 else []
            out = []
            for rec in reversed(recs):
                c = dict(rec)
                c["events"] = list(rec["events"])
                c["milestones"] = list(rec["milestones"])
                c["contrib"] = {str(i): off
                                for i, off in rec["contrib"].items()}
                c["bitmap"] = self._bitmap(rec)
                out.append(c)
        return out

    def peers(self) -> dict[str, dict]:
        """Cumulative per-share-index counters (JSON-keyed)."""
        with self._lock:
            return {str(i): dict(st)
                    for i, st in sorted(self._peers.items())}

    def reset(self) -> None:
        """Back to boot state (tests). Same lock discipline as
        Tracer.reset: a concurrent note_* either lands before the clear
        or re-creates a fresh record after it — never a KeyError."""
        with self._lock:
            self._rounds.clear()
            self._peers.clear()
            self._reach.clear()
        self.dkg.reset()


class DKGFlight:
    """Bounded ring of DKG/reshare session timelines.

    One session per protocol run, keyed by the session nonce; offsets
    are seconds since the session's ``begin`` on the protocol's own
    (injectable) clock, so FakeClock tests read exact phase math."""

    def __init__(self, max_sessions: int = 16, max_marks: int = 512):
        self.max_sessions = max_sessions
        self.max_marks = max_marks
        self._lock = threading.Lock()
        self._sessions: OrderedDict[str, dict] = OrderedDict()

    @staticmethod
    def session_id(nonce: bytes, tag: int | str | None = None) -> str:
        """Session key: nonce prefix, plus the node's own index — a
        production process runs one node, but in-process multi-node
        harnesses share the singleton and every node sees the SAME
        nonce (their timelines must not interleave)."""
        sid = nonce.hex()[:16]
        return sid if tag is None else f"{sid}/{tag}"

    def begin(self, nonce: bytes, *, mode: str, n_dealers: int,
              n_receivers: int, threshold: int, now: float,
              tag: int | str | None = None) -> str:
        sid = self.session_id(nonce, tag)
        with self._lock:
            self._sessions[sid] = {
                "session": sid, "mode": mode, "start": now,
                "n_dealers": n_dealers, "n_receivers": n_receivers,
                "threshold": threshold,
                "phases": [], "bundles": {"deal": {}, "response": {},
                                          "justification": {}},
                "qual": None, "complaints": {}, "rejects": [],
                "error": None, "done": False, "dropped": 0}
            while len(self._sessions) > self.max_sessions:
                self._sessions.popitem(last=False)
        return sid

    def _rec(self, sid: str) -> dict | None:
        return self._sessions.get(sid)

    def note_phase(self, sid: str, phase: str, *, now: float) -> None:
        """A phase transition: closes the open phase (observing
        ``dkg_phase_seconds{phase}``) and opens ``phase``."""
        from .. import metrics

        dur = None
        prev = None
        with self._lock:
            rec = self._rec(sid)
            if rec is None:
                return
            off = now - rec["start"]
            if rec["phases"] and rec["phases"][-1].get("end_s") is None:
                prev = rec["phases"][-1]
                prev["end_s"] = round(off, 6)
                dur = prev["end_s"] - prev["start_s"]
            rec["phases"].append({"phase": phase,
                                  "start_s": round(off, 6), "end_s": None})
        if prev is not None and dur is not None:
            _phase_hist(prev["phase"]).observe(max(0.0, dur))

    def note_bundle(self, sid: str, kind: str, issuer: int, *,
                    now: float) -> None:
        """A deal/response/justification bundle was accepted from
        ``issuer`` (first arrival per issuer wins)."""
        with self._lock:
            rec = self._rec(sid)
            if rec is None:
                return
            seen = rec["bundles"].setdefault(kind, {})
            if str(issuer) in seen:
                return
            if sum(len(v) for v in rec["bundles"].values()) >= self.max_marks:
                rec["dropped"] += 1
                return
            seen[str(issuer)] = round(now - rec["start"], 6)

    def note_reject(self, sid: str, phase: str, issuer: int, verdict: str,
                    *, now: float) -> None:
        """A bundle/item from ``issuer`` was rejected during ``phase``
        verification (verdict names the failed check) — the timeline
        shows WHO misbehaved, not just that the count dropped."""
        with self._lock:
            rec = self._rec(sid)
            if rec is None:
                return
            if len(rec["rejects"]) >= self.max_marks:
                rec["dropped"] += 1
                return
            rec["rejects"].append({"phase": phase, "issuer": issuer,
                                   "verdict": verdict,
                                   "t": round(now - rec["start"], 6)})

    def finish(self, sid: str, *, now: float, qual: list[int] | None = None,
               complaints: dict | None = None,
               error: str | None = None) -> None:
        """Close the session: QUAL (or the failure), open-complaint map
        {dealer: [share idxs]}, and the final phase's duration."""
        closed = None
        with self._lock:
            rec = self._rec(sid)
            if rec is None:
                return
            off = now - rec["start"]
            if rec["phases"] and rec["phases"][-1].get("end_s") is None:
                closed = rec["phases"][-1]
                closed["end_s"] = round(off, 6)
            rec["qual"] = list(qual) if qual is not None else None
            rec["complaints"] = {str(k): sorted(v) for k, v in
                                 (complaints or {}).items() if v}
            rec["error"] = error
            rec["done"] = True
        if closed is not None:
            _phase_hist(closed["phase"]).observe(
                max(0.0, closed["end_s"] - closed["start_s"]))

    def sessions(self) -> list[dict]:
        """All retained sessions, most recent first (deep-ish copies)."""
        with self._lock:
            out = []
            for rec in reversed(self._sessions.values()):
                c = dict(rec)
                c["phases"] = [dict(p) for p in rec["phases"]]
                c["bundles"] = {k: dict(v)
                                for k, v in rec["bundles"].items()}
                c["complaints"] = dict(rec["complaints"])
                c["rejects"] = [dict(r) for r in rec["rejects"]]
                out.append(c)
        return out

    def reset(self) -> None:
        with self._lock:
            self._sessions.clear()


# The per-process recorder every instrumentation site shares (the ring
# is per-process by design, like TRACER and HEALTH).
FLIGHT = FlightRecorder()
