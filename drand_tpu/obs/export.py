"""OTLP export of the round-trace ring (ISSUE 6, Dapper-style
completion of the PR-1 tracing layer).

The Span model is already W3C-shaped (32-hex trace ids, 16-hex span
ids), so serializing a ring record to OTLP/JSON ``resourceSpans`` is a
pure reshaping — no OTel SDK needed (none in this image).

Sinks, in order:

- ``DRAND_TPU_OTLP_ENDPOINT``: POST one OTLP/JSON export request per
  completed round to ``<endpoint>/v1/traces`` (or verbatim when the
  URL already ends in ``/v1/traces``) — the standard OTLP/HTTP path a
  collector expects.
- ``DRAND_TPU_OTLP_SPOOL``: append one NDJSON line per round to a
  bounded on-disk ring, so traces survive restarts and can be shipped
  later (``read_spool`` parses them back). When the file exceeds
  ``DRAND_TPU_OTLP_SPOOL_MAX`` bytes (default 4 MiB) it rotates to
  ``<path>.1`` (previous ``.1`` dropped) — disk use is bounded at ~2x
  the cap. The spool is ALSO the fallback when a configured endpoint
  POST fails, so a collector outage loses nothing.

With neither env var set the exporter is off — no surprise disk writes
or sockets from library use.

Flushing is per COMPLETED round and never on the hot path: the store
decorator calls :func:`note_round_complete`, which defers the ring
lookup + serialization + I/O with ``loop.call_soon`` (so the round's
``store`` span has closed by the time we read the ring) and runs the
POST in a background task. Outside an event loop it flushes inline —
that only happens in synchronous tools and tests.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading

from .trace import TRACER, round_trace_id

_SPAN_KIND_INTERNAL = 1


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}  # OTLP/JSON carries int64 as string
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def _nanos(t: float | None) -> str:
    return str(int((t or 0.0) * 1e9))


def round_to_otlp(rec: dict, resource_attrs: dict | None = None) -> dict:
    """One tracer ring record (``{"trace_id","round","spans",...}``) ->
    one OTLP/JSON ExportTraceServiceRequest body."""
    spans = []
    for sp in rec.get("spans", ()):
        attrs = [_attr(k, v) for k, v in (sp.get("attrs") or {}).items()]
        if rec.get("round") is not None:
            attrs.append(_attr("drand.round", rec["round"]))
        spans.append({
            "traceId": rec["trace_id"],
            "spanId": sp["span_id"],
            "parentSpanId": sp.get("parent_id") or "",
            "name": sp["name"],
            "kind": _SPAN_KIND_INTERNAL,
            "startTimeUnixNano": _nanos(sp.get("start")),
            "endTimeUnixNano": _nanos(sp.get("end")),
            "attributes": attrs,
            "status": {},
        })
    res_attrs = [_attr("service.name", "drand-tpu")]
    for k, v in (resource_attrs or {}).items():
        res_attrs.append(_attr(k, v))
    return {"resourceSpans": [{
        "resource": {"attributes": res_attrs},
        "scopeSpans": [{
            "scope": {"name": "drand_tpu.obs", "version": "1"},
            "spans": spans,
        }],
    }]}


def read_spool(path: str) -> list[dict]:
    """Parse the NDJSON spool (current file plus the rotated ``.1`` when
    present, oldest first) back into OTLP export dicts."""
    out: list[dict] = []
    for p in (path + ".1", path):
        if not os.path.isfile(p):
            continue
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


class OTLPExporter:
    def __init__(self, endpoint: str | None = None,
                 spool_path: str | None = None,
                 max_spool_bytes: int = 4 << 20,
                 resource_attrs: dict | None = None,
                 timeout: float = 5.0):
        self.endpoint = endpoint
        if endpoint and not endpoint.rstrip("/").endswith("/v1/traces"):
            self.endpoint = endpoint.rstrip("/") + "/v1/traces"
        self.spool_path = spool_path
        self.max_spool_bytes = max_spool_bytes
        self.resource_attrs = dict(resource_attrs or {})
        self.timeout = timeout
        self._spool_lock = threading.Lock()
        # one long-lived HTTP session per (exporter, event loop): a
        # fresh session per round would re-handshake TCP/TLS to the
        # collector every period, forever
        self._session = None
        self._session_loop = None

    @property
    def active(self) -> bool:
        return bool(self.endpoint or self.spool_path)

    # ------------------------------------------------------------- sinks
    def _count(self, sink: str) -> None:
        from .. import metrics

        metrics.OTLP_EXPORT_ROUNDS.labels(sink=sink).inc()

    def spool(self, payload: dict) -> bool:
        """Append one export payload to the bounded NDJSON ring."""
        if not self.spool_path:
            return False
        line = json.dumps(payload, separators=(",", ":")) + "\n"
        try:
            with self._spool_lock:
                d = os.path.dirname(self.spool_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                try:
                    size = os.path.getsize(self.spool_path)
                except OSError:
                    size = 0
                if size + len(line) > self.max_spool_bytes and size > 0:
                    os.replace(self.spool_path, self.spool_path + ".1")
                with open(self.spool_path, "a", encoding="utf-8") as f:
                    f.write(line)
            return True
        except OSError:
            return False

    async def _get_session(self):
        """The cached collector session, rebuilt when absent, closed,
        or bound to a previous event loop (sessions are loop-bound;
        tests run one loop per test)."""
        import aiohttp

        loop = asyncio.get_running_loop()
        if (self._session is None or self._session.closed
                or self._session_loop is not loop):
            if self._session is not None and not self._session.closed:
                try:
                    await self._session.close()
                except Exception:  # noqa: BLE001 — cross-loop close is
                    pass           # best-effort; the old loop is gone
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout))
            self._session_loop = loop
        return self._session

    async def _post(self, payload: dict) -> bool:
        try:
            s = await self._get_session()
            async with s.post(self.endpoint, json=payload) as r:
                return r.status < 300
        except Exception:  # noqa: BLE001 — collector outage is routine
            return False

    # ------------------------------------------------------------ export
    def export_round_sync(self, rec: dict) -> str:
        """Spool-only synchronous export (no loop): 'spool'/'dropped'."""
        payload = round_to_otlp(rec, self.resource_attrs)
        sink = "spool" if self.spool(payload) else "dropped"
        self._count(sink)
        return sink

    async def export_round(self, rec: dict) -> str:
        """POST when an endpoint is configured, spool as the fallback
        (and as the primary sink when no endpoint is set)."""
        payload = round_to_otlp(rec, self.resource_attrs)
        if self.endpoint and await self._post(payload):
            self._count("http")
            return "http"
        sink = "spool" if self.spool(payload) else "dropped"
        self._count(sink)
        return sink


# ---------------------------------------------------------------------------
# Per-process exporter + the store-side hook
# ---------------------------------------------------------------------------

_EXPORTER: OTLPExporter | None = None
_CONFIGURED = False


def exporter() -> OTLPExporter | None:
    """The env-configured per-process exporter, or None when neither
    DRAND_TPU_OTLP_ENDPOINT nor DRAND_TPU_OTLP_SPOOL is set."""
    global _EXPORTER, _CONFIGURED
    if not _CONFIGURED:
        endpoint = os.environ.get("DRAND_TPU_OTLP_ENDPOINT") or None
        spool = os.environ.get("DRAND_TPU_OTLP_SPOOL") or None
        if endpoint or spool:
            _EXPORTER = OTLPExporter(
                endpoint=endpoint, spool_path=spool,
                max_spool_bytes=int(os.environ.get(
                    "DRAND_TPU_OTLP_SPOOL_MAX", str(4 << 20))))
        _CONFIGURED = True
    return _EXPORTER


def reset_exporter() -> None:
    """Drop the cached exporter so env changes take effect (tests)."""
    global _EXPORTER, _CONFIGURED
    _EXPORTER = None
    _CONFIGURED = False


# strong references to in-flight export tasks: the loop holds tasks
# weakly, and a GC'd task would silently drop a round's trace
_PENDING_TASKS: set = set()


def note_round_complete(round_no: int, chain: bytes | str = b"") -> None:
    """A round's beacon was stored: flush its timeline off the hot path.
    Deferred one loop turn so the caller's still-open spans (``store``)
    land in the exported record; a no-op when the exporter is off or
    the ring holds nothing for the round — catch-up traffic is
    retain=False and never creates ring entries, so a node replaying a
    year-old chain schedules nothing per historical round."""
    exp = exporter()
    if exp is None or not exp.active:
        return
    trace_id = round_trace_id(round_no, chain)
    if TRACER.get_trace(trace_id) is None:
        return

    async def _flush_async() -> None:
        rec = TRACER.get_trace(trace_id)
        if rec and rec["spans"]:
            await exp.export_round(rec)

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        rec = TRACER.get_trace(trace_id)
        if rec and rec["spans"]:
            exp.export_round_sync(rec)
        return

    def _spawn() -> None:
        task = loop.create_task(_flush_async())
        _PENDING_TASKS.add(task)
        task.add_done_callback(_PENDING_TASKS.discard)

    loop.call_soon(_spawn)
