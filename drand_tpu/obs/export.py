"""OTLP export of the round-trace ring (ISSUE 6, Dapper-style
completion of the PR-1 tracing layer).

The Span model is already W3C-shaped (32-hex trace ids, 16-hex span
ids), so serializing a ring record to OTLP/JSON ``resourceSpans`` is a
pure reshaping — no OTel SDK needed (none in this image).

Sinks, in order:

- ``DRAND_TPU_OTLP_ENDPOINT``: POST one OTLP/JSON export request per
  completed round to ``<endpoint>/v1/traces`` (or verbatim when the
  URL already ends in ``/v1/traces``) — the standard OTLP/HTTP path a
  collector expects.
- ``DRAND_TPU_OTLP_SPOOL``: append one NDJSON line per round to a
  bounded on-disk ring, so traces survive restarts and can be shipped
  later (``read_spool`` parses them back). When the file exceeds
  ``DRAND_TPU_OTLP_SPOOL_MAX`` bytes (default 4 MiB) it rotates to
  ``<path>.1`` (previous ``.1`` dropped) — disk use is bounded at ~2x
  the cap. The spool is ALSO the fallback when a configured endpoint
  POST fails, so a collector outage loses nothing.

With neither env var set the exporter is off — no surprise disk writes
or sockets from library use.

A spool written while the collector was down (or on an offline relay)
is shipped later with :func:`ship_spool` — batch re-POST with
retry/backoff, truncating the ring on full success (``drand
relay-archive`` runs it when both env vars are set). Per-node resource
attributes (``drand.node.address``) are exported ONLY under
``DRAND_TPU_OTLP_NODE_ATTRS=1`` — see :func:`set_node_address` for the
privacy rationale.

Flushing is per COMPLETED round and never on the hot path: the store
decorator calls :func:`note_round_complete`, which defers the ring
lookup + serialization + I/O with ``loop.call_soon`` (so the round's
``store`` span has closed by the time we read the ring) and runs the
POST in a background task. Outside an event loop it flushes inline —
that only happens in synchronous tools and tests.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading

from .trace import TRACER, round_trace_id

_SPAN_KIND_INTERNAL = 1

# per-node resource attrs (ISSUE 10 satellite; PR-6 follow-on). The
# daemon registers its address at boot, but the attribute is OFF unless
# DRAND_TPU_OTLP_NODE_ATTRS=1: exported spans may land on a SHARED or
# public collector, and a node address on every span maps the group's
# topology to whoever reads it — the same reason gossip spans carry a
# keyed HASH of the sender instead of the raw peer IP. Operators who
# run their own collector opt in explicitly.
_NODE_ADDRESS: str | None = None


def set_node_address(addr: str) -> None:
    """Register this process's node address for span resource attrs
    (only exported when DRAND_TPU_OTLP_NODE_ATTRS=1 — see above)."""
    global _NODE_ADDRESS
    _NODE_ADDRESS = addr


def _node_resource_attrs() -> dict:
    """Read at EXPORT time, not exporter construction: the daemon may
    register its address after the first env-configured exporter was
    built, and tests flip the env per case."""
    if os.environ.get("DRAND_TPU_OTLP_NODE_ATTRS") == "1" and _NODE_ADDRESS:
        return {"drand.node.address": _NODE_ADDRESS}
    return {}


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}  # OTLP/JSON carries int64 as string
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def _nanos(t: float | None) -> str:
    return str(int((t or 0.0) * 1e9))


def round_to_otlp(rec: dict, resource_attrs: dict | None = None) -> dict:
    """One tracer ring record (``{"trace_id","round","spans",...}``) ->
    one OTLP/JSON ExportTraceServiceRequest body."""
    spans = []
    for sp in rec.get("spans", ()):
        attrs = [_attr(k, v) for k, v in (sp.get("attrs") or {}).items()]
        if rec.get("round") is not None:
            attrs.append(_attr("drand.round", rec["round"]))
        spans.append({
            "traceId": rec["trace_id"],
            "spanId": sp["span_id"],
            "parentSpanId": sp.get("parent_id") or "",
            "name": sp["name"],
            "kind": _SPAN_KIND_INTERNAL,
            "startTimeUnixNano": _nanos(sp.get("start")),
            "endTimeUnixNano": _nanos(sp.get("end")),
            "attributes": attrs,
            "status": {},
        })
    res_attrs = [_attr("service.name", "drand-tpu")]
    for k, v in (resource_attrs or {}).items():
        res_attrs.append(_attr(k, v))
    return {"resourceSpans": [{
        "resource": {"attributes": res_attrs},
        "scopeSpans": [{
            "scope": {"name": "drand_tpu.obs", "version": "1"},
            "spans": spans,
        }],
    }]}


def read_spool(path: str) -> list[dict]:
    """Parse the NDJSON spool (current file plus the rotated ``.1`` when
    present, oldest first) back into OTLP export dicts. Unparseable
    lines are skipped: a daemon killed mid-append leaves a truncated
    final line, and one bad telemetry line must never wedge a consumer
    (the relay-archive shipper runs this on every ship cycle)."""
    out: list[dict] = []
    for p in (path + ".1", path):
        if not os.path.isfile(p):
            continue
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out


class OTLPExporter:
    def __init__(self, endpoint: str | None = None,
                 spool_path: str | None = None,
                 max_spool_bytes: int = 4 << 20,
                 resource_attrs: dict | None = None,
                 timeout: float = 5.0):
        self.endpoint = _endpoint_url(endpoint) if endpoint else endpoint
        self.spool_path = spool_path
        self.max_spool_bytes = max_spool_bytes
        self.resource_attrs = dict(resource_attrs or {})
        self.timeout = timeout
        self._spool_lock = threading.Lock()
        # one long-lived HTTP session per (exporter, event loop): a
        # fresh session per round would re-handshake TCP/TLS to the
        # collector every period, forever. The rebuild is single-flight
        # per loop (_session_lock): two concurrent exports racing the
        # check would otherwise both build a session and leak one
        # unclosed (tools/analyze awaitatomic). asyncio locks are
        # loop-bound, so the lock is rebuilt alongside the session when
        # the loop changes — that swap is purely synchronous.
        self._session = None
        self._session_loop = None
        self._session_lock = None
        self._session_lock_loop = None

    @property
    def active(self) -> bool:
        return bool(self.endpoint or self.spool_path)

    # ------------------------------------------------------------- sinks
    def _count(self, sink: str) -> None:
        from .. import metrics

        metrics.OTLP_EXPORT_ROUNDS.labels(sink=sink).inc()

    def spool(self, payload: dict) -> bool:
        """Append one export payload to the bounded NDJSON ring."""
        if not self.spool_path:
            return False
        line = json.dumps(payload, separators=(",", ":")) + "\n"
        try:
            with self._spool_lock:
                d = os.path.dirname(self.spool_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                try:
                    size = os.path.getsize(self.spool_path)
                except OSError:
                    size = 0
                if size + len(line) > self.max_spool_bytes and size > 0:
                    os.replace(self.spool_path, self.spool_path + ".1")
                with open(self.spool_path, "a", encoding="utf-8") as f:
                    f.write(line)
            return True
        except OSError:
            return False

    async def _get_session(self):
        """The cached collector session, rebuilt when absent, closed,
        or bound to a previous event loop (sessions are loop-bound;
        tests run one loop per test)."""
        import aiohttp

        loop = asyncio.get_running_loop()
        if self._session_lock is None or self._session_lock_loop is not loop:
            # no suspension point between this check and the swap, so
            # the lock replacement itself cannot interleave
            self._session_lock = asyncio.Lock()
            self._session_lock_loop = loop
        async with self._session_lock:
            if (self._session is None or self._session.closed
                    or self._session_loop is not loop):
                if self._session is not None and not self._session.closed:
                    try:
                        await self._session.close()
                    except Exception:  # noqa: BLE001 — cross-loop close
                        pass           # is best-effort; old loop is gone
                self._session = aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=self.timeout))
                self._session_loop = loop
        return self._session

    async def _post(self, payload: dict) -> bool:
        try:
            s = await self._get_session()
            async with s.post(self.endpoint, json=payload) as r:
                return r.status < 300
        except Exception:  # noqa: BLE001 — collector outage is routine
            return False

    # ------------------------------------------------------------ export
    def _payload(self, rec: dict) -> dict:
        return round_to_otlp(rec, {**self.resource_attrs,
                                   **_node_resource_attrs()})

    def export_round_sync(self, rec: dict) -> str:
        """Spool-only synchronous export (no loop): 'spool'/'dropped'."""
        payload = self._payload(rec)
        sink = "spool" if self.spool(payload) else "dropped"
        self._count(sink)
        return sink

    async def export_round(self, rec: dict) -> str:
        """POST when an endpoint is configured, spool as the fallback
        (and as the primary sink when no endpoint is set)."""
        payload = self._payload(rec)
        if self.endpoint and await self._post(payload):
            self._count("http")
            return "http"
        sink = "spool" if self.spool(payload) else "dropped"
        self._count(sink)
        return sink


def _endpoint_url(endpoint: str) -> str:
    """Normalize a collector base URL to its /v1/traces path (the same
    rule the exporter applies)."""
    if endpoint.rstrip("/").endswith("/v1/traces"):
        return endpoint
    return endpoint.rstrip("/") + "/v1/traces"


async def ship_spool(path: str, endpoint: str, *, batch_size: int = 32,
                     attempts: int = 3, backoff: float = 0.5,
                     timeout: float = 10.0) -> dict:
    """Ship a spooled NDJSON ring to a collector: batch re-POST of
    :func:`read_spool` output, with per-batch retry/backoff, and spool
    truncation on FULL success (the relay-archive follow-on from
    ISSUE 6).

    Each batch merges up to ``batch_size`` spooled export requests'
    ``resourceSpans`` into one OTLP/JSON request (the protocol is a
    list — a collector ingests the merge exactly as it would the
    originals). A batch that still fails after ``attempts`` tries
    aborts the ship and LEAVES the spool intact (already-shipped
    batches are re-sent next time: re-POSTing a span is idempotent for
    any store keyed on span ids, and losing traces is worse). On full
    success both ring files are deleted. Caller owns exclusivity — the
    shipper is for offline/relay processes, not a live exporter's own
    spool."""
    import aiohttp

    from .. import metrics

    docs = read_spool(path)
    if not docs:
        return {"shipped": 0, "batches": 0, "ok": True}
    url = _endpoint_url(endpoint)
    shipped = 0
    batches = 0
    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout)) as s:
        for lo in range(0, len(docs), batch_size):
            chunk = docs[lo:lo + batch_size]
            payload = {"resourceSpans": [rs for doc in chunk
                                         for rs in
                                         doc.get("resourceSpans", [])]}
            ok = False
            for attempt in range(attempts):
                try:
                    async with s.post(url, json=payload) as r:
                        ok = r.status < 300
                except Exception:  # noqa: BLE001 — collector outage
                    ok = False
                if ok:
                    break
                await asyncio.sleep(backoff * (2 ** attempt))
            if not ok:
                return {"shipped": shipped, "batches": batches,
                        "ok": False, "failed_at": lo}
            shipped += len(chunk)
            batches += 1
    for p in (path, path + ".1"):
        try:
            os.remove(p)
        except OSError:
            pass
    metrics.OTLP_EXPORT_ROUNDS.labels(sink="http").inc(shipped)
    return {"shipped": shipped, "batches": batches, "ok": True}


# ---------------------------------------------------------------------------
# Per-process exporter + the store-side hook
# ---------------------------------------------------------------------------

_EXPORTER: OTLPExporter | None = None
_CONFIGURED = False


def exporter() -> OTLPExporter | None:
    """The env-configured per-process exporter, or None when neither
    DRAND_TPU_OTLP_ENDPOINT nor DRAND_TPU_OTLP_SPOOL is set."""
    global _EXPORTER, _CONFIGURED
    if not _CONFIGURED:
        endpoint = os.environ.get("DRAND_TPU_OTLP_ENDPOINT") or None
        spool = os.environ.get("DRAND_TPU_OTLP_SPOOL") or None
        if endpoint or spool:
            _EXPORTER = OTLPExporter(
                endpoint=endpoint, spool_path=spool,
                max_spool_bytes=int(os.environ.get(
                    "DRAND_TPU_OTLP_SPOOL_MAX", str(4 << 20))))
        _CONFIGURED = True
    return _EXPORTER


def reset_exporter() -> None:
    """Drop the cached exporter so env changes take effect (tests)."""
    global _EXPORTER, _CONFIGURED
    _EXPORTER = None
    _CONFIGURED = False


# strong references to in-flight export tasks: the loop holds tasks
# weakly, and a GC'd task would silently drop a round's trace
_PENDING_TASKS: set = set()


def note_round_complete(round_no: int, chain: bytes | str = b"") -> None:
    """A round's beacon was stored: flush its timeline off the hot path.
    Deferred one loop turn so the caller's still-open spans (``store``)
    land in the exported record; a no-op when the exporter is off or
    the ring holds nothing for the round — catch-up traffic is
    retain=False and never creates ring entries, so a node replaying a
    year-old chain schedules nothing per historical round."""
    exp = exporter()
    if exp is None or not exp.active:
        return
    trace_id = round_trace_id(round_no, chain)
    if TRACER.get_trace(trace_id) is None:
        return

    async def _flush_async() -> None:
        rec = TRACER.get_trace(trace_id)
        if rec and rec["spans"]:
            await exp.export_round(rec)

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        rec = TRACER.get_trace(trace_id)
        if rec and rec["spans"]:
            exp.export_round_sync(rec)
        return

    def _spawn() -> None:
        task = loop.create_task(_flush_async())
        _PENDING_TASKS.add(task)
        task.add_done_callback(_PENDING_TASKS.discard)

    loop.call_soon(_spawn)
