"""SLI time-series ring: the metrics catalogue, sampled over time
(ISSUE 15).

Everything the observability stack exposes so far is live-state only —
the trace/flight rings evict, the gauges overwrite, and once a fault
heals the evidence is gone. This module keeps a bounded in-process ring
of **samples**: one flat dict per round boundary (plus on-demand pulls
from /healthz probes) holding every SLI the incident rules evaluate —
quorum margin, head/lag, the missed-round counter, peer
reachability/partition suspects, breaker states, ingress rejects,
watcher sheds, sync stall, readiness.

Counters are **delta-aware**: each sample records the cumulative value
AND the delta vs the previous sample (clamped at ≥0, so a process
restart's counter reset never reads as a negative spike). Rules over
"did X increment this round?" read the delta; trend rules read the
cumulative series.

History survives restarts via an NDJSON spool with the OTLP-spool
rotation pattern (obs/export.py): one line per sample, rotate to
``<path>.1`` past the byte cap, read back with
:func:`drand_tpu.obs.export.read_spool` — a consumer of the OTLP spool
already knows how to read this one. Durability contract: writes are
buffered (a flush syscall between two pairing verifies costs real
milliseconds on overlay filesystems) and flushed every
``FLUSH_EVERY`` samples; every incident mint/close force-flushes, so
a SIGKILL can lose at most ``FLUSH_EVERY`` *healthy* samples — never
the window around a detection.

Sampling is cheap by construction: dict reads off the health/flight
snapshots, three bounded ``collect()`` walks over the relevant metric
families, one optional file append. No pairing-class work, no awaits
(``bench.py incident_overhead`` proves ≤2% on a 64-round follow).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

# counter-valued sample keys whose per-sample delta the rules consume
COUNTER_KEYS = ("missed_total", "ingress_rejects", "watcher_shed")


def _counter_total(metric) -> float:
    """Cumulative value of a prometheus Counter summed over its label
    combinations (the ``_created`` bookkeeping samples are skipped)."""
    total = 0.0
    for fam in metric.collect():
        for s in fam.samples:
            if s.name.endswith("_total"):
                total += s.value
    return total


def _gauge_by_label(metric, label: str) -> dict[str, float]:
    """Current per-label values of a labelled prometheus Gauge."""
    out: dict[str, float] = {}
    for fam in metric.collect():
        for s in fam.samples:
            key = s.labels.get(label)
            if key is not None:
                out[key] = s.value
    return out


def collect_sample(now: float, *, flight, health, period: float | None,
                   round_no: int | None = None) -> dict:
    """One flat SLI sample off the live surfaces: the health snapshot
    (head/lag/missed/stall/readiness), the flight recorder's newest
    round record (quorum margin + its round), reachability suspects,
    the global breaker-state gauge, and the flood/shed counters. The
    caller owns WHEN (round boundary or probe); this function only
    reads."""
    from .. import metrics
    from .health import is_ready

    snap = health.snapshot()
    margin = None
    flight_round = None
    for rec in flight.rounds(1):
        margin = rec.get("margin_s")
        flight_round = rec.get("round")
    reach = flight.reachability()
    breakers = _gauge_by_label(metrics.PEER_BREAKER_STATE, "index")
    sample = {
        "t": round(now, 6),
        "round": round_no,
        "head": snap["head_round"],
        "lag": snap["lag_rounds"],
        "missed_total": snap["missed_total"],
        "sync_stalled": bool(snap["sync_stalled"]),
        "ready": bool(snap["dkg_complete"]) and is_ready(snap),
        "margin_s": margin,
        "flight_round": flight_round,
        "suspects": sum(1 for up in reach.values() if not up),
        "breakers_open": sum(1 for v in breakers.values() if v >= 2),
        "ingress_rejects": _counter_total(metrics.INGRESS_REJECTS),
        "watcher_shed": _counter_total(metrics.RELAY_SHED),
    }
    if period is not None:
        sample["period"] = period
    return sample


class TimeSeriesRing:
    """Bounded sample ring + optional NDJSON disk spool.

    ``append`` computes the counter deltas against the PREVIOUS sample
    (spool-restored history counts: a restart's first live sample
    deltas against the last spooled one, clamped at ≥0 because the
    in-process counters restarted at zero)."""

    def __init__(self, max_samples: int = 512,
                 spool_path: str | None = None,
                 max_spool_bytes: int = 4 << 20):
        self.max_samples = max_samples
        self.spool_path = spool_path
        self.max_spool_bytes = max_spool_bytes
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=max_samples)
        # cached append handle + tracked size: one buffered write per
        # sample instead of makedirs/stat/open/flush per line (each fs
        # syscall between two pairing verifies costs ~2-4 ms on this
        # box's overlay fs — bench incident_overhead's 2% bar caught
        # it). Flushed every FLUSH_EVERY samples, on rotation/close,
        # and explicitly when an incident is minted (forensic moments
        # get durability; steady state gets the buffer).
        self._spool_f = None
        self._spool_size = 0
        self._spool_unflushed = 0

    FLUSH_EVERY = 32

    def set_spool(self, path: str | None) -> None:
        """Swap the spool target (closes any cached handle first)."""
        with self._lock:
            if self._spool_f is not None and not self._spool_f.closed:
                try:
                    self._spool_f.close()
                except OSError:
                    pass
            self._spool_f = None
            self._spool_size = 0
            self.spool_path = path

    # ------------------------------------------------------------ inputs
    def append(self, sample: dict) -> dict:
        """Delta-annotate ``sample``, ring it, spool it. Returns the
        annotated sample (the one the rules see)."""
        with self._lock:
            prev = self._ring[-1] if self._ring else None
            deltas = {}
            for key in COUNTER_KEYS:
                cur = sample.get(key)
                if cur is None:
                    deltas[key] = 0.0
                    continue
                base = prev.get(key) if prev else None
                deltas[key] = max(0.0, cur - base) if base is not None \
                    else 0.0
            sample = dict(sample)
            sample["deltas"] = deltas
            self._ring.append(sample)
        self._spool(sample)
        return sample

    def load_spool(self) -> int:
        """Restore ring state from the spool (newest ``max_samples``
        lines win). Returns how many samples were restored — restart
        persistence for trend rules and post-mortem windows."""
        if not self.spool_path:
            return 0
        from .export import read_spool

        self.flush()  # read-your-writes within one process
        docs = [d for d in read_spool(self.spool_path)
                if isinstance(d, dict) and "t" in d]
        if not docs:
            return 0
        with self._lock:
            for d in docs[-self.max_samples:]:
                d.setdefault("deltas",
                             dict.fromkeys(COUNTER_KEYS, 0.0))
                # restored samples are HISTORY, not live observations:
                # state-flip rules (readiness_flip) must not treat a
                # pre-restart "ready" as a live baseline, or every
                # restart that needs catch-up mints a spurious critical
                d["restored"] = True
                self._ring.append(d)
            return len(self._ring)

    def _spool(self, sample: dict) -> None:
        """The OTLP-spool pattern (obs/export.py): append one NDJSON
        line, rotate to ``.1`` past the cap — disk bounded at ~2x. The
        handle is opened once and kept (size tracked in memory); each
        line is flushed so a crash loses at most the torn final line
        read_spool already tolerates."""
        if not self.spool_path:
            return
        line = json.dumps(sample, separators=(",", ":")) + "\n"
        try:
            with self._lock:
                if self._spool_f is None or self._spool_f.closed:
                    d = os.path.dirname(self.spool_path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._spool_f = open(self.spool_path, "a",
                                         encoding="utf-8")
                    self._spool_size = self._spool_f.tell()
                if self._spool_size + len(line) > self.max_spool_bytes \
                        and self._spool_size > 0:
                    self._spool_f.close()
                    os.replace(self.spool_path, self.spool_path + ".1")
                    self._spool_f = open(self.spool_path, "a",
                                         encoding="utf-8")
                    self._spool_size = 0
                    self._spool_unflushed = 0
                self._spool_f.write(line)
                self._spool_size += len(line)
                self._spool_unflushed += 1
                if self._spool_unflushed >= self.FLUSH_EVERY:
                    self._spool_f.flush()
                    self._spool_unflushed = 0
        except OSError:
            pass  # forensics must never take the beacon plane down

    def flush(self) -> None:
        """Force buffered spool lines to disk (incident mints, tests,
        graceful handover)."""
        with self._lock:
            if self._spool_f is not None and not self._spool_f.closed:
                try:
                    self._spool_f.flush()
                    self._spool_unflushed = 0
                except OSError:
                    pass

    # ------------------------------------------------------------ outputs
    def window(self, n: int | None = None) -> list[dict]:
        """The last ``n`` samples (all when None), oldest first."""
        with self._lock:
            samples = list(self._ring)
        return samples if n is None else samples[-n:]

    def last(self) -> dict | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
