"""Chain-health state and SLOs — the node-level "is this chain alive
and on time?" answer (ISSUE 6).

One per-process :class:`HealthState` (``HEALTH``, like ``TRACER``)
accumulates what the metrics catalogue's chain-health set exposes:

- **lateness**: every stored beacon's actual emit time vs its scheduled
  round boundary (``beacon_round_lateness_seconds``), fed by the
  DiscrepancyStore decorator on the store path.
- **head / lag**: ``chain_head_round`` and ``chain_head_lag_rounds``
  (wall-clock expected round minus stored head), re-evaluated both on
  store and on every ``/healthz`` request — so a *stalled* chain (no
  stores happening at all, e.g. a peer died and the group lost
  threshold) still moves its gauges.
- **missed rounds**: a round is *missed* once a full next boundary has
  passed with no beacon stored for it. Counted exactly once per round
  (``beacon_rounds_missed_total``); a later catch-up does not uncount —
  the round WAS missed when its consumers needed it.
- **SLO**: sliding window over the last ``window`` stored rounds; a
  round is *late* when it landed more than ``period/2`` after its
  boundary. ``beacon_slo_late_fraction`` is the window's late fraction.
- **catch-up progress**: ``follow_chain`` reports rounds/sec and an ETA
  so a node syncing a year-old chain is observable instead of silent.

Readiness (``/readyz``) flips on DKG-complete (chain info exists) AND
head-lag at or below ``DRAND_TPU_READY_MAX_LAG`` (default 3 rounds).

Everything here is cheap (a lock, a deque, gauge sets) and per-process
— in-process multi-node test harnesses share one HealthState exactly
like they share the prometheus registries; tests reset() it.
"""

from __future__ import annotations

import os
import threading
from collections import deque

READY_MAX_LAG = int(os.environ.get("DRAND_TPU_READY_MAX_LAG", "3"))


class HealthState:
    def __init__(self, window: int = 64):
        self.window = window
        self._lock = threading.Lock()
        self._dkg_complete = False
        self._head_round = 0
        self._expected_round = 0
        # highest round already counted into beacon_rounds_missed_total
        # (start at -1 so round 0 / genesis never looks "new")
        self._missed_marker = -1
        self._missed_total = 0
        # (round, late: bool) ring for the SLO window
        self._late_ring: deque[tuple[int, bool]] = deque(maxlen=window)
        # follow_chain progress
        self._sync = {"active": False, "rounds_per_sec": 0.0,
                      "eta_seconds": 0.0, "done": 0, "target": 0,
                      "current": 0}
        # lagging-with-no-progress verdict of the last observe_chain
        self._sync_stalled = False

    # ------------------------------------------------------------ inputs
    def note_dkg_complete(self) -> None:
        with self._lock:
            self._dkg_complete = True

    def note_round_stored(self, round_no: int, lateness_s: float,
                          period: int) -> None:
        """One beacon landed on the chain: lateness histogram, head
        gauge, SLO window. Called by the DiscrepancyStore decorator —
        off the crypto hot path (the beacon is already recovered).

        Rounds stored more than two whole periods after their boundary
        are catch-up/backfill (a rejoining node replaying history), not
        live emissions: they advance the head but are excluded from the
        lateness histogram and the SLO ring — their slots were already
        captured by the missed-round counter, and hours-stale samples
        would peg the SLO at 1.0 for a perfectly healthy group."""
        from .. import metrics

        live = lateness_s <= 2 * period
        if live:
            metrics.BEACON_LATENESS.observe(max(0.0, lateness_s))
        with self._lock:
            if round_no <= self._head_round:
                return  # replay/rollback writes never regress the head
            self._head_round = round_no
            if live:
                self._late_ring.append((round_no,
                                        lateness_s > period / 2))
            late = sum(1 for _, is_late in self._late_ring if is_late)
            frac = late / len(self._late_ring) if self._late_ring else 0.0
        metrics.CHAIN_HEAD_ROUND.set(round_no)
        metrics.SLO_LATE_FRACTION.set(frac)

    def observe_chain(self, now: float, period: int, genesis: int,
                      head_round: int | None = None) -> dict:
        """Re-evaluate lag + missed rounds against the wall clock —
        called on store AND from /healthz, so a fully stalled chain
        still surfaces (pull-model: scrapes and health probes drive the
        gauges when no beacons do). Returns a snapshot dict."""
        from ..chain import time_math
        from .. import metrics

        expected = time_math.current_round(int(now), period, genesis)
        with self._lock:
            if head_round is not None and head_round > self._head_round:
                self._head_round = head_round
            head = self._head_round
            self._expected_round = expected
            lag = max(0, expected - head)
            # rounds in (head, expected-1] have had their WHOLE period
            # elapse unstored — each is missed, counted once. Guarded on
            # a KNOWN head: with head 0 (fresh relay before its first
            # successful tip fetch, pre-first-beacon node) "missing"
            # would be the entire chain height — a transient fetch
            # failure must not permanently inflate a Counter.
            overdue_to = expected - 1
            newly = 0
            if head > 0 and overdue_to > head:
                lo = max(head, self._missed_marker)
                newly = max(0, overdue_to - lo)
            if newly:
                self._missed_total += newly
            if head > 0:
                self._missed_marker = max(self._missed_marker, overdue_to,
                                          head)
            missed = self._missed_total
            # sync-stall (ISSUE 11, pull-model like the gauges above): a
            # node lagging beyond the readiness bound SHOULD be syncing;
            # when no follow is active — or one is but its throughput is
            # zero — the lag will never close on its own. Scrapes and
            # health probes drive it, so a fully wedged node still
            # surfaces. Guarded on a known head like the missed counter:
            # a pre-first-beacon node is bootstrapping, not stalled.
            stalled = (head > 0 and lag > READY_MAX_LAG
                       and (not self._sync["active"]
                            or self._sync["rounds_per_sec"] == 0.0))
            self._sync_stalled = stalled
        metrics.CHAIN_HEAD_LAG.set(lag)
        metrics.SYNC_STALLED.set(1 if stalled else 0)
        if newly:
            metrics.MISSED_ROUNDS.inc(newly)
        return {"head_round": head, "expected_round": expected,
                "lag_rounds": lag, "missed_total": missed,
                "sync_stalled": stalled}

    def note_sync_progress(self, done: int, elapsed_s: float,
                           current: int, target: int,
                           active: bool = True) -> None:
        """follow_chain catch-up progress: ``done`` rounds stored over
        ``elapsed_s`` of this follow, chain at ``current``, aiming for
        ``target`` (0 = unbounded live follow)."""
        from .. import metrics

        rps = done / elapsed_s if (active and elapsed_s > 0) else 0.0
        if not active:
            eta = 0.0
        elif target <= 0:
            eta = -1.0  # unbounded follow: no finish line to estimate
        elif rps > 0:
            eta = max(0.0, (target - current) / rps)
        else:
            eta = -1.0
        with self._lock:
            self._sync = {"active": active,
                          "rounds_per_sec": round(rps, 3),
                          "eta_seconds": round(eta, 3),
                          "done": done, "target": target,
                          "current": current}
        metrics.SYNC_ROUNDS_PER_SEC.set(rps)
        metrics.SYNC_ETA_SECONDS.set(eta)

    # ----------------------------------------------------------- outputs
    def snapshot(self) -> dict:
        with self._lock:
            late = sum(1 for _, is_late in self._late_ring if is_late)
            n = len(self._late_ring)
            return {
                "dkg_complete": self._dkg_complete,
                "head_round": self._head_round,
                "expected_round": self._expected_round,
                "lag_rounds": max(0, self._expected_round
                                  - self._head_round),
                "missed_total": self._missed_total,
                "slo_window": n,
                "slo_late_fraction": (late / n) if n else 0.0,
                "sync": dict(self._sync),
                "sync_stalled": self._sync_stalled,
            }

    def reset(self) -> None:
        """Back to boot state (tests — the singleton is per-process)."""
        with self._lock:
            self._dkg_complete = False
            self._head_round = 0
            self._expected_round = 0
            self._missed_marker = -1
            self._missed_total = 0
            self._late_ring.clear()
            self._sync = {"active": False, "rounds_per_sec": 0.0,
                          "eta_seconds": 0.0, "done": 0, "target": 0,
                          "current": 0}
            self._sync_stalled = False


def is_ready(snapshot: dict, max_lag: int | None = None) -> bool:
    """THE readiness rule, shared by /healthz and /readyz: head lag at
    or below the bound. The HTTP layer gates on chain info being
    servable first (a relay has no DKG; info availability is its
    completeness proxy) — keep the lag criterion here so the two
    handlers cannot drift."""
    limit = READY_MAX_LAG if max_lag is None else max_lag
    return snapshot["lag_rounds"] <= limit


# The per-process health state every producer/probe shares (like TRACER).
HEALTH = HealthState()
