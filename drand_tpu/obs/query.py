"""Shared validation for the debug plane's ``?n=`` ring-window
parameter (ISSUE 15 satellite).

``/debug/trace/rounds``, ``/debug/flight/rounds`` and
``/debug/incidents`` each take an untrusted public ``n``; before this
module each route hand-rolled the identical regex + clamp. The
semantics are frozen here exactly as the PR-6 hardening defined them:

- only PLAIN base-10 integers parse — no floats, no ``1e6``, no
  ``0x10``; a bare ``int()`` would also take surprising
  whitespace/underscore/unicode-digit forms;
- the value clamps to ``[1, cap]`` (the ring size), so negative, zero
  or huge asks can neither error nor over-allocate;
- anything else is invalid → the caller answers 400.

The URL-encoding regression matrix (a literal ``+`` in a query string
decodes to a space, so explicit-sign probes must be percent-encoded)
points at this one function now — see tests/test_zz_incident.py and
the original matrix in tests/test_zz_obs_health.py.
"""

from __future__ import annotations

import re

_N_RE = re.compile(r"[+-]?[0-9]+")


def ring_n(raw: str | None, *, default: int, cap: int) -> int | None:
    """Parse+clamp a ``?n=`` value. ``raw`` is the query param (None =
    absent → ``default``); returns the clamped window size, or None
    when the input is invalid (the caller 400s)."""
    if raw is None:
        return max(1, min(default, cap))
    raw = raw.strip()
    if not _N_RE.fullmatch(raw):
        return None
    return max(1, min(int(raw), cap))
