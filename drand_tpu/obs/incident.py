"""Incident engine: anomaly rules over the SLI time-series ring, with
auto-captured forensic bundles (ISSUE 15).

The chaos oracle (PRs 11-12) proved which SLIs predict and explain
faults — but a human had to be watching. This module turns each of
those proven signals into a **detector**: declarative rules evaluated
on every time-series sample (obs/timeseries.py), minting an *incident*
when they fire and freezing a **forensic bundle** — the evidence an
operator needs for a post-mortem, captured at the moment it still
exists in the live rings:

- the affected rounds' trace timelines (obs/trace ring),
- the flight-ring slice with contribution bitmaps (+ the derived
  ``suspect_peers`` set: who was missing/invalid/unreachable),
- the DKG timeline when a ceremony is live,
- the health snapshot, per-peer breaker states, the engine fallback
  ledger,
- the time-series window itself, and a config fingerprint.

**Rules** come in two shapes. *Edge* rules fire on a counter increment
or a state flip (missed-round increment, breaker OPEN, readiness
flip, sync stall). *Trend* rules fire on windows (quorum margin below
the warn fraction / sloping toward negative, ingress-reject floods,
watcher-shed surges, reachability drops). Each rule carries a
severity, a cooldown and dedup semantics: while a rule keeps firing
the SAME incident stays open (``fired`` counts re-triggers), it closes
after ``clear_after`` quiet samples, and the cooldown then suppresses
an immediate re-mint — one sustained fault mints exactly ONE incident,
not hundreds. The margin rule's warn fraction matches the PR-11
oracle's, so its detection lead is the oracle's by construction: it
fires rounds BEFORE ``beacon_rounds_missed_total`` moves.

**Retention**: incidents live in a bounded in-memory ring and — when an
incident directory is configured (the daemon defaults to
``<folder>/db/incidents``; ``DRAND_TPU_INCIDENT_DIR`` overrides) — as
one rotated JSON bundle file each, oldest deleted past
``DRAND_TPU_INCIDENT_MAX`` (32). Bundles are secret-hygiene-clean BY
CONSTRUCTION: every field is read off surfaces that already enforce
the no-secrets rule (flight/trace/health/metrics), and the config
fingerprint redacts any secret-named env var. tools/analyze secretflow
registers the bundle writers as sinks, so a future change routing key
material into a bundle fails the static gate like logging it would.

Surfaces: ``GET /debug/incidents`` / ``/debug/incidents/{id}`` on the
always-on debug plane, ``drand-tpu util incidents`` /
``util support-bundle`` (the manual capture reuses
:meth:`IncidentManager.capture_bundle` verbatim), and the catalogued
``incidents_total{rule,severity}`` / ``incident_active`` metrics.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import re
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable

from .timeseries import TimeSeriesRing, collect_sample

# the PR-11 oracle's warn fraction (testing/chaos.detection_lead):
# margin below this fraction of the period is the early warning — the
# incident rule fires exactly where the oracle's warn_round lands
MARGIN_WARN_FRACTION = 0.5
# trend-rule thresholds (per-sample deltas)
FLOOD_MIN = int(os.environ.get("DRAND_TPU_INCIDENT_FLOOD_MIN", "16"))
SHED_MIN = int(os.environ.get("DRAND_TPU_INCIDENT_SHED_MIN", "8"))

# env-var names matching this are value-redacted in config fingerprints
_SECRETISH_ENV = re.compile(r"(?i)(secret|_key|token|passw|share|seed)")

# remediation-ledger entries kept per incident (obs/remediate appends
# via annotate_remediation; oldest dropped past the cap)
REMEDIATION_LEDGER_MAX = 64

_log = logging.getLogger("drand_tpu.obs.incident")


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    """One declarative detector over the time-series window.

    ``trigger`` takes (window, ctx) — samples oldest-first and
    ``{"period": float | None, "open": bool}`` (``open`` = this rule
    already has an open incident, for latching triggers) — and returns
    a human detail string while the anomaly holds, else None.
    ``clear_after`` quiet samples close the incident; ``cooldown_s``
    then suppresses a re-mint."""

    name: str
    severity: str              # critical | major | warning
    kind: str                  # edge | trend
    trigger: Callable[[list[dict], dict], str | None] = field(repr=False)
    cooldown_s: float = 30.0
    clear_after: int = 2


def _t_missed(w: list[dict], ctx: dict) -> str | None:
    d = w[-1]["deltas"].get("missed_total", 0)
    if d > 0:
        return (f"{int(d)} round(s) missed this sample "
                f"(total {int(w[-1]['missed_total'])})")
    return None


def _t_margin(w: list[dict], ctx: dict) -> str | None:
    period = ctx.get("period") or w[-1].get("period")
    m = w[-1].get("margin_s")
    if not period or m is None:
        return None
    if m < MARGIN_WARN_FRACTION * period:
        return (f"quorum margin {m:.3f}s below "
                f"{MARGIN_WARN_FRACTION:.0%} of the {period}s period")
    # slope: the last 3 distinct-round margins strictly decreasing and
    # extrapolating to ≤0 within two more rounds — degradation heading
    # for a miss even while still above the warn fraction
    margins: list[float] = []
    seen_rounds: set = set()
    for s in reversed(w):
        sm, fr = s.get("margin_s"), s.get("flight_round")
        if sm is None or fr in seen_rounds:
            continue
        seen_rounds.add(fr)
        margins.append(sm)
        if len(margins) == 3:
            break
    if len(margins) == 3 and margins[0] < margins[1] < margins[2]:
        slope = margins[1] - margins[0]  # per-round loss (newest first)
        if margins[0] - 2 * slope <= 0:
            return (f"quorum margin sloping to a miss: "
                    f"{margins[2]:.3f} -> {margins[1]:.3f} -> "
                    f"{margins[0]:.3f}s over the last 3 rounds")
    return None


def _t_breaker(w: list[dict], ctx: dict) -> str | None:
    n = w[-1].get("breakers_open", 0)
    if n > 0:
        return f"{int(n)} peer circuit breaker(s) OPEN"
    return None


def _t_reach(w: list[dict], ctx: dict) -> str | None:
    n = w[-1].get("suspects", 0)
    if n > 0:
        return f"{int(n)} peer(s) unreachable (partition suspects)"
    return None


def _t_ready(w: list[dict], ctx: dict) -> str | None:
    if w[-1].get("ready"):
        return None
    # LATCHED while the incident is open: the flip's "was ready"
    # baseline ages out of the sample window during a long outage, and
    # the incident must not self-close while /readyz is still failing
    if ctx.get("open"):
        return (f"readiness still down: head lag {w[-1]['lag']} rounds "
                f"(failing /readyz)")
    # spool-restored samples never count as the "was ready" baseline: a
    # routine restart that needs catch-up is not a live readiness flip
    if any(s.get("ready") and not s.get("restored") for s in w[:-1]):
        return (f"readiness flipped: head lag {w[-1]['lag']} rounds "
                f"(was serving, now failing /readyz)")
    return None


def _t_stall(w: list[dict], ctx: dict) -> str | None:
    if w[-1].get("sync_stalled"):
        return (f"chain sync stalled at lag {w[-1]['lag']} rounds "
                f"with no catch-up progressing")
    return None


def _t_flood(w: list[dict], ctx: dict) -> str | None:
    d = w[-1]["deltas"].get("ingress_rejects", 0)
    if d >= FLOOD_MIN:
        return f"{int(d)} ingress rejects in one sample (flood)"
    return None


def _t_shed(w: list[dict], ctx: dict) -> str | None:
    d = w[-1]["deltas"].get("watcher_shed", 0)
    if d >= SHED_MIN:
        return f"{int(d)} watchers shed in one sample (overload)"
    return None


def default_rules() -> list[Rule]:
    """The built-in detector set — one rule per chaos-proven SLI
    (README "Incident forensics" documents each with its fault)."""
    return [
        Rule("missed_round", "critical", "edge", _t_missed),
        Rule("readiness_flip", "critical", "edge", _t_ready),
        Rule("breaker_open", "major", "edge", _t_breaker),
        Rule("reachability_drop", "major", "trend", _t_reach),
        Rule("sync_stall", "major", "edge", _t_stall),
        Rule("margin_degraded", "warning", "trend", _t_margin),
        Rule("ingress_flood", "warning", "trend", _t_flood),
        Rule("shed_surge", "warning", "trend", _t_shed),
    ]


def _incident_counter(rule: str):
    """Branch-literal rule+severity labels for incidents_total (the
    check_metrics KNOWN_LABEL_VALUES enum rule — same pattern as
    obs/flight's label helpers). Each built-in rule carries its
    canonical severity; unknown (operator-supplied) rules collapse to
    ``custom`` rather than forking the series."""
    from .. import metrics

    if rule == "missed_round":
        return metrics.INCIDENTS_TOTAL.labels(rule="missed_round",
                                              severity="critical")
    if rule == "readiness_flip":
        return metrics.INCIDENTS_TOTAL.labels(rule="readiness_flip",
                                              severity="critical")
    if rule == "breaker_open":
        return metrics.INCIDENTS_TOTAL.labels(rule="breaker_open",
                                              severity="major")
    if rule == "reachability_drop":
        return metrics.INCIDENTS_TOTAL.labels(rule="reachability_drop",
                                              severity="major")
    if rule == "sync_stall":
        return metrics.INCIDENTS_TOTAL.labels(rule="sync_stall",
                                              severity="major")
    if rule == "margin_degraded":
        return metrics.INCIDENTS_TOTAL.labels(rule="margin_degraded",
                                              severity="warning")
    if rule == "ingress_flood":
        return metrics.INCIDENTS_TOTAL.labels(rule="ingress_flood",
                                              severity="warning")
    if rule == "shed_surge":
        return metrics.INCIDENTS_TOTAL.labels(rule="shed_surge",
                                              severity="warning")
    if rule == "worker_down":
        # minted by obs/remediate.worker_down_rule (supervised-worker
        # death feeding the respawn playbook)
        return metrics.INCIDENTS_TOTAL.labels(rule="worker_down",
                                              severity="major")
    return metrics.INCIDENTS_TOTAL.labels(rule="custom",
                                          severity="warning")


# ---------------------------------------------------------------------------
# forensics
# ---------------------------------------------------------------------------

def config_fingerprint() -> dict:
    """The node's operational knobs (DRAND_TPU_*) plus a stable digest
    — enough to answer "was this node configured like the others?"
    without shipping the whole environment. Secret-named values are
    redacted by construction (defense in depth: no current knob holds
    key material, and a future one that does must not leak here)."""
    env = {}
    for k in sorted(os.environ):
        if not k.startswith("DRAND_TPU_"):
            continue
        env[k] = "<redacted>" if _SECRETISH_ENV.search(k) \
            else os.environ[k]
    digest = hashlib.blake2b(
        json.dumps(env, sort_keys=True).encode(), digest_size=8).hexdigest()
    return {"fingerprint": digest, "env": env}


def suspect_peers(flight) -> dict:
    """The faulted peer set, named from the FROZEN evidence: the
    newest flight round's contribution bitmap (missing / invalid /
    late share indices) plus the reachability view (unreachable)."""
    from .flight import BITMAP_INVALID, BITMAP_LATE, BITMAP_MISSING

    out: dict = {"round": None, "missing": [], "invalid": [],
                 "late": [], "unreachable": []}
    for rec in flight.rounds(1):
        out["round"] = rec.get("round")
        for idx, ch in enumerate(rec.get("bitmap") or ""):
            if ch == BITMAP_MISSING:
                out["missing"].append(idx)
            elif ch == BITMAP_INVALID:
                out["invalid"].append(idx)
            elif ch == BITMAP_LATE:
                out["late"].append(idx)
    out["unreachable"] = sorted(
        int(i) for i, up in flight.reachability().items() if not up)
    return out


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

class IncidentManager:
    """Sampling + rule evaluation + incident lifecycle + bundles.

    Per-process singleton (``INCIDENTS``) by default, reading the
    FLIGHT/HEALTH singletons; in-process multi-node harnesses build one
    per probe node with that node's recorders injected (the
    BeaconConfig.flight/.health pattern). Thread-safe: sampling is
    driven both from the store path (to_thread aggregation workers) and
    from /healthz probes on the loop — every mutation is under one
    lock, no awaits or pairing-class work inside it."""

    def __init__(self, *, flight=None, health=None,
                 rules: list[Rule] | None = None,
                 ring: TimeSeriesRing | None = None,
                 dir_path: str | None = None,
                 max_incidents: int = 32,
                 ts_window: int = 64,
                 bundle_rounds: int = 16,
                 poll_min_interval: float = 1.0):
        self._flight = flight
        self._health = health
        self.rules = list(rules) if rules is not None else default_rules()
        self.ring = ring if ring is not None else TimeSeriesRing()
        self.dir_path = dir_path
        self.max_incidents = max_incidents
        self.ts_window = ts_window
        self.bundle_rounds = bundle_rounds
        self.poll_min_interval = poll_min_interval
        self._lock = threading.Lock()
        # id -> {"summary": dict, "bundle": dict | None (on disk only)}
        self._incidents: OrderedDict[str, dict] = OrderedDict()
        self._active: dict[str, dict] = {}      # rule name -> summary
        self._quiet: dict[str, int] = {}        # rule name -> quiet samples
        self._cooldown_until: dict[str, float] = {}
        self._seq = 0
        self._period: float | None = None
        self._last_sample_t = float("-inf")
        self._persist_warned = False
        self._sample_warned = False
        # the attached auto-remediation PlaybookEngine (obs/remediate);
        # None = detection-only (the seed behavior)
        self.engine = None
        self._engine_warned = False

    # ------------------------------------------------------------ plumbing
    def _flight_obj(self):
        if self._flight is not None:
            return self._flight
        from .flight import FLIGHT

        return FLIGHT

    def _health_obj(self):
        if self._health is not None:
            return self._health
        from .health import HEALTH

        return HEALTH

    def configure(self, *, dir_path: str | None = None,
                  spool_path: str | None = None,
                  max_incidents: int | None = None) -> None:
        """(Re)configure persistence: incident directory, time-series
        spool, retention bound. Loads what already exists — incident
        summaries from the directory, ring history from the spool — so
        forensics survive a restart."""
        with self._lock:
            if max_incidents is not None:
                self.max_incidents = max_incidents
            if dir_path is not None:
                self.dir_path = dir_path
                self._load_dir_locked()
        if spool_path is not None and self.ring.spool_path != spool_path:
            self.ring.set_spool(spool_path)
            self.ring.load_spool()

    def _load_dir_locked(self) -> None:
        if not self.dir_path or not os.path.isdir(self.dir_path):
            return
        names = sorted(n for n in os.listdir(self.dir_path)
                       if n.startswith("inc-") and n.endswith(".json"))
        for name in names[-self.max_incidents:]:
            inc_id = name[:-len(".json")]
            if inc_id in self._incidents:
                continue
            try:
                with open(os.path.join(self.dir_path, name),
                          encoding="utf-8") as f:
                    bundle = json.load(f)
            except (OSError, ValueError):
                continue  # a torn write must not wedge boot
            summary = {k: bundle.get(k) for k in
                       ("id", "rule", "severity", "detail", "opened_at",
                        "round", "state", "closed_at", "fired",
                        "last_seen")}
            # an incident that was open when the process died never got
            # its close sample — it must not read as live forever (the
            # rule re-mints if the fault persists across the restart)
            summary["state"] = "stale" \
                if summary.get("state") == "open" \
                else (summary.get("state") or "closed")
            self._incidents[inc_id] = {"summary": summary, "bundle": None}
            try:
                seq = int(inc_id.split("-")[1])
                self._seq = max(self._seq, seq)
            except (IndexError, ValueError):
                pass
        while len(self._incidents) > self.max_incidents:
            self._incidents.popitem(last=False)

    # ------------------------------------------------------------ sampling
    def on_round(self, round_no: int | None, *, now: float,
                 period: float) -> dict:
        """The round-boundary sample: called by the store hook for
        every stored beacon (and by harnesses per advanced round).
        Samples, evaluates every rule, mints/extends/closes incidents.
        Returns the annotated sample."""
        flight, health = self._flight_obj(), self._health_obj()
        sample = collect_sample(now, flight=flight, health=health,
                                period=period, round_no=round_no)
        sample = self.ring.append(sample)
        with self._lock:
            self._period = period
            self._last_sample_t = now
            dirty, events = self._evaluate_locked(now, period)
            engine = self.engine
        if dirty:
            self._persist_dirty(dirty)
        if engine is not None and events:
            # hand lifecycle events to the remediation engine OUTSIDE
            # the manager lock (ISSUE 13: playbook dispatch must never
            # run under it); a broken engine must not break detection
            try:
                engine.on_incidents(events, now)
            except Exception:  # noqa: BLE001
                with self._lock:
                    warned = self._engine_warned
                    self._engine_warned = True
                if not warned:
                    _log.warning("remediation engine hand-off failed",
                                 exc_info=True)
        return sample

    def _persist_dirty(self, dirty: list[str]) -> None:
        """Persist bundles + flush the spool OUTSIDE the manager lock —
        and, when the caller is ON the event loop (the /healthz poll
        path), off the loop entirely: a mint serializes a multi-KB
        bundle and runs several fs syscalls (2-4 ms each on this box's
        overlay fs), which must not stall every concurrent request.
        Mints are cooldown-bounded, so the spawned thread count is too.
        Synchronous callers (store-thread hook, harnesses, tests) get
        the inline path — file state is deterministic when they
        return."""

        def work() -> None:
            for inc_id in dirty:
                self._persist(inc_id)
            self.ring.flush()  # forensic moments get spool durability

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            work()
            return
        threading.Thread(target=work, name="incident-persist",
                         daemon=True).start()

    def poll(self, now: float, period: float | None = None) -> dict | None:
        """The on-demand sample (pull model, like HEALTH.observe_chain):
        /healthz probes drive detection even when NO beacons land — a
        fully stalled chain still samples, so the missed-round and
        readiness rules fire without a single store. Rate-limited so a
        probe storm cannot grow the ring faster than time passes (the
        slot is RESERVED inside the locked check — a store-thread
        sample racing a loop-side probe cannot both pass it)."""
        with self._lock:
            p = period if period is not None else self._period
            if p is None:
                return None
            if now - self._last_sample_t < self.poll_min_interval:
                return None
            self._last_sample_t = now
        return self.on_round(None, now=now, period=p)

    # ------------------------------------------------------------- rules
    def _evaluate_locked(self, now: float, period: float | None,
                         ) -> tuple[list[str], list[dict]]:
        """Evaluate every rule against the window; mint/extend/close.
        Returns (dirty incident ids to persist, lifecycle events for
        the remediation engine) — both handled by the caller OUTSIDE
        the lock. Event summaries are copies: the engine reads them on
        its own schedule."""
        from .. import metrics

        window = self.ring.window(self.ring.max_samples)
        if not window:
            return [], []
        dirty: list[str] = []
        events: list[dict] = []
        for rule in self.rules:
            # ctx carries whether THIS rule already has an open
            # incident, so a trigger can latch on it (readiness_flip)
            ctx = {"period": period, "open": rule.name in self._active}
            try:
                detail = rule.trigger(window, ctx)
            except Exception:  # noqa: BLE001 — a broken operator rule
                detail = None  # must not kill the built-in detectors
            open_inc = self._active.get(rule.name)
            if detail is not None:
                self._quiet[rule.name] = 0
                if open_inc is not None:
                    open_inc["fired"] += 1
                    open_inc["last_seen"] = now
                    open_inc["detail"] = detail
                    events.append({"event": "extended",
                                   "summary": dict(open_inc)})
                elif now >= self._cooldown_until.get(rule.name,
                                                     float("-inf")):
                    inc_id = self._mint_locked(rule, detail, now,
                                               window[-1])
                    dirty.append(inc_id)
                    events.append({
                        "event": "minted",
                        "summary": dict(
                            self._incidents[inc_id]["summary"])})
            elif open_inc is not None:
                q = self._quiet.get(rule.name, 0) + 1
                self._quiet[rule.name] = q
                if q >= rule.clear_after:
                    open_inc["state"] = "closed"
                    open_inc["closed_at"] = now
                    dirty.append(open_inc["id"])
                    events.append({"event": "closed",
                                   "summary": dict(open_inc)})
                    del self._active[rule.name]
                    self._cooldown_until[rule.name] = now + rule.cooldown_s
        metrics.INCIDENT_ACTIVE.set(len(self._active))
        return dirty, events

    def _mint_locked(self, rule: Rule, detail: str, now: float,
                     sample: dict) -> str:
        self._seq += 1
        inc_id = f"inc-{self._seq:05d}-{rule.name}"
        summary = {"id": inc_id, "rule": rule.name,
                   "severity": rule.severity, "detail": detail,
                   "opened_at": round(now, 6),
                   "round": sample.get("round") or sample.get("head"),
                   "state": "open", "closed_at": None,
                   "fired": 1, "last_seen": round(now, 6)}
        bundle = self._freeze_locked(summary, sample)
        self._incidents[inc_id] = {"summary": summary, "bundle": bundle}
        self._active[rule.name] = summary
        self._quiet[rule.name] = 0
        # retention: evict oldest CLOSED incidents past the bound. OPEN
        # ones are never evicted (they'd go inconsistent with _active
        # and lose their eventual close) — at most len(rules) can be
        # open, so memory stays bounded at max_incidents + rules.
        excess = len(self._incidents) - self.max_incidents
        if excess > 0:
            for victim_id in [i for i, rec in self._incidents.items()
                              if rec["summary"]["state"] != "open"][:excess]:
                del self._incidents[victim_id]
        _incident_counter(rule.name).inc()
        return inc_id

    # ------------------------------------------------------------ bundles
    def _freeze_locked(self, summary: dict, sample: dict | None) -> dict:
        """Freeze the forensic evidence NOW, while the rings still hold
        it. Every field reads an existing no-secrets surface; the
        writer itself is a registered secretflow sink."""
        from ..crypto import batch
        from .. import metrics
        from .timeseries import _gauge_by_label
        from .trace import TRACER

        flight, health = self._flight_obj(), self._health_obj()
        bundle = dict(summary)
        bundle.update({
            "period": self._period,
            "sample": sample,
            "timeseries": self.ring.window(self.ts_window),
            "suspect_peers": suspect_peers(flight),
            "flight": {"rounds": flight.rounds(self.bundle_rounds),
                       "peers": flight.peers(),
                       "reach": flight.reachability()},
            "dkg": flight.dkg.sessions(),
            "trace": TRACER.rounds(min(8, self.bundle_rounds)),
            "health": health.snapshot(),
            "breakers": _gauge_by_label(metrics.PEER_BREAKER_STATE,
                                        "index"),
            "fallback_ledger": batch.fallback_ledger(),
            "config": config_fingerprint(),
        })
        return bundle

    def _persist(self, inc_id: str) -> None:
        """Write/refresh the bundle file and rotate the directory down
        to ``max_incidents`` (oldest first — ids are seq-ordered; files
        of still-open incidents are never rotated away). Serialization
        happens under a brief lock; all fs syscalls run OUTSIDE it."""
        if not self.dir_path:
            return
        with self._lock:
            rec = self._incidents.get(inc_id)
            if rec is None or rec["bundle"] is None:
                return  # evicted, or a disk-loaded summary: file is
                # already in its final state
            rec["bundle"].update(rec["summary"])  # state/closed refresh
            payload = json.dumps(rec["bundle"], separators=(",", ":"))
            keep = {f"{s['id']}.json" for s in self._active.values()}
            dir_path, bound = self.dir_path, self.max_incidents
        try:
            os.makedirs(dir_path, exist_ok=True)
            path = os.path.join(dir_path, f"{inc_id}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp, path)
            names = sorted(n for n in os.listdir(dir_path)
                           if n.startswith("inc-") and n.endswith(".json"))
            excess = len(names) - bound
            if excess > 0:
                for name in [n for n in names if n not in keep][:excess]:
                    os.unlink(os.path.join(dir_path, name))
        except OSError:
            with self._lock:
                warned, self._persist_warned = self._persist_warned, True
            if not warned:
                _log.warning("incident bundle write failed for %s "
                             "(dir %s); forensics stay in memory only",
                             inc_id, self.dir_path)

    def annotate_remediation(self, inc_id: str, entry: dict) -> bool:
        """Append one remediation-ledger entry to the incident's
        summary — and therefore its bundle (the persist/get_bundle
        lifecycle refresh carries ``summary`` keys into the frozen
        bundle). THE audit trail the tentpole requires: every attempted
        action and outcome, in the forensic record, capped so a
        flapping playbook cannot grow a bundle without bound. Called by
        the PlaybookEngine's ledger writer (a registered secretflow
        sink, like the bundle writers). Returns False for unknown or
        evicted incidents."""
        with self._lock:
            rec = self._incidents.get(inc_id)
            if rec is None:
                return False
            ledger = rec["summary"].setdefault("remediation", [])
            ledger.append(dict(entry))
            del ledger[:-REMEDIATION_LEDGER_MAX]
        self._persist_dirty([inc_id])
        return True

    def capture_bundle(self, *, now: float | None = None,
                       reason: str = "manual") -> dict:
        """One-shot MANUAL capture — ``drand-tpu util support-bundle``
        and ``GET /debug/support-bundle``. Reuses the incident bundle
        writer verbatim (same freeze, same surfaces) but mints no
        incident and counts nothing: operators get forensics without
        waiting for an anomaly."""
        with self._lock:
            if now is None:
                last = self.ring.last()
                now = last["t"] if last else 0.0
            summary = {"id": f"support-{reason}", "rule": reason,
                       "severity": "none", "detail": "manual capture",
                       "opened_at": round(now, 6), "round": None,
                       "state": "manual", "closed_at": None,
                       "fired": 0, "last_seen": round(now, 6)}
            return self._freeze_locked(summary, self.ring.last())

    # ------------------------------------------------------------ outputs
    def incidents(self, n: int = 32) -> list[dict]:
        """The last ``n`` incident summaries, most recent first."""
        with self._lock:
            recs = list(self._incidents.values())[-n:] if n > 0 else []
            return [dict(r["summary"]) for r in reversed(recs)]

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def get_bundle(self, inc_id: str) -> dict | None:
        """The full bundle for one incident — memory first, then the
        on-disk file (summaries loaded at boot keep bundles on disk)."""
        with self._lock:
            rec = self._incidents.get(inc_id)
            if rec is not None and rec["bundle"] is not None:
                # lifecycle fields (state/closed_at/fired) live on the
                # summary; refresh the frozen bundle so a memory-only
                # node (no incident dir — _persist never runs) serves
                # the same lifecycle the listing shows
                rec["bundle"].update(rec["summary"])
                return dict(rec["bundle"])
            dir_path = self.dir_path
        if rec is None and not _INC_ID_RE.fullmatch(inc_id):
            return None  # never let a crafted id walk the filesystem
        if dir_path:
            path = os.path.join(dir_path, f"{inc_id}.json")
            try:
                with open(path, encoding="utf-8") as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        return None

    def reset(self) -> None:
        """Back to boot state (tests) — memory only; disk untouched."""
        with self._lock:
            self._incidents.clear()
            self._active.clear()
            self._quiet.clear()
            self._cooldown_until.clear()
            self._seq = 0
            self._period = None
            self._last_sample_t = float("-inf")
            self._persist_warned = False
            self._sample_warned = False
            self._engine_warned = False
        self.ring.reset()


# ids are minted as inc-<seq>-<rule>; anything else never touches disk
_INC_ID_RE = re.compile(r"inc-[0-9]{1,12}-[a-z_]{1,40}")


# The per-process manager every hook shares (like TRACER/HEALTH/FLIGHT).
INCIDENTS = IncidentManager()


def configure_from_env(default_dir: str | None = None) -> None:
    """Wire the singleton's persistence from the environment (the
    daemon passes ``<folder>/db/incidents`` as the default; relays opt
    in via ``DRAND_TPU_INCIDENT_DIR``)."""
    dir_path = os.environ.get("DRAND_TPU_INCIDENT_DIR") or default_dir
    if not dir_path:
        return
    spool = os.environ.get("DRAND_TPU_INCIDENT_SPOOL") \
        or os.path.join(dir_path, "timeseries.ndjson")
    INCIDENTS.configure(
        dir_path=dir_path, spool_path=spool,
        max_incidents=int(os.environ.get("DRAND_TPU_INCIDENT_MAX", "32")))


def note_round_stored(round_no: int, *, now: float, period: float,
                      incidents: IncidentManager | None = None) -> None:
    """The DiscrepancyStore hook: sample + evaluate at the round
    boundary. Telemetry must never take the store path down — failures
    log once and are dropped."""
    mgr = incidents if incidents is not None else INCIDENTS
    try:
        mgr.on_round(round_no, now=now, period=period)
    except Exception:  # noqa: BLE001 — forensics must not break stores
        with mgr._lock:
            warned, mgr._sample_warned = mgr._sample_warned, True
        if not warned:
            _log.warning("incident sampling failed at round %s",
                         round_no, exc_info=True)
