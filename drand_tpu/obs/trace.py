"""Round-lifecycle tracing: spans, a per-process ring of round timelines,
and W3C-``traceparent``-style cross-node correlation.

Modeled on upstream drand's later OpenTelemetry instrumentation
(metrics/otel.go in recent drand) but self-contained — no OTel SDK in
this image, and the beacon pipeline needs only three primitives:

- :class:`Span`: one named, timed stage (``partial``, ``collect``,
  ``recover``, ``verify``, ``store``, ...) with free-form attributes.
- :class:`Tracer`: ``contextvars``-scoped span stack + a bounded
  per-process ring buffer of completed *round* traces. Every span
  closure also feeds the ``beacon_stage_seconds{stage=...}`` Prometheus
  histogram, so continuous stage timing is visible from any running
  daemon independent of the bench driver.
- round-correlation ids: the trace id of round *r* on chain *c* is
  ``blake2b(c || r)`` — DETERMINISTIC, so every node of a group derives
  the same id for the same round and one round's timeline can be
  stitched across nodes without any coordination. The id still travels
  as an ``x-drand-traceparent`` header/metadata entry (gRPC + HTTP) in
  the W3C ``00-<trace>-<span>-01`` layout so foreign hops (relays,
  clients) can adopt it verbatim.

The tracer is deliberately cheap: span open/close is a dict append under
a lock, no I/O, no sampling machinery. Spans recorded outside any active
trace context (e.g. client-side verification) are timed into the
histograms but NOT retained in the ring — the ring holds round
timelines only.
"""

from __future__ import annotations

import asyncio
import contextvars
import hashlib
import os
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field

TRACEPARENT_HEADER = "x-drand-traceparent"
_VERSION = "00"
_FLAGS = "01"

# (trace_id: str, round_no: int | None, retain: bool) of the active
# round trace; retain=False contexts feed histograms and logs but may
# not CREATE ring entries (bulk-historical traffic like sync catch-up
# must not evict live round timelines)
_ctx_trace: contextvars.ContextVar = contextvars.ContextVar(
    "drand_trace", default=None)
# span id of the innermost open span (parent for new spans)
_ctx_span: contextvars.ContextVar = contextvars.ContextVar(
    "drand_span", default=None)


def round_trace_id(round_no: int, chain: bytes | str = b"") -> str:
    """Deterministic 16-byte trace id for (chain, round) — every group
    member computes the same id, which is what makes cross-node
    stitching free."""
    if isinstance(chain, str):
        chain = chain.encode()
    h = hashlib.blake2b(chain + b"|drand-round|%d" % round_no,
                        digest_size=16)
    return h.hexdigest()


def make_traceparent(trace_id: str, span_id: str | None = None) -> str:
    """W3C traceparent: 00-<32 hex>-<16 hex>-01."""
    return f"{_VERSION}-{trace_id}-{span_id or '0' * 16}-{_FLAGS}"


_HEX = frozenset("0123456789abcdef")


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """-> (trace_id, parent_span_id), or None on anything malformed
    (ingress headers are untrusted). Strict lowercase hex per W3C —
    int(x, 16) would admit '0x'/sign/'_' forms, letting a peer inject
    ids that can't match any legitimately derived one into logs and
    the /debug/trace ring."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, _flags = parts
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16:
        return None
    if not (_HEX.issuperset(tid) and _HEX.issuperset(sid)):
        return None
    return tid, sid


def current_trace_id() -> str | None:
    ctx = _ctx_trace.get()
    return ctx[0] if ctx else None


def current_round() -> int | None:
    ctx = _ctx_trace.get()
    return ctx[1] if ctx else None


def traceparent() -> str | None:
    """Header value for the active trace context (None when inactive)."""
    ctx = _ctx_trace.get()
    if ctx is None:
        return None
    return make_traceparent(ctx[0], _ctx_span.get())


def outbound_metadata() -> tuple | None:
    """gRPC-metadata pairs carrying the active correlation id, or None
    when no trace context is active — shared by every egress hop."""
    tp = traceparent()
    if tp is None:
        return None
    return ((TRACEPARENT_HEADER, tp),)


def traceparent_from(metadata) -> str | None:
    """The traceparent entry of an iterable of (key, value) pairs.
    Never raises — ingress metadata is untrusted and tracing must never
    break an RPC."""
    try:
        for k, v in metadata or ():
            if str(k).lower() == TRACEPARENT_HEADER:
                return v
    except Exception:  # noqa: BLE001
        pass
    return None


def traceparent_from_context(context) -> str | None:
    """The traceparent entry of a gRPC server call's invocation
    metadata; never raises (shared by the protocol gateway and the
    gossip relay so the guard cannot drift)."""
    try:
        md = context.invocation_metadata()
    except Exception:  # noqa: BLE001
        return None
    return traceparent_from(md)


def _new_span_id() -> str:
    return os.urandom(8).hex()


@dataclass
class Span:
    """One completed-or-open stage of a round's lifecycle."""

    name: str
    trace_id: str | None
    span_id: str
    parent_id: str | None
    start: float                       # wall clock (time.time())
    t0: float                          # perf counter, for the duration
    end: float | None = None
    duration_ms: float | None = None
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_ms": self.duration_ms,
            "attrs": {k: _jsonable(v) for k, v in self.attrs.items()},
        }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, bytes):
        return v.hex()
    return str(v)


class Tracer:
    """Span factory + bounded ring of completed round traces.

    ``max_rounds`` bounds the number of retained round timelines;
    ``max_spans`` bounds each timeline (a pathological round — e.g. a
    partial flood — must not grow memory without bound; overflow is
    counted in the record's ``dropped`` field rather than silently
    lost)."""

    def __init__(self, max_rounds: int = 64, max_spans: int = 512):
        self.max_rounds = max_rounds
        self.max_spans = max_spans
        self._lock = threading.Lock()
        # trace_id -> {"trace_id","round","spans":[...],"dropped":int}
        self._traces: OrderedDict[str, dict] = OrderedDict()

    # ------------------------------------------------------------ context
    @contextmanager
    def activate(self, round_no: int | None = None, chain: bytes | str = b"",
                 trace_id: str | None = None, retain: bool = True):
        """Bind a round trace to the current (task) context; nested spans
        and KV log lines pick it up automatically. ``retain=False``
        spans still feed the histograms, carry the correlation id, and
        append to an EXISTING ring entry, but never create one — bulk
        historical traffic (sync catch-up) must not evict live round
        timelines from the bounded ring."""
        if trace_id is None:
            if round_no is None:
                raise ValueError("activate needs round_no or trace_id")
            trace_id = round_trace_id(round_no, chain)
        tok = _ctx_trace.set((trace_id, round_no, retain))
        try:
            yield trace_id
        finally:
            _ctx_trace.reset(tok)

    @contextmanager
    def activate_traceparent(self, header: str | None):
        """Adopt a peer's traceparent header; a missing/malformed header
        is a no-op passthrough (ingress is untrusted)."""
        parsed = parse_traceparent(header)
        if parsed is None:
            yield None
            return
        tid, parent_span = parsed
        tok_t = _ctx_trace.set((tid, None, True))
        tok_s = _ctx_span.set(parent_span)
        try:
            yield tid
        finally:
            _ctx_span.reset(tok_s)
            _ctx_trace.reset(tok_t)

    # -------------------------------------------------------------- spans
    @contextmanager
    def span(self, name: str, **attrs):
        """Open a stage span. On close: record it into the active round's
        timeline (if any) and observe ``beacon_stage_seconds{stage=name}``.
        The yielded Span is live — callers may update ``attrs``."""
        ctx = _ctx_trace.get()
        sp = Span(
            name=name,
            trace_id=ctx[0] if ctx else None,
            span_id=_new_span_id(),
            parent_id=_ctx_span.get(),
            start=time.time(),
            t0=time.perf_counter(),
            attrs=attrs,
        )
        tok = _ctx_span.set(sp.span_id)
        suffix = ""
        try:
            yield sp
        except BaseException as e:
            # failed stages must be distinguishable in the timeline
            # (e.g. a wedged device dispatch before the host fallback)
            sp.attrs.setdefault("error", True)
            # ValueError is this codebase's semantic-rejection convention
            # (below-threshold recover, malformed input) — an instant
            # raise, not a wedged stage; same taxonomy as the
            # batch-dispatch _timed wrapper's <path>_invalid. Task
            # cancellation (daemon stop mid-breather) is routine, not a
            # failure — it must not land in the *_error alert series.
            if isinstance(e, ValueError):
                suffix = "_invalid"
            elif isinstance(e, asyncio.CancelledError):
                suffix = "_cancelled"
            else:
                suffix = "_error"
            raise
        finally:
            _ctx_span.reset(tok)
            dur = time.perf_counter() - sp.t0
            sp.end = time.time()
            sp.duration_ms = dur * 1000.0
            self._record(sp, ctx[1] if ctx else None,
                         ctx[2] if ctx else True)
            from .. import metrics

            # failed stages land under stage="<name>_error" (or
            # "<name>_invalid" for semantic rejections) so e.g. a wedged
            # device dispatch's timeout doesn't masquerade as real
            # recover latency (the host-fallback retry then contributes
            # the round's real sample)
            metrics.BEACON_STAGE_SECONDS.labels(
                stage=name + suffix).observe(dur)

    def _record(self, sp: Span, round_no: int | None,
                retain: bool = True) -> None:
        if sp.trace_id is None:
            return  # no round context: histogram-only span
        with self._lock:
            rec = self._traces.get(sp.trace_id)
            if rec is None:
                if not retain:
                    return  # histogram-only: never evict live timelines
                rec = {"trace_id": sp.trace_id, "round": round_no,
                       "spans": [], "dropped": 0}
                self._traces[sp.trace_id] = rec
                while len(self._traces) > self.max_rounds:
                    self._traces.popitem(last=False)
            elif rec.get("round") is None and round_no is not None:
                rec["round"] = round_no
            if len(rec["spans"]) >= self.max_spans:
                rec["dropped"] += 1
                return
            rec["spans"].append(sp.to_dict())

    # ------------------------------------------------------------- export
    def rounds(self, n: int = 8) -> list[dict]:
        """The last ``n`` round timelines, most recent first. Each entry:
        ``{"trace_id", "round", "spans": [...], "dropped"}``."""
        with self._lock:
            recs = list(self._traces.values())[-n:] if n > 0 else []
        out = []
        for rec in reversed(recs):
            out.append({"trace_id": rec["trace_id"], "round": rec["round"],
                        "dropped": rec["dropped"],
                        "spans": list(rec["spans"])})
        return out

    def get_trace(self, trace_id: str) -> dict | None:
        """A copy of one retained round timeline (the OTLP exporter's
        lookup), or None when the ring holds nothing for the id."""
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            return {"trace_id": rec["trace_id"], "round": rec["round"],
                    "dropped": rec["dropped"],
                    "spans": list(rec["spans"])}

    def reset(self) -> None:
        """Drop all retained traces (tests). Safe against concurrent
        ``_record``: both take ``self._lock``, and ``_record`` re-reads
        ``self._traces`` under it — a span closing mid-reset either
        lands before the clear (and is dropped with everything else) or
        re-creates a fresh ring entry after it; never a KeyError or a
        write into an orphaned record."""
        with self._lock:
            self._traces.clear()


# The per-process tracer every instrumentation site shares (the ring is
# per-process by design — ISSUE: continuous in-process stage timing).
TRACER = Tracer()


def merge_round_timelines(sources: list[tuple[str, dict]]) -> list[dict]:
    """Cross-node timeline merge: interleave several nodes'
    ``/debug/trace/rounds`` payloads into one timeline per trace id.

    The trace id of round *r* is ``blake2b(chain || r)`` on EVERY node,
    so the same round's spans from different nodes share an id and can
    be stitched with zero coordination — this is the payoff of the
    deterministic-id design (``drand util trace --merge``).

    ``sources``: ``(node_label, payload)`` pairs. Returns one record per
    trace id — ``{"trace_id", "round", "nodes", "dropped", "spans"}`` —
    spans interleaved by wall-clock start, each tagged with its source
    label under ``"node"``; records ordered most-recent-round first
    (unknown rounds last)."""
    merged: dict[str, dict] = {}
    for label, payload in sources:
        for rec in (payload or {}).get("rounds", ()):
            tid = rec.get("trace_id")
            if not tid:
                continue
            out = merged.setdefault(tid, {
                "trace_id": tid, "round": rec.get("round"),
                "nodes": [], "dropped": 0, "spans": []})
            if out["round"] is None:
                out["round"] = rec.get("round")
            if label not in out["nodes"]:
                out["nodes"].append(label)
            out["dropped"] += rec.get("dropped", 0) or 0
            for sp in rec.get("spans", ()):
                sp = dict(sp)
                sp["node"] = label
                out["spans"].append(sp)
    for out in merged.values():
        out["spans"].sort(key=lambda s: s.get("start") or 0.0)
    return sorted(merged.values(),
                  key=lambda r: (r["round"] is None, -(r["round"] or 0)))
