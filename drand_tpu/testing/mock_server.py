"""Mock beacon source with cryptographically REAL signatures.

Reference: test/mock/grpcserver.go:184-238 — a fake public server whose
chain is a real 1-of-2 threshold-BLS chain, with deliberate corruption
switches for negative tests, plus stream control (EmitRand :97).
Implements the client.Client surface and the sync_chain service so both
the client stack and the syncer can be tested against it.
"""

from __future__ import annotations

import asyncio

from ..chain import time_math
from ..chain.beacon import Beacon, message, message_v2
from ..chain.info import Info
from ..client.interface import Client, ClientError, result_from_beacon
from ..crypto import tbls
from ..crypto.poly import PriPoly
from ..net.transport import TransportError


class MockBeaconServer(Client):
    """Pre-generates `nrounds` of a real 1-of-2 tbls chain.

    Switches:
    - ``bad_second_round``: corrupt round 2's signature (grpcserver.go:184
      generateMockData's deliberate corruption)
    - ``bad_round(r, field)``: corrupt any round/field after the fact
    """

    def __init__(self, nrounds: int = 10, period: int = 30,
                 genesis_time: int = 1_700_000_000,
                 bad_second_round: bool = False,
                 seed: bytes = b"mock-server"):
        poly = PriPoly.random(2, seed=seed)
        self._pub = poly.commit()
        shares = poly.shares(2)
        self._shares = shares
        self.chain_info = Info(
            public_key=self._pub.commit(),
            period=period,
            genesis_time=genesis_time,
            genesis_seed=b"\x77" * 32,
            group_hash=b"\x77" * 32,
        )
        self.beacons: dict[int, Beacon] = {}
        prev = self.chain_info.genesis_seed
        for rnd in range(1, nrounds + 1):
            msg = message(rnd, prev)
            partials = [tbls.sign_partial(s, msg) for s in shares]
            sig = tbls.recover(self._pub, msg, partials, 2, 2)
            partials_v2 = [tbls.sign_partial(s, message_v2(rnd))
                           for s in shares]
            sig_v2 = tbls.recover(self._pub, message_v2(rnd), partials_v2, 2, 2)
            self.beacons[rnd] = Beacon(round=rnd, previous_sig=prev,
                                       signature=sig, signature_v2=sig_v2)
            prev = sig
        self._tip = nrounds
        if bad_second_round and 2 in self.beacons:
            self.bad_round(2)
        self._watchers: list[asyncio.Queue] = []

    # -------------------------------------------------------- corruption
    def bad_round(self, rnd: int, field: str = "signature") -> None:
        b = self.beacons[rnd]
        data = getattr(b, field)
        setattr(b, field, bytes([data[0] ^ 1]) + data[1:])

    # ------------------------------------------------------------ control
    def emit(self, b: Beacon | None = None) -> Beacon:
        """Append (or inject) the next beacon and wake watchers
        (grpcserver.go:97 EmitRand)."""
        if b is None:
            rnd = self._tip + 1
            prev = self.beacons[self._tip].signature
            msg = message(rnd, prev)
            poly_sig = self._resign(msg)
            sig_v2 = self._resign(message_v2(rnd))
            b = Beacon(round=rnd, previous_sig=prev, signature=poly_sig,
                       signature_v2=sig_v2)
        self.beacons[b.round] = b
        self._tip = max(self._tip, b.round)
        for q in list(self._watchers):
            q.put_nowait(b)
        return b

    def _resign(self, msg: bytes) -> bytes:
        partials = [tbls.sign_partial(s, msg) for s in self._shares]
        return tbls.recover(self._pub, msg, partials, 2, 2)

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0):
        rnd = round_no or self._tip
        b = self.beacons.get(rnd)
        if b is None:
            raise ClientError(f"mock: no round {rnd}")
        return result_from_beacon(b)

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        try:
            while True:
                yield result_from_beacon(await q.get())
        finally:
            self._watchers.remove(q)

    async def info(self) -> Info:  # Client surface
        return self.chain_info

    def round_at(self, t: float) -> int:
        return time_math.current_round(int(t), self.chain_info.period,
                                       self.chain_info.genesis_time)

    # -------------------------------------------- sync service (server side)
    async def sync_chain(self, from_addr: str, req):
        if req.from_round > self._tip:
            raise TransportError("mock: nothing to sync")
        for rnd in range(max(1, req.from_round), self._tip + 1):
            yield self.beacons[rnd]
