"""In-process multi-node test harness.

Reproduces the reference's test machinery (SURVEY.md §4):
- DKG-bypass share synthesis from a master polynomial
  (chain/beacon/node_test.go:52-104 dkgShares)
- in-process multi-node network with fault injection
  (core/util_test.go:32 DrandTest2, :450 DenyClient)
- fake clock driving rounds deterministically.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ..chain.engine.handler import BeaconConfig, Handler
from ..chain.store import MemStore, Store
from ..crypto.poly import PriPoly
from ..key.group import Group
from ..key.keys import DistPublic, Node, Pair, Share, new_key_pair
from ..net.transport import LocalNetwork
from ..utils.clock import Clock, FakeClock
from ..utils.logging import default_logger


def synthesize_shares(n: int, t: int, seed: bytes = b"test-dkg") -> tuple[list[Share], DistPublic]:
    """Create n shares of a fresh t-of-n secret WITHOUT running the DKG —
    equivalent output distribution (the DKG's sum of polynomials is itself a
    random polynomial)."""
    poly = PriPoly.random(t, seed=seed)
    pub = poly.commit()
    shares = [
        Share(commits=list(pub.commits), pri_share=s) for s in poly.shares(n)
    ]
    return shares, DistPublic(list(pub.commits))


def make_test_group(
    n: int,
    t: int,
    period: int,
    genesis_time: int,
    seed: bytes = b"test-dkg",
    catchup_period: int = 0,
) -> tuple[Group, list[Pair], list[Share]]:
    pairs = [
        new_key_pair(f"node-{i}.test:8{i:03d}", seed=b"pair%d" % i + seed)
        for i in range(n)
    ]
    shares, dist = synthesize_shares(n, t, seed=seed)
    nodes = [Node(identity=p.public, index=i) for i, p in enumerate(pairs)]
    group = Group(
        nodes=nodes,
        threshold=t,
        period=period,
        genesis_time=genesis_time,
        catchup_period=catchup_period or max(1, period // 2),
        public_key=dist,
    )
    group.get_genesis_seed()
    return group, pairs, shares


@dataclass
class TestNode:
    pair: Pair
    share: Share
    store: Store
    handler: Handler

    @property
    def addr(self) -> str:
        return self.pair.public.addr


class BeaconTestNetwork:
    """n-node beacon network over an in-memory transport with a fake clock.

    Usage:
        net = BeaconTestNetwork(n=3, t=2, period=2)
        await net.start_all()
        await net.advance_rounds(5)
        net.check_chain(...)
    """

    def __init__(self, n: int, t: int, period: int = 2,
                 genesis_delay: int = 2, clock: Clock | None = None,
                 store_factory=None, seed: bytes = b"test-dkg"):
        self.clock = clock or FakeClock()
        self.genesis_time = int(self.clock.now()) + genesis_delay
        self.group, self.pairs, self.shares = make_test_group(
            n, t, period, self.genesis_time, seed=seed
        )
        self.network = LocalNetwork()
        self.nodes: list[TestNode] = []
        store_factory = store_factory or (lambda i: MemStore())
        logger = default_logger("beacon-test", level="none")
        for i in range(n):
            store = store_factory(i)
            conf = BeaconConfig(
                public=self.group.nodes[i],
                share=self.shares[i],
                group=self.group,
                clock=self.clock,
            )
            handler = Handler(
                client=self.network.client_for(self.pairs[i].public.addr),
                store=store,
                conf=conf,
                logger=logger.named(f"n{i}"),
            )
            self.network.register(self.pairs[i].public.addr, handler)
            self.nodes.append(TestNode(self.pairs[i], self.shares[i], store, handler))

    async def start_all(self, indices: list[int] | None = None) -> None:
        for i, node in enumerate(self.nodes):
            if indices is None or i in indices:
                await node.handler.start()

    async def advance_to_genesis(self) -> None:
        await self.clock.advance_to(self.genesis_time)

    async def advance_rounds(self, k: int, settle_s: float = 0.0) -> None:
        """Advance the fake clock k periods, letting each round complete."""
        for _ in range(k):
            await self.clock.advance(self.group.period)

    async def wait_round(self, node_idx: int, round_no: int, timeout: float = 30.0) -> None:
        """Wait (real time) until the node's chain reaches round_no."""
        node = self.nodes[node_idx]
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            try:
                if node.store.last().round >= round_no:
                    return
            except Exception:
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"node {node_idx} never reached round {round_no} "
                    f"(at {node.store.last().round})"
                )
            await asyncio.sleep(0.01)

    def stop_all(self) -> None:
        for node in self.nodes:
            node.handler.stop()
