"""Chaos network simulator: scripted fault schedules whose assertion
surface is the observability stack itself (ISSUE 11, ROADMAP #5).

The reference daemon survives partitions, lagging peers and gossip
abuse in the wild (PAPER.md: syncer/catch-up, gossip ban machinery) —
but until this module, only 2-3-node happy-path e2e tests ever
exercised the SLIs built in PRs 1/6/10. This harness runs an
in-process N=32-64 node beacon network on the injectable FakeClock and
drives it with declarative **fault schedules**: partitions (heal and
no-heal), per-link delay/jitter(reorder)/duplication/drop, per-node
clock skew, byzantine members (garbage partials, index framing),
external garbage floods, rolling crash-restart storms, and a
mid-ceremony reshare under churn. Every recovery invariant is asserted
THROUGH the existing surfaces — quorum margins, contribution bitmaps,
reachability/partition-suspect gauges, /healthz lag thresholds, DKG
phase timelines — never by peeking at protocol internals.

Design notes:

- **Per-node recorders** (``BeaconConfig.flight`` / ``.health``): every
  node gets its own :class:`~drand_tpu.obs.flight.FlightRecorder` and
  :class:`~drand_tpu.obs.health.HealthState`, exactly like
  one-process-per-node production. Without this, a byzantine node's
  own "valid" self-note would pollute the honest nodes' shared
  telemetry, and the singleton HealthState's monotonic-max head would
  make a minority-partition probe observe the majority's progress.
  ``TRACER`` and the global singletons still want
  ``obs.state.isolated_observability()`` around each scenario.

- **Deterministic time**: all nodes share one FakeClock base;
  :class:`SkewClock` gives each node an offset view (clock-skew
  faults). :meth:`ChaosBeaconNetwork.advance_round` steps the clock
  from wake target to wake target (``FakeClock.next_wake``) and lets
  the event loop + worker threads quiesce at each stop, so a delayed
  delivery is timestamped at ITS wake time — margins then read the
  injected fault, not scheduler noise.

- **Structural crypto** (:func:`structural_crypto`): a 32-node round
  costs ~4000 host pairings at ~58 ms each — minutes per round on the
  1-core box, which would make big-N chaos unrunnable. The context
  manager swaps the pairing-heavy leaves (partial sign/verify, round
  aggregation, chain verification) for structural blake2b stand-ins
  that preserve every verdict the observability layer depends on:
  partial bodies are index-bound (a wrong-index or garbage partial is
  "invalid" against the claimed index, like real crypto), recovery
  needs t distinct valid indices, recovered/chain signatures check
  against the per-message group digest. Scenarios about *verdict
  plumbing and timing* run under it; anything about real signatures
  belongs in the crypto suites.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from ..chain import beacon as chain_beacon
from ..chain import time_math
from ..client import checkpoint as ckpt_mod
from ..chain.engine import crypto as engine_crypto
from ..chain.engine import handler as handler_mod
from ..chain.engine.handler import BeaconConfig, Handler
from ..chain.store import MemStore
from ..crypto import batch, tbls
from ..net.packets import PartialBeaconPacket
from ..net.transport import (LocalClient, LocalNetwork, ProtocolService,
                             TransportError)
from ..obs.flight import FlightRecorder
from ..obs.health import HealthState
from ..utils.clock import Clock, FakeClock
from ..utils.logging import default_logger
from .harness import make_test_group

# ---------------------------------------------------------------------------
# structural (fast) crypto
# ---------------------------------------------------------------------------

_SIG_HALF = 48  # half the 96-byte compressed-G2 wire size


def _h96(tag: bytes, msg: bytes) -> bytes:
    """96 bytes of shake-256 — the structural stand-in for a
    compressed G2 signature (same wire size, same determinism). One
    XOF call instead of two fixed-size digests: million-round
    structural chains hash on the bench/test critical path. The tag is
    length-prefixed so tag/message boundaries can't collide."""
    return hashlib.shake_256(
        len(tag).to_bytes(1, "big") + tag + msg).digest(96)


def group_sig(msg: bytes) -> bytes:
    """The structural group signature for ``msg`` — what recovery from
    ANY t-subset yields and what chain verification checks against."""
    return _h96(b"chaos-group", msg)


def partial_body(msg: bytes, index: int) -> bytes:
    """The structural partial-signature body for share ``index`` —
    index-BOUND so a wrong-index claim fails verification against the
    claimed index, mirroring pub_poly.eval(index) in real tbls."""
    return _h96(b"chaos-partial-%d" % index, msg)


def make_partial(msg: bytes, index: int) -> bytes:
    return index.to_bytes(tbls.INDEX_BYTES, "big") + partial_body(msg, index)


def _structural_verify_packet(pub, p: PartialBeaconPacket,
                              ckpt_msg: bytes | None = None) -> str | None:
    """Drop-in for chain.engine.handler._verify_partial_packet — same
    rejection strings, structural checks."""
    msg = chain_beacon.message(p.round, p.previous_sig)
    if (len(p.partial_sig) != tbls.PARTIAL_SIG_SIZE
            or p.partial_sig[tbls.INDEX_BYTES:]
            != partial_body(msg, tbls.index_of(p.partial_sig))):
        return "invalid partial signature"
    if p.partial_sig_v2:
        if len(p.partial_sig_v2) != tbls.PARTIAL_SIG_SIZE:
            return "invalid partial signature v2"
        if tbls.index_of(p.partial_sig_v2) != tbls.index_of(p.partial_sig):
            return "partial signature index mismatch"
        msg_v2 = chain_beacon.message_v2(p.round)
        if p.partial_sig_v2[tbls.INDEX_BYTES:] != partial_body(
                msg_v2, tbls.index_of(p.partial_sig_v2)):
            return "invalid partial signature v2"
    if p.partial_ckpt:
        if ckpt_msg is None:
            return "unexpected checkpoint partial"
        if tbls.index_of(p.partial_ckpt) != tbls.index_of(p.partial_sig):
            return "checkpoint partial index mismatch"
        if (len(p.partial_ckpt) != tbls.PARTIAL_SIG_SIZE
                or p.partial_ckpt[tbls.INDEX_BYTES:] != partial_body(
                    ckpt_msg, tbls.index_of(p.partial_ckpt))):
            return "invalid checkpoint partial"
    return None


def _structural_aggregate_round(pub_poly, msg: bytes, partials, t: int,
                                n: int, dst: bytes = b"", *,
                                prevalidated: bool = False):
    """Drop-in for crypto.batch.aggregate_round: t distinct in-group
    valid bodies recover the group digest; short counts raise the same
    ValueError shape the aggregator logs."""
    oks, seen = [], set()
    for p in partials:
        ok = (len(p) == tbls.PARTIAL_SIG_SIZE
              and tbls.index_of(p) < n
              and p[tbls.INDEX_BYTES:] == partial_body(
                  msg, tbls.index_of(p)))
        oks.append(ok)
        if ok:
            seen.add(tbls.index_of(p))
    if len(seen) < t:
        raise ValueError(f"not enough valid partials: {len(seen)} < {t}")
    return oks, group_sig(msg)


def _structural_verify_beacon(pubkey, b) -> bool:
    return b.signature == group_sig(
        chain_beacon.message(b.round, b.previous_sig))


def _structural_verify_beacon_v2(pubkey, b) -> bool:
    return b.signature_v2 == group_sig(chain_beacon.message_v2(b.round))


# group_sig's shake-256 input prefix for the inlined hot loop below —
# must stay byte-identical to _h96(b"chaos-group", ...)
_GROUP_PRE = len(b"chaos-group").to_bytes(1, "big") + b"chaos-group"
assert hashlib.shake_256(_GROUP_PRE + b"x").digest(96) == _h96(
    b"chaos-group", b"x")


def _structural_verify_beacons(pubkey, beacons, dst: bytes = b""):
    # hot loop: million-round catch-up walks verify through this
    # stand-in — group_sig(message(...)) is inlined (see the guard
    # above) to shed four Python call layers per beacon
    shake, sha, pre = hashlib.shake_256, hashlib.sha256, _GROUP_PRE
    gs = group_sig
    return np.fromiter(
        (b.signature == shake(
            pre + sha(b.previous_sig
                      + b.round.to_bytes(8, "big")).digest()).digest(96)
         and (not b.signature_v2
              or b.signature_v2 == gs(chain_beacon.message_v2(b.round)))
         for b in beacons),
        dtype=bool, count=len(beacons))


@contextmanager
def structural_crypto():
    """Swap the pairing-class leaves for the structural stand-ins (see
    module docstring). Restores everything on exit, including on
    failure — never leave a patched process for the next test."""

    def _sign_partial(self, msg: bytes) -> bytes:
        with self._lock:
            idx = self._share.pri_share.index
        return make_partial(msg, idx)

    def _structural_verify_checkpoint(pubkey, chain_hash, ckpt) -> bool:
        # mirrors client/checkpoint.py verify_checkpoint: same sanity
        # rejections, group-digest check instead of a BLS pairing
        if (ckpt.round < 1 or ckpt.chain_hash != chain_hash
                or not ckpt.signature or not ckpt.ckpt_sig):
            return False
        return ckpt.ckpt_sig == group_sig(ckpt_mod.checkpoint_message(
            ckpt.chain_hash, ckpt.round, ckpt.signature))

    saved = (engine_crypto.CryptoStore.sign_partial,
             handler_mod._verify_partial_packet,
             batch.aggregate_round, batch.verify_beacons,
             chain_beacon.verify_beacon, chain_beacon.verify_beacon_v2,
             ckpt_mod.verify_checkpoint)
    engine_crypto.CryptoStore.sign_partial = _sign_partial
    handler_mod._verify_partial_packet = _structural_verify_packet
    batch.aggregate_round = _structural_aggregate_round
    batch.verify_beacons = _structural_verify_beacons
    chain_beacon.verify_beacon = _structural_verify_beacon
    chain_beacon.verify_beacon_v2 = _structural_verify_beacon_v2
    ckpt_mod.verify_checkpoint = _structural_verify_checkpoint
    try:
        yield
    finally:
        (engine_crypto.CryptoStore.sign_partial,
         handler_mod._verify_partial_packet,
         batch.aggregate_round, batch.verify_beacons,
         chain_beacon.verify_beacon,
         chain_beacon.verify_beacon_v2,
         ckpt_mod.verify_checkpoint) = saved


# ---------------------------------------------------------------------------
# clocks + links
# ---------------------------------------------------------------------------

class SkewClock(Clock):
    """Per-node offset view over a shared base clock: ``now()`` reads
    ``base + skew`` (a skewed node computes boundaries early/late by
    exactly the skew), sleeps are durations on the base clock."""

    def __init__(self, base: Clock, skew: float = 0.0):
        self.base = base
        self.skew = skew

    def now(self) -> float:
        return self.base.now() + self.skew

    async def sleep(self, seconds: float) -> None:
        await self.base.sleep(seconds)


@dataclass
class LinkPolicy:
    """Per-link message mutation. ``jitter_s`` adds a uniform random
    extra delay per message — with concurrent per-peer sends that IS
    reordering; ``drop`` loses the message silently IN FLIGHT (the
    sender saw a successful send — receiver-side loss), while
    partitions/crashes surface as TransportError (sender-visible)."""

    delay_s: float = 0.0
    jitter_s: float = 0.0
    drop: float = 0.0
    dup: float = 0.0


class ChaosNet(LocalNetwork):
    """LocalNetwork + partitions and per-link policies."""

    def __init__(self, clock: Clock, seed: int = 7):
        super().__init__(seed)
        self.clock = clock
        self.rng = random.Random(seed)
        self._links: dict[tuple[str, str], LinkPolicy] = {}
        self._default_link: LinkPolicy | None = None
        self._partition: dict[str, int] | None = None

    # ---------------------------------------------------------- faults
    def partition(self, groups: list[list[str]]) -> None:
        """Addresses in different groups cannot reach each other (an
        address in no group is isolated from every listed one)."""
        self._partition = {addr: gi
                           for gi, grp in enumerate(groups)
                           for addr in grp}

    def heal(self) -> None:
        self._partition = None

    def set_link(self, src: str, dst: str,
                 policy: LinkPolicy | None) -> None:
        if policy is None:
            self._links.pop((src, dst), None)
        else:
            self._links[(src, dst)] = policy

    def set_default_link(self, policy: LinkPolicy | None) -> None:
        self._default_link = policy

    def clear_links(self) -> None:
        self._links.clear()
        self._default_link = None

    def link_policy(self, src: str, dst: str) -> LinkPolicy | None:
        return self._links.get((src, dst), self._default_link)

    # -------------------------------------------------------- delivery
    def _target(self, src: str, peer) -> ProtocolService:
        dst = peer.address() if hasattr(peer, "address") else str(peer)
        if self._partition is not None:
            gs = self._partition.get(src, -1)
            gd = self._partition.get(dst, -2)
            if gs != gd:
                raise TransportError(
                    f"{src} -> {dst}: partitioned (chaos)")
        return super()._target(src, peer)

    def client_for(self, address: str) -> "ChaosClient":
        return ChaosClient(self, address)


class ChaosClient(LocalClient):
    """LocalClient applying the link policy on the round-critical
    partial path (sync/DKG/info calls see partitions and downs via
    ``_target``, but not delay/drop — catch-up streams model their own
    faults at the peer level)."""

    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        net: ChaosNet = self._net
        dst = peer.address() if hasattr(peer, "address") else str(peer)
        pol = net.link_policy(self._addr, dst)
        if pol is not None:
            if pol.drop and net.rng.random() < pol.drop:
                # lost in flight: receiver never sees it, sender saw a
                # send (reachability must NOT flag the peer down)
                return
            d = pol.delay_s
            if pol.jitter_s:
                d += net.rng.random() * pol.jitter_s
            if d > 0:
                await net.clock.sleep(d)
            if pol.dup and net.rng.random() < pol.dup:
                svc = net._target(self._addr, peer)
                try:
                    await svc.process_partial_beacon(self._addr, packet)
                except TransportError:
                    pass  # the duplicate's reject never outranks the
                    # original delivery's verdict below
        await super().partial_beacon(peer, packet)


# ---------------------------------------------------------------------------
# byzantine member
# ---------------------------------------------------------------------------

class ByzantineCrypto:
    """Wraps a node's CryptoStore so its outbound partials are faulty.

    kinds: ``garbage`` — random bytes under its OWN index (a corrupted
    member; honest bitmaps mark it ``!``); ``wrong_index`` — a valid
    body under ANOTHER node's index prefix (index framing: the frame
    lands on the claimed index, which is exactly what real crypto does
    with an attacker-controlled prefix — documented in obs/flight)."""

    def __init__(self, inner, kind: str, rng: random.Random,
                 frame_index: int | None = None):
        self._inner = inner
        self._kind = kind
        self._rng = rng
        self._frame = frame_index

    def sign_partial(self, msg: bytes) -> bytes:
        own = self._inner.index()
        if self._kind == "wrong_index":
            claim = self._frame if self._frame is not None \
                else (own + 1) % len(self._inner.get_group())
            return claim.to_bytes(tbls.INDEX_BYTES, "big") \
                + partial_body(msg, own)
        return own.to_bytes(tbls.INDEX_BYTES, "big") \
            + self._rng.randbytes(2 * _SIG_HALF)

    def __getattr__(self, name):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------

@dataclass
class RoundObservation:
    """One advanced round, read ONLY off the observability surfaces:
    the probe node's flight record (margin, bitmap), the health pull
    path (lag/missed/sync-stall — the same function /healthz drives),
    and the probe's reachability view."""

    round: int
    stored: bool
    head: int
    lag: int
    missed_total: int
    sync_stalled: bool
    margin_s: float | None
    bitmap: str
    suspects: int


@dataclass
class FaultEvent:
    """One scripted fault, applied just before advancing INTO
    ``at_round``. Actions (kwargs):

    - ``partition`` (groups=[[idx,...],...]) / ``heal``
    - ``link_all`` (policy=LinkPolicy|None) / ``link`` (src,dst,policy)
    - ``deny`` (src,dst) / ``allow`` (src,dst) — ONE-directional edge
      cut (asymmetric partitions: src's calls to dst fail while dst's
      calls to src still go through)
    - ``skew`` (node, seconds)
    - ``crash`` (nodes=[...]) / ``restart`` (nodes=[...])
    - ``byzantine`` (node, kind, frame_index=None)
    - ``flood`` (target, count, kind, round_offset)
    """

    at_round: int
    action: str
    kwargs: dict = field(default_factory=dict)


class ChaosBeaconNetwork:
    """N-node beacon network over a ChaosNet with per-node flight
    recorders and SkewClocks. Use under ``structural_crypto()`` (and
    ``isolated_observability()``) for anything beyond a handful of
    nodes/rounds."""

    def __init__(self, n: int, t: int, period: int = 4,
                 genesis_delay: int = 4, seed: bytes = b"chaos-dkg",
                 net_seed: int = 7, log_level: str = "none",
                 repair: bool = True):
        # repair=False runs the pre-ISSUE-12 passive plane (A/B
        # baselines: bench chaos_soak's with/without-repair comparison)
        self.repair = repair
        self.base_clock = FakeClock()
        self.genesis_time = int(self.base_clock.now()) + genesis_delay
        self.group, self.pairs, self.shares = make_test_group(
            n, t, period, self.genesis_time, seed=seed)
        self.network = ChaosNet(self.base_clock, seed=net_seed)
        self.clocks = [SkewClock(self.base_clock) for _ in range(n)]
        self.flights = [FlightRecorder() for _ in range(n)]
        # per-node health states (BeaconConfig.health): the process
        # singleton's head is a monotonic MAX across in-process nodes,
        # which would make a minority-partition probe observe the
        # majority's progress (lag 0 while its own chain stalls)
        self.healths = [HealthState() for _ in range(n)]
        self._logger = default_logger("chaos", level=log_level)
        self.handlers: list[Handler] = []
        self.stores = [MemStore() for _ in range(n)]
        for i in range(n):
            self.handlers.append(self._make_handler(i))
        self.crashed: set[int] = set()

    # ------------------------------------------------------------- build
    def addr(self, i: int) -> str:
        return self.pairs[i].public.addr

    def flight(self, i: int) -> FlightRecorder:
        return self.flights[i]

    def _make_handler(self, i: int) -> Handler:
        conf = BeaconConfig(
            public=self.group.nodes[i], share=self.shares[i],
            group=self.group, clock=self.clocks[i],
            flight=self.flights[i], health=self.healths[i],
            repair=self.repair)
        h = Handler(client=self.network.client_for(self.addr(i)),
                    store=self.stores[i], conf=conf,
                    logger=self._logger.named(f"n{i}"))
        self.network.register(self.addr(i), h)
        return h

    async def start_all(self) -> None:
        for h in self.handlers:
            await h.start()

    async def advance_to_genesis(self) -> None:
        await self.base_clock.advance_to(self.genesis_time)
        await self._quiesce()

    def stop_all(self) -> None:
        for h in self.handlers:
            h.stop()

    # ------------------------------------------------------------ faults
    def crash(self, i: int) -> None:
        self.handlers[i].stop()
        self.network.set_down(self.addr(i))
        self.crashed.add(i)

    async def restart(self, i: int) -> None:
        """Crash-restart: a FRESH handler over the surviving store (the
        process died; its chain db did not), rejoining via catchup."""
        self.network.set_down(self.addr(i), False)
        self.handlers[i] = self._make_handler(i)  # re-register replaces
        await self.handlers[i].catchup()
        self.crashed.discard(i)

    def skew(self, i: int, seconds: float) -> None:
        self.clocks[i].skew = seconds

    def partition(self, groups: list[list[int]]) -> None:
        self.network.partition(
            [[self.addr(i) for i in grp] for grp in groups])

    def heal(self) -> None:
        self.network.heal()

    def set_link_all(self, policy: LinkPolicy | None) -> None:
        self.network.set_default_link(policy)

    def make_byzantine(self, i: int, kind: str = "garbage",
                       frame_index: int | None = None) -> None:
        self.handlers[i].crypto = ByzantineCrypto(
            self.handlers[i].crypto, kind, self.network.rng,
            frame_index=frame_index)

    # ------------------------------------------------------- injections
    def make_bad_partial(self, round_no: int, claim_index: int,
                         kind: str = "garbage",
                         prev_sig: bytes | None = None,
                         ) -> PartialBeaconPacket:
        """An attacker-crafted packet: ``garbage`` (random body under
        the claimed index), ``wrong_index`` (another index's valid
        body), ``short`` (truncated)."""
        if prev_sig is None:
            prev_sig = self._head_beacon().signature
        msg = chain_beacon.message(round_no, prev_sig)
        if kind == "wrong_index":
            body = partial_body(msg, (claim_index + 1) % len(self.group))
        elif kind == "short":
            body = b"\x00" * 7
        else:
            body = self.network.rng.randbytes(2 * _SIG_HALF)
        sig = claim_index.to_bytes(tbls.INDEX_BYTES, "big") + body
        return PartialBeaconPacket(round=round_no, previous_sig=prev_sig,
                                   partial_sig=sig, partial_sig_v2=b"")

    async def inject_partials(self, packets, targets=None,
                              from_addr: str = "chaos-attacker:666") -> int:
        """Deliver crafted packets straight to target handlers' ingress
        (the real service surface). Returns how many were REJECTED
        (TransportError — window checks and verification)."""
        rejected = 0
        if targets is None:
            targets = [i for i in range(len(self.handlers))
                       if i not in self.crashed]
        for t in targets:
            for p in packets:
                try:
                    await self.handlers[t].process_partial_beacon(
                        from_addr, p)
                except TransportError:
                    rejected += 1
        return rejected

    # ---------------------------------------------------------- advance
    def _head(self, i: int) -> int:
        try:
            return self.stores[i].last().round
        except Exception:  # noqa: BLE001 — empty store during boot
            return 0

    def _head_beacon(self):
        probe = max(range(len(self.stores)), key=self._head)
        return self.stores[probe].last()

    async def _quiesce(self, stable_checks: int = 3,
                       interval: float = 0.005,
                       timeout: float = 3.0) -> None:
        """Let the event loop + to_thread workers drain while the fake
        clock PARKS: wait until per-node heads and the spawned-task
        count are stable for a few consecutive real-time checks."""
        from ..utils import aio

        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        last, stable = None, 0
        while loop.time() < deadline:
            await asyncio.sleep(interval)
            sig = (tuple(self._head(i) for i in range(len(self.stores))),
                   aio.pending_tasks())
            if sig == last:
                stable += 1
                if stable >= stable_checks:
                    return
            else:
                last, stable = sig, 0

    # intra-round wake targets closer together than this (fake seconds)
    # are stepped through in ONE hop before quiescing: a jittered 32-node
    # round scatters ~1000 distinct delivery times, and a real-time
    # quiesce at every single one would cost minutes of wall clock for
    # microseconds of work. Timestamps stay exact per delivery (advance
    # steps through each target); only the quiesce points coalesce, so
    # quorum times can read at most this much late.
    coalesce_s = 0.25

    async def advance_round(self) -> int:
        """Advance INTO the next round and step through the intra-round
        wake targets (delayed links, skewed tickers, catchup breathers),
        quiescing at each coalesced stop so deliveries timestamp at
        their own wake times. Returns the advanced-into round."""
        period = self.group.period
        now = self.base_clock.now()
        nxt, ttime = time_math.next_round(int(now), period,
                                          self.genesis_time)
        await self.base_clock.advance(ttime - now)
        await self._quiesce()
        end = ttime + period
        while True:
            nw = self.base_clock.next_wake()
            if nw is None or nw >= end - 1e-9:
                break
            stop = min(nw + self.coalesce_s, end - 1e-9)
            while nw is not None and nw <= stop:
                await self.base_clock.advance(nw - self.base_clock.now())
                nw = self.base_clock.next_wake()
            await self._quiesce()
        return nxt

    # ------------------------------------------------------ observation
    def observe(self, round_no: int, probe: int = 0) -> RoundObservation:
        """Read the round off the probe node's observability surfaces —
        flight record + the same health pull `/healthz` drives."""
        rec = next((r for r in self.flights[probe].rounds(64)
                    if r["round"] == round_no), None)
        head = self._head(probe)
        snap = self.healths[probe].observe_chain(
            self.clocks[probe].now(), self.group.period,
            self.genesis_time, head_round=head)
        reach = self.flights[probe].reachability()
        return RoundObservation(
            round=round_no, stored=head >= round_no, head=head,
            lag=snap["lag_rounds"], missed_total=snap["missed_total"],
            sync_stalled=snap["sync_stalled"],
            margin_s=rec["margin_s"] if rec else None,
            bitmap=rec["bitmap"] if rec else "",
            suspects=sum(1 for up in reach.values() if not up))

    # --------------------------------------------------------- schedule
    async def apply(self, ev: FaultEvent) -> None:
        kw = ev.kwargs
        if ev.action == "partition":
            self.partition(kw["groups"])
        elif ev.action == "heal":
            self.heal()
            self.network.clear_links()
            self.network.allow_all()
        elif ev.action == "link_all":
            self.set_link_all(kw.get("policy"))
        elif ev.action == "link":
            self.network.set_link(self.addr(kw["src"]),
                                  self.addr(kw["dst"]), kw.get("policy"))
        elif ev.action == "deny":
            self.network.deny(self.addr(kw["src"]), self.addr(kw["dst"]))
        elif ev.action == "allow":
            self.network.allow(self.addr(kw["src"]), self.addr(kw["dst"]))
        elif ev.action == "skew":
            self.skew(kw["node"], kw["seconds"])
        elif ev.action == "crash":
            for i in kw["nodes"]:
                self.crash(i)
        elif ev.action == "restart":
            for i in kw["nodes"]:
                await self.restart(i)
        elif ev.action == "byzantine":
            self.make_byzantine(kw["node"], kw.get("kind", "garbage"),
                                kw.get("frame_index"))
        elif ev.action == "flood":
            head = self._head_beacon().round
            pkts = [self.make_bad_partial(
                head + kw.get("round_offset", 1), kw.get("claim", 0),
                kind=kw.get("kind", "garbage"))
                for _ in range(kw.get("count", 32))]
            await self.inject_partials(pkts,
                                       targets=kw.get("targets"))
        else:
            raise ValueError(f"unknown fault action: {ev.action}")

    async def run_schedule(self, schedule: list[FaultEvent], rounds: int,
                           probe: int = 0,
                           on_round=None) -> list[RoundObservation]:
        """Advance ``rounds`` rounds, applying each event just before
        advancing into its ``at_round``; returns per-round observations
        read off the probe's observability surfaces.

        ``on_round(round_no, now)`` — optional per-round-boundary hook
        run AFTER the probe observation (so health gauges are fresh):
        the incident-engine proof harness (ISSUE 15) drives its sampler
        here, exactly where a live node's store/probe hooks would."""
        by_round: dict[int, list[FaultEvent]] = {}
        for ev in schedule:
            by_round.setdefault(ev.at_round, []).append(ev)
        out: list[RoundObservation] = []
        for _ in range(rounds):
            nxt, _t = time_math.next_round(
                int(self.base_clock.now()), self.group.period,
                self.genesis_time)
            for ev in by_round.get(nxt, []):
                await self.apply(ev)
            advanced = await self.advance_round()
            out.append(self.observe(advanced, probe))
            if on_round is not None:
                on_round(advanced, self.clocks[probe].now())
        return out

    # ---------------------------------------------------------- reshare
    async def reshare_under_churn(self, silent_dealers: set[int],
                                  threshold: int | None = None,
                                  phase_timeout: float = 10.0,
                                  nonce: bytes = b"chaos-reshare"):
        """Mid-ceremony reshare while the beacon network keeps running
        on the same clock (churn): ``silent_dealers`` never run their
        protocol. Returns the live nodes' DistKeyShare results; the
        stall is asserted through FLIGHT.dkg phase timelines (the
        global recorder — DKG sessions are keyed per node tag)."""
        from ..dkg import DKGConfig, DKGProtocol, LocalBoard

        n = len(self.group)
        live = [i for i in range(n) if i not in silent_dealers]
        boards = LocalBoard.make_group(n)
        configs = {
            i: DKGConfig(
                longterm=self.pairs[i], nonce=nonce,
                new_nodes=self.group.nodes,
                threshold=threshold or self.group.threshold,
                old_nodes=self.group.nodes,
                public_coeffs=list(self.group.public_key.coefficients),
                old_threshold=self.group.threshold,
                share=self.shares[i].pri_share,
                clock=self.clocks[i], phase_timeout=phase_timeout,
                seed=b"chaos-reshare-poly")
            for i in live}

        async def drive() -> None:
            # the beacon rounds keep ticking underneath: churn
            for _ in range(8):
                await self.base_clock.advance(phase_timeout)
                await self._quiesce(stable_checks=2, timeout=1.0)

        runs = asyncio.gather(*(DKGProtocol(configs[i], boards[i]).run()
                                for i in live))
        await asyncio.gather(runs, drive())
        return runs.result()


# ---------------------------------------------------------------------------
# report math (shared by tests and bench.py chaos_soak)
# ---------------------------------------------------------------------------

def detection_lead(observations: list[RoundObservation], period: float,
                   warn_fraction: float = 0.5) -> dict:
    """Margin-warning → missed-round lead time. ``warn_round`` is the
    first round whose quorum margin dropped below
    ``warn_fraction * period`` (or that never reached quorum);
    ``missed_round`` the first where the missed counter moved."""
    base_missed = observations[0].missed_total if observations else 0
    warn_round = missed_round = None
    for ob in observations:
        if warn_round is None and (
                ob.margin_s is None
                or ob.margin_s < warn_fraction * period):
            warn_round = ob.round
        if missed_round is None and ob.missed_total > base_missed:
            missed_round = ob.round
            break
    lead = (missed_round - warn_round
            if warn_round is not None and missed_round is not None
            else None)
    return {"warn_round": warn_round, "missed_round": missed_round,
            "lead_rounds": lead,
            "lead_seconds": lead * period if lead is not None else None}


def recovery_seconds(observations: list[RoundObservation],
                     heal_round: int, period: float) -> float | None:
    """Fault heal → lag back to 0, in (fake-clock) seconds."""
    for ob in observations:
        if ob.round >= heal_round and ob.lag == 0:
            return (ob.round - heal_round) * period
    return None
