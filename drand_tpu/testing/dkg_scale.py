"""Large-group DKG/reshare harness (ISSUE 19).

Running a REAL n=256 ceremony in-process means ~n² ECIES point-muls and
n² share checks — minutes of pairing-class arithmetic that measures the
bignum library, not the protocol. This module swaps the GROUP, not the
protocol: :class:`ScalarPoint` is the additive group (Z_r, +) wearing
the PointG1 interface (``g·s`` is literally ``s``), so every structural
property the protocol enforces — commitment consistency, share
verification, complaint/justification state, reshare key preservation —
still holds or fails exactly as it would on G1, while a full n=256
ceremony runs in seconds. The discrete log is trivial by design; this
is a STRUCTURAL harness, never a cryptographic one. Bit-exactness of
the batched verdicts against the real curve is proven separately at
smaller n with real crypto (tests/test_zz_dkg_scale.py).

Pattern follows testing/chaos.structural_crypto: save → patch → yield →
restore in a finally, so a failing test never leaks a patched process.
Schnorr bundle signatures stay REAL — authentication is cheap (2 muls
per bundle, not per deal) and keeping it real exercises the board's
bad_signature reject path at scale.
"""

from __future__ import annotations

import asyncio
from contextlib import contextmanager

from ..crypto import batch, ecies
from ..crypto.fields import R
from ..crypto.poly import PriPoly, PubPoly
from ..dkg import DKGConfig, DKGProtocol, LocalBoard
from ..key.keys import Node, new_key_pair
from ..obs.flight import FLIGHT

_ENC_MARK = b"SDKG"  # structural-ciphertext marker (decrypt rejects junk)


class ScalarPoint:
    """(Z_r, +) with the PointG1 surface the DKG touches: generator=1,
    infinity=0, ``mul`` is field multiplication, serialization is a
    48-byte tag+value that NO real compressed G1 point shares (the
    0x1f lead byte has the compression bit clear, so a structural
    commit fed to the real parser is rejected, never confused)."""

    __slots__ = ("v",)

    def __init__(self, v: int):
        self.v = v % R

    @classmethod
    def infinity(cls) -> "ScalarPoint":
        return cls(0)

    @classmethod
    def generator(cls) -> "ScalarPoint":
        return cls(1)

    def is_infinity(self) -> bool:
        return self.v == 0

    def mul(self, k: int) -> "ScalarPoint":
        return ScalarPoint(self.v * (k % R))

    def __add__(self, other: "ScalarPoint") -> "ScalarPoint":
        return ScalarPoint(self.v + other.v)

    def __neg__(self) -> "ScalarPoint":
        return ScalarPoint(-self.v)

    def __eq__(self, other) -> bool:
        return isinstance(other, ScalarPoint) and self.v == other.v

    def __hash__(self) -> int:
        return hash(("ScalarPoint", self.v))

    def __repr__(self) -> str:
        return f"ScalarPoint({self.v})"

    def to_bytes(self) -> bytes:
        return b"\x1f" + self.v.to_bytes(47, "big")

    @classmethod
    def from_bytes(cls, data: bytes) -> "ScalarPoint":
        if len(data) != 48 or data[:1] != b"\x1f":
            raise ValueError("not a structural point")
        return cls(int.from_bytes(data[1:], "big"))


def _structural_commit(self, base=None) -> PubPoly:
    if base is None:
        base = ScalarPoint.generator()
    return PubPoly([base.mul(c) for c in self.coeffs], base)


def _structural_parse_commits(bundles) -> list:
    out = []
    for cs in bundles:
        try:
            out.append([ScalarPoint.from_bytes(c) for c in cs])
        except ValueError:
            out.append(None)
    return out


def _structural_eval_commits(polys, index) -> list:
    return [p.eval(index).value for p in polys]


def _structural_eval_poly_indices(pub_poly, indices) -> list:
    return [s.value for s in pub_poly.eval_many(indices)]


def _structural_share_checks(pairs) -> list[bool]:
    return [ScalarPoint(s) == exp for s, exp in pairs]


def _structural_reshare_bindings(old_pub, items) -> list[bool]:
    return [old_pub.eval(i).value == q for i, q in items]


def _structural_encrypt(public, msg: bytes) -> bytes:
    return _ENC_MARK + msg


def _structural_decrypt(sk: int, ciphertext: bytes) -> bytes:
    if not ciphertext.startswith(_ENC_MARK):
        raise ValueError("structural ciphertext marker missing")
    return ciphertext[len(_ENC_MARK):]


@contextmanager
def structural_dkg_crypto():
    """Swap the DKG's group/cipher leaves for the scalar stand-ins; the
    batch dispatchers are replaced wholesale (their host/device paths
    assume the real curve — structural points must never reach the
    engine). Everything is restored on exit, including on failure."""
    saved = (PriPoly.commit, batch.parse_commits, batch.eval_commits,
             batch.eval_poly_indices, batch.share_checks,
             batch.reshare_bindings, ecies.encrypt, ecies.decrypt)
    PriPoly.commit = _structural_commit
    batch.parse_commits = _structural_parse_commits
    batch.eval_commits = _structural_eval_commits
    batch.eval_poly_indices = _structural_eval_poly_indices
    batch.share_checks = _structural_share_checks
    batch.reshare_bindings = _structural_reshare_bindings
    ecies.encrypt = _structural_encrypt
    ecies.decrypt = _structural_decrypt
    try:
        yield
    finally:
        (PriPoly.commit, batch.parse_commits, batch.eval_commits,
         batch.eval_poly_indices, batch.share_checks,
         batch.reshare_bindings, ecies.encrypt, ecies.decrypt) = saved


# ---------------------------------------------------------------------------
# ceremony drivers
# ---------------------------------------------------------------------------

def make_group(n: int, prefix: str = "scale") -> tuple[list, list[Node]]:
    """n deterministic longterm pairs + their Node records (indices
    0..n-1). Real schnorr keys — bundle signing stays real."""
    pairs = [new_key_pair(f"{prefix}-{i}.test:9000",
                          seed=b"%s-%d" % (prefix.encode(), i))
             for i in range(n)]
    nodes = [Node(identity=p.public, index=i)
             for i, p in enumerate(pairs)]
    return pairs, nodes


async def run_ceremony(n: int, t: int, *, nonce: bytes = b"scale-dkg",
                       seed: bytes = b"scale-seed", clock=None,
                       phase_timeout: float = 60.0,
                       pairs=None, nodes=None) -> list:
    """Fresh n-node ceremony on LocalBoards (fast-sync short-circuits,
    so wall time is work-bound, not timeout-bound). Returns every
    node's DistKeyShare. Call under :func:`structural_dkg_crypto` for
    big n; real crypto works too at small n."""
    from ..utils.clock import SystemClock

    if pairs is None or nodes is None:
        pairs, nodes = make_group(n)
    boards = LocalBoard.make_group(n)
    clock = clock or SystemClock()
    configs = [DKGConfig(longterm=pairs[i], nonce=nonce, new_nodes=nodes,
                         threshold=t, clock=clock,
                         phase_timeout=phase_timeout, seed=seed)
               for i in range(n)]
    return await asyncio.gather(
        *(DKGProtocol(c, b).run() for c, b in zip(configs, boards)))


async def run_reshare(results: list, pairs, nodes, t_old: int, t_new: int,
                      *, nonce: bytes = b"scale-reshare", clock=None,
                      seed: bytes = b"scale-reseed",
                      phase_timeout: float = 60.0,
                      bad_dealers: tuple[int, ...] = ()) -> list:
    """Reshare an existing group onto the SAME membership (old group ==
    new group — the large-group refresh case). ``bad_dealers`` deal
    from a corrupted old share (constant term off by one): the binding
    check must exclude exactly those dealers from QUAL."""
    from ..crypto.poly import PriShare
    from ..utils.clock import SystemClock

    n = len(nodes)
    boards = LocalBoard.make_group(n)
    clock = clock or SystemClock()
    public_coeffs = list(results[0].commits)
    configs = []
    for i in range(n):
        share = results[i].pri_share
        if i in bad_dealers and share is not None:
            share = PriShare(share.index, (share.value + 1) % R)
        configs.append(DKGConfig(
            longterm=pairs[i], nonce=nonce, new_nodes=nodes,
            threshold=t_new, old_nodes=nodes,
            public_coeffs=public_coeffs, old_threshold=t_old,
            share=share, clock=clock, phase_timeout=phase_timeout,
            seed=seed))
    return await asyncio.gather(
        *(DKGProtocol(c, b).run() for c, b in zip(configs, boards)))


def check_structural_consistency(results: list, t: int,
                                 expected_key=None) -> PubPoly:
    """The structural analogue of test_dkg.check_group_consistency:
    identical commits everywhere, every share satisfies g·s ==
    pub.eval(i) in the stand-in group, optional group-key pin."""
    commits0 = results[0].commits
    for r in results:
        assert [c.to_bytes() for c in r.commits] == \
            [c.to_bytes() for c in commits0]
        assert len(r.commits) == t
    if expected_key is not None:
        assert commits0[0] == expected_key
    pub = PubPoly(list(commits0))
    for r in results:
        if r.pri_share is None:
            continue
        assert ScalarPoint(r.pri_share.value) == \
            pub.eval(r.pri_share.index).value
    return pub


def phase_timeline(mode: str | None = None) -> dict[str, float]:
    """Per-phase seconds from a retained completed flight session (the
    ring keeps max_sessions=16 of the n begun — any retained DONE
    session is a representative timeline; every node ran the same
    phases on the same clock)."""
    for rec in FLIGHT.dkg.sessions():
        if not rec["done"] or rec["error"] is not None:
            continue
        if mode is not None and rec["mode"] != mode:
            continue
        out = {}
        for p in rec["phases"]:
            if p["end_s"] is not None:
                out[p["phase"]] = out.get(p["phase"], 0.0) + \
                    (p["end_s"] - p["start_s"])
        if out:
            return out
    return {}
