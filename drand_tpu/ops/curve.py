"""Batched Jacobian point arithmetic on G1/G2 for the device engine.

Used by the batched Lagrange recovery (the reference's Scheme.Recover hot
call, chain/beacon/chain.go:136), hash-to-curve's cofactor clearing, and
subgroup checks on deserialized signatures.

Representation: a point is a 4-tuple (X, Y, Z, inf) of device arrays — X/Y/Z
field elements (Fp: (..., 32); Fp2: (..., 2, 32)) and inf a boolean batch
mask. Formulas are the same a=0 Jacobian ones as the host reference
(crypto/curves.py), with exceptional cases resolved by masked selects so the
whole thing stays branch-free under jit.

Field genericity: ops take an `F` namespace (F1 for Fp, F2 for Fp2) so G1
and G2 share one implementation.
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P as _P
from ..crypto import curves as hcurves
from . import limb, tower

# ---------------------------------------------------------------------------
# Field namespaces
# ---------------------------------------------------------------------------

F1 = SimpleNamespace(
    name="fp",
    add=limb.add,
    sub=limb.sub,
    neg=limb.neg,
    mul=limb.mont_mul,
    sqr=limb.mont_sqr,
    mul_small=limb.mul_small,
    inv=limb.inv,
    select=limb.select,
    is_zero=limb.is_zero_mod_p,
    zero=lambda shape=(): jnp.zeros(shape + (limb.NLIMBS,), limb.DTYPE),
    one=lambda shape=(): jnp.broadcast_to(jnp.asarray(limb.ONE_MONT),
                                          shape + (limb.NLIMBS,)),
    elem_ndim=1,
)

F2 = SimpleNamespace(
    name="fp2",
    add=tower.f2_add,
    sub=tower.f2_sub,
    neg=tower.f2_neg,
    mul=tower.f2_mul,
    sqr=tower.f2_sqr,
    mul_small=tower.f2_mul_small,
    inv=tower.f2_inv,
    select=tower.f2_select,
    is_zero=tower.f2_is_zero,
    zero=lambda shape=(): jnp.zeros(shape + (2, limb.NLIMBS), limb.DTYPE),
    one=lambda shape=(): jnp.broadcast_to(
        tower.f2_one(), shape + (2, limb.NLIMBS)),
    elem_ndim=2,
)

# Curve constants (mont domain): b coefficients.
B_G1 = np.asarray(limb.int_to_limbs(4 * limb.R_MONT % _P))


def _fp2_const(c0: int, c1: int) -> np.ndarray:
    return np.stack([limb.int_to_limbs(c0 * limb.R_MONT % _P),
                     limb.int_to_limbs(c1 * limb.R_MONT % _P)])


B_G2 = _fp2_const(4, 4)


# ---------------------------------------------------------------------------
# Host <-> device
# ---------------------------------------------------------------------------

def g1_to_device(p: hcurves.PointG1):
    if p.is_infinity():
        z = jnp.zeros((limb.NLIMBS,), limb.DTYPE)
        return (F1.one(()), F1.one(()), z, jnp.asarray(True))
    x, y = p.to_affine()
    return (limb.fp_to_device(x.v), limb.fp_to_device(y.v), F1.one(()),
            jnp.asarray(False))


def g2_to_device(q: hcurves.PointG2):
    if q.is_infinity():
        z = jnp.zeros((2, limb.NLIMBS), limb.DTYPE)
        return (F2.one(()), F2.one(()), z, jnp.asarray(True))
    x, y = q.to_affine()
    return (tower.fp2_to_device(x), tower.fp2_to_device(y), F2.one(()),
            jnp.asarray(False))


def stack_points(pts):
    """Stack a list of same-kind device points along a new leading axis."""
    return tuple(jnp.stack([p[i] for p in pts]) for i in range(4))


def g1_from_device(pt) -> hcurves.PointG1:
    X, Y, Z, inf = (np.asarray(t) for t in pt)
    if bool(inf):
        return hcurves.PointG1.infinity()
    from ..crypto.fields import Fp
    return hcurves.PointG1(Fp(limb.fp_from_device(X)), Fp(limb.fp_from_device(Y)),
                           Fp(limb.fp_from_device(Z)))


def g2_from_device(pt) -> hcurves.PointG2:
    X, Y, Z, inf = (np.asarray(t) for t in pt)
    if bool(inf):
        return hcurves.PointG2.infinity()
    return hcurves.PointG2(tower.fp2_from_device(X), tower.fp2_from_device(Y),
                           tower.fp2_from_device(Z))


# ---------------------------------------------------------------------------
# Group law (branch-free)
# ---------------------------------------------------------------------------

def pt_select(F, cond, a, b):
    # the inf flag is selected through int32: Mosaic cannot lower selects
    # whose BRANCHES are i1 vectors (i8 truncation path); the bool->int
    # conversion itself goes through where (astype lowers as an invalid
    # i1->i32 vreg bitcast)
    inf = jnp.where(cond, jnp.where(a[3], 1, 0), jnp.where(b[3], 1, 0)) != 0
    return (F.select(cond, a[0], b[0]), F.select(cond, a[1], b[1]),
            F.select(cond, a[2], b[2]), inf)


def pt_infinity(F, batch_shape):
    return (F.one(batch_shape), F.one(batch_shape), F.zero(batch_shape),
            jnp.ones(batch_shape, bool))


def pt_neg(F, p):
    X, Y, Z, inf = p
    return (X, F.neg(Y), Z, inf)


def pt_dbl(F, p):
    X, Y, Z, inf = p
    A = F.sqr(X)
    B = F.sqr(Y)
    C = F.sqr(B)
    D = F.mul_small(F.sub(F.sqr(F.add(X, B)), F.add(A, C)), 2)
    E = F.mul_small(A, 3)
    Ff = F.sqr(E)
    X3 = F.sub(Ff, F.mul_small(D, 2))
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), F.mul_small(C, 8))
    Z3 = F.mul_small(F.mul(Y, Z), 2)
    return (X3, Y3, Z3, inf)


def pt_add(F, p1, p2):
    X1, Y1, Z1, inf1 = p1
    X2, Y2, Z2, inf2 = p2
    Z1Z1 = F.sqr(Z1)
    Z2Z2 = F.sqr(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    H = F.sub(U2, U1)
    Sd = F.sub(S2, S1)
    I = F.mul_small(F.sqr(H), 4)
    J = F.mul(H, I)
    r = F.mul_small(Sd, 2)
    V = F.mul(U1, I)
    X3 = F.sub(F.sqr(r), F.add(J, F.mul_small(V, 2)))
    Y3 = F.sub(F.mul(r, F.sub(V, X3)), F.mul_small(F.mul(S1, J), 2))
    Z3 = F.mul(F.sub(F.sqr(F.add(Z1, Z2)), F.add(Z1Z1, Z2Z2)), H)
    # inf flags DERIVED from operands (no constant bool vectors: Mosaic
    # lowers an i1 splat through an i8 buffer whose i1 truncation is
    # unsupported — "Unsupported target bitwidth for truncation")
    added = (X3, Y3, Z3, inf1 & ~inf1)

    h_zero = F.is_zero(H)
    s_zero = F.is_zero(Sd)
    both_live = (~inf1) & (~inf2)
    dbl_case = h_zero & s_zero & both_live
    inf_case = h_zero & (~s_zero) & both_live

    batch_shape = jnp.broadcast_shapes(inf1.shape, inf2.shape)
    inf_pt = (F.one(batch_shape), F.one(batch_shape), F.zero(batch_shape),
              jnp.broadcast_to(inf1 | ~inf1, batch_shape))
    out = pt_select(F, dbl_case, pt_dbl(F, p1), added)
    out = pt_select(F, inf_case, inf_pt, out)
    out = pt_select(F, inf2 & ~inf1, p1, out)
    out = pt_select(F, inf1, p2, out)
    return out


def pt_to_affine(F, p):
    """Affine (x, y) with arbitrary values where inf is set."""
    X, Y, Z, inf = p
    zsafe = F.select(inf, F.one(inf.shape), Z)
    zi = F.inv(zsafe)
    zi2 = F.sqr(zi)
    return F.mul(X, zi2), F.mul(Y, F.mul(zi2, zi)), inf


# ---------------------------------------------------------------------------
# Scalar multiplication
# ---------------------------------------------------------------------------

def _pt_infinity_like(F, p, batch_shape):
    """Infinity with each component arithmetically derived from p's, so the
    result carries p's varying-manual-axes type under shard_map (a fresh
    constant as a lax.scan carry fails typechecking in a mapped region)."""
    zero_tag = jnp.zeros(batch_shape, limb.DTYPE) + (
        p[0].reshape(p[3].shape + (-1,))[..., 0] * 0)
    tag = zero_tag[..., None, None] if F.elem_ndim == 2 else zero_tag[..., None]
    return (F.one(batch_shape) + tag, F.one(batch_shape) + tag,
            F.zero(batch_shape) + tag, jnp.ones(batch_shape, bool) | (zero_tag != 0))


def pt_mul_bits(F, p, bits):
    """Variable-scalar multiplication. bits: (..., nbits) int32, MSB first,
    broadcastable against the point's batch shape. Returns bits ⋅ p."""
    nbits = bits.shape[-1]
    batch_shape = jnp.broadcast_shapes(p[3].shape, bits.shape[:-1])
    acc = _pt_infinity_like(F, p, batch_shape)
    base = tuple(jnp.broadcast_to(c, batch_shape + c.shape[len(p[3].shape):])
                 for c in p)

    def step(acc, bit):
        acc = pt_dbl(F, acc)
        with_add = pt_add(F, acc, base)
        return pt_select(F, bit.astype(bool), with_add, acc), None

    xs = jnp.moveaxis(bits, -1, 0)
    acc, _ = jax.lax.scan(step, acc, xs)
    return acc


def scalar_to_bits(k: int, nbits: int) -> np.ndarray:
    """Host: MSB-first fixed-width bit vector of a non-negative scalar."""
    if k < 0 or k >> nbits:
        raise ValueError("scalar out of range")
    return np.array([(k >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                    dtype=np.int32)


def pt_mul_const(F, p, k: int):
    """Fixed-scalar multiplication (sign-aware), segmented like the Miller
    loop: doubling runs under scan, adds unrolled at the (few) set bits."""
    if k < 0:
        return pt_mul_const(F, pt_neg(F, p), -k)
    if k == 0:
        return pt_infinity(F, p[3].shape)
    bits = bin(k)[3:]  # MSB implicit
    acc = p
    run = 0

    def dbl_body(acc, _):
        return pt_dbl(F, acc), None

    for ch in bits:
        run += 1
        if ch == "1":
            acc, _ = jax.lax.scan(dbl_body, acc, None, length=run)
            acc = pt_add(F, acc, p)
            run = 0
    if run:
        acc, _ = jax.lax.scan(dbl_body, acc, None, length=run)
    return acc


def _pt_index(F, points, idx: int):
    """Select point `idx` along the trailing points axis."""
    return tuple(c[..., idx, :, :] if F.elem_ndim == 2 else c[..., idx, :]
                 for c in points[:3]) + (points[3][..., idx],)


def _pt_axis_pairs(F, pts, half: int):
    """Split the trailing points axis in two halves and add elementwise."""
    lo = tuple(c[..., :half, :, :] if F.elem_ndim == 2 else c[..., :half, :]
               for c in pts[:3]) + (pts[3][..., :half],)
    hi = tuple(c[..., half:, :, :] if F.elem_ndim == 2 else c[..., half:, :]
               for c in pts[:3]) + (pts[3][..., half:],)
    return pt_add(F, lo, hi)


def msm_pippenger(F, points, bits, c: int = 4):
    """Windowed (Pippenger) multi-scalar multiplication, latency-optimized
    for the TPU: the naive interleaved ladder is nbits sequential rounds of
    n sequential masked adds (depth ~ nbits*n point-adds); this runs one
    lax.scan over the ~nbits/c windows whose body is bucket-select (cheap
    masked moves), a log2(n)-depth tree reduction VECTORIZED across the
    2^c-1 buckets, and a 2^c-depth weighted bucket combine — total depth
    ~ (nbits/c) * (log2 n + 2^c + c) point-ops instead of nbits*n.

    points: device point with batch shape (..., n); bits: (..., n, nbits)
    MSB-first. Returns sum_i bits_i * points_i with batch shape (...,).
    """
    n = points[3].shape[-1]
    nbits = bits.shape[-1]
    batch_shape = points[3].shape[:-1]
    nbuckets = (1 << c) - 1
    nwin = -(-nbits // c)
    pad_bits = nwin * c - nbits
    if pad_bits:  # pad scalars at the MSB end with zeros
        bits = jnp.concatenate(
            [jnp.zeros(bits.shape[:-1] + (pad_bits,), bits.dtype), bits],
            axis=-1)
    # digits: (..., n, nwin), MSB window first
    weights = jnp.asarray([1 << (c - 1 - j) for j in range(c)],
                          dtype=bits.dtype)
    digits = jnp.sum(bits.reshape(bits.shape[:-1] + (nwin, c)) *
                     weights, axis=-1)
    # pad the points axis to a power of two with infinity (tree reduce)
    n_pad = 1 << max(1, (n - 1).bit_length())
    p0 = _pt_index(F, points, 0)
    if n_pad != n:
        inf_tail = _pt_infinity_like(F, p0, batch_shape + (n_pad - n,))
        points = tuple(
            jnp.concatenate([a, b], axis=-(F.elem_ndim + 1))
            for a, b in zip(points[:3], inf_tail[:3])
        ) + (jnp.concatenate([points[3], inf_tail[3]], axis=-1),)
        digits = jnp.concatenate(
            [digits, jnp.zeros(batch_shape + (n_pad - n, nwin),
                               digits.dtype)], axis=-2)

    bucket_ids = jnp.arange(1, nbuckets + 1, dtype=digits.dtype)

    def window_body(acc, digit_col):
        # digit_col: (..., n_pad) — this window's digit per point
        for _ in range(c):
            acc = pt_dbl(F, acc)
        # select each point into its bucket: shapes (..., nbuckets, n_pad)
        in_bucket = digit_col[..., None, :] == bucket_ids[:, None]
        sel = pt_select(
            F, in_bucket,
            tuple(jnp.expand_dims(comp, -(F.elem_ndim + 2))
                  for comp in points[:3]) + (points[3][..., None, :],),
            _pt_infinity_like(F, p0, batch_shape + (nbuckets, n_pad)))
        # tree-reduce the points axis, vectorized across buckets
        width = n_pad
        while width > 1:
            width //= 2
            sel = _pt_axis_pairs(F, sel, width)
        buckets = _pt_index(F, sel, 0)  # (..., nbuckets)
        # weighted combine sum_b b*S_b via running suffix sums:
        # running = S_max; total = S_max; then running += S_b, total += running
        running = _pt_index(F, buckets, nbuckets - 1)
        total = running
        for b in range(nbuckets - 2, -1, -1):
            running = pt_add(F, running, _pt_index(F, buckets, b))
            total = pt_add(F, total, running)
        return pt_add(F, acc, total), None

    acc = _pt_infinity_like(F, p0, batch_shape)
    xs = jnp.moveaxis(digits, -1, 0)  # (nwin, ..., n_pad)
    acc, _ = jax.lax.scan(window_body, acc, xs)
    return acc


def msm_scan(F, points, bits):
    """Interleaved-ladder MSM with BOTH loops under ``lax.scan`` — compile
    size is O(1) in n and nbits (one pt_dbl + one pt_add + select in the
    trace), where :func:`msm` unrolls the points axis and
    :func:`msm_pippenger` traces a whole window body; on the XLA limb
    path those unrolled graphs take >10 min to compile at n=128 on a
    small host. Runtime is latency-bound (nbits·n sequential adds on a
    single lane) — right for the aggregator's ONE recovery per round,
    wrong for bulk throughput.

    points: device point with batch shape (..., n); bits: (..., n, nbits)
    MSB-first. Returns sum_i bits_i ⋅ points_i with batch shape (...,).
    """
    lead = F.elem_ndim + 1

    def pts_axis_first(p):
        return tuple(jnp.moveaxis(c, -lead, 0) for c in p[:3]) + (
            jnp.moveaxis(p[3], -1, 0),)

    pts = pts_axis_first(points)            # components (n, ..., elem)
    p0 = tuple(c[0] for c in pts[:3]) + (pts[3][0],)
    batch_shape = points[3].shape[:-1]
    # (nbits, n, ...) — outer scan over bit positions, inner over points
    bits_nf = jnp.moveaxis(jnp.moveaxis(bits, -1, 0), -1, 1) \
        if bits.ndim > 2 else jnp.moveaxis(bits, -1, 0)[:, :]

    def bit_step(acc, bit_col):
        acc = pt_dbl(F, acc)

        def pt_step(a, xs):
            (px, py, pz, pinf, b_i) = xs
            with_add = pt_add(F, a, (px, py, pz, pinf))
            return pt_select(F, b_i.astype(bool), with_add, a), None

        acc, _ = jax.lax.scan(pt_step, acc, pts + (bit_col,))
        return acc, None

    acc = _pt_infinity_like(F, p0, batch_shape)
    acc, _ = jax.lax.scan(bit_step, acc, bits_nf)
    return acc


def msm_lanes(F, points, bits):
    """MSM as per-lane scalar ladders + a log-tree lane reduction: each
    of the n points runs its own double-and-add (vectorized across the
    batch — one 255-step scan), then the n per-lane results fold in
    log2(n) cross-lane adds. Sequential depth ~nbits + log2(n) ≈ 520 ops
    vs ~nbits·n for :func:`msm_scan` — the one-shot recovery MSM that is
    neither a compile bomb (msm/msm_pippenger unroll over points) nor
    latency-bound. Requires n to be a power of two (bucket-padded).

    points: device point with batch shape (n,); bits: (n, nbits)
    MSB-first. Returns sum_i bits_i ⋅ points_i (batch shape ()).
    """
    n = points[3].shape[-1]
    if n & (n - 1):
        raise ValueError(f"msm_lanes needs a power-of-two batch, got {n}")
    acc = pt_mul_bits(F, points, bits)
    width = n
    while width > 1:
        width //= 2
        acc = _pt_axis_pairs(F, acc, width)
    return _pt_index(F, acc, 0)


def msm(F, points, bits):
    """Multi-scalar multiplication over the trailing *points* axis.

    points: device point with batch shape (..., n); bits: (..., n, nbits).
    Returns sum_i bits_i ⋅ points_i with batch shape (...,).

    Interleaved double-and-add: one shared doubling chain for the
    accumulated sum — cost nbits doublings + nbits*n masked adds.
    Prefer :func:`msm_pippenger` for n beyond a handful of points.
    """
    n = points[3].shape[-1]
    nbits = bits.shape[-1]
    batch_shape = points[3].shape[:-1]
    p0 = tuple(c[..., 0, :, :] if F.elem_ndim == 2 else c[..., 0, :]
               for c in points[:3]) + (points[3][..., 0],)
    acc = _pt_infinity_like(F, p0, batch_shape)

    def step(acc, bit_col):
        # bit_col: (..., n)
        acc = pt_dbl(F, acc)
        for i in range(n):
            p_i = tuple(c[..., i, :, :] if F.elem_ndim == 2 else c[..., i, :]
                        for c in points[:3]) + (points[3][..., i],)
            with_add = pt_add(F, acc, p_i)
            acc = pt_select(F, bit_col[..., i].astype(bool), with_add, acc)
        return acc, None

    xs = jnp.moveaxis(bits, -1, 0)  # (nbits, ..., n)
    acc, _ = jax.lax.scan(step, acc, xs)
    return acc
