"""Batch-last Pallas kernel for DKG deal verification.

The deal check evaluates every dealer's public commitment polynomial at
this node's index: ``eval_d = Σ_k C_{d,k}·(idx+1)^k`` (reference kyber
vss VerifyDeal; BASELINE config "n=128 deal verify"). The XLA limb-path
graph (ops/engine._eval_commits_graph) is correct but per-op-latency
bound — measured 0.74× the HOST loop at n=128 in round 3. This kernel
runs the same vectorized Horner — t-1 steps of ([idx+1]·acc + C_k) with
a shared-index double-and-add ladder — as ONE fused Mosaic kernel in the
batch-last layout (dealers on lanes, limbs on sublanes), the layout that
took the pairing path from ~50 to ~20k checks/s.

Design choices:
- The ladder/point formulas are the generic F-parametric ones
  (ops/curve.pt_add/pt_dbl, bl_curve.pt_mul_bits_getter) over the
  batch-last Fp namespace (bl_curve.make_f1) — no new group law to
  trust; golden-tested against the host oracle on the CPU path
  (tests/test_eval_commits.py) and KAT-gated per (t, bucket) on device
  (engine._check_eval_bucket).
- The kernel returns JACOBIAN coordinates + infinity mask: the final
  affine conversion needs one field inverse per dealer, which on device
  is a 381-step Fermat ladder (~770 muls — comparable to the whole
  t=65 Horner); the engine instead batch-inverts on host with the
  Montgomery trick (one bigint modexp for the whole bucket).
- Index bits ride in SMEM ((1, NBITS) int32, MSB-first), read
  element-wise by the ladder (pallas_pairing.smem_bit_getter).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import bl
from . import bl_curve
from . import curve as xc
from .bl import DTYPE, NLIMBS
from .pallas_pairing import _pallas, smem_bit_getter

# index ladder width — matches engine._EVAL_IDX_BITS (groups to n=1022)
NBITS = 10
LANE_BLOCK = 128


def horner_bl(F, get_commit, bit_getter, t: int, b: int):
    """Shared Horner body: ``acc = C_{t-1}; repeat acc = [m]·acc + C_k``
    (k = t-2 .. 0, m = idx+1 from ``bit_getter``, MSB-first NBITS wide).

    ``get_commit(k)`` returns the k-th commitment row as batch-last
    affine ``(x, y)`` each (32, b). Returns Jacobian (X, Y, Z, inf).
    Runs under both Mosaic (refs) and plain XLA (values) — the CPU
    goldens exercise exactly this function."""
    one = F.one((b,))
    no_inf = jnp.zeros((b,), DTYPE)

    x0, y0 = get_commit(t - 1)
    state = (x0, y0, one, no_inf)

    def body(i, st):
        acc = (st[0], st[1], st[2], st[3] != 0)
        acc = bl_curve.pt_mul_bits_getter(F, acc, bit_getter, NBITS)
        cx, cy = get_commit(t - 2 - i)
        acc = xc.pt_add(F, acc, (cx, cy, one, no_inf != 0))
        return (acc[0], acc[1], acc[2], jnp.where(acc[3], 1, 0))

    X, Y, Z, inf32 = jax.lax.fori_loop(0, t - 1, body, state)
    return X, Y, Z, inf32


def _eval_kernel(t: int, c_ref, bits_ref, xs_ref, ys_ref,
                 ox_ref, oy_ref, oz_ref, oinf_ref):
    from jax.experimental import pallas as pl

    b = xs_ref.shape[-1]
    with bl.const_context(c_ref[:]):
        F = bl_curve.make_f1()

        def get_commit(k):
            # dynamic index on the untiled leading (commit) axis
            return (xs_ref[pl.ds(k, 1), :, :][0],
                    ys_ref[pl.ds(k, 1), :, :][0])

        X, Y, Z, inf32 = horner_bl(F, get_commit,
                                   smem_bit_getter(bits_ref), t, b)
    ox_ref[:] = X
    oy_ref[:] = Y
    oz_ref[:] = Z
    oinf_ref[:] = inf32[None, :]


@functools.partial(jax.jit, static_argnames=("t",))
def eval_commits_pl(xs, ys, bits, t: int):
    """Batched commitment evaluation on the Pallas path.

    xs/ys: (t, b, NLIMBS) batch-leading affine mont limbs (the engine's
    packing layout); bits: (NBITS,) int32 MSB-first shared index.
    Returns batch-leading Jacobian (X, Y, Z) each (b, NLIMBS) + inf (b,).
    b must be a multiple of LANE_BLOCK; blocks run as separate kernel
    launches inside this one jit."""
    b = xs.shape[1]
    if b % LANE_BLOCK:
        raise ValueError(f"batch {b} not a LANE_BLOCK multiple")
    xs_bl = jnp.moveaxis(xs, -1, -2)          # (t, 32, b)
    ys_bl = jnp.moveaxis(ys, -1, -2)
    bits2d = bits[None, :].astype(jnp.int32)  # (1, NBITS) SMEM table
    cbuf = jnp.asarray(bl.lane_buffer(LANE_BLOCK))
    shp = jax.ShapeDtypeStruct((NLIMBS, LANE_BLOCK), DTYPE)
    inf_shp = jax.ShapeDtypeStruct((1, LANE_BLOCK), DTYPE)
    call = _pallas(functools.partial(_eval_kernel, t),
                   (shp, shp, shp, inf_shp), "vsvv")
    outs = []
    for s in range(0, b, LANE_BLOCK):
        blk = slice(s, s + LANE_BLOCK)
        outs.append(call(cbuf, bits2d, xs_bl[..., blk], ys_bl[..., blk]))
    X = jnp.concatenate([jnp.moveaxis(o[0], 0, -1) for o in outs], axis=0)
    Y = jnp.concatenate([jnp.moveaxis(o[1], 0, -1) for o in outs], axis=0)
    Z = jnp.concatenate([jnp.moveaxis(o[2], 0, -1) for o in outs], axis=0)
    inf = jnp.concatenate([o[3][0] for o in outs], axis=0)
    return X, Y, Z, inf
