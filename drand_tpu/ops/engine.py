"""Batched device crypto engine — the TPU execution path of the Scheme.

This is the component the whole build exists for (BASELINE.json north star):
the reference's per-round sequential crypto hot calls —
``Scheme.VerifyPartial`` (chain/beacon/node.go:112), ``Scheme.Recover`` +
``VerifyRecovered`` (chain/beacon/chain.go:136-141), and the chain-catchup
verifier (client/verify.go:146-163) — become batched device computations:

- ``verify_bls``: one jitted multi-pairing graph checks a whole tensor of
  (pubkey, signature, message) triples at once.
- ``verify_beacons``: dual V1+V2 beacon verification for a span of rounds,
  flattened into one such tensor.
- ``verify_partials``: all of a round's partials against their per-index
  public key shares in one call.
- ``recover``: Lagrange interpolation of the full signature as a device MSM
  over the partials (the ``Scheme.Recover`` analogue).

Batch shapes are bucketed (padded up to a small set of sizes) so the number
of XLA compilations is bounded; compiled executables are reused across
calls and persisted via the compilation cache (utils/jit_cache.py).

Host-side preparation (SHA-256 message expansion, point decompression,
hash-to-curve) currently runs on the host reference implementation; the
engine interface takes wire-format bytes so the prep can migrate on-device
without touching callers.
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto import batch_verify, tbls
from ..crypto.curves import PointG1, PointG2
from ..crypto.fields import P, R
from ..crypto.hash_to_curve import DEFAULT_DST_G2, hash_to_g2
from ..crypto.poly import PubPoly, PubShare, lagrange_coefficients
from . import curve, limb, pairing, tower

# Each bucket is one XLA compilation of the pairing graph (minutes on a
# cold cache) — keep the set small. Batches above the top bucket split
# into multiple top-bucket calls.
#
# Buckets >= PALLAS_MIN_BUCKET run the fused batch-last Pallas path
# (ops/pallas_pairing.py — Mosaic-compiled kernels, per-kernel fusion);
# smaller buckets run the XLA graph (ops/pairing.py). The axon TPU stack
# currently returns WRONG results for the XLA graph at batch >= ~16
# (libtpu version skew between the client's AOT compiler and the terminal
# runtime; CPU correct at every size) — the Pallas path both dodges that
# compiler path and removes the per-op dispatch overhead. Every bucket is
# still known-answer-validated before first use; failing buckets are
# disabled automatically.
DEFAULT_BUCKETS = (4, 128, 512)
PALLAS_MIN_BUCKET = int(os.environ.get("DRAND_TPU_PALLAS_MIN", "32"))
# wire-prep kernels hold more live state per lane (decompress + h2c +
# pairing); cap their bucket size — larger batches chunk and pipeline
WIRE_MAX_BUCKET = 128

# Device-side randomized batch verification (RLC — crypto/batch_verify.py
# documents the scheme and its soundness): the batched verify graphs
# collapse a span's 2N Miller loops into 2 by combining the span on
# device with the same MSM machinery recovery uses. Scalars are 128-bit,
# per-call, from the host CSPRNG. Lane counts are bucketed (one compile
# per bucket), and spans below ENGINE_RLC_MIN keep the per-item graphs
# (one dispatch either way; the per-shape compile isn't worth it).
# The WIRE tier folds the same combination into the wire pipeline
# (verify_wire_rlc): device hash-to-curve + decompression feed an
# in-graph lane MSM, so catch-up costs 2 Miller loops end-to-end with no
# host hashing either — dispatched by crypto/batch.py under
# engine_op_seconds{path="wire_rlc"} with false-reject-only fallback to
# the per-item wire graph. On a mesh engine the combine additionally
# SHARDS over the batch axis (per-shard h2c + decompression + lane-MSM,
# one cross-shard reduction before the single pairing row) under
# path="wire_rlc_sharded" — N shards of MSM work, still exactly one
# product check per span.
RLC_NBITS = batch_verify.RLC_SCALAR_BITS
RLC_LANE_BUCKETS = (8, 32, 128, 512)
ENGINE_RLC_MIN = int(os.environ.get("DRAND_TPU_ENGINE_RLC_MIN", "8"))

# Device pairing-row meter — the device twin of crypto/pairing.py's
# N_PRODUCT_CHECKS/N_MILLER_PAIRS: every row of a dispatched verify
# graph is one 2-pairing product check executed on device, so tests and
# bench can PROVE Miller-loop claims ("an all-valid wire_rlc catch-up
# span costs exactly 2 Miller pairs, was 2N") without monkeypatching
# graphs. Counted at the public dispatch entrypoints only; known-answer
# probes go through the internal launchers and are not counted.
N_PRODUCT_CHECKS = 0   # verify-graph dispatches
N_MILLER_PAIRS = 0     # 2 x requested rows across those dispatches


def _meter_rows(n: int) -> None:
    global N_PRODUCT_CHECKS, N_MILLER_PAIRS
    N_PRODUCT_CHECKS += 1
    N_MILLER_PAIRS += 2 * n


def _meter_gt_rows(n: int) -> None:
    """One batched GT (pairing-value) dispatch of n single-pair rows —
    the timelock round-open graph (one Miller pair per lane, not the
    verify tiers' two). Counted once per public dispatch like
    _meter_rows, so tests can prove "K ciphertexts opened in ONE
    dispatch" from the same meters."""
    global N_PRODUCT_CHECKS, N_MILLER_PAIRS
    N_PRODUCT_CHECKS += 1
    N_MILLER_PAIRS += n


def _drain(launches) -> np.ndarray:
    """Collect per-bucket outputs with ONE device-side stack and ONE
    host transfer. Through the remote transport, every d2h transfer —
    even of a completed (b,) bool array — pays a ~100 ms polling floor
    (measured: 79 separate np.asarray drains cost 7.5 s after all
    compute finished); stacking on device first makes it one floor
    total. Returns the stacked (n_buckets, b) bool array."""
    devs = [dev for dev, _, _ in launches]
    if len(devs) == 1:
        return np.asarray(devs[0])[None]
    return np.asarray(jnp.stack(devs))


def _pallas_ok(b: int) -> bool:
    """Pallas kernels are compiled by Mosaic — TPU only (the CPU backend
    runs the XLA graphs, which are correct there at every batch size)."""
    import jax

    return b >= PALLAS_MIN_BUCKET and jax.default_backend() == "tpu"


def shard_map_unchecked(f, **kw):
    """``jax.shard_map`` with the replication checker off (a post-gather
    fold makes every device compute the identical total, which the
    varying-axes checker can't infer). Handles both jax layouts: the
    import moved out of experimental and the kwarg was renamed
    check_rep -> check_vma across releases. Shared by the engine's
    sharded wire-RLC combine and the driver's mesh dryrun."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.8 layout
        from jax.experimental.shard_map import shard_map

    try:
        return shard_map(f, check_vma=False, **kw)
    except TypeError:
        return shard_map(f, check_rep=False, **kw)


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# ---------------------------------------------------------------------------
# Host-side packing: wire/host objects -> mont-domain limb arrays
# ---------------------------------------------------------------------------

def _g1_xy(xy) -> np.ndarray:
    x, y = xy
    return np.stack([limb.int_to_mont_limbs(x.v), limb.int_to_mont_limbs(y.v)])


def _g2_xy(xy) -> np.ndarray:
    x, y = xy
    return np.stack([
        np.stack([limb.int_to_mont_limbs(x.c0), limb.int_to_mont_limbs(x.c1)]),
        np.stack([limb.int_to_mont_limbs(y.c0), limb.int_to_mont_limbs(y.c1)]),
    ])


def _g1_aff(p: PointG1) -> np.ndarray:
    return _g1_xy(p.to_affine())


def _g2_aff(q: PointG2) -> np.ndarray:
    return _g2_xy(q.to_affine())


class BatchedEngine:
    """Stateful facade: owns the jitted graphs and the shape buckets."""

    # recovery thresholds at or above this size use the Pippenger MSM
    # (windowed buckets, log-depth tree reduction) instead of the
    # interleaved ladder — the ladder's depth grows linearly with t
    PIPPENGER_MIN_T = 16

    def __init__(self, buckets=DEFAULT_BUCKETS,
                 wire_prep: bool | None = None, mesh=None):
        """``mesh``: an optional 1-axis ``jax.sharding.Mesh``; verify
        batches are sharded over the batch axis (data parallel over
        rounds — SURVEY §5: the chain-catchup verifier sharded across
        chips with pjit; buckets that don't divide the mesh pad up to
        it). The same pattern the driver's dryrun_multichip validates.
        A mesh also arms the SHARDED wire-RLC tier: per-shard device
        h2c + decompression + lane-MSM with one cross-shard reduction
        before the single pairing row (see verify_wire_rlc)."""
        self.buckets = tuple(sorted(buckets))
        self.mesh = mesh
        self._verify = jax.jit(pairing.verify_prepared)
        self._verify_sharded = None
        self._wire_rlc_sharded_jit = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = mesh.axis_names[0]
            shard = NamedSharding(mesh, P(axis))
            self._mesh_size = mesh.devices.size
            self._verify_sharded = jax.jit(
                pairing.verify_prepared,
                in_shardings=(shard, shard, shard), out_shardings=shard)
            self._wire_rlc_sharded_jit = self._make_wire_rlc_sharded()
        self._msm_g2 = jax.jit(
            lambda pts, bits: curve.pt_to_affine(
                curve.F2, curve.msm(curve.F2, pts, bits)))
        self._msm_g2_pip = jax.jit(
            lambda pts, bits: curve.pt_to_affine(
                curve.F2, curve.msm_pippenger(curve.F2, pts, bits)))
        self._msm_g2_lanes = jax.jit(
            lambda pts, bits: curve.pt_to_affine(
                curve.F2, curve.msm_lanes(curve.F2, pts, bits)))
        self._msg_cache: dict[tuple[bytes, bytes], PointG2] = {}
        # wire-prep: hash-to-curve + decompression + subgroup checks run
        # on the DEVICE (Pallas kernels at bucket >= PALLAS_MIN_BUCKET,
        # the XLA graph below) instead of ~60ms/item of host Python — the
        # catch-up throughput fix. DRAND_TPU_WIRE_PREP: "auto" (default,
        # wire path for batches that reach the Pallas bucket), "1"
        # (always), "0" (never).
        if wire_prep is None:
            mode = os.environ.get("DRAND_TPU_WIRE_PREP", "auto")
            wire_prep = {"auto": None, "1": True, "0": False}.get(mode)
        self.wire_prep = wire_prep
        self._verify_wire = jax.jit(self._wire_graph)
        # Known-answer validation per bucket: the axon TPU stack's libtpu
        # version skew produces silently-wrong executables at graph- and
        # shape-dependent thresholds (correct at one batch size, all-wrong
        # at another, moving between graph revisions). Every bucket is
        # self-checked on first use; failing buckets are disabled and
        # batches re-chunk to the largest PROVEN bucket.
        self._bucket_ok: dict[int, bool] = {}
        self._wire_ok: dict[int, bool] = {}
        self._eval_ok: dict[tuple[int, int], bool] = {}
        self._poly_eval_ok: dict[tuple[int, int], bool] = {}
        # keyed (pairing bucket, msm lanes, msm scalar bits) — GLS4 and
        # full-width aggregates compile different executables per shape
        self._agg_ok: dict[tuple[int, int, int], bool] = {}
        self._agg_graph_jit = jax.jit(self._agg_graph)
        # RLC fast paths: per-shape KAT cache + jitted graphs. rlc_min /
        # rlc_lane_buckets are instance attrs so tests can shrink them.
        self.rlc_min = ENGINE_RLC_MIN
        self.rlc_lane_buckets = RLC_LANE_BUCKETS
        self._rlc_ok: dict[tuple, bool] = {}
        self._rlc_g2g2_jit = jax.jit(self._rlc_combine_g2g2_graph)
        self._rlc_g1g2_jit = jax.jit(self._rlc_combine_g1g2_graph)
        # wire-RLC: the combine runs AFTER device hash-to-curve, so a
        # catch-up span needs no host hashing at all (see verify_wire_rlc)
        self._wire_rlc_ok: dict[int, bool] = {}
        self._wire_rlc_jit = jax.jit(self._wire_rlc_graph)
        self._wire_rlc_sharded_ok: dict[int, bool] = {}
        # timelock round-open: batched canonical-GT pairings against ONE
        # shared (pre-folded) G2 point — the round's V2 signature; the K
        # varying U points ride the batch axis (crypto/timelock.py
        # documents the shared-signature structure and the 3^-1 fold)
        self._tl_ok: dict[int, bool] = {}
        self._tl_jit = jax.jit(self._tl_graph)
        # GLS ψ² 4-D scalar split for the recovery/aggregation MSMs:
        # 255-bit Lagrange scalars become four <= 64-bit digit lanes on
        # (P, -ψP, ψ²P, -ψ³P) (crypto/endo.py), quartering the device
        # ladder scan. DRAND_TPU_GLS4=0 reverts to full-width ladders.
        self.gls4 = os.environ.get("DRAND_TPU_GLS4", "1") != "0"

    @staticmethod
    def _wire_graph(pub_aff, sig_x, sig_sign, u_pairs):
        """Fully-device verification from wire-format inputs: decompress +
        subgroup-check the signatures, hash the messages to G2, run the
        batched pairing check."""
        from . import h2c

        sig_pt, on_curve = h2c.decompress_g2_device(sig_x, sig_sign)
        in_subgroup = h2c.subgroup_check_g2(sig_pt)
        msg_pt = h2c.hash_to_g2_device(u_pairs)
        mx, my, _ = curve.pt_to_affine(curve.F2, msg_pt)
        sig_aff = jnp.stack([sig_pt[0], sig_pt[1]], axis=-3)
        msg_aff = jnp.stack([mx, my], axis=-3)
        ok = pairing.verify_prepared(pub_aff, sig_aff, msg_aff)
        return ok & on_curve & in_subgroup

    @staticmethod
    def _wire_rlc_graph(sig_x, sig_sign, u_pairs, live, bits):
        """The wire-RLC combine from wire-format inputs, entirely on
        device: decompress + subgroup-check the signatures, hash the
        messages to G2, then collapse the bucket to (Σc·sig, Σc·H(m))
        with two lane MSMs sharing the scalar vector. Lanes that fail
        decode, hash to infinity, or are padding (``live`` false) are
        masked to infinity in BOTH MSMs, so a bad encoding never poisons
        the combination — it is simply reported False in ``ok``. Returns
        (ok, sx, sy, sinf, mx, my, minf); the combined pair feeds the
        ordinary KAT-gated verify_bls pairing bucket (2 Miller pairs for
        the whole span)."""
        from . import h2c

        sig_pt, on_curve = h2c.decompress_g2_device(sig_x, sig_sign)
        in_subgroup = h2c.subgroup_check_g2(sig_pt)
        msg_pt = h2c.hash_to_g2_device(u_pairs)
        ok = on_curve & in_subgroup & live & ~msg_pt[3]
        dead = ~ok
        sig_jac = (sig_pt[0], sig_pt[1], sig_pt[2], sig_pt[3] | dead)
        msg_jac = (msg_pt[0], msg_pt[1], msg_pt[2], msg_pt[3] | dead)
        sx, sy, sinf = curve.pt_to_affine(
            curve.F2, curve.msm_lanes(curve.F2, sig_jac, bits))
        mx, my, minf = curve.pt_to_affine(
            curve.F2, curve.msm_lanes(curve.F2, msg_jac, bits))
        return ok, sx, sy, sinf, mx, my, minf

    def _make_wire_rlc_sharded(self):
        """The wire-RLC combine SHARDED over the batch axis of the
        1-axis mesh: every shard runs its own decompress + h2c +
        lane-MSM on b/N lanes, then ONE cross-shard reduction (N-1
        point-adds over the gathered per-shard partial sums) precedes
        the affine conversion — so an N-sharded catch-up span is N
        shards of MSM work and still exactly one pairing row
        downstream. Same output contract as ``_wire_rlc_graph``; the
        per-item ``ok`` mask stays sharded, the combined pair comes
        back replicated."""
        from jax.sharding import PartitionSpec as P

        axis = self.mesh.axis_names[0]
        nsh = self._mesh_size

        def local(sig_x, sig_sign, u_pairs, live, bits):
            from . import h2c

            sig_pt, on_curve = h2c.decompress_g2_device(sig_x, sig_sign)
            in_subgroup = h2c.subgroup_check_g2(sig_pt)
            msg_pt = h2c.hash_to_g2_device(u_pairs)
            ok = on_curve & in_subgroup & live & ~msg_pt[3]
            dead = ~ok
            sig_jac = (sig_pt[0], sig_pt[1], sig_pt[2], sig_pt[3] | dead)
            msg_jac = (msg_pt[0], msg_pt[1], msg_pt[2], msg_pt[3] | dead)
            s_part = curve.msm_lanes(curve.F2, sig_jac, bits)
            m_part = curve.msm_lanes(curve.F2, msg_jac, bits)

            def fold(part):
                # the single cross-shard reduction: gather the N partial
                # sums and fold them on every device (each then holds
                # the identical span total — out_specs P() below)
                gathered = tuple(jax.lax.all_gather(c, axis)
                                 for c in part)
                total = tuple(c[0] for c in gathered)
                for k in range(1, nsh):
                    total = curve.pt_add(
                        curve.F2, total, tuple(c[k] for c in gathered))
                return total

            sx, sy, sinf = curve.pt_to_affine(curve.F2, fold(s_part))
            mx, my, minf = curve.pt_to_affine(curve.F2, fold(m_part))
            return ok, sx, sy, sinf, mx, my, minf

        spec = P(axis)
        return jax.jit(shard_map_unchecked(
            local, mesh=self.mesh,
            in_specs=(spec, spec, spec, spec, spec),
            out_specs=(spec, P(), P(), P(), P(), P(), P())))

    def _wire_rlc_shardable(self, b: int) -> bool:
        """A combine bucket shards iff it divides evenly over the mesh
        with a power-of-two per-shard lane count (the local msm_lanes
        fold needs it); the Mosaic path (TPU) takes precedence — the
        sharded XLA combine targets the virtual CPU mesh and real
        multi-chip data parallelism, not single-chip Pallas."""
        if self.mesh is None or _pallas_ok(b):
            return False
        if b % self._mesh_size:
            return False
        per_shard = b // self._mesh_size
        return per_shard >= 1 and not (per_shard & (per_shard - 1))

    # ---------------------------------------------------- introspection
    def introspect(self) -> dict:
        """JSON-ready snapshot of the engine's runtime state for
        ``GET /debug/engine`` / ``drand util engine`` (ISSUE 6):
        backend/device identity, the bucket configuration, and every
        graph family's per-shape KAT-gate verdicts (True = proven,
        False = disabled after a failed known-answer probe; shapes not
        listed were never dispatched). Reading the KAT caches never
        triggers a probe — the report reflects what actually ran."""
        devices = []
        try:
            devices = [str(d) for d in jax.devices()]
        except Exception:  # noqa: BLE001 — a dying tunnel must not 500
            pass
        return {
            "backend": jax.default_backend(),
            "devices": devices,
            "mesh": (None if self.mesh is None
                     else {"axes": list(self.mesh.axis_names),
                           "size": int(self.mesh.devices.size)}),
            "buckets": list(self.buckets),
            "wire_buckets": list(self._wire_buckets()),
            "wire_rlc_buckets": list(self._wire_rlc_buckets()),
            "wire_rlc_sharded_buckets": [
                b for b in self._wire_rlc_buckets()
                if self._wire_rlc_shardable(b)],
            "rlc_lane_buckets": list(self.rlc_lane_buckets),
            "rlc_min": self.rlc_min,
            "wire_prep": self.wire_prep,
            "gls4": self.gls4,
            "pallas_min_bucket": PALLAS_MIN_BUCKET,
            "kat": {
                "verify": {str(b): ok
                           for b, ok in sorted(self._bucket_ok.items())},
                "wire": {str(b): ok
                         for b, ok in sorted(self._wire_ok.items())},
                "rlc": {f"{kind}/{lanes}": ok for (kind, lanes), ok
                        in sorted(self._rlc_ok.items())},
                "wire_rlc": {str(b): ok for b, ok
                             in sorted(self._wire_rlc_ok.items())},
                "timelock": {str(b): ok for b, ok
                             in sorted(self._tl_ok.items())},
                # shard-shape key: bucket over mesh lanes per shard
                "wire_rlc_sharded": {
                    f"b{b}/m{self._mesh_size}": ok for b, ok
                    in sorted(self._wire_rlc_sharded_ok.items())}
                if self.mesh is not None else {},
                "eval": {f"t{t}/b{b}": ok for (t, b), ok
                         in sorted(self._eval_ok.items())},
                "poly_eval": {f"t{t}/b{b}": ok for (t, b), ok
                              in sorted(self._poly_eval_ok.items())},
                "agg": {f"b{b}/msm{m}/w{nb}": ok for (b, m, nb), ok
                        in sorted(self._agg_ok.items())},
            },
        }

    # -- hashing (host, memoized: the aggregator re-verifies the same round
    #    message for every partial) -----------------------------------------
    def _hash_msg(self, msg: bytes, dst: bytes) -> PointG2:
        key = (msg, dst)
        got = self._msg_cache.get(key)
        if got is None:
            if len(self._msg_cache) > 4096:
                self._msg_cache.clear()
            got = hash_to_g2(msg, dst)
            self._msg_cache[key] = got
        return got

    # ------------------------------------------- RLC batch verification
    # Device version of crypto/batch_verify.py: the span's random linear
    # combination is computed ON DEVICE (G1/G2 MSMs over the lane axis —
    # the same scalar-ladder machinery recovery uses) and the combined
    # row goes through the ordinary KAT-gated verify_bls bucket, so the
    # span costs 2 Miller loops instead of 2N. The combine graph is a
    # SEPARATE jit from the pairing bucket on purpose: a fused
    # MSM+pairing graph is a fresh multi-minute XLA compile per shape,
    # while the composed form reuses the pairing executable every other
    # path already compiled (one extra dispatch — through the tunnel
    # that is ~100 ms, still far below N-2 saved Miller loops for real
    # catch-up spans). A wrong verdict can only be a false REJECT (the
    # per-item fallback then decides); the combine graphs are still
    # KAT-checked against the host MSM before first use.

    @staticmethod
    def _z_one_f2(like):
        return jnp.zeros_like(like).at[:, 0, :].set(
            jnp.asarray(limb.ONE_MONT))

    @staticmethod
    def _rlc_combine_g2g2_graph(ax, ay, ainf, bx, by, binf, bits):
        """Two G2 MSMs sharing one scalar vector: (Σc·A_i, Σc·B_i) —
        the sig/message combination of a one-key-many-messages span."""
        z2 = BatchedEngine._z_one_f2(ax)
        a_pt = curve.msm_lanes(curve.F2, (ax, ay, z2, ainf), bits)
        b_pt = curve.msm_lanes(curve.F2, (bx, by, z2, binf), bits)
        axa, aya, a_inf = curve.pt_to_affine(curve.F2, a_pt)
        bxa, bya, b_inf = curve.pt_to_affine(curve.F2, b_pt)
        return axa, aya, a_inf, bxa, bya, b_inf

    @staticmethod
    def _rlc_combine_g1g2_graph(px, py, pinf, sx, sy, sinf, bits):
        """G1 MSM + G2 MSM sharing one scalar vector: (Σc·pk_i, Σc·sig_i)
        — the key/sig combination of a one-message-many-keys span."""
        one = jnp.asarray(limb.ONE_MONT)
        z1 = jnp.broadcast_to(one, px.shape)
        z2 = BatchedEngine._z_one_f2(sx)
        k_pt = curve.msm_lanes(curve.F1, (px, py, z1, pinf), bits)
        s_pt = curve.msm_lanes(curve.F2, (sx, sy, z2, sinf), bits)
        kx, ky, k_inf = curve.pt_to_affine(curve.F1, k_pt)
        sxa, sya, s_inf = curve.pt_to_affine(curve.F2, s_pt)
        return kx, ky, k_inf, sxa, sya, s_inf

    def _rlc_wanted(self, n: int) -> bool:
        """Same escape hatch as the host dispatch (DRAND_TPU_BATCH_VERIFY)
        plus the engine's own floor."""
        from ..crypto.batch import _rlc_threshold

        thr = _rlc_threshold()
        return thr is not None and n >= max(thr, self.rlc_min)

    def _rlc_lanes(self, n: int) -> int | None:
        for b in self.rlc_lane_buckets:
            if n <= b:
                return b
        return None

    @staticmethod
    def _pack_rlc_bits(scalars, lanes: int) -> np.ndarray:
        bits = np.zeros((lanes, RLC_NBITS), np.int32)
        for i, c in enumerate(scalars):
            bits[i] = curve.scalar_to_bits(c, RLC_NBITS)
        return bits

    @staticmethod
    def _pack_rlc_g2(pts, lanes: int):
        pad = _g2_aff(PointG2.generator())
        arr = np.broadcast_to(pad, (lanes, 2, 2, limb.NLIMBS)).copy()
        inf = np.ones(lanes, dtype=bool)
        for i, xy in enumerate(PointG2.batch_to_affine(pts)):
            arr[i] = _g2_xy(xy)
            inf[i] = False
        return arr[:, 0], arr[:, 1], inf

    @staticmethod
    def _pack_rlc_g1(pts, lanes: int):
        pad = _g1_aff(PointG1.generator())
        arr = np.broadcast_to(pad, (lanes, 2, limb.NLIMBS)).copy()
        inf = np.ones(lanes, dtype=bool)
        for i, xy in enumerate(PointG1.batch_to_affine(pts)):
            arr[i] = _g1_xy(xy)
            inf[i] = False
        return arr[:, 0], arr[:, 1], inf

    def _combine_g2g2(self, a_pts, b_pts, cs, lanes: int):
        """One combine dispatch: (Σc·a_i, Σc·b_i) as host PointG2s, or
        None when either combination is degenerate (infinity — never for
        honest inputs except with ~2^-128 probability)."""
        bits = self._pack_rlc_bits(cs, lanes)
        ax, ay, ainf = self._pack_rlc_g2(a_pts, lanes)
        bx, by, binf = self._pack_rlc_g2(b_pts, lanes)
        out = self._rlc_g2g2_jit(
            jnp.asarray(ax), jnp.asarray(ay), jnp.asarray(ainf),
            jnp.asarray(bx), jnp.asarray(by), jnp.asarray(binf),
            jnp.asarray(bits))
        axa, aya, a_inf, bxa, bya, b_inf = (np.asarray(c) for c in out)
        if bool(a_inf) or bool(b_inf):
            return None
        return _g2_from_affine_dev(axa, aya), _g2_from_affine_dev(bxa, bya)

    def _combine_g1g2(self, pk_pts, sig_pts, cs, lanes: int):
        """One combine dispatch: (Σc·pk_i on G1, Σc·sig_i on G2)."""
        bits = self._pack_rlc_bits(cs, lanes)
        px, py, pinf = self._pack_rlc_g1(pk_pts, lanes)
        sx, sy, sinf = self._pack_rlc_g2(sig_pts, lanes)
        out = self._rlc_g1g2_jit(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(sx), jnp.asarray(sy), jnp.asarray(sinf),
            jnp.asarray(bits))
        kx, ky, k_inf, sxa, sya, s_inf = (np.asarray(c) for c in out)
        if bool(k_inf) or bool(s_inf):
            return None
        return _g1_from_affine_dev(kx, ky), _g2_from_affine_dev(sxa, sya)

    def _check_rlc(self, kind: str, lanes: int) -> bool:
        """KAT one combine shape against the host MSM on fixed points and
        scalars. A miscompiled combine can only produce a false REJECT
        downstream (the pairing row is the separately-KAT-gated
        verify_bls bucket, and a wrong combined point fails it), so this
        gate protects the fast path's usefulness, not soundness."""
        key = (kind, lanes)
        ok = self._rlc_ok.get(key)
        if ok is not None:
            return ok
        g2 = PointG2.generator()
        cs = [5, 7]
        a = [g2.mul(2), g2.mul(3)]
        try:
            if kind == "g2g2":
                b = [g2.mul(9), g2.mul(4)]
                got = self._combine_g2g2(a, b, cs, lanes)
                ok = (got is not None
                      and got[0] == g2.mul(2 * 5 + 3 * 7)
                      and got[1] == g2.mul(9 * 5 + 4 * 7))
            else:
                g1 = PointG1.generator()
                pks = [g1.mul(2), g1.mul(3)]
                got = self._combine_g1g2(pks, a, cs, lanes)
                ok = (got is not None
                      and got[0] == g1.mul(2 * 5 + 3 * 7)
                      and got[1] == g2.mul(2 * 5 + 3 * 7))
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._rlc_ok[key] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "rlc_combine_disabled", kind=kind, lanes=lanes)
        return ok

    def _combine_span(self, kind: str, xs, ys):
        """Combine a whole span (chunked over the top lane bucket, chunk
        sums added on host): (combined_x, combined_y) host points, or
        None when a shape is untrusted or a combination degenerates."""
        n = len(xs)
        cs = batch_verify.rlc_scalars(n)
        fn = self._combine_g2g2 if kind == "g2g2" else self._combine_g1g2
        acc_x = acc_y = None
        top = self.rlc_lane_buckets[-1]
        for lo in range(0, n, top):
            hi = min(lo + top, n)
            lanes = self._rlc_lanes(hi - lo)
            if lanes is None or not self._check_rlc(kind, lanes):
                return None
            got = fn(xs[lo:hi], ys[lo:hi], cs[lo:hi], lanes)
            if got is None:
                return None
            acc_x = got[0] if acc_x is None else acc_x + got[0]
            acc_y = got[1] if acc_y is None else acc_y + got[1]
        if acc_x.is_infinity() or acc_y.is_infinity():
            return None
        return acc_x, acc_y

    def _rlc_verify_beacons(self, pubkey: PointG1, beacons,
                            dst: bytes) -> np.ndarray | None:
        """RLC fast path for a span of beacons: per-beacon bool array
        when the all-valid 2-pairing check lands, None to fall back to
        the per-item graphs (some check failed / shape disabled)."""
        if pubkey.is_infinity():
            return None
        from ..chain import beacon as chain_beacon

        ok_mask = np.ones(len(beacons), dtype=bool)
        sig_pts, msg_pts = [], []
        for i, bcn in enumerate(beacons):
            checks = [(chain_beacon.message(bcn.round, bcn.previous_sig),
                       bcn.signature)]
            if bcn.is_v2():
                checks.append((chain_beacon.message_v2(bcn.round),
                               bcn.signature_v2))
            pts = [batch_verify.decode_sig(s) for _, s in checks]
            if any(p is None for p in pts):
                ok_mask[i] = False  # per-item reject, never combined
                continue
            sig_pts.extend(pts)
            msg_pts.extend(self._hash_msg(m, dst) for m, _ in checks)
        if not sig_pts:
            return ok_mask  # nothing decodable: every beacon already False
        comb = self._combine_span("g2g2", sig_pts, msg_pts)
        if comb is None:
            return None
        s_comb, m_comb = comb
        if bool(self.verify_bls([(pubkey, s_comb, m_comb)])[0]):
            return ok_mask
        return None

    def _rlc_verify_partials(self, pub_poly: PubPoly, msg: bytes, partials,
                             dst: bytes) -> list[bool] | None:
        msg_pt = self._hash_msg(msg, dst)
        if msg_pt.is_infinity():
            return None
        got = self._rlc_partials_comb(pub_poly, msg_pt, partials)
        if got is None:
            return None
        mask, k_comb, s_comb = got
        if bool(self.verify_bls([(k_comb, s_comb, msg_pt)])[0]):
            return [bool(v) for v in mask]
        return None

    def _rlc_partials_comb(self, pub_poly: PubPoly, msg_pt: PointG2,
                           partials):
        """Shared prefilter+combine of a round's partials: (wellformed
        mask, Σc·pk, Σc·sig) or None."""
        pubkeys = self._share_pubkeys(pub_poly, partials)
        mask = np.zeros(len(partials), dtype=bool)
        pk_pts, sig_pts = [], []
        for i, (p, pk) in enumerate(zip(partials, pubkeys)):
            if pk is None or pk.is_infinity():
                continue
            pt = batch_verify.decode_sig(p[tbls.INDEX_BYTES:])
            if pt is None:
                continue
            mask[i] = True
            pk_pts.append(pk)
            sig_pts.append(pt)
        if not sig_pts:
            return None
        comb = self._combine_span("g1g2", pk_pts, sig_pts)
        if comb is None:
            return None
        return mask, comb[0], comb[1]

    # ------------------------------------------------------------ verify
    # -------------------------------------------------- bucket validation
    def _known_answer_triples(self):
        from ..crypto import bls

        sk = 0x5A17
        pub = PointG1.generator().mul(sk)
        m_ok, m_bad = b"engine-bucket-check-ok", b"engine-bucket-check-bad"
        sig_ok = PointG2.from_bytes(bls.sign(sk, m_ok), subgroup_check=False)
        return [(pub, sig_ok, self._hash_msg(m_ok, DEFAULT_DST_G2)),
                (pub, sig_ok, self._hash_msg(m_bad, DEFAULT_DST_G2))]

    def _check_bucket(self, b: int) -> bool:
        ok = self._bucket_ok.get(b)
        if ok is not None:
            return ok
        triples = self._known_answer_triples()
        try:
            if b == 1:  # one row per call
                out = np.concatenate([self._run_bucket(triples[:1], 1),
                                      self._run_bucket(triples[1:], 1)])
                ok = bool(out[0]) and not bool(out[1])
            else:
                dev, valid, _ = self._launch_bucket(triples, b)
                full = np.asarray(dev)
                # Rows 0/1 are the positive/negative probes; every pad row
                # is the deterministic generator triple, which verifies
                # True — the documented axon failure mode is lane-dependent
                # silent miscompiles, so ALL lanes must match, not just the
                # probe lanes.
                ok = (bool(full[0]) and not bool(full[1])
                      and bool(full[2:].all()) and bool(valid[:2].all()))
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._bucket_ok[b] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "bucket_disabled", bucket=b,
                reason="known-answer test failed (backend miscompile)")
        return ok

    def _good_bucket(self, n: int, check=None, buckets=None) -> int | None:
        """Smallest validated bucket >= n, else the largest validated one
        (the caller chunks), else None (no trustworthy bucket)."""
        check = check or self._check_bucket
        buckets = buckets if buckets is not None else self.buckets
        for b in buckets:
            if b >= n and check(b):
                return b
        for b in reversed(buckets):
            if check(b):
                return b
        return None

    def verify_bls(self, triples) -> np.ndarray:
        """Batch-verify BLS triples ``(pub: PointG1, sig: PointG2|None,
        msg_point: PointG2)``; a None signature marks an entry already known
        invalid (failed decode). Returns a bool array of len(triples).

        Batches beyond the largest validated bucket are dispatched as
        multiple ASYNC device calls and drained with a single tail sync —
        a blocking sync through the remote-device transport costs ~100 ms
        of polling latency regardless of the wait, so per-chunk syncs
        would serialize the whole batch on host round-trips. With no
        validated bucket the engine raises (auto mode falls back to the
        host path)."""
        n = len(triples)
        if n == 0:
            return np.zeros(0, dtype=bool)
        b = self._good_bucket(n)
        if b is None:
            raise RuntimeError(
                "device engine: no bucket passed known-answer validation")
        _meter_rows(n)
        launches = [self._launch_bucket(triples[i:i + b], b)
                    for i in range(0, n, b)]
        stacked = _drain(launches)
        return np.concatenate([(stacked[j] & valid)[:c]
                               for j, (_, valid, c) in enumerate(launches)])

    def _launch_bucket(self, triples, b: int):
        """Dispatch one padded bucket; returns (device_out, valid, count)
        WITHOUT synchronizing — callers drain all launches at once.

        On a mesh engine the bucket pads UP to the next mesh multiple
        (extra generator rows masked out via ``valid``, the same trick
        the wire-RLC combine uses for bad lanes) so the sharded
        executable always engages — a bucket that doesn't divide the
        mesh used to drop silently to a single device."""
        n = len(triples)
        if self.mesh is not None and b % self._mesh_size:
            b = -(-b // self._mesh_size) * self._mesh_size
        pubs = np.zeros((b, 2, limb.NLIMBS), np.int32)
        sigs = np.zeros((b, 2, 2, limb.NLIMBS), np.int32)
        msgs = np.zeros((b, 2, 2, limb.NLIMBS), np.int32)
        valid = np.zeros(b, dtype=bool)
        # pad rows must be well-formed non-infinity points: use g1/g2 bases
        pad_pub, pad_g2 = _g1_aff(PointG1.generator()), _g2_aff(PointG2.generator())
        pubs[:], sigs[:], msgs[:] = pad_pub, pad_g2, pad_g2
        # one simultaneous inversion for every point in the bucket (the
        # per-point to_affine inverse dominates host packing otherwise)
        rows, g1s, g2s = [], [], []
        for i, (pub, sig, msg_pt) in enumerate(triples):
            if sig is None or sig.is_infinity() or pub.is_infinity() \
                    or msg_pt.is_infinity():
                continue
            rows.append(i)
            g1s.append(pub)
            g2s.append(sig)
            g2s.append(msg_pt)
        g1_xy = PointG1.batch_to_affine(g1s)
        g2_xy = PointG2.batch_to_affine(g2s)
        for j, i in enumerate(rows):
            pubs[i] = _g1_xy(g1_xy[j])
            sigs[i] = _g2_xy(g2_xy[2 * j])
            msgs[i] = _g2_xy(g2_xy[2 * j + 1])
            valid[i] = True
        sharded = self.mesh is not None  # b is a mesh multiple by now
        if _pallas_ok(b):
            from . import pallas_pairing

            if sharded and (b // self._mesh_size) % \
                    pallas_pairing.GRID_BLOCK == 0:
                ok = pallas_pairing.verify_prepared_pl_sharded(
                    pubs, sigs, msgs, self.mesh)
            else:
                ok = pallas_pairing.verify_prepared_pl(pubs, sigs, msgs)
        elif sharded:
            ok = self._verify_sharded(jnp.asarray(pubs), jnp.asarray(sigs),
                                      jnp.asarray(msgs))
        else:
            ok = self._verify(jnp.asarray(pubs), jnp.asarray(sigs),
                              jnp.asarray(msgs))
        return ok, valid, n

    def _run_bucket(self, triples, b: int) -> np.ndarray:
        dev, valid, n = self._launch_bucket(triples, b)
        return (np.asarray(dev) & valid)[:n]

    def verify_beacons(self, pubkey: PointG1, beacons,
                       dst: bytes = DEFAULT_DST_G2, *,
                       try_wire_rlc: bool = True) -> np.ndarray:
        """Dual-verify a span of beacons (V1 chain message + V2 when present)
        in one flattened batch — the chain-catchup hot path
        (client/verify.go:146-163 made parallel). Returns per-beacon bools.

        ``try_wire_rlc=False`` skips the wire-RLC fast path — used by the
        crypto/batch.py dispatcher, which attempts that tier itself under
        its own ``engine_op_seconds{path="wire_rlc"}`` label so a clean
        fallback doesn't pay the combine dispatch twice."""
        from ..chain import beacon as chain_beacon

        n_checks = sum(1 + (1 if bcn.is_v2() else 0) for bcn in beacons)
        use_wire = (self.wire_prep if self.wire_prep is not None
                    else n_checks >= PALLAS_MIN_BUCKET)
        if use_wire:
            if try_wire_rlc and self._rlc_wanted(n_checks):
                got = self.verify_beacons_wire_rlc(pubkey, beacons, dst)
                if got is not None:
                    return got
            checks = []  # (msg_bytes, sig_bytes)
            spans = []
            for bcn in beacons:
                start = len(checks)
                checks.append((chain_beacon.message(bcn.round,
                                                    bcn.previous_sig),
                               bcn.signature))
                if bcn.is_v2():
                    checks.append((chain_beacon.message_v2(bcn.round),
                                   bcn.signature_v2))
                spans.append((start, len(checks) - start))
            try:
                flat = self.verify_wire(pubkey, checks, dst)
                return np.array([bool(flat[s:s + c].all())
                                 for s, c in spans])
            except Exception:  # noqa: BLE001 — incl. Mosaic trace/lowering
                if self.wire_prep:  # explicitly requested: surface it
                    raise
                # auto mode: wire buckets failed known-answer validation
                # (or the wire graph failed to trace/lower) — fall through
                # to the (still-validated) triples path rather than the
                # slow host loop
        if self._rlc_wanted(n_checks):
            # RLC fast path: the whole span as 2 Miller loops; a failed
            # (or untrusted) combination falls through to the per-item
            # triples graph for exact per-beacon verdicts
            got = self._rlc_verify_beacons(pubkey, beacons, dst)
            if got is not None:
                return got
        triples = []
        spans = []  # (start, count) per beacon
        for bcn in beacons:
            start = len(triples)
            msg = chain_beacon.message(bcn.round, bcn.previous_sig)
            triples.append((pubkey, _decode_sig(bcn.signature),
                            self._hash_msg(msg, dst)))
            if bcn.is_v2():
                msg2 = chain_beacon.message_v2(bcn.round)
                triples.append((pubkey, _decode_sig(bcn.signature_v2),
                                self._hash_msg(msg2, dst)))
            spans.append((start, len(triples) - start))
        flat = self.verify_bls(triples)
        return np.array([bool(flat[s:s + c].all()) for s, c in spans])

    def _wire_buckets(self):
        """On TPU only Pallas-path sizes: the XLA wire graph at small
        buckets is the axon stack's flaky regime AND a multi-minute
        compile — not worth probing mid-batch. CPU runs the XLA graph at
        any size."""
        ok = tuple(b for b in self.buckets if b <= WIRE_MAX_BUCKET)
        if jax.default_backend() == "tpu":
            ok = tuple(b for b in ok if b >= PALLAS_MIN_BUCKET) or ok[-1:]
        return ok

    def _check_wire_bucket(self, b: int) -> bool:
        ok = self._wire_ok.get(b)
        if ok is not None:
            return ok
        from ..crypto import bls

        sk = 0x5A17
        pub = PointG1.generator().mul(sk)
        m = b"engine-wire-bucket-check"
        checks = [(m, bls.sign(sk, m)), (b"other-msg", bls.sign(sk, m))]
        try:
            if b == 1:  # one row per call (same split as _check_bucket)
                out = np.concatenate(
                    [self._run_wire_bucket(pub, checks[:1], 1),
                     self._run_wire_bucket(pub, checks[1:], 1)])
                ok = bool(out[0]) and not bool(out[1])
            else:
                dev, valid, _ = self._launch_wire_bucket(pub, checks, b)
                full = np.asarray(dev)
                # pad rows carry the generator as "signature" over the pad
                # message under this pubkey — they must all verify False
                # (full-lane check; see _check_bucket)
                ok = (bool(full[0]) and not bool(full[1])
                      and not bool(full[2:].any())
                      and bool(valid[:2].all()))
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._wire_ok[b] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "wire_bucket_disabled", bucket=b)
        return ok

    def verify_wire(self, pubkey: PointG1, checks,
                    dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
        """Batch-verify (message bytes, compressed signature) pairs with
        DEVICE-side hashing/decompression/subgroup checks (ops/h2c.py):
        host work is only SHA-256 expansion and byte unpacking. Buckets are
        known-answer-validated like verify_bls's; chunks dispatch async
        with one tail drain (see verify_bls)."""
        n = len(checks)
        if n == 0:
            return np.zeros(0, dtype=bool)
        b = self._good_bucket(n, check=self._check_wire_bucket,
                              buckets=self._wire_buckets())
        if b is None:
            raise RuntimeError(
                "device engine: no wire bucket passed validation")
        _meter_rows(n)
        launches = [self._launch_wire_bucket(pubkey, checks[i:i + b], b, dst)
                    for i in range(0, n, b)]
        stacked = _drain(launches)
        return np.concatenate([(stacked[j] & valid)[:c]
                               for j, (_, valid, c) in enumerate(launches)])

    def pack_wire_bucket(self, pubkey: PointG1, checks, b: int,
                         dst: bytes = DEFAULT_DST_G2):
        """Host-side prep of one padded wire bucket: SHA message
        expansion + signature byte unpacking. The packed tuple can be
        re-dispatched any number of times via :meth:`dispatch_wire_packed`
        — the measured-replay bench streams millions of rounds by cycling
        a content-varied pool of packed buckets, so the timed loop is
        pure device work (client/verify.go:146-163 scale)."""
        from . import h2c

        n = len(checks)
        pad_msg = b"drand-tpu-pad"
        msgs = [m for m, _ in checks] + [pad_msg] * (b - n)
        u = h2c.msgs_to_u(msgs, dst)
        pad_sig = _PAD_SIG()
        sigs = [s for _, s in checks] + [pad_sig] * (b - n)
        xs, sign, valid = h2c.sigs_to_x(sigs)
        return (_g1_aff(pubkey), u, xs, sign, valid, n, b)

    def dispatch_wire_packed(self, packed):
        """Async-dispatch one packed wire bucket; returns (device_out,
        valid, count) without synchronizing (see _launch_bucket)."""
        pub_aff, u, xs, sign, valid, n, b = packed
        if _pallas_ok(b):
            from . import pallas_wire

            ok = pallas_wire.verify_wire_pl(pub_aff, u, xs, sign,
                                            sync=False)
        else:
            pubs = np.broadcast_to(pub_aff, (b, 2, limb.NLIMBS))
            ok = self._verify_wire(
                jnp.asarray(pubs), jnp.asarray(xs), jnp.asarray(sign),
                jnp.asarray(u))
        return ok, valid, n

    def _launch_wire_bucket(self, pubkey: PointG1, checks, b: int,
                            dst: bytes = DEFAULT_DST_G2):
        """Dispatch one padded wire bucket; no sync (see _launch_bucket)."""
        return self.dispatch_wire_packed(
            self.pack_wire_bucket(pubkey, checks, b, dst))

    def _run_wire_bucket(self, pubkey: PointG1, checks, b: int,
                         dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
        dev, valid, n = self._launch_wire_bucket(pubkey, checks, b, dst)
        return (np.asarray(dev) & valid)[:n]

    # ------------------------------------------------- wire-RLC tier
    # The RLC combination folded INTO the wire pipeline: device
    # hash-to-curve + decompression feed an in-graph lane-MSM, so a
    # catch-up span costs 2 Miller loops end-to-end with no host hashing
    # either (the host does only SHA-256 expansion, byte unpacking and
    # scalar sampling). Same discipline as every other graph family:
    # per-bucket KAT gate against the host MSM, and a wrong verdict can
    # only be a false REJECT (the caller falls back to the per-item wire
    # graph for exact verdicts).

    def wire_rlc_active(self, n_checks: int) -> bool:
        """True iff a span of ``n_checks`` wire checks takes the device
        wire-RLC tier (env gate, engine floor, wire-prep mode) — the
        dispatch/bench-facing twin of agg_rlc_active; the per-bucket KAT
        gate still applies at dispatch time."""
        use_wire = (self.wire_prep if self.wire_prep is not None
                    else n_checks >= PALLAS_MIN_BUCKET)
        return bool(use_wire) and self._rlc_wanted(n_checks)

    def wire_rlc_sharded_active(self, n_checks: int) -> bool:
        """True iff a span of ``n_checks`` wire checks would run the
        MESH-sharded wire-RLC combine (the crypto/batch.py dispatcher
        labels such spans path="wire_rlc_sharded"). Predicted from the
        bucket geometry alone — reading this never triggers a KAT
        probe; the per-shard-shape gate still applies at dispatch."""
        if not self.wire_rlc_active(n_checks):
            return False
        buckets = self._wire_rlc_buckets()
        if not buckets:
            return False
        b = next((bb for bb in buckets if bb >= n_checks), buckets[-1])
        return self._wire_rlc_shardable(b)

    def _wire_rlc_buckets(self):
        # the lane-MSM's cross-lane fold needs power-of-two lanes
        return tuple(b for b in self._wire_buckets() if not (b & (b - 1)))

    def _combine_wire_chunk(self, checks, cs, b: int, dst: bytes,
                            sharded: bool | None = None):
        """One combine dispatch of <= b wire checks: (decode-ok mask,
        Σc·sig, Σc·H(m)) with host points, (mask, None, None) when no
        lane survives decode, or None when a live combination
        degenerates to infinity (fall back; ~2^-128 honest).
        ``sharded``: force the mesh-sharded / unsharded combine (the
        KAT probes pin the path they gate); None consults the sharded
        KAT cache — never probes — so dispatch follows whatever verdict
        bucket selection already established."""
        from . import h2c

        n = len(checks)
        pad_msg = b"drand-tpu-pad"
        msgs = [m for m, _ in checks] + [pad_msg] * (b - n)
        u = h2c.msgs_to_u(msgs, dst)
        pad_sig = _PAD_SIG()
        sigs = [s for _, s in checks] + [pad_sig] * (b - n)
        xs, sign, valid = h2c.sigs_to_x(sigs)
        live = valid.copy()
        live[n:] = False
        bits = np.zeros((b, RLC_NBITS), np.int32)
        for i, c in enumerate(cs):
            bits[i] = curve.scalar_to_bits(c, RLC_NBITS)
        if sharded is None:
            sharded = bool(self._wire_rlc_shardable(b)
                           and self._wire_rlc_sharded_ok.get(b))
        if _pallas_ok(b):
            from . import pallas_wire

            out = pallas_wire.wire_rlc_pl(u, xs, sign, live, bits)
        elif sharded:
            out = self._wire_rlc_sharded_jit(
                jnp.asarray(xs), jnp.asarray(sign), jnp.asarray(u),
                jnp.asarray(live), jnp.asarray(bits))
        else:
            out = self._wire_rlc_jit(
                jnp.asarray(xs), jnp.asarray(sign), jnp.asarray(u),
                jnp.asarray(live), jnp.asarray(bits))
        ok, sx, sy, sinf, mx, my, minf = (np.asarray(o) for o in out)
        ok = ok.astype(bool)[:n]
        if not ok.any():
            return ok, None, None
        if bool(sinf) or bool(minf):
            return None
        return ok, _g2_from_affine_dev(sx, sy), _g2_from_affine_dev(mx, my)

    def _wire_rlc_kat_probe(self, b: int, sharded: bool) -> bool:
        """One wire-RLC combine KAT against the host MSM on fixed
        signatures and scalars, including a malformed lane that must be
        excluded from the combination. Gates usefulness, not soundness
        (the pairing row is the separately-KAT-gated verify_bls bucket,
        and a wrong combined point fails it)."""
        from ..crypto import bls
        from ..crypto.hash_to_curve import hash_to_g2

        sk = 0x5A17
        m1, m2 = b"engine-wire-rlc-a", b"engine-wire-rlc-b"
        s1, s2 = bls.sign(sk, m1), bls.sign(sk, m2)
        checks = [(m1, s1), (m2, s2)]
        cs = [5, 7]
        expect_mask = [True, True]
        if b >= 3:  # malformed lane: rejected per-item, never combined
            checks.append((b"engine-wire-rlc-bad", b"\x00" * 96))
            cs.append(3)
            expect_mask.append(False)
        try:
            got = self._combine_wire_chunk(checks, cs, b, DEFAULT_DST_G2,
                                           sharded=sharded)
            if got is None:
                return False
            mask, s_comb, m_comb = got
            p1 = PointG2.from_bytes(s1, subgroup_check=False)
            p2 = PointG2.from_bytes(s2, subgroup_check=False)
            return (list(mask) == expect_mask
                    and s_comb == p1.mul(5) + p2.mul(7)
                    and m_comb == hash_to_g2(m1).mul(5)
                    + hash_to_g2(m2).mul(7))
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            return False

    def _check_wire_rlc(self, b: int) -> bool:
        ok = self._wire_rlc_ok.get(b)
        if ok is not None:
            return ok
        ok = self._wire_rlc_kat_probe(b, sharded=False)
        self._wire_rlc_ok[b] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "wire_rlc_bucket_disabled", bucket=b)
        return ok

    def _check_wire_rlc_sharded(self, b: int) -> bool:
        """KAT the MESH-sharded combine per shard shape (bucket over
        mesh) — its own cache and verdict, so a sharded miscompile
        disables only the sharded executable."""
        ok = self._wire_rlc_sharded_ok.get(b)
        if ok is not None:
            return ok
        ok = self._wire_rlc_kat_probe(b, sharded=True)
        self._wire_rlc_sharded_ok[b] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "wire_rlc_sharded_bucket_disabled", bucket=b,
                mesh=self._mesh_size if self.mesh is not None else 0)
        return ok

    def _wire_rlc_check(self, b: int) -> bool:
        """Bucket-selection gate: shardable buckets are vouched for by
        the sharded KAT (one compile per shape on a mesh engine); the
        rest by the single-device combine KAT. A failed sharded KAT
        makes the bucket unusable for THIS tier — verify_wire_rlc then
        returns None and the caller decides via the per-item wire graph
        (false-reject-only, like every other combine failure)."""
        if self._wire_rlc_shardable(b):
            return self._check_wire_rlc_sharded(b)
        return self._check_wire_rlc(b)

    def verify_wire_rlc(self, pubkey: PointG1, checks,
                        dst: bytes = DEFAULT_DST_G2) -> np.ndarray | None:
        """The wire-RLC tier core: per-check bool array when the span's
        combined 2-pairing check lands (decode failures are per-item
        False and excluded from the combination), or None to fall back
        to the per-item wire graph — on an untrusted shape, a degenerate
        combination, or a failed combined check (some signature is bad;
        the fallback produces the exact verdicts). Spans above the
        bucket chunk through it with one scalar vector, chunk sums added
        on host, ONE pairing row at the end."""
        n = len(checks)
        if n == 0:
            return np.zeros(0, dtype=bool)
        if pubkey.is_infinity():
            return None
        b = self._good_bucket(n, check=self._wire_rlc_check,
                              buckets=self._wire_rlc_buckets())
        if b is None:
            return None
        cs = batch_verify.rlc_scalars(n)
        ok_mask = np.zeros(n, dtype=bool)
        s_acc = m_acc = None
        for lo in range(0, n, b):
            hi = min(lo + b, n)
            got = self._combine_wire_chunk(checks[lo:hi], cs[lo:hi], b, dst)
            if got is None:
                return None
            ok_chunk, s_chunk, m_chunk = got
            ok_mask[lo:hi] = ok_chunk
            if s_chunk is not None:
                s_acc = s_chunk if s_acc is None else s_acc + s_chunk
                m_acc = m_chunk if m_acc is None else m_acc + m_chunk
        if s_acc is None:
            return ok_mask  # nothing decodable: every check already False
        if s_acc.is_infinity() or m_acc.is_infinity():
            return None
        if bool(self.verify_bls([(pubkey, s_acc, m_acc)])[0]):
            return ok_mask
        return None

    def verify_beacons_wire_rlc(self, pubkey: PointG1, beacons,
                                dst: bytes = DEFAULT_DST_G2
                                ) -> np.ndarray | None:
        """A span of beacons through the wire-RLC tier: per-beacon bool
        array, or None to fall back (crypto/batch.py then re-dispatches
        under the plain device tier)."""
        from ..chain import beacon as chain_beacon

        checks, spans = [], []
        for bcn in beacons:
            start = len(checks)
            checks.append((chain_beacon.message(bcn.round, bcn.previous_sig),
                           bcn.signature))
            if bcn.is_v2():
                checks.append((chain_beacon.message_v2(bcn.round),
                               bcn.signature_v2))
            spans.append((start, len(checks) - start))
        flat = self.verify_wire_rlc(pubkey, checks, dst)
        if flat is None:
            return None
        return np.array([bool(flat[s:s + c].all()) for s, c in spans])

    # ------------------------------------------------- timelock tier
    # Batched IBE decryption for the timelock vault's round-boundary
    # open (crypto/timelock.py): all K ciphertexts of a round share ONE
    # G2 point — the round's V2 signature — so the device graph runs the
    # Miller-loop line/T computation over `sig` ONCE (no batch axis) and
    # only the K varying U_i in G1 ride the batch axis, exactly like the
    # verify tiers. The graph outputs the canonical GT value per lane
    # (the 3^-1 cube correction is pre-folded into the shared point on
    # host — one G2 scalar mul per round); the Fujisaki-Okamoto
    # re-encryption check stays host-exact per item, so a wrong device
    # GT can only FALSE-REJECT (the host path then decides) — the same
    # soundness posture as every combine tier.

    @staticmethod
    def _tl_graph(xp, yp, qx, qy):
        """Canonical-GT pairings of a batch of G1 points against one
        shared G2 point: xp/yp (b, NLIMBS) affine mont G1 coords,
        qx/qy (2, NLIMBS) affine mont Fp2 coords of the PRE-FOLDED
        signature. Returns (b, 2, 3, 2, NLIMBS) Fp12 lanes."""
        q_aff = jnp.stack([qx, qy], axis=-3)[None, None]
        f = pairing.miller_loop_shared_q((xp[:, None], yp[:, None]), q_aff)
        return pairing.final_exponentiation(f, canonical=False)

    def _launch_tl_bucket(self, us, q_np, b: int):
        """Dispatch one padded GT bucket (pad lanes = generator, sliced
        away); returns (device_out, count) without synchronizing."""
        gen = _g1_aff(PointG1.generator())
        xs = np.broadcast_to(gen[0], (b, limb.NLIMBS)).copy()
        ys = np.broadcast_to(gen[1], (b, limb.NLIMBS)).copy()
        for i, xy in enumerate(PointG1.batch_to_affine(us)):
            aff = _g1_xy(xy)
            xs[i], ys[i] = aff[0], aff[1]
        out = self._tl_jit(jnp.asarray(xs), jnp.asarray(ys),
                           jnp.asarray(q_np[0]), jnp.asarray(q_np[1]))
        return out, len(us)

    def _run_tl_bucket(self, us, q_np, b: int) -> list:
        """One synced bucket as host Fp12 lanes INCLUDING pads (the KAT
        probe checks every lane)."""
        from . import tower

        dev, _ = self._launch_tl_bucket(us, q_np, b)
        host = np.asarray(dev)
        return [tower.fp12_from_device(host[i]) for i in range(b)]

    def _check_tl_bucket(self, b: int) -> bool:
        """KAT the GT graph per bucket against the host shared-signature
        decryptor on fixed points — full-lane (pad rows must reproduce
        the generator pairing; the axon failure mode is lane-dependent
        silent miscompiles). A failure disables the bucket; decryption
        soundness never depended on it (host-exact FO check)."""
        ok = self._tl_ok.get(b)
        if ok is not None:
            return ok
        from ..crypto import timelock as tl

        sig = hash_to_g2(b"engine-timelock-kat").mul(0x5A17)
        rd = tl.RoundDecryptor(sig)
        g1 = PointG1.generator()
        us = [g1.mul(2), g1.mul(3)][:b]
        try:
            got = self._run_tl_bucket(us, _g2_aff(rd.sig_folded), b)
            expect = [rd.gt(u) for u in us]
            pad_expect = rd.gt(g1)
            ok = (all(g == e for g, e in zip(got, expect))
                  and all(g == pad_expect for g in got[len(us):]))
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._tl_ok[b] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "timelock_bucket_disabled", bucket=b)
        return ok

    def timelock_open(self, signature, cts) -> list | None:
        """Open a round's timelock ciphertexts with ONE batched GT
        dispatch: per-item ``(ok, plaintext, error)`` outcomes, or None
        when no bucket passed known-answer validation (the dispatcher
        falls back to the host shared-signature tier). Decode failures
        and infinity U points are per-item host decisions and never
        enter the batch; the FO accept/reject runs host-exact on every
        item (crypto/timelock._finish), with device-rejected items
        re-decided by the host pairing — false-reject-only."""
        from ..crypto import timelock as tl
        from . import tower

        n = len(cts)
        if n == 0:
            return []
        rd = tl.RoundDecryptor(signature)
        us: list[PointG1 | None] = []
        for ct in cts:
            try:
                u = PointG1.from_bytes(ct.u, subgroup_check=False)
                us.append(None if u.is_infinity() else u)
            except ValueError:
                us.append(None)
        live = [u for u in us if u is not None]
        if not live:
            return rd.decrypt_many(cts)
        b = self._good_bucket(len(live), check=self._check_tl_bucket)
        if b is None:
            return None
        _meter_gt_rows(len(live))
        q_np = _g2_aff(rd.sig_folded)
        launches = [self._launch_tl_bucket(live[i:i + b], q_np, b)
                    for i in range(0, len(live), b)]
        # one device-side concat + one host transfer (see _drain)
        if len(launches) == 1:
            host = np.asarray(launches[0][0])
        else:
            host = np.asarray(jnp.concatenate([d for d, _ in launches]))
        flat = []
        for j, (_, cnt) in enumerate(launches):
            rows = host[j * b:j * b + cnt]
            flat.extend(tower.fp12_from_device(rows[i])
                        for i in range(cnt))
        it = iter(flat)
        gts = [None if u is None else next(it) for u in us]
        return rd.decrypt_many(cts, gts=gts)

    def verify_sigs(self, pubkey: PointG1, pairs,
                    dst: bytes = DEFAULT_DST_G2) -> list[bool]:
        """Batch of (msg, sig_bytes) full-signature checks against one
        public key — the aggregator's V1+V2 re-verification
        (chain/beacon/chain.go:141,159)."""
        triples = [(pubkey, _decode_sig(sig), self._hash_msg(msg, dst))
                   for msg, sig in pairs]
        return [bool(v) for v in self.verify_bls(triples)]

    def verify_partials(self, pub_poly: PubPoly, msg: bytes, partials,
                        dst: bytes = DEFAULT_DST_G2) -> list[bool]:
        """All partials of one round against their public key shares.
        The per-index public keys come from ONE batched device Horner
        over the commitment polynomial (the host loop costs ~10 point
        ops per coefficient per index — seconds at 67-of-100 scale)."""
        if self._rlc_wanted(len(partials)):
            got = self._rlc_verify_partials(pub_poly, msg, partials, dst)
            if got is not None:
                return got
        msg_pt = self._hash_msg(msg, dst)
        pubkeys = self._share_pubkeys(pub_poly, partials)
        triples = []
        for p, pk in zip(partials, pubkeys):
            if pk is None:
                triples.append((PointG1.generator(), None, msg_pt))
            else:
                triples.append((pk, _decode_sig(p[tbls.INDEX_BYTES:]),
                                msg_pt))
        return [bool(v) for v in self.verify_bls(triples)]

    def eval_poly_indices(self, pub_poly: PubPoly,
                          indices: list[int]) -> list[PointG1]:
        """ONE polynomial evaluated at MANY indices — the dual of
        eval_commits: commits broadcast across lanes, per-lane index
        bits through the same KAT-gated Horner graph."""
        n = len(indices)
        if n == 0:
            return []
        for i in indices:
            if not 0 <= i + 1 < (1 << _EVAL_IDX_BITS):
                raise ValueError("index out of range")
        if any(c.is_infinity() for c in pub_poly.commits):
            return [pub_poly.eval(i).value for i in indices]
        t = len(pub_poly.commits)
        eb = [b for b in self.buckets if b >= 32] or [128]
        b = self._good_bucket(
            n, check=lambda bb: self._check_poly_eval_bucket(t, bb),
            buckets=eb)
        if b is None:
            raise RuntimeError(
                "device engine: no eval bucket passed validation")
        out = []
        for s in range(0, n, b):
            out.extend(self._run_poly_eval_bucket(
                pub_poly, indices[s:s + b], b))
        return out

    def _check_poly_eval_bucket(self, t: int, b: int) -> bool:
        """KAT for the many-indices mode — a DIFFERENT executable from
        eval_commits' shared-index mode (per-lane bits), gated and cached
        independently so a failure here never disables the other."""
        key = (t, b)
        ok = self._poly_eval_ok.get(key)
        if ok is not None:
            return ok
        g = PointG1.generator()
        poly = PubPoly([g.mul(1 + k) for k in range(t)])
        probe_idx = [0, 3, 7][:min(3, b)]
        try:
            got = self._run_poly_eval_bucket(poly, probe_idx, b)
            ok = got == [poly.eval(i).value for i in probe_idx]
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._poly_eval_ok[key] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "poly_eval_bucket_disabled", t=t, bucket=b)
        return ok

    def _run_poly_eval_bucket(self, pub_poly, indices, b: int):
        t = len(pub_poly.commits)
        xs = np.zeros((t, b, limb.NLIMBS), np.int32)
        ys = np.zeros((t, b, limb.NLIMBS), np.int32)
        flat = PointG1.batch_to_affine(pub_poly.commits)
        for k in range(t):
            aff = _g1_xy(flat[k])
            xs[k, :] = aff[0]
            ys[k, :] = aff[1]
        bits = np.zeros((b, _EVAL_IDX_BITS), np.int32)
        for j, idx in enumerate(indices):
            bits[j] = curve.scalar_to_bits(idx + 1, _EVAL_IDX_BITS)
        # pad lanes evaluate at abscissa 0 — harmless, sliced away
        dev = _eval_commits_graph(
            jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(bits), t=t)
        return self._unpack_eval(dev, len(indices))

    # ------------------------------------------------- commitment evals
    def eval_commits(self, polys, index: int) -> list[PointG1]:
        """Batched ``PubPoly.eval(index)`` across many commitment
        polynomials — the DKG deal-verification hot loop
        (reference kyber vss: one polynomial evaluation per dealer,
        n per node per DKG round; BASELINE config "n=128 deal verify").

        Device graph: vectorized Horner over the dealer axis — t-1 steps
        of ([index]·acc + C_k) with the shared small index as a 16-bit
        double-and-add ladder. Buckets are known-answer-validated per
        (t, bucket) against the host oracle on deterministic commitments
        (full-lane check) before first use."""
        n = len(polys)
        if n == 0:
            return []
        t = len(polys[0].commits)
        if any(len(p.commits) != t for p in polys):
            raise ValueError("mixed commitment lengths")
        if not 0 <= index + 1 < (1 << _EVAL_IDX_BITS):
            raise ValueError("index out of range")
        # polynomials carrying a point-at-infinity commitment (legal wire
        # encoding a malicious dealer can ship) have no affine packing —
        # evaluate those on the host, the rest on device
        bad = {i for i, p in enumerate(polys)
               if any(c.is_infinity() for c in p.commits)}
        if bad:
            good = [p for i, p in enumerate(polys) if i not in bad]
            dev = iter(self.eval_commits(good, index))
            return [polys[i].eval(index).value if i in bad else next(dev)
                    for i in range(n)]
        eb = [b for b in self.buckets if b >= 32] or [128]
        b = self._good_bucket(n, check=lambda bb: self._check_eval_bucket(
            t, bb), buckets=eb)
        if b is None:
            raise RuntimeError(
                "device engine: no eval bucket passed validation")
        # async chunk dispatch; pack every chunk's coords + inf into one
        # device-side int32 block and pull ALL chunks with ONE host
        # transfer (ADVICE r3: per-chunk np.asarray×3 paid 3×chunks
        # ~100 ms tunnel polling floors — same discipline as _drain)
        launches = [self._launch_eval_bucket(polys[i:i + b], index, b)
                    for i in range(0, n, b)]
        packed = jnp.concatenate(
            [jnp.concatenate(
                [*dev[:-1], dev[-1][:, None].astype(jnp.int32)], axis=1)
             for dev, _ in launches], axis=0)
        host = np.asarray(packed)
        out = []
        for chunk, (dev, cnt) in zip(range(0, len(launches) * b, b),
                                     launches):
            rows = host[chunk:chunk + b]
            out.extend(self._unpack_eval_host(rows, len(dev) - 1, cnt))
        return out

    def _eval_use_pallas(self, b: int) -> bool:
        from . import pallas_eval

        return _pallas_ok(b) and b % pallas_eval.LANE_BLOCK == 0

    @staticmethod
    def _unpack_eval_host(rows, ncoords: int, n: int) -> list[PointG1]:
        """Host-side unpack of a packed eval chunk: 2 coords = affine
        (XLA graph), 3 = Jacobian (Pallas kernel — converted here with a
        Montgomery-trick batch inversion: ONE bigint modexp for the whole
        bucket instead of a per-lane 381-step device Fermat ladder)."""
        from ..crypto.fields import Fp

        L = limb.NLIMBS
        inf = rows[:, -1].astype(bool)
        if ncoords == 2:
            return BatchedEngine._unpack_eval_rows(
                rows[:, :L], rows[:, L:2 * L], inf, n)
        xs = [limb.fp_from_device(rows[d, :L]) for d in range(n)]
        ys = [limb.fp_from_device(rows[d, L:2 * L]) for d in range(n)]
        zs = [limb.fp_from_device(rows[d, 2 * L:3 * L]) for d in range(n)]
        zz = [1 if inf[d] else (zs[d] or 1) for d in range(n)]
        pref = [1] * (n + 1)
        for i, z in enumerate(zz):
            pref[i + 1] = pref[i] * z % P
        acc = pow(pref[n], P - 2, P)
        invs = [0] * n
        for i in range(n - 1, -1, -1):
            invs[i] = acc * pref[i] % P
            acc = acc * zz[i] % P
        out = []
        for d in range(n):
            if inf[d] or zs[d] == 0:
                out.append(PointG1.infinity())
                continue
            zi = invs[d]
            zi2 = zi * zi % P
            out.append(PointG1(Fp(xs[d] * zi2 % P),
                               Fp(ys[d] * zi2 % P * zi % P), Fp(1)))
        return out

    def _run_eval_bucket(self, polys, index: int, b: int) -> list[PointG1]:
        dev, n = self._launch_eval_bucket(polys, index, b)
        rows = np.concatenate(
            [np.asarray(c) for c in dev[:-1]]
            + [np.asarray(dev[-1])[:, None].astype(np.int32)], axis=1)
        return self._unpack_eval_host(rows, len(dev) - 1, n)

    def _launch_eval_bucket(self, polys, index: int, b: int):
        t = len(polys[0].commits)
        n = len(polys)
        gen = _g1_aff(PointG1.generator())
        xs = np.zeros((t, b, limb.NLIMBS), np.int32)
        ys = np.zeros((t, b, limb.NLIMBS), np.int32)
        xs[:], ys[:] = gen[0], gen[1]
        flat = PointG1.batch_to_affine(
            [c for poly in polys for c in poly.commits])
        for d, poly in enumerate(polys):
            for k in range(t):
                aff = _g1_xy(flat[d * t + k])
                xs[k, d], ys[k, d] = aff[0], aff[1]
        # evaluation abscissa is index + 1 (kyber share convention —
        # crypto/poly._x_of)
        bits = curve.scalar_to_bits(index + 1, _EVAL_IDX_BITS)
        if self._eval_use_pallas(b):
            from . import pallas_eval

            # fused Mosaic Horner (Jacobian out; host batch-inverts) —
            # the XLA limb graph below measured 0.74x HOST at n=128
            dev = pallas_eval.eval_commits_pl(
                jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(bits), t=t)
        else:
            dev = _eval_commits_graph(
                jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(bits), t=t)
        return dev, n

    @staticmethod
    def _unpack_eval(dev, n: int) -> list[PointG1]:
        ax, ay, inf = (np.asarray(c) for c in dev)
        return BatchedEngine._unpack_eval_rows(ax, ay, inf, n)

    @staticmethod
    def _unpack_eval_rows(ax, ay, inf, n: int) -> list[PointG1]:
        from ..crypto.fields import Fp

        out = []
        for d in range(n):
            if inf[d]:
                out.append(PointG1.infinity())
            else:
                out.append(PointG1(Fp(limb.fp_from_device(ax[d])),
                                   Fp(limb.fp_from_device(ay[d])),
                                   Fp(1)))
        return out

    def _check_eval_bucket(self, t: int, b: int) -> bool:
        key = (t, b)
        ok = self._eval_ok.get(key)
        if ok is not None:
            return ok
        g = PointG1.generator()
        polys = [PubPoly([g.mul(1 + 31 * d + k) for k in range(t)])
                 for d in range(min(3, b))]
        index = 5
        try:
            got = self._run_eval_bucket(polys, index, b)
            expect = [p.eval(index).value for p in polys]
            ok = all(a == e for a, e in zip(got, expect))
            if ok and b > len(polys):
                # full-lane check: pad rows are constant generator
                # polynomials, eval = [sum((index+1)^k)] * g  (the
                # abscissa is index + 1 — crypto/poly._x_of)
                s = sum((index + 1) ** k for k in range(t))
                pad_expect = g.mul(s)
                pads = self._run_eval_bucket(
                    [PubPoly([g] * t)] * b, index, b)
                ok = all(p == pad_expect for p in pads)
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._eval_ok[key] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "eval_bucket_disabled", t=t, bucket=b)
        return ok

    # ------------------------------------------------------------ recover
    @staticmethod
    def _select_shares(partials, t: int, n: int) -> list[PubShare]:
        """First t distinct well-formed indices win — the tbls.recover
        selection semantics, shared by recover and the fused round."""
        shares: list[PubShare] = []
        seen: set[int] = set()
        for p in partials:
            if len(p) != tbls.PARTIAL_SIG_SIZE:
                continue
            idx = tbls.index_of(p)
            if idx in seen or idx >= n:
                continue
            pt = _decode_sig(p[tbls.INDEX_BYTES:])
            if pt is None:
                continue
            seen.add(idx)
            shares.append(PubShare(idx, pt))
            if len(shares) == t:
                break
        return shares

    def _gls4_active(self, t: int) -> bool:
        """GLS ψ² 4-D split for a t-share recovery MSM: always on the
        shape-flexible XLA paths (CPU / small buckets); on TPU only
        while the four digit lanes per share still fit the Mosaic
        kernel's fixed LANES width — beyond that the full-width Pallas
        ladder stays the better program."""
        if not self.gls4:
            return False
        if jax.default_backend() != "tpu":
            return True
        from . import pallas_msm

        return 4 * t <= pallas_msm.LANES

    @staticmethod
    def _pack_msm_gls4(shares, lambdas, b: int):
        """GLS-split MSM packing: each share expands to its four ψ-basis
        lanes (P, -ψP, ψ²P, -ψ³P) with the base-M digits of its Lagrange
        coefficient as scalars (crypto/endo.gls4_*), so the device
        ladder runs GLS4_DIGIT_BITS-step scans instead of 255. The
        basis points come straight off the batch-normalized affine
        coordinates — two Fp2 multiplications per lane, no inversions.
        Returns (pts (b,2,2,L), inf (b,), bits (b, GLS4_DIGIT_BITS))."""
        from ..crypto import endo

        pad = _g2_aff(PointG2.generator())
        pts_np = np.broadcast_to(pad, (b, 2, 2, limb.NLIMBS)).copy()
        inf = np.ones(b, dtype=bool)  # padding rows masked out as infinity
        nbits = endo.GLS4_DIGIT_BITS
        bits = np.zeros((b, nbits), np.int32)
        share_xy = PointG2.batch_to_affine([s.value for s in shares])
        for i, s in enumerate(shares):
            digits = endo.gls4_decompose(lambdas[s.index] % R)
            basis = endo.gls4_points_from_affine(*share_xy[i])
            for k, d in enumerate(digits):
                lane = 4 * i + k
                if not d:
                    continue  # zero digit: lane stays masked infinity
                # basis points carry z == 1: (X, Y) are affine already
                pts_np[lane] = _g2_xy((basis[k].X, basis[k].Y))
                inf[lane] = False
                bits[lane] = curve.scalar_to_bits(d, nbits)
        return pts_np, inf, bits

    def recover(self, pub_poly: PubPoly, msg: bytes, partials, t: int, n: int,
                dst: bytes = DEFAULT_DST_G2, *, shares=None) -> bytes:
        """Lagrange-recover the full signature on device: one G2 MSM with
        the Lagrange coefficients as scalars (Scheme.Recover,
        chain/beacon/chain.go:136). Same selection semantics as the host
        tbls.recover: first t distinct valid indices win. ``shares``:
        pre-selected PubShares (internal callers that already decoded
        the partials skip the duplicate decode+subgroup pass).

        The scalars run GLS-split by default (``self.gls4``): four
        <= 64-bit digit lanes per share instead of one 255-bit ladder —
        a quarter of the sequential scan every threshold-aggregation
        round pays, not just catch-up (ROADMAP #5)."""
        if shares is None:
            shares = self._select_shares(partials, t, n)
        if len(shares) < t:
            raise ValueError(f"not enough valid partials: {len(shares)} < {t}")
        lambdas = lagrange_coefficients([s.index for s in shares])
        from . import pallas_msm

        if self._gls4_active(len(shares)):
            b = max(8, 1 << (4 * len(shares) - 1).bit_length())
            if jax.default_backend() == "tpu":
                b = max(b, pallas_msm.LANES)  # keep the Mosaic MSM engaged
            pts_np, inf, bits = self._pack_msm_gls4(shares, lambdas, b)
            use_lanes = (jax.default_backend() == "tpu"
                         and b > self.PIPPENGER_MIN_T)
        else:
            # buckets bound the PAIRING batch shapes; the MSM must still
            # fit all t shares even when a custom engine's top bucket is
            # smaller
            b = max(_bucket(t, self.buckets), t)
            use_lanes = (jax.default_backend() == "tpu"
                         and b > self.PIPPENGER_MIN_T)
            if use_lanes and b & (b - 1):
                # msm_lanes' log-tree fold needs power-of-two lanes; a
                # custom BatchedEngine(buckets=...) may hand us any size —
                # pad up, the extra rows are masked infinity (ADVICE r3)
                b = 1 << (b - 1).bit_length()
            pad = _g2_aff(PointG2.generator())
            pts_np = np.broadcast_to(pad, (b, 2, 2, limb.NLIMBS)).copy()
            inf = np.ones(b, dtype=bool)  # padding masked out as infinity
            bits = np.zeros((b, 255), np.int32)
            for i, s in enumerate(shares):
                pts_np[i] = _g2_aff(s.value)
                inf[i] = False
                bits[i] = curve.scalar_to_bits(lambdas[s.index] % R, 255)

        if use_lanes and b == pallas_msm.LANES:
            # one fused Mosaic program: per-lane ladders + lane-roll fold
            # + in-kernel to-affine. Output is verified cryptographically
            # by every caller (VerifyRecovered), so correctness cannot
            # silently degrade to an accepted wrong signature.
            x_aff, y_aff, is_inf = pallas_msm.msm_g2_pl(
                pts_np[:, 0], pts_np[:, 1], inf, bits,
                nbits=bits.shape[1])
        else:
            z_one = np.zeros((b, 2, limb.NLIMBS), np.int32)
            z_one[:, 0] = np.asarray(limb.ONE_MONT)
            pts = (jnp.asarray(pts_np[:, 0]), jnp.asarray(pts_np[:, 1]),
                   jnp.asarray(z_one), jnp.asarray(inf))
            if use_lanes:
                # per-lane ladders + log-tree fold (msm_lanes): the
                # unrolled ladder/window graphs take >10 min to COMPILE
                # at b=128 on the XLA limb path, and a fully-sequential
                # scan is latency-fragile through the tunnel
                msm_fn = self._msm_g2_lanes
            else:
                msm_fn = (self._msm_g2_pip if b >= self.PIPPENGER_MIN_T
                          else self._msm_g2)
            x_aff, y_aff, is_inf = msm_fn(pts, jnp.asarray(bits))
        if bool(np.asarray(is_inf)):
            raise ValueError("recovered signature is the point at infinity")
        return _g2_from_affine_dev(np.asarray(x_aff),
                                   np.asarray(y_aff)).to_bytes()

    # ------------------------------------------- fused aggregator round
    @staticmethod
    def _agg_graph(pubs, sigs, msgs, slot_mask, mx, my, mz, minf, mbits):
        """The aggregator's whole per-round crypto as ONE device graph
        (chain/beacon/chain.go:91-166 in a single dispatch): Lagrange MSM
        over the chosen partials, recovered signature spliced into the
        pairing batch at the ``slot_mask`` row, every partial AND the
        recovered signature verified together. Output is one flat int32
        vector so the host pays a single transfer:
        [ok (b,), rec_x (2*NLIMBS), rec_y (2*NLIMBS), rec_inf (1)]."""
        b = pubs.shape[0]
        from . import pallas_msm

        if (jax.default_backend() == "tpu"
                and mx.shape[0] == pallas_msm.LANES):
            # Mosaic MSM: keeps the whole fused graph on the Pallas path
            # (the plain-XLA limb MSM between Mosaic kernels is the known
            # libtpu-flaky regime). nbits follows the packing — 255 for
            # full-width ladders, GLS4_DIGIT_BITS for the ψ² split.
            rx, ry, rinf = pallas_msm.msm_g2_pl(mx, my, minf, mbits,
                                                nbits=mbits.shape[-1])
        else:
            rx, ry, rinf = curve.pt_to_affine(
                curve.F2, curve.msm_lanes(curve.F2, (mx, my, mz, minf),
                                          mbits))
        rec_row = jnp.stack([rx, ry])                      # (2, 2, NLIMBS)
        sig_full = jnp.where(slot_mask[:, None, None, None],
                             rec_row[None], sigs)
        if jax.default_backend() == "tpu" and b >= PALLAS_MIN_BUCKET:
            from . import pallas_pairing as pp

            xp, yp, q = pp.pack_verify_inputs(pubs, sig_full, msgs)
            if b % pp.GRID_BLOCK == 0:
                ok = pp._verify_pl_grid(xp, yp, q, npairs=2, b=b)
            else:
                ok = pp._verify_pl(xp, yp, q, npairs=2, b=b)
        else:
            ok = pairing.verify_prepared(pubs, sig_full, msgs)
        ok = ok & (~slot_mask | ~rinf)
        return jnp.concatenate([
            ok.astype(jnp.int32), rx.reshape(-1), ry.reshape(-1),
            rinf.reshape(1).astype(jnp.int32)])

    def _share_pubkeys(self, pub_poly: PubPoly, partials):
        """Per-partial share public keys via ONE batched device Horner
        (eval_poly_indices), cache-backed; None for malformed partials."""
        idxs = sorted({tbls.index_of(p) for p in partials
                       if len(p) == tbls.PARTIAL_SIG_SIZE})
        need = [i for i in idxs if i not in pub_poly._eval_cache
                and 0 <= i + 1 < (1 << _EVAL_IDX_BITS)]
        if need:
            try:
                evals = self.eval_poly_indices(pub_poly, need)
                from ..crypto.poly import PubShare

                for i, v in zip(need, evals):
                    pub_poly._eval_cache[i] = PubShare(i, v)
            except Exception:  # noqa: BLE001 — host oracle fallback
                pass  # pub_poly.eval below computes host-side
        out = []
        for p in partials:
            if len(p) != tbls.PARTIAL_SIG_SIZE:
                out.append(None)
            else:
                out.append(pub_poly.eval(tbls.index_of(p)).value)
        return out

    def _check_agg_bucket(self, b: int, b_msm: int, nbits: int) -> bool:
        """KAT-gate the fused executable per (bucket, msm-lane, msm-bit)
        shape — same axon-miscompile discipline as every other graph
        family: a toy 2-of-3 group whose recovery and verdicts are known
        on host. The probe packs the SAME scalar decomposition the
        dispatch will (GLS4 digit lanes vs full-width), so the verdict
        vouches for the executable that actually runs."""
        key = (b, b_msm, nbits)
        ok = self._agg_ok.get(key)
        if ok is not None:
            return ok
        from ..crypto.poly import PriPoly

        try:
            poly = PriPoly.random(2, seed=b"engine-agg-kat")
            pub_poly = poly.commit()
            msg = b"engine-agg-bucket-check"
            parts = [tbls.sign_partial(s, msg) for s in poly.shares(3)]
            bad = parts[2][:tbls.INDEX_BYTES] + parts[1][tbls.INDEX_BYTES:]
            expect_sig = tbls.recover(pub_poly, msg, parts[:2], 2, 3)
            oks, rec = self._run_agg(pub_poly, msg, parts[:2] + [bad],
                                     2, 3, DEFAULT_DST_G2, b, b_msm,
                                     gls4=nbits != 255)
            ok = (oks == [True, True, False] and rec == expect_sig)
        except Exception:  # noqa: BLE001 — trace/lowering failures too
            ok = False
        self._agg_ok[key] = ok
        if not ok:
            from ..utils.logging import default_logger

            default_logger("engine").warn(
                "engine", "agg_bucket_disabled", bucket=b, msm_lanes=b_msm,
                msm_bits=nbits)
        return ok

    def aggregate_round(self, pub_poly: PubPoly, msg: bytes, partials,
                        t: int, n: int,
                        dst: bytes = DEFAULT_DST_G2):
        """Verify all partials + Lagrange-recover + verify the recovered
        signature in ONE device dispatch with one result transfer — the
        aggregator's per-round work (chain/beacon/chain.go:91-166) that
        previously took 3+ synced calls, each paying the ~100 ms tunnel
        polling floor.

        Returns ``(oks, sig_bytes)`` with ``oks`` aligned to ``partials``.
        Optimistic: recovery uses the first ``t`` well-formed distinct
        indices (tbls.recover selection); if one of those turns out
        invalid — or the recovered signature fails — falls back to the
        classic verify→filter→recover→verify path. Raises ``ValueError``
        when fewer than ``t`` well-formed partials exist."""
        npart = len(partials)
        shares = self._select_shares(partials, t, n)
        if len(shares) < t:
            raise ValueError(f"not enough valid partials: {len(shares)} < {t}")
        if self._rlc_wanted(npart):
            got = self._try_agg_rlc(pub_poly, msg, partials, t, n, dst,
                                    shares)
            if got is not None:
                return got
        b, b_msm, msm_nbits = self.agg_shape(npart, t)
        if npart + 1 > b or not self._check_agg_bucket(b, b_msm, msm_nbits):
            oks = self.verify_partials(pub_poly, msg, partials, dst)
            return oks, self._recover_verified(pub_poly, msg, partials, oks,
                                               t, n, dst)
        _meter_rows(npart + 1)
        oks, rec = self._run_agg(pub_poly, msg, partials, t, n, dst,
                                 b, b_msm, shares=shares,
                                 gls4=msm_nbits != 255)
        chosen = {s.index for s in shares}
        chosen_ok = all(
            ok for p, ok in zip(partials, oks)
            if len(p) == tbls.PARTIAL_SIG_SIZE
            and tbls.index_of(p) in chosen)
        if rec is not None and chosen_ok:
            return oks, rec
        # a chosen partial was invalid (or the recovery failed): recover
        # from the verified survivors instead
        return oks, self._recover_verified(pub_poly, msg, partials, oks,
                                           t, n, dst)

    def agg_shape(self, npart: int, t: int) -> tuple[int, int, int]:
        """(pairing bucket, msm lanes, msm scalar bits) the fused round
        would use — the KAT cache key shape. GLS-split rounds pack four
        digit lanes per share with GLS4_DIGIT_BITS scalars, full-width
        rounds one 255-bit lane per share; the bit width is part of the
        key because the two compile DIFFERENT executables even at equal
        lane counts."""
        if self._gls4_active(t):
            from ..crypto import endo

            b_msm = max(8, 1 << (4 * t - 1).bit_length())
            if jax.default_backend() == "tpu":
                from . import pallas_msm

                b_msm = max(b_msm, pallas_msm.LANES)
            return (_bucket(npart + 1, self.buckets), b_msm,
                    endo.GLS4_DIGIT_BITS)
        return (_bucket(npart + 1, self.buckets),
                max(8, 1 << (t - 1).bit_length()), 255)

    def agg_fused_active(self, npart: int, t: int) -> bool:
        """True iff an (npart, t) aggregate_round runs the single-dispatch
        fused executable (its KAT passed) rather than the fallback —
        callers (bench.py) report this without reaching into the KAT
        cache internals."""
        return bool(self._agg_ok.get(self.agg_shape(npart, t)))

    def agg_rlc_active(self, npart: int) -> bool:
        """True iff an npart-partial aggregate_round takes the RLC
        combine fast path (env gate, floor, and a trusted combine shape
        — spans above the top lane bucket chunk over it, so the first
        chunk's shape decides). The bench-facing twin of
        agg_fused_active."""
        if not self._rlc_wanted(npart):
            return False
        lanes = self._rlc_lanes(min(npart, self.rlc_lane_buckets[-1]))
        return lanes is not None and bool(self._rlc_ok.get(("g1g2", lanes)))

    def _try_agg_rlc(self, pub_poly, msg, partials, t, n, dst,
                     shares=None):
        """RLC-shaped aggregator round: combine dispatch + recovery MSM
        + ONE 2-row pairing dispatch (combined-partials row and
        recovered-signature row) — 4 Miller pairs total instead of the
        classic fused graph's 2(N+1). Returns (oks, sig) when both rows
        land, else None (the classic fused/fallback path takes over,
        including the exact per-partial verdicts on bad rounds).
        ``shares``: aggregate_round's already-selected t shares, reused
        so recover() skips a duplicate decode+select pass."""
        msg_pt = self._hash_msg(msg, dst)
        if msg_pt.is_infinity():
            return None
        got = self._rlc_partials_comb(pub_poly, msg_pt, partials)
        if got is None:
            return None
        mask, k_comb, s_comb = got
        try:
            rec = self.recover(pub_poly, msg, partials, t, n, dst,
                               shares=shares)
        except ValueError:
            return None
        rec_pt = batch_verify.decode_sig(rec)
        if rec_pt is None:
            return None
        flat = self.verify_bls([(k_comb, s_comb, msg_pt),
                                (pub_poly.commit(), rec_pt, msg_pt)])
        if bool(flat[0]) and bool(flat[1]):
            return [bool(v) for v in mask], rec
        return None

    def _recover_verified(self, pub_poly, msg, partials, oks, t, n, dst):
        """Classic tail: recover from the partials that verified, then
        cryptographically check the recovered signature."""
        good = [p for p, ok in zip(partials, oks) if ok]
        if len(good) < t:
            raise ValueError(
                f"not enough valid partials: {len(good)} < {t}")
        sig = self.recover(pub_poly, msg, good, t, n, dst)
        if self.verify_sigs(pub_poly.commit(), [(msg, sig)], dst) != [True]:
            raise tbls.RecoveredSignatureInvalid(
                "recovered signature failed verification")
        return sig

    def _run_agg(self, pub_poly, msg, partials, t, n, dst, b, b_msm,
                 shares=None, gls4=None):
        """Pack, dispatch and unpack one fused round; returns (oks, sig
        bytes | None-if-recovered-infinity). ``gls4`` pins the MSM
        packing (aggregate_round passes agg_shape's decision so the KAT
        and the dispatch compile the same executable); None falls back
        to the engine policy."""
        npart = len(partials)
        msg_pt = self._hash_msg(msg, dst)
        pubkeys = self._share_pubkeys(pub_poly, partials)
        if shares is None:
            shares = self._select_shares(partials, t, n)
        lambdas = lagrange_coefficients([s.index for s in shares])

        # pairing batch: rows 0..npart-1 = partials, row npart = recovered
        pubs = np.zeros((b, 2, limb.NLIMBS), np.int32)
        sigs = np.zeros((b, 2, 2, limb.NLIMBS), np.int32)
        msgs = np.zeros((b, 2, 2, limb.NLIMBS), np.int32)
        valid = np.zeros(b, dtype=bool)
        pad_pub, pad_g2 = (_g1_aff(PointG1.generator()),
                           _g2_aff(PointG2.generator()))
        pubs[:], sigs[:], msgs[:] = pad_pub, pad_g2, pad_g2
        rows, g1s, g2s = [], [], []
        for i, (p, pk) in enumerate(zip(partials, pubkeys)):
            if pk is None or pk.is_infinity():
                continue
            pt = _decode_sig(p[tbls.INDEX_BYTES:])
            if pt is None or pt.is_infinity():
                continue
            rows.append(i)
            g1s.append(pk)
            g2s.append(pt)
        slot = npart
        group_key = pub_poly.commit()
        g1s.append(group_key)
        g2s.append(msg_pt)  # recovered row checks against H(msg) too
        g1_xy = PointG1.batch_to_affine(g1s)
        g2_xy = PointG2.batch_to_affine(g2s)
        msg_aff = _g2_xy(g2_xy[-1])
        for j, i in enumerate(rows):
            pubs[i] = _g1_xy(g1_xy[j])
            sigs[i] = _g2_xy(g2_xy[j])
            msgs[i] = msg_aff
            valid[i] = True
        pubs[slot] = _g1_xy(g1_xy[-1])
        msgs[slot] = msg_aff
        slot_mask = np.zeros(b, dtype=bool)
        slot_mask[slot] = True

        # MSM lanes (same packing as recover(), b_msm power-of-two):
        # GLS-split digit lanes when active, full 255-bit ladders else
        if gls4 is None:
            gls4 = self._gls4_active(len(shares))
        if gls4:
            pts_np, inf, bits = self._pack_msm_gls4(shares, lambdas, b_msm)
        else:
            pad = _g2_aff(PointG2.generator())
            pts_np = np.broadcast_to(pad, (b_msm, 2, 2, limb.NLIMBS)).copy()
            inf = np.ones(b_msm, dtype=bool)
            bits = np.zeros((b_msm, 255), np.int32)
            share_xy = PointG2.batch_to_affine([s.value for s in shares])
            for i, s in enumerate(shares):
                pts_np[i] = _g2_xy(share_xy[i])
                inf[i] = False
                bits[i] = curve.scalar_to_bits(lambdas[s.index] % R, 255)
        z_one = np.zeros((b_msm, 2, limb.NLIMBS), np.int32)
        z_one[:, 0] = np.asarray(limb.ONE_MONT)

        flat = np.asarray(self._agg_graph_jit(
            jnp.asarray(pubs), jnp.asarray(sigs), jnp.asarray(msgs),
            jnp.asarray(slot_mask), jnp.asarray(pts_np[:, 0]),
            jnp.asarray(pts_np[:, 1]), jnp.asarray(z_one),
            jnp.asarray(inf), jnp.asarray(bits)))
        ok = flat[:b].astype(bool) & valid
        L = limb.NLIMBS
        rx = flat[b:b + 2 * L].reshape(2, L)
        ry = flat[b + 2 * L:b + 4 * L].reshape(2, L)
        rinf = bool(flat[-1])
        oks = [bool(v) for v in ok[:npart]]
        if rinf or not flat[slot]:
            return oks, None
        return oks, _g2_from_affine_dev(rx, ry).to_bytes()


# index width for the eval_commits ladder (node indices are tiny; 11 bits
# covers groups up to n=2046 with one jit shape — the large-group ceremony
# target is n=1024, whose top abscissa x = 1024 overflowed the old 10-bit
# width)
_EVAL_IDX_BITS = 11

import functools as _functools


@_functools.partial(jax.jit, static_argnames=("t",))
def _eval_commits_graph(xs, ys, bits, t: int):
    """Vectorized Horner: eval_d = C[d,t-1]; repeat (·index, +C[d,k]).
    xs/ys: (t, b, NLIMBS) affine mont limbs (generator in pad lanes);
    bits: (_EVAL_IDX_BITS,) MSB-first shared index bits. The Horner steps
    run under lax.scan (one compiled body) — an unrolled loop's HLO count
    scales with t and stalls XLA compilation."""
    F = curve.F1
    b = xs.shape[1]
    z_one = jnp.broadcast_to(jnp.asarray(limb.ONE_MONT), (b, limb.NLIMBS))
    no_inf = jnp.zeros((b,), bool)

    def body(acc, c):
        cx, cy = c
        acc = curve.pt_mul_bits(F, acc, bits)
        acc = curve.pt_add(F, acc, (cx, cy, z_one, no_inf))
        return acc, None

    acc0 = (xs[t - 1], ys[t - 1], z_one, no_inf)
    acc, _ = jax.lax.scan(
        body, acc0, (jnp.flip(xs[:t - 1], axis=0),
                     jnp.flip(ys[:t - 1], axis=0)))
    return curve.pt_to_affine(F, acc)


_PAD_SIG_BYTES: bytes | None = None


def _PAD_SIG() -> bytes:
    """A well-formed compressed G2 point for padding rows (sliced away)."""
    global _PAD_SIG_BYTES
    if _PAD_SIG_BYTES is None:
        _PAD_SIG_BYTES = PointG2.generator().to_bytes()
    return _PAD_SIG_BYTES


def _g2_from_affine_dev(x_aff: np.ndarray, y_aff: np.ndarray) -> PointG2:
    """Mont-limb affine device output (2, NLIMBS) pairs -> host point."""
    from ..crypto.fields import Fp2

    return PointG2(
        Fp2(limb.fp_from_device(x_aff[0]), limb.fp_from_device(x_aff[1])),
        Fp2(limb.fp_from_device(y_aff[0]), limb.fp_from_device(y_aff[1])),
        Fp2.one())


def _g1_from_affine_dev(x_aff: np.ndarray, y_aff: np.ndarray) -> PointG1:
    from ..crypto.fields import Fp

    return PointG1(Fp(limb.fp_from_device(x_aff)),
                   Fp(limb.fp_from_device(y_aff)), Fp(1))


def _decode_sig(sig_bytes: bytes) -> PointG2 | None:
    """Wire signature -> subgroup-checked point; None if malformed.
    Delegates to the shared prefilter (ψ-endomorphism subgroup check,
    same accept set as the generic order-r multiplication, ~3x cheaper
    per decode)."""
    return batch_verify.decode_sig(sig_bytes)
