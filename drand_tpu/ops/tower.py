"""Batched BLS12-381 extension towers on device.

Layout (trailing dims; any leading dims are batch):
    Fp   (..., 32)           — limb.py
    Fp2  (..., 2, 32)        — c0 + c1*u,  u^2 = -1
    Fp6  (..., 3, 2, 32)     — c0 + c1*v + c2*v^2,  v^3 = xi = 1+u
    Fp12 (..., 2, 3, 2, 32)  — c0 + c1*w,  w^2 = v

Formulas mirror the host reference drand_tpu.crypto.fields (golden-tested
against it); everything is Montgomery-domain and batch-broadcasting.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto import fields as hf
from . import limb
from .limb import NLIMBS

# ---------------------------------------------------------------------------
# Host<->device conversion
# ---------------------------------------------------------------------------

def fp2_to_device(x: hf.Fp2) -> jnp.ndarray:
    return jnp.stack([limb.fp_to_device(x.c0), limb.fp_to_device(x.c1)], axis=-2)


def fp2_from_device(a) -> hf.Fp2:
    return hf.Fp2(limb.fp_from_device(a[..., 0, :]), limb.fp_from_device(a[..., 1, :]))


def fp12_to_device(x: hf.Fp12) -> jnp.ndarray:
    c = [
        jnp.stack([fp2_to_device(f6.c0), fp2_to_device(f6.c1), fp2_to_device(f6.c2)],
                  axis=-3)
        for f6 in (x.c0, x.c1)
    ]
    return jnp.stack(c, axis=-4)


def fp12_from_device(a) -> hf.Fp12:
    def f6(b):
        return hf.Fp6(fp2_from_device(b[0]), fp2_from_device(b[1]), fp2_from_device(b[2]))

    return hf.Fp12(f6(np.asarray(a)[0]), f6(np.asarray(a)[1]))


# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------

def f2(c0: jnp.ndarray, c1: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([c0, c1], axis=-2)


def f2_zero(batch_shape=()) -> jnp.ndarray:
    return jnp.zeros(batch_shape + (2, NLIMBS), limb.DTYPE)


def f2_one(batch_shape=()) -> jnp.ndarray:
    one = jnp.asarray(limb.ONE_MONT)
    return f2(jnp.broadcast_to(one, batch_shape + (NLIMBS,)),
              jnp.zeros(batch_shape + (NLIMBS,), limb.DTYPE))


def f2_add(a, b):
    return limb.reduce_light(a + b)


def f2_sub(a, b):
    return limb.sub(a, b)


def f2_neg(a):
    return limb.neg(a)


def f2_mul(a, b):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    # Karatsuba: stack the three products into one mont_mul
    pa = jnp.stack([a0, a1, limb.add(a0, a1)], axis=-2)
    pb = jnp.stack([b0, b1, limb.add(b0, b1)], axis=-2)
    v = limb.mont_mul(pa, pb)
    v0, v1, v2 = v[..., 0, :], v[..., 1, :], v[..., 2, :]
    return f2(limb.sub(v0, v1), limb.sub(v2, limb.add(v0, v1)))


def f2_sqr(a):
    # (a+bu)^2 = (a+b)(a-b) + 2ab u
    a0, a1 = a[..., 0, :], a[..., 1, :]
    pa = jnp.stack([limb.add(a0, a1), a0], axis=-2)
    pb = jnp.stack([limb.sub(a0, a1), a1], axis=-2)
    v = limb.mont_mul(pa, pb)
    return f2(v[..., 0, :], limb.double(v[..., 1, :]))


def f2_mul_fp(a, s):
    """Fp2 * Fp (s has shape (..., 32))."""
    return limb.mont_mul(a, s[..., None, :])


def f2_mul_small(a, k: int):
    return limb.mul_small(a, k)


def f2_conj(a):
    return f2(a[..., 0, :], limb.neg(a[..., 1, :]))


def f2_mul_by_xi(a):
    """Multiply by xi = 1+u: (c0 - c1) + (c0 + c1) u."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return f2(limb.sub(a0, a1), limb.add(a0, a1))


def f2_inv(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    sq = limb.mont_mul(jnp.stack([a0, a1], axis=-2), jnp.stack([a0, a1], axis=-2))
    norm = limb.add(sq[..., 0, :], sq[..., 1, :])
    t = limb.inv(norm)
    return f2(limb.mont_mul(a0, t), limb.neg(limb.mont_mul(a1, t)))


def f2_select(cond, a, b):
    return jnp.where(cond[..., None, None], a, b)


def f2_is_zero(a):
    return limb.is_zero_mod_p(a[..., 0, :]) & limb.is_zero_mod_p(a[..., 1, :])


def f2_eq(a, b):
    return f2_is_zero(f2_sub(a, b))


def f2_pow_const(a, e: int):
    """a^e for fixed e, LSB-first scan."""
    if e == 0:
        return jnp.broadcast_to(f2_one(), a.shape)
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())], dtype=np.int32)

    def step(state, bit):
        result, base = state
        result = f2_select(jnp.broadcast_to(bit.astype(bool), result.shape[:-2]),
                           f2_mul(result, base), result)
        return (result, f2_sqr(base)), None

    # `one + a*0` keeps the carry's varying-manual-axes type aligned with
    # `a` under shard_map (a broadcast constant fails the carry typecheck)
    (result, _), _ = jax.lax.scan(step, (f2_one() + a * 0, a),
                                  jnp.asarray(bits))
    return result


# ---------------------------------------------------------------------------
# Fp6 (over Fp2, v^3 = xi)
# ---------------------------------------------------------------------------

def f6(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-3)


def f6_add(a, b):
    return limb.reduce_light(a + b)


def f6_sub(a, b):
    return limb.sub(a, b)


def f6_neg(a):
    return limb.neg(a)


def f6_mul(a, b):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    b0, b1, b2 = b[..., 0, :, :], b[..., 1, :, :], b[..., 2, :, :]
    # Toom-style: 6 Fp2 mults, stacked into two mont batches via f2_mul's
    # internal stacking (call f2_mul on stacked operands).
    pa = jnp.stack([a0, a1, a2,
                    f2_add(a1, a2), f2_add(a0, a1), f2_add(a0, a2)], axis=-3)
    pb = jnp.stack([b0, b1, b2,
                    f2_add(b1, b2), f2_add(b0, b1), f2_add(b0, b2)], axis=-3)
    v = f2_mul(pa, pb)
    v0, v1, v2 = v[..., 0, :, :], v[..., 1, :, :], v[..., 2, :, :]
    m12, m01, m02 = v[..., 3, :, :], v[..., 4, :, :], v[..., 5, :, :]
    c0 = f2_add(v0, f2_mul_by_xi(f2_sub(m12, f2_add(v1, v2))))
    c1 = f2_add(f2_sub(m01, f2_add(v0, v1)), f2_mul_by_xi(v2))
    c2 = f2_add(f2_sub(m02, f2_add(v0, v2)), v1)
    return f6(c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_fp2(a, k):
    """Fp6 * Fp2 scalar (k shape (..., 2, 32))."""
    return f2_mul(a, k[..., None, :, :])


def f6_mul_by_v(a):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    return f6(f2_mul_by_xi(a2), a0, a1)


def f6_inv(a):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    t0 = f2_sub(f2_sqr(a0), f2_mul_by_xi(f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul_by_xi(f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    denom = f2_add(f2_mul(a0, t0),
                   f2_add(f2_mul_by_xi(f2_mul(a2, t1)),
                          f2_mul_by_xi(f2_mul(a1, t2))))
    dinv = f2_inv(denom)
    return f6(f2_mul(t0, dinv), f2_mul(t1, dinv), f2_mul(t2, dinv))


# ---------------------------------------------------------------------------
# Fp12 (over Fp6, w^2 = v)
# ---------------------------------------------------------------------------

def f12(c0, c1):
    return jnp.stack([c0, c1], axis=-4)


def f12_one(batch_shape=()) -> jnp.ndarray:
    out = jnp.zeros(batch_shape + (2, 3, 2, NLIMBS), limb.DTYPE)
    return out.at[..., 0, 0, 0, :].set(jnp.asarray(limb.ONE_MONT))


def f12_mul(a, b):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    b0, b1 = b[..., 0, :, :, :], b[..., 1, :, :, :]
    pa = jnp.stack([a0, a1, f6_add(a0, a1)], axis=-4)
    pb = jnp.stack([b0, b1, f6_add(b0, b1)], axis=-4)
    v = f6_mul(pa, pb)
    v0, v1, v2 = v[..., 0, :, :, :], v[..., 1, :, :, :], v[..., 2, :, :, :]
    return f12(f6_add(v0, f6_mul_by_v(v1)), f6_sub(v2, f6_add(v0, v1)))


def f12_sqr(a):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    v0 = f6_mul(a0, a1)
    c0 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_by_v(a1))),
                f6_add(v0, f6_mul_by_v(v0)))
    return f12(c0, f6_add(v0, v0))


def f12_conj(a):
    return f12(a[..., 0, :, :, :], f6_neg(a[..., 1, :, :, :]))


def f12_inv(a):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    denom = f6_sub(f6_sqr(a0), f6_mul_by_v(f6_sqr(a1)))
    dinv = f6_inv(denom)
    return f12(f6_mul(a0, dinv), f6_neg(f6_mul(a1, dinv)))


def f12_select(cond, a, b):
    return jnp.where(cond[..., None, None, None, None], a, b)


def f12_is_one(a):
    d = limb.sub(a, f12_one())
    z = limb.is_zero_mod_p(d)  # (..., 2, 3, 2)
    return jnp.all(z, axis=(-3, -2, -1))


# -- w-basis (coefficients of w^0..w^5 over Fp2) ----------------------------

def f12_to_w(a) -> jnp.ndarray:
    """(..., 2, 3, 2, 32) -> (..., 6, 2, 32) in w-power order."""
    c0, c1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    return jnp.stack([
        c0[..., 0, :, :], c1[..., 0, :, :], c0[..., 1, :, :],
        c1[..., 1, :, :], c0[..., 2, :, :], c1[..., 2, :, :],
    ], axis=-3)


def f12_from_w(w) -> jnp.ndarray:
    c0 = jnp.stack([w[..., 0, :, :], w[..., 2, :, :], w[..., 4, :, :]], axis=-3)
    c1 = jnp.stack([w[..., 1, :, :], w[..., 3, :, :], w[..., 5, :, :]], axis=-3)
    return f12(c0, c1)


# -- Frobenius --------------------------------------------------------------

_GAMMA_DEV = {
    k: np.stack([
        np.stack([limb.int_to_limbs(g.c0 * limb.R_MONT % hf.P),
                  limb.int_to_limbs(g.c1 * limb.R_MONT % hf.P)])
        for g in hf._FROBENIUS_GAMMA[k]
    ])
    for k in (1, 2, 3)
}


def f12_frobenius(a, power: int = 1):
    """x -> x^(p^power), power in {1, 2, 3}."""
    w = f12_to_w(a)
    if power % 2 == 1:
        w = f2_conj(w)
    gam = jnp.asarray(_GAMMA_DEV[power])  # (6, 2, 32)
    return f12_from_w(f2_mul(w, gam))


# -- cyclotomic subgroup ops ------------------------------------------------

def f12_cyclotomic_sqr(a):
    """Granger-Scott squaring (mirrors fields.Fp12.cyclotomic_square)."""
    w = f12_to_w(a)
    g = [w[..., i, :, :] for i in range(6)]

    def sq2(x, y):
        t0 = f2_sqr(x)
        t1 = f2_sqr(y)
        return f2_add(t0, f2_mul_by_xi(t1)), f2_sub(f2_sqr(f2_add(x, y)),
                                                    f2_add(t0, t1))

    a0, a1 = sq2(g[0], g[3])
    b0, b1 = sq2(g[1], g[4])
    c0, c1 = sq2(g[2], g[5])

    def fmi(goal, t):  # 3t - 2*goal
        return f2_add(f2_mul_small(f2_sub(t, goal), 2), t)

    def gpl(goal, t):  # 3t + 2*goal
        return f2_add(f2_mul_small(f2_add(t, goal), 2), t)

    h = [fmi(g[0], a0), gpl(g[1], f2_mul_by_xi(c1)), fmi(g[2], b0),
         gpl(g[3], a1), fmi(g[4], c0), gpl(g[5], b1)]
    return f12_from_w(jnp.stack(h, axis=-3))


def f12_cyc_pow_const(a, e: int):
    """a^e in the cyclotomic subgroup for fixed e (negative -> conjugate)."""
    if e < 0:
        return f12_cyc_pow_const(f12_conj(a), -e)
    if e == 0:
        return jnp.broadcast_to(f12_one(), a.shape)
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())], dtype=np.int32)

    def step(state, bit):
        result, base = state
        cond = jnp.broadcast_to(bit.astype(bool), result.shape[:-4])
        result = f12_select(cond, f12_mul(result, base), result)
        return (result, f12_cyclotomic_sqr(base)), None

    (result, _), _ = jax.lax.scan(
        step, (f12_one() + a * 0, a), jnp.asarray(bits))
    return result
