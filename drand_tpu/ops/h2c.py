"""Device hash-to-G2 and G2 decompression — the batch-prep pipeline.

Host-side preparation was the bottleneck of batched verification: a pure-
Python hash_to_g2 costs ~45ms per message and a subgroup-checked
decompression ~18ms per signature, capping any catch-up batch at ~15
beacons/s regardless of device speed. This module moves everything after
the SHA-256 message expansion onto the device:

  host:   expand_message_xmd (SHA-256) -> two Fp2 u-values per message;
          signature bytes -> x-coordinate limbs + sign flag
  device: simplified SWU onto E' -> derived 3-isogeny -> E2 -> cofactor
          clearing (one scan);  sqrt-based decompression with the zcash
          lexicographic sign rule;  r-order subgroup checks (one scan)

Mirrors drand_tpu.crypto.hash_to_curve (RFC 9380 pipeline, constants
imported from the host derivation so the two paths cannot diverge) and
crypto.curves.PointG2.from_bytes semantics.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto import hash_to_curve as h2c_host
from ..crypto.fields import P, R
from ..crypto.hash_to_curve import (
    DEFAULT_DST_G2,
    _A_PRIME,
    _B_PRIME,
    _B_OVER_ZA,
    _H_CLEAR,
    _ISO_PARAMS,
    _MINUS_B_OVER_A,
    _Z_SSWU,
    hash_to_field_fp2,
)
from . import curve, limb, tower
from .tower import (
    f2_add,
    f2_eq,
    f2_is_zero,
    f2_inv,
    f2_mul,
    f2_mul_small,
    f2_neg,
    f2_pow_const,
    f2_select,
    f2_sqr,
    f2_sub,
)

# ---------------------------------------------------------------------------
# Device constants (mont limbs) from the host-derived parameters
# ---------------------------------------------------------------------------

def _c_f2(x) -> np.ndarray:
    return np.stack([limb.int_to_mont_limbs(x.c0), limb.int_to_mont_limbs(x.c1)])


_A_P = _c_f2(_A_PRIME)
_B_P = _c_f2(_B_PRIME)
_Z_C = _c_f2(_Z_SSWU)
_MBA = _c_f2(_MINUS_B_OVER_A)
_BZA = _c_f2(_B_OVER_ZA)
_X0, _V_SUM, _U_SUM, _C2, _C3 = (_c_f2(v) for v in _ISO_PARAMS)
_B_G2 = _c_f2(type(_A_PRIME)(4, 4))

# sqrt in Fp2 (q = p^2 ≡ 9 mod 16): candidate a^((q+7)/16) times a 4th root
# of unity (crypto/fields.py Fp2.sqrt)
_SQRT_EXP = (P * P + 7) // 16
from ..crypto.fields import _FP2_ROOTS_OF_UNITY_4  # noqa: E402

_ROOTS4 = np.stack([_c_f2(r) for r in _FP2_ROOTS_OF_UNITY_4])

_H_BITS = curve.scalar_to_bits(_H_CLEAR, _H_CLEAR.bit_length())
_R_BITS = curve.scalar_to_bits(R, 255)

# (p-1)/2 exact limbs, for the G1-style parity checks if ever needed
_HALF_P = np.asarray(limb.int_to_limbs((P - 1) // 2))


# ---------------------------------------------------------------------------
# Field helpers
# ---------------------------------------------------------------------------

def _sqrt_f2(a):
    """(root, is_square) — candidate exponentiation + 4th-root correction.
    a must follow the engine invariant; root is in Montgomery form."""
    cand = f2_pow_const(a, _SQRT_EXP)
    roots = jnp.asarray(_ROOTS4)
    best = None
    found = None
    for i in range(roots.shape[0]):
        r = f2_mul(cand, jnp.broadcast_to(roots[i], cand.shape))
        ok = f2_eq(f2_sqr(r), a)
        if best is None:
            best, found = r, ok
        else:
            best = f2_select(ok, r, best)
            found = found | ok
    return best, found


def _canonical_f2(a):
    """Exact canonical (non-Montgomery) limbs of an Fp2 element: (c0, c1)
    each (..., NLIMBS)."""
    raw_c0 = limb.from_mont(a[..., 0, :])
    raw_c1 = limb.from_mont(a[..., 1, :])
    return limb.canonicalize(raw_c0), limb.canonicalize(raw_c1)


def _sgn0_f2(a):
    """RFC 9380 sgn0 for Fp2 (fields.py Fp2.sgn0) on canonical limbs."""
    c0, c1 = _canonical_f2(a)
    sign0 = c0[..., 0] & 1
    zero0 = jnp.all(c0 == 0, axis=-1)
    sign1 = c1[..., 0] & 1
    return (sign0.astype(bool)) | (zero0 & sign1.astype(bool))


def _lex_largest_f2(y):
    """zcash rule (curves.py PointG2._y_is_lexicographically_largest):
    compare (c1, c0) of y against -y."""
    yc0, yc1 = _canonical_f2(y)
    ny = f2_neg(y)
    nc0, nc1 = _canonical_f2(ny)
    c1_gt = limb._lex_ge(yc1, nc1) & ~jnp.all(yc1 == nc1, axis=-1)
    c1_eq = jnp.all(yc1 == nc1, axis=-1)
    c0_gt = limb._lex_ge(yc0, nc0) & ~jnp.all(yc0 == nc0, axis=-1)
    return c1_gt | (c1_eq & c0_gt)


# ---------------------------------------------------------------------------
# SSWU + isogeny + cofactor clearing
# ---------------------------------------------------------------------------

def map_to_curve_g2(u):
    """u: (..., 2, 32) Fp2 mont limbs -> affine (x, y) on E2 (pre-cofactor).
    Branch-free SSWU (RFC 9380 §6.6.2) then the derived 3-isogeny."""
    a_p = jnp.asarray(_A_P)
    b_p = jnp.asarray(_B_P)
    zu2 = f2_mul(jnp.asarray(_Z_C), f2_sqr(u))
    tv = f2_add(f2_sqr(zu2), zu2)
    tv_zero = f2_is_zero(tv)
    # guard the inversion against tv == 0 (inv(0) = 0 is harmless but the
    # select must pick the exceptional constant)
    x1_main = f2_mul(jnp.asarray(_MBA),
                     f2_add(tower.f2_one() + tv * 0, f2_inv(tv)))
    x1 = f2_select(tv_zero, jnp.broadcast_to(jnp.asarray(_BZA), x1_main.shape),
                   x1_main)

    def g_prime(x):
        return f2_add(f2_add(f2_mul(f2_sqr(x), x), f2_mul(a_p, x)), b_p)

    gx1 = g_prime(x1)
    y1, sq1 = _sqrt_f2(gx1)
    x2 = f2_mul(zu2, x1)
    gx2 = g_prime(x2)
    y2, _ = _sqrt_f2(gx2)
    x = f2_select(sq1, x1, x2)
    y = f2_select(sq1, y1, y2)
    # sign: sgn0(y) must equal sgn0(u)
    flip = _sgn0_f2(u) != _sgn0_f2(y)
    y = f2_select(flip, f2_neg(y), y)
    # 3-isogeny + isomorphism onto E2 (hash_to_curve._iso_apply)
    d = f2_sub(x, jnp.asarray(_X0))
    dinv = f2_inv(d)
    dinv2 = f2_sqr(dinv)
    X = f2_add(x, f2_add(f2_mul(jnp.asarray(_V_SUM), dinv),
                         f2_mul(jnp.asarray(_U_SUM), dinv2)))
    one = tower.f2_one() + x * 0
    Y = f2_mul(y, f2_sub(one, f2_add(
        f2_mul(jnp.asarray(_V_SUM), dinv2),
        f2_mul(f2_mul_small(jnp.asarray(_U_SUM), 2), f2_mul(dinv2, dinv)))))
    return f2_mul(jnp.asarray(_C2), X), f2_mul(jnp.asarray(_C3), Y)


def hash_to_g2_device(u_pairs):
    """u_pairs: (..., 2, 2, 32) — TWO Fp2 u-values per message (RFC
    hash_to_curve is map(u0) + map(u1)). Returns a device G2 point (the
    full point tuple) in the r-order subgroup."""
    x0, y0 = map_to_curve_g2(u_pairs[..., 0, :, :])
    x1, y1 = map_to_curve_g2(u_pairs[..., 1, :, :])
    one_z = tower.f2_one() + x0 * 0
    inf = jnp.zeros(x0.shape[:-2], bool) | (x0[..., 0, 0] * 0).astype(bool)
    p0 = (x0, y0, one_z, inf)
    p1 = (x1, y1, one_z, inf)
    q = curve.pt_add(curve.F2, p0, p1)
    bits = jnp.asarray(_H_BITS)
    return curve.pt_mul_bits(curve.F2, q, bits)


def msgs_to_u(msgs: list[bytes], dst: bytes = DEFAULT_DST_G2) -> np.ndarray:
    """Host: SHA-256 expansion of each message to its two Fp2 u-values,
    packed as (n, 2, 2, 32) mont limbs — the only host step of hashing."""
    out = np.zeros((len(msgs), 2, 2, limb.NLIMBS), np.int32)
    for i, msg in enumerate(msgs):
        u0, u1 = hash_to_field_fp2(msg, dst, 2)
        out[i, 0] = _c_f2(u0)
        out[i, 1] = _c_f2(u1)
    return out


# ---------------------------------------------------------------------------
# Decompression + subgroup check
# ---------------------------------------------------------------------------

def sigs_to_x(sigs: list[bytes]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host: split 96-byte compressed G2 signatures into x limbs (mont),
    the sign flag, and a validity mask (header bits / range checks).
    zcash layout: byte0 top bits = [compressed, infinity, sign]."""
    n = len(sigs)
    xs = np.zeros((n, 2, limb.NLIMBS), np.int32)  # (n, [c0,c1], limbs)
    sign = np.zeros(n, bool)
    valid = np.zeros(n, bool)
    for i, s in enumerate(sigs):
        if len(s) != 96:
            continue
        b0 = s[0]
        if not (b0 & 0x80) or (b0 & 0x40):  # must be compressed, not inf
            continue
        c1 = int.from_bytes(bytes([b0 & 0x1F]) + s[1:48], "big")
        c0 = int.from_bytes(s[48:96], "big")
        if c0 >= P or c1 >= P:
            continue
        xs[i, 0] = limb.int_to_mont_limbs(c0)
        xs[i, 1] = limb.int_to_mont_limbs(c1)
        sign[i] = bool(b0 & 0x20)
        valid[i] = True
    return xs, sign, valid


def decompress_g2_device(x, sign_bit):
    """x: (..., 2, 32) mont limbs; sign_bit: (...,) bool (lexicographically
    largest y). Returns (point, ok): ok=False where x is not on the curve.
    The r-order subgroup check is separate (subgroup_check_g2)."""
    gx = f2_add(f2_mul(f2_sqr(x), x), jnp.asarray(_B_G2))
    y, on_curve = _sqrt_f2(gx)
    is_largest = _lex_largest_f2(y)
    y = f2_select(jnp.not_equal(is_largest, sign_bit), f2_neg(y), y)
    one_z = tower.f2_one() + x * 0
    inf = jnp.zeros(x.shape[:-2], bool) | (x[..., 0, 0] * 0).astype(bool)
    return (x, y, one_z, inf), on_curve


def subgroup_check_g2(pt):
    """[r]Q == O — the r-order check from PointG2.from_bytes."""
    bits = jnp.asarray(_R_BITS)
    out = curve.pt_mul_bits(curve.F2, pt, bits)
    return out[3]  # infinity flag
