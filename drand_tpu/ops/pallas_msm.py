"""Batch-last Pallas kernel for the Lagrange-recovery G2 MSM.

``Scheme.Recover`` (chain/beacon/chain.go:136) is one multi-scalar
multiplication: Σ λ_i·σ_i over the chosen partials. The XLA limb-path
``curve.msm_lanes`` works but is per-op-latency bound (r3: ~1.4 s warm
for 67-of-100) AND, embedded inside the fused aggregator graph, rides
the known-flaky plain-XLA-between-Mosaic-kernels regime. This kernel
runs the whole MSM as ONE Mosaic program in the batch-last layout
(partials on lanes, limbs on sublanes):

- per-lane 255-step double-and-add ladders, vectorized across lanes —
  the scalar bits ride in VMEM ((nbits, B) int32, one row read per step);
- a log2(B)-step cross-lane fold by lane ROTATION: after step w every
  lane i < w holds the sum of lanes {i, i+w}; lane 0 ends with the
  total (7 extra point-adds at B=128 — noise next to the ladder);
- in-kernel to-affine (Fermat inverse via the SMEM p−2 bit table, as
  ops/pallas_wire's kernels do) so no XLA-limb arithmetic touches the
  result before it feeds the pairing rows of the fused graph.

Point formulas are the generic F-parametric ones (ops/curve) over the
batch-last Fp2 namespace (bl_curve.make_f2) — the same code the CPU
golden tests pin. Callers always verify the recovered signature
cryptographically (the fused graph in-batch; engine.recover's callers
via VerifyRecovered), so a miscompile cannot produce an accepted wrong
signature — it surfaces as a failed round, and the fused path's KAT
(engine._check_agg_bucket) additionally gates this kernel's executable
on device.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import bl
from . import bl_curve
from . import curve as xc
from .bl import DTYPE, NLIMBS
from .pallas_pairing import PM2_FLAT, _pallas, smem_bit_getter

LANES = 128  # one VREG of lanes; recovery thresholds pad up to this


def _roll(a, w: int):
    return jnp.roll(a, -w, axis=-1)


def msm_fold_bl(F, p, nlanes: int):
    """Cross-lane log-tree fold: returns the point whose lane 0 is the
    sum over all ``nlanes`` input lanes (other lanes carry garbage)."""
    X, Y, Z, inf = p
    inf32 = jnp.where(inf, 1, 0)[None, :]  # 2-D: Mosaic-safe rolls
    w = nlanes // 2
    while w >= 1:
        q = (_roll(X, w), _roll(Y, w), _roll(Z, w), _roll(inf32, w)[0] != 0)
        X, Y, Z, inf = xc.pt_add(F, (X, Y, Z, inf32[0] != 0), q)
        inf32 = jnp.where(inf, 1, 0)[None, :]
        w //= 2
    return X, Y, Z, inf32[0] != 0


def _msm_kernel(nbits: int, c_ref, pm2_ref, bits_ref, xs_ref, ys_ref,
                inf_ref, ox_ref, oy_ref, oinf_ref):
    from jax.experimental import pallas as pl

    with bl.const_context(c_ref[:]):
        F = bl_curve.make_f2(smem_bit_getter(pm2_ref))
        b = xs_ref.shape[-1]
        one2 = F.one((b,))
        pts = (xs_ref[:], ys_ref[:], one2, inf_ref[:][0] != 0)

        def bit_getter(i):
            # per-lane bit row: (b,) int32 vector select in the ladder
            return bits_ref[pl.ds(i, 1), :][0]

        acc = bl_curve.pt_mul_bits_getter(F, pts, bit_getter, nbits)
        total = msm_fold_bl(F, acc, b)
        ax, ay, ainf = xc.pt_to_affine(F, total)
    ox_ref[:] = ax
    oy_ref[:] = ay
    oinf_ref[:] = jnp.where(ainf, 1, 0)[None, :]


def msm_g2_bl(xs_bl, ys_bl, inf2, bits_bl, nbits: int = 255):
    """Batch-LAST Mosaic MSM entry — traced pieces only, so kernel
    chains (ops/pallas_wire's wire-RLC combine) can feed it directly
    without a host round-trip or an XLA transpose between kernels.

    xs_bl/ys_bl: (2, NLIMBS, b) affine mont limbs; inf2: (1, b) int32
    mask (nonzero = excluded lane); bits_bl: (nbits, b) int32 MSB-first.
    b must be a power of two (the cross-lane fold rolls). Returns affine
    (x (2, NLIMBS), y (2, NLIMBS), inf ()) of Σ bits_i ⋅ P_i."""
    b = xs_bl.shape[-1]
    if b & (b - 1):
        raise ValueError(f"msm_g2_bl needs power-of-two lanes, got {b}")
    cbuf = jnp.asarray(bl.lane_buffer(b))
    pm2 = jnp.asarray(PM2_FLAT)
    shp = jax.ShapeDtypeStruct((2, NLIMBS, b), DTYPE)
    inf_shp = jax.ShapeDtypeStruct((1, b), DTYPE)
    ax, ay, ainf = _pallas(
        functools.partial(_msm_kernel, nbits),
        (shp, shp, inf_shp), "vsvvvv")(
        cbuf, pm2, bits_bl, xs_bl, ys_bl, inf2)
    # lane 0 holds the fold result
    return ax[..., 0], ay[..., 0], ainf[0, 0] != 0


@functools.partial(jax.jit, static_argnames=("nbits",))
def msm_g2_pl(xs, ys, inf, bits, nbits: int = 255):
    """Σ bits_i ⋅ P_i over G2 on the Pallas path.

    xs/ys: (b, 2, NLIMBS) batch-leading affine mont limbs; inf: (b,)
    bool mask (padding rows); bits: (b, nbits) int32 MSB-first scalars.
    b must equal LANES (the engine pads). Returns affine
    (x (2, NLIMBS), y (2, NLIMBS), inf ()) of the sum — device arrays,
    usable directly inside an enclosing jit (the fused aggregator)."""
    b = xs.shape[0]
    if b != LANES:
        raise ValueError(f"msm_g2_pl needs exactly {LANES} lanes, got {b}")
    xs_bl = jnp.moveaxis(jnp.asarray(xs), 0, -1)   # (2, 32, b)
    ys_bl = jnp.moveaxis(jnp.asarray(ys), 0, -1)
    inf2 = jnp.asarray(inf).astype(jnp.int32)[None, :]        # (1, b)
    bits_bl = jnp.asarray(bits).T.astype(jnp.int32)           # (nbits, b)
    return msm_g2_bl(xs_bl, ys_bl, inf2, bits_bl, nbits)
