"""Batch-last G2 point arithmetic + ψ fast paths for Pallas kernels.

Reuses the generic branch-free Jacobian formulas of ops/curve.py (pt_dbl,
pt_add, pt_select, …) through a batch-last Fp2 namespace: a point is
(X, Y, Z, inf) with X/Y/Z shaped (..., 2, 32, B) and inf (..., B).

Adds the two scalar-heavy G2 operations the wire-prep pipeline needs, in
their ψ-endomorphism fast forms (host oracle: crypto/endo.py, which
probes and validates the constants at import):

- ``subgroup_check``: ψ(Q) == [x]Q — one 64-bit double-and-add chain
  (hamming weight 6) instead of a 255-bit [r]Q chain;
- ``clear_cofactor``: Budroni-Pintore
  [x²−x−1]P + ψ([x−1]P) + ψ²([2]P) — two nested [x]-chains instead of
  one 636-bit [h_eff] chain.

Scalar-multiplication bit schedules come from bit getters (SMEM refs in
kernels, traced values in the XLA/CPU test path), like ops/pallas_pairing.
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto import endo
from ..crypto.fields import X_BLS
from . import bl
from . import curve as xc  # the generic (F-parametric) point formulas
from . import limb as _limb
from .bl import DTYPE, NLIMBS


def _f2_rows(x) -> np.ndarray:
    """Host Fp2 -> 2 mont-limb rows for the const buffer."""
    return np.stack([_limb.int_to_mont_limbs(x.c0),
                     _limb.int_to_mont_limbs(x.c1)])


bl.register_consts([
    ("PSI_CX", _f2_rows(endo.PSI_CX)),
    ("PSI_CY", _f2_rows(endo.PSI_CY)),
    ("PSI2_CX", _f2_rows(endo.PSI2_CX)),
    ("PSI2_CY", _f2_rows(endo.PSI2_CY)),
])


def _csec_f2(name: str):
    """Fp2 const: (2, 32, 1) column from a (K, 32) buffer, (2, 32, B)
    from a lane-broadcast (K, 32, B) kernel buffer."""
    sec = bl._csec(name)
    return sec[..., None] if sec.ndim == 2 else sec


# ---------------------------------------------------------------------------
# Batch-last Fp2 namespace for ops/curve's generic formulas
# ---------------------------------------------------------------------------

def _sel(cond, a, b):
    cond = jnp.asarray(cond)
    if cond.ndim == 0:
        return jnp.where(cond, a, b)
    return jnp.where(cond[..., None, None, :], a, b)


def make_f2(inv_bit_getter=None) -> SimpleNamespace:
    """The namespace; ``inv_bit_getter`` feeds the Fermat-inverse exponent
    bits (kernels pass an SMEM getter — the default dynamic-slice getter
    does not lower in Mosaic)."""

    def inv(a):
        return bl.f2_inv(a, inv_bit_getter)

    return SimpleNamespace(
        name="fp2-bl",
        add=bl.f2_add,
        sub=bl.f2_sub,
        neg=bl.f2_neg,
        mul=bl.f2_mul,
        sqr=bl.f2_sqr,
        mul_small=bl.f2_mul_small,
        inv=inv,
        select=_sel,
        is_zero=lambda a: (bl.is_zero_mod_p(a[..., 0, :, :])
                           & bl.is_zero_mod_p(a[..., 1, :, :])),
        zero=lambda bs: jnp.zeros(bs[:-1] + (2, NLIMBS) + bs[-1:], DTYPE),
        one=lambda bs: jnp.broadcast_to(
            jnp.stack([bl._crow("ONE"),
                       jnp.zeros_like(bl._crow("ONE"))], axis=0),
            bs[:-1] + (2, NLIMBS) + bs[-1:]).astype(DTYPE),
        elem_ndim=2,
    )


F2 = make_f2()  # XLA/CPU-path namespace (kernel paths build their own)


def _sel_fp(cond, a, b):
    cond = jnp.asarray(cond)
    if cond.ndim == 0:
        return jnp.where(cond, a, b)
    return jnp.where(cond[..., None, :], a, b)


def make_f1(inv_bit_getter=None) -> SimpleNamespace:
    """Batch-last Fp namespace for the generic point formulas — G1 points
    as (X, Y, Z, inf) with coords (..., 32, B) and inf (..., B). Used by
    the DKG deal-verification Horner kernel (ops/pallas_eval.py)."""

    def inv(a):
        return bl.fp_inv(a, inv_bit_getter)

    return SimpleNamespace(
        name="fp-bl",
        add=bl.add,
        sub=bl.sub,
        neg=bl.neg,
        mul=bl.mont_mul,
        sqr=bl.mont_sqr,
        mul_small=bl.mul_small,
        inv=inv,
        select=_sel_fp,
        is_zero=bl.is_zero_mod_p,
        zero=lambda bs: jnp.zeros(bs[:-1] + (NLIMBS,) + bs[-1:], DTYPE),
        one=lambda bs: jnp.broadcast_to(
            bl._crow("ONE"), bs[:-1] + (NLIMBS,) + bs[-1:]).astype(DTYPE),
        elem_ndim=1,
    )


F1 = make_f1()  # XLA/CPU-path namespace (kernel paths build their own)


# ---------------------------------------------------------------------------
# ψ endomorphism (Jacobian: ψ(X, Y, Z) = (cx·X̄, cy·Ȳ, Z̄) — no inversion)
# ---------------------------------------------------------------------------

def psi(p):
    X, Y, Z, inf = p
    return (bl.f2_mul(bl.f2_conj(X), _csec_f2("PSI_CX")),
            bl.f2_mul(bl.f2_conj(Y), _csec_f2("PSI_CY")),
            bl.f2_conj(Z), inf)


def psi2(p):
    X, Y, Z, inf = p
    return (bl.f2_mul(X, _csec_f2("PSI2_CX")),
            bl.f2_mul(Y, _csec_f2("PSI2_CY")), Z, inf)


# ---------------------------------------------------------------------------
# Scalar multiplication by |x| (bit-getter driven) and the fast paths
# ---------------------------------------------------------------------------

_X_ABS = abs(X_BLS)
X_BITS = np.zeros((1, 64), dtype=np.int32)
X_BITS[0, :_X_ABS.bit_length()] = [int(c) for c in bin(_X_ABS)[2:]]
N_XBITS = _X_ABS.bit_length()


def pt_mul_bits_getter(F, p, bit_getter, nbits: int):
    """MSB-first double-and-add with masked adds (fori_loop body).

    The infinity mask crosses loop iterations as INT32: a 1-D bool carry
    lowers through an i8 Mosaic buffer whose i8->i1 truncation is
    unsupported ("Unsupported target bitwidth for truncation")."""
    batch = p[3].shape

    def body(i, state):
        X, Y, Z, inf32 = state
        acc = xc.pt_dbl(F, (X, Y, Z, inf32 != 0))
        wa = xc.pt_add(F, acc, p)
        # scalar cond (uniform across lanes): broadcasting an i1 scalar to
        # a 1-D lane vector materializes an i8 buffer whose i1 truncation
        # Mosaic cannot lower
        cond = bit_getter(i) != 0
        out = xc.pt_select(F, cond, wa, acc)
        return out[0], out[1], out[2], jnp.where(out[3], 1, 0)

    init = (F.one(batch), F.one(batch), F.zero(batch),
            jnp.ones(batch, DTYPE))  # int mask: no constant-bool splats
    out = jax.lax.fori_loop(0, nbits, body, init)
    return out[0], out[1], out[2], out[3] != 0


def mul_x(F, p, x_bit_getter):
    """[x]P (x = X_BLS < 0): [|x|]P then negate."""
    return xc.pt_neg(F, pt_mul_bits_getter(F, p, x_bit_getter, N_XBITS))


def subgroup_check(F, q, x_bit_getter):
    """ψ(Q) == [x]Q per batch lane (Scott; host oracle
    endo.subgroup_check_fast). Infinity counts as a member."""
    lhs = psi(q)
    rhs = mul_x(F, q, x_bit_getter)
    # Jacobian equality: X1·Z2² == X2·Z1² and Y1·Z2³ == Y2·Z1³
    z1s, z2s = bl.f2_sqr(lhs[2]), bl.f2_sqr(rhs[2])
    ex = F.is_zero(bl.f2_sub(bl.f2_mul(lhs[0], z2s),
                             bl.f2_mul(rhs[0], z1s)))
    z1c, z2c = bl.f2_mul(z1s, lhs[2]), bl.f2_mul(z2s, rhs[2])
    ey = F.is_zero(bl.f2_sub(bl.f2_mul(lhs[1], z2c),
                             bl.f2_mul(rhs[1], z1c)))
    both = ex & ey & ~lhs[3] & ~rhs[3]
    return both | (lhs[3] & rhs[3]) | q[3]


def clear_cofactor(F, p, x_bit_getter):
    """[h_eff]P via Budroni-Pintore (host oracle endo.clear_cofactor_fast):
    [x²−x−1]P + ψ([x−1]P) + ψ²([2]P), with the [x]-chains as bit-getter
    double-and-adds."""
    t1 = mul_x(F, p, x_bit_getter)                       # [x]P
    t2 = mul_x(F, t1, x_bit_getter)                      # [x²]P
    part1 = xc.pt_add(F, xc.pt_add(F, t2, xc.pt_neg(F, t1)),
                      xc.pt_neg(F, p))                   # [x²−x−1]P
    part2 = psi(xc.pt_add(F, t1, xc.pt_neg(F, p)))       # ψ([x−1]P)
    part3 = psi2(xc.pt_dbl(F, p))                        # ψ²([2]P)
    return xc.pt_add(F, xc.pt_add(F, part1, part2), part3)


# ---------------------------------------------------------------------------
# Host <-> batch-last packing (tests, engine prep)
# ---------------------------------------------------------------------------

def pack_g2_points(points) -> tuple:
    """list[PointG2] -> batch-last device point (2, 32, B) coords."""
    import numpy as _np

    n = len(points)
    X = _np.zeros((2, NLIMBS, n), _np.int32)
    Y = _np.zeros((2, NLIMBS, n), _np.int32)
    Z = _np.zeros((2, NLIMBS, n), _np.int32)
    inf = _np.zeros(n, bool)
    for j, p in enumerate(points):
        if p.is_infinity():
            inf[j] = True
            X[0, :, j] = _np.asarray(_limb.ONE_MONT)
            Y[0, :, j] = _np.asarray(_limb.ONE_MONT)
            continue
        x, y = p.to_affine()
        X[0, :, j] = _limb.int_to_mont_limbs(x.c0)
        X[1, :, j] = _limb.int_to_mont_limbs(x.c1)
        Y[0, :, j] = _limb.int_to_mont_limbs(y.c0)
        Y[1, :, j] = _limb.int_to_mont_limbs(y.c1)
        Z[0, :, j] = _np.asarray(_limb.ONE_MONT)
    return (jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z),
            jnp.asarray(inf))


def unpack_g2_points(pt) -> list:
    """Batch-last device point -> list[PointG2]."""
    from ..crypto.curves import PointG2
    from ..crypto.fields import Fp2

    X, Y, Z, inf = (np.asarray(t) for t in pt)
    out = []
    for j in range(inf.shape[-1]):
        if inf[..., j]:
            out.append(PointG2.infinity())
            continue
        out.append(PointG2(
            Fp2(_limb.fp_from_device(X[0, :, j]),
                _limb.fp_from_device(X[1, :, j])),
            Fp2(_limb.fp_from_device(Y[0, :, j]),
                _limb.fp_from_device(Y[1, :, j])),
            Fp2(_limb.fp_from_device(Z[0, :, j]),
                _limb.fp_from_device(Z[1, :, j])),
        ))
    return out
