"""Wire-format BLS verification fully on device: Pallas kernels for
hash-to-G2, decompression, subgroup checks, then the pairing chain.

End-to-end catch-up (client/verify.go:146-163) and aggregator
re-verification take WIRE inputs: message bytes + 96-byte compressed
signatures. The host formerly paid ~45ms (hash-to-curve) + ~18ms
(subgroup-checked decompression) of pure Python per item; here the host
does only SHA-256 expansion + byte splitting (ops/h2c.msgs_to_u /
sigs_to_x) and everything else runs as batch-last Mosaic kernels
(ops/bl_h2c.py, ops/bl_curve.py — ψ fast paths), feeding the pairing
kernels of ops/pallas_pairing.py. Per the axon-stack rule (see
pallas_pairing), NO per-element XLA runs between kernels.

Kernel chain (per batch of B lanes):
    K_map (x2)  u-value -> pre-clearing E2 point        [sswu + isogeny]
    K_ptadd     q0 + q1                                 [Jacobian add]
    K_mulx (x2) [x]P chains of Budroni-Pintore          [64-bit fori]
    K_glue      BP combination + to-affine              [ψ, adds]
    K_sig       decompress + Scott subgroup + to-affine
    ... then miller/easy/pow/is_one from pallas_pairing.

The wire-RLC tier (wire_rlc_pl) swaps the per-lane pairing tail for two
batch-last lane-MSM kernels (pallas_msm.msm_g2_bl, 128-bit RLC scalar
ladders + cross-lane fold) that collapse the bucket to (Σc·sig,
Σc·H(m)) — the combined pair then runs ONE row of the ordinary pairing
bucket, so an all-valid span costs 2 Miller loops end-to-end.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import bl, bl_curve as blc, bl_h2c as blh
from . import curve as xc
from . import pallas_pairing as pp
from .bl import DTYPE, NLIMBS

# bit tables (SMEM inputs)
SQRT_BITS = blh.SQRT_BITS          # (1, 768)
X_BITS = blc.X_BITS                # (1, 64)
PM2_FLAT = pp.PM2_FLAT             # (1, 384)


def _mask_out(ok, shape0=8):
    """(B,) bool -> (8, B) int32 tile-safe output (bool->int via where:
    astype lowers as an invalid i1->i32 vreg bitcast in Mosaic)."""
    return jnp.broadcast_to(jnp.where(ok, 1, 0)[None, :],
                            (shape0, ok.shape[-1])).astype(DTYPE)


def _kernel_f2(pm2_ref):
    """Batch-last F2 namespace whose inversions read the p-2 bits from an
    SMEM ref (kernels cannot dynamic-slice values)."""
    return blc.make_f2(pp.smem_bit_getter(pm2_ref))


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def _map_kernel(c_ref, sqrt_ref, pm2_ref, u_ref, ox_ref, oy_ref):
    """One u-value -> affine pre-clearing E2 point (sswu + isogeny)."""
    with bl.const_context(c_ref[:]):
        x, y = blh.map_to_curve(u_ref[:], pp.smem_bit_getter(sqrt_ref),
                                pp.smem_bit_getter(pm2_ref))
        ox_ref[:] = x
        oy_ref[:] = y


def _ptadd_affine_kernel(c_ref, x0_ref, y0_ref, x1_ref, y1_ref,
                         ox_ref, oy_ref, oz_ref, oinf_ref):
    """Jacobian sum of two affine points (never infinity inputs — map
    outputs); Jacobian out."""
    with bl.const_context(c_ref[:]):
        b = x0_ref.shape[-1]
        F = blc.make_f2()  # no inversion used in pt_add
        one_z = F.one((b,))
        inf0 = jnp.zeros((b,), DTYPE) != 0  # computed, not an i1 splat
        out = xc.pt_add(F, (x0_ref[:], y0_ref[:], one_z, inf0),
                        (x1_ref[:], y1_ref[:], one_z, inf0))
        ox_ref[:], oy_ref[:], oz_ref[:] = out[0], out[1], out[2]
        oinf_ref[:] = _mask_out(out[3])


def _mulx_kernel(c_ref, xbits_ref, x_ref, y_ref, z_ref, inf_ref,
                 ox_ref, oy_ref, oz_ref, oinf_ref):
    """[x]P (x < 0) on a Jacobian point."""
    with bl.const_context(c_ref[:]):
        F = blc.make_f2()
        p = (x_ref[:], y_ref[:], z_ref[:], inf_ref[0] != 0)
        out = blc.mul_x(F, p, pp.smem_bit_getter(xbits_ref))
        ox_ref[:], oy_ref[:], oz_ref[:] = out[0], out[1], out[2]
        oinf_ref[:] = _mask_out(out[3])


def _clear_glue_kernel(c_ref, pm2_ref,
                       px_ref, py_ref, pz_ref, pinf_ref,
                       t1x_ref, t1y_ref, t1z_ref, t1inf_ref,
                       t2x_ref, t2y_ref, t2z_ref, t2inf_ref,
                       ox_ref, oy_ref, oinf_ref):
    """Budroni-Pintore combination [x²−x−1]P + ψ([x−1]P) + ψ²([2]P) from
    precomputed t1 = [x]P, t2 = [x²]P; then to-affine."""
    with bl.const_context(c_ref[:]):
        F = _kernel_f2(pm2_ref)
        p = (px_ref[:], py_ref[:], pz_ref[:], pinf_ref[0] != 0)
        t1 = (t1x_ref[:], t1y_ref[:], t1z_ref[:], t1inf_ref[0] != 0)
        t2 = (t2x_ref[:], t2y_ref[:], t2z_ref[:], t2inf_ref[0] != 0)
        part1 = xc.pt_add(F, xc.pt_add(F, t2, xc.pt_neg(F, t1)),
                          xc.pt_neg(F, p))
        part2 = blc.psi(xc.pt_add(F, t1, xc.pt_neg(F, p)))
        part3 = blc.psi2(xc.pt_dbl(F, p))
        out = xc.pt_add(F, xc.pt_add(F, part1, part2), part3)
        ax, ay, ainf = xc.pt_to_affine(F, out)
        ox_ref[:], oy_ref[:] = ax, ay
        oinf_ref[:] = _mask_out(ainf)


def _sig_kernel(c_ref, sqrt_ref, xbits_ref, pm2_ref, sx_ref, sign_ref,
                ox_ref, oy_ref, ook_ref):
    """Compressed-signature pipeline: decompress (sqrt + zcash sign rule),
    Scott subgroup check, to-affine. ok = on_curve & in_subgroup."""
    with bl.const_context(c_ref[:]):
        F = _kernel_f2(pm2_ref)
        sign_bit = sign_ref[0] != 0
        pt, on_curve = blh.decompress_g2_bl(
            sx_ref[:], sign_bit, F, pp.smem_bit_getter(sqrt_ref))
        in_sub = blc.subgroup_check(F, pt, pp.smem_bit_getter(xbits_ref))
        ox_ref[:] = pt[0]
        oy_ref[:] = pt[1]
        ook_ref[:] = _mask_out(on_curve & in_sub)


# ---------------------------------------------------------------------------
# The jitted chain
# ---------------------------------------------------------------------------

def _f2shape(b):
    return jax.ShapeDtypeStruct((2, NLIMBS, b), DTYPE)


def _mask_shape(b):
    return jax.ShapeDtypeStruct((8, b), DTYPE)


def _pt_shapes(b):
    return (_f2shape(b), _f2shape(b), _f2shape(b), _mask_shape(b))


@functools.partial(jax.jit, static_argnames=("b",))
def _hash_msgs_pl(u_pairs, b: int):
    """u_pairs (2, 2, 32, B) -> affine message point (x, y) on G2."""
    # lane-broadcast const buffer: these kernels multiply constants into
    # the convolution (see bl.mont_mul docstring)
    consts = jnp.asarray(bl.lane_buffer(b))
    sqrt_b = jnp.asarray(SQRT_BITS)
    pm2_b = jnp.asarray(PM2_FLAT)
    xb = jnp.asarray(X_BITS)

    map_call = pp._pallas(_map_kernel, (_f2shape(b), _f2shape(b)), "vssv")
    x0, y0 = map_call(consts, sqrt_b, pm2_b, u_pairs[0])
    x1, y1 = map_call(consts, sqrt_b, pm2_b, u_pairs[1])
    q = pp._pallas(_ptadd_affine_kernel, _pt_shapes(b), "vvvvv")(
        consts, x0, y0, x1, y1)
    mulx = pp._pallas(_mulx_kernel, _pt_shapes(b), "vsvvvv")
    t1 = mulx(consts, xb, *q)
    t2 = mulx(consts, xb, *t1)
    mx, my, minf = pp._pallas(
        _clear_glue_kernel, (_f2shape(b), _f2shape(b), _mask_shape(b)),
        "vs" + "v" * 12)(consts, pm2_b, *q, *t1, *t2)
    return mx, my, minf


@functools.partial(jax.jit, static_argnames=("b",))
def _sig_pl(sig_x, sign_mask, b: int):
    consts = jnp.asarray(bl.lane_buffer(b))
    return pp._pallas(_sig_kernel,
                      (_f2shape(b), _f2shape(b), _mask_shape(b)),
                      "vsssvv")(
        consts, jnp.asarray(SQRT_BITS), jnp.asarray(X_BITS),
        jnp.asarray(PM2_FLAT), sig_x, sign_mask)


@functools.partial(jax.jit, static_argnames=("b",))
def _wire_verify_pl(pub_xp, pub_yp, u_pairs, sig_x, sign_mask, b: int):
    """Full wire check per lane: decompress+subgroup the signature, hash
    the message, then the pairing chain. pub_xp/yp: (32, B) G1 affine
    coords of the (broadcast) public key."""
    sx, sy, sig_ok = _sig_pl(sig_x, sign_mask, b)
    mx, my, minf = _hash_msgs_pl(u_pairs, b)

    neg = np.asarray(pp._neg_g1_np())  # (2, 32)
    ng1x = jnp.broadcast_to(jnp.asarray(neg[0])[:, None], (NLIMBS, b))
    ng1y = jnp.broadcast_to(jnp.asarray(neg[1])[:, None], (NLIMBS, b))
    xp = jnp.stack([ng1x, pub_xp])            # (NP, 32, B)
    yp = jnp.stack([ng1y, pub_yp])
    sig_aff = jnp.stack([sx, sy])             # (2coord, 2, 32, B)
    msg_aff = jnp.stack([mx, my])
    q = jnp.stack([sig_aff, msg_aff])         # (NP, 2, 2, 32, B)
    pair_ok = pp._verify_pl(xp, yp, q, npairs=2, b=b)
    return pair_ok & (sig_ok[0] != 0) & (minf[0] == 0)


@functools.partial(jax.jit, static_argnames=("b",))
def _wire_rlc_pl(u_pairs, sig_x, sign_mask, live_mask, bits, b: int):
    """Wire-RLC combine fully on device: decompress + subgroup-check the
    signatures, hash the messages, then collapse the bucket to
    (Σc·sig, Σc·H(m)) with two batch-last Mosaic lane-MSMs sharing the
    scalar bits (pallas_msm.msm_g2_bl — the recovery MSM kernel with a
    128-bit ladder). Lanes that fail decode, hash to infinity, or are
    padding are masked to infinity in BOTH MSMs so one bad encoding
    cannot poison the combination; the combined pair then feeds the
    ordinary KAT-gated pairing bucket (2 Miller pairs for the span)."""
    from . import pallas_msm

    sx, sy, sig_ok = _sig_pl(sig_x, sign_mask, b)
    mx, my, minf = _hash_msgs_pl(u_pairs, b)
    ok = (sig_ok[0] != 0) & (live_mask[0] != 0) & (minf[0] == 0)
    dead = jnp.where(ok, 0, 1)[None, :]                       # (1, b)
    s_x, s_y, s_inf = pallas_msm.msm_g2_bl(sx, sy, dead, bits, nbits=128)
    m_x, m_y, m_inf = pallas_msm.msm_g2_bl(mx, my, dead, bits, nbits=128)
    return ok, s_x, s_y, s_inf, m_x, m_y, m_inf


def wire_rlc_pl(u_pairs_np, sig_x_np, sign_np, live_np, bits_np):
    """Host entry for the wire-RLC combine: u_pairs_np (B, 2, 2, 32)
    batch-leading (ops/h2c.msgs_to_u layout); sig_x_np (B, 2, 32);
    sign_np/live_np (B,) bool; bits_np (B, 128) MSB-first int32 scalar
    bits. Returns numpy (ok (B,), s_x (2, 32), s_y, s_inf (), m_x, m_y,
    m_inf) — the same shapes as the XLA combine graph so the engine
    consumes either interchangeably."""
    b = u_pairs_np.shape[0]
    u_bl = jnp.asarray(np.moveaxis(u_pairs_np, 0, -1))        # (2, 2, 32, B)
    sig_bl = jnp.asarray(np.moveaxis(sig_x_np, 0, -1))        # (2, 32, B)
    sign_mask = jnp.asarray(
        np.broadcast_to(sign_np.astype(np.int32)[None, :], (8, b)))
    live_mask = jnp.asarray(
        np.broadcast_to(live_np.astype(np.int32)[None, :], (8, b)))
    bits_bl = jnp.asarray(bits_np.T.astype(np.int32))         # (128, B)
    out = _wire_rlc_pl(u_bl, sig_bl, sign_mask, live_mask, bits_bl, b)
    return tuple(np.asarray(o) for o in out)


def verify_wire_pl(pubkey_aff, u_pairs_np, sig_x_np, sign_np,
                   sync: bool = True):
    """Host entry: pubkey_aff (2, 32) mont limbs; u_pairs_np (B, 2, 2, 32)
    batch-leading (ops/h2c.msgs_to_u layout); sig_x_np (B, 2, 32); sign_np
    (B,) bool. Returns (B,) bool — as numpy when ``sync`` (the default),
    else the un-synced device array so callers can pipeline chunks and
    drain once."""
    b = u_pairs_np.shape[0]
    u_bl = jnp.asarray(np.moveaxis(u_pairs_np, 0, -1))  # (2, 2, 32, B)
    sig_bl = jnp.asarray(np.moveaxis(sig_x_np, 0, -1))  # (2, 32, B)
    sign_mask = jnp.asarray(
        np.broadcast_to(sign_np.astype(np.int32)[None, :], (8, b)))
    pub_xp = jnp.asarray(np.broadcast_to(pubkey_aff[0][:, None],
                                         (NLIMBS, b)))
    pub_yp = jnp.asarray(np.broadcast_to(pubkey_aff[1][:, None],
                                         (NLIMBS, b)))
    out = _wire_verify_pl(pub_xp, pub_yp, u_bl, sig_bl, sign_mask, b)
    return np.asarray(out) if sync else out
