"""Multi-limb Fp arithmetic for BLS12-381 on TPU — the base of the batched
crypto engine.

Replaces the reference's native field arithmetic (kyber-bls12381 wrapping
kilic/bls12-381, Go + x86-64 assembly — /root/reference/go.mod:9-10) with a
TPU-native design:

- An Fp element is a vector of ``NLIMBS = 32`` limbs of ``BITS = 12`` bits
  stored little-endian in int32. 12-bit limbs are chosen so a full schoolbook
  product fits int32 without widening: 32 * (2^12)^2 = 2^29, and Montgomery
  accumulation stays under 2^31. No int64 anywhere (TPU-friendly).
- Montgomery representation with R = 2^384. ``mont_mul`` is the single hot
  primitive: schoolbook convolution + 32 unrolled Montgomery steps, all
  element-wise over an arbitrary leading batch shape, so `vmap`/`pjit`
  batching is plain broadcasting.
- Lazy carries: the engine invariant is limbs in [0, ~4100] (a few over
  the 12-bit mask are tolerated — the slack avoids worst-case ripple
  loops; the binding constraint is the int32 convolution bound
  32 * 4100^2 < 2^29.01, far under 2^31). Values live in [0, ~2^384);
  exact canonical form only matters at equality checks, which go through
  ``is_zero_mod_p`` (an exact carry scan + comparison against the
  multiples of p below ~2^384).

Everything here is shape-static and jit-safe; functions take and return
plain ``jnp.ndarray``s of trailing dimension ``NLIMBS``.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P

BITS = 12
NLIMBS = 32
MASK = (1 << BITS) - 1
DTYPE = jnp.int32

assert NLIMBS * BITS == 384
assert NLIMBS * (MASK + 1) ** 2 <= 2**29, "convolution must fit int32"

R_MONT = 1 << (BITS * NLIMBS)  # 2^384
N0INV = pow(-P, -1, 1 << BITS)  # -p^-1 mod 2^BITS (Montgomery constant)


# ---------------------------------------------------------------------------
# Host-side conversions
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    """Little-endian limb decomposition of a non-negative int (host)."""
    if x < 0:
        raise ValueError("negative value")
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    if x:
        raise ValueError(f"value does not fit in {n} limbs")
    return out


def limbs_to_int(a) -> int:
    """Reassemble a limb vector (any per-limb values) into an int (host)."""
    a = np.asarray(a)
    return sum(int(v) << (BITS * i) for i, v in enumerate(a.tolist()))


def int_to_mont_limbs(x: int) -> np.ndarray:
    """Host int -> Montgomery-domain limb vector (numpy; the shared packing
    used by the engine's host-side preparation)."""
    return int_to_limbs(x * R_MONT % P)


def fp_to_device(x: int, mont: bool = True):
    """Host int -> device limbs (Montgomery form by default)."""
    if mont:
        x = (x * R_MONT) % P
    return jnp.asarray(int_to_limbs(x % P))


def fp_from_device(a, mont: bool = True) -> int:
    """Device limbs -> canonical host int."""
    v = limbs_to_int(np.asarray(a)) % P
    if mont:
        v = (v * pow(R_MONT, -1, P)) % P
    return v


# ---------------------------------------------------------------------------
# Device constants
# ---------------------------------------------------------------------------

P_LIMBS = np.asarray(int_to_limbs(P))
# R mod p — the Montgomery form of 1
ONE_MONT = np.asarray(int_to_limbs(R_MONT % P))
ZERO = np.zeros(NLIMBS, dtype=np.int32)
# R^2 mod p — to_mont multiplier
R2 = np.asarray(int_to_limbs((R_MONT * R_MONT) % P))
# Wrap rows: limbs of 2^(BITS*(NLIMBS+i)) mod p, for folding limbs >= 32
# back under 2^384. Row count covers the 63-limb convolution output.
_WRAP_ROWS = np.stack(
    [int_to_limbs(pow(2, BITS * (NLIMBS + i), P)) for i in range(NLIMBS + 4)]
)
# Negation addend: value v with v ≡ -(2^385 - 2) (mod p), so that
# (2^385-2) - b (a borrow-free per-limb complement) plus v is ≡ -b.
_NEG_ADDEND = np.asarray(int_to_limbs((-(2**385 - 2)) % P))
# Multiples of p below ~2^384: an exactly-normalized value < 2^384(1+eps)
# is ≡ 0 mod p iff it equals one of these. 33 limbs (room for the eps).
_P_MULTIPLES = np.stack(
    [int_to_limbs(k * P, NLIMBS + 1) for k in range(R_MONT // P + 1)]
)

_WRAP_ROWS.setflags(write=False)
_P_MULTIPLES.setflags(write=False)


# ---------------------------------------------------------------------------
# Carry folding and reduction
# ---------------------------------------------------------------------------

def _fold(t: jnp.ndarray, rounds: int, grow: bool = True) -> jnp.ndarray:
    """Carry-fold: after `rounds` passes limbs are <= MASK+1 (the +1 ripple
    edge is tolerated everywhere by design). grow=True appends one limb to
    catch the final carry-out."""
    if grow:
        pad = [(0, 0)] * (t.ndim - 1) + [(0, 1)]
        t = jnp.pad(t, pad)
    for _ in range(rounds):
        lo = t & MASK
        carry = t >> BITS
        t = lo + jnp.concatenate(
            [jnp.zeros_like(carry[..., :1]), carry[..., :-1]], axis=-1
        )
    return t


def _wrap(t: jnp.ndarray, passes: int, fold_rounds: int = 3) -> jnp.ndarray:
    """Reduce a (..., >=NLIMBS)-limb value into NLIMBS limbs, preserving the
    value mod p, by folding high limbs through 2^(12k) mod p. Each pass
    shrinks the overflow geometrically; `passes` is sized by the caller's
    input bound (2 covers anything below ~8*2^384)."""
    for _ in range(passes):
        if t.shape[-1] <= NLIMBS:
            break
        lo, hi = t[..., :NLIMBS], t[..., NLIMBS:]
        rows = jnp.asarray(_WRAP_ROWS[: hi.shape[-1]])
        red = jnp.sum(hi[..., None] * rows, axis=-2, dtype=DTYPE)
        t = _fold(lo + red, rounds=fold_rounds, grow=True)
    return t[..., :NLIMBS]


def reduce_limbs(t: jnp.ndarray, passes: int = 2, pre_rounds: int = 2) -> jnp.ndarray:
    """Normalize arbitrary (..., K>=NLIMBS) limbs (each < ~2^30) to the
    engine invariant: NLIMBS limbs in [0, ~4100], value in [0, ~2^384)."""
    t = _fold(t, rounds=pre_rounds, grow=True)
    return _wrap(t, passes)


def reduce_light(t: jnp.ndarray) -> jnp.ndarray:
    """Normalization for SMALL overflows (limbs < 2^16 — add/sub/mul_small
    outputs): one fold round, then THREE wrap passes with 2-round folds.

    The third pass is load-bearing. Soundness (w0 = 2^384 mod p ≈
    0.086·2^384): the initial fold leaves a carry limb t32 ≤ 16, so after
    pass 1 the value can be as large as V1 ≤ (1.004 + 16·0.086)·2^384 ≈
    2.4·2^384; after pass 2 it is V2 ≤ (1.004 + 2·0.086)·2^384 ≈
    1.18·2^384 — still ≥ 2^384, so a 2-pass wrap can end with a NONZERO
    carry limb that truncation silently drops (a −2^384 ≡ −R error; found
    as a live ~2^-12-per-sub bug via a failing pairing witness,
    tests/test_limb_regression.py). After pass 3, V3 ≤ (0.18 + 0.086)·
    2^384 < 2^384, so the final carry limb is provably zero and the
    truncation is exact."""
    t = _fold(t, rounds=1, grow=True)
    return _wrap(t, passes=3, fold_rounds=2)


# ---------------------------------------------------------------------------
# Field ops (Montgomery domain)
# ---------------------------------------------------------------------------

def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return reduce_light(a + b)


def add3(a: jnp.ndarray, b: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    return reduce_light(a + b + c)


def neg(b: jnp.ndarray) -> jnp.ndarray:
    # borrow-free complement: (2^385-2) - b has limbs 8190 - b_i >= ~4090
    # (non-negative for any b_i <= 8190, i.e. any invariant-respecting input)
    comp = (2 * MASK) - b
    return reduce_light(comp + jnp.asarray(_NEG_ADDEND))


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    comp = (2 * MASK) - b
    return reduce_light(a + comp + jnp.asarray(_NEG_ADDEND))


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative int constant.

    k <= 15: keeps a*k limbs under reduce_light's < 2^16 input domain
    (4100 * 15 = 61500 < 65536). Current call sites use k <= 8.
    """
    if not 0 <= k <= 15:
        raise ValueError("mul_small constant out of domain (0..15)")
    return reduce_light(a * k)


def double(a: jnp.ndarray) -> jnp.ndarray:
    return mul_small(a, 2)


# Gather tables for the shifted-stack convolution: row i of the stack is b
# shifted up by i limbs. _SHIFT_IDX[i, j] = j - i (clamped to range),
# _SHIFT_MASK zeroes the out-of-range positions.
_SHIFT_IDX = np.zeros((NLIMBS, 2 * NLIMBS), dtype=np.int32)
_SHIFT_MASK = np.zeros((NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(2 * NLIMBS):
        _k = _j - _i
        if 0 <= _k < NLIMBS:
            _SHIFT_IDX[_i, _j] = _k
            _SHIFT_MASK[_i, _j] = 1
_SHIFT_IDX.setflags(write=False)
_SHIFT_MASK.setflags(write=False)

# XLA-path conv strategy (trace-time constant, like bl.CONV_MODE):
#   "gather" (default): one gather + mask + multiply-sum — 2048 lane
#       multiplies of which half are masked zeros, but measured 7x
#       FASTER at execution on XLA:CPU than the skew form (9.6 -> 1.4 ms
#       for a 255-step scan at B=64; XLA:CPU fuses the gather+reduce,
#       while skew's pad/flatten/reshape materializes copies per step).
#       This is also the form behind every r3/r4 TPU measurement.
#   "skew": outer product + stride-trick reshape — exactly the 1024
#       true products; candidate for the TPU fused-aggregator path
#       (ROOFLINE r5), to be A/B'd on hardware before becoming default.
XCONV_MODE = __import__("os").environ.get("DRAND_TPU_XCONV", "gather")


def _shift_stack(b: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """(..., 32) -> (..., 32, out_len): row i is b shifted up by i limbs."""
    idx = jnp.asarray(_SHIFT_IDX[:, :out_len])
    mask = jnp.asarray(_SHIFT_MASK[:, :out_len])
    return b[..., idx] * mask


def _conv_skew(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Anti-diagonal sums of the outer product via the skew-reshape
    trick: (..., 32) x (..., 32) -> (..., 63) with EXACTLY the n*m = 1024
    true limb products — the windowed gather form multiplies ~50% zeros.

    outer[i, j] = a_i * b_j padded to row width 2n, flattened, then
    re-viewed at row stride 2n-1: row i of the view is outer row i
    shifted right by i (flat index i*(2n-1)+k = i*2n + (k-i)), so a
    single sum over rows yields C[k] = sum_{i+j=k} a_i b_j. Values are
    bit-identical to the gather form (same non-negative int32 products,
    associative sum).
    NB: explicit multiply+sum, NOT einsum/dot — integer dots may be
    lowered through inexact float accumulation paths on some backends."""
    outer = a[..., :, None] * b[..., None, :]        # (..., 32, 32)
    z = jnp.zeros(outer.shape[:-1] + (NLIMBS,), DTYPE)
    x = jnp.concatenate([outer, z], axis=-1)         # (..., 32, 64)
    flat = x.reshape(x.shape[:-2] + (2 * NLIMBS * NLIMBS,))
    skew = flat[..., : NLIMBS * (2 * NLIMBS - 1)].reshape(
        x.shape[:-2] + (NLIMBS, 2 * NLIMBS - 1))
    return jnp.sum(skew, axis=-2, dtype=DTYPE)       # (..., 63)


def _conv_full(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Product convolution: (..., 32) x (..., 32) -> (..., 64), limb values
    <= 2^29."""
    if XCONV_MODE == "skew":
        c = _conv_skew(a, b)
        return jnp.pad(c, [(0, 0)] * (c.ndim - 1) + [(0, 1)])
    bs = _shift_stack(b, 2 * NLIMBS)
    return jnp.sum(a[..., None] * bs, axis=-2, dtype=DTYPE)


def _conv_lo(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Low half of the convolution: result limbs 0..31 only (values mod-2^384
    arithmetic — exactly what Montgomery's m needs)."""
    if XCONV_MODE == "skew":
        return _conv_skew(a, b)[..., :NLIMBS]
    bs = _shift_stack(b, 2 * NLIMBS)[..., :NLIMBS]
    return jnp.sum(a[..., None] * bs, axis=-2, dtype=DTYPE)


def _fold_drop(t: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Carry-fold that DROPS carries out of the top limb: computes the limb
    normalization of (value mod 2^(12*len))."""
    for _ in range(rounds):
        lo = t & MASK
        carry = t >> BITS
        t = lo + jnp.concatenate(
            [jnp.zeros_like(carry[..., :1]), carry[..., :-1]], axis=-1
        )
    return t


# -(p^-1) mod 2^384, as limbs — the full-width Montgomery constant
_NPRIME_LIMBS = np.asarray(int_to_limbs((-pow(P, -1, R_MONT)) % R_MONT))


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a * b * R^-1 mod p (REDC, fully parallel).

        T = a*b
        m = (T mod R) * (-p^-1) mod R
        U = T + m*p          (U ≡ 0 mod R)
        result = U / R  =  U_high + [U_low != 0]

    The last step works because after carry-folding, U_low's value is a
    multiple of R in [0, R(1+eps)) — i.e. exactly 0 or R — so the quotient
    bit is just "any non-zero low limb". No sequential carry chain anywhere.

    The optimization_barrier pins the operands: without it, an XLA:CPU
    rewrite across stack/slice producer patterns miscompiles this graph
    (observed on jax 0.9.0: jit(f12_mul) != eager f12_mul; the barrier is
    load-bearing, do not remove without re-running the tower golden tests).
    """
    a, b = jax.lax.optimization_barrier((a, b))
    t = _conv_full(a, b)  # (..., 64), limbs <= 2^29
    t = _fold(t, rounds=3, grow=True)  # (..., 65), limbs <= 4096
    m = _conv_lo(t[..., :NLIMBS], jnp.asarray(_NPRIME_LIMBS))
    m = _fold_drop(m, rounds=3)  # limbs <= 4096, ≡ T*(-p^-1) mod R
    u = _conv_full(m, jnp.asarray(P_LIMBS))  # (..., 64), limbs <= 2^29
    u = jnp.pad(u, [(0, 0)] * (u.ndim - 1) + [(0, 1)]) + t
    u = _fold(u, rounds=3, grow=True)  # (..., 66), limbs <= 4096
    k = jnp.any(u[..., :NLIMBS] != 0, axis=-1).astype(DTYPE)
    r = u[..., NLIMBS:].at[..., 0].add(k)
    # r value < 2^384 + p + 1 -> wrap passes normalize under 2^384
    return _wrap(_fold(r, rounds=1, grow=False), passes=2)


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, jnp.asarray(R2))


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    one = jnp.zeros(NLIMBS, DTYPE).at[0].set(1)
    return mont_mul(a, jnp.broadcast_to(one, a.shape))


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Element-wise limb select; cond has the batch shape (no limb dim)."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# Exact normalization and zero test
# ---------------------------------------------------------------------------

def exact_normalize(t: jnp.ndarray) -> jnp.ndarray:
    """Sequential carry propagation -> limbs exactly in [0, MASK], plus one
    carry-out limb: shape (..., NLIMBS+1). Used only at equality checks."""

    def step(carry, x):
        s = x + carry
        return s >> BITS, s & MASK

    # derive the initial carry from t (not a fresh constant) so it inherits
    # t's varying-manual-axes type under shard_map — a constant carry fails
    # lax.scan's carry typecheck inside a mapped region
    carry0 = t[..., 0] * 0
    # scan over the limb axis (move it to front)
    xs = jnp.moveaxis(t, -1, 0)
    carry, ys = jax.lax.scan(step, carry0, xs)
    out = jnp.moveaxis(ys, 0, -1)
    return jnp.concatenate([out, carry[..., None]], axis=-1)


def is_zero_mod_p(a: jnp.ndarray) -> jnp.ndarray:
    """True where the value ≡ 0 (mod p). Sound for any value < ~2^384(1+eps):
    exact-normalize, then compare against every multiple of p in range."""
    norm = exact_normalize(a)  # (..., 33)
    mults = jnp.asarray(_P_MULTIPLES)  # (10, 33)
    eq = jnp.all(norm[..., None, :] == mults, axis=-1)  # (..., 10)
    return jnp.any(eq, axis=-1)


def eq_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero_mod_p(sub(a, b))


def _lex_ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b for little-endian EXACT limb vectors (same trailing length):
    the most significant differing limb decides."""
    eq = a == b
    gt = a > b
    # all limbs ABOVE position j equal: reversed-cumprod trick
    eq_rev = jnp.flip(eq, -1)
    higher_eq = jnp.concatenate(
        [jnp.ones_like(eq_rev[..., :1]),
         jnp.cumprod(eq_rev[..., :-1].astype(DTYPE), axis=-1).astype(bool)],
        axis=-1)
    gt_rev = jnp.flip(gt, -1)
    return jnp.any(gt_rev & higher_eq, axis=-1) | jnp.all(eq, axis=-1)


def canonicalize(a: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical form: limbs of (value mod p), each in [0, MASK],
    shape (..., NLIMBS). Needed wherever the INTEGER value matters (sgn0,
    lexicographic y selection, serialization) — the engine invariant only
    guarantees the value mod p."""
    norm = exact_normalize(a)  # (..., 33) exact, value < ~2^385
    mults = jnp.asarray(_P_MULTIPLES)  # (K, 33): k*p for k = 0..K-1
    ge = _lex_ge(norm[..., None, :], mults)  # (..., K)
    k = jnp.sum(ge.astype(DTYPE), axis=-1) - 1  # value in [k*p, (k+1)*p)
    diffs = norm[..., None, :] - mults  # (..., K, 33), limbs possibly < 0

    def borrow_step(carry, x):
        s = x + carry
        return s >> BITS, s & MASK

    xs = jnp.moveaxis(diffs, -1, 0)
    _, ys = jax.lax.scan(borrow_step, diffs[..., 0] * 0, xs)
    fixed = jnp.moveaxis(ys, 0, -1)  # exact non-negative for the right k
    onehot = (jnp.arange(mults.shape[0]) == k[..., None]).astype(DTYPE)
    return jnp.sum(fixed * onehot[..., None], axis=-2,
                   dtype=DTYPE)[..., :NLIMBS]


# ---------------------------------------------------------------------------
# Fixed-exponent powering (device, scanned over a host-fixed bit pattern)
# ---------------------------------------------------------------------------

def pow_const(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a fixed non-negative exponent, LSB-first square-and-multiply
    under lax.scan (compact trace for ~381-bit exponents)."""
    if e < 0:
        raise ValueError("negative exponent (use inverse)")
    if e == 0:
        return jnp.asarray(ONE_MONT) + a * 0
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())], dtype=np.int32)

    def step(state, bit):
        result, base = state
        result = select(bit.astype(bool), mont_mul(result, base), result)
        base = mont_sqr(base)
        return (result, base), None

    # `one + a*0` (not broadcast_to of a constant): keeps the scan carry's
    # varying-manual-axes type aligned with `a` under shard_map
    init = (jnp.asarray(ONE_MONT) + a * 0, a)
    (result, _), _ = jax.lax.scan(step, init, jnp.asarray(bits))
    return result


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inverse a^(p-2). Stays in Montgomery form. inv(0) = 0."""
    return pow_const(a, P - 2)
