"""Fused batch-last pairing: Pallas TPU kernels for the BLS hot path.

The XLA graph pairing (ops/pairing.py) is correct but dispatch-bound
(~50k tiny HLOs per call) and, on the current axon stack, miscompiled
above small batch sizes. This module re-expresses the SAME mathematics
(M-twist denominator-eliminated Miller loop, Hayashida final
exponentiation — golden reference drand_tpu.crypto.pairing) in the
batch-last layout of ops/bl.py, and wraps the heavy loops in Pallas
kernels compiled by Mosaic — a different compiler path with per-kernel
fusion instead of per-op dispatch:

    K1  miller_kernel    — full 63-iteration Miller loop, both pairs
    K2  easy_kernel      — f^((p^6-1)(p^2+1)) incl. the Fermat Fp inverse
    K3  pow_kernel       — one cyclotomic pow-by-|e| chain (called 4x)

Inter-kernel glue (Frobenius twists, f12 products, the final ==1 check)
runs as plain XLA on the same bl arrays — a few hundred HLOs, negligible.

Everything is also runnable WITHOUT Pallas (``use_pallas=False``): the
math functions are pure jnp, so the CPU test suite validates them
directly and the TPU engine known-answer-validates the kernels at every
batch shape before trusting them (see ops/engine.py bucket validation).

Reference hot calls replaced: chain/beacon/chain.go:136-141,
client/verify.go:146-163, chain/beacon/node.go:112.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P, X_BLS
from . import bl
from . import limb as _limb
from .bl import (
    NLIMBS, DTYPE,
    f2, f2_add, f2_sub, f2_neg, f2_mul, f2_sqr, f2_mul_fp, f2_mul_small,
    f2_mul_by_xi, f12, f12_mul, f12_sqr, f12_conj, f12_inv, f12_frobenius,
    f12_cyclotomic_sqr, f12_one, f12_from_w, f12_to_w,
    reduce_light,
)

# ---------------------------------------------------------------------------
# Bit schedules (host constants, passed to kernels as inputs)
# ---------------------------------------------------------------------------

# trace-time constant (read at first kernel compile; see bl.CONV_MODE)
PAIRFOLD = __import__("os").environ.get("DRAND_TPU_PAIRFOLD", "1") == "1"

_X_ABS = abs(X_BLS)


def _bits_2d(e: int, msb_skip_leading: bool) -> np.ndarray:
    """MSB-first bit table padded to (1, 64) int32."""
    s = bin(e)[2:]
    if msb_skip_leading:
        s = s[1:]
    out = np.zeros((1, 64), dtype=np.int32)
    out[0, :len(s)] = [int(c) for c in s]
    return out


MILLER_FLAGS = _bits_2d(_X_ABS, msb_skip_leading=True)   # 63 used
N_MILLER = len(bin(_X_ABS)[3:])
BITS_XM1 = _bits_2d(abs(X_BLS - 1), msb_skip_leading=False)  # 63 used
N_XM1 = abs(X_BLS - 1).bit_length()
BITS_X = _bits_2d(_X_ABS, msb_skip_leading=False)            # 64 used
N_X = _X_ABS.bit_length()


def value_bit_getter(bits2d):
    """Bit getter over a traced (1, 64) value — XLA path only (Mosaic has
    no dynamic_slice on values; kernels use smem_bit_getter)."""
    def get(i):
        return jax.lax.dynamic_slice(bits2d, (0, i), (1, 1))[0, 0]

    return get


def smem_bit_getter(bits_ref):
    """Bit getter over a (1, 64) SMEM ref inside a Pallas kernel."""
    def get(i):
        return bits_ref[0, i]

    return get


# ---------------------------------------------------------------------------
# Miller loop (batch-last). Shapes:
#   xp, yp: (NP, 32, B) G1 affine coords per pair
#   q:      (NP, 2, 2, 32, B) G2 affine (coord, c0/c1, limb, batch)
#   f:      (2, 3, 2, 32, B)
# The pair axis NP rides as a leading batch axis through all f2 ops.
# ---------------------------------------------------------------------------

def _dbl_step(T, xp, yp):
    """Jacobian doubling + line (c0, c3, c5); see ops/pairing._dbl_step."""
    X, Y, Z = T
    X2 = f2_sqr(X)
    Y2 = f2_sqr(Y)
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    YZ3 = f2_mul(Y, Z3)
    lam_s = f2_mul_small(f2_mul(X2, Z2), 3)
    c0 = f2_mul_by_xi(f2_mul_fp(f2_mul_small(YZ3, 2), yp))
    c5 = f2_neg(f2_mul_fp(lam_s, xp))
    X3cu = f2_mul(X2, X)
    c3 = f2_sub(f2_mul_small(X3cu, 3), f2_mul_small(Y2, 2))
    C = f2_sqr(Y2)
    D = f2_mul_small(f2_sub(f2_sqr(f2_add(X, Y2)), f2_add(X2, C)), 2)
    E = f2_mul_small(X2, 3)
    F = f2_sqr(E)
    Xn = f2_sub(F, f2_mul_small(D, 2))
    Yn = f2_sub(f2_mul(E, f2_sub(D, Xn)), f2_mul_small(C, 8))
    Zn = f2_mul_small(f2_mul(Y, Z), 2)
    return (Xn, Yn, Zn), (c0, c3, c5)


def _add_step(T, q, xp, yp):
    """Mixed addition + line; see ops/pairing._add_step."""
    X, Y, Z = T
    xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    U2 = f2_mul(xq, Z2)
    S2 = f2_mul(yq, Z3)
    H = f2_sub(U2, X)
    M = f2_sub(S2, Y)
    HZ = f2_mul(H, Z)
    c0 = f2_mul_by_xi(f2_mul_fp(HZ, yp))
    c5 = f2_neg(f2_mul_fp(M, xp))
    c3 = f2_sub(f2_mul(M, xq), f2_mul(HZ, yq))
    HH = f2_sqr(H)
    HHH = f2_mul(HH, H)
    V = f2_mul(X, HH)
    M2 = f2_sqr(M)
    Xn = f2_sub(M2, f2_add(HHH, f2_mul_small(V, 2)))
    Yn = f2_sub(f2_mul(M, f2_sub(V, Xn)), f2_mul(Y, HHH))
    Zn = f2_mul(Z, H)
    return (Xn, Yn, Zn), (c0, c3, c5)


def _lines_product(l0, l1):
    """Product of two 035-sparse lines as a full f12 element.

    (c0 + c3 w^3 + c5 w^5)(d0 + d3 w^3 + d5 w^5) via the 6-multiply
    3-term Karatsuba (m0, m1, m2 plus the three pair-sum products), then
    w-power folding with w^6 = xi: the w^6/w^8/w^10 terms land on
    w^0/w^2/w^4 with a xi twist, leaving slot w^1 zero. 6 Fp2 muls (one
    stacked mont_mul) instead of the naive 9."""
    c0, c3, c5 = l0
    d0, d3, d5 = l1
    pa = jnp.stack([c0, c3, c5, f2_add(c0, c3), f2_add(c0, c5),
                    f2_add(c3, c5)], axis=0)
    pb = jnp.stack([d0, d3, d5, f2_add(d0, d3), f2_add(d0, d5),
                    f2_add(d3, d5)], axis=0)
    m = f2_mul(pa, pb)
    m0, m1, m2 = m[0], m[1], m[2]
    s03 = f2_sub(m[3], f2_add(m0, m1))   # c0d3 + c3d0 -> w^3
    s05 = f2_sub(m[4], f2_add(m0, m2))   # c0d5 + c5d0 -> w^5
    s35 = f2_sub(m[5], f2_add(m1, m2))   # c3d5 + c5d3 -> w^8 = xi w^2
    e0 = f2_add(m0, f2_mul_by_xi(m1))    # w^0 + xi (from w^6)
    e2 = f2_mul_by_xi(s35)
    e4 = f2_mul_by_xi(m2)                # w^10 = xi w^4
    cL0 = jnp.stack([e0, e2, e4], axis=-4)             # w^0, w^2, w^4
    cL1 = jnp.stack([jnp.zeros_like(e0), s03, s05], axis=-4)  # w^1,3,5
    return f12(cL0, cL1)


def _sparse_mul_035(f, lines, npairs: int, split: bool = False):
    """f * prod_j L_j for per-pair lines L_j = c0 + c3*w^3 + c5*w^5
    (slots from the M-twist untwist — see ops/pairing._sparse_mul_035).

    Lines are folded in PAIRS: L_j * L_{j+1} is formed first with
    :func:`_lines_product` (6 Fp2 muls) and multiplied into f as one
    full f12_mul (18 Fp2 muls) — 24 Fp2 muls per line pair with NO
    w-basis round trip of f, versus 36 Fp2 muls plus two to_w/from_w
    shuffles for the sequential per-line fold (kept below for an odd
    trailing line). ``split`` shrinks peak temporaries on that odd-line
    path only.

    VMEM note: the pair fold's peak temporaries inside the Miller
    kernels match a BB-batch f12_mul (~the pow kernels' working set,
    proven on-chip); set DRAND_TPU_PAIRFOLD=0 (trace-time constant,
    like DRAND_TPU_CONV) to A/B or fall back to the sequential fold if
    a Mosaic VMEM limit is hit at some batch shape."""
    c0, c3, c5 = lines  # each (NP, 2, 32, B)
    j = 0
    while PAIRFOLD and j + 1 < npairs:
        L = _lines_product((c0[j], c3[j], c5[j]),
                           (c0[j + 1], c3[j + 1], c5[j + 1]))
        f = f12_mul(f, L)
        j += 2
    for j in range(j, npairs):
        fw = f12_to_w(f)  # (6, 2, 32, B)
        if split:
            p0 = f2_mul(fw, c0[j][None])
            p3 = f2_mul(fw, c3[j][None])
            p5 = f2_mul(fw, c5[j][None])
        else:
            cj = jnp.stack([c0[j], c3[j], c5[j]], axis=0)  # (3, 2, 32, B)
            prod = f2_mul(fw[None], cj[:, None])  # (3, 6, 2, 32, B)
            p0, p3, p5 = prod[0], prod[1], prod[2]
        out = []
        for k in range(6):
            term = p0[k]
            t3 = p3[(k - 3) % 6]
            if k - 3 < 0:
                t3 = f2_mul_by_xi(t3)
            t5 = p5[(k - 5) % 6]
            if k - 5 < 0:
                t5 = f2_mul_by_xi(t5)
            out.append(reduce_light(term + t3 + t5))
        f = f12_from_w(jnp.stack(out, axis=0))
    return f


def miller_loop_bl(xp, yp, q, flag_getter):
    """Batched Miller loop, single fori_loop with masked add steps.

    flag_getter(i) != 0 => mixed addition after doubling i (the set bits
    of |x| after the implicit MSB). Conjugation for x < 0 is applied.
    Returns f (2, 3, 2, 32, B).
    """
    npairs = q.shape[0]
    b = q.shape[-1]
    xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]
    # Z = 1 in Fp2, per pair — stacked build (no scatter in Mosaic)
    one_fp = jnp.broadcast_to(bl._crow("ONE"),
                              xq.shape[:-3] + (NLIMBS, b)).astype(DTYPE)
    one2 = jnp.stack([one_fp, jnp.zeros_like(one_fp)], axis=-3)
    f0 = f12_one((), b)

    def body(i, state):
        f, X, Y, Z = state
        f = f12_sqr(f)
        (X, Y, Z), lines = _dbl_step((X, Y, Z), xp, yp)
        f = _sparse_mul_035(f, lines, npairs)
        (Xa, Ya, Za), lines_a = _add_step((X, Y, Z), q, xp, yp)
        fa = _sparse_mul_035(f, lines_a, npairs)
        cond = flag_getter(i) != 0
        f = jnp.where(cond, fa, f)
        X = jnp.where(cond, Xa, X)
        Y = jnp.where(cond, Ya, Y)
        Z = jnp.where(cond, Za, Z)
        return f, X, Y, Z

    f, _, _, _ = jax.lax.fori_loop(0, N_MILLER, body, (f0, xq, yq, one2))
    return f12_conj(f)  # x < 0


# ---------------------------------------------------------------------------
# Final exponentiation pieces
# ---------------------------------------------------------------------------

def final_exp_easy_bl(f, bit_getter=None):
    """f^((p^6-1)(p^2+1)) — includes the single Fp Fermat inversion.
    ``bit_getter`` feeds the p-2 exponent bits (kernels pass an SMEM-ref
    getter; the XLA path defaults to the constant-buffer PM2 section)."""
    f1 = f12_mul(f12_conj(f), f12_inv(f, bit_getter))
    return f12_mul(f12_frobenius(f1, 2), f1)


def cyc_pow_neg_bl(m, bit_getter, nbits: int):
    """m^(-|e|) for cyclotomic m, MSB-first square-and-multiply."""
    base = f12_conj(m)

    def body(i, acc):
        acc = f12_cyclotomic_sqr(acc)
        return jnp.where(bit_getter(i) != 0, f12_mul(acc, base), acc)

    init = f12_one((), m.shape[-1])
    return jax.lax.fori_loop(0, nbits, body, init)


def final_exp_hard_bl(m, g_xm1, g_x):
    """Hayashida chain (cube of the canonical pairing — equality checks
    are cube-invariant; mirrors ops/pairing._hard_part). g_xm1 / g_x are
    bit getters for |x-1| and |x|."""
    a1 = cyc_pow_neg_bl(m, g_xm1, N_XM1)
    a2 = cyc_pow_neg_bl(a1, g_xm1, N_XM1)
    a3 = f12_mul(cyc_pow_neg_bl(a2, g_x, N_X), f12_frobenius(a2, 1))
    t = cyc_pow_neg_bl(a3, g_x, N_X)
    a4 = f12_mul(f12_mul(cyc_pow_neg_bl(t, g_x, N_X),
                         f12_frobenius(a3, 2)), f12_conj(a3))
    return f12_mul(a4, f12_mul(m, f12_cyclotomic_sqr(m)))


def final_exp_hard_is_one_bl(m, g_xm1, g_x):
    """Hard part + ==1 check (per batch lane) — the finish kernel body."""
    return bl.f12_is_one(final_exp_hard_bl(m, g_xm1, g_x))


def final_exp_bl(f):
    """Full (cubed) final exponentiation, pure jnp (no Pallas)."""
    m = final_exp_easy_bl(f)
    return final_exp_hard_bl(m, value_bit_getter(jnp.asarray(BITS_XM1)),
                             value_bit_getter(jnp.asarray(BITS_X)))


def multi_pairing_bl(xp, yp, q):
    """prod_j e(P_j, Q_j) (cubed), pure jnp — the no-Pallas reference."""
    return final_exp_bl(miller_loop_bl(
        xp, yp, q, value_bit_getter(jnp.asarray(MILLER_FLAGS))))


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _pallas(kernel, out_shape, in_memspaces, scratch_shapes=()):
    """pallas_call with per-input memory spaces: 'v' = VMEM tensor input,
    's' = SMEM scalar table (bit schedules, read element-wise).
    scratch_shapes: (shape, ...) tuples allocated as VMEM scratch refs."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    spaces = {"v": pltpu.VMEM, "s": pltpu.SMEM}
    out_specs = jax.tree.map(
        lambda _: pl.BlockSpec(memory_space=pltpu.VMEM), out_shape)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=spaces[c])
                  for c in in_memspaces],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM(s, DTYPE) for s in scratch_shapes],
    )


def _miller_kernel(c_ref, flags_ref, xp_ref, yp_ref, q_ref, o_ref,
                   f_ref, tx_ref, ty_ref, tz_ref):
    """Miller loop with scratch-ref state and @pl.when-gated add steps:
    |x| has hamming weight 6, so the mixed addition + its sparse multiply
    are SKIPPED at runtime on 57 of 63 iterations (the masked-select
    variant in miller_loop_bl computes them every iteration — ~1.4x more
    work; that pure-jnp version remains the CPU-testable reference)."""
    from jax.experimental import pallas as pl

    with bl.const_context(c_ref[:]):
        xp, yp, q = xp_ref[:], yp_ref[:], q_ref[:]
        npairs = q.shape[0]
        b = q.shape[-1]
        xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]
        one_fp = jnp.broadcast_to(
            bl._crow("ONE"), xq.shape[:-3] + (NLIMBS, b)).astype(DTYPE)
        f_ref[:] = f12_one((), b)
        tx_ref[:] = xq
        ty_ref[:] = yq
        tz_ref[:] = jnp.stack([one_fp, jnp.zeros_like(one_fp)], axis=-3)

        def body(i, carry):
            f = f12_sqr(f_ref[:])
            T, lines = _dbl_step((tx_ref[:], ty_ref[:], tz_ref[:]), xp, yp)
            f_ref[:] = _sparse_mul_035(f, lines, npairs, split=True)
            tx_ref[:], ty_ref[:], tz_ref[:] = T

            @pl.when(flags_ref[0, i] != 0)
            def _add():
                Ta, lines_a = _add_step(
                    (tx_ref[:], ty_ref[:], tz_ref[:]), q, xp, yp)
                f_ref[:] = _sparse_mul_035(f_ref[:], lines_a, npairs,
                                           split=True)
                tx_ref[:], ty_ref[:], tz_ref[:] = Ta

            return carry

        jax.lax.fori_loop(0, N_MILLER, body, 0)
        o_ref[:] = f12_conj(f_ref[:])  # x < 0


def _easy_kernel(c_ref, pm2_ref, f_ref, o_ref):
    with bl.const_context(c_ref[:]):
        o_ref[:] = final_exp_easy_bl(
            f_ref[:], bit_getter=smem_bit_getter(pm2_ref))


def _pow_kernel(nbits: int, c_ref, bits_ref, m_ref, o_ref, acc_ref):
    """Cyclotomic pow with the f12 multiply under @pl.when — skipped at
    runtime on zero bits (the |x| chains have hamming weight 6/64; the
    |x-1| chains are dense, where it is roughly cost-neutral)."""
    from jax.experimental import pallas as pl

    with bl.const_context(c_ref[:]):
        base = f12_conj(m_ref[:])
        acc_ref[:] = f12_one((), m_ref.shape[-1])

        def body(i, carry):
            acc_ref[:] = f12_cyclotomic_sqr(acc_ref[:])

            @pl.when(bits_ref[0, i] != 0)
            def _mul():
                acc_ref[:] = f12_mul(acc_ref[:], base)

            return carry

        jax.lax.fori_loop(0, nbits, body, 0)
        o_ref[:] = acc_ref[:]


# The XLA glue between kernels is NOT safe on the axon stack (the same
# backend miscompile that breaks the batched XLA pairing graph corrupts
# plain f12 glue ops at B >= ~16 — bisected 2026-07-30), so every
# per-element operation after input packing stays inside Mosaic kernels.
# The hard part is split into SMALL kernels: one fused kernel holds too
# much live state for the 16 MB VMEM at B = 128.

def _mul_frob1_kernel(c_ref, x_ref, y_ref, o_ref):
    """out = x * frobenius(y, 1)."""
    with bl.const_context(c_ref[:]):
        o_ref[:] = f12_mul(x_ref[:], f12_frobenius(y_ref[:], 1))


def _a4_kernel(c_ref, x_ref, y_ref, o_ref):
    """out = x * frobenius(y, 2) * conj(y)."""
    with bl.const_context(c_ref[:]):
        o_ref[:] = f12_mul(f12_mul(x_ref[:], f12_frobenius(y_ref[:], 2)),
                           f12_conj(y_ref[:]))


def _is_one_kernel(c_ref, a4_ref, m_ref, o_ref):
    """ok = (a4 * m * cyc_sqr(m) == 1); (8, B) int32 out, row 0 is read."""
    with bl.const_context(c_ref[:]):
        m = m_ref[:]
        out = f12_mul(a4_ref[:], f12_mul(m, f12_cyclotomic_sqr(m)))
        ok = bl.f12_is_one(out)
        o_ref[:] = jnp.broadcast_to(ok.astype(DTYPE)[None, :], o_ref.shape)


# p-2 bits as a flat (1, 384) MSB-first SMEM table for the easy kernel
PM2_FLAT = bl._PM2_ROWS.reshape(1, 384)


# ---------------------------------------------------------------------------
# Grid kernels — one Miller/pow iteration per grid step, batch-blocked.
#
# The single-fori_loop kernels above compile to poor code when the loop
# body is large (measured 15M fp-mul/s inside _miller_kernel vs 157M for
# a lean chain kernel at the same batch — Mosaic register allocation
# degrades with body size). Re-expressing the outer loop as a Pallas grid
# dimension gives each step a small body and measured ~5x on the Miller
# loop (96.7 -> 19.5 ms at B=128, bit-identical output). The grid's
# leading dimension blocks the batch at BB lanes, so any B = k*BB runs
# in bounded VMEM; scratch state persists across the inner iteration
# steps and is re-initialised at step 0 of every batch block.
# ---------------------------------------------------------------------------

GRID_BLOCK = 128  # lanes per batch block (the VPU-native lane width)


def _miller_grid_kernel(flags_ref, c_ref, xp_ref, yp_ref, q_ref, o_ref,
                        f_ref, tx_ref, ty_ref, tz_ref):
    """One Miller iteration per inner grid step; batch blocks outer."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    with bl.const_context(c_ref[:]):
        xp, yp, q = xp_ref[:], yp_ref[:], q_ref[:]
        npairs = q.shape[0]
        b = q.shape[-1]
        xq, yq = q[..., 0, :, :, :], q[..., 1, :, :, :]

        @pl.when(i == 0)
        def _init():
            one_fp = jnp.broadcast_to(
                bl._crow("ONE"), xq.shape[:-3] + (NLIMBS, b)).astype(DTYPE)
            f_ref[:] = f12_one((), b)
            tx_ref[:] = xq
            ty_ref[:] = yq
            tz_ref[:] = jnp.stack([one_fp, jnp.zeros_like(one_fp)], axis=-3)

        f = f12_sqr(f_ref[:])
        T, lines = _dbl_step((tx_ref[:], ty_ref[:], tz_ref[:]), xp, yp)
        f_ref[:] = _sparse_mul_035(f, lines, npairs, split=True)
        tx_ref[:], ty_ref[:], tz_ref[:] = T

        @pl.when(flags_ref[i] != 0)
        def _add():
            Ta, lines_a = _add_step(
                (tx_ref[:], ty_ref[:], tz_ref[:]), q, xp, yp)
            f_ref[:] = _sparse_mul_035(f_ref[:], lines_a, npairs,
                                       split=True)
            tx_ref[:], ty_ref[:], tz_ref[:] = Ta

        @pl.when(i == pl.num_programs(1) - 1)
        def _fin():
            o_ref[:] = f12_conj(f_ref[:])


def _pow_grid_kernel(bits_ref, c_ref, m_ref, o_ref, acc_ref):
    """One cyclotomic square (+masked multiply) per inner grid step:
    computes m^(-|e|) like _pow_kernel."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    with bl.const_context(c_ref[:]):
        @pl.when(i == 0)
        def _init():
            acc_ref[:] = f12_one((), m_ref.shape[-1])

        acc_ref[:] = f12_cyclotomic_sqr(acc_ref[:])

        @pl.when(bits_ref[i] != 0)
        def _mul():
            acc_ref[:] = f12_mul(acc_ref[:], f12_conj(m_ref[:]))

        @pl.when(i == pl.num_programs(1) - 1)
        def _fin():
            o_ref[:] = acc_ref[:]


def _easy_grid_kernel(pm2_ref, c_ref, f_ref, o_ref):
    """Easy part over one batch block per grid step."""
    with bl.const_context(c_ref[:]):
        o_ref[:] = final_exp_easy_bl(
            f_ref[:], bit_getter=lambda i: pm2_ref[i])


def _mul_frob1_grid_kernel(c_ref, x_ref, y_ref, o_ref):
    with bl.const_context(c_ref[:]):
        o_ref[:] = f12_mul(x_ref[:], f12_frobenius(y_ref[:], 1))


def _a4_grid_kernel(c_ref, x_ref, y_ref, o_ref):
    with bl.const_context(c_ref[:]):
        o_ref[:] = f12_mul(f12_mul(x_ref[:], f12_frobenius(y_ref[:], 2)),
                           f12_conj(y_ref[:]))


def _is_one_grid_kernel(c_ref, a4_ref, m_ref, o_ref):
    with bl.const_context(c_ref[:]):
        m = m_ref[:]
        out = f12_mul(a4_ref[:], f12_mul(m, f12_cyclotomic_sqr(m)))
        ok = bl.f12_is_one(out)
        o_ref[:] = jnp.broadcast_to(ok.astype(DTYPE)[None, :], o_ref.shape)


def _block_last(shape, bb):
    """Full-array block except the lane axis blocked at bb; index_map
    keeps every axis at block 0 and walks the lane axis by batch block."""
    block = shape[:-1] + (bb,)
    nd = len(shape)

    def imap(bi, i, *_):
        return (0,) * (nd - 1) + (bi,)

    return block, imap


@functools.partial(jax.jit, static_argnames=("npairs", "b", "bb"))
def _verify_pl_grid(xp, yp, q, npairs: int, b: int, bb: int = GRID_BLOCK):
    """Grid-kernel verify chain: same mathematics and contract as
    _verify_pl, restructured as batch-blocked iteration grids. Requires
    b % bb == 0."""
    assert b % bb == 0, (b, bb)
    nb = b // bb
    consts = jnp.asarray(bl.CONST_BUFFER)
    cshape = bl.CONST_BUFFER.shape
    f12_shape = jax.ShapeDtypeStruct((2, 3, 2, NLIMBS, b), DTYPE)
    f12_block = (2, 3, 2, NLIMBS, bb)
    f12_dims = f12_block
    t_dims = (npairs, 2, NLIMBS, bb)

    def cmap(bi, i, *_):
        return (0, 0)

    def run(kernel, n_inner, scalars, ins, scratch, out_shape=f12_shape,
            out_block=None):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        out_block = out_block or _block_last(out_shape.shape, bb)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=(nb, n_inner),
            in_specs=[pl.BlockSpec(cshape, cmap)] + [
                pl.BlockSpec(*_block_last(a.shape, bb)) for a in ins],
            out_specs=pl.BlockSpec(*out_block),
            scratch_shapes=[pltpu.VMEM(s, DTYPE) for s in scratch],
        )
        return pl.pallas_call(kernel, out_shape=out_shape,
                              grid_spec=grid_spec)(*scalars, consts, *ins)

    flags = jnp.asarray(MILLER_FLAGS[0].astype(np.int32))
    pm2 = jnp.asarray(PM2_FLAT[0].astype(np.int32))
    bits_xm1 = jnp.asarray(BITS_XM1[0].astype(np.int32))
    bits_x = jnp.asarray(BITS_X[0].astype(np.int32))

    f = run(_miller_grid_kernel, N_MILLER, (flags,), (xp, yp, q),
            (f12_dims, t_dims, t_dims, t_dims))
    m = run(_easy_grid_kernel, 1, (pm2,), (f,), ())

    def pow_neg(x, bits, nbits):
        return run(_pow_grid_kernel, nbits, (bits,), (x,), (f12_dims,))

    a1 = pow_neg(m, bits_xm1, N_XM1)
    a2 = pow_neg(a1, bits_xm1, N_XM1)
    a3 = run(_mul_frob1_grid_kernel, 1, (),
             (pow_neg(a2, bits_x, N_X), a2), ())
    t = pow_neg(a3, bits_x, N_X)
    a4 = run(_a4_grid_kernel, 1, (), (pow_neg(t, bits_x, N_X), a3), ())
    ok = run(_is_one_grid_kernel, 1, (), (a4, m),
             (), out_shape=jax.ShapeDtypeStruct((8, b), DTYPE))
    return ok[0] != 0


@functools.partial(jax.jit, static_argnames=("npairs", "b"))
def _verify_pl(xp, yp, q, npairs: int, b: int):
    """Full BLS batch check with ALL per-element math inside Pallas
    kernels (miller -> easy -> pow chains -> glue -> is_one).
    Returns (B,) bool."""
    consts = jnp.asarray(bl.CONST_BUFFER)
    f12_shape = jax.ShapeDtypeStruct((2, 3, 2, NLIMBS, b), DTYPE)

    f12_dims = (2, 3, 2, NLIMBS, b)
    t_dims = (npairs, 2, NLIMBS, b)
    f = _pallas(_miller_kernel, f12_shape, "vsvvv",
                scratch_shapes=(f12_dims, t_dims, t_dims, t_dims))(
        consts, jnp.asarray(MILLER_FLAGS), xp, yp, q)
    m = _pallas(_easy_kernel, f12_shape, "vsv")(
        consts, jnp.asarray(PM2_FLAT), f)

    def pow_neg(x, bits2d, nbits):
        return _pallas(functools.partial(_pow_kernel, nbits),
                       f12_shape, "vsv", scratch_shapes=(f12_dims,))(
            consts, jnp.asarray(bits2d), x)

    a1 = pow_neg(m, BITS_XM1, N_XM1)
    a2 = pow_neg(a1, BITS_XM1, N_XM1)
    a3 = _pallas(_mul_frob1_kernel, f12_shape, "vvv")(
        consts, pow_neg(a2, BITS_X, N_X), a2)
    t = pow_neg(a3, BITS_X, N_X)
    a4 = _pallas(_a4_kernel, f12_shape, "vvv")(
        consts, pow_neg(t, BITS_X, N_X), a3)
    ok = _pallas(_is_one_kernel, jax.ShapeDtypeStruct((8, b), DTYPE),
                 "vvv")(consts, a4, m)
    return ok[0] != 0


# ---------------------------------------------------------------------------
# Verification entry points
# ---------------------------------------------------------------------------

def _f12_is_one_bl(f):
    """==1 check in XLA: transpose to the limb-last layout and reuse the
    proven exact-normalize comparison from ops/limb."""
    from . import tower as _tw

    g = jnp.moveaxis(f, -1, 0)  # (B, 2, 3, 2, 32)
    d = _limb.sub(g, _tw.f12_one())
    z = _limb.is_zero_mod_p(d)  # (B, 2, 3, 2)
    return jnp.all(z, axis=(-3, -2, -1))


def pack_verify_inputs(pub_aff, sig_aff, msg_aff):
    """Batch-leading engine arrays -> batch-last kernel arrays.

    pub_aff (B, 2, 32), sig_aff/msg_aff (B, 2, 2, 32) — the layout of
    ops/engine._run_bucket — become xp/yp (2, 32, B) and q (2, 2, 2, 32, B)
    with pair 0 = (-g1, sig) and pair 1 = (pub, msg).
    """
    neg_g1 = np.broadcast_to(_neg_g1_np(), pub_aff.shape)
    xp = jnp.stack([jnp.moveaxis(jnp.asarray(neg_g1[:, 0]), 0, -1),
                    jnp.moveaxis(jnp.asarray(pub_aff[:, 0]), 0, -1)])
    yp = jnp.stack([jnp.moveaxis(jnp.asarray(neg_g1[:, 1]), 0, -1),
                    jnp.moveaxis(jnp.asarray(pub_aff[:, 1]), 0, -1)])
    q = jnp.stack([jnp.moveaxis(jnp.asarray(sig_aff), 0, -1),
                   jnp.moveaxis(jnp.asarray(msg_aff), 0, -1)])
    return xp, yp, q


_NEG_G1_NP = None


def _neg_g1_np():
    global _NEG_G1_NP
    if _NEG_G1_NP is None:
        from ..crypto.curves import PointG1

        x, y = (-PointG1.generator()).to_affine()
        _NEG_G1_NP = np.stack([_limb.int_to_mont_limbs(x.v),
                               _limb.int_to_mont_limbs(y.v)])
    return _NEG_G1_NP


_SHARDED_VERIFY_CACHE: dict = {}


def _sharded_verify_fn(mesh, b_local: int):
    """Build (once per (mesh, b_local)) the shard_map-wrapped grid verify
    — fresh closures per call would defeat jax's dispatch cache on the
    catchup hot path."""
    key = (mesh, b_local)
    fn = _SHARDED_VERIFY_CACHE.get(key)
    if fn is None:
        try:
            from jax import shard_map
        except ImportError:  # jax < 0.8 layout
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        axis = mesh.axis_names[0]

        def local(xp, yp, q):
            return _verify_pl_grid(xp, yp, q, npairs=2, b=b_local)

        fn = jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=(P(None, None, axis), P(None, None, axis),
                      P(None, None, None, None, axis)),
            out_specs=P(axis)))
        _SHARDED_VERIFY_CACHE[key] = fn
    return fn


def verify_prepared_pl_sharded(pub_aff, sig_aff, msg_aff, mesh):
    """verify_prepared_pl with the batch axis sharded over a 1-axis mesh
    via shard_map — each device runs the grid-kernel chain on its local
    lanes (data parallel over rounds; SURVEY §5's pjit-sharded catchup
    design, same shape as the driver's dryrun_multichip). Requires the
    per-device batch to be a GRID_BLOCK multiple."""
    xp, yp, q = pack_verify_inputs(np.asarray(pub_aff), np.asarray(sig_aff),
                                   np.asarray(msg_aff))
    b = q.shape[-1]
    ndev = mesh.devices.size
    b_local = b // ndev
    if b % ndev or b_local % GRID_BLOCK:
        raise ValueError(f"batch {b} not shardable over {ndev} devices")
    return _sharded_verify_fn(mesh, b_local)(xp, yp, q)


def verify_prepared_pl(pub_aff, sig_aff, msg_aff, use_pallas: bool = True):
    """Batched BLS verify — same contract as ops/pairing.verify_prepared
    (e(-g1, sig) * e(pub, H(msg)) == 1 per batch row) on the batch-last
    Pallas path. Inputs in the engine's batch-leading layout. Batches
    that are a multiple of GRID_BLOCK take the grid-kernel chain (~5x
    the fused-fori kernels); others keep the fused kernels."""
    xp, yp, q = pack_verify_inputs(np.asarray(pub_aff), np.asarray(sig_aff),
                                   np.asarray(msg_aff))
    b = q.shape[-1]
    if use_pallas:
        if b % GRID_BLOCK == 0:
            return _verify_pl_grid(xp, yp, q, npairs=2, b=b)
        return _verify_pl(xp, yp, q, npairs=2, b=b)
    return _f12_is_one_bl(_multi_pairing_jit(xp, yp, q))


@jax.jit
def _multi_pairing_jit(xp, yp, q):
    return multi_pairing_bl(xp, yp, q)
