"""Batch-last BLS12-381 field/tower arithmetic for Pallas TPU kernels.

The XLA graph engine (ops/limb.py, ops/tower.py, ops/pairing.py) dispatches
tens of thousands of tiny HLOs per pairing — per-op overhead caps it at
~3 pairing-checks/sec/batch-row. This module re-expresses the same
arithmetic in a layout designed for *fused* Pallas kernels:

    Fp    (..., 32, B)            limbs on SUBLANES, batch on LANES
    Fp2   (..., 2, 32, B)
    Fp6   (..., 3, 2, 32, B)
    Fp12  (..., 2, 3, 2, 32, B)

With B = 128 the trailing (32, 128) tile maps exactly onto the VPU's
native (8, 128) vector registers: every elementwise op processes 128
batch elements at full lane utilization, and limb shifts are sublane
shifts. All functions are pure jnp compositions — usable inside Pallas
kernel bodies (no gather, no scan, no pad with interior padding; only
static slices, concatenations, multiplies and adds, all Mosaic-lowerable).

Algorithms (12-bit limbs, Montgomery R = 2^384, lazy carries) mirror
ops/limb.py / ops/tower.py and are golden-tested against the host
reference drand_tpu.crypto.fields (tests/test_pallas_field.py).

Reference hot-path equivalence: kyber-bls12381's assembly field backend
(/root/reference/go.mod:9-10) — here the batch axis replaces instruction-
level parallelism.
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto import fields as hf
from ..crypto.fields import P
from . import limb as _x  # host-side packing helpers + shared constants

BITS = _x.BITS
NLIMBS = _x.NLIMBS
MASK = _x.MASK
DTYPE = _x.DTYPE

# conv strategy:
#   "tree"   = product rows + log-tree aligned accumulation (default —
#              same values as "unroll" by pure reassociation, but ~half
#              the lane-multiplies: no zero-padded window mults, and the
#              accumulation adds shrink from n_rows*out_len to a
#              ~1.1*out_len log-tree)
#   "unroll" = 32 static shifted out_len-wide partial products
#   "loop"   = fori_loop accumulation (compact trace, serial)
# TRACE-TIME constant: it is read when a kernel first compiles and is NOT
# part of any jit cache key — set it before the first compile (e.g. in a
# test's setup) and never flip it mid-process; a flip after compilation is
# silently ignored for already-jitted callers. Tests cover all modes.
CONV_MODE = __import__("os").environ.get("DRAND_TPU_CONV", "tree")


# ---------------------------------------------------------------------------
# Host-side packing (numpy; batch-last)
# ---------------------------------------------------------------------------

def pack_fp(values: list[int]) -> np.ndarray:
    """ints -> (32, B) Montgomery-domain limbs."""
    return np.stack([_x.int_to_mont_limbs(v) for v in values], axis=-1)


def unpack_fp(a) -> list[int]:
    """(32, B) -> canonical host ints."""
    a = np.asarray(a)
    return [_x.fp_from_device(a[..., j]) for j in range(a.shape[-1])]


# ---------------------------------------------------------------------------
# Device constants — ONE packed (K, 32) int32 buffer.
#
# Pallas kernels may not close over array constants ("captures constants —
# pass them as inputs"), so every array constant lives in a single packed
# buffer that kernels take as their first input and activate with
# ``const_context``; outside kernels the module-level numpy copy is used
# (a plain jnp constant in XLA graphs).
# ---------------------------------------------------------------------------

_GAMMA_ROWS = {
    k: np.stack([
        np.stack([_x.int_to_limbs(g.c0 * _x.R_MONT % P),
                  _x.int_to_limbs(g.c1 * _x.R_MONT % P)])
        for g in hf._FROBENIUS_GAMMA[k]
    ]).reshape(12, NLIMBS)
    for k in (1, 2, 3)
}

# p-2 bits, MSB-first, padded to 384 with trailing zeros, as (12, 32)
_PM2_BITS_MSB = np.array([int(c) for c in bin(P - 2)[2:]], dtype=np.int32)
PM2_NBITS = _PM2_BITS_MSB.shape[0]  # 381
_PM2_ROWS = np.zeros(384, dtype=np.int32)
_PM2_ROWS[:PM2_NBITS] = _PM2_BITS_MSB
_PM2_ROWS = _PM2_ROWS.reshape(12, NLIMBS)

# multiples of p below ~2^384 (k*p, k = 0..K-1), 33 limbs each — the
# exact-equality table behind is_zero_mod_p (low 32 limbs in the const
# buffer; the top limb is compared as a host int scalar)
_PMULT_33 = np.stack([_x.int_to_limbs(k * P, NLIMBS + 1)
                      for k in range(_x.R_MONT // P + 1)])
N_PMULT = _PMULT_33.shape[0]

# ---- lazy-reduction complement profiles (see "Lazy reduction" below) ----
#
# An unreduced subtraction x - y is computed borrow-free as
# x + (CMAX - y) + D where CMAX is a per-limb upper bound on y and
# D ≡ -Σ CMAX_k 2^12k (mod p). CMAX profiles are VALUE-AWARE: limb k of
# a value <= Yv is <= Yv >> 12k, so the numeric inflation of the
# complement stays ~2x the subtrahend's value bound instead of
# CMAX_flat * 2^(12 width) — this is what keeps redc's wrap convergence
# at a handful of passes.
_A_INV = 4100                                # engine-invariant limb bound
_UW = 2 * NLIMBS + 2                         # canonical unreduced width


def _usub_profile(flat: int, width: int, value_bound: int) -> list[int]:
    # limb k of a non-negative-limb value <= Yv is <= floor(Yv / 2^12k)
    return [min(flat, value_bound >> (12 * k)) for k in range(width)]


_USUB_PROFILES = {
    # raw product convolution: triangular coefficient-count profile
    # (count(k) operand pairs, each product <= 4100^2), width 64
    "C": [(min(k, 31) - max(0, k - 31) + 1) * _A_INV * _A_INV
          if k < 63 else 0 for k in range(64)],
    # f2-core outputs (limbs <= 2^18.1 after fold, value <= 2^770)
    "T": _usub_profile(1 << 19, _UW, 1 << 771),
    # sums of two f2-core outputs
    "S": _usub_profile(1 << 20, _UW, 1 << 772),
    # xi-combine inputs at the f6 level (<= 2^20.4, value <= 2^772.5)
    "X": _usub_profile(1 << 21, _UW, 1 << 773),
    # single f6-core output coefficient (<= 2^22, value <= 2^774.2)
    "Y": _usub_profile(1 << 23, _UW, 1 << 775),
    # sums of two f6-core coefficients / xi outputs at the f12 level
    "Z": _usub_profile(1 << 24, _UW, 1 << 777),
}


# ---- the authoritative redc input ceiling --------------------------------
#
# The largest value any lazy chain feeds ``redc`` is a "Z"-site
# subtraction output (f12_mul's c1 / f12_sqr's c0): an _u_sub at site S
# bounds its result value by  x.value + W(S) + p  where W(S) is the
# site's whole complement-profile total (comp = C_S - y <= W(S), plus
# the D_S < p addend). With the x side's f12-level coefficient bound
# covered by 2^777 (annotated <= 2^776.2 at both call sites), the exact
# worst case is
#
#     REDC_VALUE_CEILING = 2^777 + W("Z") + p   (~2^778.59)
#
# — ABOVE the 2^778 the old docstring chain covered and the 2^778.5 the
# tests claimed (ADVICE r5 low finding: the stated proof did not reach
# the actual worst case). The wrap-chain convergence for this ceiling is
# re-verified statically below (see _redc_wrap_converges); ``redc``'s
# wrap_passes=6 leaves two passes of proven margin over the 4 the chain
# needs.

def _usub_value_ceiling(site: str, x_value_bound: int) -> int:
    prof = _USUB_PROFILES[site]
    w_total = sum(c << (12 * k) for k, c in enumerate(prof))
    return x_value_bound + w_total + P


REDC_VALUE_CEILING = _usub_value_ceiling("Z", 1 << 777)


def _redc_wrap_converges(value_bound: int, wrap_passes: int,
                         width: int = _UW) -> bool:
    """Exact-integer certificate that ``redc(t, wrap_passes)`` of a lazy
    value <= value_bound truncates no live carry limb. Sound per-pass
    model of _wrap (all ints, no floats):

    - after the 3-round folds, limbs are <= MASK + 1 (the ripple), so
      the low 32 limbs hold at most LO_CAP = (MASK+1)·(2^384−1)/MASK;
    - substitution upper bound: v' <= LO_CAP + Σ_i hi_i·row_i with
      hi_i <= min(MASK+1, v >> (384+12i)) and row_i = 2^(12(32+i)) mod p;
    - substitution descent: the hi limbs hold at least v − LO_CAP, and
      replacing 2^384 by row_0 removes >= 2^384 − row_0 per unit, so
      v' <= v − ceil((v − LO_CAP)/2^384)·(2^384 − row_0);
    - wrap never increases the value, and once v < 2^384 every later
      fold keeps the grown carry limb at zero (a nonzero limb 32 would
      contribute >= 2^384 to a value that is preserved exactly), so the
      final [:32] truncation is exact.

    The model takes the min of the three bounds per pass and requires
    the value bound to land below 2^384 by the end of the pass chain."""
    r384 = 1 << (12 * NLIMBS)
    limb_cap = MASK + 1
    lo_cap = limb_cap * (r384 - 1) // MASK
    rows = [(1 << (12 * (NLIMBS + i))) % P for i in range(width)]
    # the REDC tail ahead of the wrap: u = t + m·p with m < 2^384,
    # r = u / 2^384 (exact division), first wrap pass sees 4 hi limbs
    v = (value_bound + r384 * P) >> (12 * NLIMBS)
    hi_w = 4
    for _ in range(wrap_passes):
        if v < r384:
            return True
        sub = lo_cap + sum(min(limb_cap, v >> (12 * (NLIMBS + i))) * rows[i]
                           for i in range(hi_w))
        hi_units = -(-(v - lo_cap) // r384) if v > lo_cap else 0
        desc = v - hi_units * (r384 - rows[0]) if hi_units else v
        v = min(v, sub, max(desc, 0))
        hi_w = 1  # passes after the first leave a single grown carry limb
    return v < r384


if not _redc_wrap_converges(REDC_VALUE_CEILING, wrap_passes=6):
    raise AssertionError(
        "redc wrap chain does not cover the Z-site worst case — a limb "
        "profile bump exceeded REDC_VALUE_CEILING's proven convergence")


def _usub_rows():
    out = []
    for name, prof in _USUB_PROFILES.items():
        w_total = sum(c << (12 * k) for k, c in enumerate(prof))
        pad = (-len(prof)) % NLIMBS
        rows = np.asarray(prof + [0] * pad, dtype=np.int32).reshape(
            -1, NLIMBS)
        out.append((f"UC_{name}", rows))
        out.append((f"UD_{name}",
                    np.asarray(_x.int_to_limbs((-w_total) % P),
                               dtype=np.int32)[None, :]))
    return out


_CONST_SECTIONS = [
    ("P", np.asarray(_x.P_LIMBS, dtype=np.int32)[None, :]),
    ("ONE", np.asarray(_x.ONE_MONT, dtype=np.int32)[None, :]),
    ("NEG_ADDEND", np.asarray(_x._NEG_ADDEND, dtype=np.int32)[None, :]),
    ("NPRIME", np.asarray(_x._NPRIME_LIMBS, dtype=np.int32)[None, :]),
    ("WRAP", np.asarray(_x._WRAP_ROWS, dtype=np.int32)),
    ("GAMMA1", _GAMMA_ROWS[1]),
    ("GAMMA2", _GAMMA_ROWS[2]),
    ("GAMMA3", _GAMMA_ROWS[3]),
    ("PM2", _PM2_ROWS),
    ("PMULT_LO", _PMULT_33[:, :NLIMBS].astype(np.int32)),
] + _usub_rows()
_OFFSETS: dict[str, tuple[int, int]] = {}


def _rebuild_buffer() -> None:
    global CONST_BUFFER
    _OFFSETS.clear()
    off = 0
    for name, rows in _CONST_SECTIONS:
        _OFFSETS[name] = (off, rows.shape[0])
        off += rows.shape[0]
    CONST_BUFFER = np.concatenate([r for _, r in _CONST_SECTIONS], axis=0)
    CONST_BUFFER.setflags(write=False)


def register_consts(sections: list[tuple[str, np.ndarray]]) -> None:
    """Append constant sections (name, (n, 32) int32 rows) — used by
    bl_curve/bl_h2c at import, BEFORE any kernel compiles (the buffer is
    re-snapshot at every kernel call, so order of registration only has
    to be deterministic across processes for the compile cache)."""
    known = {n for n, _ in _CONST_SECTIONS}
    for name, rows in sections:
        if name in known:
            raise ValueError(f"duplicate const section {name!r}")
        if rows.ndim != 2 or rows.shape[1] != NLIMBS:
            raise ValueError(f"section {name!r} must be (n, {NLIMBS})")
        _CONST_SECTIONS.append((name, rows.astype(np.int32)))
    _rebuild_buffer()


_rebuild_buffer()

_ACTIVE_BUF = None


@contextlib.contextmanager
def const_context(buf):
    """Route constants through `buf` (a traced (K, 32) array — e.g. a
    Pallas kernel input ref's value) for the ops traced inside."""
    global _ACTIVE_BUF
    prev = _ACTIVE_BUF
    _ACTIVE_BUF = buf
    try:
        yield
    finally:
        _ACTIVE_BUF = prev


def _cbuf():
    if _ACTIVE_BUF is not None:
        return _ACTIVE_BUF
    return jnp.asarray(CONST_BUFFER)


def _crow(name: str):
    """Single-row constant: (32, 1) column from a (K, 32) buffer, or
    (32, B) lanes from a (K, 32, B) lane-broadcast buffer (kernels whose
    constants reach the convolution use the latter — Mosaic cannot
    dual-broadcast a (…, 1, 1) slice)."""
    off, n = _OFFSETS[name]
    assert n == 1, name
    row = _cbuf()[off]
    return row[:, None] if row.ndim == 1 else row


def _csec(name: str):
    """(n, 32) or (n, 32, B) section."""
    off, n = _OFFSETS[name]
    return _cbuf()[off:off + n]


def _colrow(row):
    """A section row -> broadcastable column: (32,) -> (32, 1); a
    lane-ful (32, B) row passes through."""
    return row[:, None] if row.ndim == 1 else row


def lane_buffer(b: int) -> np.ndarray:
    """The (K, 32, b) lane-broadcast const buffer (host numpy) — pass as
    the const input of kernels whose constants reach the convolution."""
    return np.broadcast_to(CONST_BUFFER[:, :, None],
                           CONST_BUFFER.shape + (b,))


def one_mont(shape_prefix, b):
    return jnp.broadcast_to(_crow("ONE"),
                            tuple(shape_prefix) + (NLIMBS, b))


# ---------------------------------------------------------------------------
# Carry folding / reduction (limb axis = -2)
# ---------------------------------------------------------------------------

def _shift_down_one(c):
    """Prepend a zero limb row, drop the top row: carry := carry << 1 limb."""
    z = jnp.zeros_like(c[..., :1, :])
    return jnp.concatenate([z, c[..., :-1, :]], axis=-2)


def _fold(t, rounds: int, grow: bool = True):
    if grow:
        z = jnp.zeros_like(t[..., :1, :])
        t = jnp.concatenate([t, z], axis=-2)
    for _ in range(rounds):
        t = (t & MASK) + _shift_down_one(t >> BITS)
    return t


def _fold_drop(t, rounds: int):
    for _ in range(rounds):
        t = (t & MASK) + _shift_down_one(t >> BITS)
    return t


def _wrap(t, passes: int, fold_rounds: int = 3):
    """Fold limbs >= NLIMBS back through 2^(12k) mod p."""
    for _ in range(passes):
        if t.shape[-2] <= NLIMBS:
            break
        lo, hi = t[..., :NLIMBS, :], t[..., NLIMBS:, :]
        k = hi.shape[-2]
        wrap_rows = _csec("WRAP")
        red = jnp.zeros_like(lo)
        for i in range(k):
            row = _colrow(wrap_rows[i])
            red = red + hi[..., i:i + 1, :] * row
        t = _fold(lo + red, rounds=fold_rounds, grow=True)
    return t[..., :NLIMBS, :]


def reduce_light(t):
    """Normalize small overflows (limbs < 2^16). See limb.reduce_light for
    the THREE-pass soundness argument: two wrap passes can leave the value
    ≥ 2^384 and truncate a live carry limb (the −R-off-by-one pairing bug
    witnessed in tests/test_limb_regression.py); pass 3 provably lands
    below 2^384."""
    t = _fold(t, rounds=1, grow=True)
    return _wrap(t, passes=3, fold_rounds=2)


# ---------------------------------------------------------------------------
# Field ops (Montgomery domain)
# ---------------------------------------------------------------------------

def add(a, b):
    return reduce_light(a + b)


def neg(b):
    comp = (2 * MASK) - b
    return reduce_light(comp + _crow("NEG_ADDEND"))


def sub(a, b):
    comp = (2 * MASK) - b
    return reduce_light(a + comp + _crow("NEG_ADDEND"))


def mul_small(a, k: int):
    if not 0 <= k <= 15:
        raise ValueError("mul_small constant out of domain (0..15)")
    return reduce_light(a * k)


def double(a):
    return mul_small(a, 2)


def _conv_unrolled(a, b, out_len: int):
    """Schoolbook product convolution via static shifted partial products:
    C[k] = sum_{i+j=k} a_i * b_j, limbs <= 2^29. Fully parallel."""
    z = jnp.zeros_like(b)
    # b_ext[j] = b[j - NLIMBS]: window slides give every shift statically
    b_ext = jnp.concatenate([z, b, z], axis=-2)  # (..., 96, B)
    terms = []
    for i in range(NLIMBS):
        # shift_i[k] = b[k - i] for k in [0, out_len)
        win = b_ext[..., NLIMBS - i: NLIMBS - i + out_len, :]
        terms.append(a[..., i:i + 1, :] * win)
    return jnp.sum(jnp.stack(terms, axis=0), axis=0, dtype=DTYPE)


def _conv_tree(a, b, out_len: int):
    """Product rows + log-tree aligned accumulation, TRUNCATED at out_len.

    Row i is the UNPADDED product a_i * b, pre-clipped to the limbs that
    can reach output index < out_len (row i feeds outputs [i, i+32), so
    it keeps min(32, out_len - i) limbs); rows then combine pairwise —
    each combine concatenates one zero block of the offset delta and
    adds. The construction clip is the only clip needed: inductively
    offset + len <= out_len for every row, so combined lengths never
    exceed out_len - offset. For out_len = 2n (the product convs)
    nothing is clipped and row lengths grow 32 -> 33 -> 35 -> 39 ->
    47 -> 63; for out_len = n (the REDC NPRIME conv) only the
    lower-triangular n(n+1)/2 = 528 of 1024 products are executed —
    everything clipped was discarded by the final slice before. Versus
    _conv_unrolled this executes exactly the true limb products (the
    windowed form multiplies ~50% zeros at out_len=2n and ~75% at
    out_len=n) and ~out_len*log(n) accumulation adds instead of
    out_len*n. Values are bit-identical on [0, out_len) (pure
    reassociation of the same non-negative int32 sums — the 2^29
    coefficient bound of the schoolbook form is unchanged). Mosaic-safe:
    static slices, concats and elementwise ops only."""
    n = a.shape[-2]
    # (row, offset): row i clipped to the limbs below out_len
    rows = [(a[..., i:i + 1, :] * b[..., :min(n, out_len - i), :], i)
            for i in range(n) if out_len - i > 0]
    def pad_to(v, ln, lead=0):
        parts = []
        if lead:
            parts.append(jnp.zeros(v.shape[:-2] + (lead, v.shape[-1]),
                                   v.dtype))
        parts.append(v)
        tail = ln - lead - v.shape[-2]
        if tail:
            parts.append(jnp.zeros(v.shape[:-2] + (tail, v.shape[-1]),
                                   v.dtype))
        return parts[0] if len(parts) == 1 else jnp.concatenate(
            parts, axis=-2)

    while len(rows) > 1:
        nxt = []
        for j in range(0, len(rows) - 1, 2):
            (x, ox), (y, oy) = rows[j], rows[j + 1]
            d = oy - ox
            keep = max(x.shape[-2], d + y.shape[-2])  # <= out_len - ox
            nxt.append((pad_to(x, keep) + pad_to(y, keep, lead=d), ox))
        if len(rows) % 2:
            nxt.append(rows[-1])
        rows = nxt
    out, off = rows[0]
    assert off == 0
    got = out.shape[-2]
    if got < out_len:
        z = jnp.zeros(out.shape[:-2] + (out_len - got, out.shape[-1]),
                      out.dtype)
        return jnp.concatenate([out, z], axis=-2)
    return out[..., :out_len, :]


def _conv_karatsuba(a, b, out_len: int):
    """One Karatsuba level over the tree conv: split 32 limbs into 16/16
    halves, compute the three 16-limb products (a0·b0, a1·b1,
    (a0+a1)·(b0+b1)) as ONE stacked tree conv, recombine.

    768 true limb products instead of 1024 (~13% fewer total VPU ops
    after the extra adds). COEFFICIENT-exact vs schoolbook: the middle
    term pm − p0 − p1 equals the cross-term sums per coefficient (every
    partial product is non-negative, so no signed-intermediate hazard),
    and the shifted recombination reproduces C[k] = Σ_{i+j=k} a_i·b_j
    identically. Magnitudes: half-sums ≤ 2^13, pm coefficients
    ≤ 16·2^26 = 2^30, recombined ≤ 2^28 + 2^30 + 2^28 < 2^31 — int32
    safe. A second level would overflow the middle product's
    (2^14)²·8 = 2^31 bound; not taken."""
    h = NLIMBS // 2
    a0, a1 = a[..., :h, :], a[..., h:, :]
    b0, b1 = b[..., :h, :], b[..., h:, :]
    pa = jnp.stack([a0, a1, a0 + a1], axis=0)
    pb = jnp.stack([b0, b1, b0 + b1], axis=0)
    p = _conv_tree(pa, pb, 2 * h - 1)       # (3, ..., 31, B)
    p0, p1, pm = p[0], p[1], p[2]
    mid = pm - p0 - p1                       # cross terms, >= 0 per coeff
    z = jnp.zeros_like(p0[..., :1, :])

    def zpad(n):
        return jnp.broadcast_to(z, z.shape[:-2] + (n, z.shape[-1]))

    full = (jnp.concatenate([p0, zpad(33)], axis=-2)
            + jnp.concatenate([zpad(h), mid, zpad(17)], axis=-2)
            + jnp.concatenate([zpad(2 * h), p1, zpad(1)], axis=-2))
    if out_len <= full.shape[-2]:
        return full[..., :out_len, :]
    return jnp.concatenate(
        [full, zpad(out_len - full.shape[-2])], axis=-2)


def _conv_looped(a, b, out_len: int):
    """Same convolution as a fori_loop (compact trace for huge kernels)."""
    z = jnp.zeros_like(b)
    b_ext = jnp.concatenate([z, b, z], axis=-2)

    def body(i, acc):
        win = jax.lax.dynamic_slice_in_dim(b_ext, NLIMBS - i, out_len,
                                           axis=-2)
        ai = jax.lax.dynamic_slice_in_dim(a, i, 1, axis=-2)
        return acc + ai * win

    init = jnp.zeros(a.shape[:-2] + (out_len, a.shape[-1]), DTYPE)
    return jax.lax.fori_loop(0, NLIMBS, body, init)


def _conv(a, b, out_len: int):
    if CONV_MODE == "tree":
        return _conv_tree(a, b, out_len)
    if CONV_MODE == "kara":
        return _conv_karatsuba(a, b, out_len)
    if CONV_MODE == "unroll":
        return _conv_unrolled(a, b, out_len)
    if CONV_MODE == "loop":
        return _conv_looped(a, b, out_len)
    raise ValueError(
        f"unknown DRAND_TPU_CONV mode {CONV_MODE!r} "
        f"(expected tree|kara|unroll|loop)")


def mont_mul(a, b):
    """Montgomery product a * b * R^-1 mod p (REDC) — see limb.mont_mul for
    the quotient-bit argument. Identical algorithm, batch-last layout.

    Constant-column operands ((…, 32, 1)) are fine in XLA; kernels whose
    constants reach this convolution must use a LANE-BROADCAST const
    buffer (const_context with a (K, 32, B) buffer — bl.lane_buffer):
    a (…, 1, 1) slice times a full window would need a both-sublanes-
    and-lanes vector broadcast, which Mosaic cannot lower."""
    t = _conv(a, b, 2 * NLIMBS)                     # (..., 64, B)
    t = _fold(t, rounds=3, grow=True)               # (..., 65, B)
    m = _conv(t[..., :NLIMBS, :], jnp.broadcast_to(
        _crow("NPRIME"), t.shape[:-2] + (NLIMBS, t.shape[-1])),
        NLIMBS)
    m = _fold_drop(m, rounds=3)
    u = _conv(m, jnp.broadcast_to(_crow("P"),
                                  m.shape[:-2] + (NLIMBS, m.shape[-1])),
              2 * NLIMBS)
    z = jnp.zeros_like(u[..., :1, :])
    u = jnp.concatenate([u, z], axis=-2) + t        # (..., 65, B)
    u = _fold(u, rounds=3, grow=True)               # (..., 66, B)
    k = jnp.any(u[..., :NLIMBS, :] != 0, axis=-2).astype(DTYPE)  # (..., B)
    hi = u[..., NLIMBS:, :]
    r = jnp.concatenate([hi[..., :1, :] + k[..., None, :], hi[..., 1:, :]],
                        axis=-2)
    return _wrap(_fold(r, rounds=1, grow=False), passes=2)


def mont_sqr(a):
    return mont_mul(a, a)


# ---------------------------------------------------------------------------
# Lazy reduction (BLST-style): accumulate unreduced products, REDC once.
#
# A "lazy" value is a plain (..., w, B) int32 array, w in [64, _UW],
# holding non-negative limbs of an UNREDUCED integer congruent (mod p)
# to the product/combination it represents; ``redc`` turns it into an
# engine-invariant Montgomery field element. f2/f6/f12 multiplication
# computes all product convolutions first, combines them linearly in the
# lazy domain (adds, profile-complemented subs, xi twists — no REDC),
# and reduces ONCE per output coefficient: per f12_mul the REDC count
# drops from 54 to 12 (per f6_mul 18 -> 6, per f2_mul 3 -> 2) while the
# convolution count is unchanged. Bounds are tracked statically at each
# call site (comments); every site keeps limbs < 2^30 ahead of redc and
# < 2^31 everywhere (int32).
#
# Product convolutions on this path ALWAYS use the tree conv: the "C"
# complement profile is the schoolbook/tree triangular coefficient
# bound, which Karatsuba recombination does not satisfy.
# ---------------------------------------------------------------------------

LAZY = __import__("os").environ.get("DRAND_TPU_LAZY", "1") == "1"


def _u_pad(t, w: int):
    k = w - t.shape[-2]
    if k == 0:
        return t
    z = jnp.zeros(t.shape[:-2] + (k, t.shape[-1]), t.dtype)
    return jnp.concatenate([t, z], axis=-2)


def _u_fold1(t):
    """One carry-fold round, +1 limb: limbs < 2^30 -> <= MASK + 2^18."""
    return _fold(t, rounds=1, grow=True)


def _u_sub(x, y, site: str):
    """x - y (mod p) in the lazy domain, borrow-free:
    x + (CMAX_site - y) + D_site. ``y`` must match the site's profile
    width and per-limb/value bounds (see _USUB_PROFILES); x.width >=
    y.width. Result width = x.width, limbs <= x.bound + CMAX_flat +
    MASK; value <= x.value + ~2*y.value_bound + p."""
    prof_rows = _csec(f"UC_{site}")
    # (m, 32[, B]) rows -> (m*32[, B]) profile via concat of row slices
    # (NOT reshape — Mosaic has no general reshape lowering)
    prof = jnp.concatenate([prof_rows[i] for i in range(prof_rows.shape[0])],
                           axis=0)
    if prof.ndim == 1:
        prof = prof[:, None]
    yw = y.shape[-2]
    # y must span the site's full profile width: D cancels the WHOLE
    # profile sum mod p, so a narrower y would leave the tail
    # uncancelled (pad y with zero limbs at the call site)
    assert yw >= len(_USUB_PROFILES[site]), (site, yw)
    comp = prof[:yw] - y
    d = _colrow(_csec(f"UD_{site}")[0])
    xw = x.shape[-2]
    low = x[..., :NLIMBS, :] + comp[..., :NLIMBS, :] + d
    mid = x[..., NLIMBS:yw, :] + comp[..., NLIMBS:, :]
    parts = [low, mid]
    if xw > yw:
        parts.append(x[..., yw:, :])
    return jnp.concatenate(parts, axis=-2)


def _u_xi(pair, site: str):
    """xi * (x0 + x1 u) = (x0 - x1) + (x0 + x1) u in the lazy domain."""
    x0, x1 = pair
    return _u_sub(x0, x1, site), x0 + x1


def redc(t, wrap_passes: int = 6):
    """REDC of a lazy value: non-negative limbs < 2^30, any width in
    [64, _UW], value <= REDC_VALUE_CEILING (~2^778.59 — the authoritative
    input bound, derived from the "Z"-site worst case where the profiles
    are built; the 2^778/2^778.1/2^778.5 figures previously scattered
    across docstrings and tests all sat BELOW the true worst case).
    Identical algorithm to :func:`mont_mul`'s tail. ``wrap_passes`` = 6
    covers the ceiling with two passes of margin: the statically-checked
    chain (_redc_wrap_converges, exact ints) is 2^778.59 -> r < 2^394.6
    -> 1300p -> 121p -> 13p -> 3.2p < 2^384 after pass 4, and once the
    value bound is under 2^384 the remaining passes preserve it, so the
    grown carry limb is provably zero and the [:32] truncation exact —
    the reduce_light 3-pass lesson applied at this scale."""
    t = _fold(t, rounds=3, grow=True)              # limbs <= MASK+1
    m = _conv(t[..., :NLIMBS, :], jnp.broadcast_to(
        _crow("NPRIME"), t.shape[:-2] + (NLIMBS, t.shape[-1])), NLIMBS)
    m = _fold_drop(m, rounds=3)                    # ≡ T*(-p^-1) mod R
    u = _conv(m, jnp.broadcast_to(
        _crow("P"), m.shape[:-2] + (NLIMBS, m.shape[-1])), 2 * NLIMBS)
    u = _u_pad(u, t.shape[-2]) + t                 # ≡ 0 mod R
    u = _fold(u, rounds=3, grow=True)              # limbs <= MASK+1
    k = jnp.any(u[..., :NLIMBS, :] != 0, axis=-2).astype(DTYPE)
    hi = u[..., NLIMBS:, :]
    r = jnp.concatenate([hi[..., :1, :] + k[..., None, :], hi[..., 1:, :]],
                        axis=-2)
    return _wrap(_fold(r, rounds=1, grow=False), passes=wrap_passes)


def _f2_mul_core(a, b):
    """Unreduced Karatsuba f2 product: (T0, T1) lazy pair, width _UW,
    limbs <= 2^18.1, value <= 2^770 (redc(T_i) = Montgomery product
    coefficients). Inputs engine-invariant."""
    a0, a1 = a[..., 0, :, :], a[..., 1, :, :]
    b0, b1 = b[..., 0, :, :], b[..., 1, :, :]
    pa = jnp.stack([a0, a1, add(a0, a1)], axis=-3)
    pb = jnp.stack([b0, b1, add(b0, b1)], axis=-3)
    w = _conv_tree(pa, pb, 2 * NLIMBS)     # limbs <= 2^29.01, val <= 2^768.1
    w0, w1, w2 = w[..., 0, :, :], w[..., 1, :, :], w[..., 2, :, :]
    # t0 = a0b0 - a1b1: sub <= 2^30.02 limbs / 2^769.3 value, fold ->
    # <= 2^18.1 / width 65
    t0 = _u_fold1(_u_sub(w0, w1, "C"))
    # t1 = (a0+a1)(b0+b1) - a0b0 - a1b1: two chained "C" subs with a
    # fold between (2^30.03 peak), value <= 2^770
    t1 = _u_fold1(_u_sub(_u_fold1(_u_sub(w2, w0, "C")), w1, "C"))
    return _u_pad(t0, _UW), _u_pad(t1, _UW)


def _redc_pairs(pairs):
    """redc a list of (x0, x1) lazy f2 pairs in ONE stacked call; returns
    the (len(pairs), ..., 2, 32, B)-shaped reduced stack."""
    flat = [c for p in pairs for c in p]
    r = redc(jnp.stack(flat, axis=-3))
    n = len(pairs)
    return r.reshape(r.shape[:-3] + (n, 2) + r.shape[-2:])


def _f6_mul_core(a, b):
    """Unreduced f6 product: 3 lazy f2 pairs [(c0), (c1), (c2)], limbs
    <= 2^22, value <= 2^774.2. One 18-product conv + lazy combines; no
    REDC."""
    a0, a1, a2 = a[..., 0, :, :, :], a[..., 1, :, :, :], a[..., 2, :, :, :]
    b0, b1, b2 = b[..., 0, :, :, :], b[..., 1, :, :, :], b[..., 2, :, :, :]
    pa = jnp.stack([a0, a1, a2,
                    f2_add(a1, a2), f2_add(a0, a1), f2_add(a0, a2)], axis=-4)
    pb = jnp.stack([b0, b1, b2,
                    f2_add(b1, b2), f2_add(b0, b1), f2_add(b0, b2)], axis=-4)
    T0, T1 = _f2_mul_core(pa, pb)  # (..., 6, _UW, B) each

    def v(j):
        return (T0[..., j, :, :], T1[..., j, :, :])

    v0, v1, v2, m12, m01, m02 = (v(j) for j in range(6))

    def uadd(x, y):
        return (x[0] + y[0], x[1] + y[1])

    def usub(x, y, site):
        return (_u_sub(x[0], y[0], site), _u_sub(x[1], y[1], site))

    def uxi(x, site):
        return _u_xi(x, site)

    # c0 = v0 + xi*(m12 - (v1+v2)):
    #   s12 <= 2^19.1/2^771 ("S" fits); sub <= 2^20.4/2^772.5; xi at
    #   "X" -> <= 2^21.8/2^774; + v0 -> <= 2^21.9/2^774.1
    c0 = uadd(v0, uxi(usub(m12, uadd(v1, v2), "S"), "X"))
    # c1 = (m01 - (v0+v1)) + xi*v2: xi at "T" (<= 2^19.7/2^772);
    #   total <= 2^20.8/2^773
    c1 = uadd(usub(m01, uadd(v0, v1), "S"), uxi(v2, "T"))
    # c2 = (m02 - (v0+v2)) + v1 <= 2^20.5/2^772.6
    c2 = uadd(usub(m02, uadd(v0, v2), "S"), v1)
    return [c0, c1, c2]


def _u_mul_by_v(cs, site: str):
    """mul_by_v on a lazy f6 coefficient list: (c0,c1,c2) -> (xi*c2, c0, c1)."""
    return [_u_xi(cs[2], site), cs[0], cs[1]]


def select(cond, a, b):
    """cond has the batch shape of a without the (limb, B) trailing axes —
    i.e. cond shape == a.shape[:-2]."""
    return jnp.where(cond[..., None, None], a, b)


# ---------------------------------------------------------------------------
# Fp2 (c0 + c1*u, u^2 = -1): (..., 2, 32, B)
# ---------------------------------------------------------------------------

def f2(c0, c1):
    return jnp.stack([c0, c1], axis=-3)


def f2_add(a, b):
    return reduce_light(a + b)


def f2_sub(a, b):
    return sub(a, b)


def f2_neg(a):
    return neg(a)


def f2_mul(a, b):
    if LAZY:
        # Karatsuba with the cross-term subtractions in the lazy
        # domain: 3 convolutions, 2 REDCs (one stacked call)
        t0, t1 = _f2_mul_core(a, b)
        return redc(jnp.stack([t0, t1], axis=-3))
    a0, a1 = a[..., 0, :, :], a[..., 1, :, :]
    b0, b1 = b[..., 0, :, :], b[..., 1, :, :]
    # Karatsuba: 3 Fp products in one stacked mont_mul
    pa = jnp.stack([a0, a1, add(a0, a1)], axis=-3)
    pb = jnp.stack([b0, b1, add(b0, b1)], axis=-3)
    v = mont_mul(pa, pb)
    v0, v1, v2 = v[..., 0, :, :], v[..., 1, :, :], v[..., 2, :, :]
    return f2(sub(v0, v1), sub(v2, add(v0, v1)))


def f2_sqr(a):
    a0, a1 = a[..., 0, :, :], a[..., 1, :, :]
    pa = jnp.stack([add(a0, a1), a0], axis=-3)
    pb = jnp.stack([sub(a0, a1), a1], axis=-3)
    v = mont_mul(pa, pb)
    return f2(v[..., 0, :, :], double(v[..., 1, :, :]))


def f2_mul_fp(a, s):
    """Fp2 * Fp (s: (..., 32, B))."""
    return mont_mul(a, s[..., None, :, :])


def f2_mul_small(a, k: int):
    return mul_small(a, k)


def f2_conj(a):
    return f2(a[..., 0, :, :], neg(a[..., 1, :, :]))


def f2_mul_by_xi(a):
    a0, a1 = a[..., 0, :, :], a[..., 1, :, :]
    return f2(sub(a0, a1), add(a0, a1))


def f2_select(cond, a, b):
    return jnp.where(cond[..., None, None, None], a, b)


# ---------------------------------------------------------------------------
# Fp6 (over Fp2, v^3 = xi): (..., 3, 2, 32, B)
# ---------------------------------------------------------------------------

def f6(c0, c1, c2):
    return jnp.stack([c0, c1, c2], axis=-4)


def f6_add(a, b):
    return reduce_light(a + b)


def f6_sub(a, b):
    return sub(a, b)


def f6_neg(a):
    return neg(a)


def f6_mul(a, b):
    if LAZY:
        # 18 convolutions, 6 REDCs (one stacked call): the Toom-style
        # cross combines happen in the lazy domain
        return _redc_pairs(_f6_mul_core(a, b))
    a0, a1, a2 = a[..., 0, :, :, :], a[..., 1, :, :, :], a[..., 2, :, :, :]
    b0, b1, b2 = b[..., 0, :, :, :], b[..., 1, :, :, :], b[..., 2, :, :, :]
    pa = jnp.stack([a0, a1, a2,
                    f2_add(a1, a2), f2_add(a0, a1), f2_add(a0, a2)], axis=-4)
    pb = jnp.stack([b0, b1, b2,
                    f2_add(b1, b2), f2_add(b0, b1), f2_add(b0, b2)], axis=-4)
    v = f2_mul(pa, pb)
    v0, v1, v2 = v[..., 0, :, :, :], v[..., 1, :, :, :], v[..., 2, :, :, :]
    m12, m01, m02 = (v[..., 3, :, :, :], v[..., 4, :, :, :],
                     v[..., 5, :, :, :])
    c0 = f2_add(v0, f2_mul_by_xi(f2_sub(m12, f2_add(v1, v2))))
    c1 = f2_add(f2_sub(m01, f2_add(v0, v1)), f2_mul_by_xi(v2))
    c2 = f2_add(f2_sub(m02, f2_add(v0, v2)), v1)
    return f6(c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_by_v(a):
    a0, a1, a2 = a[..., 0, :, :, :], a[..., 1, :, :, :], a[..., 2, :, :, :]
    return f6(f2_mul_by_xi(a2), a0, a1)


# ---------------------------------------------------------------------------
# Fp12 (over Fp6, w^2 = v): (..., 2, 3, 2, 32, B)
# ---------------------------------------------------------------------------

def f12(c0, c1):
    return jnp.stack([c0, c1], axis=-5)


def f12_one(shape_prefix, b):
    """Built by stacking (no scatter — Mosaic has no scatter lowering)."""
    pre = tuple(shape_prefix)
    one_fp = jnp.broadcast_to(_crow("ONE"), pre + (NLIMBS, b)).astype(DTYPE)
    z_fp = jnp.zeros(pre + (NLIMBS, b), DTYPE)
    f2_one_ = jnp.stack([one_fp, z_fp], axis=-3)
    f2_z = jnp.zeros(pre + (2, NLIMBS, b), DTYPE)
    f6_one_ = jnp.stack([f2_one_, f2_z, f2_z], axis=-4)
    f6_z = jnp.zeros(pre + (3, 2, NLIMBS, b), DTYPE)
    return jnp.stack([f6_one_, f6_z], axis=-5)


def _u_prod(cs, k):
    """Slice product k out of a stacked-core coefficient list."""
    return [(c0[..., k, :, :], c1[..., k, :, :]) for c0, c1 in cs]


def _u_add6(x, y):
    return [(p[0] + q[0], p[1] + q[1]) for p, q in zip(x, y)]


def _u_sub6(x, y, site: str):
    return [(_u_sub(p[0], q[0], site), _u_sub(p[1], q[1], site))
            for p, q in zip(x, y)]


def f12_mul(a, b):
    a0, a1 = a[..., 0, :, :, :, :], a[..., 1, :, :, :, :]
    b0, b1 = b[..., 0, :, :, :, :], b[..., 1, :, :, :, :]
    pa = jnp.stack([a0, a1, f6_add(a0, a1)], axis=-5)
    pb = jnp.stack([b0, b1, f6_add(b0, b1)], axis=-5)
    if LAZY:
        # 54 convolutions, 12 REDCs: both Karatsuba levels combine in
        # the lazy domain
        cs = _f6_mul_core(pa, pb)
        v0, v1, v2 = (_u_prod(cs, k) for k in range(3))
        # c0 = v0 + v*v1 (xi-shift at "Y": coeffs <= 2^22/2^774.2)
        #   -> <= 2^23.8 limbs / 2^776.2 value
        c0 = _u_add6(v0, _u_mul_by_v(v1, "Y"))
        # c1 = v2 - (v0+v1): "Z" (y <= 2^23.3/2^775.2)
        #   -> <= 2^24.4 / 2^778.1 (under REDC_VALUE_CEILING ~2^778.59)
        c1 = _u_sub6(v2, _u_add6(v0, v1), "Z")
        r = _redc_pairs(c0 + c1)  # (..., 6, 2, 32, B)
        return f12(r[..., :3, :, :, :], r[..., 3:, :, :, :])
    v = f6_mul(pa, pb)
    v0 = v[..., 0, :, :, :, :]
    v1 = v[..., 1, :, :, :, :]
    v2 = v[..., 2, :, :, :, :]
    return f12(f6_add(v0, f6_mul_by_v(v1)), f6_sub(v2, f6_add(v0, v1)))


def f12_sqr(a):
    a0, a1 = a[..., 0, :, :, :, :], a[..., 1, :, :, :, :]
    if LAZY:
        t = f6_add(a0, a1)
        u = f6_add(a0, f6_mul_by_v(a1))
        pa = jnp.stack([a0, t], axis=-5)
        pb = jnp.stack([a1, u], axis=-5)
        cs = _f6_mul_core(pa, pb)
        v0 = _u_prod(cs, 0)   # a0*a1
        w = _u_prod(cs, 1)    # (a0+a1)(a0+v*a1)
        # c0 = w - (v0 + v*v0): y <= 2^23.8/2^776.2, "Z" -> c0 <=
        # 2^24.4 limbs / 2^778.1 value (under REDC_VALUE_CEILING ~2^778.59)
        c0 = _u_sub6(w, _u_add6(v0, _u_mul_by_v(v0, "Y")), "Z")
        c1 = _u_add6(v0, v0)
        r = _redc_pairs(c0 + c1)
        return f12(r[..., :3, :, :, :], r[..., 3:, :, :, :])
    v0 = f6_mul(a0, a1)
    c0 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_by_v(a1))),
                f6_add(v0, f6_mul_by_v(v0)))
    return f12(c0, f6_add(v0, v0))


def f12_conj(a):
    return f12(a[..., 0, :, :, :, :], f6_neg(a[..., 1, :, :, :, :]))


def f12_select(cond, a, b):
    return jnp.where(cond[..., None, None, None, None, None], a, b)


# -- w-basis ----------------------------------------------------------------

def f12_to_w(a):
    """(..., 2, 3, 2, 32, B) -> (..., 6, 2, 32, B) in w-power order."""
    c0, c1 = a[..., 0, :, :, :, :], a[..., 1, :, :, :, :]
    return jnp.stack([
        c0[..., 0, :, :, :], c1[..., 0, :, :, :], c0[..., 1, :, :, :],
        c1[..., 1, :, :, :], c0[..., 2, :, :, :], c1[..., 2, :, :, :],
    ], axis=-4)


def f12_from_w(w):
    c0 = jnp.stack([w[..., 0, :, :, :], w[..., 2, :, :, :],
                    w[..., 4, :, :, :]], axis=-4)
    c1 = jnp.stack([w[..., 1, :, :, :], w[..., 3, :, :, :],
                    w[..., 5, :, :, :]], axis=-4)
    return f12(c0, c1)


# -- Frobenius --------------------------------------------------------------

def f12_frobenius(a, power: int = 1):
    w = f12_to_w(a)
    if power % 2 == 1:
        w = f2_conj(w)
    sec = _csec(f"GAMMA{power}")
    if sec.ndim == 2:   # (12, 32) -> (6, 2, 32, 1)
        gam = sec.reshape(6, 2, NLIMBS)[..., None]
    else:               # (12, 32, B) lane-ful -> (6, 2, 32, B)
        gam = sec.reshape(6, 2, NLIMBS, sec.shape[-1])
    return f12_from_w(f2_mul(w, gam))


# -- cyclotomic squaring ----------------------------------------------------

def _f12_cyclotomic_sqr_lazy(a):
    """Granger–Scott cyclotomic square with the SQUARE combines in the
    lazy domain: 18 product convolutions in ONE stacked call, 12 REDCs
    (was 18). The 3t±2g finish stays in the reduced domain — g is
    Montgomery-scale (gR) while lazy squares are product-scale (xyR^2),
    and the two cannot be combined pre-REDC without an extra lifting
    convolution that would cost the saving back. Bounds: lazy squares
    <= 2^18.2/2^769.2 after fold; A/B <= 2^20.6 limbs / <= 2^773.3
    value ("T" subs) — under redc's 2^30 / REDC_VALUE_CEILING ceilings."""
    w = f12_to_w(a)
    g = [w[..., i, :, :, :] for i in range(6)]
    rows_a, rows_b = [], []
    for x, y in ((g[0], g[3]), (g[1], g[4]), (g[2], g[5])):
        s = f2_add(x, y)
        for v in (x, y, s):
            v0, v1 = v[..., 0, :, :], v[..., 1, :, :]
            rows_a += [add(v0, v1), v0]
            rows_b += [sub(v0, v1), v1]
    pa = jnp.stack(rows_a, axis=-3)              # (..., 18, 32, B)
    pb = jnp.stack(rows_b, axis=-3)
    wv = _conv_tree(pa, pb, 2 * NLIMBS)          # (..., 18, 64, B)

    def sq(j):
        """Lazy f2 square j: ((a0+a1)(a0-a1), 2*a0a1), width _UW."""
        s0 = _u_pad(_u_fold1(wv[..., 2 * j, :, :]), _UW)
        d = wv[..., 2 * j + 1, :, :]
        s1 = _u_pad(_u_fold1(d + d), _UW)
        return s0, s1

    AB = []
    for pi in range(3):
        t0, t1, t2 = sq(3 * pi), sq(3 * pi + 1), sq(3 * pi + 2)
        # A = t0 + xi*t1 ; B = (x+y)^2 - t0 - t1
        A = (t0[0] + _u_sub(t1[0], t1[1], "T"), t0[1] + t1[0] + t1[1])
        B = (_u_sub(_u_sub(t2[0], t0[0], "T"), t1[0], "T"),
             _u_sub(_u_sub(t2[1], t0[1], "T"), t1[1], "T"))
        AB.append((A, B))

    r = _redc_pairs([p for ab in AB for p in ab])  # (..., 6, 2, 32, B)
    return _cyc_finish(g, r[..., 0, :, :, :], r[..., 1, :, :, :],
                       r[..., 2, :, :, :], r[..., 3, :, :, :],
                       r[..., 4, :, :, :], r[..., 5, :, :, :])


def _cyc_finish(g, a0, a1, b0, b1, c0, c1):
    """Granger–Scott 3t±2g finish, reduced domain (shared by the lazy
    and eager square paths)."""
    def fmi(goal, t):  # 3t - 2*goal
        return f2_add(f2_mul_small(f2_sub(t, goal), 2), t)

    def gpl(goal, t):  # 3t + 2*goal
        return f2_add(f2_mul_small(f2_add(t, goal), 2), t)

    h = [fmi(g[0], a0), gpl(g[1], f2_mul_by_xi(c1)), fmi(g[2], b0),
         gpl(g[3], a1), fmi(g[4], c0), gpl(g[5], b1)]
    return f12_from_w(jnp.stack(h, axis=-4))


def f12_cyclotomic_sqr(a):
    if LAZY:
        return _f12_cyclotomic_sqr_lazy(a)
    w = f12_to_w(a)
    g = [w[..., i, :, :, :] for i in range(6)]

    def sq2(x, y):
        t0 = f2_sqr(x)
        t1 = f2_sqr(y)
        return f2_add(t0, f2_mul_by_xi(t1)), f2_sub(f2_sqr(f2_add(x, y)),
                                                    f2_add(t0, t1))

    a0, a1 = sq2(g[0], g[3])
    b0, b1 = sq2(g[1], g[4])
    c0, c1 = sq2(g[2], g[5])
    return _cyc_finish(g, a0, a1, b0, b1, c0, c1)


# ---------------------------------------------------------------------------
# Inversion (Fermat at the bottom; tower formulas above)
# ---------------------------------------------------------------------------

def default_pm2_getter():
    """Bit getter over the PM2 constant-buffer section — XLA path only
    (Mosaic has no dynamic_slice on values; kernels pass an SMEM-ref
    getter instead)."""
    bits = _csec("PM2")

    def get(i):
        return jax.lax.dynamic_slice(bits, (i // NLIMBS, i % NLIMBS),
                                     (1, 1))[0, 0]

    return get


def fp_inv(a, bit_getter=None):
    """a^(p-2) — MSB-first square-and-multiply fori_loop; ``bit_getter(i)``
    returns the i-th exponent bit as a traced scalar (MSB-first over
    PM2_NBITS bits)."""
    if bit_getter is None:
        bit_getter = default_pm2_getter()

    def body(i, acc):
        acc = mont_sqr(acc)
        m = mont_mul(acc, a)
        return jnp.where(bit_getter(i) != 0, m, acc)

    init = jnp.broadcast_to(_crow("ONE"), a.shape).astype(DTYPE)
    return jax.lax.fori_loop(0, PM2_NBITS, body, init)


def f2_inv(a, bit_getter=None):
    a0, a1 = a[..., 0, :, :], a[..., 1, :, :]
    sq = mont_mul(jnp.stack([a0, a1], axis=-3),
                  jnp.stack([a0, a1], axis=-3))
    norm = add(sq[..., 0, :, :], sq[..., 1, :, :])
    t = fp_inv(norm, bit_getter)
    return f2(mont_mul(a0, t), neg(mont_mul(a1, t)))


def f6_inv(a, bit_getter=None):
    a0, a1, a2 = a[..., 0, :, :, :], a[..., 1, :, :, :], a[..., 2, :, :, :]
    t0 = f2_sub(f2_sqr(a0), f2_mul_by_xi(f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul_by_xi(f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    denom = f2_add(f2_mul(a0, t0),
                   f2_add(f2_mul_by_xi(f2_mul(a2, t1)),
                          f2_mul_by_xi(f2_mul(a1, t2))))
    dinv = f2_inv(denom, bit_getter)
    return f6(f2_mul(t0, dinv), f2_mul(t1, dinv), f2_mul(t2, dinv))


def f12_inv(a, bit_getter=None):
    a0, a1 = a[..., 0, :, :, :, :], a[..., 1, :, :, :, :]
    denom = f6_sub(f6_sqr(a0), f6_mul_by_v(f6_sqr(a1)))
    dinv = f6_inv(denom, bit_getter)
    return f12(f6_mul(a0, dinv), f6_neg(f6_mul(a1, dinv)))


# ---------------------------------------------------------------------------
# Exact zero test (kernel-safe: static carry unroll, no scan/dynamic slices)
# ---------------------------------------------------------------------------

def exact_normalize(t):
    """(..., 32, B) engine-invariant limbs -> (..., 33, B) exact limbs in
    [0, MASK] with the carry-out appended. Static 32-step carry chain —
    fine inside Pallas kernels (trace is ~100 tiny ops)."""
    rows = [t[..., i, :] for i in range(NLIMBS)]
    out = []
    carry = jnp.zeros_like(rows[0])
    for i in range(NLIMBS):
        s = rows[i] + carry
        out.append(s & MASK)
        carry = s >> BITS
    out.append(carry)
    return jnp.stack(out, axis=-2)


def is_zero_mod_p(a):
    """True (per batch lane) where the value of ``a`` is ≡ 0 mod p —
    sound for any engine-invariant input < ~2^384(1+eps): exact-normalize
    then compare against every multiple of p in range."""
    norm = exact_normalize(a)  # (..., 33, B)
    lo = _csec("PMULT_LO")     # (K, 32)
    eqs = []
    for k in range(N_PMULT):
        ok_lo = jnp.all(norm[..., :NLIMBS, :] == _colrow(lo[k]),
                        axis=-2)
        # top limb vs a PYTHON INT scalar — a (1,1)-vector comparison would
        # need a both-sublanes-and-lanes broadcast, which Mosaic lacks
        ok_hi = norm[..., NLIMBS, :] == int(_PMULT_33[k, NLIMBS])
        eqs.append(ok_lo & ok_hi)
    return functools.reduce(jnp.logical_or, eqs)


def f12_is_one(a):
    """==1 (Montgomery) per batch lane for (..., 2, 3, 2, 32, B)."""
    d = sub(a, f12_one(a.shape[:-5], a.shape[-1]))
    flat = d.reshape(d.shape[:-5] + (12, NLIMBS, d.shape[-1]))
    z = is_zero_mod_p(flat)  # (..., 12, B)
    return jnp.all(z, axis=-2)
