"""Batch-last hash-to-G2 + G2 decompression — wire-prep for the Pallas
verification path.

Host-side preparation (pure-Python hash_to_g2 ~45ms/message, subgroup-
checked decompression ~18ms/signature) caps end-to-end catch-up at ~15
beacons/s no matter how fast the pairing kernels are. This module ports
the device pipeline of ops/h2c.py to the batch-last layout so it can run
inside Mosaic kernels next to the pairing chain, with two algorithmic
upgrades over the XLA version:

- cofactor clearing via Budroni-Pintore ψ-composition (bl_curve.clear_
  cofactor): two 64-bit [x]-chains instead of one 636-bit [h_eff] chain;
- subgroup membership via Scott's ψ(Q) == [x]Q (bl_curve.subgroup_check)
  instead of a 255-bit [r]Q chain.

Only SHA-256 message expansion and signature byte-splitting stay on the
host (ops/h2c.msgs_to_u / sigs_to_x, transposed to batch-last here).

Mirrors drand_tpu.crypto.hash_to_curve (RFC 9380) and
crypto.curves.PointG2.from_bytes; golden tests: tests/test_bl_h2c.py.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P
from ..crypto.hash_to_curve import (
    _A_PRIME, _B_PRIME, _B_OVER_ZA, _ISO_PARAMS, _MINUS_B_OVER_A, _Z_SSWU,
)
from ..crypto.fields import _FP2_ROOTS_OF_UNITY_4
from . import bl, bl_curve as blc
from . import limb as _limb
from .bl import DTYPE, MASK, NLIMBS
from .bl_curve import _csec_f2, _f2_rows


_X0, _V_SUM, _U_SUM, _C2, _C3 = _ISO_PARAMS
_B_G2_F2 = type(_A_PRIME)(4, 4)

bl.register_consts([
    ("SSWU_A", _f2_rows(_A_PRIME)),
    ("SSWU_B", _f2_rows(_B_PRIME)),
    ("SSWU_Z", _f2_rows(_Z_SSWU)),
    ("SSWU_MBA", _f2_rows(_MINUS_B_OVER_A)),
    ("SSWU_BZA", _f2_rows(_B_OVER_ZA)),
    ("ISO_X0", _f2_rows(_X0)),
    ("ISO_VSUM", _f2_rows(_V_SUM)),
    ("ISO_USUM", _f2_rows(_U_SUM)),
    ("ISO_C2", _f2_rows(_C2)),
    ("ISO_C3", _f2_rows(_C3)),
    ("B_G2", _f2_rows(_B_G2_F2)),
    ("ROOTS4", np.concatenate([_f2_rows(r) for r in _FP2_ROOTS_OF_UNITY_4])),
    ("RAW1", _limb.int_to_limbs(1)[None, :]),
])

# sqrt exponent (q = p^2 ≡ 9 mod 16): candidate a^((q+7)/16), then a 4th
# root of unity correction. MSB-first bits, padded to (1, 768).
_SQRT_EXP = (P * P + 7) // 16
SQRT_NBITS = _SQRT_EXP.bit_length()
SQRT_BITS = np.zeros((1, 768), dtype=np.int32)
SQRT_BITS[0, :SQRT_NBITS] = [int(c) for c in bin(_SQRT_EXP)[2:]]


# ---------------------------------------------------------------------------
# Field helpers
# ---------------------------------------------------------------------------

def f2_pow_getter(a, bit_getter, nbits: int):
    """a^e, MSB-first square-and-multiply, bits via getter."""

    def body(i, acc):
        acc = bl.f2_sqr(acc)
        return jnp.where(bit_getter(i) != 0, bl.f2_mul(acc, a), acc)

    init = jnp.broadcast_to(
        jnp.stack([jnp.broadcast_to(bl._crow("ONE"), a.shape[-2:]),
                   jnp.zeros(a.shape[-2:], DTYPE)], axis=0), a.shape
    ).astype(DTYPE)
    return jax.lax.fori_loop(0, nbits, body, init)


def sqrt_f2(a, sqrt_bit_getter):
    """(root, is_square): candidate exponentiation + 4th-root-of-unity
    correction (mirrors ops/h2c._sqrt_f2)."""
    cand = f2_pow_getter(a, sqrt_bit_getter, SQRT_NBITS)
    sec = bl._csec("ROOTS4")
    if sec.ndim == 2:
        roots = sec.reshape(4, 2, NLIMBS)[..., None]
    else:
        roots = sec.reshape(4, 2, NLIMBS, sec.shape[-1])
    best, found = None, None
    for i in range(4):
        r = bl.f2_mul(cand, roots[i])
        d = bl.sub(bl.f2_sqr(r), a)
        ok = bl.is_zero_mod_p(d[..., 0, :, :]) & bl.is_zero_mod_p(
            d[..., 1, :, :])
        if best is None:
            best, found = r, ok
        else:
            best = blc._sel(ok, r, best)
            found = found | ok
    return best, found


def from_mont(a):
    """Montgomery -> raw limbs (value mod p, engine invariant)."""
    return bl.mont_mul(a, jnp.broadcast_to(bl._crow("RAW1"), a.shape))


def _lex_ge_rows(a, b):
    """a >= b lexicographically for exact limb stacks (..., L, B) vs
    (..., L, B): MSB (highest row) decides. Static unroll over L."""
    L = a.shape[-2]
    # Mosaic-safe formulation: no constant bool vectors (an i1 splat
    # lowers through an unsupported i8 truncation) and no selects on
    # i1-typed BRANCHES (same i8 path) — the running state is INT32 0/1
    top = L - 1
    ge = jnp.where(a[..., top, :] >= b[..., top, :], 1, 0)
    decided = jnp.where(a[..., top, :] != b[..., top, :], 1, 0)
    for i in range(L - 2, -1, -1):
        ai, bi = a[..., i, :], b[..., i, :]
        gt = jnp.where(ai > bi, 1, 0)
        eq = jnp.where(ai == bi, 1, 0)
        ge = jnp.where(decided != 0, ge, gt | (eq & ge))
        decided = decided | (1 - eq)
    return ge != 0


def canonicalize(a):
    """Exact canonical limbs of (value mod p): (..., 32, B), each limb in
    [0, MASK]. Static port of limb.canonicalize (select the right multiple
    of p, subtract with a borrow chain)."""
    norm = bl.exact_normalize(a)  # (..., 33, B) exact, value < ~2^385
    lo = bl._csec("PMULT_LO")     # (K, 32)
    K = bl.N_PMULT
    # count multiples <= value -> k index, then build the chosen multiple
    ge_ks = []
    for k in range(K):
        row = bl._colrow(lo[k])
        top = jnp.full_like(row[:1], int(bl._PMULT_33[k, NLIMBS]))
        mult_col = jnp.concatenate([row, top], axis=0)
        ge_ks.append(_lex_ge_rows(norm, mult_col))
    # stack as INT32 — concatenating i1 vectors hits an invalid
    # vreg bitcast in Mosaic
    ge = jnp.stack([jnp.where(g, 1, 0) for g in ge_ks], axis=0)
    kidx = jnp.sum(ge, axis=0) - 1          # (..., B)
    chosen = jnp.zeros_like(norm)
    for k in range(K):
        onehot = (kidx == k)
        row = bl._colrow(lo[k])
        top = jnp.full_like(row[:1], int(bl._PMULT_33[k, NLIMBS]))
        mult_col = jnp.concatenate([row, top], axis=0)
        chosen = chosen + jnp.where(onehot[..., None, :], mult_col, 0)
    diff = norm - chosen
    # borrow chain, static 33 steps
    rows = [diff[..., i, :] for i in range(diff.shape[-2])]
    out = []
    carry = jnp.zeros_like(rows[0])
    for i in range(len(rows)):
        s = rows[i] + carry
        out.append(s & MASK)
        carry = s >> bl.BITS
    return jnp.stack(out[:NLIMBS], axis=-2)


def sgn0_f2(a):
    """RFC 9380 sgn0 for Fp2 on canonical limbs; (..., B) bool."""
    c0 = canonicalize(from_mont(a[..., 0, :, :]))
    c1 = canonicalize(from_mont(a[..., 1, :, :]))
    sign0 = (c0[..., 0, :] & 1) != 0
    zero0 = jnp.all(c0 == 0, axis=-2)
    sign1 = (c1[..., 0, :] & 1) != 0
    return sign0 | (zero0 & sign1)


def lex_largest_f2(y):
    """zcash sign rule: y lexicographically larger than -y (compare c1
    then c0 on canonical limbs)."""
    yc0 = canonicalize(from_mont(y[..., 0, :, :]))
    yc1 = canonicalize(from_mont(y[..., 1, :, :]))
    ny = bl.f2_neg(y)
    nc0 = canonicalize(from_mont(ny[..., 0, :, :]))
    nc1 = canonicalize(from_mont(ny[..., 1, :, :]))
    c1_eq = jnp.all(yc1 == nc1, axis=-2)
    c1_gt = _lex_ge_rows(yc1, nc1) & ~c1_eq
    c0_gt = _lex_ge_rows(yc0, nc0) & ~jnp.all(yc0 == nc0, axis=-2)
    return c1_gt | (c1_eq & c0_gt)


# ---------------------------------------------------------------------------
# SSWU + isogeny (port of ops/h2c.map_to_curve_g2, batch-last)
# ---------------------------------------------------------------------------

def map_to_curve(u, sqrt_bit_getter, inv_bit_getter=None):
    """u: (..., 2, 32, B) Fp2 mont -> affine (x, y) on E2 pre-clearing."""
    a_p = _csec_f2("SSWU_A")
    b_p = _csec_f2("SSWU_B")
    zu2 = bl.f2_mul(_csec_f2("SSWU_Z"), bl.f2_sqr(u))
    tv = bl.f2_add(bl.f2_sqr(zu2), zu2)
    tv_zero = bl.is_zero_mod_p(tv[..., 0, :, :]) & bl.is_zero_mod_p(
        tv[..., 1, :, :])
    one = blc.make_f2().one(u.shape[:-3] + (u.shape[-1],)) + u * 0
    x1_main = bl.f2_mul(_csec_f2("SSWU_MBA"),
                        bl.f2_add(one, bl.f2_inv(tv, inv_bit_getter)))
    x1 = blc._sel(tv_zero,
                  jnp.broadcast_to(_csec_f2("SSWU_BZA"), x1_main.shape),
                  x1_main)

    def g_prime(x):
        return bl.f2_add(bl.f2_add(bl.f2_mul(bl.f2_sqr(x), x),
                                   bl.f2_mul(a_p, x)), b_p)

    gx1 = g_prime(x1)
    y1, sq1 = sqrt_f2(gx1, sqrt_bit_getter)
    x2 = bl.f2_mul(zu2, x1)
    gx2 = g_prime(x2)
    y2, _ = sqrt_f2(gx2, sqrt_bit_getter)
    x = blc._sel(sq1, x1, x2)
    y = blc._sel(sq1, y1, y2)
    flip = sgn0_f2(u) != sgn0_f2(y)
    y = blc._sel(flip, bl.f2_neg(y), y)
    # 3-isogeny + isomorphism onto E2
    d = bl.f2_sub(x, _csec_f2("ISO_X0"))
    dinv = bl.f2_inv(d, inv_bit_getter)
    dinv2 = bl.f2_sqr(dinv)
    X = bl.f2_add(x, bl.f2_add(bl.f2_mul(_csec_f2("ISO_VSUM"), dinv),
                               bl.f2_mul(_csec_f2("ISO_USUM"), dinv2)))
    Y = bl.f2_mul(y, bl.f2_sub(one, bl.f2_add(
        bl.f2_mul(_csec_f2("ISO_VSUM"), dinv2),
        bl.f2_mul(bl.f2_mul_small(_csec_f2("ISO_USUM"), 2),
                  bl.f2_mul(dinv2, dinv)))))
    return bl.f2_mul(_csec_f2("ISO_C2"), X), bl.f2_mul(_csec_f2("ISO_C3"), Y)


def hash_to_g2_bl(u_pairs, F, sqrt_bit_getter, x_bit_getter,
                  inv_bit_getter=None):
    """u_pairs: (2, 2, 32, B) — two Fp2 u-values per message. Returns the
    r-order G2 point (Jacobian, batch-last)."""
    x0, y0 = map_to_curve(u_pairs[0], sqrt_bit_getter, inv_bit_getter)
    x1, y1 = map_to_curve(u_pairs[1], sqrt_bit_getter, inv_bit_getter)
    b = u_pairs.shape[-1]
    one_z = F.one((b,))
    inf = jnp.zeros((b,), bl.DTYPE) != 0  # computed, not an i1 splat
    q = blc.xc.pt_add(F, (x0, y0, one_z, inf), (x1, y1, one_z, inf))
    return blc.clear_cofactor(F, q, x_bit_getter)


# ---------------------------------------------------------------------------
# Decompression + subgroup check (port of ops/h2c decompress path)
# ---------------------------------------------------------------------------

def decompress_g2_bl(x, sign_bit, F, sqrt_bit_getter):
    """x: (2, 32, B) mont; sign_bit: (B,) bool. -> (point, on_curve)."""
    gx = bl.f2_add(bl.f2_mul(bl.f2_sqr(x), x), _csec_f2("B_G2"))
    y, on_curve = sqrt_f2(gx, sqrt_bit_getter)
    is_largest = lex_largest_f2(y)
    y = blc._sel(jnp.not_equal(is_largest, sign_bit), bl.f2_neg(y), y)
    b = x.shape[-1]
    inf = jnp.zeros((b,), bl.DTYPE) != 0
    return (x, y, F.one((b,)), inf), on_curve
